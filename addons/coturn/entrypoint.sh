#!/bin/bash
# turnserver in REST-credential mode: usernames minted by turn-rest /
# the signaling /turn endpoint validate against the same shared secret.
set -e

EXTERNAL_IP="${EXTERNAL_IP:-$(curl -fs https://checkip.amazonaws.com 2>/dev/null || hostname -I | awk '{print $1}')}"

exec turnserver -n \
    --listening-port="${TURN_PORT:-3478}" \
    --tls-listening-port="${TURN_TLS_PORT:-5349}" \
    --realm="${TURN_REALM:-selkies.local}" \
    --use-auth-secret \
    --static-auth-secret="${TURN_SHARED_SECRET:?TURN_SHARED_SECRET required}" \
    --external-ip="${EXTERNAL_IP}" \
    --min-port="${TURN_MIN_PORT:-49152}" \
    --max-port="${TURN_MAX_PORT:-65535}" \
    --prometheus \
    --no-cli \
    --fingerprint \
    --verbose
