#!/bin/bash
# Build the conda package and produce a relocatable tarball via conda-pack
# (parity: the reference's portable distribution flow).
set -euo pipefail
cd "$(dirname "$0")"

conda build . --output-folder ./out
conda create -y -p ./env-pack python=3.12
conda install -y -p ./env-pack ./out/*/selkies-tpu-*.tar.bz2
conda pack -p ./env-pack -o selkies-tpu-portable.tar.gz
echo "portable distribution: $(pwd)/selkies-tpu-portable.tar.gz"
