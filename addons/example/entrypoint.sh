#!/bin/bash
# Boots the virtual display with the extensions the capture/input planes
# need (MIT-SHM for XShm capture, XTEST for injection, RANDR for layout,
# DAMAGE for change detection — parity: reference example entrypoint).
set -e

export DISPLAY="${DISPLAY:-:20}"
SCREEN="${XVFB_SCREEN:-8192x4096x24}"

Xvfb "$DISPLAY" -screen 0 "$SCREEN" \
     +extension MIT-SHM +extension XTEST +extension RANDR \
     +extension DAMAGE +extension XFIXES -nolisten tcp -noreset &

for i in $(seq 1 50); do
    xdpyinfo -display "$DISPLAY" >/dev/null 2>&1 && break
    sleep 0.2
done

# gamepad shims for applications launched inside this session
export SELKIES_INTERPOSER_SOCKET_DIR=/tmp
if [ -f /usr/lib/selkies/selkies_joystick_interposer.so ]; then
    export LD_PRELOAD="/usr/lib/selkies/selkies_joystick_interposer.so${LD_PRELOAD:+:$LD_PRELOAD}"
fi

exec supervisord -n -c /etc/supervisor/supervisord.conf
