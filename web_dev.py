"""Minimal WS echo + static server for client development (parity:
reference web.py dev harness): serves web/ and echoes every WebSocket
message back, so the client's connection/demux plumbing can be exercised
without the full streaming server.

Usage: python web_dev.py [port]
"""

from __future__ import annotations

import asyncio
import os
import sys


async def main(port: int) -> None:
    from selkies_tpu.rtc.signaling import SignalingServer
    import websockets.asyncio.server as ws_server

    web_root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "web")
    http_server = SignalingServer(addr="0.0.0.0", port=port, web_root=web_root)

    async def echo(ws):
        async for message in ws:
            await ws.send(message)

    async with ws_server.serve(echo, "0.0.0.0", port + 2):
        print(f"static http://0.0.0.0:{port}/  ws-echo :{port + 2}")
        await http_server.run()


if __name__ == "__main__":
    asyncio.run(main(int(sys.argv[1]) if len(sys.argv) > 1 else 8090))
