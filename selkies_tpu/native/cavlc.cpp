// H.264 Constrained-Baseline CAVLC slice coder (host side of tpuenc v1).
//
// Role: turn the device encoder's quantized level arrays + motion vectors
// (selkies_tpu/encoder/h264_device.py) into Annex-B slice NAL units that a
// stock WebCodecs/ffmpeg decoder accepts.  Replaces the entropy-coding
// stage of the reference's x264 path (pixelflux striped-x264; legacy
// gstwebrtc_app.py:609-665 x264enc branch).
//
// Supported subset (by construction of the device encoder):
//   * IDR pictures: every MB its own slice, I_16x16 DC prediction,
//     chroma DC prediction (pred == 128 because all neighbors are outside
//     the slice).
//   * P pictures: one slice, P_L0_16x16 with one MV per MB (or P_Skip when
//     the spec-predicted skip MV matches and the MB has no coefficients).
//   * CAVLC per ITU-T H.264 §9.2 (tables 9-5..9-10), deblocking disabled.
//
// Everything here is sequential per slice but trivially parallel across
// stripes; the Python layer fans stripes across a thread pool.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// bit writer (RBSP), EBSP escaping happens at NAL flush

struct BitWriter {
  std::vector<uint8_t> buf;
  uint32_t acc = 0;
  int nbits = 0;

  void put(uint32_t value, int len) {
    // len <= 24 per call
    acc = (acc << len) | (value & ((len >= 32 ? 0 : (1u << len)) - 1));
    nbits += len;
    while (nbits >= 8) {
      nbits -= 8;
      buf.push_back(static_cast<uint8_t>((acc >> nbits) & 0xFF));
    }
  }
  void put_long(uint32_t value, int len) {   // len up to 32
    if (len > 16) {
      put(value >> 16, len - 16);
      put(value & 0xFFFF, 16);
    } else {
      put(value, len);
    }
  }
  void ue(uint32_t v) {
    // Exp-Golomb
    uint32_t vp1 = v + 1;
    int nb = 0;
    for (uint32_t t = vp1; t > 1; t >>= 1) nb++;
    put_long(0, nb);
    put_long(vp1, nb + 1);
  }
  void se(int32_t v) {
    uint32_t m = v <= 0 ? (uint32_t)(-2 * (int64_t)v) : (uint32_t)(2 * (int64_t)v - 1);
    ue(m);
  }
  void rbsp_trailing() {
    put(1, 1);
    if (nbits) put(0, 8 - nbits);
  }
  void reset() { buf.clear(); acc = 0; nbits = 0; }
};

// append NAL: 4-byte start code + header byte + EBSP-escaped RBSP
bool append_nal(std::vector<uint8_t>& out, int nal_ref_idc, int nal_type,
                const std::vector<uint8_t>& rbsp) {
  out.push_back(0); out.push_back(0); out.push_back(0); out.push_back(1);
  out.push_back(static_cast<uint8_t>((nal_ref_idc << 5) | nal_type));
  int zeros = 0;
  for (uint8_t b : rbsp) {
    if (zeros >= 2 && b <= 3) {
      out.push_back(3);
      zeros = 0;
    }
    out.push_back(b);
    zeros = (b == 0) ? zeros + 1 : 0;
  }
  return true;
}

// ---------------------------------------------------------------------------
// CAVLC tables (ITU-T H.264 Table 9-5): coeff_token per nC class.
// Indexed [class][totalCoeff*4 + trailingOnes] → length / bits.

const uint8_t kCoeffTokenLen[3][68] = {
    {// 0 <= nC < 2
     1, 0, 0, 0, 6, 2, 0, 0, 8, 6, 3, 0, 9, 8, 7, 5,
     10, 9, 8, 6, 11, 10, 9, 7, 13, 11, 10, 8, 13, 13, 11, 9,
     13, 13, 13, 10, 14, 14, 13, 11, 14, 14, 14, 13, 15, 15, 14, 14,
     15, 15, 15, 14, 16, 15, 15, 15, 16, 16, 16, 15, 16, 16, 16, 16,
     16, 16, 16, 16},
    {// 2 <= nC < 4
     2, 0, 0, 0, 6, 2, 0, 0, 6, 5, 3, 0, 7, 6, 6, 4,
     8, 6, 6, 4, 8, 7, 7, 5, 9, 8, 8, 6, 11, 9, 9, 6,
     11, 11, 11, 7, 12, 11, 11, 9, 12, 12, 12, 11, 12, 12, 12, 11,
     13, 13, 13, 12, 13, 13, 13, 13, 13, 14, 13, 13, 14, 14, 14, 13,
     14, 14, 14, 14},
    {// 4 <= nC < 8
     4, 0, 0, 0, 6, 4, 0, 0, 6, 5, 4, 0, 6, 5, 5, 4,
     7, 5, 5, 4, 7, 5, 5, 4, 7, 6, 6, 4, 7, 6, 6, 4,
     8, 7, 7, 5, 8, 8, 7, 6, 9, 8, 8, 7, 9, 9, 8, 8,
     9, 9, 9, 8, 10, 9, 9, 9, 10, 10, 10, 10, 10, 10, 10, 10,
     10, 10, 10, 10},
};

const uint8_t kCoeffTokenBits[3][68] = {
    {1, 0, 0, 0, 5, 1, 0, 0, 7, 4, 1, 0, 7, 6, 5, 3,
     7, 6, 5, 3, 7, 6, 5, 4, 15, 6, 5, 4, 11, 14, 5, 4,
     8, 10, 13, 4, 15, 14, 9, 4, 11, 10, 13, 12, 15, 14, 9, 12,
     11, 10, 13, 8, 15, 1, 9, 12, 11, 14, 13, 8, 7, 10, 9, 12,
     4, 6, 5, 8},
    {3, 0, 0, 0, 11, 2, 0, 0, 7, 7, 3, 0, 7, 10, 9, 5,
     7, 6, 5, 4, 4, 6, 5, 6, 7, 6, 5, 8, 15, 6, 5, 4,
     11, 14, 13, 4, 15, 10, 9, 4, 11, 14, 13, 12, 8, 10, 9, 8,
     15, 14, 13, 12, 11, 10, 9, 12, 7, 11, 6, 8, 9, 8, 10, 1,
     7, 6, 5, 4},
    {15, 0, 0, 0, 15, 14, 0, 0, 11, 15, 13, 0, 8, 12, 14, 12,
     15, 10, 11, 11, 11, 8, 9, 10, 9, 14, 13, 9, 8, 10, 9, 8,
     15, 14, 13, 13, 11, 14, 10, 12, 15, 10, 13, 12, 11, 14, 9, 12,
     8, 10, 13, 8, 13, 7, 9, 12, 9, 12, 11, 10, 5, 8, 7, 6,
     1, 4, 3, 2},
};

// chroma DC (nC == -1), 4:2:0 (maxNumCoeff 4)
const uint8_t kCoeffTokenChromaDCLen[20] = {
    2, 0, 0, 0, 6, 1, 0, 0, 6, 6, 3, 0, 6, 7, 7, 6, 6, 8, 8, 7};
const uint8_t kCoeffTokenChromaDCBits[20] = {
    1, 0, 0, 0, 7, 1, 0, 0, 4, 6, 1, 0, 3, 3, 2, 5, 2, 3, 2, 0};

// total_zeros, 4×4 blocks (Tables 9-7/9-8): [totalCoeff][totalZeros]
const uint8_t kTotalZerosLen[16][16] = {
    {0},
    {1, 3, 3, 4, 4, 5, 5, 6, 6, 7, 7, 8, 8, 9, 9, 9},
    {3, 3, 3, 3, 3, 4, 4, 4, 4, 5, 5, 6, 6, 6, 6},
    {4, 3, 3, 3, 4, 4, 3, 3, 4, 5, 5, 6, 5, 6},
    {5, 3, 4, 4, 3, 3, 3, 4, 3, 4, 5, 5, 5},
    {4, 4, 4, 3, 3, 3, 3, 3, 4, 5, 4, 5},
    {6, 5, 3, 3, 3, 3, 3, 3, 4, 3, 6},
    {6, 5, 3, 3, 3, 2, 3, 4, 3, 6},
    {6, 4, 5, 3, 2, 2, 3, 3, 6},
    {6, 6, 4, 2, 2, 3, 2, 5},
    {5, 5, 3, 2, 2, 2, 4},
    {4, 4, 3, 3, 1, 3},
    {4, 4, 2, 1, 3},
    {3, 3, 1, 2},
    {2, 2, 1},
    {1, 1},
};
const uint8_t kTotalZerosBits[16][16] = {
    {0},
    {1, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 1},
    {7, 6, 5, 4, 3, 5, 4, 3, 2, 3, 2, 3, 2, 1, 0},
    {5, 7, 6, 5, 4, 3, 4, 3, 2, 3, 2, 1, 1, 0},
    {3, 7, 5, 4, 6, 5, 4, 3, 3, 2, 2, 1, 0},
    {5, 4, 3, 7, 6, 5, 4, 3, 2, 1, 1, 0},
    {1, 1, 7, 6, 5, 4, 3, 2, 1, 1, 0},
    {1, 1, 5, 4, 3, 3, 2, 1, 1, 0},
    {1, 1, 1, 3, 3, 2, 2, 1, 0},
    {1, 0, 1, 3, 2, 1, 1, 1},
    {1, 0, 1, 3, 2, 1, 1},
    {0, 1, 1, 2, 1, 3},
    {0, 1, 1, 1, 1},
    {0, 1, 1, 1},
    {0, 1, 1},
    {0, 1},
};

// chroma DC total_zeros (Table 9-9a, 4:2:0): [totalCoeff][totalZeros]
const uint8_t kTotalZerosChromaDCLen[4][4] = {
    {0}, {1, 2, 3, 3}, {1, 2, 2, 0}, {1, 1, 0, 0}};
const uint8_t kTotalZerosChromaDCBits[4][4] = {
    {0}, {1, 1, 1, 0}, {1, 1, 0, 0}, {1, 0, 0, 0}};

// run_before (Table 9-10): [min(zerosLeft,7)][run]
const uint8_t kRunBeforeLen[8][15] = {
    {0},
    {1, 1},
    {1, 2, 2},
    {2, 2, 2, 2},
    {2, 2, 2, 3, 3},
    {2, 2, 3, 3, 3, 3},
    {2, 3, 3, 3, 3, 3, 3},
    {3, 3, 3, 3, 3, 3, 3, 4, 5, 6, 7, 8, 9, 10, 11},
};
const uint8_t kRunBeforeBits[8][15] = {
    {0},
    {1, 0},
    {1, 1, 0},
    {3, 2, 1, 0},
    {3, 2, 1, 1, 0},
    {3, 2, 3, 2, 1, 0},
    {3, 0, 1, 3, 2, 5, 4},
    {7, 6, 5, 4, 3, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1},
};

// coded_block_pattern me(v) mapping for Inter prediction (Table 9-4,
// codeNum → cbp); inverted at first use.
const uint8_t kCbpInterByCodeNum[48] = {
    0,  16, 1,  2,  4,  8,  32, 3,  5,  10, 12, 15, 47, 7,  11, 13,
    14, 6,  9,  31, 35, 37, 42, 44, 33, 34, 36, 40, 39, 43, 45, 46,
    17, 18, 20, 24, 19, 21, 26, 28, 23, 27, 29, 30, 22, 25, 38, 41};

int cbp_inter_code_num(int cbp) {
  static int inv[48];
  static bool init = false;
  if (!init) {
    for (int i = 0; i < 48; i++) inv[kCbpInterByCodeNum[i]] = i;
    init = true;
  }
  return inv[cbp];
}

const int kZigzag4[16] = {0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15};

// ---------------------------------------------------------------------------
// residual_block CAVLC (§9.2)
//
// coeffs: in scan order already (length n_coeff).  nC: luma/chroma-AC
// context value, or -1 for chroma DC.  Returns totalCoeff.

int write_residual_block(BitWriter& bw, const int32_t* coeffs, int n_coeff,
                         int nC) {
  int nz_pos[16];
  int total = 0;
  for (int i = 0; i < n_coeff; i++)
    if (coeffs[i]) nz_pos[total++] = i;

  // coeff_token
  int t1 = 0;
  for (int i = total - 1; i >= 0 && t1 < 3; i--) {
    int32_t v = coeffs[nz_pos[i]];
    if (v == 1 || v == -1) t1++;
    else break;
  }
  if (nC == -1) {
    bw.put(kCoeffTokenChromaDCBits[total * 4 + t1],
           kCoeffTokenChromaDCLen[total * 4 + t1]);
  } else if (nC >= 8) {
    int v = total == 0 ? 3 : ((total - 1) << 2) | t1;
    bw.put(v, 6);
  } else {
    int cls = nC < 2 ? 0 : (nC < 4 ? 1 : 2);
    bw.put(kCoeffTokenBits[cls][total * 4 + t1],
           kCoeffTokenLen[cls][total * 4 + t1]);
  }
  if (total == 0) return 0;

  // trailing-one signs (reverse scan order)
  for (int i = 0; i < t1; i++) {
    int32_t v = coeffs[nz_pos[total - 1 - i]];
    bw.put(v < 0 ? 1 : 0, 1);
  }

  // remaining levels, reverse order
  int suffix_length = (total > 10 && t1 < 3) ? 1 : 0;
  for (int i = total - 1 - t1; i >= 0; i--) {
    int32_t level = coeffs[nz_pos[i]];
    uint32_t mag = level < 0 ? -level : level;
    uint32_t level_code = (mag - 1) * 2 + (level < 0 ? 1 : 0);
    if (i == total - 1 - t1 && t1 < 3) level_code -= 2;

    if (suffix_length == 0) {
      if (level_code < 14) {
        bw.put(1, level_code + 1);                    // prefix zeros + 1
      } else if (level_code < 14 + 16) {
        bw.put(1, 15);                                // prefix 14
        bw.put(level_code - 14, 4);
      } else {
        uint32_t lc = level_code - 30;
        int prefix = 15;
        // spec extension: prefix >= 16 gives (prefix-3)-bit suffix with
        // offset (1<<(prefix-3)) - 4096
        uint32_t limit = 1u << 12;
        while (lc >= limit) {
          lc -= limit;
          prefix++;
          limit = 1u << (prefix - 3);
        }
        bw.put_long(1, prefix + 1);
        bw.put_long(lc, prefix <= 15 ? 12 : prefix - 3);
      }
    } else {
      if (level_code < (15u << suffix_length)) {
        uint32_t prefix = level_code >> suffix_length;
        bw.put_long(1, prefix + 1);
        bw.put(level_code & ((1u << suffix_length) - 1), suffix_length);
      } else {
        uint32_t lc = level_code - (15u << suffix_length);
        int prefix = 15;
        uint32_t limit = 1u << 12;
        while (lc >= limit) {
          lc -= limit;
          prefix++;
          limit = 1u << (prefix - 3);
        }
        bw.put_long(1, prefix + 1);
        bw.put_long(lc, prefix <= 15 ? 12 : prefix - 3);
      }
    }
    if (suffix_length == 0) suffix_length = 1;
    if (mag > (3u << (suffix_length - 1)) && suffix_length < 6)
      suffix_length++;
  }

  // total_zeros
  int max_coeff = (nC == -1) ? 4 : n_coeff;
  int total_zeros = nz_pos[total - 1] + 1 - total;
  if (total < max_coeff) {
    if (nC == -1) {
      bw.put(kTotalZerosChromaDCBits[total][total_zeros],
             kTotalZerosChromaDCLen[total][total_zeros]);
    } else {
      bw.put(kTotalZerosBits[total][total_zeros],
             kTotalZerosLen[total][total_zeros]);
    }
  }

  // run_before, reverse order (not for the last/lowest-frequency coeff)
  int zeros_left = total_zeros;
  for (int i = total - 1; i > 0 && zeros_left > 0; i--) {
    int run = nz_pos[i] - nz_pos[i - 1] - 1;
    int zl = zeros_left < 7 ? zeros_left : 7;
    bw.put(kRunBeforeBits[zl][run], kRunBeforeLen[zl][run]);
    zeros_left -= run;
  }
  return total;
}

// ---------------------------------------------------------------------------
// per-picture encoding state

struct PicCtx {
  int mb_w, mb_h, n_mb;
  const int32_t* mv;         // (n,2) (dy,dx)
  const int32_t* luma;       // (n,16,4,4) raster 4×4 grid within MB
  const int32_t* luma_dc;    // (n,4,4)
  const int32_t* chroma_dc;  // (n,2,2,2)
  const int32_t* chroma_ac;  // (n,2,4,4,4) raster 2×2 grid of 4×4
  // nC context: per-4×4-block totalCoeff, luma grid (mb_h*4 × mb_w*4),
  // chroma grids (mb_h*2 × mb_w*2) per component.  -1 = unavailable.
  std::vector<int8_t> nnz_luma;
  std::vector<int8_t> nnz_cb;
  std::vector<int8_t> nnz_cr;
  // slice id per MB (availability boundary)
  std::vector<int32_t> slice_of;

  void init(int w, int h) {
    mb_w = w; mb_h = h; n_mb = w * h;
    nnz_luma.assign(mb_h * 4 * mb_w * 4, -1);
    nnz_cb.assign(mb_h * 2 * mb_w * 2, -1);
    nnz_cr.assign(mb_h * 2 * mb_w * 2, -1);
    slice_of.assign(n_mb, -1);
  }

  const int32_t* luma_blk(int mb, int r, int c) const {
    return luma + ((mb * 16) + (r * 4 + c)) * 16;
  }
  const int32_t* chroma_blk(int mb, int comp, int r, int c) const {
    return chroma_ac + (((mb * 2 + comp) * 4) + (r * 2 + c)) * 16;
  }

  // nC for a luma 4×4 at global block coords (gr, gc) inside MB `mb`
  int luma_nC(int mb, int gr, int gc) const {
    int na = -1, nb = -1;
    if (gc > 0) {
      int left_mb = (gr / 4) * mb_w + (gc - 1) / 4;
      if (slice_of[left_mb] == slice_of[mb])
        na = nnz_luma[gr * mb_w * 4 + gc - 1];
    }
    if (gr > 0) {
      int top_mb = ((gr - 1) / 4) * mb_w + gc / 4;
      if (slice_of[top_mb] == slice_of[mb])
        nb = nnz_luma[(gr - 1) * mb_w * 4 + gc];
    }
    if (na >= 0 && nb >= 0) return (na + nb + 1) >> 1;
    if (na >= 0) return na;
    if (nb >= 0) return nb;
    return 0;
  }
  int chroma_nC(const std::vector<int8_t>& grid, int mb, int gr,
                int gc) const {
    int na = -1, nb = -1;
    if (gc > 0) {
      int left_mb = (gr / 2) * mb_w + (gc - 1) / 2;
      if (slice_of[left_mb] == slice_of[mb])
        na = grid[gr * mb_w * 2 + gc - 1];
    }
    if (gr > 0) {
      int top_mb = ((gr - 1) / 2) * mb_w + gc / 2;
      if (slice_of[top_mb] == slice_of[mb])
        nb = grid[(gr - 1) * mb_w * 2 + gc];
    }
    if (na >= 0 && nb >= 0) return (na + nb + 1) >> 1;
    if (na >= 0) return na;
    if (nb >= 0) return nb;
    return 0;
  }
};

// spec z-scan emission order of luma 4×4 blocks as (row, col) in the MB
const int kLumaScanRC[16][2] = {
    {0, 0}, {0, 1}, {1, 0}, {1, 1}, {0, 2}, {0, 3}, {1, 2}, {1, 3},
    {2, 0}, {2, 1}, {3, 0}, {3, 1}, {2, 2}, {2, 3}, {3, 2}, {3, 3}};

void scan_block(const int32_t* blk, int32_t* out16) {
  for (int i = 0; i < 16; i++) out16[i] = blk[kZigzag4[i]];
}

struct MbInfo {
  int cbp_luma = 0;    // 4 bits by 8×8
  int cbp_chroma = 0;  // 0/1/2
  bool any_coeff = false;
};

MbInfo analyze_mb(const PicCtx& ctx, int mb, bool intra16) {
  MbInfo info;
  for (int b = 0; b < 16; b++) {
    int r = b / 4, c = b % 4;
    const int32_t* blk = ctx.luma_blk(mb, r, c);
    bool nz = false;
    // for I16 the DC position is carried separately and blk[0] is 0
    for (int i = 0; i < 16; i++)
      if (blk[i]) { nz = true; break; }
    if (nz) info.cbp_luma |= 1 << ((r / 2) * 2 + (c / 2));
  }
  if (intra16) {
    // I_16x16 signals "any AC" as cbp 0 or 15
    info.cbp_luma = info.cbp_luma ? 15 : 0;
  }
  bool dc_nz = false, ac_nz = false;
  for (int comp = 0; comp < 2; comp++) {
    for (int i = 0; i < 4; i++)
      if (ctx.chroma_dc[(mb * 2 + comp) * 4 + i]) dc_nz = true;
    for (int b = 0; b < 4; b++) {
      const int32_t* blk = ctx.chroma_blk(mb, comp, b / 2, b % 2);
      for (int i = 0; i < 16; i++)
        if (blk[i]) { ac_nz = true; break; }
    }
  }
  info.cbp_chroma = ac_nz ? 2 : (dc_nz ? 1 : 0);
  info.any_coeff = info.cbp_luma || info.cbp_chroma;
  return info;
}

// write luma + chroma residuals for one MB and update nC grids
void write_mb_residuals(BitWriter& bw, PicCtx& ctx, int mb, bool intra16,
                        const MbInfo& info) {
  int mby = mb / ctx.mb_w, mbx = mb % ctx.mb_w;
  int32_t scanned[16];

  if (intra16) {
    // Intra16x16DCLevel: 16 coeffs, nC from block (0,0) neighbors
    const int32_t* dc = ctx.luma_dc + mb * 16;
    int32_t dcz[16];
    for (int i = 0; i < 16; i++) dcz[i] = dc[kZigzag4[i]];
    int nC = ctx.luma_nC(mb, mby * 4, mbx * 4);
    write_residual_block(bw, dcz, 16, nC);
  }

  // luma 4×4 blocks in spec scan order
  for (int s = 0; s < 16; s++) {
    int r = kLumaScanRC[s][0], c = kLumaScanRC[s][1];
    int b8 = (r / 2) * 2 + (c / 2);
    int gr = mby * 4 + r, gc = mbx * 4 + c;
    if (!(info.cbp_luma & (1 << b8))) {
      ctx.nnz_luma[gr * ctx.mb_w * 4 + gc] = 0;
      continue;
    }
    const int32_t* blk = ctx.luma_blk(mb, r, c);
    int nC = ctx.luma_nC(mb, gr, gc);
    int total;
    if (intra16) {
      // AC-only: 15 coeffs, scan positions 1..15
      for (int i = 1; i < 16; i++) scanned[i - 1] = blk[kZigzag4[i]];
      total = write_residual_block(bw, scanned, 15, nC);
    } else {
      scan_block(blk, scanned);
      total = write_residual_block(bw, scanned, 16, nC);
    }
    ctx.nnz_luma[gr * ctx.mb_w * 4 + gc] = static_cast<int8_t>(total);
  }

  // chroma DC (both components) then chroma AC
  if (info.cbp_chroma) {
    for (int comp = 0; comp < 2; comp++) {
      const int32_t* dc = ctx.chroma_dc + (mb * 2 + comp) * 4;
      // 2×2 raster order IS the chroma DC scan order
      write_residual_block(bw, dc, 4, -1);
    }
  }
  for (int comp = 0; comp < 2; comp++) {
    std::vector<int8_t>& grid = comp ? ctx.nnz_cr : ctx.nnz_cb;
    for (int b = 0; b < 4; b++) {
      int r = b / 2, c = b % 2;
      int gr = mby * 2 + r, gc = mbx * 2 + c;
      if (info.cbp_chroma != 2) {
        grid[gr * ctx.mb_w * 2 + gc] = 0;
        continue;
      }
      const int32_t* blk = ctx.chroma_blk(mb, comp, r, c);
      for (int i = 1; i < 16; i++) scanned[i - 1] = blk[kZigzag4[i]];
      int nC = ctx.chroma_nC(grid, mb, gr, gc);
      int total = write_residual_block(bw, scanned, 15, nC);
      grid[gr * ctx.mb_w * 2 + gc] = static_cast<int8_t>(total);
    }
  }
}

// median MV prediction for P_16x16 (§8.4.1.3); returns (pred_dy, pred_dx)
void mv_pred(const PicCtx& ctx, const std::vector<uint8_t>& is_coded,
             int mb, int* pred_dy, int* pred_dx, bool* a_avail_out,
             bool* b_avail_out, int* mva_out, int* mvb_out) {
  int mby = mb / ctx.mb_w, mbx = mb % ctx.mb_w;
  // availability within same slice (single slice for P pictures)
  bool a_av = mbx > 0;
  bool b_av = mby > 0;
  bool c_av = mby > 0 && mbx + 1 < ctx.mb_w;
  bool d_av = mby > 0 && mbx > 0;
  const int32_t* mv = ctx.mv;
  int a[2] = {0, 0}, b[2] = {0, 0}, c[2] = {0, 0};
  if (a_av) { a[0] = mv[(mb - 1) * 2]; a[1] = mv[(mb - 1) * 2 + 1]; }
  if (b_av) { b[0] = mv[(mb - ctx.mb_w) * 2]; b[1] = mv[(mb - ctx.mb_w) * 2 + 1]; }
  if (c_av) {
    c[0] = mv[(mb - ctx.mb_w + 1) * 2];
    c[1] = mv[(mb - ctx.mb_w + 1) * 2 + 1];
  } else if (d_av) {
    c[0] = mv[(mb - ctx.mb_w - 1) * 2];
    c[1] = mv[(mb - ctx.mb_w - 1) * 2 + 1];
    c_av = true;
  }
  if (a_avail_out) *a_avail_out = a_av;
  if (b_avail_out) *b_avail_out = b_av;
  if (mva_out) { mva_out[0] = a[0]; mva_out[1] = a[1]; }
  if (mvb_out) { mvb_out[0] = b[0]; mvb_out[1] = b[1]; }
  (void)is_coded;

  // special case: only A "usable" (B, C both unavailable) → pred = A
  if (a_av && !b_av && !c_av) {
    *pred_dy = a[0];
    *pred_dx = a[1];
    return;
  }
  // componentwise median (unavailable → 0, already initialized)
  for (int k = 0; k < 2; k++) {
    int x = a[k], y = b[k], z = c[k];
    int mx = x > y ? (x > z ? (y > z ? y : z) : x)
                   : (y > z ? (x > z ? x : z) : y);
    if (k == 0) *pred_dy = mx; else *pred_dx = mx;
  }
}

// P_Skip predicted MV (§8.4.1.1): zero if A/B unavailable or zero-MV,
// else the median prediction.
void skip_mv(const PicCtx& ctx, int mb, int* dy, int* dx) {
  bool a_av, b_av;
  int mva[2], mvb[2];
  int pdy, pdx;
  mv_pred(ctx, {}, mb, &pdy, &pdx, &a_av, &b_av, mva, mvb);
  if (!a_av || !b_av || (mva[0] == 0 && mva[1] == 0) ||
      (mvb[0] == 0 && mvb[1] == 0)) {
    *dy = 0;
    *dx = 0;
    return;
  }
  *dy = pdy;
  *dx = pdx;
}

// ---------------------------------------------------------------------------
// slice writers

void write_slice_header(BitWriter& bw, bool idr, int first_mb, int qp,
                        int frame_num, int idr_pic_id, int deblock_idc) {
  bw.ue(first_mb);
  bw.ue(idr ? 7 : 5);  // slice_type: I-all / P-all
  bw.ue(0);            // pps id
  bw.put(frame_num & 0xF, 4);
  if (idr) bw.ue(idr_pic_id);
  if (!idr) {
    bw.put(0, 1);  // num_ref_idx_active_override_flag
    bw.put(0, 1);  // ref_pic_list_modification_flag_l0
  }
  // dec_ref_pic_marking (nal_ref_idc != 0)
  if (idr) {
    bw.put(0, 1);  // no_output_of_prior_pics
    bw.put(0, 1);  // long_term_reference
  } else {
    bw.put(0, 1);  // adaptive_ref_pic_marking_mode
  }
  bw.se(qp - 26);  // slice_qp_delta (pic_init_qp = 26)
  bw.ue(deblock_idc);  // disable_deblocking_filter_idc (1 = off)
  if (deblock_idc != 1) {
    bw.se(0);  // slice_alpha_c0_offset_div2
    bw.se(0);  // slice_beta_offset_div2
  }
}

}  // namespace

extern "C" {

// Encode one picture as Annex-B slice NALs.  Returns bytes written, or -1
// on insufficient capacity.
int64_t h264_encode_picture(
    int is_idr, int mb_w, int mb_h, int qp, int frame_num, int idr_pic_id,
    const int32_t* mv, const int32_t* luma, const int32_t* luma_dc,
    const int32_t* chroma_dc, const int32_t* chroma_ac,
    uint8_t* out, int64_t cap, int deblock) {
  PicCtx ctx;
  ctx.init(mb_w, mb_h);
  ctx.mv = mv;
  ctx.luma = luma;
  ctx.luma_dc = luma_dc;
  ctx.chroma_dc = chroma_dc;
  ctx.chroma_ac = chroma_ac;

  std::vector<uint8_t> result;
  result.reserve(1 << 16);
  BitWriter bw;

  if (is_idr) {
    // one slice per MB: prediction neighbors all unavailable → pred 128
    for (int mb = 0; mb < ctx.n_mb; mb++) ctx.slice_of[mb] = mb;
    for (int mb = 0; mb < ctx.n_mb; mb++) {
      bw.reset();
      write_slice_header(bw, true, mb, qp, frame_num, idr_pic_id, 1);
      MbInfo info = analyze_mb(ctx, mb, true);
      // I_16x16: 1 + predMode(2=DC) + 4*cbp_chroma + 12*(cbp_luma==15)
      int mb_type = 1 + 2 + 4 * info.cbp_chroma +
                    (info.cbp_luma == 15 ? 12 : 0);
      bw.ue(mb_type);
      bw.ue(0);  // intra_chroma_pred_mode: DC
      bw.se(0);  // mb_qp_delta
      write_mb_residuals(bw, ctx, mb, true, info);
      bw.rbsp_trailing();
      append_nal(result, 3, 5, bw.buf);
    }
  } else {
    // single P slice
    for (int mb = 0; mb < ctx.n_mb; mb++) ctx.slice_of[mb] = 0;
    bw.reset();
    // deblock=1 → disable_deblocking_filter_idc=0: the decoder runs the
    // in-loop filter over the whole (single-slice) P picture, matching
    // the device-side filter applied to the encoder's reference planes
    // (encoder/deblock.py). IDR slices stay idc=1: per-MB slices would
    // otherwise filter across slice boundaries after decode, and intra
    // pictures are refreshed wholesale anyway.
    write_slice_header(bw, false, 0, qp, frame_num, idr_pic_id,
                       deblock ? 0 : 1);

    // decide skip per MB
    std::vector<MbInfo> infos(ctx.n_mb);
    std::vector<uint8_t> skip(ctx.n_mb, 0);
    for (int mb = 0; mb < ctx.n_mb; mb++) {
      infos[mb] = analyze_mb(ctx, mb, false);
      if (!infos[mb].any_coeff) {
        int sdy, sdx;
        skip_mv(ctx, mb, &sdy, &sdx);
        if (sdy == ctx.mv[mb * 2] && sdx == ctx.mv[mb * 2 + 1]) skip[mb] = 1;
      }
    }

    int run = 0;
    for (int mb = 0; mb < ctx.n_mb; mb++) {
      if (skip[mb]) {
        run++;
        // skipped MB: all nnz contexts go to 0
        int mby = mb / ctx.mb_w, mbx = mb % ctx.mb_w;
        for (int r = 0; r < 4; r++)
          for (int c = 0; c < 4; c++)
            ctx.nnz_luma[(mby * 4 + r) * ctx.mb_w * 4 + mbx * 4 + c] = 0;
        for (int r = 0; r < 2; r++)
          for (int c = 0; c < 2; c++) {
            ctx.nnz_cb[(mby * 2 + r) * ctx.mb_w * 2 + mbx * 2 + c] = 0;
            ctx.nnz_cr[(mby * 2 + r) * ctx.mb_w * 2 + mbx * 2 + c] = 0;
          }
        continue;
      }
      bw.ue(run);
      run = 0;
      const MbInfo& info = infos[mb];
      bw.ue(0);  // mb_type P_L0_16x16
      int pdy, pdx;
      mv_pred(ctx, skip, mb, &pdy, &pdx, nullptr, nullptr, nullptr, nullptr);
      // mvd order: x (horizontal) first.  MVs are integer-pel; the
      // bitstream carries quarter-pel units.
      bw.se(ctx.mv[mb * 2 + 1] * 4 - pdx * 4);
      bw.se(ctx.mv[mb * 2] * 4 - pdy * 4);
      bw.ue(cbp_inter_code_num(info.cbp_luma | (info.cbp_chroma << 4)));
      if (info.any_coeff) bw.se(0);  // mb_qp_delta
      write_mb_residuals(bw, ctx, mb, false, info);
    }
    if (run > 0) bw.ue(run);
    bw.rbsp_trailing();
    append_nal(result, 3, 1, bw.buf);
  }

  if (static_cast<int64_t>(result.size()) > cap) return -1;
  std::memcpy(out, result.data(), result.size());
  return static_cast<int64_t>(result.size());
}

}  // extern "C"
