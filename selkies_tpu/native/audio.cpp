// Audio runtime: Opus encode/decode + optional PulseAudio capture/playback.
//
// The pcmflux-equivalent of this framework (reference consumes pcmflux's
// AudioCaptureSettings/AudioCapture/AudioChunkCallback, selkies.py:1005-1026;
// the legacy pipeline is pulsesrc→opusenc, gstwebrtc_app.py:1004-1121).
// Audio stays on CPU — it is not a TPU target (SURVEY.md §7).
//
// All external deps are dlopen'd with locally-declared prototypes for the
// stable public APIs, so the lib builds with no dev headers installed and
// degrades gracefully: sa_opus_available()/sa_pulse_available() report what
// the host actually has.

#include <dlfcn.h>

#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// libopus (public API, opus.h)

typedef struct OpusEncoder OpusEncoder;
typedef struct OpusDecoder OpusDecoder;

constexpr int OPUS_APPLICATION_AUDIO = 2049;
constexpr int OPUS_APPLICATION_RESTRICTED_LOWDELAY = 2051;
constexpr int OPUS_SET_BITRATE = 4002;
constexpr int OPUS_SET_VBR = 4006;
constexpr int OPUS_SET_COMPLEXITY = 4010;
constexpr int OPUS_SET_INBAND_FEC = 4012;
constexpr int OPUS_SET_PACKET_LOSS_PERC = 4014;

struct OpusApi {
    OpusEncoder *(*encoder_create)(int32_t, int, int, int *);
    int32_t (*encode)(OpusEncoder *, const int16_t *, int, uint8_t *, int32_t);
    int (*encoder_ctl)(OpusEncoder *, int, ...);
    void (*encoder_destroy)(OpusEncoder *);
    OpusDecoder *(*decoder_create)(int32_t, int, int *);
    int (*decode)(OpusDecoder *, const uint8_t *, int32_t, int16_t *, int, int);
    void (*decoder_destroy)(OpusDecoder *);
    bool ok = false;
};

OpusApi *opus_api() {
    static OpusApi api;
    static bool tried = false;
    if (tried) return api.ok ? &api : nullptr;
    tried = true;
    void *h = dlopen("libopus.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libopus.so", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return nullptr;
    api.encoder_create = (OpusEncoder * (*)(int32_t, int, int, int *))
        dlsym(h, "opus_encoder_create");
    api.encode = (int32_t(*)(OpusEncoder *, const int16_t *, int, uint8_t *,
                             int32_t))dlsym(h, "opus_encode");
    api.encoder_ctl = (int (*)(OpusEncoder *, int, ...))
        dlsym(h, "opus_encoder_ctl");
    api.encoder_destroy = (void (*)(OpusEncoder *))
        dlsym(h, "opus_encoder_destroy");
    api.decoder_create = (OpusDecoder * (*)(int32_t, int, int *))
        dlsym(h, "opus_decoder_create");
    api.decode = (int (*)(OpusDecoder *, const uint8_t *, int32_t, int16_t *,
                          int, int))dlsym(h, "opus_decode");
    api.decoder_destroy = (void (*)(OpusDecoder *))
        dlsym(h, "opus_decoder_destroy");
    api.ok = api.encoder_create && api.encode && api.encoder_ctl &&
             api.encoder_destroy && api.decoder_create && api.decode &&
             api.decoder_destroy;
    return api.ok ? &api : nullptr;
}

// ---------------------------------------------------------------------------
// libpulse-simple (public API, pulse/simple.h) — optional

typedef struct pa_simple pa_simple;

struct pa_sample_spec {
    int format;       // PA_SAMPLE_S16LE = 3
    uint32_t rate;
    uint8_t channels;
};

constexpr int PA_SAMPLE_S16LE = 3;
constexpr int PA_STREAM_PLAYBACK = 1;
constexpr int PA_STREAM_RECORD = 2;

struct PulseApi {
    pa_simple *(*simple_new)(const char *, const char *, int, const char *,
                             const char *, const pa_sample_spec *,
                             const void *, const void *, int *);
    int (*simple_read)(pa_simple *, void *, size_t, int *);
    int (*simple_write)(pa_simple *, const void *, size_t, int *);
    void (*simple_free)(pa_simple *);
    bool ok = false;
};

PulseApi *pulse_api() {
    static PulseApi api;
    static bool tried = false;
    if (tried) return api.ok ? &api : nullptr;
    tried = true;
    void *h = dlopen("libpulse-simple.so.0", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return nullptr;
    api.simple_new = (pa_simple * (*)(const char *, const char *, int,
                                      const char *, const char *,
                                      const pa_sample_spec *, const void *,
                                      const void *, int *))
        dlsym(h, "pa_simple_new");
    api.simple_read = (int (*)(pa_simple *, void *, size_t, int *))
        dlsym(h, "pa_simple_read");
    api.simple_write = (int (*)(pa_simple *, const void *, size_t, int *))
        dlsym(h, "pa_simple_write");
    api.simple_free = (void (*)(pa_simple *))dlsym(h, "pa_simple_free");
    api.ok = api.simple_new && api.simple_read && api.simple_write &&
             api.simple_free;
    return api.ok ? &api : nullptr;
}

}  // namespace

extern "C" {

int sa_opus_available() { return opus_api() != nullptr; }
int sa_pulse_available() { return pulse_api() != nullptr; }

// -- encoder ----------------------------------------------------------------

void *sa_enc_new(int rate, int channels, int bitrate, int vbr,
                 int complexity, int lowdelay, int inband_fec) {
    OpusApi *api = opus_api();
    if (!api) return nullptr;
    int err = 0;
    OpusEncoder *e = api->encoder_create(
        rate, channels,
        lowdelay ? OPUS_APPLICATION_RESTRICTED_LOWDELAY
                 : OPUS_APPLICATION_AUDIO,
        &err);
    if (!e || err != 0) return nullptr;
    api->encoder_ctl(e, OPUS_SET_BITRATE, bitrate);
    api->encoder_ctl(e, OPUS_SET_VBR, vbr ? 1 : 0);
    api->encoder_ctl(e, OPUS_SET_COMPLEXITY, complexity);
    if (inband_fec) {
        api->encoder_ctl(e, OPUS_SET_INBAND_FEC, 1);
        api->encoder_ctl(e, OPUS_SET_PACKET_LOSS_PERC, 5);
    }
    return e;
}

// pcm: interleaved s16, `frames` samples per channel (must be a valid Opus
// frame size for the rate, e.g. 960 for 20 ms @ 48 kHz).  Returns packet
// bytes written, or negative opus error.
int sa_enc_encode(void *h, const int16_t *pcm, int frames, uint8_t *out,
                  int32_t cap) {
    OpusApi *api = opus_api();
    if (!api || !h) return -1;
    return api->encode((OpusEncoder *)h, pcm, frames, out, cap);
}

void sa_enc_free(void *h) {
    OpusApi *api = opus_api();
    if (api && h) api->encoder_destroy((OpusEncoder *)h);
}

// -- decoder ----------------------------------------------------------------

void *sa_dec_new(int rate, int channels) {
    OpusApi *api = opus_api();
    if (!api) return nullptr;
    int err = 0;
    OpusDecoder *d = api->decoder_create(rate, channels, &err);
    return (err == 0) ? d : nullptr;
}

// Returns decoded samples per channel (≤ max_frames), or negative error.
int sa_dec_decode(void *h, const uint8_t *data, int32_t size, int16_t *out,
                  int max_frames) {
    OpusApi *api = opus_api();
    if (!api || !h) return -1;
    return api->decode((OpusDecoder *)h, data, size, out, max_frames, 0);
}

// In-band FEC recovery: reconstruct the LOST frame from the redundant
// data embedded in the FOLLOWING packet (fec=1). max_frames must equal
// the lost frame's duration (e.g. 960 for 20 ms @ 48 kHz).
int sa_dec_decode_fec(void *h, const uint8_t *data, int32_t size,
                      int16_t *out, int max_frames) {
    OpusApi *api = opus_api();
    if (!api || !h) return -1;
    return api->decode((OpusDecoder *)h, data, size, out, max_frames, 1);
}

// Packet-loss concealment: synthesize max_frames samples with no packet.
int sa_dec_plc(void *h, int16_t *out, int max_frames) {
    OpusApi *api = opus_api();
    if (!api || !h) return -1;
    return api->decode((OpusDecoder *)h, nullptr, 0, out, max_frames, 0);
}

void sa_dec_free(void *h) {
    OpusApi *api = opus_api();
    if (api && h) api->decoder_destroy((OpusDecoder *)h);
}

// -- PulseAudio capture / playback (optional on this host) -------------------

void *sa_pa_new(const char *device, int rate, int channels, int playback,
                const char *stream_name) {
    PulseApi *api = pulse_api();
    if (!api) return nullptr;
    pa_sample_spec ss;
    ss.format = PA_SAMPLE_S16LE;
    ss.rate = (uint32_t)rate;
    ss.channels = (uint8_t)channels;
    int err = 0;
    const char *dev = (device && device[0]) ? device : nullptr;
    return api->simple_new(nullptr, "selkies-tpu",
                           playback ? PA_STREAM_PLAYBACK : PA_STREAM_RECORD,
                           dev, stream_name ? stream_name : "stream", &ss,
                           nullptr, nullptr, &err);
}

int sa_pa_read(void *h, int16_t *out, int64_t bytes) {
    PulseApi *api = pulse_api();
    if (!api || !h) return -1;
    int err = 0;
    return api->simple_read((pa_simple *)h, out, (size_t)bytes, &err) == 0
               ? 0 : -err;
}

int sa_pa_write(void *h, const int16_t *pcm, int64_t bytes) {
    PulseApi *api = pulse_api();
    if (!api || !h) return -1;
    int err = 0;
    return api->simple_write((pa_simple *)h, pcm, (size_t)bytes, &err) == 0
               ? 0 : -err;
}

void sa_pa_free(void *h) {
    PulseApi *api = pulse_api();
    if (api && h) api->simple_free((pa_simple *)h);
}

}  // extern "C"
