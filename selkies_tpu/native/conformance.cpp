// Conformance decoder for tpuenc bitstreams, backed by the system libavcodec.
//
// The browser's WebCodecs VideoDecoder/ImageDecoder are the real consumers of
// the tpuenc H.264/JPEG output (reference client selkies-core.js:2032,2155,
// 2925-2968); bitstream bugs there present as silent black canvases.  This
// lib gives CI an equivalent oracle: decode our Annex-B / JFIF output with a
// production decoder and compare the pixels against the encoder's own
// reconstruction (H.264: must be bit-exact; JPEG: close to source).
//
// Built lazily by selkies_tpu.native.conformance_lib(); only used by tests
// and debug tooling, never on the streaming hot path.

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavutil/imgutils.h>
}

#include <cstdint>
#include <cstring>

namespace {

struct Dec {
    const AVCodec *codec = nullptr;
    AVCodecContext *ctx = nullptr;
    AVFrame *frame = nullptr;
    AVPacket *pkt = nullptr;
};

Dec *dec_new(AVCodecID id) {
    const AVCodec *codec = avcodec_find_decoder(id);
    if (!codec) return nullptr;
    Dec *d = new Dec();
    d->codec = codec;
    d->ctx = avcodec_alloc_context3(codec);
    if (!d->ctx) { delete d; return nullptr; }
    // our streams have no reordering (poc type 2, no B-frames)
    d->ctx->flags |= AV_CODEC_FLAG_LOW_DELAY;
    if (avcodec_open2(d->ctx, codec, nullptr) < 0) {
        avcodec_free_context(&d->ctx);
        delete d;
        return nullptr;
    }
    d->frame = av_frame_alloc();
    d->pkt = av_packet_alloc();
    return d;
}

void dec_free(Dec *d) {
    if (!d) return;
    if (d->pkt) av_packet_free(&d->pkt);
    if (d->frame) av_frame_free(&d->frame);
    if (d->ctx) avcodec_free_context(&d->ctx);
    delete d;
}

// Copy one decoded frame's planes into tightly-packed caller buffers of
// y_cap / c_cap bytes.  Returns 0 on success, -6 if the frame exceeds the
// caller's capacity (never writes past it).
int copy_planes(const AVFrame *f, uint8_t *y, uint8_t *u, uint8_t *v,
                int64_t y_cap, int64_t c_cap, int *out_w, int *out_h) {
    const int w = f->width, h = f->height;
    *out_w = w;
    *out_h = h;
    const AVPixelFormat fmt = (AVPixelFormat)f->format;
    if (fmt != AV_PIX_FMT_YUV420P && fmt != AV_PIX_FMT_YUVJ420P)
        return -2;
    if ((int64_t)w * h > y_cap
        || (int64_t)((w + 1) / 2) * ((h + 1) / 2) > c_cap)
        return -6;
    for (int r = 0; r < h; ++r)
        memcpy(y + (size_t)r * w, f->data[0] + (size_t)r * f->linesize[0], w);
    const int cw = (w + 1) / 2, ch = (h + 1) / 2;
    for (int r = 0; r < ch; ++r) {
        memcpy(u + (size_t)r * cw, f->data[1] + (size_t)r * f->linesize[1], cw);
        memcpy(v + (size_t)r * cw, f->data[2] + (size_t)r * f->linesize[2], cw);
    }
    return 0;
}

}  // namespace

extern "C" {

void *conf_h264_new() { return dec_new(AV_CODEC_ID_H264); }
void *conf_mjpeg_new() { return dec_new(AV_CODEC_ID_MJPEG); }

void conf_dec_free(void *h) { dec_free((Dec *)h); }

// Feed one access unit (or a whole SPS+PPS+slice chunk); returns the number
// of frames decoded out (0 or 1 for our low-delay streams), negative on
// error.  On 1, the planes are written into y/u/v and dims into out_w/out_h.
int conf_dec_decode(void *h, const uint8_t *data, int64_t size,
                    uint8_t *y, uint8_t *u, uint8_t *v,
                    int64_t y_cap, int64_t c_cap,
                    int *out_w, int *out_h) {
    Dec *d = (Dec *)h;
    if (!d) return -1;
    // libavcodec requires input padding
    uint8_t *buf = (uint8_t *)av_malloc(size + AV_INPUT_BUFFER_PADDING_SIZE);
    if (!buf) return -1;
    memcpy(buf, data, size);
    memset(buf + size, 0, AV_INPUT_BUFFER_PADDING_SIZE);
    av_packet_unref(d->pkt);
    d->pkt->data = buf;
    d->pkt->size = (int)size;
    int rc = avcodec_send_packet(d->ctx, d->pkt);
    d->pkt->data = nullptr;
    d->pkt->size = 0;
    av_free(buf);
    if (rc < 0) return -3;
    int got = 0;
    while (true) {
        rc = avcodec_receive_frame(d->ctx, d->frame);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        int cp = copy_planes(d->frame, y, u, v, y_cap, c_cap, out_w, out_h);
        if (cp != 0) return cp == -6 ? -6 : -5;
        got += 1;
    }
    return got;
}

// Drain buffered frames at end of stream (harmless for low-delay streams).
int conf_dec_flush(void *h, uint8_t *y, uint8_t *u, uint8_t *v,
                   int64_t y_cap, int64_t c_cap,
                   int *out_w, int *out_h) {
    Dec *d = (Dec *)h;
    if (!d) return -1;
    if (avcodec_send_packet(d->ctx, nullptr) < 0) return -3;
    int got = 0;
    while (true) {
        int rc = avcodec_receive_frame(d->ctx, d->frame);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        int cp = copy_planes(d->frame, y, u, v, y_cap, c_cap, out_w, out_h);
        if (cp != 0) return cp == -6 ? -6 : -5;
        got += 1;
    }
    return got;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Reference x264 encoder (quality-gate tooling, VERDICT r3 item 4).
//
// The reference's daily driver is pixelflux's x264 at preset superfast with
// zerolatency tuning (reference gstwebrtc_app.py:609-640 x264enc
// speed-preset=superfast tune=zerolatency). tools/quality_measure.py encodes
// the same frames through THIS encoder and through tpuenc-H.264 and compares
// rate/distortion — the gate that decides whether deblocking/sub-pel/intra-4x4
// are worth building.  Tooling only, never on the streaming path.

namespace {

struct Enc {
    AVCodecContext *ctx = nullptr;
    AVFrame *frame = nullptr;
    AVPacket *pkt = nullptr;
    int64_t pts = 0;
};

void enc_free(Enc *e) {
    if (!e) return;
    if (e->pkt) av_packet_free(&e->pkt);
    if (e->frame) av_frame_free(&e->frame);
    if (e->ctx) avcodec_free_context(&e->ctx);
    delete e;
}

}  // namespace

extern "C" {

// crf >= 0 selects CRF rate control; bitrate_kbps > 0 selects ABR instead.
void *conf_x264_new(int w, int h, int crf, int bitrate_kbps,
                    const char *preset) {
    const AVCodec *codec = avcodec_find_encoder_by_name("libx264");
    if (!codec) return nullptr;
    Enc *e = new Enc();
    e->ctx = avcodec_alloc_context3(codec);
    if (!e->ctx) { delete e; return nullptr; }
    e->ctx->width = w;
    e->ctx->height = h;
    e->ctx->time_base = {1, 60};
    e->ctx->framerate = {60, 1};
    e->ctx->pix_fmt = AV_PIX_FMT_YUV420P;
    e->ctx->gop_size = 600;            // streaming posture: IDR then P's
    e->ctx->max_b_frames = 0;
    AVDictionary *opts = nullptr;
    av_dict_set(&opts, "preset", preset ? preset : "superfast", 0);
    av_dict_set(&opts, "tune", "zerolatency", 0);
    if (crf >= 0) {
        char buf[16];
        snprintf(buf, sizeof buf, "%d", crf);
        av_dict_set(&opts, "crf", buf, 0);
    } else if (bitrate_kbps > 0) {
        e->ctx->bit_rate = (int64_t)bitrate_kbps * 1000;
    }
    if (avcodec_open2(e->ctx, codec, &opts) < 0) {
        av_dict_free(&opts);
        enc_free(e);
        return nullptr;
    }
    av_dict_free(&opts);
    e->frame = av_frame_alloc();
    e->pkt = av_packet_alloc();
    e->frame->format = AV_PIX_FMT_YUV420P;
    e->frame->width = w;
    e->frame->height = h;
    if (av_frame_get_buffer(e->frame, 0) < 0) { enc_free(e); return nullptr; }
    return e;
}

void conf_enc_free(void *h) { enc_free((Enc *)h); }

// Encode one tightly-packed YUV420 frame; appends any produced packets to
// `out` (Annex-B) and returns bytes written (0 = buffered), negative on error.
int64_t conf_enc_encode(void *h, const uint8_t *y, const uint8_t *u,
                        const uint8_t *v, uint8_t *out, int64_t out_cap) {
    Enc *e = (Enc *)h;
    if (!e) return -1;
    if (av_frame_make_writable(e->frame) < 0) return -2;
    const int w = e->ctx->width, hgt = e->ctx->height;
    for (int r = 0; r < hgt; ++r)
        memcpy(e->frame->data[0] + (size_t)r * e->frame->linesize[0],
               y + (size_t)r * w, w);
    const int cw = (w + 1) / 2, ch = (hgt + 1) / 2;
    for (int r = 0; r < ch; ++r) {
        memcpy(e->frame->data[1] + (size_t)r * e->frame->linesize[1],
               u + (size_t)r * cw, cw);
        memcpy(e->frame->data[2] + (size_t)r * e->frame->linesize[2],
               v + (size_t)r * cw, cw);
    }
    e->frame->pts = e->pts++;
    if (avcodec_send_frame(e->ctx, e->frame) < 0) return -3;
    int64_t n = 0;
    while (true) {
        int rc = avcodec_receive_packet(e->ctx, e->pkt);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        if (n + e->pkt->size > out_cap) { av_packet_unref(e->pkt); return -6; }
        memcpy(out + n, e->pkt->data, e->pkt->size);
        n += e->pkt->size;
        av_packet_unref(e->pkt);
    }
    return n;
}

int64_t conf_enc_flush(void *h, uint8_t *out, int64_t out_cap) {
    Enc *e = (Enc *)h;
    if (!e) return -1;
    if (avcodec_send_frame(e->ctx, nullptr) < 0) return -3;
    int64_t n = 0;
    while (true) {
        int rc = avcodec_receive_packet(e->ctx, e->pkt);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        if (n + e->pkt->size > out_cap) { av_packet_unref(e->pkt); return -6; }
        memcpy(out + n, e->pkt->data, e->pkt->size);
        n += e->pkt->size;
        av_packet_unref(e->pkt);
    }
    return n;
}

}  // extern "C"
