// Conformance decoder for tpuenc bitstreams, backed by the system libavcodec.
//
// The browser's WebCodecs VideoDecoder/ImageDecoder are the real consumers of
// the tpuenc H.264/JPEG output (reference client selkies-core.js:2032,2155,
// 2925-2968); bitstream bugs there present as silent black canvases.  This
// lib gives CI an equivalent oracle: decode our Annex-B / JFIF output with a
// production decoder and compare the pixels against the encoder's own
// reconstruction (H.264: must be bit-exact; JPEG: close to source).
//
// Built lazily by selkies_tpu.native.conformance_lib(); only used by tests
// and debug tooling, never on the streaming hot path.

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavutil/imgutils.h>
}

#include <cstdint>
#include <cstring>

namespace {

struct Dec {
    const AVCodec *codec = nullptr;
    AVCodecContext *ctx = nullptr;
    AVFrame *frame = nullptr;
    AVPacket *pkt = nullptr;
};

Dec *dec_new(AVCodecID id) {
    const AVCodec *codec = avcodec_find_decoder(id);
    if (!codec) return nullptr;
    Dec *d = new Dec();
    d->codec = codec;
    d->ctx = avcodec_alloc_context3(codec);
    if (!d->ctx) { delete d; return nullptr; }
    // our streams have no reordering (poc type 2, no B-frames)
    d->ctx->flags |= AV_CODEC_FLAG_LOW_DELAY;
    if (avcodec_open2(d->ctx, codec, nullptr) < 0) {
        avcodec_free_context(&d->ctx);
        delete d;
        return nullptr;
    }
    d->frame = av_frame_alloc();
    d->pkt = av_packet_alloc();
    return d;
}

void dec_free(Dec *d) {
    if (!d) return;
    if (d->pkt) av_packet_free(&d->pkt);
    if (d->frame) av_frame_free(&d->frame);
    if (d->ctx) avcodec_free_context(&d->ctx);
    delete d;
}

// Copy one decoded frame's planes into tightly-packed caller buffers of
// y_cap / c_cap bytes.  Returns 0 on success, -6 if the frame exceeds the
// caller's capacity (never writes past it).
int copy_planes(const AVFrame *f, uint8_t *y, uint8_t *u, uint8_t *v,
                int64_t y_cap, int64_t c_cap, int *out_w, int *out_h) {
    const int w = f->width, h = f->height;
    *out_w = w;
    *out_h = h;
    const AVPixelFormat fmt = (AVPixelFormat)f->format;
    if (fmt != AV_PIX_FMT_YUV420P && fmt != AV_PIX_FMT_YUVJ420P)
        return -2;
    if ((int64_t)w * h > y_cap
        || (int64_t)((w + 1) / 2) * ((h + 1) / 2) > c_cap)
        return -6;
    for (int r = 0; r < h; ++r)
        memcpy(y + (size_t)r * w, f->data[0] + (size_t)r * f->linesize[0], w);
    const int cw = (w + 1) / 2, ch = (h + 1) / 2;
    for (int r = 0; r < ch; ++r) {
        memcpy(u + (size_t)r * cw, f->data[1] + (size_t)r * f->linesize[1], cw);
        memcpy(v + (size_t)r * cw, f->data[2] + (size_t)r * f->linesize[2], cw);
    }
    return 0;
}

}  // namespace

extern "C" {

void *conf_h264_new() { return dec_new(AV_CODEC_ID_H264); }
void *conf_mjpeg_new() { return dec_new(AV_CODEC_ID_MJPEG); }

void conf_dec_free(void *h) { dec_free((Dec *)h); }

// Feed one access unit (or a whole SPS+PPS+slice chunk); returns the number
// of frames decoded out (0 or 1 for our low-delay streams), negative on
// error.  On 1, the planes are written into y/u/v and dims into out_w/out_h.
int conf_dec_decode(void *h, const uint8_t *data, int64_t size,
                    uint8_t *y, uint8_t *u, uint8_t *v,
                    int64_t y_cap, int64_t c_cap,
                    int *out_w, int *out_h) {
    Dec *d = (Dec *)h;
    if (!d) return -1;
    // libavcodec requires input padding
    uint8_t *buf = (uint8_t *)av_malloc(size + AV_INPUT_BUFFER_PADDING_SIZE);
    if (!buf) return -1;
    memcpy(buf, data, size);
    memset(buf + size, 0, AV_INPUT_BUFFER_PADDING_SIZE);
    av_packet_unref(d->pkt);
    d->pkt->data = buf;
    d->pkt->size = (int)size;
    int rc = avcodec_send_packet(d->ctx, d->pkt);
    d->pkt->data = nullptr;
    d->pkt->size = 0;
    av_free(buf);
    if (rc < 0) return -3;
    int got = 0;
    while (true) {
        rc = avcodec_receive_frame(d->ctx, d->frame);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        int cp = copy_planes(d->frame, y, u, v, y_cap, c_cap, out_w, out_h);
        if (cp != 0) return cp == -6 ? -6 : -5;
        got += 1;
    }
    return got;
}

// Drain buffered frames at end of stream (harmless for low-delay streams).
int conf_dec_flush(void *h, uint8_t *y, uint8_t *u, uint8_t *v,
                   int64_t y_cap, int64_t c_cap,
                   int *out_w, int *out_h) {
    Dec *d = (Dec *)h;
    if (!d) return -1;
    if (avcodec_send_packet(d->ctx, nullptr) < 0) return -3;
    int got = 0;
    while (true) {
        int rc = avcodec_receive_frame(d->ctx, d->frame);
        if (rc == AVERROR(EAGAIN) || rc == AVERROR_EOF) break;
        if (rc < 0) return -4;
        int cp = copy_planes(d->frame, y, u, v, y_cap, c_cap, out_w, out_h);
        if (cp != 0) return cp == -6 ? -6 : -5;
        got += 1;
    }
    return got;
}

}  // extern "C"
