// Baseline-JPEG Huffman entropy coder — native runtime component.
//
// The TPU device pipeline emits zigzagged, quantized int16 DCT coefficients;
// entropy coding is inherently serial/branchy (wrong shape for the MXU/VPU),
// so it runs here on host, overlapped with the next frame's device dispatch.
// This mirrors the reference's split where pixelflux's C++ threads own the
// bitstream (reference: pixelflux consumed at selkies.py:2897-2904) — but the
// transform half of the codec lives on TPU instead of in libjpeg/x264.
//
// Python binding is ctypes (see selkies_tpu/native/__init__.py); the
// pure-Python oracle is selkies_tpu/encoder/entropy_py.py.

#include <cstdint>
#include <cstring>

namespace {

struct BitWriter {
  uint8_t* out;
  int64_t cap;
  int64_t pos = 0;
  uint64_t acc = 0;
  int nbits = 0;
  bool overflow = false;

  inline void put_byte(uint8_t b) {
    if (pos >= cap) { overflow = true; return; }
    out[pos++] = b;
    if (b == 0xFF) {          // JPEG byte stuffing
      if (pos >= cap) { overflow = true; return; }
      out[pos++] = 0x00;
    }
  }

  inline void write(uint32_t value, int n) {
    if (n == 0) return;
    acc = (acc << n) | (value & ((1u << n) - 1u));
    nbits += n;
    while (nbits >= 8) {
      nbits -= 8;
      put_byte((uint8_t)((acc >> nbits) & 0xFF));
    }
    acc &= (1ull << nbits) - 1ull;
  }

  inline void flush() {
    if (nbits) {
      int pad = 8 - nbits;
      write((1u << pad) - 1u, pad);  // pad with 1-bits (T.81 F.1.2.3)
    }
  }
};

struct HuffLut {
  const uint32_t* code;  // [256]
  const uint8_t* len;    // [256]
};

// Magnitude category: number of bits in |v| (T.81 F.1.2.1).
inline int cat(int v) {
  unsigned a = (unsigned)(v < 0 ? -v : v);
  if (a == 0) return 0;
  return 32 - __builtin_clz(a);
}

// Encode one zigzagged 64-coeff block; returns the block's DC value.
inline int encode_block(BitWriter& bw, const int16_t* zz, int pred_dc,
                        const HuffLut& dc, const HuffLut& ac) {
  int dcv = zz[0];
  int diff = dcv - pred_dc;
  int size = cat(diff);
  bw.write(dc.code[size], dc.len[size]);
  if (size) bw.write((uint32_t)(diff > 0 ? diff : diff + (1 << size) - 1), size);

  int run = 0;
  for (int k = 1; k < 64; ++k) {
    int v = zz[k];
    if (v == 0) { ++run; continue; }
    while (run >= 16) {
      bw.write(ac.code[0xF0], ac.len[0xF0]);  // ZRL
      run -= 16;
    }
    int s = cat(v);
    int sym = (run << 4) | s;
    bw.write(ac.code[sym], ac.len[sym]);
    bw.write((uint32_t)(v > 0 ? v : v + (1 << s) - 1), s);
    run = 0;
  }
  if (run) bw.write(ac.code[0x00], ac.len[0x00]);  // EOB
  return dcv;
}

}  // namespace

extern "C" {

// 4:2:0 interleaved scan: MCU = 4 Y blocks (2x2) + Cb + Cr.
// y:  [by,  bx,  64] int16 (by, bx even), cb/cr: [by/2, bx/2, 64].
// Returns bytes written, or -1 on output overflow.
int64_t jpeg_encode_scan_420(
    const int16_t* y, const int16_t* cb, const int16_t* cr,
    int by, int bx,
    const uint32_t* dc_l_code, const uint8_t* dc_l_len,
    const uint32_t* ac_l_code, const uint8_t* ac_l_len,
    const uint32_t* dc_c_code, const uint8_t* dc_c_len,
    const uint32_t* ac_c_code, const uint8_t* ac_c_len,
    uint8_t* out, int64_t out_capacity) {
  BitWriter bw{out, out_capacity};
  HuffLut dcl{dc_l_code, dc_l_len}, acl{ac_l_code, ac_l_len};
  HuffLut dcc{dc_c_code, dc_c_len}, acc_{ac_c_code, ac_c_len};
  int pred_y = 0, pred_cb = 0, pred_cr = 0;
  int cbx = bx / 2;
  for (int mr = 0; mr < by / 2; ++mr) {
    for (int mc = 0; mc < bx / 2; ++mc) {
      for (int dy2 = 0; dy2 < 2; ++dy2)
        for (int dx2 = 0; dx2 < 2; ++dx2)
          pred_y = encode_block(
              bw, y + (((int64_t)(2 * mr + dy2) * bx + (2 * mc + dx2)) << 6),
              pred_y, dcl, acl);
      pred_cb = encode_block(bw, cb + (((int64_t)mr * cbx + mc) << 6),
                             pred_cb, dcc, acc_);
      pred_cr = encode_block(bw, cr + (((int64_t)mr * cbx + mc) << 6),
                             pred_cr, dcc, acc_);
      if (bw.overflow) return -1;
    }
  }
  bw.flush();
  return bw.overflow ? -1 : bw.pos;
}

// 4:4:4 interleaved scan: MCU = Y + Cb + Cr, all [by, bx, 64].
int64_t jpeg_encode_scan_444(
    const int16_t* y, const int16_t* cb, const int16_t* cr,
    int by, int bx,
    const uint32_t* dc_l_code, const uint8_t* dc_l_len,
    const uint32_t* ac_l_code, const uint8_t* ac_l_len,
    const uint32_t* dc_c_code, const uint8_t* dc_c_len,
    const uint32_t* ac_c_code, const uint8_t* ac_c_len,
    uint8_t* out, int64_t out_capacity) {
  BitWriter bw{out, out_capacity};
  HuffLut dcl{dc_l_code, dc_l_len}, acl{ac_l_code, ac_l_len};
  HuffLut dcc{dc_c_code, dc_c_len}, acc_{ac_c_code, ac_c_len};
  int pred_y = 0, pred_cb = 0, pred_cr = 0;
  for (int r = 0; r < by; ++r) {
    for (int c = 0; c < bx; ++c) {
      int64_t off = ((int64_t)r * bx + c) << 6;
      pred_y = encode_block(bw, y + off, pred_y, dcl, acl);
      pred_cb = encode_block(bw, cb + off, pred_cb, dcc, acc_);
      pred_cr = encode_block(bw, cr + off, pred_cr, dcc, acc_);
      if (bw.overflow) return -1;
    }
  }
  bw.flush();
  return bw.overflow ? -1 : bw.pos;
}

}  // extern "C"
