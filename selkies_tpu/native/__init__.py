"""Native (C++) runtime components, built lazily with the system toolchain.

The build is a single ``g++ -O3 -shared`` invocation cached next to the
sources; if no toolchain is available the callers fall back to the
pure-Python implementations (slower but correct).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "entropy.cpp")
_SO = os.path.join(_DIR, "_libselkies_entropy.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile_lib(src: str, so: str, extra: tuple = ()) -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", so, src,
           *extra]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native build of %s failed (%s)", src, e)
        return False


def _stale(so: str, src: str) -> bool:
    if not os.path.exists(so):
        return True
    try:
        return os.path.getmtime(so) < os.path.getmtime(src)
    except OSError:
        return False  # source missing but .so present: use the .so


def _compile() -> bool:
    return _compile_lib(_SRC, _SO)


def entropy_lib() -> Optional[ctypes.CDLL]:
    """The compiled entropy coder, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _stale(_SO, _SRC) and not _compile():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            logger.warning("native entropy coder load failed: %s", e)
            return None
        i16p = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        sig = [
            i16p, i16p, i16p, ctypes.c_int, ctypes.c_int,
            u32p, u8p, u32p, u8p, u32p, u8p, u32p, u8p,
            u8p, ctypes.c_int64,
        ]
        for name in ("jpeg_encode_scan_420", "jpeg_encode_scan_444"):
            fn = getattr(lib, name)
            fn.argtypes = sig
            fn.restype = ctypes.c_int64
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# CAVLC slice coder (H.264 tpuenc v1)

_CAVLC_SRC = os.path.join(_DIR, "cavlc.cpp")
_CAVLC_SO = os.path.join(_DIR, "_libselkies_cavlc.so")
_cavlc_lock = threading.Lock()
_cavlc_lib: Optional[ctypes.CDLL] = None
_cavlc_tried = False


_CONF_SRC = os.path.join(_DIR, "conformance.cpp")
_CONF_SO = os.path.join(_DIR, "_libselkies_conformance.so")
_conf_lock = threading.Lock()
_conf_lib: Optional[ctypes.CDLL] = None
_conf_tried = False


def conformance_lib() -> Optional[ctypes.CDLL]:
    """libavcodec-backed conformance decoder, or None if unavailable.

    Test/debug oracle only (never on the hot path): decodes our Annex-B
    H.264 and JFIF output with a production decoder, standing in for the
    browser's WebCodecs decoders.
    """
    global _conf_lib, _conf_tried
    with _conf_lock:
        if _conf_lib is not None or _conf_tried:
            return _conf_lib
        _conf_tried = True
        if _stale(_CONF_SO, _CONF_SRC) and not _compile_lib(
                _CONF_SRC, _CONF_SO, ("-lavcodec", "-lavutil")):
            return None
        try:
            lib = ctypes.CDLL(_CONF_SO)
        except OSError as e:
            logger.warning("conformance decoder load failed: %s", e)
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i32p = ctypes.POINTER(ctypes.c_int)
        lib.conf_h264_new.restype = ctypes.c_void_p
        lib.conf_mjpeg_new.restype = ctypes.c_void_p
        lib.conf_dec_free.argtypes = [ctypes.c_void_p]
        caps = [ctypes.c_int64, ctypes.c_int64]
        lib.conf_dec_decode.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int64,
                                        u8p, u8p, u8p, *caps, i32p, i32p]
        lib.conf_dec_decode.restype = ctypes.c_int
        lib.conf_dec_flush.argtypes = [ctypes.c_void_p, u8p, u8p, u8p,
                                       *caps, i32p, i32p]
        lib.conf_dec_flush.restype = ctypes.c_int
        _conf_lib = lib
        return _conf_lib


def cavlc_lib() -> Optional[ctypes.CDLL]:
    """The compiled H.264 CAVLC slice coder, or None if unavailable."""
    global _cavlc_lib, _cavlc_tried
    with _cavlc_lock:
        if _cavlc_lib is not None or _cavlc_tried:
            return _cavlc_lib
        _cavlc_tried = True
        if _stale(_CAVLC_SO, _CAVLC_SRC) and not _compile_lib(
                _CAVLC_SRC, _CAVLC_SO):
            return None
        try:
            lib = ctypes.CDLL(_CAVLC_SO)
        except OSError as e:
            logger.warning("cavlc coder load failed: %s", e)
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        fn = lib.h264_encode_picture
        fn.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            i32p, i32p, i32p, i32p, i32p,
            u8p, ctypes.c_int64,
        ]
        fn.restype = ctypes.c_int64
        _cavlc_lib = lib
        return _cavlc_lib
