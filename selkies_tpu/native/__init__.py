"""Native (C++) runtime components, built lazily with the system toolchain.

Each lib is a single ``g++ -O3 -shared`` invocation cached next to the
sources; if no toolchain is available the callers fall back to the
pure-Python implementations (slower but correct).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))


#: Set SELKIES_NATIVE_SANITIZE=address|thread|undefined to build every
#: native lib with the matching -fsanitize instrumentation (the sanitized
#: .so is cached under a distinct name, so it never shadows the production
#: build). Load the matching runtime first, e.g.
#: ``LD_PRELOAD=$(g++ -print-file-name=libasan.so)`` for address.
_SANITIZE_ENV = "SELKIES_NATIVE_SANITIZE"


def _sanitize_mode() -> str:
    mode = os.environ.get(_SANITIZE_ENV, "").strip()
    if mode and mode not in ("address", "thread", "undefined"):
        logger.warning("%s=%r not one of address|thread|undefined; ignored",
                       _SANITIZE_ENV, mode)
        return ""
    return mode


def _compile_lib(src: str, so: str, extra: tuple = (),
                 sanitize: str = "") -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", so, src]
    if sanitize:
        cmd += [f"-fsanitize={sanitize}", "-g", "-fno-omit-frame-pointer"]
    cmd += list(extra)
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native build of %s failed (%s)", src, e)
        return False


def _stale(so: str, src: str) -> bool:
    if not os.path.exists(so):
        return True
    try:
        return os.path.getmtime(so) < os.path.getmtime(src)
    except OSError:
        return False  # source missing but .so present: use the .so


class _LazyLib:
    """Build-once/load-once holder for one native lib."""

    def __init__(self, name: str, extra: tuple = (),
                 register: Optional[Callable] = None) -> None:
        self.src = os.path.join(_DIR, name + ".cpp")
        # resolved once so the flags and the cache filename can't diverge
        # (an env-var change after import must not write an instrumented
        # binary under the production .so name)
        self.sanitize = _sanitize_mode()
        suffix = f"_{self.sanitize}" if self.sanitize else ""
        self.so = os.path.join(_DIR, f"_libselkies_{name}{suffix}.so")
        self.extra = extra
        self.register = register
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self._tried = False

    def get(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._lib is not None or self._tried:
                return self._lib
            self._tried = True
            if _stale(self.so, self.src) and not _compile_lib(
                    self.src, self.so, self.extra, sanitize=self.sanitize):
                return None
            try:
                lib = ctypes.CDLL(self.so)
            except OSError as e:
                logger.warning("native lib %s load failed: %s", self.so, e)
                return None
            if self.register is not None:
                self.register(lib)
            self._lib = lib
            return self._lib


_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i16p = np.ctypeslib.ndpointer(np.int16, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


def _register_entropy(lib: ctypes.CDLL) -> None:
    sig = [
        _i16p, _i16p, _i16p, ctypes.c_int, ctypes.c_int,
        _u32p, _u8p, _u32p, _u8p, _u32p, _u8p, _u32p, _u8p,
        _u8p, ctypes.c_int64,
    ]
    for name in ("jpeg_encode_scan_420", "jpeg_encode_scan_444"):
        fn = getattr(lib, name)
        fn.argtypes = sig
        fn.restype = ctypes.c_int64


def _register_cavlc(lib: ctypes.CDLL) -> None:
    fn = lib.h264_encode_picture
    fn.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        _i32p, _i32p, _i32p, _i32p, _i32p,
        _u8p, ctypes.c_int64, ctypes.c_int,
    ]
    fn.restype = ctypes.c_int64


def _register_conformance(lib: ctypes.CDLL) -> None:
    i32p = ctypes.POINTER(ctypes.c_int)
    lib.conf_h264_new.restype = ctypes.c_void_p
    lib.conf_mjpeg_new.restype = ctypes.c_void_p
    lib.conf_dec_free.argtypes = [ctypes.c_void_p]
    caps = [ctypes.c_int64, ctypes.c_int64]
    lib.conf_dec_decode.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int64,
                                    _u8p, _u8p, _u8p, *caps, i32p, i32p]
    lib.conf_dec_decode.restype = ctypes.c_int
    lib.conf_dec_flush.argtypes = [ctypes.c_void_p, _u8p, _u8p, _u8p,
                                   *caps, i32p, i32p]
    lib.conf_dec_flush.restype = ctypes.c_int
    # x264 reference encoder (quality-gate tooling)
    lib.conf_x264_new.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_char_p]
    lib.conf_x264_new.restype = ctypes.c_void_p
    lib.conf_enc_free.argtypes = [ctypes.c_void_p]
    lib.conf_enc_encode.argtypes = [ctypes.c_void_p, _u8p, _u8p, _u8p,
                                    _u8p, ctypes.c_int64]
    lib.conf_enc_encode.restype = ctypes.c_int64
    lib.conf_enc_flush.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int64]
    lib.conf_enc_flush.restype = ctypes.c_int64


def _register_audio(lib: ctypes.CDLL) -> None:
    lib.sa_opus_available.restype = ctypes.c_int
    lib.sa_pulse_available.restype = ctypes.c_int
    lib.sa_enc_new.argtypes = [ctypes.c_int] * 7
    lib.sa_enc_new.restype = ctypes.c_void_p
    lib.sa_enc_encode.argtypes = [ctypes.c_void_p, _i16p, ctypes.c_int,
                                  _u8p, ctypes.c_int32]
    lib.sa_enc_encode.restype = ctypes.c_int
    lib.sa_enc_free.argtypes = [ctypes.c_void_p]
    lib.sa_dec_new.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.sa_dec_new.restype = ctypes.c_void_p
    lib.sa_dec_decode.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int32,
                                  _i16p, ctypes.c_int]
    lib.sa_dec_decode.restype = ctypes.c_int
    lib.sa_dec_decode_fec.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int32,
                                      _i16p, ctypes.c_int]
    lib.sa_dec_decode_fec.restype = ctypes.c_int
    lib.sa_dec_plc.argtypes = [ctypes.c_void_p, _i16p, ctypes.c_int]
    lib.sa_dec_plc.restype = ctypes.c_int
    lib.sa_dec_free.argtypes = [ctypes.c_void_p]
    lib.sa_pa_new.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                              ctypes.c_int, ctypes.c_char_p]
    lib.sa_pa_new.restype = ctypes.c_void_p
    lib.sa_pa_read.argtypes = [ctypes.c_void_p, _i16p, ctypes.c_int64]
    lib.sa_pa_read.restype = ctypes.c_int
    lib.sa_pa_write.argtypes = [ctypes.c_void_p, _i16p, ctypes.c_int64]
    lib.sa_pa_write.restype = ctypes.c_int
    lib.sa_pa_free.argtypes = [ctypes.c_void_p]


_ENTROPY = _LazyLib("entropy", register=_register_entropy)
_CAVLC = _LazyLib("cavlc", register=_register_cavlc)
_CONFORMANCE = _LazyLib("conformance", ("-lavcodec", "-lavutil"),
                        _register_conformance)
_AUDIO = _LazyLib("audio", ("-ldl",), _register_audio)


def entropy_lib() -> Optional[ctypes.CDLL]:
    """The compiled JPEG entropy coder, or None if unavailable."""
    return _ENTROPY.get()


def cavlc_lib() -> Optional[ctypes.CDLL]:
    """The compiled H.264 CAVLC slice coder, or None if unavailable."""
    return _CAVLC.get()


def conformance_lib() -> Optional[ctypes.CDLL]:
    """libavcodec-backed conformance decoder, or None if unavailable.

    Test/debug oracle only (never on the hot path): decodes our Annex-B
    H.264 and JFIF output with a production decoder, standing in for the
    browser's WebCodecs decoders.
    """
    return _CONFORMANCE.get()


def audio_lib() -> Optional[ctypes.CDLL]:
    """Opus/Pulse audio runtime (the pcmflux equivalent), or None."""
    return _AUDIO.get()
