"""Declarative settings/flag system.

Capability parity with the reference's config subsystem
(``/root/reference/src/selkies/settings.py:36-222``): a single declarative
registry from which CLI flags, environment variables, the client-facing
``server_settings`` schema, and server-side clamping of client requests are all
derived. Precedence: CLI flag > ``SELKIES_<NAME>`` env > legacy env > default.

Design differences from the reference (this is a new implementation):
  * specs are typed dataclasses, not dicts;
  * a ``Settings`` instance is an explicit object you construct (the module
    also exposes a lazily-created process-wide singleton for convenience);
  * values are normalized at parse time into typed Python values
    (``BoolValue``/``RangeValue`` carry their lock state explicitly);
  * TPU-encoder knobs (stripe height, device selection, precision) are
    first-class settings.

Client-visible setting *names* match the reference so the reference web
client's settings UI works unchanged against this server.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


# --------------------------------------------------------------------------
# Typed values


@dataclass(frozen=True)
class BoolValue:
    """A boolean setting value plus whether the client may change it."""

    value: bool
    locked: bool = False

    def __bool__(self) -> bool:  # allow `if settings.audio_enabled:`
        return self.value


class EnumValue(str):
    """An enum setting value that may carry a *restricted* allowed list.

    Mirrors the reference's enum-override semantics
    (/root/reference/src/selkies/settings.py:29-31): overriding an enum with
    ``SELKIES_ENCODER="jpeg,x264enc"`` makes the first item the default and
    the full list the allowed options; a single value locks the choice.
    Subclasses ``str`` so consumers keep using it as the plain value.
    """

    allowed: Tuple[str, ...] = ()

    def __new__(cls, value: str, allowed: Sequence[str] = ()):
        self = super().__new__(cls, value)
        # frozen-style: set via object.__setattr__ for clarity of intent
        object.__setattr__(self, "allowed", tuple(allowed))
        return self

    @property
    def locked(self) -> bool:
        return len(self.allowed) == 1


@dataclass(frozen=True)
class RangeValue:
    """An allowed [lo, hi] range plus the default the client starts at.

    A single-value range (lo == hi) locks the client UI, mirroring the
    reference's convention (settings.py doc block lines 25-33).
    """

    lo: int
    hi: int
    default: int

    @property
    def locked(self) -> bool:
        return self.lo == self.hi

    def clamp(self, v: int) -> int:
        return max(self.lo, min(self.hi, int(v)))


# --------------------------------------------------------------------------
# Specs


@dataclass(frozen=True)
class Spec:
    """One declared setting. Subclasses define parsing per type."""

    name: str
    default: Any
    help: str = ""
    legacy_env: Optional[str] = None
    # Names excluded from the client-facing schema (server-only knobs).
    server_only: bool = False

    @property
    def env_var(self) -> str:
        return "SELKIES_" + self.name.upper()

    @property
    def cli_flag(self) -> str:
        return "--" + self.name.replace("_", "-")

    kind: str = field(default="str", init=False)

    def parse(self, raw: str) -> Any:
        return raw

    def normalize_default(self) -> Any:
        return self.default


@dataclass(frozen=True)
class StrSpec(Spec):
    kind: str = field(default="str", init=False)


@dataclass(frozen=True)
class IntSpec(Spec):
    kind: str = field(default="int", init=False)

    def parse(self, raw: str) -> int:
        return int(raw)


@dataclass(frozen=True)
class BoolSpec(Spec):
    kind: str = field(default="bool", init=False)

    def parse(self, raw: str) -> BoolValue:
        locked = False
        text = raw.strip()
        if text.lower().endswith("|locked"):
            locked = True
            text = text[: -len("|locked")]
        return BoolValue(text.strip().lower() in ("true", "1", "yes", "on"), locked)

    def normalize_default(self) -> BoolValue:
        d = self.default
        return d if isinstance(d, BoolValue) else BoolValue(bool(d))


@dataclass(frozen=True)
class EnumSpec(Spec):
    allowed: Tuple[str, ...] = ()
    kind: str = field(default="enum", init=False)

    def parse(self, raw: str) -> EnumValue:
        """A comma list restricts the allowed options (first item becomes
        the default); a single value locks the choice — the reference's
        documented override semantics (settings.py:29-31)."""
        items = tuple(p.strip() for p in raw.split(",") if p.strip())
        bad = [p for p in items if p not in self.allowed]
        if not items or bad:
            raise ValueError(
                f"{self.name}: {bad or raw!r} not in allowed set "
                f"{list(self.allowed)}")
        return EnumValue(items[0], items)

    def normalize_default(self) -> EnumValue:
        return EnumValue(str(self.default), self.allowed)


@dataclass(frozen=True)
class ListSpec(Spec):
    """Comma-separated subset of `allowed`; '' or 'none' means empty."""

    allowed: Tuple[str, ...] = ()
    kind: str = field(default="list", init=False)

    def parse(self, raw: str) -> Tuple[str, ...]:
        text = raw.strip().lower()
        if text in ("", "none"):
            return ()
        items = tuple(p.strip() for p in text.split(",") if p.strip())
        bad = [p for p in items if p not in self.allowed]
        if bad:
            raise ValueError(f"{self.name}: {bad} not in allowed set {list(self.allowed)}")
        return items

    def normalize_default(self) -> Tuple[str, ...]:
        if isinstance(self.default, str):
            return self.parse(self.default)
        return tuple(self.default)


_RANGE_RE = re.compile(r"^\s*(\d+)\s*(?:-\s*(\d+)\s*)?$")


@dataclass(frozen=True)
class RangeSpec(Spec):
    default_value: int = 0
    kind: str = field(default="range", init=False)

    def parse(self, raw: str) -> RangeValue:
        m = _RANGE_RE.match(raw)
        if not m:
            raise ValueError(f"{self.name}: bad range {raw!r} (want 'N' or 'LO-HI')")
        lo = int(m.group(1))
        hi = int(m.group(2)) if m.group(2) else lo
        if hi < lo:
            lo, hi = hi, lo
        return RangeValue(lo, hi, max(lo, min(hi, self.default_value)))

    def normalize_default(self) -> RangeValue:
        if isinstance(self.default, RangeValue):
            return self.default
        return self.parse(str(self.default))


# --------------------------------------------------------------------------
# Registry — client-visible names match the reference server's schema
# (/root/reference/src/selkies/settings.py:36-108) so the reference web
# client can drive this server; TPU-specific entries are new.

SETTING_DEFINITIONS: List[Spec] = [
    # Core feature toggles
    BoolSpec("audio_enabled", True, "Enable server-to-client audio streaming."),
    BoolSpec("microphone_enabled", True, "Enable client-to-server microphone forwarding."),
    BoolSpec("gamepad_enabled", True, "Enable gamepad support."),
    BoolSpec("clipboard_enabled", True, "Enable clipboard synchronization."),
    BoolSpec("command_enabled", True, "Enable command websocket messages."),
    ListSpec("file_transfers", "upload,download", "Allowed file transfer directions.",
             allowed=("upload", "download")),

    # Video / encoder
    EnumSpec("encoder", "jpeg", "Default video encoder profile.",
             allowed=("x264enc", "x264enc-striped", "jpeg")),
    RangeSpec("framerate", "8-120", "Allowed framerate range.", default_value=60),
    RangeSpec("h264_crf", "5-50", "Allowed H.264 CRF range.", default_value=25),
    RangeSpec("jpeg_quality", "1-100", "Allowed JPEG quality range.", default_value=40),
    BoolSpec("h264_fullcolor", False, "Full-range color for H.264 profiles."),
    BoolSpec("h264_streaming_mode", False, "H.264 streaming mode."),
    BoolSpec("use_cpu", False, "Force CPU (non-TPU) encode path."),
    BoolSpec("use_paint_over_quality", True, "High-quality paint-over for static scenes."),
    RangeSpec("paint_over_jpeg_quality", "1-100", "JPEG paint-over quality.", default_value=90),
    RangeSpec("h264_paintover_crf", "5-50", "H.264 paint-over CRF.", default_value=18),
    RangeSpec("h264_paintover_burst_frames", "1-30", "Paint-over burst frames.", default_value=5),
    BoolSpec("second_screen", True, "Enable a second monitor/display."),
    EnumSpec("second_screen_position", "right",
             "Secondary display placement relative to the primary.",
             allowed=("right", "left", "up", "down")),

    # Audio
    EnumSpec("audio_bitrate", "320000", "Default audio bitrate.",
             allowed=("64000", "128000", "265000", "320000")),

    # Forward error correction (WebRTC mode; reference
    # legacy/gstwebrtc_app.py video_packetloss_percent -> ulpfec)
    IntSpec("video_packetloss_percent", 0,
            "Video ULP/RED FEC overhead percent (0 disables)."),

    # Display / resolution
    BoolSpec("is_manual_resolution_mode", False, "Lock resolution to manual width/height."),
    IntSpec("manual_width", 0, "Fixed width (forces manual resolution mode)."),
    IntSpec("manual_height", 0, "Fixed height (forces manual resolution mode)."),
    EnumSpec("scaling_dpi", "96", "UI scaling DPI.",
             allowed=("96", "120", "144", "168", "192", "216", "240", "264", "288")),

    # Input / client behavior
    BoolSpec("enable_binary_clipboard", False, "Allow binary clipboard payloads."),
    BoolSpec("use_browser_cursors", False, "Use browser CSS cursors."),
    BoolSpec("use_css_scaling", False, "CSS-stretch a lower client resolution."),

    # UI visibility
    StrSpec("ui_title", "Selkies", "Sidebar title."),
    BoolSpec("ui_show_logo", True, "Show logo."),
    BoolSpec("ui_show_core_buttons", True, "Show core component buttons."),
    BoolSpec("ui_show_sidebar", True, "Show sidebar."),
    BoolSpec("ui_sidebar_show_video_settings", True, "Show video settings."),
    BoolSpec("ui_sidebar_show_screen_settings", True, "Show screen settings."),
    BoolSpec("ui_sidebar_show_audio_settings", True, "Show audio settings."),
    BoolSpec("ui_sidebar_show_stats", True, "Show stats."),
    BoolSpec("ui_sidebar_show_clipboard", True, "Show clipboard."),
    BoolSpec("ui_sidebar_show_files", True, "Show file transfer."),
    BoolSpec("ui_sidebar_show_apps", True, "Show applications."),
    BoolSpec("ui_sidebar_show_sharing", True, "Show sharing."),
    BoolSpec("ui_sidebar_show_gamepads", True, "Show gamepads."),
    BoolSpec("ui_sidebar_show_fullscreen", True, "Show fullscreen button."),
    BoolSpec("ui_sidebar_show_gaming_mode", True, "Show gaming mode button."),
    BoolSpec("ui_sidebar_show_trackpad", True, "Show virtual trackpad button."),
    BoolSpec("ui_sidebar_show_keyboard_button", True, "Show on-screen keyboard button."),
    BoolSpec("ui_sidebar_show_soft_buttons", True, "Show soft buttons."),

    # Server / operational (server-only: excluded from client schema)
    IntSpec("port", 8082, "Data websocket server port.",
            legacy_env="CUSTOM_WS_PORT", server_only=True),
    StrSpec("dri_node", "", "Unused on TPU; kept for CLI compat.", server_only=True),
    StrSpec("audio_device_name", "output.monitor", "Audio capture device.", server_only=True),
    StrSpec("watermark_path", "", "Watermark PNG path.",
            legacy_env="WATERMARK_PNG", server_only=True),
    IntSpec("watermark_location", -1, "Watermark location enum (0-6).",
            legacy_env="WATERMARK_LOCATION"),
    BoolSpec("debug", False, "Debug logging.", server_only=True),
    IntSpec("max_upload_mb", 4096, "Absolute per-file upload cap in MiB "
            "(enforced regardless of the client-declared size).",
            server_only=True),
    IntSpec("web_port", 8080, "HTTP port for the web client + signaling "
            "(reference signalling_web.py default).", server_only=True),
    IntSpec("metrics_port", 8000, "Prometheus metrics port (0 disables; "
            "reference legacy/metrics.py default). Also serves /healthz, "
            "/debug/trace, and (opt-in) /debug/jax-trace "
            "(docs/observability.md).", server_only=True),
    BoolSpec("jax_trace_enabled", False, "Allow on-demand jax.profiler "
             "captures via /debug/jax-trace on the metrics port "
             "(writes profile files to a temp dir; off by default).",
             server_only=True),
    StrSpec("turn_host", "", "TURN server hostname for /turn credentials.",
            legacy_env="TURN_HOST", server_only=True),
    StrSpec("turn_port", "3478", "TURN server port.",
            legacy_env="TURN_PORT", server_only=True),
    StrSpec("turn_shared_secret", "", "coturn shared secret for HMAC "
            "credentials.", legacy_env="TURN_SHARED_SECRET", server_only=True),

    # Sharing
    BoolSpec("enable_sharing", True, "Master sharing toggle."),
    BoolSpec("enable_collab", True, "Collaborative sharing link."),
    BoolSpec("enable_shared", True, "View-only sharing links."),
    BoolSpec("enable_player2", True, "Gamepad player 2 link."),
    BoolSpec("enable_player3", True, "Gamepad player 3 link."),
    BoolSpec("enable_player4", True, "Gamepad player 4 link."),

    # --- Robustness / supervision (server-only; docs/robustness.md) ---
    StrSpec("tpu_faults", "", "Comma list of fault points to arm for chaos "
            "runs and tests (grammar: name[*count][=arg]; see "
            "docs/robustness.md).", server_only=True),
    IntSpec("supervisor_max_restarts", 6, "Failure/watchdog restarts allowed "
            "per display loop within the restart window before the display "
            "is marked failed.", server_only=True),
    IntSpec("supervisor_restart_window_s", 60, "Sliding window (seconds) the "
            "supervisor restart budget is counted over.", server_only=True),
    IntSpec("watchdog_frames", 600, "Frame intervals without capture-loop "
            "progress before the watchdog cancels and restarts the pipeline "
            "(0 disables the watchdog).", server_only=True),
    IntSpec("ladder_fail_threshold", 3, "Consecutive encoder failures before "
            "the degradation ladder steps down a rung "
            "(device -> host -> jpeg).", server_only=True),
    IntSpec("ladder_probe_ms", 15000, "Clean-run milliseconds at a degraded "
            "rung before the ladder probes back up one rung.",
            server_only=True),

    # --- Edge hardening / admission control (server-only; docs/hardening.md)
    IntSpec("max_clients", 32, "Maximum concurrent websocket clients; the "
            "next connection is rejected with KILL server_full "
            "(0 = unlimited).", server_only=True),
    IntSpec("max_displays", 4, "Maximum concurrent display pipelines; a "
            "SETTINGS handshake for a further display is rejected with "
            "KILL server_full (0 = unlimited).", server_only=True),
    IntSpec("protocol_error_budget", 25, "Per-connection protocol-error "
            "budget (token bucket, slow refill); exhausting it sends "
            "KILL protocol_abuse and closes that socket.", server_only=True),
    StrSpec("rate_limits", "", "Per-class rate-limit overrides, grammar "
            "class=rate[:burst],... over classes input/control/settings/"
            "resize/upload/mic (empty = built-in defaults; see "
            "docs/hardening.md).", server_only=True),
    IntSpec("resize_debounce_ms", 200, "Debounce window for display "
            "reconfiguration: resize/SETTINGS churn inside the window "
            "coalesces into one stop-the-world reconfigure.",
            server_only=True),
    IntSpec("max_send_queue", 240, "Per-client bounded send-queue depth for "
            "media messages (drop-oldest-video; control is never dropped).",
            server_only=True),
    IntSpec("slow_client_evict_s", 4, "Seconds of sustained send-queue "
            "overflow before a slow consumer is evicted with "
            "KILL slow_consumer.", server_only=True),
    IntSpec("max_mic_chunk_kb", 256, "Largest accepted microphone PCM chunk "
            "in KiB; oversize chunks are dropped before reaching the audio "
            "pipeline.", server_only=True),
    IntSpec("max_ws_message_mb", 32, "Largest accepted websocket message in "
            "MiB (transport-level cap; 0 = unlimited, reference behavior).",
            server_only=True),
    IntSpec("shed_drop_threshold", 0, "Load shedding: encoder frames "
            "dropped per stats tick that count as sustained overload; two "
            "consecutive overloaded ticks reject NEW connections with "
            "KILL server_full until the drop rate recovers (0 = disabled).",
            server_only=True),

    # --- Session scheduler / slot fault domains (server-only;
    # --- docs/scaling.md) ---
    IntSpec("mesh_max_lanes", 4, "Batch lanes per mesh geometry bucket: "
            "each lane is one compiled SPMD encoder whose slots admit "
            "sessions dynamically; lanes are built on demand up to this "
            "cap and retired when drained.", server_only=True),
    IntSpec("admission_queue_ms", 250, "How long a display join may wait "
            "in the admission queue for a scheduler slot to free before "
            "it is shed with KILL server_full (0 = shed immediately).",
            server_only=True),
    IntSpec("slot_quarantine_errors", 3, "Per-slot error EWMA threshold: "
            "roughly this many attributed errors within the health window "
            "quarantines the slot and live-migrates its session to a "
            "healthy lane.", server_only=True),
    IntSpec("slot_health_window_s", 30, "Half-life (seconds) of the "
            "per-slot error score: a slot's past errors decay over this "
            "window, so only sustained faulting trips quarantine.",
            server_only=True),
    BoolSpec("mesh_overflow_solo", False, "When the scheduler is out of "
             "lane capacity, serve the overflow display with a solo "
             "encoder pipeline (pre-scheduler behavior) instead of "
             "queue/shed admission verdicts.", server_only=True),
    IntSpec("sfe_min_pixels", 8294400, "Split-frame encoding threshold: a "
            "display whose width x height crosses this claims a "
            "stripe-sharded SFE lane spanning several chips (one frame's "
            "stripe bands encoded in parallel over the ICI mesh) instead "
            "of a one-chip session slot. Default 3840x2160; 0 disables "
            "SFE.", server_only=True),
    IntSpec("sfe_shards", 0, "Chips one SFE frame is sharded across "
            "(stripe mesh axis). 0 = auto: every chip of the tpu_mesh "
            "slice; clamped to the largest count that tiles the slice.",
            server_only=True),

    # --- TPU-native additions (server-only) ---
    IntSpec("tpu_stripe_height", 64, "Encoder stripe height in rows (multiple of 16).",
            server_only=True),
    EnumSpec("tpu_precision", "float32", "Transform precision on device.",
             allowed=("float32", "bfloat16"), server_only=True),
    IntSpec("tpu_sessions_per_chip", 1, "Frame-batched sessions per chip.", server_only=True),
    StrSpec("tpu_mesh", "", "Device mesh spec, e.g. 'session:8' (empty = single chip).",
            server_only=True),
    BoolSpec("tpu_interpret", False, "Run Pallas kernels in interpreter mode.",
             server_only=True),
]

_SPECS_BY_NAME: Dict[str, Spec] = {s.name: s for s in SETTING_DEFINITIONS}


# --------------------------------------------------------------------------
# Settings object


class Settings:
    """Resolved settings: one attribute per spec name.

    Resolution order per setting: CLI > SELKIES_<NAME> env > legacy env >
    declared default (reference precedence, settings.py:11-18).
    """

    def __init__(
        self,
        argv: Optional[Sequence[str]] = None,
        env: Optional[Dict[str, str]] = None,
    ) -> None:
        env = dict(os.environ if env is None else env)
        parser = argparse.ArgumentParser(prog="selkies-tpu", add_help=True)
        for spec in SETTING_DEFINITIONS:
            parser.add_argument(spec.cli_flag, dest=spec.name, type=str,
                                default=None, help=spec.help)
        if argv is None:
            argv = sys.argv[1:]
        ns, _unknown = parser.parse_known_args(list(argv))

        self._values: Dict[str, Any] = {}
        for spec in SETTING_DEFINITIONS:
            raw = getattr(ns, spec.name)
            if raw is None:
                raw = env.get(spec.env_var)
            if raw is None and spec.legacy_env:
                raw = env.get(spec.legacy_env)
            if raw is None:
                self._values[spec.name] = spec.normalize_default()
            else:
                self._values[spec.name] = spec.parse(raw)

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name) from None

    def get(self, name: str) -> Any:
        return self._values[name]

    def set(self, name: str, value: Any) -> None:
        if name not in _SPECS_BY_NAME:
            raise KeyError(name)
        self._values[name] = value

    # -- client-facing schema ------------------------------------------------

    def schema_payload(self) -> Dict[str, Any]:
        """The ``server_settings`` JSON body pushed to clients at connect.

        Shape matches the reference handshake (selkies.py:1524-1545) so the
        reference client's settings UI binds to it unchanged.
        """
        out: Dict[str, Any] = {"type": "server_settings", "settings": {}}
        for spec in SETTING_DEFINITIONS:
            if spec.server_only:
                continue
            v = self._values[spec.name]
            entry: Dict[str, Any]
            if isinstance(spec, BoolSpec):
                entry = {"value": v.value, "locked": v.locked}
            elif isinstance(spec, RangeSpec):
                entry = {"value": v.default, "min": v.lo, "max": v.hi,
                         "default": v.default}
            elif isinstance(spec, EnumSpec):
                allowed = v.allowed if isinstance(v, EnumValue) and v.allowed \
                    else spec.allowed
                entry = {"value": str(v), "allowed": list(allowed)}
            elif isinstance(spec, ListSpec):
                entry = {"value": list(v) if isinstance(v, tuple) else v,
                         "allowed": list(spec.allowed)}
            else:
                entry = {"value": v}
            out["settings"][spec.name] = entry
        return out

    # -- clamping ------------------------------------------------------------

    def clamp_client_value(self, name: str, value: Any) -> Any:
        """Sanitize a client-requested value against server limits.

        Mirrors the behavior of the reference's _apply_client_settings clamp
        (selkies.py:1322-1361): ranges clamp, enums/lists reject unknown
        values (falling back to the server value), locked bools are ignored.
        """
        spec = _SPECS_BY_NAME.get(name)
        if spec is None:
            raise KeyError(name)
        current = self._values[name]
        if isinstance(spec, RangeSpec):
            return current.clamp(int(value))
        if isinstance(spec, BoolSpec):
            if current.locked:
                return current.value
            if isinstance(value, str):
                return value.strip().lower() in ("true", "1", "yes", "on")
            return bool(value)
        if isinstance(spec, EnumSpec):
            allowed = current.allowed if isinstance(current, EnumValue) \
                and current.allowed else spec.allowed
            return value if value in allowed else (
                current if isinstance(current, str) else spec.normalize_default())
        if isinstance(spec, ListSpec):
            items = value if isinstance(value, (list, tuple)) else str(value).split(",")
            return tuple(i for i in items if i in spec.allowed)
        if isinstance(spec, IntSpec):
            return int(value)
        return str(value)


_singleton: Optional[Settings] = None


def get_settings(argv: Optional[Sequence[str]] = None) -> Settings:
    """Process-wide settings singleton (created on first call)."""
    global _singleton
    if _singleton is None:
        _singleton = Settings(argv=argv)
    return _singleton


def reset_settings() -> None:
    """Testing hook: drop the singleton."""
    global _singleton
    _singleton = None
