"""Byte-exact wire protocol codec for the streaming data channel.

This module is the compatibility contract with the Selkies web client: the
binary layouts here are exactly what ``selkies-core.js`` demuxes in its
``websocket.onmessage`` switch (reference ``addons/gst-web-core/selkies-core.js``
lines 2753-2990) and the text verbs are what both sides exchange around it.
Keeping these byte-identical lets the reference client be used as an oracle
against this server.

Binary frames, server → client (first byte = type):

  0x00  full-frame H.264   [0x00][flags: 1=key][frame_id u16be][annexb...]
  0x01  audio              [0x01][0x00][opus packet...]
  0x03  JPEG stripe        [0x03][0x00][frame_id u16be][y_start u16be][jfif...]
  0x04  H.264 stripe       [0x04][flags: 1=key][frame_id u16be][y_start u16be]
                           [width u16be][height u16be][annexb...]

Binary frames, client → server:

  0x01  file upload chunk  [0x01][file bytes...]
  0x02  microphone PCM     [0x02][s16le PCM...]

Frame IDs are unsigned 16-bit with wraparound; see :class:`FrameId`.
"""

from __future__ import annotations

import enum
import json
import struct
from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union


class BinaryType(enum.IntEnum):
    """Server → client binary frame types (first byte)."""

    H264_FULL_FRAME = 0x00
    AUDIO_OPUS = 0x01
    JPEG_STRIPE = 0x03
    H264_STRIPE = 0x04


class ClientBinaryType(enum.IntEnum):
    """Client → server binary frame types; 0x01 here is a FILE chunk with a
    1-byte header (selkies-core.js:4030), not audio — direction matters."""

    FILE_CHUNK = 0x01
    MIC_PCM = 0x02


_U16 = struct.Struct(">H")


class ProtocolError(ValueError):
    """A frame that violates the client→server wire contract.

    Subclasses :class:`ValueError` so pre-existing callers that catch
    ``ValueError`` keep working; the server's per-message exception
    boundary counts these against the connection's error budget.
    """


# --------------------------------------------------------------------------
# Frame-id arithmetic (u16 wraparound)


class FrameId:
    """Unsigned-16-bit frame-id arithmetic with wraparound.

    The backpressure protocol computes ``sent - acked`` desync in modular
    arithmetic (reference selkies.py:1203-1214); a desync above
    ``WINDOW`` is treated as an anomalous wrap and reset.
    """

    MOD = 1 << 16
    WINDOW = 1 << 15

    @staticmethod
    def next(fid: int) -> int:
        return (fid + 1) % FrameId.MOD

    @staticmethod
    def desync(sent: int, acked: int) -> int:
        """How far `acked` lags `sent`, modulo 2**16; negative is clamped to
        the modular interpretation."""
        return (sent - acked) % FrameId.MOD

    @staticmethod
    def is_anomalous(sent: int, acked: int) -> bool:
        return FrameId.desync(sent, acked) >= FrameId.WINDOW


# --------------------------------------------------------------------------
# Typed frames


@dataclass(frozen=True)
class VideoStripe:
    frame_id: int
    y_start: int
    payload: bytes
    is_key: bool = True
    width: int = 0   # H.264 stripes only
    height: int = 0  # H.264 stripes only


@dataclass(frozen=True)
class FullFrame:
    frame_id: int
    payload: bytes
    is_key: bool


@dataclass(frozen=True)
class AudioChunk:
    payload: bytes


@dataclass(frozen=True)
class FileChunk:
    payload: bytes


@dataclass(frozen=True)
class MicChunk:
    payload: bytes


# --------------------------------------------------------------------------
# Packers


def pack_jpeg_stripe(frame_id: int, y_start: int, jpeg: bytes) -> bytes:
    """[0x03][0x00][frame_id][y_start][jfif] — client reads frame_id at
    offset 2 and y_start at offset 4 (selkies-core.js:2908-2915)."""
    return (
        bytes((BinaryType.JPEG_STRIPE, 0))
        + _U16.pack(frame_id & 0xFFFF)
        + _U16.pack(y_start & 0xFFFF)
        + jpeg
    )


def pack_h264_stripe(
    frame_id: int, y_start: int, width: int, height: int, annexb: bytes,
    is_key: bool,
) -> bytes:
    """10-byte header demuxed at selkies-core.js:2925-2945."""
    return (
        bytes((BinaryType.H264_STRIPE, 0x01 if is_key else 0x00))
        + _U16.pack(frame_id & 0xFFFF)
        + _U16.pack(y_start & 0xFFFF)
        + _U16.pack(width & 0xFFFF)
        + _U16.pack(height & 0xFFFF)
        + annexb
    )


def pack_full_frame(frame_id: int, annexb: bytes, is_key: bool) -> bytes:
    """[0x00][flags][frame_id][payload] (selkies-core.js:2814-2822)."""
    return (
        bytes((BinaryType.H264_FULL_FRAME, 0x01 if is_key else 0x00))
        + _U16.pack(frame_id & 0xFFFF)
        + annexb
    )


def pack_system_health(displays: Dict[str, Dict],
                       mesh: Dict[str, Dict] = None) -> str:
    """The ``system,health`` feed: per-display supervision state pushed to
    clients so degraded sessions are visible, not silent.

    ``displays`` maps display_id to a dict with at least ``rung`` (current
    degradation-ladder rung, see :data:`~selkies_tpu.robustness.RUNGS`),
    ``supervisor`` (lifecycle state), and the restart counters. ``mesh``
    (optional) maps geometry-bucket keys to the session scheduler's
    lane/slot health snapshot (docs/scaling.md) — per-slot errors,
    quarantines, and migrations, so a sick fault domain is visible from
    the client overlay, not only from ``stats()``. Rides the same JSON
    channel as the stats feed; clients switch on ``type``.
    """
    payload = {
        "type": "system_health",
        "subsystem": "system,health",
        "displays": displays,
    }
    if mesh:
        payload["mesh"] = mesh
    return json.dumps(payload)


def pack_audio_chunk(opus: bytes) -> bytes:
    """[0x01][0x00][opus] (selkies-core.js:2874-2880, server selkies.py:976)."""
    return bytes((BinaryType.AUDIO_OPUS, 0)) + opus


def pack_mic_chunk(pcm_s16le: bytes) -> bytes:
    return bytes((ClientBinaryType.MIC_PCM,)) + pcm_s16le


def pack_file_chunk(chunk: bytes) -> bytes:
    return bytes((ClientBinaryType.FILE_CHUNK,)) + chunk


# --------------------------------------------------------------------------
# Unpacker (used by tests and by any Python client / conformance harness)


def unpack_client_binary(data: bytes) -> Union[FileChunk, MicChunk]:
    """Demux a client → server binary frame (1-byte header).

    This is a trust boundary: a server→client type byte (0x00/0x03/0x04)
    arriving *from* a client is a wrong-direction frame and raises
    :class:`ProtocolError`, same as any unknown type.
    """
    if not data:
        raise ProtocolError("empty binary frame")
    t = data[0]
    if t == ClientBinaryType.FILE_CHUNK:
        return FileChunk(payload=bytes(data[1:]))
    if t == ClientBinaryType.MIC_PCM:
        return MicChunk(payload=bytes(data[1:]))
    if t in BinaryType._value2member_map_:
        raise ProtocolError(
            f"server->client type byte 0x{t:02x} in a client frame")
    raise ProtocolError(f"unknown client binary type 0x{t:02x}")


def unpack_binary(
    data: bytes,
) -> Union[VideoStripe, FullFrame, AudioChunk, Tuple[BinaryType, bytes]]:
    """Demux a server → client binary frame (for client→server frames use
    :func:`unpack_client_binary` — type byte 0x01 means different things per
    direction)."""
    if not data:
        raise ValueError("empty binary frame")
    t = data[0]
    if t == BinaryType.H264_FULL_FRAME:
        if len(data) < 4:
            raise ValueError("short 0x00 frame")
        return FullFrame(
            frame_id=_U16.unpack_from(data, 2)[0],
            payload=bytes(data[4:]),
            is_key=data[1] == 1,
        )
    if t == BinaryType.AUDIO_OPUS:
        if len(data) < 2:
            raise ValueError("short 0x01 frame")
        return AudioChunk(payload=bytes(data[2:]))
    if t == BinaryType.JPEG_STRIPE:
        if len(data) < 6:
            raise ValueError("short 0x03 frame")
        return VideoStripe(
            frame_id=_U16.unpack_from(data, 2)[0],
            y_start=_U16.unpack_from(data, 4)[0],
            payload=bytes(data[6:]),
            is_key=True,
        )
    if t == BinaryType.H264_STRIPE:
        if len(data) < 10:
            raise ValueError("short 0x04 frame")
        return VideoStripe(
            frame_id=_U16.unpack_from(data, 2)[0],
            y_start=_U16.unpack_from(data, 4)[0],
            width=_U16.unpack_from(data, 6)[0],
            height=_U16.unpack_from(data, 8)[0],
            payload=bytes(data[10:]),
            is_key=data[1] == 0x01,
        )
    return (BinaryType(t) if t in BinaryType._value2member_map_ else t, bytes(data[1:]))


# --------------------------------------------------------------------------
# Text-message grammar
#
# Client → server verbs (reference ws_handler dispatch, selkies.py:1843-2300,
# and client sends in selkies-core.js / lib/input.js):
#
#   SETTINGS,{json}            settings negotiation
#   CLIENT_FRAME_ACK <id>      backpressure ack
#   r,<W>x<H>,<display_id>     resize request
#   s,<scale>                  scale request
#   cmd,<command>              command execution
#   SET_NATIVE_CURSOR_RENDERING,<0|1>
#   START_VIDEO / STOP_VIDEO / START_AUDIO / STOP_AUDIO
#   FILE_UPLOAD_START:<path>:<size> / FILE_UPLOAD_END:<path> /
#   FILE_UPLOAD_ERROR:<path>:<msg>
#   cr                         clipboard read request
#   cw,<b64> | cb,<mime>,<b64> clipboard write (text | binary)
#   cws,<size> cwd,<b64> cwe   chunked text clipboard
#   cbs,<mime>,<size> cbd,<b64> cbe  chunked binary clipboard
#   kd,<keysym> ku,<keysym>    key down/up
#   kr                         keyboard reset (all keys up)
#   m,... m2,...               mouse (abs , rel)
#   js,c/b/a/d,...             gamepad connect/button/axis/disconnect
#   _f <fps> / _l <latency>    client-reported metrics
#
# Server → client verbs:
#
#   MODE websockets
#   {json} with "type": server_settings | system_stats | gpu_stats |
#          network_stats | stream_resolution | display_config_update |
#          system_health (supervision/degradation state, "system,health"
#          feed — pack_system_health below)
#   cursor,{json}
#   clipboard,<b64> | clipboard_binary,<mime>,<b64>
#   clipboard_start,<mime>,<size> clipboard_data,<b64> clipboard_finish
#   PIPELINE_RESETTING <display_id>
#   KILL <reason>
#   VIDEO_STARTED / VIDEO_STOPPED / AUDIO_STARTED / AUDIO_STOPPED
#   system_stats etc. as JSON


@dataclass(frozen=True)
class TextMessage:
    """A parsed client→server text message."""

    verb: str
    args: Tuple[str, ...] = ()
    json_body: Optional[str] = None


_SIMPLE_VERBS = frozenset(
    {
        "START_VIDEO", "STOP_VIDEO", "START_AUDIO", "STOP_AUDIO",
        "cr", "cwe", "cbe", "kr",
    }
)

_COLON_VERBS = ("FILE_UPLOAD_START", "FILE_UPLOAD_END", "FILE_UPLOAD_ERROR")

#: server → client verbs that must never be accepted *from* a client: the
#: parser is a trust boundary, and before the exact-delimiter tightening
#: these fell through toward the input handler when spoofed by a client
_SERVER_ONLY_VERBS = frozenset({
    "KILL", "PIPELINE_RESETTING", "MODE",
    "VIDEO_STARTED", "VIDEO_STOPPED", "AUDIO_STARTED", "AUDIO_STOPPED",
})


def _is_verb(message: str, verb: str, delims: str = " ,") -> bool:
    """Exact verb-plus-delimiter match: ``verb`` alone, or ``verb``
    immediately followed by one of ``delims`` — never a prefix match, so
    ``CLIENT_FRAME_ACKjunk`` is NOT ``CLIENT_FRAME_ACK``."""
    if message == verb:
        return True
    return (message.startswith(verb)
            and len(message) > len(verb)
            and message[len(verb)] in delims)


def parse_text_message(message: str) -> TextMessage:
    """Parse a client→server text message into (verb, args).

    The grammar is positional and comma/space/colon-delimited depending on the
    verb family; this mirrors how the reference server branches on prefixes
    (selkies.py:1843-2300) but centralizes it in one typed parser.

    Trust-boundary rules (this parses *hostile* input):

    * verbs match exactly up to their delimiter — ``CLIENT_FRAME_ACKjunk``
      is an unknown verb, not an ACK;
    * server→client verbs (``KILL``, ``PIPELINE_RESETTING``, ``MODE``,
      ``VIDEO_STARTED``/…) raise :class:`ProtocolError` instead of falling
      through toward the input handler.
    """
    for verb in _SERVER_ONLY_VERBS:
        if _is_verb(message, verb):
            raise ProtocolError(
                f"server->client verb {verb!r} received from a client")
    if message in _SIMPLE_VERBS:
        return TextMessage(message)
    if message.startswith("SETTINGS,"):
        return TextMessage("SETTINGS", json_body=message[len("SETTINGS,"):])
    if _is_verb(message, "CLIENT_FRAME_ACK", " "):
        parts = message.split()
        return TextMessage("CLIENT_FRAME_ACK", tuple(parts[1:2]))
    for verb in _COLON_VERBS:
        if message.startswith(verb + ":"):
            rest = message[len(verb) + 1:]
            if verb == "FILE_UPLOAD_START":
                path, _, size = rest.rpartition(":")
                return TextMessage(verb, (path, size))
            if verb == "FILE_UPLOAD_ERROR":
                path, _, msg = rest.partition(":")
                return TextMessage(verb, (path, msg))
            return TextMessage(verb, (rest,))
    if _is_verb(message, "_f", " ") or _is_verb(message, "_l", " "):
        verb, _, val = message.partition(" ")
        return TextMessage(verb, (val,))
    if message.startswith("cmd,"):
        # the whole remainder is one free-text command; commas are content
        return TextMessage("cmd", (message[4:],))
    if "," in message:
        verb, _, rest = message.partition(",")
        return TextMessage(verb, tuple(rest.split(",")) if rest else ())
    return TextMessage(message)
