from .wire import (  # noqa: F401
    BinaryType,
    VideoStripe,
    FullFrame,
    AudioChunk,
    pack_jpeg_stripe,
    pack_h264_stripe,
    pack_full_frame,
    pack_audio_chunk,
    unpack_binary,
    FrameId,
    TextMessage,
    parse_text_message,
)
