"""Forward error correction for the video RTP stream: RED + ULP FEC.

RFC 2198 (RED) encapsulation with RFC 5109 (ULP FEC, level 0, 16-bit
mask) recovery packets, the same scheme the reference turns on with its
``ulpfec percentage`` knob on the WebRTC video stream
(reference: src/selkies/legacy/gstwebrtc_app.py:996-1000). NACK/RTX costs
a round trip per loss; FEC recovers single losses inside a protection
group with zero feedback latency — the difference between a blip and a
frozen frame on lossy last-mile paths.

Layout mirrors libwebrtc's use of the RFCs: media packets go on the wire
RED-encapsulated (primary block only), FEC packets ride the same SSRC and
sequence space as RED blocks with the ULPFEC payload type, and the XOR
bit strings are computed over the *de-RED'ed* media packets (original
payload type, everything after the fixed 12-byte header counted as the
protected body).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

RED_PT = 103
ULPFEC_PT = 104


def red_wrap(block_pt: int, payload: bytes) -> bytes:
    """Single-block (primary-only) RED encapsulation: one header octet
    with F=0, then the payload."""
    return bytes([block_pt & 0x7F]) + payload


def red_unwrap(payload: bytes) -> List[Tuple[int, bytes]]:
    """Parse an RFC 2198 RED payload into (block_pt, data) blocks.

    Redundant blocks carry 4-byte headers (F=1 | PT | ts-offset | length);
    the final primary block a 1-byte header. Returns [] on truncation.
    """
    headers: List[Tuple[int, int]] = []      # (pt, length) for redundant
    pos = 0
    primary_pt = None
    while pos < len(payload):
        b0 = payload[pos]
        if not b0 & 0x80:                    # primary block header
            primary_pt = b0 & 0x7F
            pos += 1
            break
        if pos + 4 > len(payload):
            return []
        length = ((payload[pos + 2] & 0x03) << 8) | payload[pos + 3]
        headers.append((b0 & 0x7F, length))
        pos += 4
    if primary_pt is None:
        return []
    out: List[Tuple[int, bytes]] = []
    for pt, length in headers:
        if pos + length > len(payload):
            return []
        out.append((pt, payload[pos:pos + length]))
        pos += length
    out.append((primary_pt, payload[pos:]))
    return out


@dataclass
class FecPacket:
    """Parsed RFC 5109 FEC payload (level 0)."""
    pxcc_rec: int          # P|X|CC recovery (low 6 bits of header byte 0)
    mpt_rec: int           # M|PT recovery
    sn_base: int
    ts_rec: int
    len_rec: int
    prot_len: int
    offsets: Tuple[int, ...]   # protected packets at sn_base + offset
    body: bytes


def _fields(raw: bytes) -> Tuple[int, int, int, int]:
    """(byte0, byte1, timestamp, body_length) of a serialized RTP packet."""
    b0, b1 = raw[0], raw[1]
    ts = struct.unpack_from("!I", raw, 4)[0]
    return b0, b1, ts, len(raw) - 12


def build_fec(packets: List[bytes]) -> bytes:
    """One FEC payload protecting ≤16 serialized media RTP packets with
    consecutive sequence numbers (the first packet's seq is the SN base)."""
    if not 1 <= len(packets) <= 16:
        raise ValueError("ULP FEC (L=0) protects 1..16 packets")
    sn_base = struct.unpack_from("!H", packets[0], 2)[0]
    b0x = b1x = tsx = lenx = 0
    prot_len = 0
    for raw in packets:
        b0, b1, ts, blen = _fields(raw)
        b0x ^= b0
        b1x ^= b1
        tsx ^= ts
        lenx ^= blen
        prot_len = max(prot_len, blen)
    body = bytearray(prot_len)
    for raw in packets:
        pl = raw[12:]
        for i, b in enumerate(pl):
            body[i] ^= b
    mask = 0
    for i in range(len(packets)):
        mask |= 1 << (15 - i)
    hdr = struct.pack(
        "!BBHIH", b0x & 0x3F, b1x, sn_base, tsx & 0xFFFFFFFF, lenx & 0xFFFF)
    level0 = struct.pack("!HH", prot_len, mask)
    return hdr + level0 + bytes(body)


def parse_fec(payload: bytes) -> Optional[FecPacket]:
    if len(payload) < 14:
        return None
    b0, b1, sn_base, tsx, lenx = struct.unpack_from("!BBHIH", payload)
    if b0 & 0x80:
        return None                      # E bit must be 0
    if b0 & 0x40:
        return None                      # L=1 (48-bit mask) unsupported
    prot_len, mask = struct.unpack_from("!HH", payload, 10)
    body = payload[14:]
    if len(body) < prot_len:
        return None
    offsets = tuple(i for i in range(16) if mask & (1 << (15 - i)))
    if not offsets:
        return None
    return FecPacket(pxcc_rec=b0 & 0x3F, mpt_rec=b1, sn_base=sn_base,
                     ts_rec=tsx, len_rec=lenx, prot_len=prot_len,
                     offsets=offsets, body=body[:prot_len])


def recover(fec: FecPacket, have: Dict[int, bytes],
            ssrc: int) -> Optional[Tuple[int, bytes]]:
    """Reconstruct the single missing protected packet, if exactly one is
    missing and every other protected packet is in ``have`` (seq → raw).
    Returns (seq, raw_rtp) or None."""
    protected = [(fec.sn_base + off) & 0xFFFF for off in fec.offsets]
    missing = [s for s in protected if s not in have]
    if len(missing) != 1:
        return None
    b0x, b1x, tsx, lenx = fec.pxcc_rec, fec.mpt_rec, fec.ts_rec, fec.len_rec
    body = bytearray(fec.body)
    for s in protected:
        if s == missing[0]:
            continue
        raw = have[s]
        b0, b1, ts, blen = _fields(raw)
        b0x ^= b0 & 0x3F
        b1x ^= b1
        tsx ^= ts
        lenx ^= blen
        pl = raw[12:]
        for i, b in enumerate(pl[:len(body)]):
            body[i] ^= b
    if lenx > fec.prot_len:
        return None                      # inconsistent FEC — refuse
    hdr = struct.pack("!BBHII", 0x80 | (b0x & 0x3F), b1x,
                      missing[0], tsx & 0xFFFFFFFF, ssrc)
    return missing[0], hdr + bytes(body[:lenx])


class UlpFecEncoder:
    """Groups outgoing media packets and emits one FEC payload per group.

    ``percentage`` follows the reference's knob: FEC overhead as a share
    of media packets (25 → one FEC packet per 4 media packets)."""

    def __init__(self, percentage: int) -> None:
        pct = max(1, min(100, int(percentage)))
        self.group = max(1, min(16, round(100.0 / pct)))
        self._pending: List[bytes] = []

    def push(self, raw_media: bytes) -> Optional[bytes]:
        self._pending.append(raw_media)
        if len(self._pending) < self.group:
            return None
        out = build_fec(self._pending)
        self._pending = []
        return out


class UlpFecDecoder:
    """Receive-side cache + recovery: de-RED'ed media packets in, FEC
    payloads in, recovered raw RTP packets out."""

    MEDIA_CACHE = 512
    FEC_CACHE = 64

    def __init__(self) -> None:
        self._media: Dict[int, bytes] = {}
        self._fecs: List[FecPacket] = []
        self.recovered_count = 0

    def add_media(self, raw: bytes) -> None:
        seq = struct.unpack_from("!H", raw, 2)[0]
        self._media[seq] = raw
        while len(self._media) > self.MEDIA_CACHE:
            del self._media[next(iter(self._media))]

    def add_fec(self, payload: bytes) -> None:
        fec = parse_fec(payload)
        if fec is None:
            return
        self._fecs.append(fec)
        if len(self._fecs) > self.FEC_CACHE:
            del self._fecs[0]

    def try_recover(self, ssrc: int) -> List[bytes]:
        """Attempt recovery with every cached FEC packet; recovered
        packets enter the media cache (they can help later recoveries)."""
        out: List[bytes] = []
        keep: List[FecPacket] = []
        for fec in self._fecs:
            protected = [(fec.sn_base + off) & 0xFFFF for off in fec.offsets]
            missing = [s for s in protected if s not in self._media]
            if not missing:
                continue                 # group complete — FEC spent
            got = recover(fec, self._media, ssrc)
            if got is None:
                keep.append(fec)         # >1 missing: wait for more media
                continue
            seq, raw = got
            self.add_media(raw)
            self.recovered_count += 1
            out.append(raw)
        self._fecs = keep
        return out
