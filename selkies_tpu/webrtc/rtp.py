"""RTP and RTCP packet codecs.

Wire formats per RFC 3550 (RTP/SR/RR/SDES/BYE), RFC 4585 (PLI/NACK), RFC
5104 (FIR), draft-holmer-rmcat-transport-wide-cc-extensions-01 (TWCC
feedback), and draft-alvestrand-rmcat-remb (REMB). Role parity with the
reference's vendored ``src/selkies/webrtc/rtp.py`` (SURVEY.md §2.4) —
re-designed, not translated: plain dataclasses + struct packing, no GObject.

Header extensions supported (two-byte forms are not needed by the browser
peers we target): abs-send-time, transport-wide sequence number, and the
playout-delay extension the reference injects in
``legacy/gstwebrtc_app.py:1744-1780``.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

RTP_VERSION = 2
RTP_HEADER_LEN = 12

# RTCP packet types
RTCP_SR = 200
RTCP_RR = 201
RTCP_SDES = 202
RTCP_BYE = 203
RTCP_RTPFB = 205   # transport-layer feedback (NACK=1, TWCC=15)
RTCP_PSFB = 206    # payload-specific feedback (PLI=1, FIR=4, REMB=15)


def unwrap_seq(last_unwrapped: int, seq: int) -> int:
    """Extend a u16 sequence number into a monotone int (nearest wrap)."""
    if last_unwrapped < 0:
        return seq
    last16 = last_unwrapped & 0xFFFF
    delta = ((seq - last16 + 0x8000) & 0xFFFF) - 0x8000
    return last_unwrapped + delta


@dataclass
class RtpPacket:
    payload_type: int = 0
    sequence_number: int = 0
    timestamp: int = 0
    ssrc: int = 0
    payload: bytes = b""
    marker: int = 0
    csrc: List[int] = field(default_factory=list)
    extensions: Dict[int, bytes] = field(default_factory=dict)  # id -> data
    padding: int = 0

    def serialize(self, extension_profile: int = 0xBEDE) -> bytes:
        has_ext = bool(self.extensions)
        b0 = (RTP_VERSION << 6) | ((1 if self.padding else 0) << 5) \
            | ((1 if has_ext else 0) << 4) | len(self.csrc)
        b1 = (self.marker << 7) | self.payload_type
        out = bytearray(struct.pack(
            "!BBHII", b0, b1, self.sequence_number & 0xFFFF,
            self.timestamp & 0xFFFFFFFF, self.ssrc))
        for c in self.csrc:
            out += struct.pack("!I", c)
        if has_ext:
            body = bytearray()
            for ext_id, data in sorted(self.extensions.items()):
                if not 1 <= ext_id <= 14:
                    raise ValueError("one-byte extension id must be 1-14")
                if not 1 <= len(data) <= 16:
                    raise ValueError("one-byte extension length must be 1-16")
                body.append((ext_id << 4) | (len(data) - 1))
                body += data
            while len(body) % 4:
                body.append(0)
            out += struct.pack("!HH", extension_profile, len(body) // 4)
            out += body
        out += self.payload
        if self.padding:
            out += b"\x00" * (self.padding - 1) + bytes([self.padding])
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "RtpPacket":
        if len(data) < RTP_HEADER_LEN:
            raise ValueError("RTP packet too short")
        b0, b1, seq, ts, ssrc = struct.unpack_from("!BBHII", data)
        if b0 >> 6 != RTP_VERSION:
            raise ValueError("bad RTP version")
        cc = b0 & 0x0F
        has_pad = (b0 >> 5) & 1
        has_ext = (b0 >> 4) & 1
        pos = RTP_HEADER_LEN
        csrc = []
        for _ in range(cc):
            (c,) = struct.unpack_from("!I", data, pos)
            csrc.append(c)
            pos += 4
        extensions: Dict[int, bytes] = {}
        if has_ext:
            profile, words = struct.unpack_from("!HH", data, pos)
            pos += 4
            ext_end = pos + words * 4
            if profile == 0xBEDE:  # one-byte header extensions
                p = pos
                while p < ext_end:
                    hdr = data[p]
                    p += 1
                    if hdr == 0:
                        continue
                    ext_id, ln = hdr >> 4, (hdr & 0x0F) + 1
                    if ext_id == 15:
                        break
                    extensions[ext_id] = data[p:p + ln]
                    p += ln
            pos = ext_end
        end = len(data)
        padding = 0
        if has_pad and end > pos:
            padding = data[-1]
            end -= padding
        return cls(
            payload_type=b1 & 0x7F, marker=b1 >> 7, sequence_number=seq,
            timestamp=ts, ssrc=ssrc, csrc=csrc, extensions=extensions,
            payload=data[pos:end], padding=padding)


def is_rtcp(data: bytes) -> bool:
    """Demux RTCP from RTP on one socket (RFC 5761 packet-type ranges)."""
    return len(data) >= 2 and 200 <= data[1] <= 206


# ------------------------------------------------------------------ RTCP


@dataclass
class ReceiverReport:
    ssrc: int
    fraction_lost: int = 0
    packets_lost: int = 0
    highest_sequence: int = 0
    jitter: int = 0
    lsr: int = 0
    dlsr: int = 0

    def serialize(self) -> bytes:
        lost = self.packets_lost & 0xFFFFFF
        return struct.pack(
            "!IIIIII", self.ssrc,
            ((self.fraction_lost & 0xFF) << 24) | lost,
            self.highest_sequence & 0xFFFFFFFF, self.jitter,
            self.lsr, self.dlsr)

    @classmethod
    def parse(cls, data: bytes) -> "ReceiverReport":
        ssrc, fl_lost, hseq, jitter, lsr, dlsr = struct.unpack_from("!IIIIII", data)
        lost = fl_lost & 0xFFFFFF
        if lost & 0x800000:
            lost -= 0x1000000
        return cls(ssrc, fl_lost >> 24, lost, hseq, jitter, lsr, dlsr)


@dataclass
class RtcpSenderReport:
    ssrc: int
    ntp_time: int = 0          # 64-bit NTP
    rtp_time: int = 0
    packet_count: int = 0
    octet_count: int = 0
    reports: List[ReceiverReport] = field(default_factory=list)

    def serialize(self) -> bytes:
        body = struct.pack(
            "!IQIII", self.ssrc, self.ntp_time, self.rtp_time & 0xFFFFFFFF,
            self.packet_count, self.octet_count)
        for r in self.reports:
            body += r.serialize()
        return _rtcp_header(RTCP_SR, len(self.reports), body) + body

    @classmethod
    def parse(cls, body: bytes, count: int) -> "RtcpSenderReport":
        ssrc, ntp, rtp_t, pc, oc = struct.unpack_from("!IQIII", body)
        reports = [ReceiverReport.parse(body[24 + i * 24:]) for i in range(count)]
        return cls(ssrc, ntp, rtp_t, pc, oc, reports)


@dataclass
class RtcpReceiverReport:
    ssrc: int
    reports: List[ReceiverReport] = field(default_factory=list)

    def serialize(self) -> bytes:
        body = struct.pack("!I", self.ssrc)
        for r in self.reports:
            body += r.serialize()
        return _rtcp_header(RTCP_RR, len(self.reports), body) + body

    @classmethod
    def parse(cls, body: bytes, count: int) -> "RtcpReceiverReport":
        (ssrc,) = struct.unpack_from("!I", body)
        reports = [ReceiverReport.parse(body[4 + i * 24:]) for i in range(count)]
        return cls(ssrc, reports)


@dataclass
class RtcpSdes:
    items: List[Tuple[int, str]] = field(default_factory=list)  # (ssrc, cname)

    def serialize(self) -> bytes:
        body = b""
        for ssrc, cname in self.items:
            chunk = struct.pack("!I", ssrc) + bytes([1, len(cname)]) + cname.encode()
            chunk += b"\x00"  # item-list terminator
            while len(chunk) % 4:
                chunk += b"\x00"
            body += chunk
        return _rtcp_header(RTCP_SDES, len(self.items), body) + body

    @classmethod
    def parse(cls, body: bytes, count: int) -> "RtcpSdes":
        items = []
        pos = 0
        for _ in range(count):
            (ssrc,) = struct.unpack_from("!I", body, pos)
            pos += 4
            cname = ""
            while pos < len(body) and body[pos] != 0:
                t, ln = body[pos], body[pos + 1]
                val = body[pos + 2:pos + 2 + ln]
                if t == 1:
                    cname = val.decode(errors="replace")
                pos += 2 + ln
            # one terminator octet, then pad the CHUNK to a 32-bit boundary
            pos += 1
            pos = (pos + 3) & ~3
            items.append((ssrc, cname))
        return cls(items)


@dataclass
class RtcpBye:
    sources: List[int] = field(default_factory=list)

    def serialize(self) -> bytes:
        body = b"".join(struct.pack("!I", s) for s in self.sources)
        return _rtcp_header(RTCP_BYE, len(self.sources), body) + body

    @classmethod
    def parse(cls, body: bytes, count: int) -> "RtcpBye":
        return cls([struct.unpack_from("!I", body, i * 4)[0] for i in range(count)])


@dataclass
class RtcpPli:
    sender_ssrc: int
    media_ssrc: int

    def serialize(self) -> bytes:
        body = struct.pack("!II", self.sender_ssrc, self.media_ssrc)
        return _rtcp_header(RTCP_PSFB, 1, body) + body


@dataclass
class RtcpFir:
    sender_ssrc: int
    media_ssrc: int
    seq: int

    def serialize(self) -> bytes:
        body = struct.pack("!II", self.sender_ssrc, 0)
        body += struct.pack("!IBBH", self.media_ssrc, self.seq & 0xFF, 0, 0)
        return _rtcp_header(RTCP_PSFB, 4, body) + body


@dataclass
class RtcpNack:
    sender_ssrc: int
    media_ssrc: int
    lost: List[int] = field(default_factory=list)   # sequence numbers

    def serialize(self) -> bytes:
        fci = b""
        lost = sorted(set(s & 0xFFFF for s in self.lost))
        i = 0
        while i < len(lost):
            pid = lost[i]
            blp = 0
            j = i + 1
            while j < len(lost) and 0 < ((lost[j] - pid) & 0xFFFF) <= 16:
                blp |= 1 << (((lost[j] - pid) & 0xFFFF) - 1)
                j += 1
            fci += struct.pack("!HH", pid, blp)
            i = j
        body = struct.pack("!II", self.sender_ssrc, self.media_ssrc) + fci
        return _rtcp_header(RTCP_RTPFB, 1, body) + body

    @classmethod
    def parse(cls, body: bytes) -> "RtcpNack":
        sender, media = struct.unpack_from("!II", body)
        lost = []
        pos = 8
        while pos + 4 <= len(body):
            pid, blp = struct.unpack_from("!HH", body, pos)
            lost.append(pid)
            for bit in range(16):
                if blp & (1 << bit):
                    lost.append((pid + bit + 1) & 0xFFFF)
            pos += 4
        return cls(sender, media, lost)


@dataclass
class RtcpRemb:
    sender_ssrc: int
    bitrate: int
    ssrcs: List[int] = field(default_factory=list)

    def serialize(self) -> bytes:
        exponent = 0
        mantissa = self.bitrate
        while mantissa > 0x3FFFF:
            mantissa >>= 1
            exponent += 1
        body = struct.pack("!II", self.sender_ssrc, 0)
        body += b"REMB" + bytes([len(self.ssrcs)])
        body += struct.pack("!I", (exponent << 18) | mantissa)[1:]  # 3 bytes
        for s in self.ssrcs:
            body += struct.pack("!I", s)
        return _rtcp_header(RTCP_PSFB, 15, body) + body

    @classmethod
    def parse(cls, body: bytes) -> "RtcpRemb":
        sender, _ = struct.unpack_from("!II", body)
        if body[8:12] != b"REMB":
            raise ValueError("not a REMB packet")
        num = body[12]
        b = struct.unpack("!I", b"\x00" + body[13:16])[0]
        exponent = b >> 18
        mantissa = b & 0x3FFFF
        ssrcs = [struct.unpack_from("!I", body, 16 + i * 4)[0] for i in range(num)]
        return cls(sender, mantissa << exponent, ssrcs)


# TWCC feedback (draft-holmer-rmcat-transport-wide-cc-extensions-01 §3.1)

TWCC_SYMBOL_NOT_RECEIVED = 0
TWCC_SYMBOL_SMALL_DELTA = 1
TWCC_SYMBOL_LARGE_DELTA = 2


@dataclass
class RtcpTwcc:
    sender_ssrc: int
    media_ssrc: int
    base_seq: int
    fb_count: int
    ref_time: int                       # multiples of 64 ms
    received: List[Tuple[int, Optional[int]]] = field(default_factory=list)
    # (seq, recv_time_us or None) — consecutive from base_seq

    def serialize(self) -> bytes:
        symbols: List[int] = []
        deltas = b""
        prev_time: Optional[int] = self.ref_time * 64000
        for _seq, t in self.received:
            if t is None:
                symbols.append(TWCC_SYMBOL_NOT_RECEIVED)
                continue
            delta = (t - prev_time) // 250
            if 0 <= delta <= 255:
                symbols.append(TWCC_SYMBOL_SMALL_DELTA)
                deltas += bytes([delta])
            else:
                delta = max(-32768, min(32767, delta))
                symbols.append(TWCC_SYMBOL_LARGE_DELTA)
                deltas += struct.pack("!h", delta)
            # advance by the value actually encoded, as the parser will
            prev_time = prev_time + delta * 250
        # encode all symbols as two-bit status vector chunks (7 per chunk)
        chunks = b""
        for i in range(0, len(symbols), 7):
            group = symbols[i:i + 7]
            val = 0xC000  # vector chunk, two-bit symbols
            for j, s in enumerate(group):
                val |= s << (12 - 2 * j)
            chunks += struct.pack("!H", val)
        body = struct.pack("!II", self.sender_ssrc, self.media_ssrc)
        body += struct.pack("!HH", self.base_seq & 0xFFFF, len(self.received))
        body += struct.pack("!I", ((self.ref_time & 0xFFFFFF) << 8)
                            | (self.fb_count & 0xFF))
        body += chunks + deltas
        body += b"\x00" * ((-len(body)) % 4)  # FCI zero-padding to 32 bits
        return _rtcp_header(RTCP_RTPFB, 15, body) + body

    @classmethod
    def parse(cls, body: bytes) -> "RtcpTwcc":
        sender, media = struct.unpack_from("!II", body)
        base_seq, count = struct.unpack_from("!HH", body, 8)
        (word,) = struct.unpack_from("!I", body, 12)
        ref_time = word >> 8
        if ref_time & 0x800000:
            ref_time -= 0x1000000
        fb_count = word & 0xFF
        pos = 16
        symbols: List[int] = []
        while len(symbols) < count:
            (chunk,) = struct.unpack_from("!H", body, pos)
            pos += 2
            if chunk & 0x8000:  # status vector
                two_bit = chunk & 0x4000
                n = 7 if two_bit else 14
                for j in range(n):
                    if two_bit:
                        symbols.append((chunk >> (12 - 2 * j)) & 0x3)
                    else:
                        symbols.append((chunk >> (13 - j)) & 0x1)
            else:  # run-length
                symbol = (chunk >> 13) & 0x3
                run = chunk & 0x1FFF
                symbols.extend([symbol] * run)
        symbols = symbols[:count]
        received: List[Tuple[int, Optional[int]]] = []
        t = ref_time * 64000
        for i, s in enumerate(symbols):
            seq = (base_seq + i) & 0xFFFF
            if s == TWCC_SYMBOL_NOT_RECEIVED:
                received.append((seq, None))
                continue
            if s == TWCC_SYMBOL_SMALL_DELTA:
                delta = body[pos]
                pos += 1
            else:
                (delta,) = struct.unpack_from("!h", body, pos)
                pos += 2
            t += delta * 250
            received.append((seq, t))
        return cls(sender, media, base_seq, fb_count, ref_time, received)


def _rtcp_header(pt: int, count: int, body: bytes) -> bytes:
    length = (len(body) + 3) // 4  # in 32-bit words minus one (header incl.)
    pad = (-len(body)) % 4
    if pad:
        raise ValueError("RTCP body must be 32-bit aligned")
    return struct.pack("!BBH", (RTP_VERSION << 6) | count, pt, length)


def parse_rtcp(data: bytes) -> List[object]:
    """Parse a compound RTCP packet into typed packets (unknown ones skipped)."""
    out: List[object] = []
    pos = 0
    while pos + 4 <= len(data):
        b0, pt, length = struct.unpack_from("!BBH", data, pos)
        count = b0 & 0x1F
        body = data[pos + 4:pos + 4 + length * 4]
        pos += 4 + length * 4
        try:
            if pt == RTCP_SR:
                out.append(RtcpSenderReport.parse(body, count))
            elif pt == RTCP_RR:
                out.append(RtcpReceiverReport.parse(body, count))
            elif pt == RTCP_SDES:
                out.append(RtcpSdes.parse(body, count))
            elif pt == RTCP_BYE:
                out.append(RtcpBye.parse(body, count))
            elif pt == RTCP_RTPFB and count == 1:
                out.append(RtcpNack.parse(body))
            elif pt == RTCP_RTPFB and count == 15:
                out.append(RtcpTwcc.parse(body))
            elif pt == RTCP_PSFB and count == 1:
                out.append(RtcpPli(*struct.unpack_from("!II", body)))
            elif pt == RTCP_PSFB and count == 15:
                out.append(RtcpRemb.parse(body))
        except (struct.error, ValueError, IndexError):
            continue
    return out


# ---------------------------------------------------------- ext helpers


def pack_abs_send_time(t_seconds: float) -> bytes:
    """24-bit 6.18 fixed point of the send time (RFC 5285 ext)."""
    v = int(t_seconds * (1 << 18)) & 0xFFFFFF
    return v.to_bytes(3, "big")


def unpack_abs_send_time(data: bytes) -> float:
    return int.from_bytes(data, "big") / (1 << 18)


def pack_twcc_seq(seq: int) -> bytes:
    return struct.pack("!H", seq & 0xFFFF)


def pack_playout_delay(min_ms: int = 0, max_ms: int = 0) -> bytes:
    """12+12-bit playout delay in 10 ms units (reference injects 0/0 to make
    the browser render with minimal delay, gstwebrtc_app.py:1744)."""
    v = ((min_ms // 10) << 12) | (max_ms // 10)
    return v.to_bytes(3, "big")
