"""H.264 RTP payloader/depayloader (RFC 6184, non-interleaved mode).

Carries the tpuenc H.264 bitstream over RTP *without re-encoding* — the
exact role the reference stages its vendored aiortc for (SURVEY.md §2.4
"externally encoded H.264 → packetizer without re-encode";
``src/selkies/webrtc/codecs/h264.py`` consumed at ref ``h264.py:157``).

Annex-B access units split into NAL units; NALs ≤ MTU ship as single NAL
packets, small ones may aggregate into STAP-A, large ones fragment into
FU-A. Depacketization reassembles Annex-B access units keyed on the RTP
marker bit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional

from .rtp import RtpPacket

NAL_STAP_A = 24
NAL_FU_A = 28

ANNEXB_3 = b"\x00\x00\x01"
ANNEXB_4 = b"\x00\x00\x00\x01"


def split_annexb(data: bytes) -> List[bytes]:
    """Split an Annex-B stream into raw NAL units (start codes removed)."""
    out: List[bytes] = []
    i = 0
    n = len(data)
    # find first start code
    start = None
    while i + 3 <= n:
        if data[i:i + 3] == ANNEXB_3:
            start = i + 3
            i += 3
            break
        i += 1
    if start is None:
        return [data] if data else []
    while i + 3 <= n:
        if data[i:i + 3] == ANNEXB_3:
            end = i - 1 if i > 0 and data[i - 1] == 0 else i
            if end > start:
                out.append(data[start:end])
            start = i + 3
            i += 3
        else:
            i += 1
    if start < n:
        out.append(data[start:])
    return [x for x in out if x]


class H264Payloader:
    """Annex-B access unit → RTP payloads (same timestamp, marker on last)."""

    def __init__(self, mtu: int = 1200):
        self.mtu = mtu

    def payloads(self, access_unit: bytes) -> List[bytes]:
        nals = split_annexb(access_unit)
        out: List[bytes] = []
        agg: List[bytes] = []
        agg_size = 0

        def flush_agg():
            nonlocal agg, agg_size
            if not agg:
                return
            if len(agg) == 1:
                out.append(agg[0])
            else:
                nri = max((n[0] >> 5) & 3 for n in agg)
                pkt = bytearray([(nri << 5) | NAL_STAP_A])
                for n in agg:
                    pkt += len(n).to_bytes(2, "big") + n
                out.append(bytes(pkt))
            agg, agg_size = [], 0

        for nal in nals:
            if len(nal) <= self.mtu:
                if agg_size + len(nal) + 3 > self.mtu:
                    flush_agg()
                agg.append(nal)
                agg_size += len(nal) + 2 + 1
                continue
            flush_agg()
            # FU-A fragmentation
            hdr = nal[0]
            nri = (hdr >> 5) & 3
            ntype = hdr & 0x1F
            payload = nal[1:]
            pos = 0
            first = True
            chunk = self.mtu - 2
            while pos < len(payload):
                piece = payload[pos:pos + chunk]
                pos += len(piece)
                fu_ind = (nri << 5) | NAL_FU_A
                fu_hdr = ntype | (0x80 if first else 0) \
                    | (0x40 if pos >= len(payload) else 0)
                out.append(bytes([fu_ind, fu_hdr]) + piece)
                first = False
        flush_agg()
        return out

    def packetize(
        self, access_unit: bytes, ssrc: int, payload_type: int,
        sequence_number: int, timestamp: int,
    ) -> List[RtpPacket]:
        payloads = self.payloads(access_unit)
        pkts = []
        for i, p in enumerate(payloads):
            pkts.append(RtpPacket(
                payload_type=payload_type,
                sequence_number=(sequence_number + i) & 0xFFFF,
                timestamp=timestamp & 0xFFFFFFFF,
                ssrc=ssrc,
                payload=p,
                marker=1 if i == len(payloads) - 1 else 0,
            ))
        return pkts


@dataclass
class _FuState:
    header: int = 0
    data: bytearray = None  # type: ignore[assignment]


class H264Depayloader:
    """RTP payloads → Annex-B access units.

    Feed packets in sequence order; an access unit is returned when the
    marker-bit packet lands. Mid-FU loss drops the fragmented NAL only.
    """

    def __init__(self):
        self._nals: List[bytes] = []
        self._fu: Optional[_FuState] = None
        self._last_seq: Optional[int] = None

    def feed(self, packet: RtpPacket) -> Optional[bytes]:
        p = packet.payload
        if not p:
            return None
        # a sequence gap invalidates any FU-A reassembly in progress —
        # emitting a spliced NAL would hand the decoder corrupt slices
        if self._last_seq is not None and \
                packet.sequence_number != (self._last_seq + 1) & 0xFFFF:
            self._fu = None
        self._last_seq = packet.sequence_number
        ntype = p[0] & 0x1F
        if ntype == NAL_STAP_A:
            pos = 1
            while pos + 2 <= len(p):
                ln = int.from_bytes(p[pos:pos + 2], "big")
                pos += 2
                self._nals.append(p[pos:pos + ln])
                pos += ln
        elif ntype == NAL_FU_A:
            if len(p) < 2:
                return None
            fu_hdr = p[1]
            start, end = fu_hdr & 0x80, fu_hdr & 0x40
            if start:
                nal_hdr = (p[0] & 0xE0) | (fu_hdr & 0x1F)
                self._fu = _FuState(nal_hdr, bytearray([nal_hdr]) )
                self._fu.data += p[2:]
            elif self._fu is not None:
                self._fu.data += p[2:]
            if end and self._fu is not None:
                self._nals.append(bytes(self._fu.data))
                self._fu = None
        else:
            self._nals.append(p)

        if packet.marker:
            au = b"".join(ANNEXB_4 + n for n in self._nals)
            self._nals = []
            self._fu = None
            return au
        return None
