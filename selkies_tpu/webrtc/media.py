"""Media plumbing: MediaPlayer / MediaRecorder / MediaRelay / MediaBlackhole.

Role parity with the reference's vendored contrib/media.py
(``/root/reference/src/selkies/webrtc/contrib/media.py:87-300``), re-scoped
to this framework's formats instead of PyAV: the compute path produces
Annex-B H.264 (tpuenc), JPEG stripes, and Opus/PCM audio, so the file
plumbing speaks exactly those containers —

  MediaPlayer    .wav (PCM s16) → 20 ms audio frames (Opus-encoded when
                 libopus is loaded, raw PCM otherwise)
                 .h264/.264 (Annex-B) → access units at a fixed fps
                 .y4m (YUV4MPEG2 420) → raw frames for encoder pipelines
  MediaRecorder  .wav ← audio frames (Opus decoded back to PCM when
                 possible), .h264 ← Annex-B AUs, .mjpeg ← JPEG frames
  MediaRelay     one source track fanned out to N subscriber tracks
  MediaBlackhole consume-and-discard sink (keeps senders pumping)

Tracks are tiny async objects: ``await track.recv()`` yields
``(payload: bytes, timestamp_ms: int)`` and raises ``MediaStreamError``
at end of stream — the contract `stream_to()` uses to pump a
``MediaSender``.
"""

from __future__ import annotations

import asyncio
import os
import struct
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "MediaStreamError", "MediaTrack", "MediaBlackhole", "MediaPlayer",
    "MediaRecorder", "MediaRelay", "stream_to",
]


class MediaStreamError(Exception):
    """End of stream (or track stopped)."""


class MediaTrack:
    kind = "video"

    async def recv(self) -> Tuple[bytes, int]:
        raise NotImplementedError

    def stop(self) -> None:
        pass


# ---------------------------------------------------------------- sources


def _split_access_units(data: bytes) -> List[bytes]:
    """Split an Annex-B elementary stream into access units.

    A new AU starts at an AUD NAL (type 9) or at a VCL NAL whose
    first_mb_in_slice == 0 — the first ue(v) of the slice header, which
    is zero exactly when the first payload bit after the NAL header is 1.
    Multi-slice pictures (e.g. .h264 files recorded from this framework's
    own multi-stripe frames, one slice NAL per stripe) therefore keep all
    their slices in one AU and replay at the real frame rate. Stripe
    recordings replay as full-frame AUs: per-stripe geometry is not
    representable in an elementary stream. Leading SPS/PPS/SEI attach to
    the AU that follows them."""
    starts: List[int] = []
    i = 0
    n = len(data)
    while i < n - 3:
        if data[i:i + 3] == b"\x00\x00\x01":
            starts.append(i)
            i += 3
        elif data[i:i + 4] == b"\x00\x00\x00\x01":
            starts.append(i)
            i += 4
        else:
            i += 1
    if not starts:
        return [data] if data else []
    units: List[Tuple[int, int, int]] = []   # (nal_type, offset, payload_off)
    for off in starts:
        j = off + (4 if data[off:off + 4] == b"\x00\x00\x00\x01" else 3)
        if j < n:
            units.append((data[j] & 0x1F, off, j + 1))
    if not units:
        return [data]
    new_au = []
    for nal, off, poff in units:
        first_slice = (nal in (1, 5) and poff < n
                       and (data[poff] & 0x80) != 0)
        new_au.append(nal == 9 or first_slice)
    bounds: List[int] = [0]              # indices into units starting an AU
    seen_vcl = False
    for idx, (nal, off, poff) in enumerate(units):
        if idx > 0 and new_au[idx] and seen_vcl:
            # pull the contiguous non-VCL run before this NAL into the
            # new AU — those parameter sets/SEI prefix the coming picture
            j = idx
            while j - 1 > bounds[-1] and units[j - 1][0] not in (1, 5):
                j -= 1
            bounds.append(j)
            seen_vcl = False
        if nal in (1, 5):
            seen_vcl = True
    aus: List[bytes] = []
    for bi, ui in enumerate(bounds):
        start = units[ui][1]
        end = units[bounds[bi + 1]][1] if bi + 1 < len(bounds) else n
        aus.append(data[start:end])
    return aus


class _AudioFileTrack(MediaTrack):
    kind = "audio"

    def __init__(self, pcm: "memoryview", sample_rate: int, channels: int,
                 frame_ms: int = 20, loop: bool = False,
                 encode_opus: bool = True):
        import numpy as np
        self._np = np
        self._pcm = np.frombuffer(pcm, dtype=np.int16).reshape(-1, channels)
        self.sample_rate = sample_rate
        self.channels = channels
        self.samples_per_frame = sample_rate * frame_ms // 1000
        self._pos = 0
        self._loop = loop
        self._t0: Optional[float] = None
        self._frames = 0
        self._enc = None
        if encode_opus:
            try:
                from ..audio.codec import OpusEncoder
                self._enc = OpusEncoder(sample_rate, channels)
            except Exception:
                self._enc = None    # raw PCM frames (tests / no libopus)

    @property
    def encodes_opus(self) -> bool:
        return self._enc is not None

    async def recv(self) -> Tuple[bytes, int]:
        spf = self.samples_per_frame
        if self._pos + spf > len(self._pcm):
            if not self._loop or not len(self._pcm):
                raise MediaStreamError("end of audio")
            self._pos = 0
        chunk = self._pcm[self._pos:self._pos + spf]
        self._pos += spf
        # real-time pacing so a live PeerConnection isn't flooded
        if self._t0 is None:
            self._t0 = time.monotonic()
        due = self._t0 + self._frames * spf / self.sample_rate
        delay = due - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        self._frames += 1
        ts = (self._frames - 1) * spf
        if self._enc is not None:
            return self._enc.encode(self._np.ascontiguousarray(chunk)), ts
        return chunk.tobytes(), ts


class _VideoFileTrack(MediaTrack):
    kind = "video"

    def __init__(self, aus: List[bytes], fps: float, loop: bool = False):
        self._aus = aus
        self._fps = fps
        self._i = 0
        self._loop = loop
        self._t0: Optional[float] = None
        self._sent = 0

    async def recv(self) -> Tuple[bytes, int]:
        if self._i >= len(self._aus):
            if not self._loop or not self._aus:
                raise MediaStreamError("end of video")
            self._i = 0
        au = self._aus[self._i]
        self._i += 1
        if self._t0 is None:
            self._t0 = time.monotonic()
        due = self._t0 + self._sent / self._fps
        delay = due - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        ts = int(self._sent * 90000 / self._fps)   # RTP video clock
        self._sent += 1
        return au, ts


class _Y4mFileTrack(MediaTrack):
    """Raw YUV4MPEG2 4:2:0 frames as (H, W, 3)-shaped RGB-like planes are
    NOT reconstructed here — frames are yielded as the raw planar YUV
    bytes plus timestamp; encoder pipelines own the colorspace."""

    kind = "video"

    def __init__(self, path: str, loop: bool = False):
        self._f = open(path, "rb")
        header = self._f.readline().decode("ascii", "replace")
        if not header.startswith("YUV4MPEG2"):
            raise ValueError("not a y4m file")
        self.width = self.height = 0
        num, den = 30, 1
        for tok in header.split()[1:]:
            if tok[0] == "W":
                self.width = int(tok[1:])
            elif tok[0] == "H":
                self.height = int(tok[1:])
            elif tok[0] == "F":
                num, den = (int(x) for x in tok[1:].split(":"))
        self.fps = num / max(1, den)
        self._frame_bytes = self.width * self.height * 3 // 2
        self._loop = loop
        self._start = self._f.tell()
        self._n = 0
        self._t0: Optional[float] = None

    async def recv(self) -> Tuple[bytes, int]:
        line = self._f.readline()
        if not line.startswith(b"FRAME"):
            if self._loop and line == b"":
                self._f.seek(self._start)
                line = self._f.readline()
            if not line.startswith(b"FRAME"):
                raise MediaStreamError("end of y4m")
        data = self._f.read(self._frame_bytes)
        if len(data) < self._frame_bytes:
            raise MediaStreamError("truncated y4m frame")
        if self._t0 is None:
            self._t0 = time.monotonic()
        due = self._t0 + self._n / self.fps
        delay = due - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        ts = int(self._n * 90000 / self.fps)
        self._n += 1
        return data, ts

    def stop(self) -> None:
        self._f.close()


def _parse_wav(path: str) -> Tuple[bytes, int, int]:
    """(pcm_s16_bytes, sample_rate, channels) from a RIFF WAVE file."""
    with open(path, "rb") as f:
        riff = f.read(12)
        if riff[:4] != b"RIFF" or riff[8:12] != b"WAVE":
            raise ValueError("not a wav file")
        rate = channels = 0
        data = b""
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                break
            cid, size = hdr[:4], struct.unpack("<I", hdr[4:])[0]
            body = f.read(size)
            if cid == b"fmt ":
                fmt, channels, rate = struct.unpack_from("<HHI", body)
                bits = struct.unpack_from("<H", body, 14)[0]
                if fmt != 1 or bits != 16:
                    raise ValueError("only PCM s16 wav supported")
            elif cid == b"data":
                data = body
            if size % 2:
                f.read(1)
        if not rate or not channels:
            raise ValueError("wav missing fmt chunk")
        return data, rate, channels


class MediaPlayer:
    """File → tracks. ``player.audio`` / ``player.video`` expose whichever
    track the file provides (None otherwise)."""

    def __init__(self, path: str, loop: bool = False, fps: float = 30.0,
                 encode_opus: bool = True):
        self.audio: Optional[MediaTrack] = None
        self.video: Optional[MediaTrack] = None
        ext = os.path.splitext(path)[1].lower()
        if ext == ".wav":
            pcm, rate, ch = _parse_wav(path)
            self.audio = _AudioFileTrack(memoryview(pcm), rate, ch,
                                         loop=loop, encode_opus=encode_opus)
        elif ext in (".h264", ".264", ".annexb"):
            with open(path, "rb") as f:
                aus = _split_access_units(f.read())
            self.video = _VideoFileTrack(aus, fps, loop=loop)
        elif ext == ".y4m":
            self.video = _Y4mFileTrack(path, loop=loop)
        else:
            raise ValueError(f"unsupported media container: {ext!r}")

    def stop(self) -> None:
        for t in (self.audio, self.video):
            if t is not None:
                t.stop()


# ------------------------------------------------------------------ sinks


class MediaBlackhole:
    """Consume tracks and discard frames (keeps upstream pumps draining)."""

    def __init__(self) -> None:
        self._tracks: List[MediaTrack] = []
        self._tasks: List[asyncio.Task] = []
        self.consumed = 0

    def addTrack(self, track: MediaTrack) -> None:
        self._tracks.append(track)

    async def start(self) -> None:
        for t in self._tracks:
            self._tasks.append(asyncio.ensure_future(self._drain(t)))

    async def _drain(self, track: MediaTrack) -> None:
        while True:
            try:
                await track.recv()
            except MediaStreamError:
                return
            self.consumed += 1

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        self._tasks = []


class MediaRecorder:
    """Tracks → file. Container from the extension: .wav / .h264 / .mjpeg."""

    def __init__(self, path: str, sample_rate: int = 48000,
                 channels: int = 2):
        self.path = path
        self.sample_rate = sample_rate
        self.channels = channels
        self._ext = os.path.splitext(path)[1].lower()
        if self._ext not in (".wav", ".h264", ".264", ".mjpeg", ".mjpg"):
            raise ValueError(f"unsupported recorder container: {self._ext!r}")
        self._tracks: List[MediaTrack] = []
        self._tasks: List[asyncio.Task] = []
        self._f = None
        self._pcm_bytes = 0
        self._dec = None

    def addTrack(self, track: MediaTrack) -> None:
        self._tracks.append(track)

    async def start(self) -> None:
        self._f = open(self.path, "wb")
        if self._ext == ".wav":
            self._f.write(b"\x00" * 44)         # header backpatched on stop
            try:
                from ..audio.codec import OpusDecoder
                self._dec = OpusDecoder(self.sample_rate, self.channels)
            except Exception:
                self._dec = None
        for t in self._tracks:
            self._tasks.append(asyncio.ensure_future(self._pump(t)))

    async def _pump(self, track: MediaTrack) -> None:
        while True:
            try:
                payload, _ts = await track.recv()
            except MediaStreamError:
                return
            if self._f is None:
                return
            if self._ext == ".wav":
                data = payload
                if self._dec is not None:
                    try:
                        data = self._dec.decode(payload).tobytes()
                    except Exception:
                        pass            # raw PCM track — write as-is
                self._f.write(data)
                self._pcm_bytes += len(data)
            else:
                self._f.write(payload)

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []
        if self._f is None:
            return
        if self._ext == ".wav":
            sr, ch, nbytes = self.sample_rate, self.channels, self._pcm_bytes
            self._f.seek(0)
            self._f.write(
                b"RIFF" + struct.pack("<I", 36 + nbytes) + b"WAVE"
                + b"fmt " + struct.pack("<IHHIIHH", 16, 1, ch, sr,
                                        sr * ch * 2, ch * 2, 16)
                + b"data" + struct.pack("<I", nbytes))
        self._f.close()
        self._f = None


# ------------------------------------------------------------------ relay


class _RelayTrack(MediaTrack):
    def __init__(self, kind: str, buffered: bool):
        self.kind = kind
        self._q: asyncio.Queue = asyncio.Queue() if buffered \
            else asyncio.Queue(maxsize=1)
        self._stopped = False
        self._ended = False

    async def recv(self) -> Tuple[bytes, int]:
        if self._stopped:
            raise MediaStreamError("relay stopped")
        if self._ended and self._q.empty():
            raise MediaStreamError("source ended")
        item = await self._q.get()
        if item is None:
            raise MediaStreamError("source ended")
        return item

    def _push(self, item) -> None:
        if self._stopped:
            return
        if self._q.maxsize == 1 and self._q.full():
            try:                         # live mode: newest frame wins
                self._q.get_nowait()
            except asyncio.QueueEmpty:
                pass
        self._q.put_nowait(item)

    def _finish(self) -> None:
        """End of source: never displace a pending frame — wake blocked
        consumers with the sentinel only when the queue is empty."""
        self._ended = True
        if self._q.empty():
            self._q.put_nowait(None)

    def stop(self) -> None:
        self._stopped = True


class MediaRelay:
    """Fan one source track out to many subscribers. ``buffered=False``
    (live) drops stale frames for slow consumers; ``buffered=True``
    queues everything (recording)."""

    def __init__(self) -> None:
        self._pumps: Dict[int, asyncio.Task] = {}
        self._subs: Dict[int, List[_RelayTrack]] = {}

    def subscribe(self, track: MediaTrack,
                  buffered: bool = True) -> MediaTrack:
        key = id(track)
        out = _RelayTrack(track.kind, buffered)
        self._subs.setdefault(key, []).append(out)
        if key not in self._pumps:
            self._pumps[key] = asyncio.ensure_future(self._pump(key, track))
        return out

    async def _pump(self, key: int, track: MediaTrack) -> None:
        while True:
            try:
                item = await track.recv()
            except MediaStreamError:
                for sub in self._subs.get(key, []):
                    sub._finish()
                return
            for sub in self._subs.get(key, []):
                sub._push(item)

    def stop(self) -> None:
        for task in self._pumps.values():
            task.cancel()
        self._pumps.clear()
        for subs in self._subs.values():
            for s in subs:
                s.stop()
        self._subs.clear()


# ------------------------------------------------------------------ pump


async def stream_to(sender, track: MediaTrack) -> int:
    """Pump a track into a MediaSender until end of stream; returns the
    number of frames shipped."""
    n = 0
    while True:
        try:
            payload, ts = await track.recv()
        except MediaStreamError:
            return n
        sender.send_frame(payload, timestamp=ts)
        n += 1
