"""STUN message codec (RFC 5389) with the ICE attributes of RFC 8445.

Foundation for :mod:`.ice` connectivity checks and server-reflexive
candidate discovery against the coturn/STUN infrastructure the reference
deploys (``addons/coturn/``, SURVEY.md §2.6). aioice is not available in
this environment; this is a from-scratch codec.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

MAGIC_COOKIE = 0x2112A442
HEADER_LEN = 20
FINGERPRINT_XOR = 0x5354554E

# methods / classes
BINDING = 0x001
CLASS_REQUEST = 0x00
CLASS_INDICATION = 0x01
CLASS_SUCCESS = 0x02
CLASS_ERROR = 0x03

# attributes
ATTR_MAPPED_ADDRESS = 0x0001
ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009
ATTR_XOR_MAPPED_ADDRESS = 0x0020
ATTR_PRIORITY = 0x0024
ATTR_USE_CANDIDATE = 0x0025
ATTR_SOFTWARE = 0x8022
ATTR_FINGERPRINT = 0x8028
ATTR_ICE_CONTROLLED = 0x8029
ATTR_ICE_CONTROLLING = 0x802A


def message_type(method: int, msg_class: int) -> int:
    """Interleave method and class bits per RFC 5389 §6."""
    m = method
    return ((m & 0x0F80) << 2) | ((m & 0x0070) << 1) | (m & 0x000F) \
        | ((msg_class & 2) << 7) | ((msg_class & 1) << 4)


def split_type(mtype: int) -> Tuple[int, int]:
    method = ((mtype >> 2) & 0x0F80) | ((mtype >> 1) & 0x0070) | (mtype & 0x000F)
    msg_class = ((mtype >> 7) & 2) | ((mtype >> 4) & 1)
    return method, msg_class


def xor_address(addr: Tuple[str, int], transaction_id: bytes) -> bytes:
    import ipaddress

    ip = ipaddress.ip_address(addr[0])
    port = addr[1] ^ (MAGIC_COOKIE >> 16)
    if ip.version == 4:
        xip = int(ip) ^ MAGIC_COOKIE
        return struct.pack("!BBH", 0, 0x01, port) + xip.to_bytes(4, "big")
    xor_key = MAGIC_COOKIE.to_bytes(4, "big") + transaction_id
    raw = bytes(a ^ b for a, b in zip(ip.packed, xor_key))
    return struct.pack("!BBH", 0, 0x02, port) + raw


def unxor_address(data: bytes, transaction_id: bytes) -> Tuple[str, int]:
    import ipaddress

    family = data[1]
    port = struct.unpack_from("!H", data, 2)[0] ^ (MAGIC_COOKIE >> 16)
    if family == 0x01:
        ip = int.from_bytes(data[4:8], "big") ^ MAGIC_COOKIE
        return str(ipaddress.IPv4Address(ip)), port
    xor_key = MAGIC_COOKIE.to_bytes(4, "big") + transaction_id
    raw = bytes(a ^ b for a, b in zip(data[4:20], xor_key))
    return str(ipaddress.IPv6Address(raw)), port


@dataclass
class StunMessage:
    method: int = BINDING
    msg_class: int = CLASS_REQUEST
    transaction_id: bytes = field(default_factory=lambda: os.urandom(12))
    attributes: Dict[int, bytes] = field(default_factory=dict)

    # -- attribute sugar ---------------------------------------------------

    def set_xor_mapped_address(self, addr: Tuple[str, int]) -> None:
        self.attributes[ATTR_XOR_MAPPED_ADDRESS] = xor_address(
            addr, self.transaction_id)

    def xor_mapped_address(self) -> Optional[Tuple[str, int]]:
        raw = self.attributes.get(ATTR_XOR_MAPPED_ADDRESS)
        return unxor_address(raw, self.transaction_id) if raw else None

    def set_username(self, username: str) -> None:
        self.attributes[ATTR_USERNAME] = username.encode()

    def username(self) -> Optional[str]:
        raw = self.attributes.get(ATTR_USERNAME)
        return raw.decode() if raw is not None else None

    def set_error(self, code: int, reason: str = "") -> None:
        self.attributes[ATTR_ERROR_CODE] = struct.pack(
            "!HBB", 0, code // 100, code % 100) + reason.encode()

    def error(self) -> Optional[Tuple[int, str]]:
        raw = self.attributes.get(ATTR_ERROR_CODE)
        if raw is None:
            return None
        return raw[2] * 100 + raw[3], raw[4:].decode(errors="replace")

    # -- serialize / parse -------------------------------------------------

    def serialize(self, integrity_key: Optional[bytes] = None,
                  add_fingerprint: bool = True) -> bytes:
        body = b""
        for attr, value in self.attributes.items():
            body += struct.pack("!HH", attr, len(value)) + value
            body += b"\x00" * ((-len(value)) % 4)

        def header(extra_len: int) -> bytes:
            return struct.pack(
                "!HHI", message_type(self.method, self.msg_class),
                len(body) + extra_len, MAGIC_COOKIE) + self.transaction_id

        if integrity_key is not None:
            mac = hmac.new(integrity_key, header(24) + body, hashlib.sha1).digest()
            body += struct.pack("!HH", ATTR_MESSAGE_INTEGRITY, 20) + mac
        if add_fingerprint:
            crc = (zlib.crc32(header(8) + body) & 0xFFFFFFFF) ^ FINGERPRINT_XOR
            body += struct.pack("!HHI", ATTR_FINGERPRINT, 4, crc)
        return header(0) + body

    @classmethod
    def parse(cls, data: bytes) -> "StunMessage":
        if len(data) < HEADER_LEN:
            raise ValueError("STUN message too short")
        mtype, length, cookie = struct.unpack_from("!HHI", data)
        if cookie != MAGIC_COOKIE:
            raise ValueError("bad magic cookie")
        if mtype & 0xC000:
            raise ValueError("not a STUN message")
        if len(data) < HEADER_LEN + length:
            raise ValueError("truncated STUN message")
        method, msg_class = split_type(mtype)
        msg = cls(method=method, msg_class=msg_class,
                  transaction_id=data[8:20], attributes={})
        pos = HEADER_LEN
        end = HEADER_LEN + length
        while pos + 4 <= end:
            attr, alen = struct.unpack_from("!HH", data, pos)
            pos += 4
            msg.attributes[attr] = data[pos:pos + alen]
            pos += alen + ((-alen) % 4)
        return msg

    def verify_integrity(self, key: bytes) -> bool:
        mac = self.attributes.get(ATTR_MESSAGE_INTEGRITY)
        if mac is None:
            return False
        clone = StunMessage(self.method, self.msg_class, self.transaction_id,
                            {})
        for attr, value in self.attributes.items():
            if attr in (ATTR_MESSAGE_INTEGRITY, ATTR_FINGERPRINT):
                continue
            clone.attributes[attr] = value
        expect = StunMessage.parse(
            clone.serialize(integrity_key=key, add_fingerprint=False)
        ).attributes[ATTR_MESSAGE_INTEGRITY]
        return hmac.compare_digest(mac, expect)


def is_stun(data: bytes) -> bool:
    """First-octet demux per RFC 7983: 0-3 = STUN."""
    return len(data) >= HEADER_LEN and data[0] < 4 \
        and struct.unpack_from("!I", data, 4)[0] == MAGIC_COOKIE
