"""SDP parse/serialize — the JSEP subset the streaming plane needs.

Role parity with the vendored ``src/selkies/webrtc/sdp.py`` (617 LoC,
SURVEY.md §2.4), redesigned as plain dataclasses: bundle-capable audio +
video media sections with ICE credentials/candidates, DTLS fingerprint +
setup role, RTP codec maps with fmtp/rtcp-fb, header extensions, and data
channel (SCTP) sections.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .ice import Candidate


@dataclass
class RtpCodec:
    payload_type: int
    name: str
    clock_rate: int
    channels: Optional[int] = None
    fmtp: Optional[str] = None
    rtcp_fb: List[str] = field(default_factory=list)

    @property
    def rtpmap(self) -> str:
        base = f"{self.name}/{self.clock_rate}"
        return base + (f"/{self.channels}" if self.channels else "")


@dataclass
class MediaSection:
    kind: str                       # audio | video | application
    mid: str = "0"
    port: int = 9
    protocol: str = "UDP/TLS/RTP/SAVPF"
    direction: str = "sendrecv"
    codecs: List[RtpCodec] = field(default_factory=list)
    ssrc: Optional[int] = None
    cname: Optional[str] = None
    msid: Optional[str] = None
    ice_ufrag: Optional[str] = None
    ice_pwd: Optional[str] = None
    ice_lite: bool = False
    candidates: List[Candidate] = field(default_factory=list)
    end_of_candidates: bool = False
    dtls_fingerprint: Optional[str] = None   # "sha-256 AB:CD:..."
    dtls_setup: Optional[str] = None         # actpass | active | passive
    extmap: Dict[int, str] = field(default_factory=dict)
    sctp_port: Optional[int] = None
    max_message_size: Optional[int] = None
    rtcp_mux: bool = True


@dataclass
class SessionDescription:
    session_id: int = 1
    media: List[MediaSection] = field(default_factory=list)
    bundle: List[str] = field(default_factory=list)

    # ------------------------------------------------------------ serialize

    def serialize(self) -> str:
        lines = [
            "v=0",
            f"o=- {self.session_id} 2 IN IP4 127.0.0.1",
            "s=-",
            "t=0 0",
        ]
        if self.bundle:
            lines.append("a=group:BUNDLE " + " ".join(self.bundle))
        lines.append("a=msid-semantic: WMS *")
        for m in self.media:
            lines += self._media_lines(m)
        return "\r\n".join(lines) + "\r\n"

    @staticmethod
    def _media_lines(m: MediaSection) -> List[str]:
        if m.kind == "application":
            fmt = "webrtc-datachannel"
        else:
            fmt = " ".join(str(c.payload_type) for c in m.codecs)
        lines = [f"m={m.kind} {m.port} {m.protocol} {fmt}",
                 "c=IN IP4 0.0.0.0"]
        if m.kind != "application":
            lines.append("a=rtcp:9 IN IP4 0.0.0.0")
        if m.ice_ufrag:
            lines.append(f"a=ice-ufrag:{m.ice_ufrag}")
        if m.ice_pwd:
            lines.append(f"a=ice-pwd:{m.ice_pwd}")
        if m.ice_lite:
            lines.append("a=ice-lite")
        if m.dtls_fingerprint:
            lines.append(f"a=fingerprint:{m.dtls_fingerprint}")
        if m.dtls_setup:
            lines.append(f"a=setup:{m.dtls_setup}")
        lines.append(f"a=mid:{m.mid}")
        for ext_id, uri in sorted(m.extmap.items()):
            lines.append(f"a=extmap:{ext_id} {uri}")
        if m.kind != "application":
            lines.append(f"a={m.direction}")
            if m.rtcp_mux:
                lines.append("a=rtcp-mux")
            for c in m.codecs:
                lines.append(f"a=rtpmap:{c.payload_type} {c.rtpmap}")
                for fb in c.rtcp_fb:
                    lines.append(f"a=rtcp-fb:{c.payload_type} {fb}")
                if c.fmtp:
                    lines.append(f"a=fmtp:{c.payload_type} {c.fmtp}")
            if m.ssrc is not None:
                if m.msid:
                    lines.append(f"a=ssrc:{m.ssrc} msid:{m.msid}")
                lines.append(f"a=ssrc:{m.ssrc} cname:{m.cname or 'selkies'}")
        else:
            lines.append(f"a=sctp-port:{m.sctp_port or 5000}")
            if m.max_message_size:
                lines.append(f"a=max-message-size:{m.max_message_size}")
        for cand in m.candidates:
            lines.append("a=" + cand.to_sdp())
        if m.end_of_candidates:
            lines.append("a=end-of-candidates")
        return lines

    # ------------------------------------------------------------ parse

    @classmethod
    def parse(cls, text: str) -> "SessionDescription":
        desc = cls(media=[])
        current: Optional[MediaSection] = None
        # Session-level attributes (before the first m= line) are defaults
        # for every media section — Firefox in particular puts
        # a=fingerprint at session level, and dropping it would leave the
        # DTLS layer with no fingerprint to pin.
        session = MediaSection(kind="session", codecs=[])
        for raw in text.replace("\r\n", "\n").split("\n"):
            line = raw.strip()
            if not line:
                continue
            if line.startswith("o="):
                try:
                    desc.session_id = int(line.split()[1])
                except (IndexError, ValueError):
                    pass
            elif line.startswith("m="):
                parts = line[2:].split()
                current = MediaSection(kind=parts[0], port=int(parts[1]),
                                       protocol=parts[2], codecs=[])
                desc.media.append(current)
            elif line.startswith("a="):
                desc._attr(current if current is not None else session,
                           line[2:])
        for m in desc.media:
            if m.ice_ufrag is None:
                m.ice_ufrag = session.ice_ufrag
            if m.ice_pwd is None:
                m.ice_pwd = session.ice_pwd
            if m.dtls_fingerprint is None:
                m.dtls_fingerprint = session.dtls_fingerprint
            if m.dtls_setup is None:
                m.dtls_setup = session.dtls_setup
            m.ice_lite = m.ice_lite or session.ice_lite
        return desc

    def _attr(self, m: Optional[MediaSection], attr: str) -> None:
        key, _, value = attr.partition(":")
        if key == "group" and value.startswith("BUNDLE"):
            self.bundle = value.split()[1:]
            return
        if m is None:
            return
        if key == "mid":
            m.mid = value
        elif key == "ice-ufrag":
            m.ice_ufrag = value
        elif key == "ice-pwd":
            m.ice_pwd = value
        elif key == "ice-lite":
            m.ice_lite = True
        elif key == "fingerprint":
            m.dtls_fingerprint = value
        elif key == "setup":
            m.dtls_setup = value
        elif key == "rtcp-mux":
            m.rtcp_mux = True
        elif key == "sctp-port":
            m.sctp_port = int(value)
        elif key == "max-message-size":
            m.max_message_size = int(value)
        elif key in ("sendrecv", "sendonly", "recvonly", "inactive"):
            m.direction = key
        elif key == "extmap":
            parts = value.split()
            m.extmap[int(parts[0].split("/")[0])] = parts[1]
        elif key == "rtpmap":
            pt_s, _, map_s = value.partition(" ")
            bits = map_s.split("/")
            codec = RtpCodec(
                payload_type=int(pt_s), name=bits[0],
                clock_rate=int(bits[1]),
                channels=int(bits[2]) if len(bits) > 2 else None)
            m.codecs.append(codec)
        elif key == "fmtp":
            pt_s, _, fmtp = value.partition(" ")
            for c in m.codecs:
                if c.payload_type == int(pt_s):
                    c.fmtp = fmtp
        elif key == "rtcp-fb":
            pt_s, _, fb = value.partition(" ")
            for c in m.codecs:
                if str(c.payload_type) == pt_s:
                    c.rtcp_fb.append(fb)
        elif key == "ssrc":
            parts = value.split(None, 1)
            try:
                m.ssrc = int(parts[0])
            except ValueError:
                return
            if len(parts) > 1:
                field_, _, fv = parts[1].partition(":")
                if field_ == "cname":
                    m.cname = fv
                elif field_ == "msid":
                    m.msid = fv
        elif key == "candidate":
            m.candidates.append(Candidate.from_sdp("candidate:" + value))
        elif key == "end-of-candidates":
            m.end_of_candidates = True


# Default codec maps matching the browser client's expectations
# (H.264 constrained-baseline packetization-mode=1 — what WebCodecs and
# webrtcbin negotiate in the reference, gstwebrtc_app.py:944-984).

def default_video_codecs() -> List[RtpCodec]:
    return [
        RtpCodec(
            payload_type=102, name="H264", clock_rate=90000,
            fmtp="level-asymmetry-allowed=1;packetization-mode=1;"
                 "profile-level-id=42e01f",
            rtcp_fb=["nack", "nack pli", "ccm fir", "goog-remb",
                     "transport-cc"]),
        # RED/ULPFEC (RFC 2198/5109) — negotiated so the browser's native
        # stack accepts the FEC-protected wire format (webrtc/fec.py)
        RtpCodec(payload_type=103, name="red", clock_rate=90000),
        RtpCodec(payload_type=104, name="ulpfec", clock_rate=90000),
    ]


def default_audio_codecs() -> List[RtpCodec]:
    return [RtpCodec(
        payload_type=111, name="opus", clock_rate=48000, channels=2,
        fmtp="minptime=10;useinbandfec=1", rtcp_fb=["transport-cc"])]
