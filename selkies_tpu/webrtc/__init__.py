"""TPU-native WebRTC stack (transport phase 2 of SURVEY.md §7).

The reference stages a vendored aiortc fork (``src/selkies/webrtc/``,
SURVEY.md §2.4) to carry externally-encoded H.264 over real WebRTC without
re-encoding. This package plays the same role for tpuenc bitstreams, built
from scratch on ``cryptography`` primitives (no pyav/pylibsrtp/aioice in
this environment):

  - :mod:`.rtp`        RTP/RTCP packetization (RFC 3550/4585/5104, TWCC, REMB)
  - :mod:`.h264`       Annex-B ↔ FU-A/STAP-A payloader/depayloader (RFC 6184)
  - :mod:`.opus`       Opus payloader (RFC 7587)
  - :mod:`.jitterbuffer` receive-side reorder/assembly
  - :mod:`.rate`       Google Congestion Control (trendline + AIMD)
  - :mod:`.stun`       STUN message codec (RFC 5389)
  - :mod:`.ice`        ICE agent (host candidates + connectivity checks)
  - :mod:`.sdp`        SDP parse/serialize (JSEP subset)
  - :mod:`.srtp`       SRTP/SRTCP protect/unprotect (RFC 3711)
  - :mod:`.dtls`       DTLS 1.2 handshake with use_srtp (RFC 5764)
  - :mod:`.sctp`       SCTP over DTLS + DCEP data channels (RFC 8831/8832)
"""
