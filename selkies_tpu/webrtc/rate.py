"""Google Congestion Control: delay-gradient + loss based rate estimation.

Replaces the reference's GStreamer ``rtpgccbwe`` element
(``legacy/gstwebrtc_app.py:1555-1572``), whose estimated-bitrate signal
feeds ``set_video_bitrate``; here the estimate feeds the tpuenc rate
controller (quality/CRF clamps) and the REMB/TWCC feedback builders.

Structure follows the published GCC draft (draft-ietf-rmcat-gcc-02): an
arrival-time filter over packet groups, a linear-regression *trendline*
estimator of the queuing-delay slope, an overuse detector with adaptive
threshold, and an AIMD rate controller; a separate loss-based controller
takes over above 10% loss. Pure Python, deterministic, unit-testable —
no wall clock reads inside the algorithm (callers pass timestamps).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Tuple

BURST_INTERVAL_MS = 5.0
TRENDLINE_WINDOW = 20
OVERUSE_TIME_TH_MS = 10.0
K_UP = 0.0087
K_DOWN = 0.039
ETA = 1.08            # multiplicative increase
ALPHA = 0.85          # decrease factor
MIN_BITRATE = 150_000
MAX_BITRATE = 40_000_000


@dataclass
class _Group:
    first_send_ms: float
    last_send_ms: float
    first_arrival_ms: float
    last_arrival_ms: float
    size: int


class TrendlineEstimator:
    """Slope of (arrival delta - send delta) accumulation over time."""

    def __init__(self, window: int = TRENDLINE_WINDOW):
        self.window = window
        self._history: Deque[Tuple[float, float]] = deque(maxlen=window)
        self._acc = 0.0
        self._first_arrival: Optional[float] = None
        self.trend = 0.0

    def update(self, recv_delta_ms: float, send_delta_ms: float,
               arrival_ms: float) -> float:
        delta = recv_delta_ms - send_delta_ms
        self._acc += delta
        if self._first_arrival is None:
            self._first_arrival = arrival_ms
        self._history.append((arrival_ms - self._first_arrival, self._acc))
        if len(self._history) >= self.window:
            xs = [h[0] for h in self._history]
            ys = [h[1] for h in self._history]
            n = len(xs)
            mx = sum(xs) / n
            my = sum(ys) / n
            den = sum((x - mx) ** 2 for x in xs)
            if den > 0:
                self.trend = sum(
                    (x - mx) * (y - my) for x, y in zip(xs, ys)) / den
        return self.trend


class OveruseDetector:
    """Adaptive-threshold comparison of the (gained) trend signal."""

    def __init__(self):
        self.threshold = 12.5
        self.state = "normal"          # normal | overuse | underuse
        self._overuse_start: Optional[float] = None
        self._last_update: Optional[float] = None

    def update(self, trend: float, n_deltas: int, now_ms: float) -> str:
        modified = trend * min(n_deltas, 60) * 4.0
        if self._last_update is not None:
            # adapt threshold toward |signal| (k_up/k_down asymmetric)
            k = K_DOWN if abs(modified) < self.threshold else K_UP
            dt = min(now_ms - self._last_update, 100.0)
            self.threshold += k * (abs(modified) - self.threshold) * dt
            self.threshold = min(max(self.threshold, 6.0), 600.0)
        self._last_update = now_ms

        if modified > self.threshold:
            if self._overuse_start is None:
                self._overuse_start = now_ms
            elif now_ms - self._overuse_start > OVERUSE_TIME_TH_MS:
                self.state = "overuse"
        elif modified < -self.threshold:
            self.state = "underuse"
            self._overuse_start = None
        else:
            self.state = "normal"
            self._overuse_start = None
        return self.state


class AimdRateController:
    def __init__(self, start_bitrate: int = 2_000_000):
        self.bitrate = start_bitrate
        self._state = "increase"       # increase | decrease | hold
        self._last_update: Optional[float] = None
        self._avg_max_bitrate: Optional[float] = None

    def update(self, state: str, incoming_bitrate: float, now_ms: float) -> int:
        if self._last_update is None:
            self._last_update = now_ms
        dt = min((now_ms - self._last_update) / 1000.0, 1.0)
        self._last_update = now_ms

        if state == "overuse":
            self._state = "decrease"
        elif state == "underuse":
            self._state = "hold"
        else:  # normal
            if self._state == "decrease":
                self._state = "hold"
            elif self._state == "hold":
                self._state = "increase"

        if self._state == "decrease":
            self.bitrate = int(ALPHA * incoming_bitrate) \
                if incoming_bitrate > 0 else int(ALPHA * self.bitrate)
            m = self._avg_max_bitrate
            self._avg_max_bitrate = incoming_bitrate if m is None \
                else 0.95 * m + 0.05 * incoming_bitrate
        elif self._state == "increase":
            near_max = (self._avg_max_bitrate is not None
                        and incoming_bitrate > 0.95 * self._avg_max_bitrate)
            if near_max:
                self.bitrate += int(max(1000, 0.08 * self.bitrate) * dt * 8)
            else:
                self.bitrate = int(self.bitrate * (ETA ** dt))
        self.bitrate = max(MIN_BITRATE, min(MAX_BITRATE, self.bitrate))
        return self.bitrate


class DelayBasedEstimator:
    """Packet feed → bitrate estimate (receiver- or TWCC-sender-side)."""

    def __init__(self, start_bitrate: int = 2_000_000):
        self.trendline = TrendlineEstimator()
        self.detector = OveruseDetector()
        self.controller = AimdRateController(start_bitrate)
        self._group: Optional[_Group] = None
        self._prev_group: Optional[_Group] = None
        self._n_deltas = 0
        self._recv_window: Deque[Tuple[float, int]] = deque()

    @property
    def bitrate(self) -> int:
        return self.controller.bitrate

    def incoming_bitrate(self, now_ms: float, window_ms: float = 500.0) -> float:
        while self._recv_window and self._recv_window[0][0] < now_ms - window_ms:
            self._recv_window.popleft()
        if not self._recv_window:
            return 0.0
        span = max(now_ms - self._recv_window[0][0], 1.0)
        return sum(s for _, s in self._recv_window) * 8000.0 / span

    def add_packet(self, send_ms: float, arrival_ms: float, size: int) -> int:
        """Feed one packet (send timestamp, arrival timestamp, bytes);
        returns the current bitrate estimate."""
        self._recv_window.append((arrival_ms, size))
        g = self._group
        if g is None:
            self._group = _Group(send_ms, send_ms, arrival_ms, arrival_ms, size)
            return self.controller.bitrate
        if send_ms - g.first_send_ms > BURST_INTERVAL_MS:
            # close the group, compare with previous
            if self._prev_group is not None:
                send_delta = g.last_send_ms - self._prev_group.last_send_ms
                recv_delta = g.last_arrival_ms - self._prev_group.last_arrival_ms
                self._n_deltas += 1
                trend = self.trendline.update(recv_delta, send_delta, arrival_ms)
                state = self.detector.update(trend, self._n_deltas, arrival_ms)
                self.controller.update(
                    state, self.incoming_bitrate(arrival_ms), arrival_ms)
            self._prev_group = g
            self._group = _Group(send_ms, send_ms, arrival_ms, arrival_ms, size)
        else:
            g.last_send_ms = max(g.last_send_ms, send_ms)
            g.last_arrival_ms = max(g.last_arrival_ms, arrival_ms)
            g.size += size
        return self.controller.bitrate


class LossBasedEstimator:
    """RFC-style loss controller: cut above 10% loss, grow below 2%."""

    def __init__(self, start_bitrate: int = 2_000_000):
        self.bitrate = start_bitrate

    def update(self, fraction_lost: float) -> int:
        if fraction_lost > 0.10:
            self.bitrate = int(self.bitrate * (1 - 0.5 * fraction_lost))
        elif fraction_lost < 0.02:
            self.bitrate = int(self.bitrate * 1.05 + 1000)
        self.bitrate = max(MIN_BITRATE, min(MAX_BITRATE, self.bitrate))
        return self.bitrate


class GccEstimator:
    """Combined estimator: min(delay-based, loss-based)."""

    def __init__(self, start_bitrate: int = 2_000_000):
        self.delay = DelayBasedEstimator(start_bitrate)
        self.loss = LossBasedEstimator(start_bitrate)

    @property
    def bitrate(self) -> int:
        return min(self.delay.bitrate, self.loss.bitrate)

    def add_packet(self, send_ms: float, arrival_ms: float, size: int) -> int:
        self.delay.add_packet(send_ms, arrival_ms, size)
        return self.bitrate

    def add_loss_report(self, fraction_lost: float) -> int:
        self.loss.update(fraction_lost)
        return self.bitrate

    def feed_remb(self, bitrate: int) -> int:
        """Receiver-estimated max bitrate caps the loss-based estimate
        (it recovers upward by the loss controller's clean-report growth)."""
        self.loss.bitrate = min(self.loss.bitrate,
                                max(MIN_BITRATE, int(bitrate)))
        return self.bitrate

    def feed_twcc(self, received: List[Tuple[int, Optional[int]]],
                  send_info: dict) -> int:
        """Sender-side estimation from a TWCC feedback packet: ``received``
        is RtcpTwcc.received; ``send_info`` maps twcc-seq → either a send
        time (ms) or a ``(send_ms, size_bytes)`` tuple — real sizes keep
        the AIMD decrease target honest."""
        lost = sum(1 for _, t in received if t is None)
        if received:
            self.loss.update(lost / len(received))
        for seq, t_us in received:
            if t_us is None:
                continue
            info = send_info.get(seq)
            if info is None:
                continue
            if isinstance(info, tuple):
                send_ms, size = info
            else:
                send_ms, size = info, 1200
            self.delay.add_packet(send_ms, t_us / 1000.0, size)
        return self.bitrate
