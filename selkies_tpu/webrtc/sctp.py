"""SCTP over DTLS + DCEP data channels (RFC 4960 subset, RFC 8831/8832).

Role parity with the vendored ``webrtc/rtcsctptransport.py`` (1,865 LoC,
SURVEY.md §2.4): carries the "input" data channel the reference opens with
ordered + max-retransmits=0 semantics (``legacy/gstwebrtc_app.py:1700``).

Subset implemented (sufficient for browser data channels):
  - INIT/INIT-ACK/COOKIE-ECHO/COOKIE-ACK association setup (DTLS handles
    privacy/auth; the cookie is just opaque state echo)
  - DATA with TSN/SID/SSN/PPID, message fragmentation (B/E bits),
  - SACK with cumulative ack + gap blocks; timer + fast retransmit,
  - HEARTBEAT/HEARTBEAT-ACK, ABORT, SHUTDOWN handling,
  - DCEP DATA_CHANNEL_OPEN / ACK (PPID 50) and string (51) / binary (53)
    payloads; empty-string (56) / empty-binary (57) map to b"".

Congestion control (RFC 4960 §7): a per-association cwnd with slow start
and congestion avoidance gates the DATA send path, so a data channel can
carry bulk payloads (file transfers) without flooding the path; SACK gap
reports drive fast retransmit (ssthresh = cwnd/2), and a T3-RTO collapses
cwnd to one MTU. Sends beyond min(cwnd, peer rwnd) queue in order and
drain on SACK arrival or from ``check_retransmit``.
"""

from __future__ import annotations

import logging
import os
import struct
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("selkies_tpu.webrtc.sctp")

# chunk types
CT_DATA = 0
CT_INIT = 1
CT_INIT_ACK = 2
CT_SACK = 3
CT_HEARTBEAT = 4
CT_HEARTBEAT_ACK = 5
CT_ABORT = 6
CT_SHUTDOWN = 7
CT_SHUTDOWN_ACK = 8
CT_ERROR = 9
CT_COOKIE_ECHO = 10
CT_COOKIE_ACK = 11
CT_SHUTDOWN_COMPLETE = 14
CT_FORWARD_TSN = 192

# DCEP (RFC 8832)
PPID_DCEP = 50
PPID_STRING = 51
PPID_BINARY = 53
PPID_STRING_EMPTY = 56
PPID_BINARY_EMPTY = 57

DCEP_OPEN = 0x03
DCEP_ACK = 0x02

CHANNEL_RELIABLE = 0x00
CHANNEL_PARTIAL_RELIABLE_REXMIT = 0x01
CHANNEL_PARTIAL_RELIABLE_TIMED = 0x02
CHANNEL_UNORDERED_FLAG = 0x80

MTU = 1150
RTO = 0.5


def crc32c(data: bytes) -> int:
    """CRC32c (Castagnoli), required by the SCTP common header."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


# table-driven CRC32c for packets of realistic size
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c_fast(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def tsn_gt(a: int, b: int) -> bool:
    return ((a - b) & 0xFFFFFFFF) < 0x80000000 and a != b


def ssn_gt(a: int, b: int) -> bool:
    """16-bit serial comparison for stream sequence numbers."""
    return ((a - b) & 0xFFFF) < 0x8000 and a != b


@dataclass
class DataChannel:
    stream_id: int
    label: str = ""
    protocol: str = ""
    ordered: bool = True
    channel_type: int = CHANNEL_RELIABLE
    reliability: int = 0
    open: bool = False
    on_message: Optional[Callable[[bytes], None]] = None
    on_open: Optional[Callable[[], None]] = None


@dataclass
class _OutChunk:
    tsn: int
    data: bytes                 # full DATA chunk bytes
    sent_at: float
    retransmits: int = 0
    missed: int = 0             # SACK rounds this TSN was reported missing
    fast_rtxed: bool = False


class SctpAssociation:
    """One SCTP association over a DTLS transport (sans-IO)."""

    def __init__(
        self,
        is_client: bool,
        on_send: Callable[[bytes], None],
        port: int = 5000,
    ):
        self.is_client = is_client
        self.on_send = on_send
        self.port = port
        self.state = "closed"       # closed | connecting | established
        self.local_vtag = struct.unpack("!I", os.urandom(4))[0] or 1
        self.remote_vtag = 0
        self.next_tsn = struct.unpack("!I", os.urandom(4))[0]
        self.cum_ack = 0            # last received cumulative TSN
        self._seen_first = False
        self.a_rwnd = 1 << 20
        self.channels: Dict[int, DataChannel] = {}
        self.on_channel: Optional[Callable[[DataChannel], None]] = None

        self._ssn: Dict[int, int] = {}
        self._next_ssn: Dict[int, int] = {}     # sid -> next expected SSN
        self._ordered_hold: Dict[int, Dict[int, Tuple[int, bytes]]] = {}
        self._reasm: Dict[Tuple[int, int], List] = {}
        # unordered fragments reassemble by TSN adjacency, not SSN: senders
        # commonly stamp every unordered message SSN 0, so (sid, ssn) would
        # collide across messages
        self._u_reasm: Dict[int, Dict[int, Tuple[bool, bool, int, bytes]]] = {}
        self._out: Dict[int, _OutChunk] = {}
        self._queue: List[_OutChunk] = []   # cwnd-gated, FIFO by TSN
        # RFC 4960 §7.2.1 initial cwnd; ssthresh starts at the peer's
        # advertised window (updated from every SACK)
        self.cwnd = min(4 * MTU, max(2 * MTU, 4380))
        self.ssthresh = 1 << 20
        # remaining NEW-data allowance: a_rwnd minus outstanding bytes,
        # decremented on each send and recomputed from every SACK
        self.peer_rwnd = 1 << 20
        self.flight = 0                     # DATA chunk bytes outstanding
        self._partial_bytes_acked = 0
        self._last_t3 = 0.0                 # last T3 cwnd-collapse time
        self._recv_tsns: set = set()
        self._next_even_odd = 0 if is_client else 1
        self._setup_chunk: Optional[Tuple[bytes, int]] = None  # (chunk, vtag)
        self._setup_sent_at = 0.0

    # ------------------------------------------------------------ control

    def start(self) -> None:
        # receive() is live as soon as DTLS delivers app data, so on a
        # fast path the peer's INIT/COOKIE exchange can complete before
        # the owning transport gets here — start() must not regress an
        # already-established association back to "connecting"
        if self.state != "closed":
            return
        self.state = "connecting"
        if self.is_client:
            self._send_init()

    def create_channel(self, label: str, protocol: str = "",
                       ordered: bool = True,
                       max_retransmits: Optional[int] = None) -> DataChannel:
        sid = self._next_stream_id()
        ctype = CHANNEL_RELIABLE
        rel = 0
        if max_retransmits is not None:
            ctype = CHANNEL_PARTIAL_RELIABLE_REXMIT
            rel = max_retransmits
        if not ordered:
            ctype |= CHANNEL_UNORDERED_FLAG
        ch = DataChannel(stream_id=sid, label=label, protocol=protocol,
                         ordered=ordered, channel_type=ctype, reliability=rel)
        self.channels[sid] = ch
        if self.state == "established":
            self._send_dcep_open(ch)
        return ch

    def _next_stream_id(self) -> int:
        sid = self._next_even_odd
        while sid in self.channels:
            sid += 2
        self._next_even_odd = sid + 2
        return sid

    def send(self, channel: DataChannel, data, ppid: Optional[int] = None) -> None:
        if isinstance(data, str):
            payload = data.encode()
            ppid = ppid or (PPID_STRING if payload else PPID_STRING_EMPTY)
        else:
            payload = bytes(data)
            ppid = ppid or (PPID_BINARY if payload else PPID_BINARY_EMPTY)
        if not payload:
            payload = b"\x00"  # empty PPIDs carry one padding byte
        self._send_data(channel.stream_id, ppid, payload,
                        unordered=not channel.ordered)

    # ------------------------------------------------------------ timers

    def check_retransmit(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self.state == "connecting" and self._setup_chunk is not None \
                and now - self._setup_sent_at > RTO:
            chunk, vtag = self._setup_chunk
            self._setup_sent_at = now
            self._send_packet([chunk], vtag=vtag)
        # dict preserves insertion order == send order, so this list is
        # already earliest-TSN-first within the association
        expired = [c for c in self._out.values()
                   if now - c.sent_at > RTO * (2 ** min(c.retransmits, 4))]
        if expired:
            # RFC 4960 §7.2.3: collapse cwnd to one MTU FIRST, then
            # retransmit only the earliest chunk(s) that fit that single
            # MTU. The rest stay marked expired; SACK arrivals and later
            # timer fires drive them out, so one timeout cannot re-blast
            # the whole outstanding window into a congested path. The
            # multiplicative decrease applies once per RTO window, not on
            # every 50 ms tick that still sees the draining backlog —
            # otherwise ssthresh gets crushed to its 4-MTU floor and the
            # path-capacity memory it carries is destroyed.
            if now - self._last_t3 >= RTO:
                self._last_t3 = now
                self.ssthresh = max(self.cwnd // 2, 4 * MTU)
                self.cwnd = MTU
                self._partial_bytes_acked = 0
            sent = 0
            for chunk in expired:
                if sent and sent + len(chunk.data) > MTU:
                    break
                chunk.retransmits += 1
                if chunk.retransmits > 8:
                    # RFC 4960 §8.1: endpoint failure — a reliable channel
                    # must not silently turn best-effort
                    logger.error("SCTP peer unreachable after %d "
                                 "retransmits; aborting association",
                                 chunk.retransmits)
                    self.state = "closed"
                    self._out.clear()
                    self._queue.clear()
                    self.flight = 0
                    return
                chunk.sent_at = now
                self._send_packet([chunk.data])
                sent += len(chunk.data)
        self._flush(now)

    # ----------------------------------------------------------- receive

    def receive(self, packet: bytes) -> None:
        if len(packet) < 12:
            return
        src, dst, vtag = struct.unpack_from("!HHI", packet)
        pos = 12
        chunks = []
        while pos + 4 <= len(packet):
            ctype, flags, length = struct.unpack_from("!BBH", packet, pos)
            if length < 4:
                break
            body = packet[pos + 4:pos + length]
            chunks.append((ctype, flags, body))
            pos += length + ((-length) % 4)
        sacked = False
        for ctype, flags, body in chunks:
            if ctype == CT_INIT:
                self._on_init(body)
            elif ctype == CT_INIT_ACK:
                self._on_init_ack(body)
            elif ctype == CT_COOKIE_ECHO:
                self._send_packet([self._chunk(CT_COOKIE_ACK, 0, b"")])
                self._establish()
            elif ctype == CT_COOKIE_ACK:
                self._establish()
            elif ctype == CT_DATA:
                self._on_data(flags, body)
                sacked = True
            elif ctype == CT_SACK:
                self._on_sack(body)
            elif ctype == CT_HEARTBEAT:
                self._send_packet([self._chunk(CT_HEARTBEAT_ACK, 0, body)])
            elif ctype == CT_ABORT:
                self.state = "closed"
            elif ctype == CT_SHUTDOWN:
                self._send_packet([self._chunk(CT_SHUTDOWN_ACK, 0, b"")])
                self.state = "closed"
            elif ctype == CT_SHUTDOWN_ACK:
                self._send_packet([self._chunk(CT_SHUTDOWN_COMPLETE, 0, b"")])
                self.state = "closed"
            elif ctype == CT_FORWARD_TSN:
                self._on_forward_tsn(body)
        if sacked:
            self._send_sack()

    # ------------------------------------------------------ assoc setup

    def _send_init(self) -> None:
        body = struct.pack("!IIHHI", self.local_vtag, self.a_rwnd,
                           1024, 1024, self.next_tsn)
        chunk = self._chunk(CT_INIT, 0, body)
        self._setup_chunk = (chunk, 0)
        self._setup_sent_at = time.monotonic()
        self._send_packet([chunk], vtag=0)

    def _on_init(self, body: bytes) -> None:
        vtag, rwnd, os_, is_, itsn = struct.unpack_from("!IIHHI", body)
        self.remote_vtag = vtag
        self.cum_ack = (itsn - 1) & 0xFFFFFFFF
        self._seen_first = True
        ack = struct.pack("!IIHHI", self.local_vtag, self.a_rwnd,
                          1024, 1024, self.next_tsn)
        cookie = os.urandom(8)
        ack += struct.pack("!HH", 7, 4 + len(cookie)) + cookie  # state cookie
        self._send_packet([self._chunk(CT_INIT_ACK, 0, ack)])

    def _on_init_ack(self, body: bytes) -> None:
        vtag, rwnd, os_, is_, itsn = struct.unpack_from("!IIHHI", body)
        self.remote_vtag = vtag
        self.cum_ack = (itsn - 1) & 0xFFFFFFFF
        self._seen_first = True
        # echo the state cookie parameter
        pos = 16
        cookie = b""
        while pos + 4 <= len(body):
            ptype, plen = struct.unpack_from("!HH", body, pos)
            if ptype == 7:
                cookie = body[pos + 4:pos + plen]
            pos += plen + ((-plen) % 4)
        chunk = self._chunk(CT_COOKIE_ECHO, 0, cookie)
        self._setup_chunk = (chunk, None)
        self._setup_sent_at = time.monotonic()
        self._send_packet([chunk])

    def _establish(self) -> None:
        if self.state == "established":
            return
        self.state = "established"
        self._setup_chunk = None
        for ch in self.channels.values():
            if not ch.open:
                self._send_dcep_open(ch)

    # ------------------------------------------------------------- DATA

    def _send_data(self, sid: int, ppid: int, payload: bytes,
                   unordered: bool = False) -> None:
        ssn = self._ssn.get(sid, 0)
        if not unordered:
            self._ssn[sid] = (ssn + 1) & 0xFFFF
        max_frag = MTU - 16
        pieces = [payload[i:i + max_frag]
                  for i in range(0, len(payload), max_frag)] or [b""]
        for i, piece in enumerate(pieces):
            flags = (0x04 if unordered else 0)
            if i == 0:
                flags |= 0x02                      # B
            if i == len(pieces) - 1:
                flags |= 0x01                      # E
            tsn = self.next_tsn
            self.next_tsn = (self.next_tsn + 1) & 0xFFFFFFFF
            body = struct.pack("!IHHI", tsn, sid, ssn, ppid) + piece
            chunk = self._chunk(CT_DATA, flags, body)
            self._queue.append(_OutChunk(tsn, chunk, 0.0))
        self._flush()

    def _flush(self, now: Optional[float] = None) -> None:
        """Send queued DATA while the flight fits min(cwnd, peer rwnd).

        One chunk is always allowed when nothing is in flight (the
        zero-window probe of RFC 4960 §6.1 A), so the association cannot
        deadlock on a zero advertisement.

        The two windows gate differently: cwnd bounds total outstanding
        bytes (flight + new), while peer_rwnd is already the REMAINING
        new-data allowance (a_rwnd minus outstanding, recomputed on every
        SACK and decremented per send) — comparing flight against it too
        would double-count the in-flight bytes."""
        while self._queue:
            chunk = self._queue[0]
            size = len(chunk.data)
            if self.flight > 0 and (self.flight + size > self.cwnd
                                    or size > self.peer_rwnd):
                return
            self._queue.pop(0)
            chunk.sent_at = time.monotonic() if now is None else now
            self._out[chunk.tsn] = chunk
            self.flight += size
            self.peer_rwnd = max(0, self.peer_rwnd - size)
            self._send_packet([chunk.data])

    def _on_data(self, flags: int, body: bytes) -> None:
        if len(body) < 12:
            return
        tsn, sid, ssn, ppid = struct.unpack_from("!IHHI", body)
        payload = body[12:]
        # at/below the cumulative ack = already delivered (the TSN set is
        # pruned there, so this guard is what stops SACK-loss re-delivery)
        if self._seen_first and not tsn_gt(tsn, self.cum_ack):
            return
        if tsn in self._recv_tsns:
            return
        self._recv_tsns.add(tsn)
        # advance cumulative ack over any contiguous run
        while ((self.cum_ack + 1) & 0xFFFFFFFF) in self._recv_tsns:
            self.cum_ack = (self.cum_ack + 1) & 0xFFFFFFFF
        begin, end = flags & 0x02, flags & 0x01
        unordered = bool(flags & 0x04)
        if begin and end:
            self._deliver_complete(sid, ssn, ppid, payload, unordered)
        elif unordered:
            ufrags = self._u_reasm.setdefault(sid, {})
            ufrags[tsn] = (bool(begin), bool(end), ppid, payload)
            self._try_unordered_reasm(sid, tsn)
        else:
            key = (sid, ssn)
            frags = self._reasm.setdefault(key, [])
            frags.append((tsn, begin, end, payload))
            # serial sort robust to the 32-bit wrap: all fragments of one
            # message lie within a tiny TSN span, so distances measured
            # from (any member - 2^31) are monotone with no discontinuity
            base = (frags[0][0] - 0x80000000) & 0xFFFFFFFF
            frags.sort(key=lambda f: (f[0] - base) & 0xFFFFFFFF)
            if frags[0][1] and frags[-1][2] and \
                    all(((frags[i + 1][0] - frags[i][0]) & 0xFFFFFFFF) == 1
                        for i in range(len(frags) - 1)):
                whole = b"".join(f[3] for f in frags)
                del self._reasm[key]
                self._deliver_complete(sid, ssn, ppid, whole, unordered)

    def _try_unordered_reasm(self, sid: int, tsn: int) -> None:
        """Assemble an unordered message around ``tsn`` by TSN adjacency
        (RFC 4960 §6.6: unordered fragments of one message occupy
        consecutive TSNs from the B fragment to the E fragment)."""
        ufrags = self._u_reasm[sid]
        start = tsn
        while True:
            f = ufrags.get(start)
            if f is None:
                return
            if f[0]:        # B fragment
                break
            start = (start - 1) & 0xFFFFFFFF
        stop = tsn
        while True:
            f = ufrags.get(stop)
            if f is None:
                return
            if f[1]:        # E fragment
                break
            stop = (stop + 1) & 0xFFFFFFFF
        run = []
        t = start
        while True:
            run.append(t)
            if t == stop:
                break
            t = (t + 1) & 0xFFFFFFFF
        ppid = ufrags[start][2]
        whole = b"".join(ufrags[t][3] for t in run)
        for t in run:
            del ufrags[t]
        self._deliver(sid, ppid, whole)

    def _on_forward_tsn(self, body: bytes) -> None:
        """RFC 3758: the peer abandoned chunks up to a new cumulative TSN.

        Advance the receive state so ordered streams do not hold back
        forever behind an abandoned SSN."""
        if len(body) < 4:
            return
        new_cum = struct.unpack_from("!I", body)[0]
        if not tsn_gt(new_cum, self.cum_ack):
            return
        self.cum_ack = new_cum
        self._seen_first = True
        # continue over anything contiguous we already hold
        while ((self.cum_ack + 1) & 0xFFFFFFFF) in self._recv_tsns:
            self.cum_ack = (self.cum_ack + 1) & 0xFFFFFFFF
        pos = 4
        while pos + 4 <= len(body):
            sid, ssn = struct.unpack_from("!HH", body, pos)
            pos += 4
            old = self._next_ssn.setdefault(sid, 0)
            new_next = (ssn + 1) & 0xFFFF
            hold = self._ordered_hold.get(sid, {})
            if ssn_gt(new_next, old):
                # the skip unblocks fully received messages queued at or
                # below the abandoned SSN — deliver them, don't drop them
                for s in sorted(hold, key=lambda s: (s - old) & 0xFFFF):
                    if ssn_gt(s, ssn):
                        continue
                    item = hold.pop(s)
                    self._deliver(sid, item[0], item[1])
                self._next_ssn[sid] = new_next
            # drop reassembly state for abandoned messages on this stream
            for key in [k for k in self._reasm
                        if k[0] == sid and not ssn_gt(k[1], ssn)]:
                del self._reasm[key]
            # release anything now contiguous past the skip
            while True:
                nxt = self._next_ssn[sid]
                item = hold.pop(nxt, None)
                if item is None:
                    break
                self._next_ssn[sid] = (nxt + 1) & 0xFFFF
                self._deliver(sid, item[0], item[1])
        self._prune_unordered_reasm(new_cum)
        self._send_sack()

    def _prune_unordered_reasm(self, cum: int) -> None:
        """Unordered fragments of messages abandoned by a FORWARD TSN can
        never complete (TSNs at/below cum are dropped on arrival) — free
        them instead of leaking per-connection memory."""
        for ufrags in self._u_reasm.values():
            for t in [t for t in ufrags if not tsn_gt(t, cum)]:
                del ufrags[t]
            # cascade upward: a non-B fragment at boundary+1 whose
            # predecessor was abandoned can never reach its B fragment
            boundary = cum
            for t in sorted(ufrags, key=lambda x: (x - cum) & 0xFFFFFFFF):
                prev = (t - 1) & 0xFFFFFFFF
                if not ufrags[t][0] and prev not in ufrags \
                        and not tsn_gt(prev, boundary):
                    del ufrags[t]
                    boundary = t

    def _deliver_complete(self, sid: int, ssn: int, ppid: int,
                          payload: bytes, unordered: bool) -> None:
        """Deliver a fully reassembled message, honoring stream ordering.

        Ordered streams (the "input" data channel is opened ordered) must
        not surface messages in TSN-completion order under UDP reordering —
        e.g. keyup before keydown. Hold out-of-order messages per stream
        and release them in SSN sequence.
        """
        if unordered:
            self._deliver(sid, ppid, payload)
            return
        nxt = self._next_ssn.setdefault(sid, 0)
        if ssn != nxt and not ssn_gt(ssn, nxt):
            return  # stale duplicate of an already-delivered SSN
        hold = self._ordered_hold.setdefault(sid, {})
        hold[ssn] = (ppid, payload)
        while True:
            nxt = self._next_ssn[sid]
            item = hold.pop(nxt, None)
            if item is None:
                return
            self._next_ssn[sid] = (nxt + 1) & 0xFFFF
            self._deliver(sid, item[0], item[1])

    def _send_sack(self) -> None:
        gaps = b""
        n_gaps = 0
        # gap ack blocks relative to cum_ack
        pending = sorted(t for t in self._recv_tsns if tsn_gt(t, self.cum_ack))
        start = end = None
        blocks = []
        for t in pending:
            off = (t - self.cum_ack) & 0xFFFFFFFF
            if start is None:
                start = end = off
            elif off == end + 1:
                end = off
            else:
                blocks.append((start, end))
                start = end = off
        if start is not None:
            blocks.append((start, end))
        for s, e in blocks[:20]:
            if e > 0xFFFF:
                # gap-block offsets are 16-bit; anything further ahead is
                # left for the peer's RTX timer rather than raising
                # struct.error out of the receive path
                continue
            gaps += struct.pack("!HH", s, e)
            n_gaps += 1
        body = struct.pack("!IIHH", self.cum_ack, self.a_rwnd, n_gaps, 0) + gaps
        self._send_packet([self._chunk(CT_SACK, 0, body)])
        # TSNs at or below the cumulative ack can never be needed again
        self._recv_tsns = {t for t in self._recv_tsns
                           if tsn_gt(t, self.cum_ack)}

    def _on_sack(self, body: bytes) -> None:
        if len(body) < 12:
            return
        cum, rwnd, n_gaps, n_dups = struct.unpack_from("!IIHH", body)
        acked_bytes = 0

        def _ack(tsn: int) -> None:
            nonlocal acked_bytes
            chunk = self._out.pop(tsn, None)
            if chunk is not None:
                acked_bytes += len(chunk.data)
                self.flight = max(0, self.flight - len(chunk.data))

        for tsn in list(self._out):
            if not tsn_gt(tsn, cum):
                _ack(tsn)
        pos = 12
        gap_acked: set = set()
        highest = cum
        for _ in range(n_gaps):
            if pos + 4 > len(body):
                break
            s, e = struct.unpack_from("!HH", body, pos)
            pos += 4
            for off in range(s, e + 1):
                t = (cum + off) & 0xFFFFFFFF
                gap_acked.add(t)
                if tsn_gt(t, highest):
                    highest = t
                _ack(t)
        if acked_bytes:
            if self.cwnd <= self.ssthresh:
                # slow start: at most one MTU per SACK that acks new data
                self.cwnd += min(acked_bytes, MTU)
            else:
                # congestion avoidance: one MTU per cwnd of acked bytes
                self._partial_bytes_acked += acked_bytes
                if self._partial_bytes_acked >= self.cwnd:
                    self._partial_bytes_acked -= self.cwnd
                    self.cwnd += MTU
        # fast retransmit (RFC 4960 §7.2.4): a TSN below the highest
        # gap-acked TSN reported missing by 3 SACKs goes out immediately,
        # once, with multiplicative decrease
        fast_rtx = False
        if gap_acked:
            for tsn, chunk in self._out.items():
                if tsn_gt(highest, tsn) and tsn not in gap_acked:
                    chunk.missed += 1
                    if chunk.missed >= 3 and not chunk.fast_rtxed:
                        chunk.fast_rtxed = True
                        chunk.sent_at = time.monotonic()
                        self._send_packet([chunk.data])
                        fast_rtx = True
        if fast_rtx:
            self.ssthresh = max(self.cwnd // 2, 4 * MTU)
            self.cwnd = self.ssthresh
            self._partial_bytes_acked = 0
        # RFC 4960 §6.2.1: the usable window is the advertised a_rwnd less
        # bytes still in flight that this SACK did not cover, so _flush
        # cannot overrun the receiver's buffer by a full flight
        self.peer_rwnd = max(0, rwnd - self.flight)
        self._flush()

    # ------------------------------------------------------------- DCEP

    def _send_dcep_open(self, ch: DataChannel) -> None:
        label = ch.label.encode()
        proto = ch.protocol.encode()
        msg = struct.pack("!BBHIHH", DCEP_OPEN, ch.channel_type, 0,
                          ch.reliability, len(label), len(proto))
        msg += label + proto
        self._send_data(ch.stream_id, PPID_DCEP, msg)

    def _deliver(self, sid: int, ppid: int, payload: bytes) -> None:
        if ppid == PPID_DCEP:
            self._on_dcep(sid, payload)
            return
        ch = self.channels.get(sid)
        if ch is None:
            return
        if ppid in (PPID_STRING_EMPTY, PPID_BINARY_EMPTY):
            payload = b""
        if ch.on_message is not None:
            ch.on_message(payload)

    def _on_dcep(self, sid: int, payload: bytes) -> None:
        if not payload:
            return
        if payload[0] == DCEP_OPEN:
            (_, ctype, prio, rel, llen, plen) = struct.unpack_from(
                "!BBHIHH", payload)
            label = payload[12:12 + llen].decode(errors="replace")
            proto = payload[12 + llen:12 + llen + plen].decode(errors="replace")
            ch = self.channels.get(sid)
            if ch is None:
                ch = DataChannel(stream_id=sid, label=label, protocol=proto,
                                 ordered=not (ctype & CHANNEL_UNORDERED_FLAG),
                                 channel_type=ctype, reliability=rel)
                self.channels[sid] = ch
            ch.open = True
            self._send_data(sid, PPID_DCEP, bytes([DCEP_ACK]))
            if self.on_channel is not None:
                self.on_channel(ch)
            if ch.on_open is not None:
                ch.on_open()
        elif payload[0] == DCEP_ACK:
            ch = self.channels.get(sid)
            if ch is not None and not ch.open:
                ch.open = True
                if ch.on_open is not None:
                    ch.on_open()

    # ------------------------------------------------------------- wire

    def _chunk(self, ctype: int, flags: int, body: bytes) -> bytes:
        chunk = struct.pack("!BBH", ctype, flags, 4 + len(body)) + body
        return chunk + b"\x00" * ((-len(chunk)) % 4)

    def _send_packet(self, chunks: List[bytes], vtag: Optional[int] = None) -> None:
        vtag = self.remote_vtag if vtag is None else vtag
        hdr = struct.pack("!HHI", self.port, self.port, vtag)
        packet = hdr + struct.pack("!I", 0) + b"".join(chunks)
        crc = crc32c_fast(packet)
        packet = hdr + struct.pack("<I", crc) + b"".join(chunks)
        self.on_send(packet)
