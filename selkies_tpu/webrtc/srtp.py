"""SRTP/SRTCP protection (RFC 3711) for the DTLS-SRTP profile
SRTP_AES128_CM_HMAC_SHA1_80 (RFC 5764 §4.1.2).

Replaces pylibsrtp (used by the reference's vendored stack at
``webrtc/rtcdtlstransport.py:44-51``, not available here) with a pure
Python implementation on ``cryptography``'s AES-CTR + HMAC-SHA1: session
key derivation (§4.3 AES-CM KDF), RTP/RTCP encrypt + 80-bit auth tags,
ROC/sequence tracking with the §3.3.1 index estimate, and a 64-entry
replay window.

Throughput note: media encryption happens per packet on the host CPU;
~1200-byte packets at 60 fps × a few packets/frame is well within
hashlib/AES-NI performance. (The heavy lifting — media encode — is on
the TPU; SRTP is framing.)
"""

from __future__ import annotations

import hmac as hmac_mod
import struct
from hashlib import sha1
from typing import Optional, Tuple

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

SRTP_AES128_CM_HMAC_SHA1_80 = 0x0001
PROFILE_NAMES = {SRTP_AES128_CM_HMAC_SHA1_80: "SRTP_AES128_CM_HMAC_SHA1_80"}

KEY_LEN = 16
SALT_LEN = 14
AUTH_KEY_LEN = 20
AUTH_TAG_LEN = 10      # 80 bits
REPLAY_WINDOW = 64

# KDF labels (RFC 3711 §4.3.2)
LABEL_RTP_ENCRYPTION = 0x00
LABEL_RTP_AUTH = 0x01
LABEL_RTP_SALT = 0x02
LABEL_RTCP_ENCRYPTION = 0x03
LABEL_RTCP_AUTH = 0x04
LABEL_RTCP_SALT = 0x05


def _aes_cm_keystream(key: bytes, iv16: bytes, length: int) -> bytes:
    cipher = Cipher(algorithms.AES(key), modes.CTR(iv16))
    enc = cipher.encryptor()
    return enc.update(b"\x00" * length) + enc.finalize()


def kdf(master_key: bytes, master_salt: bytes, label: int,
        length: int, index: int = 0, kdr: int = 0) -> bytes:
    """AES-CM key derivation (RFC 3711 §4.3.1/§4.3.3)."""
    div = (index // kdr) if kdr else 0
    key_id = (label << 48) | div
    x = int.from_bytes(master_salt, "big") ^ key_id
    iv = (x << 16).to_bytes(16, "big")
    return _aes_cm_keystream(master_key, iv, length)


class _ReplayWindow:
    def __init__(self):
        self.highest: Optional[int] = None
        self.mask = 0

    def check_and_update(self, index: int) -> bool:
        if self.highest is None:
            self.highest = index
            self.mask = 1
            return True
        if index > self.highest:
            shift = index - self.highest
            self.mask = ((self.mask << shift) | 1) & ((1 << REPLAY_WINDOW) - 1)
            self.highest = index
            return True
        delta = self.highest - index
        if delta >= REPLAY_WINDOW or (self.mask >> delta) & 1:
            return False
        self.mask |= 1 << delta
        return True


class SrtpContext:
    """One direction of an SRTP session (one master key/salt)."""

    def __init__(self, master_key: bytes, master_salt: bytes):
        if len(master_key) != KEY_LEN or len(master_salt) != SALT_LEN:
            raise ValueError("bad master key/salt length")
        self.rtp_key = kdf(master_key, master_salt, LABEL_RTP_ENCRYPTION, KEY_LEN)
        self.rtp_auth = kdf(master_key, master_salt, LABEL_RTP_AUTH, AUTH_KEY_LEN)
        self.rtp_salt = kdf(master_key, master_salt, LABEL_RTP_SALT, SALT_LEN)
        self.rtcp_key = kdf(master_key, master_salt, LABEL_RTCP_ENCRYPTION, KEY_LEN)
        self.rtcp_auth = kdf(master_key, master_salt, LABEL_RTCP_AUTH, AUTH_KEY_LEN)
        self.rtcp_salt = kdf(master_key, master_salt, LABEL_RTCP_SALT, SALT_LEN)
        # per-SSRC state
        self._roc: dict = {}         # ssrc -> rollover counter
        self._s_l: dict = {}         # ssrc -> highest seq seen
        self._replay: dict = {}      # ssrc -> _ReplayWindow
        self._rtcp_index = 0
        self._rtcp_replay: dict = {}

    # ---------------------------------------------------------------- RTP

    def _rtp_index(self, ssrc: int, seq: int) -> int:
        """§3.3.1 packet index estimate from ROC and highest seq."""
        roc = self._roc.get(ssrc, 0)
        s_l = self._s_l.get(ssrc)
        if s_l is None:
            return (roc << 16) | seq
        if s_l < 32768:
            v = roc - 1 if seq - s_l > 32768 else roc
        else:
            v = roc + 1 if s_l - seq > 32768 else roc
        return (max(v, 0) << 16) | seq

    def _advance(self, ssrc: int, seq: int, index: int) -> None:
        roc = index >> 16
        s_l = self._s_l.get(ssrc)
        if s_l is None or index > ((self._roc.get(ssrc, 0) << 16) | s_l):
            self._roc[ssrc] = roc
            self._s_l[ssrc] = seq

    def _rtp_iv(self, ssrc: int, index: int) -> bytes:
        x = (int.from_bytes(self.rtp_salt, "big") << 16) \
            ^ (ssrc << 64) ^ (index << 16)
        return (x & ((1 << 128) - 1)).to_bytes(16, "big")

    @staticmethod
    def _header_len(packet: bytes) -> int:
        cc = packet[0] & 0x0F
        pos = 12 + 4 * cc
        if packet[0] & 0x10:  # extension
            if len(packet) < pos + 4:
                raise ValueError("truncated RTP header")
            (_, words) = struct.unpack_from("!HH", packet, pos)
            pos += 4 + words * 4
        return pos

    def protect_rtp(self, packet: bytes) -> bytes:
        ssrc = struct.unpack_from("!I", packet, 8)[0]
        seq = struct.unpack_from("!H", packet, 2)[0]
        index = self._rtp_index(ssrc, seq)
        self._advance(ssrc, seq, index)
        hdr_len = self._header_len(packet)
        keystream = _aes_cm_keystream(
            self.rtp_key, self._rtp_iv(ssrc, index), len(packet) - hdr_len)
        enc = bytes(a ^ b for a, b in zip(packet[hdr_len:], keystream))
        auth_in = packet[:hdr_len] + enc + (index >> 16).to_bytes(4, "big")
        tag = hmac_mod.new(self.rtp_auth, auth_in, sha1).digest()[:AUTH_TAG_LEN]
        return packet[:hdr_len] + enc + tag

    def unprotect_rtp(self, data: bytes) -> bytes:
        if len(data) < 12 + AUTH_TAG_LEN:
            raise ValueError("SRTP packet too short")
        packet, tag = data[:-AUTH_TAG_LEN], data[-AUTH_TAG_LEN:]
        ssrc = struct.unpack_from("!I", packet, 8)[0]
        seq = struct.unpack_from("!H", packet, 2)[0]
        index = self._rtp_index(ssrc, seq)
        auth_in = packet + (index >> 16).to_bytes(4, "big")
        expect = hmac_mod.new(self.rtp_auth, auth_in, sha1).digest()[:AUTH_TAG_LEN]
        if not hmac_mod.compare_digest(tag, expect):
            raise ValueError("SRTP auth failure")
        replay = self._replay.setdefault(ssrc, _ReplayWindow())
        if not replay.check_and_update(index):
            raise ValueError("SRTP replay")
        self._advance(ssrc, seq, index)
        hdr_len = self._header_len(packet)
        keystream = _aes_cm_keystream(
            self.rtp_key, self._rtp_iv(ssrc, index), len(packet) - hdr_len)
        return packet[:hdr_len] + bytes(
            a ^ b for a, b in zip(packet[hdr_len:], keystream))

    # --------------------------------------------------------------- RTCP

    def _rtcp_iv(self, ssrc: int, index: int) -> bytes:
        x = (int.from_bytes(self.rtcp_salt, "big") << 16) \
            ^ (ssrc << 64) ^ (index << 16)
        return (x & ((1 << 128) - 1)).to_bytes(16, "big")

    def protect_rtcp(self, packet: bytes) -> bytes:
        ssrc = struct.unpack_from("!I", packet, 4)[0]
        self._rtcp_index = (self._rtcp_index + 1) & 0x7FFFFFFF
        index = self._rtcp_index
        keystream = _aes_cm_keystream(
            self.rtcp_key, self._rtcp_iv(ssrc, index), len(packet) - 8)
        enc = packet[:8] + bytes(
            a ^ b for a, b in zip(packet[8:], keystream))
        e_index = struct.pack("!I", 0x80000000 | index)  # E-bit set
        auth_in = enc + e_index
        tag = hmac_mod.new(self.rtcp_auth, auth_in, sha1).digest()[:AUTH_TAG_LEN]
        return enc + e_index + tag

    def unprotect_rtcp(self, data: bytes) -> bytes:
        if len(data) < 8 + 4 + AUTH_TAG_LEN:
            raise ValueError("SRTCP packet too short")
        tag = data[-AUTH_TAG_LEN:]
        e_index_raw = data[-AUTH_TAG_LEN - 4:-AUTH_TAG_LEN]
        enc = data[:-AUTH_TAG_LEN - 4]
        expect = hmac_mod.new(
            self.rtcp_auth, enc + e_index_raw, sha1).digest()[:AUTH_TAG_LEN]
        if not hmac_mod.compare_digest(tag, expect):
            raise ValueError("SRTCP auth failure")
        (e_index,) = struct.unpack("!I", e_index_raw)
        index = e_index & 0x7FFFFFFF
        ssrc = struct.unpack_from("!I", enc, 4)[0]
        replay = self._rtcp_replay.setdefault(ssrc, _ReplayWindow())
        if not replay.check_and_update(index):
            raise ValueError("SRTCP replay")
        if not e_index & 0x80000000:
            return enc  # unencrypted SRTCP
        keystream = _aes_cm_keystream(
            self.rtcp_key, self._rtcp_iv(ssrc, index), len(enc) - 8)
        return enc[:8] + bytes(a ^ b for a, b in zip(enc[8:], keystream))


def srtp_pair_from_dtls(
    keying_material: bytes, is_client: bool,
) -> Tuple[SrtpContext, SrtpContext]:
    """Split RFC 5764 §4.2 exporter output into (tx, rx) contexts.

    Layout: client_key | server_key | client_salt | server_salt.
    """
    ck = keying_material[0:16]
    sk = keying_material[16:32]
    cs = keying_material[32:46]
    ss = keying_material[46:60]
    client_ctx = SrtpContext(ck, cs)
    server_ctx = SrtpContext(sk, ss)
    return (client_ctx, server_ctx) if is_client else (server_ctx, client_ctx)
