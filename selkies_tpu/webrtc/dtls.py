"""DTLS 1.2 endpoint (RFC 6347) with DTLS-SRTP keying (RFC 5764).

Role parity with the reference's vendored ``webrtc/rtcdtlstransport.py``
(OpenSSL + pyOpenSSL + pylibsrtp, SURVEY.md §2.4) — none of those bindings
exist in this environment, so the handshake is implemented directly on
``cryptography`` hazmat primitives:

  cipher suite   TLS_ECDHE_ECDSA_WITH_AES_128_GCM_SHA256 (0xC02B)
  curve          secp256r1, signature ecdsa_secp256r1_sha256 (0x0403)
  certificates   self-signed ECDSA P-256, mutual (WebRTC style), verified
                 by SHA-256 fingerprint against the peer's SDP a=fingerprint
  key export     RFC 5705 exporter "EXTRACTOR-dtls_srtp" → SRTP master keys
  app data       AES-128-GCM records (carries SCTP for data channels)

Flights retransmit whole on a doubling timer (RFC 6347 §4.2.4). Handshake
fragmentation is reassembled on receive; sends fit one record (P-256 certs
are ~600 B). HelloVerifyRequest is omitted (permitted by RFC 6347 §4.2.1).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import logging
import os
import struct
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.x509.oid import NameOID

logger = logging.getLogger("selkies_tpu.webrtc.dtls")

DTLS_1_0 = 0xFEFF
DTLS_1_2 = 0xFEFD

CT_CCS = 20
CT_ALERT = 21
CT_HANDSHAKE = 22
CT_APPDATA = 23

HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_HELLO_VERIFY = 3
HT_CERTIFICATE = 11
HT_SERVER_KEY_EXCHANGE = 12
HT_CERTIFICATE_REQUEST = 13
HT_SERVER_HELLO_DONE = 14
HT_CERTIFICATE_VERIFY = 15
HT_CLIENT_KEY_EXCHANGE = 16
HT_FINISHED = 20

CIPHER_ECDHE_ECDSA_AES128_GCM_SHA256 = 0xC02B
CURVE_SECP256R1 = 23
SIGALG_ECDSA_SHA256 = 0x0403

EXT_SUPPORTED_GROUPS = 10
EXT_EC_POINT_FORMATS = 11
EXT_SIGNATURE_ALGS = 13
EXT_USE_SRTP = 14
EXT_RENEGOTIATION_INFO = 0xFF01

SRTP_AES128_CM_HMAC_SHA1_80 = 0x0001
SRTP_KEYING_MATERIAL_LEN = 60   # 2*16 key + 2*14 salt

MASTER_SECRET_LEN = 48
VERIFY_DATA_LEN = 12
GCM_TAG_LEN = 16
RETRANSMIT_BASE = 1.0
MAX_FLIGHT_SENDS = 6


# ------------------------------------------------------------------ PRF


def _p_hash(secret: bytes, seed: bytes, length: int) -> bytes:
    out = b""
    a = seed
    while len(out) < length:
        a = hmac_mod.new(secret, a, hashlib.sha256).digest()
        out += hmac_mod.new(secret, a + seed, hashlib.sha256).digest()
    return out[:length]


def prf(secret: bytes, label: bytes, seed: bytes, length: int) -> bytes:
    return _p_hash(secret, label + seed, length)


# ---------------------------------------------------------- certificates


@dataclass
class DtlsCertificate:
    private_key: ec.EllipticCurvePrivateKey
    certificate: x509.Certificate

    @classmethod
    def generate(cls, common_name: str = "selkies-tpu") -> "DtlsCertificate":
        import datetime

        key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        now = datetime.datetime(2024, 1, 1)
        cert = (
            x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=3650))
            .sign(key, hashes.SHA256())
        )
        return cls(key, cert)

    @property
    def der(self) -> bytes:
        return self.certificate.public_bytes(serialization.Encoding.DER)

    def fingerprint(self) -> str:
        digest = hashlib.sha256(self.der).hexdigest().upper()
        return "sha-256 " + ":".join(
            digest[i:i + 2] for i in range(0, len(digest), 2))


def fingerprint_of_der(der: bytes) -> str:
    digest = hashlib.sha256(der).hexdigest().upper()
    return "sha-256 " + ":".join(
        digest[i:i + 2] for i in range(0, len(digest), 2))


# ------------------------------------------------------------ wire utils


def _hs_header(msg_type: int, length: int, msg_seq: int) -> bytes:
    return struct.pack("!B", msg_type) + length.to_bytes(3, "big") \
        + struct.pack("!H", msg_seq) + (0).to_bytes(3, "big") \
        + length.to_bytes(3, "big")


def _merge_range(ranges: list, start: int, end: int) -> None:
    """Insert [start, end) into a sorted list of disjoint ranges, merging."""
    if end <= start:
        return
    out = []
    for s, e in ranges:
        if e < start or s > end:
            out.append((s, e))
        else:
            start = min(start, s)
            end = max(end, e)
    out.append((start, end))
    out.sort()
    ranges[:] = out


class _Buffer:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("short read")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self.read(1)[0]

    def u16(self) -> int:
        return struct.unpack("!H", self.read(2))[0]

    def u24(self) -> int:
        return int.from_bytes(self.read(3), "big")

    def vec8(self) -> bytes:
        return self.read(self.u8())

    def vec16(self) -> bytes:
        return self.read(self.u16())

    @property
    def remaining(self) -> int:
        return len(self.data) - self.pos


# ------------------------------------------------------------- endpoint


@dataclass
class _PendingFlight:
    records: List[Tuple[int, bytes, int]] = field(default_factory=list)
    # (content_type, payload, epoch) — re-encrypted per retransmit
    sends: int = 0
    next_at: float = 0.0


class DtlsEndpoint:
    """Sans-IO DTLS endpoint: feed datagrams in, datagrams come out via
    ``on_send``; app data out via ``on_data``; completion via
    ``handshake_complete``/``export_srtp``."""

    def __init__(
        self,
        is_client: bool,
        certificate: Optional[DtlsCertificate] = None,
        on_send: Optional[Callable[[bytes], None]] = None,
        remote_fingerprint: Optional[str] = None,
        mtu: int = 1200,
    ):
        self.is_client = is_client
        self.cert = certificate or DtlsCertificate.generate()
        self.on_send = on_send or (lambda d: None)
        self.on_data: Optional[Callable[[bytes], None]] = None
        self.remote_fingerprint = remote_fingerprint
        self.mtu = mtu

        self.handshake_complete = False
        self.handshake_failed: Optional[str] = None

        self._epoch_out = 0
        self._epoch_in = 0
        self._seq_out: Dict[int, int] = {0: 0, 1: 0}
        self._msg_seq_out = 0
        self._next_recv_msg_seq = 0
        self._transcript = b""
        self._frag_buf: Dict[int, Dict] = {}

        self._client_random = os.urandom(32)
        self._server_random = os.urandom(32)
        self._ecdh_priv = ec.generate_private_key(ec.SECP256R1())
        self._peer_pub: Optional[ec.EllipticCurvePublicKey] = None
        self._peer_cert_der: Optional[bytes] = None
        self._peer_cert_verified = False
        self._replay_highest: Dict[int, int] = {}   # epoch -> highest seq
        self._replay_mask: Dict[int, int] = {}      # epoch -> 64-bit window
        self._master_secret: Optional[bytes] = None
        self._client_write_key = b""
        self._server_write_key = b""
        self._client_iv = b""
        self._server_iv = b""
        self._flight = _PendingFlight()
        self._started = False

    # ------------------------------------------------------------ public

    def start(self) -> None:
        """Client: send flight 1. Server: wait for ClientHello."""
        self._started = True
        if self.is_client:
            self._send_client_hello()

    def export_srtp(self) -> bytes:
        """RFC 5705 exporter for the dtls_srtp label (no context)."""
        if not self.handshake_complete or self._master_secret is None:
            raise RuntimeError("handshake not complete")
        return prf(self._master_secret, b"EXTRACTOR-dtls_srtp",
                   self._client_random + self._server_random,
                   SRTP_KEYING_MATERIAL_LEN)

    def local_fingerprint(self) -> str:
        return self.cert.fingerprint()

    def peer_fingerprint(self) -> Optional[str]:
        if self._peer_cert_der is None:
            return None
        return fingerprint_of_der(self._peer_cert_der)

    def send_app_data(self, data: bytes) -> None:
        if not self.handshake_complete:
            raise RuntimeError("handshake not complete")
        self._emit_record(CT_APPDATA, data)

    def check_retransmit(self, now: Optional[float] = None) -> None:
        """Call periodically; retransmits the last flight if unanswered."""
        if self.handshake_complete or not self._flight.records:
            return
        now = time.monotonic() if now is None else now
        if now < self._flight.next_at:
            return
        if self._flight.sends >= MAX_FLIGHT_SENDS:
            self.handshake_failed = "timeout"
            return
        self._retransmit()

    # --------------------------------------------------------- record IO

    def receive(self, datagram: bytes) -> None:
        pos = 0
        while pos + 13 <= len(datagram):
            ctype, ver, epoch = struct.unpack_from("!BHH", datagram, pos)
            seq = int.from_bytes(datagram[pos + 5:pos + 11], "big")
            (length,) = struct.unpack_from("!H", datagram, pos + 11)
            payload = datagram[pos + 13:pos + 13 + length]
            pos += 13 + length
            if epoch > 0:
                if not self._replay_check(epoch, seq):
                    continue
                try:
                    payload = self._decrypt(ctype, epoch, seq, payload)
                except Exception:
                    continue  # bogus record
                self._replay_update(epoch, seq)
            self._handle_record(ctype, payload)

    def _replay_check(self, epoch: int, seq: int) -> bool:
        """Sliding 64-entry anti-replay window (RFC 6347 §4.1.2.6)."""
        highest = self._replay_highest.get(epoch)
        if highest is None or seq > highest:
            return True
        delta = highest - seq
        return delta < 64 and not (self._replay_mask.get(epoch, 0) >> delta) & 1

    def _replay_update(self, epoch: int, seq: int) -> None:
        highest = self._replay_highest.get(epoch)
        mask = self._replay_mask.get(epoch, 0)
        if highest is None or seq > highest:
            shift = seq - highest if highest is not None else 1
            mask = ((mask << shift) | 1) & ((1 << 64) - 1)
            self._replay_highest[epoch] = seq
        else:
            mask |= 1 << (highest - seq)
        self._replay_mask[epoch] = mask

    def _decrypt(self, ctype: int, epoch: int, seq: int, payload: bytes) -> bytes:
        key = self._client_write_key if not self.is_client else self._server_write_key
        iv = self._client_iv if not self.is_client else self._server_iv
        explicit = payload[:8]
        nonce = iv + explicit
        cipher = AESGCM(key)
        seq_bytes = struct.pack("!H", epoch) + seq.to_bytes(6, "big")
        plain_len = len(payload) - 8 - GCM_TAG_LEN
        aad = seq_bytes + struct.pack("!BHH", ctype, DTLS_1_2, plain_len)
        return cipher.decrypt(nonce, payload[8:], aad)

    def _encrypt(self, ctype: int, payload: bytes) -> bytes:
        key = self._client_write_key if self.is_client else self._server_write_key
        iv = self._client_iv if self.is_client else self._server_iv
        epoch = self._epoch_out
        seq = self._seq_out[epoch]
        seq_bytes = struct.pack("!H", epoch) + seq.to_bytes(6, "big")
        nonce = iv + seq_bytes
        aad = seq_bytes + struct.pack("!BHH", ctype, DTLS_1_2, len(payload))
        return seq_bytes + AESGCM(key).encrypt(nonce, payload, aad)

    def _emit_record(self, ctype: int, payload: bytes,
                     epoch: Optional[int] = None, track: bool = False) -> None:
        epoch = self._epoch_out if epoch is None else epoch
        body = payload
        if epoch > 0:
            body = self._encrypt(ctype, payload)
        seq = self._seq_out[epoch]
        self._seq_out[epoch] = seq + 1
        hdr = struct.pack("!BHH", ctype, DTLS_1_2, epoch) \
            + seq.to_bytes(6, "big") + struct.pack("!H", len(body))
        self.on_send(hdr + body)
        if track:
            self._flight.records.append((ctype, payload, epoch))

    def _retransmit(self) -> None:
        records = self._flight.records
        self._flight.records = []
        for ctype, payload, epoch in records:
            self._emit_record(ctype, payload, epoch=epoch, track=True)
        self._flight.sends += 1
        self._flight.next_at = time.monotonic() + RETRANSMIT_BASE \
            * (2 ** self._flight.sends)

    def _new_flight(self) -> None:
        self._flight = _PendingFlight()
        self._flight.sends = 1
        self._flight.next_at = time.monotonic() + RETRANSMIT_BASE

    # ----------------------------------------------------- handshake I/O

    def _send_handshake(self, msg_type: int, body: bytes,
                        track: bool = True) -> None:
        hdr = _hs_header(msg_type, len(body), self._msg_seq_out)
        self._msg_seq_out += 1
        msg = hdr + body
        if msg_type != HT_HELLO_VERIFY:
            self._transcript += msg
        self._emit_record(CT_HANDSHAKE, msg, track=track)

    def _handle_record(self, ctype: int, payload: bytes) -> None:
        if ctype == CT_CCS:
            self._epoch_in = 1
            return
        if ctype == CT_ALERT:
            if len(payload) >= 2 and payload[0] == 2:
                self.handshake_failed = f"fatal alert {payload[1]}"
            return
        if ctype == CT_APPDATA:
            if self.on_data is not None:
                self.on_data(payload)
            return
        if ctype != CT_HANDSHAKE:
            return
        buf = _Buffer(payload)
        while buf.remaining >= 12:
            msg_type = buf.u8()
            length = buf.u24()
            msg_seq = struct.unpack("!H", buf.read(2))[0]
            frag_off = buf.u24()
            frag_len = buf.u24()
            frag = buf.read(frag_len)
            self._feed_fragment(msg_type, length, msg_seq, frag_off, frag)

    def _feed_fragment(self, msg_type: int, length: int, msg_seq: int,
                       frag_off: int, frag: bytes) -> None:
        if msg_seq < self._next_recv_msg_seq:
            # Peer retransmitted a message we've already processed — our
            # responding flight must have been lost (RFC 6347 §4.2.4);
            # re-send it even if our handshake is locally complete.
            if self._flight.records:
                self._retransmit()
            return
        slot = self._frag_buf.setdefault(
            msg_seq, {"type": msg_type, "len": length,
                      "data": bytearray(length), "ranges": []})
        if frag_off + len(frag) > slot["len"]:
            return  # fragment exceeds the declared message length
        data = slot["data"]
        data[frag_off:frag_off + len(frag)] = frag
        # Track received byte *ranges*, not a running count: retransmitted
        # or overlapping fragments must not double-count and declare the
        # message complete while holes remain zero-filled.
        _merge_range(slot["ranges"], frag_off, frag_off + len(frag))
        # numbering-convention tolerance: RFC 6347 has each side start its
        # message_seq at 0, but some stacks continue a single handshake-wide
        # sequence. Adopt the peer's numbering ONLY off its flight-opening
        # ServerHello (a lost seq-0 message must not shift us: anything
        # other than a flight opener arriving first just waits for the
        # retransmission). Transcript hashing is unaffected — both sides
        # hash the wire bytes as sent.
        if self.is_client and self._next_recv_msg_seq == 0 \
                and 0 not in self._frag_buf:
            lowest = min(self._frag_buf)
            if self._frag_buf[lowest]["type"] == HT_SERVER_HELLO:
                self._next_recv_msg_seq = lowest
        # process in order
        while True:
            slot = self._frag_buf.get(self._next_recv_msg_seq)
            if slot is None or \
                    sum(e - s for s, e in slot["ranges"]) < slot["len"]:
                return
            del self._frag_buf[self._next_recv_msg_seq]
            self._next_recv_msg_seq += 1
            body = bytes(slot["data"])
            full = _hs_header(slot["type"], slot["len"],
                              self._next_recv_msg_seq - 1) + body
            try:
                self._handle_handshake(slot["type"], body, full)
            except Exception as exc:  # protocol violation
                logger.exception("DTLS handshake error")
                self.handshake_failed = str(exc)
                return

    # --------------------------------------------------- message builders

    def _hello_extensions(self) -> bytes:
        exts = b""
        exts += struct.pack("!HHH", EXT_SUPPORTED_GROUPS, 4, 2) \
            + struct.pack("!H", CURVE_SECP256R1)
        exts += struct.pack("!HHB", EXT_EC_POINT_FORMATS, 2, 1) + b"\x00"
        exts += struct.pack("!HHH", EXT_SIGNATURE_ALGS, 4, 2) \
            + struct.pack("!H", SIGALG_ECDSA_SHA256)
        exts += struct.pack("!HHH", EXT_USE_SRTP, 5, 2) \
            + struct.pack("!H", SRTP_AES128_CM_HMAC_SHA1_80) + b"\x00"
        exts += struct.pack("!HHB", EXT_RENEGOTIATION_INFO, 1, 0)
        return exts

    def _send_client_hello(self) -> None:
        self._new_flight()
        exts = self._hello_extensions()
        body = struct.pack("!H", DTLS_1_2) + self._client_random \
            + b"\x00" + b"\x00" \
            + struct.pack("!H", 2) \
            + struct.pack("!H", CIPHER_ECDHE_ECDSA_AES128_GCM_SHA256) \
            + b"\x01\x00" \
            + struct.pack("!H", len(exts)) + exts
        self._send_handshake(HT_CLIENT_HELLO, body)

    def _ecdh_public_bytes(self) -> bytes:
        return self._ecdh_priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.UncompressedPoint)

    def _server_flight(self) -> None:
        self._new_flight()
        # ServerHello
        exts = b""
        exts += struct.pack("!HHB", EXT_EC_POINT_FORMATS, 2, 1) + b"\x00"
        exts += struct.pack("!HHH", EXT_USE_SRTP, 5, 2) \
            + struct.pack("!H", SRTP_AES128_CM_HMAC_SHA1_80) + b"\x00"
        exts += struct.pack("!HHB", EXT_RENEGOTIATION_INFO, 1, 0)
        body = struct.pack("!H", DTLS_1_2) + self._server_random + b"\x00" \
            + struct.pack("!H", CIPHER_ECDHE_ECDSA_AES128_GCM_SHA256) \
            + b"\x00" + struct.pack("!H", len(exts)) + exts
        self._send_handshake(HT_SERVER_HELLO, body)
        # Certificate
        der = self.cert.der
        certs = len(der).to_bytes(3, "big") + der
        self._send_handshake(
            HT_CERTIFICATE, len(certs).to_bytes(3, "big") + certs)
        # ServerKeyExchange
        pub = self._ecdh_public_bytes()
        params = b"\x03" + struct.pack("!H", CURVE_SECP256R1) \
            + bytes([len(pub)]) + pub
        signed = self._client_random + self._server_random + params
        sig = self.cert.private_key.sign(signed, ec.ECDSA(hashes.SHA256()))
        ske = params + struct.pack("!H", SIGALG_ECDSA_SHA256) \
            + struct.pack("!H", len(sig)) + sig
        self._send_handshake(HT_SERVER_KEY_EXCHANGE, ske)
        # CertificateRequest (mutual auth, WebRTC style)
        creq = b"\x01\x40" + struct.pack("!HH", 2, SIGALG_ECDSA_SHA256) \
            + struct.pack("!H", 0)
        self._send_handshake(HT_CERTIFICATE_REQUEST, creq)
        # ServerHelloDone
        self._send_handshake(HT_SERVER_HELLO_DONE, b"")

    def _client_flight2(self) -> None:
        self._new_flight()
        # Certificate
        der = self.cert.der
        certs = len(der).to_bytes(3, "big") + der
        self._send_handshake(
            HT_CERTIFICATE, len(certs).to_bytes(3, "big") + certs)
        # ClientKeyExchange
        pub = self._ecdh_public_bytes()
        self._send_handshake(HT_CLIENT_KEY_EXCHANGE, bytes([len(pub)]) + pub)
        # CertificateVerify over the transcript so far
        sig = self.cert.private_key.sign(
            self._transcript, ec.ECDSA(hashes.SHA256()))
        cv = struct.pack("!H", SIGALG_ECDSA_SHA256) \
            + struct.pack("!H", len(sig)) + sig
        self._send_handshake(HT_CERTIFICATE_VERIFY, cv)
        # keys, CCS, Finished
        self._compute_keys()
        self._emit_record(CT_CCS, b"\x01", track=True)
        self._epoch_out = 1
        verify = prf(self._master_secret, b"client finished",
                     hashlib.sha256(self._transcript).digest(),
                     VERIFY_DATA_LEN)
        self._send_handshake(HT_FINISHED, verify)

    def _server_flight2(self) -> None:
        self._new_flight()
        self._emit_record(CT_CCS, b"\x01", track=True)
        self._epoch_out = 1
        verify = prf(self._master_secret, b"server finished",
                     hashlib.sha256(self._transcript).digest(),
                     VERIFY_DATA_LEN)
        self._send_handshake(HT_FINISHED, verify)

    # ----------------------------------------------------- state machine

    def _handle_handshake(self, msg_type: int, body: bytes,
                          full_msg: bytes) -> None:
        if msg_type == HT_CLIENT_HELLO and not self.is_client:
            self._transcript = full_msg
            buf = _Buffer(body)
            buf.u16()                       # client_version
            self._client_random = buf.read(32)
            buf.vec8()                      # session id
            buf.vec8()                      # cookie
            suites = buf.vec16()
            if struct.pack("!H", CIPHER_ECDHE_ECDSA_AES128_GCM_SHA256) \
                    not in [suites[i:i + 2] for i in range(0, len(suites), 2)]:
                raise ValueError("no common cipher suite")
            self._server_flight()
            return

        if msg_type == HT_SERVER_HELLO and self.is_client:
            self._transcript += full_msg
            buf = _Buffer(body)
            buf.u16()
            self._server_random = buf.read(32)
            buf.vec8()
            suite = buf.u16()
            if suite != CIPHER_ECDHE_ECDSA_AES128_GCM_SHA256:
                raise ValueError("unexpected cipher suite")
        elif msg_type == HT_CERTIFICATE:
            self._transcript += full_msg
            buf = _Buffer(body)
            total = buf.u24()
            if total:
                self._peer_cert_der = buf.read(buf.u24())
                self._verify_peer_fingerprint()
                self._peer_cert_verified = True
        elif msg_type == HT_SERVER_KEY_EXCHANGE and self.is_client:
            self._transcript += full_msg
            buf = _Buffer(body)
            curve_type = buf.u8()
            curve = buf.u16()
            if curve_type != 3 or curve != CURVE_SECP256R1:
                raise ValueError("unsupported ECDHE params")
            point = buf.vec8()
            sigalg = buf.u16()
            sig = buf.vec16()
            peer_cert = x509.load_der_x509_certificate(self._peer_cert_der)
            params = b"\x03" + struct.pack("!H", CURVE_SECP256R1) \
                + bytes([len(point)]) + point
            peer_cert.public_key().verify(
                sig, self._client_random + self._server_random + params,
                ec.ECDSA(hashes.SHA256()))
            self._peer_pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256R1(), point)
        elif msg_type == HT_CERTIFICATE_REQUEST and self.is_client:
            self._transcript += full_msg
        elif msg_type == HT_SERVER_HELLO_DONE and self.is_client:
            self._transcript += full_msg
            self._client_flight2()
        elif msg_type == HT_CLIENT_KEY_EXCHANGE and not self.is_client:
            self._transcript += full_msg
            buf = _Buffer(body)
            point = buf.vec8()
            self._peer_pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256R1(), point)
            self._compute_keys()
        elif msg_type == HT_CERTIFICATE_VERIFY and not self.is_client:
            buf = _Buffer(body)
            buf.u16()
            sig = buf.vec16()
            transcript_before = self._transcript
            peer_cert = x509.load_der_x509_certificate(self._peer_cert_der)
            peer_cert.public_key().verify(
                sig, transcript_before, ec.ECDSA(hashes.SHA256()))
            self._peer_key_proven = True
            self._transcript += full_msg
        elif msg_type == HT_FINISHED:
            # mutual auth is mandatory when an SDP fingerprint was pinned:
            # a peer that skipped Certificate/CertificateVerify must not
            # complete the handshake (WebRTC requires client certs).
            if self.remote_fingerprint is not None and \
                    not self._peer_cert_verified:
                raise ValueError("peer sent no certificate")
            if not self.is_client and self.remote_fingerprint is not None \
                    and not getattr(self, "_peer_key_proven", False):
                raise ValueError("client sent no CertificateVerify")
            label = b"client finished" if not self.is_client \
                else b"server finished"
            expect = prf(self._master_secret, label,
                         hashlib.sha256(self._transcript).digest(),
                         VERIFY_DATA_LEN)
            if not hmac_mod.compare_digest(expect, body):
                raise ValueError("Finished verify_data mismatch")
            self._transcript += full_msg
            if self.is_client:
                self.handshake_complete = True
                self._flight = _PendingFlight()
            else:
                self._server_flight2()
                self.handshake_complete = True
        else:
            self._transcript += full_msg

    def _verify_peer_fingerprint(self) -> None:
        if self.remote_fingerprint is None:
            return
        got = fingerprint_of_der(self._peer_cert_der).lower().replace(
            "sha-256 ", "")
        want = self.remote_fingerprint.lower().replace("sha-256", "").strip()
        if got != want:
            raise ValueError("certificate fingerprint mismatch")

    def _compute_keys(self) -> None:
        shared = self._ecdh_priv.exchange(ec.ECDH(), self._peer_pub)
        self._master_secret = prf(
            shared, b"master secret",
            self._client_random + self._server_random, MASTER_SECRET_LEN)
        key_block = prf(
            self._master_secret, b"key expansion",
            self._server_random + self._client_random, 2 * 16 + 2 * 4)
        self._client_write_key = key_block[0:16]
        self._server_write_key = key_block[16:32]
        self._client_iv = key_block[32:36]
        self._server_iv = key_block[36:40]
