"""ICE agent (RFC 8445 subset): host + server-reflexive candidates, full
connectivity checks with aggressive nomination, peer-reflexive learning.

Replaces aioice (used by the reference's vendored stack at
``webrtc/rtcicetransport.py``, SURVEY.md §2.4) — not available here, so
implemented directly on asyncio datagram transports + :mod:`.stun`.

Non-STUN traffic received on the selected pair (DTLS, RTP — RFC 7983
demux) is handed to ``on_data``; ``send()`` ships application bytes on the
nominated pair. TURN relaying is delegated to the deployment's coturn
(server side is on a public address in the reference architecture); a TURN
client allocation is future work and flagged in SURVEY §7.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import secrets
import socket
import string
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import stun

logger = logging.getLogger("selkies_tpu.webrtc.ice")

RTO = 0.5
MAX_RETRIES = 5


def random_string(n: int, alphabet: str = string.ascii_letters + string.digits) -> str:
    return "".join(secrets.choice(alphabet) for _ in range(n))


def candidate_priority(type_pref: int, local_pref: int = 65535,
                       component: int = 1) -> int:
    return (type_pref << 24) | (local_pref << 8) | (256 - component)


TYPE_PREFS = {"host": 126, "prflx": 110, "srflx": 100, "relay": 0}


@dataclass(frozen=True)
class Candidate:
    foundation: str
    component: int
    transport: str
    priority: int
    host: str
    port: int
    type: str

    def to_sdp(self) -> str:
        return (f"candidate:{self.foundation} {self.component} "
                f"{self.transport} {self.priority} {self.host} {self.port} "
                f"typ {self.type}")

    @classmethod
    def from_sdp(cls, line: str) -> "Candidate":
        if line.startswith("a="):
            line = line[2:]
        if line.startswith("candidate:"):
            line = line[len("candidate:"):]
        parts = line.split()
        typ = "host"
        if "typ" in parts:
            typ = parts[parts.index("typ") + 1]
        return cls(parts[0], int(parts[1]), parts[2].lower(), int(parts[3]),
                   parts[4], int(parts[5]), typ)


def local_addresses() -> List[str]:
    """Best-effort list of local unicast IPv4 addresses."""
    addrs = []
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))  # no packets sent for UDP connect
            addrs.append(s.getsockname()[0])
        finally:
            s.close()
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       socket.AF_INET):
            addrs.append(info[4][0])
    except OSError:
        pass
    addrs.append("127.0.0.1")
    seen, out = set(), []
    for a in addrs:
        if a not in seen:
            seen.add(a)
            out.append(a)
    return out


@dataclass
class _Pair:
    local: Candidate
    remote: Candidate
    state: str = "waiting"     # waiting | inprogress | succeeded | failed
    nominated: bool = False

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.remote.host, self.remote.port)


class _Protocol(asyncio.DatagramProtocol):
    def __init__(self, agent: "IceAgent", local_cand: Candidate):
        self.agent = agent
        self.local_cand = local_cand
        self.transport: Optional[asyncio.DatagramTransport] = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        self.agent._datagram(self, data, addr)


class IceAgent:
    def __init__(
        self,
        controlling: bool,
        stun_server: Optional[Tuple[str, int]] = None,
        components: int = 1,
        interfaces: Optional[List[str]] = None,
    ):
        self.controlling = controlling
        self.stun_server = stun_server
        self.local_ufrag = random_string(4)
        self.local_pwd = random_string(22)
        self.remote_ufrag: Optional[str] = None
        self.remote_pwd: Optional[str] = None
        self.local_candidates: List[Candidate] = []
        self.remote_candidates: List[Candidate] = []
        self.on_data: Optional[Callable[[bytes], None]] = None
        self.tie_breaker = int.from_bytes(os.urandom(8), "big")
        self._interfaces = interfaces
        self._protocols: Dict[Tuple[str, int], _Protocol] = {}  # local addr
        self._pairs: List[_Pair] = []
        self._selected: Optional[_Pair] = None
        self._selected_protocol: Optional[_Protocol] = None
        self._connected_evt = asyncio.Event()
        self._pending: Dict[bytes, asyncio.Future] = {}
        self._closed = False

    # ------------------------------------------------------------ gather

    async def gather(self) -> List[Candidate]:
        loop = asyncio.get_running_loop()
        for ip in (self._interfaces or local_addresses()):
            try:
                cand = Candidate(
                    foundation=hashlib.md5(ip.encode()).hexdigest()[:8],
                    component=1, transport="udp",
                    priority=candidate_priority(TYPE_PREFS["host"]),
                    host=ip, port=0, type="host")
                proto = _Protocol(self, cand)
                transport, _ = await loop.create_datagram_endpoint(
                    lambda p=proto: p, local_addr=(ip, 0))
                port = transport.get_extra_info("sockname")[1]
                cand = Candidate(cand.foundation, 1, "udp", cand.priority,
                                 ip, port, "host")
                proto.local_cand = cand
                self._protocols[(ip, port)] = proto
                self.local_candidates.append(cand)
            except OSError:
                continue
        if self.stun_server:
            await self._gather_srflx()
        return self.local_candidates

    async def _gather_srflx(self) -> None:
        for proto in list(self._protocols.values()):
            req = stun.StunMessage(method=stun.BINDING,
                                   msg_class=stun.CLASS_REQUEST)
            try:
                resp = await self._request(proto, req, self.stun_server,
                                           integrity_key=None)
            except (asyncio.TimeoutError, OSError):
                continue
            mapped = resp.xor_mapped_address()
            if mapped and mapped[0] != proto.local_cand.host:
                cand = Candidate(
                    foundation=hashlib.md5(
                        f"srflx{mapped}".encode()).hexdigest()[:8],
                    component=1, transport="udp",
                    priority=candidate_priority(TYPE_PREFS["srflx"]),
                    host=mapped[0], port=mapped[1], type="srflx")
                self.local_candidates.append(cand)

    # ------------------------------------------------------------ control

    def set_remote_credentials(self, ufrag: str, pwd: str) -> None:
        self.remote_ufrag = ufrag
        self.remote_pwd = pwd

    def add_remote_candidate(self, cand: Optional[Candidate]) -> None:
        if cand is None or cand.transport != "udp":
            return
        self.remote_candidates.append(cand)
        for proto in self._protocols.values():
            self._pairs.append(_Pair(proto.local_cand, cand))
        self._sort_pairs()

    def _sort_pairs(self) -> None:
        def prio(p: _Pair) -> int:
            g = p.local.priority if self.controlling else p.remote.priority
            d = p.remote.priority if self.controlling else p.local.priority
            return (min(g, d) << 32) + 2 * max(g, d) + (1 if g > d else 0)
        self._pairs.sort(key=prio, reverse=True)

    async def connect(self, timeout: float = 10.0) -> None:
        """Run connectivity checks until one pair is nominated."""
        if not self._pairs:
            raise ConnectionError("no candidate pairs")
        checker = asyncio.create_task(self._check_loop())
        try:
            await asyncio.wait_for(self._connected_evt.wait(), timeout)
        finally:
            checker.cancel()

    async def _check_loop(self) -> None:
        while not self._connected_evt.is_set() and not self._closed:
            for pair in list(self._pairs):
                if pair.state in ("succeeded", "failed", "inprogress"):
                    continue
                pair.state = "inprogress"
                asyncio.ensure_future(self._check_pair(pair))
            await asyncio.sleep(0.05)

    async def _check_pair(self, pair: _Pair) -> None:
        proto = self._protocols.get((pair.local.host, pair.local.port))
        if proto is None or self.remote_pwd is None:
            pair.state = "failed"
            return
        req = stun.StunMessage(method=stun.BINDING,
                               msg_class=stun.CLASS_REQUEST)
        req.set_username(f"{self.remote_ufrag}:{self.local_ufrag}")
        req.attributes[stun.ATTR_PRIORITY] = candidate_priority(
            TYPE_PREFS["prflx"]).to_bytes(4, "big")
        if self.controlling:
            req.attributes[stun.ATTR_ICE_CONTROLLING] = \
                self.tie_breaker.to_bytes(8, "big")
            req.attributes[stun.ATTR_USE_CANDIDATE] = b""  # aggressive
        else:
            req.attributes[stun.ATTR_ICE_CONTROLLED] = \
                self.tie_breaker.to_bytes(8, "big")
        try:
            await self._request(proto, req, pair.addr,
                                integrity_key=self.remote_pwd.encode())
        except (asyncio.TimeoutError, OSError):
            pair.state = "failed"
            return
        pair.state = "succeeded"
        if self.controlling:
            self._nominate(pair, proto)

    def _nominate(self, pair: _Pair, proto: _Protocol) -> None:
        if self._selected is None:
            pair.nominated = True
            self._selected = pair
            self._selected_protocol = proto
            self._connected_evt.set()
            logger.info("ICE nominated %s:%d -> %s:%d",
                        pair.local.host, pair.local.port,
                        pair.remote.host, pair.remote.port)

    # ------------------------------------------------------------ wire

    async def _request(self, proto: _Protocol, msg: stun.StunMessage,
                       addr: Tuple[str, int],
                       integrity_key: Optional[bytes]) -> stun.StunMessage:
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg.transaction_id] = fut
        payload = msg.serialize(integrity_key=integrity_key)
        try:
            for i in range(MAX_RETRIES):
                proto.transport.sendto(payload, addr)
                try:
                    return await asyncio.wait_for(
                        asyncio.shield(fut), RTO * (2 ** i))
                except asyncio.TimeoutError:
                    continue
            raise asyncio.TimeoutError("STUN request timed out")
        finally:
            self._pending.pop(msg.transaction_id, None)

    def _datagram(self, proto: _Protocol, data: bytes,
                  addr: Tuple[str, int]) -> None:
        if stun.is_stun(data):
            try:
                msg = stun.StunMessage.parse(data)
            except ValueError:
                return
            self._handle_stun(proto, msg, addr)
            return
        if self.on_data is not None:
            self.on_data(data)

    def _handle_stun(self, proto: _Protocol, msg: stun.StunMessage,
                     addr: Tuple[str, int]) -> None:
        if msg.msg_class in (stun.CLASS_SUCCESS, stun.CLASS_ERROR):
            fut = self._pending.get(msg.transaction_id)
            if fut is not None and not fut.done():
                if msg.msg_class == stun.CLASS_ERROR:
                    fut.set_exception(OSError(f"STUN error {msg.error()}"))
                else:
                    fut.set_result(msg)
            return
        if msg.msg_class != stun.CLASS_REQUEST:
            return
        # inbound connectivity check
        if self.local_pwd and not msg.verify_integrity(self.local_pwd.encode()):
            resp = stun.StunMessage(stun.BINDING, stun.CLASS_ERROR,
                                    msg.transaction_id)
            resp.set_error(401, "Unauthorized")
            proto.transport.sendto(resp.serialize(), addr)
            return
        resp = stun.StunMessage(stun.BINDING, stun.CLASS_SUCCESS,
                                msg.transaction_id)
        resp.set_xor_mapped_address(addr)
        proto.transport.sendto(
            resp.serialize(integrity_key=self.local_pwd.encode()), addr)
        # learn peer-reflexive candidates / accept nomination
        known = any(c.host == addr[0] and c.port == addr[1]
                    for c in self.remote_candidates)
        if not known:
            prio = int.from_bytes(
                msg.attributes.get(stun.ATTR_PRIORITY, b"\x00" * 4), "big")
            self.add_remote_candidate(Candidate(
                foundation="prflx", component=1, transport="udp",
                priority=prio or candidate_priority(TYPE_PREFS["prflx"]),
                host=addr[0], port=addr[1], type="prflx"))
        if not self.controlling \
                and stun.ATTR_USE_CANDIDATE in msg.attributes:
            for pair in self._pairs:
                if pair.addr == addr and \
                        (pair.local.host, pair.local.port) == (
                            proto.local_cand.host, proto.local_cand.port):
                    pair.nominated = True
                    self._selected = pair
                    self._selected_protocol = proto
                    self._connected_evt.set()
                    break

    # ------------------------------------------------------------ app data

    def send(self, data: bytes) -> None:
        if self._selected is None or self._selected_protocol is None:
            raise ConnectionError("ICE not connected")
        self._selected_protocol.transport.sendto(data, self._selected.addr)

    @property
    def selected_pair(self) -> Optional[Tuple[Candidate, Candidate]]:
        if self._selected is None:
            return None
        return (self._selected.local, self._selected.remote)

    async def close(self) -> None:
        self._closed = True
        for proto in self._protocols.values():
            if proto.transport is not None:
                proto.transport.close()
        self._protocols.clear()
