"""PeerConnection: JSEP orchestration of ICE + DTLS-SRTP + RTP + SCTP.

Role parity with the vendored ``webrtc/rtcpeerconnection.py`` (SURVEY.md
§2.4), scoped to what the streaming platform needs: a sendrecv video
track carrying externally-encoded H.264 (tpuenc bitstream — never
re-encoded), an Opus audio track, and DCEP data channels for the input
plane. Bundle-only (one transport for everything), rtcp-mux, DTLS role
from SDP ``a=setup``, ICE role from offerer-ship.

Demux on the single socket follows RFC 7983: STUN is consumed inside the
IceAgent; first byte 20-63 → DTLS records (handshake + SCTP app data);
128-191 → SRTP/SRTCP (split by RTCP packet-type range).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time
from typing import Callable, Dict, List, Optional, Tuple

from .dtls import DtlsCertificate, DtlsEndpoint
from .fec import (ULPFEC_PT, UlpFecDecoder, UlpFecEncoder,
                  red_unwrap, red_wrap)
from .h264 import H264Depayloader, H264Payloader
from .ice import Candidate, IceAgent
from .jitterbuffer import JitterBuffer
from .opus import OpusDepayloader, OpusPayloader
from .rate import GccEstimator
from .rtp import (RtcpNack, RtcpPli, RtcpReceiverReport, RtcpRemb,
                  RtcpSenderReport, RtcpTwcc, RtpPacket, is_rtcp,
                  pack_twcc_seq, parse_rtcp)
from .sctp import DataChannel, SctpAssociation
from .sdp import (MediaSection, SessionDescription, default_audio_codecs,
                  default_video_codecs)
from .srtp import SrtpContext, srtp_pair_from_dtls

logger = logging.getLogger("selkies_tpu.webrtc.pc")

VIDEO_PT = 102
AUDIO_PT = 111
VIDEO_CLOCK = 90000
TWCC_EXT_ID = 2          # matches the a=extmap we offer in _describe
TWCC_HISTORY = 2048      # sent-packet records kept for feedback matching


class MediaSender:
    """One outbound RTP stream (externally encoded payloads in)."""

    def __init__(self, pc: "PeerConnection", kind: str, ssrc: int,
                 payload_type: int, clock_rate: int):
        self.pc = pc
        self.kind = kind
        self.ssrc = ssrc
        self.payload_type = payload_type
        self.clock_rate = clock_rate
        self.sequence = struct.unpack("!H", os.urandom(2))[0]
        self.packet_count = 0
        self.octet_count = 0
        self._payloader = H264Payloader() if kind == "video" \
            else OpusPayloader()
        self._last_rtp_ts: Optional[int] = None
        self._last_send_wall: float = 0.0
        #: recent wire packets for NACK retransmission (seq -> raw RTP)
        self._sent: Dict[int, bytes] = {}
        self._fec: Optional[UlpFecEncoder] = None

    def enable_fec(self, percentage: int) -> None:
        """RED+ULPFEC on this (video) stream, FEC overhead ≈ percentage of
        media packets (reference: ulpfec percentage,
        legacy/gstwebrtc_app.py:996-1000). 0 disables."""
        self._fec = UlpFecEncoder(percentage) if percentage > 0 else None

    def send_frame(self, payload: bytes, timestamp: int) -> None:
        """Packetize + protect + ship one encoded frame/AU."""
        packets = self._payloader.packetize(
            payload, self.ssrc, self.payload_type, self.sequence, timestamp)
        self.sequence = (self.sequence + len(packets)) & 0xFFFF
        self._last_rtp_ts = timestamp & 0xFFFFFFFF
        self._last_send_wall = time.time()
        # FEC rides only when the negotiated remote description includes
        # red+ulpfec — a peer that remapped or rejected them must get
        # plain media, not PT-103 packets it never agreed to
        red_pt = self.pc._red_pt
        ulpfec_pt = self.pc._ulpfec_pt
        fec = self._fec if (red_pt is not None
                            and ulpfec_pt is not None) else None
        for pkt in packets:
            # transport-wide sequencing feeds the sender-side GCC estimator
            pkt.extensions[TWCC_EXT_ID] = pack_twcc_seq(self.pc._next_twcc())
            if fec is None:
                self._ship(pkt.sequence_number, pkt.serialize(),
                           len(pkt.payload))
                continue
            # FEC protects the packet in its media form; the wire carries
            # the RED-encapsulated twin (same header, RED PT, 1-byte block
            # header) — matching libwebrtc's RED/ULPFEC arrangement.
            media_raw = pkt.serialize()
            fec_payload = fec.push(media_raw)
            inner = pkt.payload
            pkt.payload_type = red_pt
            pkt.payload = red_wrap(self.payload_type, inner)
            self._ship(pkt.sequence_number, pkt.serialize(), len(inner))
            if fec_payload is not None:
                self._send_fec(fec_payload, timestamp, red_pt, ulpfec_pt)

    def _send_fec(self, fec_payload: bytes, timestamp: int,
                  red_pt: int, ulpfec_pt: int) -> None:
        seq = self.sequence
        self.sequence = (self.sequence + 1) & 0xFFFF
        pkt = RtpPacket(
            payload_type=red_pt, sequence_number=seq,
            timestamp=timestamp & 0xFFFFFFFF, ssrc=self.ssrc,
            payload=red_wrap(ulpfec_pt, fec_payload))
        pkt.extensions[TWCC_EXT_ID] = pack_twcc_seq(self.pc._next_twcc())
        self._ship(seq, pkt.serialize(), len(pkt.payload))

    def _ship(self, seq: int, raw: bytes, payload_len: int) -> None:
        self.packet_count += 1
        self.octet_count += payload_len
        self._sent[seq] = raw
        while len(self._sent) > 512:
            # dicts are insertion-ordered: drop the oldest send, which
            # survives sequence wraparound (a numeric sort would evict
            # the NEWEST packets right after a wrap)
            del self._sent[next(iter(self._sent))]
        self.pc._send_rtp(raw)

    def resend(self, sequence_numbers) -> int:
        """NACK retransmission from the recent-packet buffer."""
        n = 0
        for seq in sequence_numbers:
            raw = self._sent.get(seq & 0xFFFF)
            if raw is not None:
                # no TWCC re-record: the cached packet carries its original
                # transport seq, and stamping the resend against the live
                # counter would corrupt the estimator's send-time table
                self.pc._send_rtp(raw, record_twcc=False)
                n += 1
        return n

    def sender_report(self, now_wall: float) -> Optional[RtcpSenderReport]:
        """SR with an honest NTP↔RTP mapping: the receiver uses this pair
        for A/V sync, so rtp_time must extrapolate the timestamps actually
        stamped on media packets, not an unrelated clock."""
        if self._last_rtp_ts is None:
            return None
        rtp_now = (self._last_rtp_ts + int(
            (now_wall - self._last_send_wall) * self.clock_rate)) & 0xFFFFFFFF
        ntp = int((now_wall + 2208988800) * (1 << 32)) & 0xFFFFFFFFFFFFFFFF
        return RtcpSenderReport(
            ssrc=self.ssrc, ntp_time=ntp, rtp_time=rtp_now,
            packet_count=self.packet_count, octet_count=self.octet_count)


class MediaReceiver:
    """One inbound RTP stream: jitter buffer → depayloader → frames."""

    def __init__(self, kind: str):
        self.kind = kind
        self.jitter = JitterBuffer()
        self.depayloader = H264Depayloader() if kind == "video" \
            else OpusDepayloader()
        self.on_frame: Optional[Callable[[bytes, int], None]] = None
        self.last_ssrc = 0
        self.packets = 0
        self.fec = UlpFecDecoder()
        #: negotiated ulpfec PT (updated from the remote description)
        self.ulpfec_pt = ULPFEC_PT

    def feed(self, packet: RtpPacket) -> None:
        self.last_ssrc = packet.ssrc
        self.packets += 1
        if self.kind == "audio":
            if self.on_frame is not None:
                self.on_frame(self.depayloader.feed(packet), packet.timestamp)
            return
        for pkt in self.jitter.add(packet):
            if pkt.payload_type == self.ulpfec_pt:
                continue      # seq-space placeholder (see feed_red)
            frame = self.depayloader.feed(pkt)
            if frame is not None and self.on_frame is not None:
                self.on_frame(frame, pkt.timestamp)

    def feed_red(self, packet: RtpPacket) -> None:
        """RED-encapsulated input: unwrap blocks, route ULPFEC payloads to
        the recovery cache, media blocks to the normal path, and feed any
        packets FEC can now reconstruct."""
        for pt, data in red_unwrap(packet.payload):
            if pt == self.ulpfec_pt:
                self.fec.add_fec(data)
                # FEC packets share the media sequence space (RFC 5109
                # with RED) — run an empty placeholder through the jitter
                # buffer so its seq doesn't head-of-line block the stream
                self.feed(RtpPacket(
                    payload_type=self.ulpfec_pt,
                    sequence_number=packet.sequence_number,
                    timestamp=packet.timestamp, ssrc=packet.ssrc))
                continue
            media = RtpPacket(
                payload_type=pt, sequence_number=packet.sequence_number,
                timestamp=packet.timestamp, ssrc=packet.ssrc,
                payload=data, marker=packet.marker,
                csrc=list(packet.csrc), extensions=dict(packet.extensions))
            self.fec.add_media(media.serialize())
            self.feed(media)
        for raw in self.fec.try_recover(packet.ssrc):
            try:
                self.feed(RtpPacket.parse(raw))
            except ValueError:
                continue


class PeerConnection:
    def __init__(
        self,
        certificate: Optional[DtlsCertificate] = None,
        stun_server: Optional[Tuple[str, int]] = None,
        interfaces: Optional[List[str]] = None,
    ):
        self.cert = certificate or DtlsCertificate.generate()
        self._stun_server = stun_server
        self._interfaces = interfaces
        self.ice: Optional[IceAgent] = None
        self.dtls: Optional[DtlsEndpoint] = None
        self.sctp: Optional[SctpAssociation] = None
        self.srtp_tx: Optional[SrtpContext] = None
        self.srtp_rx: Optional[SrtpContext] = None
        self.gcc = GccEstimator()
        self._twcc_seq = 0
        self._twcc_sent: Dict[int, Tuple[float, int]] = {}  # seq -> (ms, size)
        self._twcc_recv: Dict[int, int] = {}   # seq -> arrival (µs)
        self._nacked: Dict[int, float] = {}    # wire seq -> last NACK time
        self._twcc_fb_count = 0
        self._twcc_recv_ssrc = 0

        self.senders: Dict[int, MediaSender] = {}      # ssrc -> sender
        self.receivers: Dict[int, MediaReceiver] = {}  # payload type -> recv
        self.on_channel: Optional[Callable[[DataChannel], None]] = None
        self.on_bitrate: Optional[Callable[[int], None]] = None
        self.on_keyframe_request: Optional[Callable[[], None]] = None

        self.is_offerer: Optional[bool] = None
        # payload types as negotiated by the remote description; media PTs
        # start at our defaults, RED/ULPFEC stay None until a remote
        # description that includes both arrives
        self._video_pt = VIDEO_PT
        self._audio_pt = AUDIO_PT
        self._red_pt: Optional[int] = None
        self._ulpfec_pt: Optional[int] = None
        self._local_desc: Optional[SessionDescription] = None
        self._remote_desc: Optional[SessionDescription] = None
        self._pending_channels: List[Tuple[str, dict]] = []
        self._connected = asyncio.Event()
        self._closed = False
        self._run_task: Optional[asyncio.Task] = None
        self._want_data_section = False

    # ------------------------------------------------------------ tracks

    def add_video_sender(self, ssrc: Optional[int] = None) -> MediaSender:
        ssrc = ssrc or struct.unpack("!I", os.urandom(4))[0]
        s = MediaSender(self, "video", ssrc, self._video_pt, VIDEO_CLOCK)
        self.senders[ssrc] = s
        return s

    def add_audio_sender(self, ssrc: Optional[int] = None) -> MediaSender:
        ssrc = ssrc or struct.unpack("!I", os.urandom(4))[0]
        s = MediaSender(self, "audio", ssrc, self._audio_pt, 48000)
        self.senders[ssrc] = s
        return s

    def video_receiver(self) -> MediaReceiver:
        recv = self.receivers.setdefault(self._video_pt,
                                         MediaReceiver("video"))
        if self._ulpfec_pt is not None:
            recv.ulpfec_pt = self._ulpfec_pt
        return recv

    def audio_receiver(self) -> MediaReceiver:
        return self.receivers.setdefault(self._audio_pt,
                                         MediaReceiver("audio"))

    def create_data_channel(self, label: str, protocol: str = "",
                            ordered: bool = True,
                            max_retransmits: Optional[int] = None
                            ) -> "DataChannelHandle":
        self._want_data_section = True
        handle = DataChannelHandle(label, protocol, ordered, max_retransmits)
        self._pending_channels.append(handle)
        if self.sctp is not None and self.sctp.state == "established":
            handle.bind(self.sctp)
        return handle

    # -------------------------------------------------------------- JSEP

    async def create_offer(self) -> str:
        self.is_offerer = True
        await self._ensure_ice(controlling=True)
        self._local_desc = self._describe(setup="actpass")
        return self._local_desc.serialize()

    async def create_answer(self) -> str:
        if self._remote_desc is None:
            raise RuntimeError("set_remote_description first")
        self.is_offerer = False
        await self._ensure_ice(controlling=False)
        self._local_desc = self._describe(setup="active")
        self._start_transport()
        return self._local_desc.serialize()

    async def set_remote_description(self, sdp: str, sdp_type: str) -> None:
        self._remote_desc = SessionDescription.parse(sdp)
        media = self._remote_desc.media
        if not media:
            self._remote_desc = None
            raise ValueError("no media sections")
        if not any(m.dtls_fingerprint for m in media):
            # Fail closed up front (also re-checked in _start_transport):
            # an unpinned DTLS handshake would be open to on-path MITM.
            self._remote_desc = None
            raise ValueError(
                "remote description carries no DTLS fingerprint "
                "(session- or media-level a=fingerprint required)")
        m0 = media[0]
        self._negotiate_fec()
        if self.ice is not None:
            if m0.ice_ufrag and m0.ice_pwd:
                self.ice.set_remote_credentials(m0.ice_ufrag, m0.ice_pwd)
            for m in media:
                for cand in m.candidates:
                    self.ice.add_remote_candidate(cand)
        if sdp_type == "answer" and self.is_offerer:
            self._start_transport()

    def _negotiate_fec(self) -> None:
        """Adopt the remote description's payload-type numbering.

        Fixed constants broke any peer that remaps PTs: its media at the
        remapped PT would never reach a receiver and our sends would carry
        a PT it never agreed to. Applies to the media codecs (H264, opus)
        and to RED/ULPFEC — the FEC pair must BOTH be present in the
        remote video section for the RED path to engage at all."""
        self._red_pt = self._ulpfec_pt = None
        if self._remote_desc is None:
            return

        def _adopt(kind: str, codec_name: str, current: int) -> int:
            section = next((m for m in self._remote_desc.media
                            if m.kind == kind), None)
            if section is None:
                return current
            matches = [c for c in section.codecs
                       if c.name.lower() == codec_name]
            if codec_name == "h264" and len(matches) > 1:
                # browsers offer several H264 entries differing in
                # packetization-mode/profile; this stack sends FU-A
                # fragmented mode-1 constrained-baseline, so prefer the
                # entry that actually denotes that arrangement (RFC 6184:
                # absent packetization-mode means single-NAL mode 0)
                def rank(c):
                    fmtp = c.fmtp or ""
                    mode1 = "packetization-mode=1" in fmtp
                    baseline = "profile-level-id=42" in fmtp
                    return (mode1, baseline)
                matches.sort(key=rank, reverse=True)
            if (codec_name == "h264" and matches
                    and "packetization-mode=1" not in (matches[0].fmtp or "")):
                # we still emit FU-A at this PT; a strict single-NAL
                # (mode-0) receiver cannot parse fragmented units
                logger.warning(
                    "remote offers no packetization-mode=1 H264 entry "
                    "(using pt=%d); FU-A fragments may not decode on "
                    "a strict mode-0 receiver",
                    matches[0].payload_type)
            pt = matches[0].payload_type if matches else None
            if pt is None or pt == current:
                return current
            # re-key the receiver and re-stamp senders of this kind
            recv = self.receivers.pop(current, None)
            if recv is not None:
                self.receivers[pt] = recv
            for s in self.senders.values():
                if s.kind == kind:
                    s.payload_type = pt
            return pt

        self._video_pt = _adopt("video", "h264", self._video_pt)
        self._audio_pt = _adopt("audio", "opus", self._audio_pt)
        video = next((m for m in self._remote_desc.media
                      if m.kind == "video"), None)
        if video is None:
            return
        for c in video.codecs:
            if c.name.lower() == "red":
                self._red_pt = c.payload_type
            elif c.name.lower() == "ulpfec":
                self._ulpfec_pt = c.payload_type
        if self._red_pt is None or self._ulpfec_pt is None:
            self._red_pt = self._ulpfec_pt = None
            return
        recv = self.receivers.get(self._video_pt)
        if recv is not None:
            recv.ulpfec_pt = self._ulpfec_pt

    def add_ice_candidate(self, candidate_sdp: str) -> None:
        if self.ice is not None:
            self.ice.add_remote_candidate(Candidate.from_sdp(candidate_sdp))

    async def wait_connected(self, timeout: float = 15.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout)

    # ---------------------------------------------------------- internals

    async def _ensure_ice(self, controlling: bool) -> None:
        if self.ice is not None:
            return
        self.ice = IceAgent(controlling=controlling,
                            stun_server=self._stun_server,
                            interfaces=self._interfaces)
        await self.ice.gather()
        self.ice.on_data = self._ice_data
        if self._remote_desc is not None:
            m0 = self._remote_desc.media[0]
            if m0.ice_ufrag and m0.ice_pwd:
                self.ice.set_remote_credentials(m0.ice_ufrag, m0.ice_pwd)
            for m in self._remote_desc.media:
                for cand in m.candidates:
                    self.ice.add_remote_candidate(cand)

    def _describe(self, setup: str) -> SessionDescription:
        mids = []
        media = []
        fingerprint = self.cert.fingerprint()
        common = dict(
            ice_ufrag=self.ice.local_ufrag, ice_pwd=self.ice.local_pwd,
            dtls_fingerprint=fingerprint, dtls_setup=setup,
            candidates=list(self.ice.local_candidates),
            end_of_candidates=True)
        video_ssrc = next((s.ssrc for s in self.senders.values()
                           if s.kind == "video"), None)
        audio_ssrc = next((s.ssrc for s in self.senders.values()
                           if s.kind == "audio"), None)
        video_codecs = default_video_codecs()
        audio_codecs = default_audio_codecs()
        if self._remote_desc is not None:
            # answering: an answer may only contain codecs the offer holds
            # — drop red/ulpfec when the remote didn't offer them, and
            # adopt the remote's PT numbering throughout
            for c in video_codecs:
                if c.name == "H264":
                    c.payload_type = self._video_pt
                elif c.name == "red" and self._red_pt is not None:
                    c.payload_type = self._red_pt
                elif c.name == "ulpfec" and self._ulpfec_pt is not None:
                    c.payload_type = self._ulpfec_pt
            for c in audio_codecs:
                if c.name == "opus":
                    c.payload_type = self._audio_pt
            if self._red_pt is None:
                video_codecs = [c for c in video_codecs
                                if c.name not in ("red", "ulpfec")]
        mid = 0
        media.append(MediaSection(
            kind="video", mid=str(mid), codecs=video_codecs,
            ssrc=video_ssrc, cname="selkies-tpu",
            msid="selkies video0", direction="sendrecv", **common))
        mids.append(str(mid)); mid += 1
        media.append(MediaSection(
            kind="audio", mid=str(mid), codecs=audio_codecs,
            ssrc=audio_ssrc, cname="selkies-tpu",
            msid="selkies audio0", direction="sendrecv", **common))
        mids.append(str(mid)); mid += 1
        if self._want_data_section or (
                self._remote_desc is not None and any(
                    m.kind == "application" for m in self._remote_desc.media)):
            media.append(MediaSection(
                kind="application", mid=str(mid),
                protocol="UDP/DTLS/SCTP", sctp_port=5000,
                max_message_size=262144, **common))
            mids.append(str(mid))
        return SessionDescription(
            session_id=struct.unpack("!I", os.urandom(4))[0],
            media=media, bundle=mids)

    def _start_transport(self) -> None:
        remote_fp = next(
            (m.dtls_fingerprint for m in self._remote_desc.media
             if m.dtls_fingerprint), None)
        if remote_fp is None:
            # Fail closed: without a pinned fingerprint the DTLS layer
            # would complete unauthenticated, opening media and the input
            # data channel to an on-path MITM.
            raise ValueError(
                "remote description carries no DTLS fingerprint "
                "(session- or media-level a=fingerprint required)")
        # offerer offered actpass; answerer is active (DTLS client)
        is_dtls_client = not self.is_offerer
        self.dtls = DtlsEndpoint(
            is_client=is_dtls_client, certificate=self.cert,
            on_send=self._dtls_send, remote_fingerprint=remote_fp)
        self.dtls.on_data = self._dtls_app_data
        want_sctp = any(m.kind == "application"
                        for m in self._remote_desc.media) \
            or self._want_data_section
        if want_sctp:
            self.sctp = SctpAssociation(
                is_client=is_dtls_client,
                on_send=lambda d: self.dtls.send_app_data(d))
            self.sctp.on_channel = self._sctp_channel
        self._run_task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        try:
            await self.ice.connect()
        except Exception as exc:
            logger.error("ICE failed: %s", exc)
            return
        self.dtls.start()
        # drive DTLS to completion
        for _ in range(600):
            if self.dtls.handshake_complete or self.dtls.handshake_failed:
                break
            self.dtls.check_retransmit()
            await asyncio.sleep(0.02)
        if not self.dtls.handshake_complete:
            logger.error("DTLS failed: %s", self.dtls.handshake_failed)
            return
        keying = self.dtls.export_srtp()
        self.srtp_tx, self.srtp_rx = srtp_pair_from_dtls(
            keying, is_client=self.dtls.is_client)
        if self.sctp is not None:
            self.sctp.start()
        self._connected.set()
        last_sr = 0.0
        while not self._closed:
            now = time.monotonic()
            if self.sctp is not None:
                self.sctp.check_retransmit(now)
                for handle in self._pending_channels:
                    if not handle.bound and self.sctp.state == "established":
                        handle.bind(self.sctp)
            if now - last_sr > 2.0 and self.srtp_tx is not None:
                last_sr = now
                self._send_sender_reports(now)
            if self._twcc_recv and self.srtp_tx is not None:
                self._send_twcc_feedback()
            self._send_nacks()
            await asyncio.sleep(0.05)

    # ------------------------------------------------------------- demux

    def _ice_data(self, data: bytes) -> None:
        if not data:
            return
        b0 = data[0]
        if 20 <= b0 <= 63:
            self.dtls and self.dtls.receive(data)
        elif 128 <= b0 <= 191 and self.srtp_rx is not None:
            if is_rtcp(data):
                self._handle_rtcp(data)
            else:
                self._handle_rtp(data)

    def _handle_rtp(self, data: bytes) -> None:
        try:
            plain = self.srtp_rx.unprotect_rtp(data)
        except ValueError:
            return
        try:
            pkt = RtpPacket.parse(plain)
        except ValueError:
            return
        ext = pkt.extensions.get(TWCC_EXT_ID)
        if ext is not None and len(ext) == 2:
            seq = int.from_bytes(ext, "big")
            self._twcc_recv[seq] = int(time.monotonic() * 1e6)
            self._twcc_recv_ssrc = pkt.ssrc
        if self._red_pt is not None and pkt.payload_type == self._red_pt:
            self.video_receiver().feed_red(pkt)
            return
        recv = self.receivers.get(pkt.payload_type)
        if recv is not None:
            recv.feed(pkt)

    def _next_twcc(self) -> int:
        seq = self._twcc_seq
        self._twcc_seq = (self._twcc_seq + 1) & 0xFFFF
        return seq

    def _record_twcc_send(self, seq: int, size: int) -> None:
        self._twcc_sent[seq] = (time.monotonic() * 1000.0, size)
        # Evict in insertion order (dicts preserve it): numeric order would
        # drop the *newest* entries right after the 16-bit seq wrap.
        while len(self._twcc_sent) > TWCC_HISTORY:
            del self._twcc_sent[next(iter(self._twcc_sent))]

    def _handle_rtcp(self, data: bytes) -> None:
        try:
            plain = self.srtp_rx.unprotect_rtcp(data)
        except ValueError:
            return
        for pkt in parse_rtcp(plain):
            if isinstance(pkt, RtcpPli) and self.on_keyframe_request:
                self.on_keyframe_request()
            elif isinstance(pkt, RtcpReceiverReport):
                for r in pkt.reports:
                    self.gcc.add_loss_report(r.fraction_lost / 256.0)
                if self.on_bitrate:
                    self.on_bitrate(self.gcc.bitrate)
            elif isinstance(pkt, RtcpTwcc):
                self.gcc.feed_twcc(pkt.received, self._twcc_sent)
                if self.on_bitrate:
                    self.on_bitrate(self.gcc.bitrate)
            elif isinstance(pkt, RtcpRemb):
                self.gcc.feed_remb(pkt.bitrate)
                if self.on_bitrate:
                    self.on_bitrate(self.gcc.bitrate)
            elif isinstance(pkt, RtcpNack):
                sender = self.senders.get(pkt.media_ssrc)
                if sender is not None:
                    sender.resend(pkt.lost)

    def _dtls_send(self, data: bytes) -> None:
        try:
            self.ice.send(data)
        except ConnectionError:
            pass

    def _dtls_app_data(self, data: bytes) -> None:
        if self.sctp is not None:
            self.sctp.receive(data)

    def _send_rtp(self, raw: bytes, record_twcc: bool = True) -> None:
        if self.srtp_tx is None:
            return
        if record_twcc:
            # record the just-assigned transport seq against the wire size
            self._record_twcc_send((self._twcc_seq - 1) & 0xFFFF, len(raw))
        try:
            self.ice.send(self.srtp_tx.protect_rtp(raw))
        except ConnectionError:
            pass

    def _send_sender_reports(self, now: float) -> None:
        del now  # monotonic; SR mapping needs the wall clock
        wall = time.time()
        for s in self.senders.values():
            sr = s.sender_report(wall)
            if sr is None:
                continue
            try:
                self.ice.send(self.srtp_tx.protect_rtcp(sr.serialize()))
            except (ConnectionError, ValueError):
                pass

    def _send_nacks(self) -> None:
        """Request retransmission of jitter-buffer gaps (video only; audio
        rides concealment)."""
        recv = self.receivers.get(self._video_pt)
        if recv is None or self.srtp_tx is None:
            return
        missing = recv.jitter.missing()
        if not missing or len(missing) > 64:   # burst loss → PLI instead
            if missing and recv.last_ssrc:
                self.request_keyframe(recv.last_ssrc)
                recv.jitter.skip_all()
            return
        # per-seq holdoff: re-NACK only after the retransmission had a
        # chance to arrive, or duplicates flood exactly when the path hurts
        now = time.monotonic()
        due = [s for s in missing
               if now - self._nacked.get(s, 0.0) > 0.25]
        if not due:
            return
        for s in due:
            self._nacked[s] = now
        if len(self._nacked) > 1024:
            self._nacked = {s: t for s, t in self._nacked.items()
                            if now - t < 2.0}
        nack = RtcpNack(sender_ssrc=1, media_ssrc=recv.last_ssrc, lost=due)
        try:
            self.ice.send(self.srtp_tx.protect_rtcp(nack.serialize()))
        except (ConnectionError, ValueError):
            pass

    def _send_twcc_feedback(self) -> None:
        """Ship transport-wide-cc feedback for packets received since the
        last report (the signal the remote GCC estimator runs on)."""
        recv, self._twcc_recv = self._twcc_recv, {}
        seqs = sorted(recv)
        base = seqs[0]
        span = (seqs[-1] - base) & 0xFFFF
        if span > 500:   # wrap/garbage guard: report the head run only
            seqs = [s for s in seqs if ((s - base) & 0xFFFF) <= 500]
            span = (seqs[-1] - base) & 0xFFFF
        received = [((base + i) & 0xFFFF, recv.get((base + i) & 0xFFFF))
                    for i in range(span + 1)]
        ref_us = min(t for _, t in received if t is not None)
        fb = RtcpTwcc(
            sender_ssrc=1, media_ssrc=self._twcc_recv_ssrc,
            base_seq=base, fb_count=self._twcc_fb_count & 0xFF,
            ref_time=(ref_us // 64000) & 0xFFFFFF,
            received=received)
        self._twcc_fb_count += 1
        try:
            self.ice.send(self.srtp_tx.protect_rtcp(fb.serialize()))
        except (ConnectionError, ValueError):
            pass

    def request_keyframe(self, media_ssrc: int) -> None:
        if self.srtp_tx is None:
            return
        pli = RtcpPli(sender_ssrc=1, media_ssrc=media_ssrc)
        try:
            self.ice.send(self.srtp_tx.protect_rtcp(pli.serialize()))
        except ConnectionError:
            pass

    def _sctp_channel(self, ch: DataChannel) -> None:
        if self.on_channel is not None:
            self.on_channel(ch)

    async def close(self) -> None:
        self._closed = True
        if self._run_task is not None:
            self._run_task.cancel()
        if self.ice is not None:
            await self.ice.close()


class DataChannelHandle:
    """Pre-negotiation handle; binds to the SCTP association once up."""

    def __init__(self, label: str, protocol: str, ordered: bool,
                 max_retransmits: Optional[int]):
        self.label = label
        self.protocol = protocol
        self.ordered = ordered
        self.max_retransmits = max_retransmits
        self.channel: Optional[DataChannel] = None
        self.on_message: Optional[Callable[[bytes], None]] = None
        self.on_open: Optional[Callable[[], None]] = None
        self._sctp: Optional[SctpAssociation] = None

    @property
    def bound(self) -> bool:
        return self.channel is not None

    @property
    def open(self) -> bool:
        return self.channel is not None and self.channel.open

    def bind(self, sctp: SctpAssociation) -> None:
        self._sctp = sctp
        self.channel = sctp.create_channel(
            self.label, self.protocol, self.ordered, self.max_retransmits)
        self.channel.on_message = lambda d: self.on_message and self.on_message(d)
        self.channel.on_open = lambda: self.on_open and self.on_open()

    def send(self, data) -> None:
        if not self.open:
            raise ConnectionError("channel not open")
        self._sctp.send(self.channel, data)
