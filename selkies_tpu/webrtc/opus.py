"""Opus RTP payloader/depayloader (RFC 7587).

One Opus frame per RTP packet; timestamps advance at 48 kHz regardless of
the coded bandwidth. Pairs with the audio subsystem's 20 ms Opus frames
(selkies_tpu.audio.codec; reference pcmflux default, selkies.py:1008-1011).
"""

from __future__ import annotations

from typing import List

from .rtp import RtpPacket

OPUS_CLOCK = 48000


class OpusPayloader:
    def packetize(
        self, frame: bytes, ssrc: int, payload_type: int,
        sequence_number: int, timestamp: int,
    ) -> List[RtpPacket]:
        return [RtpPacket(
            payload_type=payload_type,
            sequence_number=sequence_number & 0xFFFF,
            timestamp=timestamp & 0xFFFFFFFF,
            ssrc=ssrc,
            payload=frame,
            marker=0,
        )]


class OpusDepayloader:
    def feed(self, packet: RtpPacket) -> bytes:
        return packet.payload
