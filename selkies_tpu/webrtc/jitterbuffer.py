"""Receive-side jitter buffer: reorder, loss detection, frame assembly.

Role parity with the vendored ``src/selkies/webrtc/jitterbuffer.py``
(SURVEY.md §2.4): RTP packets arrive out of order; the buffer re-sequences
them, surfaces contiguous runs to the depayloader, and reports gaps for
NACK generation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .rtp import RtpPacket, unwrap_seq


@dataclass
class JitterFrame:
    payloads: List[RtpPacket]
    timestamp: int


class JitterBuffer:
    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._packets: Dict[int, RtpPacket] = {}    # unwrapped seq -> packet
        self._last_unwrapped = -1                    # highest seen
        self._next = -1                              # next seq to release

    @property
    def pending(self) -> int:
        return len(self._packets)

    def missing(self) -> List[int]:
        """Sequence numbers (u16) between the release head and the highest
        received packet that have not arrived — NACK candidates."""
        if self._next < 0:
            return []
        return [s & 0xFFFF for s in range(self._next, self._last_unwrapped)
                if s not in self._packets]

    def add(self, packet: RtpPacket) -> List[RtpPacket]:
        """Insert one packet; returns the in-order run now releasable."""
        seq = unwrap_seq(self._last_unwrapped, packet.sequence_number)
        if seq > self._last_unwrapped:
            self._last_unwrapped = seq
        if self._next < 0:
            self._next = seq
        if seq < self._next:                 # too late — already released past
            return []
        self._packets[seq] = packet
        if len(self._packets) > self.capacity:
            # overflow: jump the release head to the oldest held packet
            self._next = max(self._next, min(self._packets))
        out: List[RtpPacket] = []
        while self._next in self._packets:
            out.append(self._packets.pop(self._next))
            self._next += 1
        return out

    def skip_all(self) -> None:
        """Abandon every gap up to the highest packet seen (burst-loss
        resync: the next keyframe restarts decoding)."""
        self._packets.clear()
        if self._last_unwrapped >= 0:
            self._next = self._last_unwrapped + 1

    def skip_to(self, seq_u16: int) -> None:
        """Abandon everything before seq (keyframe resync after loss)."""
        seq = unwrap_seq(self._last_unwrapped, seq_u16)
        for s in [s for s in self._packets if s < seq]:
            del self._packets[s]
        if self._next < seq:
            self._next = seq
