"""Pallas TPU kernel: fused 8×8 DCT + quantize for one plane.

The JPEG body's hot loop (ops/color → ops/dct → ops/quant) is
matmul-shaped work; this kernel expresses it as one Pallas program per
8×128 tile so the intermediate coefficient tensor never round-trips HBM,
and every op is a Mosaic-native 2-D matmul (no in-kernel reshapes —
Mosaic rejects the layout-hostile [8, nb, 8] contraction form):

  tile [8, 128] ──VMEM── C₈ · X            vertical DCT   (MXU 8×8 @ 8×128)
                         · BD₁₂₈            horizontal DCT (MXU 128×128)
                         × recip, round     quantize       (VPU)
                ──VMEM── out [8, 128] f32 quantized raster blocks

BD₁₂₈ is block-diag(C₈ᵀ × 16): right-multiplying by it applies the
8-point DCT independently to each of the 16 lane-groups — the trick that
keeps the horizontal pass one well-shaped matmul. Zigzag stays outside
(XLA fuses the static take into the surrounding cast).

Status: tested demonstration kernel, NOT on the default path. Measured on
v5e at 1080p: 9.2 ms vs 1.6 ms for the XLA formulation — the (136 × 15)
grid of tiny tiles pays per-invocation overhead that XLA's global fusion
doesn't, so the production encoder keeps the XLA path (ops/dct.py). The
kernel is pinned against that path in tests/test_pallas_dct.py
(interpret mode on CPU, compiled on TPU) and stands as the working
template for ops where XLA's fusion falls short.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .dct import _dct8_np

TILE_W = 128  # one MXU-width of lanes = 16 DCT blocks


@functools.lru_cache(maxsize=None)
def _block_diag_c8t() -> np.ndarray:
    """[128, 128] block-diagonal of C8^T — per-lane-group horizontal DCT."""
    c8t = _dct8_np().T
    bd = np.zeros((TILE_W, TILE_W), np.float32)
    for b in range(TILE_W // 8):
        bd[b * 8:(b + 1) * 8, b * 8:(b + 1) * 8] = c8t
    return bd


def _tile_kernel(x_ref, recip_ref, c8_ref, bd_ref, out_ref):
    # HIGHEST precision: the MXU's default f32 path rounds operands to
    # bf16, which shifts rounded coefficients near quantization boundaries
    # (same hazard ops/dct.py pins against).
    x = x_ref[:] - 128.0
    v = jnp.dot(c8_ref[:], x, preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
    y = jnp.dot(v, bd_ref[:], preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
    out_ref[:] = jnp.round(y * recip_ref[0])


@functools.partial(jax.jit, static_argnames=("interpret",))
def dct8_quant_raster(plane, row_recip, interpret: bool = False):
    """plane [H, W] f32 (W a multiple of 128), row_recip [H/8, 8, 8] f32
    reciprocal quant tables → [H, W] f32 rounded quantized coefficients in
    raster block layout (apply blockify+zigzag outside)."""
    from jax.experimental import pallas as pl

    h, w = plane.shape
    by = h // 8
    # recip tiled across the 16 lane-groups of a tile, once per band
    recip_tiled = jnp.tile(row_recip.astype(jnp.float32),
                           (1, 1, TILE_W // 8))          # [by, 8, 128]
    return pl.pallas_call(
        _tile_kernel,
        out_shape=jax.ShapeDtypeStruct((h, w), jnp.float32),
        grid=(by, w // TILE_W),
        in_specs=[
            pl.BlockSpec((8, TILE_W), lambda i, j: (i, j)),
            pl.BlockSpec((1, 8, TILE_W), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((8, 8), lambda i, j: (0, 0)),
            pl.BlockSpec((TILE_W, TILE_W), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((8, TILE_W), lambda i, j: (i, j)),
        interpret=interpret,
    )(plane.astype(jnp.float32), recip_tiled,
      jnp.asarray(_dct8_np(), jnp.float32),
      jnp.asarray(_block_diag_c8t()))


def dct8_quant_zigzag(plane, row_recip, interpret: bool = False):
    """Convenience wrapper matching the XLA path's output: [H/8, W/8, 64]
    rounded zigzag coefficients (zigzag applied outside the kernel)."""
    from .quant import ZIGZAG

    h, w = plane.shape
    q = dct8_quant_raster(plane, row_recip, interpret=interpret)
    blocks = q.reshape(h // 8, 8, w // 8, 8).transpose(0, 2, 1, 3)
    return jnp.take(blocks.reshape(h // 8, w // 8, 64),
                    jnp.asarray(ZIGZAG), axis=-1)
