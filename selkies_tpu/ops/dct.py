"""Blocked 8x8 DCT-II as matrix multiplies.

TPU-first formulation: instead of a butterfly/FFT-style DCT (serial,
scalar-heavy — good on CPUs, wrong shape for TPU), the 8x8 2-D DCT of every
block is expressed as two dense matmuls ``C @ X @ C^T`` batched over all
blocks of the frame, which XLA maps onto the MXU/VPU and fuses with the
neighboring color-convert and quantize stages. The encode pipeline is
HBM-bandwidth-bound, so the extra FLOPs of the matmul formulation are free.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _dct8_np() -> np.ndarray:
    n = 8
    c = np.zeros((n, n), dtype=np.float64)
    for k in range(n):
        for i in range(n):
            c[k, i] = math.cos(math.pi * (2 * i + 1) * k / (2 * n))
    c *= math.sqrt(2.0 / n)
    c[0, :] *= 1.0 / math.sqrt(2.0)
    return c.astype(np.float32)


def dct8_matrix():
    """The orthonormal 8-point DCT-II matrix C (C @ C.T == I)."""
    return jnp.asarray(_dct8_np())


def blockify(plane):
    """[..., H, W] → [..., H/8, W/8, 8, 8] blocks."""
    *lead, h, w = plane.shape
    x = plane.reshape(*lead, h // 8, 8, w // 8, 8)
    return jnp.swapaxes(x, -3, -2)


def unblockify(blocks):
    """Inverse of :func:`blockify`."""
    *lead, by, bx, _, _ = blocks.shape
    x = jnp.swapaxes(blocks, -3, -2)
    return x.reshape(*lead, by * 8, bx * 8)


def block_dct2(blocks):
    """2-D DCT-II of [..., 8, 8] blocks (orthonormal).

    Precision is pinned to HIGHEST: the TPU default would run the MXU in
    bfloat16, whose ~8-bit mantissa is visible against the quantizer at
    paint-over qualities.
    """
    c = dct8_matrix()
    return jnp.einsum(
        "ij,...jk,lk->...il", c, blocks, c, precision=jax.lax.Precision.HIGHEST
    )


def block_idct2(coeffs):
    """Inverse 2-D DCT (orthonormal), for tests and the decoder oracle."""
    c = dct8_matrix()
    return jnp.einsum(
        "ji,...jk,kl->...il", c, coeffs, c, precision=jax.lax.Precision.HIGHEST
    )
