"""Quantization tables, quality scaling, and zigzag ordering.

Base tables and the quality→scale mapping follow the public JPEG spec
(ITU-T T.81 Annex K) and the IJG convention, which is what the reference's
pixelflux JPEG path (libjpeg-turbo) and every browser decoder expect.
Quantization itself runs on device as an elementwise multiply by the
reciprocal table (fused by XLA into the DCT epilogue).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax.numpy as jnp
import numpy as np

# ITU-T T.81 Annex K.1 / K.2 base tables (raster order).
_BASE_LUMA = np.array(
    [
        16, 11, 10, 16, 24, 40, 51, 61,
        12, 12, 14, 19, 26, 58, 60, 55,
        14, 13, 16, 24, 40, 57, 69, 56,
        14, 17, 22, 29, 51, 87, 80, 62,
        18, 22, 37, 56, 68, 109, 103, 77,
        24, 35, 55, 64, 81, 104, 113, 92,
        49, 64, 78, 87, 103, 121, 120, 101,
        72, 92, 95, 98, 112, 100, 103, 99,
    ],
    dtype=np.int32,
).reshape(8, 8)

_BASE_CHROMA = np.array(
    [
        17, 18, 24, 47, 99, 99, 99, 99,
        18, 21, 26, 66, 99, 99, 99, 99,
        24, 26, 56, 99, 99, 99, 99, 99,
        47, 66, 99, 99, 99, 99, 99, 99,
        99, 99, 99, 99, 99, 99, 99, 99,
        99, 99, 99, 99, 99, 99, 99, 99,
        99, 99, 99, 99, 99, 99, 99, 99,
        99, 99, 99, 99, 99, 99, 99, 99,
    ],
    dtype=np.int32,
).reshape(8, 8)

# Zigzag scan: ZIGZAG[k] = raster index of the k-th zigzag coefficient.
ZIGZAG = np.array(
    [
        0, 1, 8, 16, 9, 2, 3, 10,
        17, 24, 32, 25, 18, 11, 4, 5,
        12, 19, 26, 33, 40, 48, 41, 34,
        27, 20, 13, 6, 7, 14, 21, 28,
        35, 42, 49, 56, 57, 50, 43, 36,
        29, 22, 15, 23, 30, 37, 44, 51,
        58, 59, 52, 45, 38, 31, 39, 46,
        53, 60, 61, 54, 47, 55, 62, 63,
    ],
    dtype=np.int32,
)


def base_quant_tables() -> Tuple[np.ndarray, np.ndarray]:
    return _BASE_LUMA.copy(), _BASE_CHROMA.copy()


@functools.lru_cache(maxsize=128)
def quality_scaled_tables(quality: int) -> Tuple[np.ndarray, np.ndarray]:
    """IJG quality scaling: Q in [1, 100] → (luma, chroma) uint8 tables."""
    q = max(1, min(100, int(quality)))
    scale = 5000 // q if q < 50 else 200 - 2 * q

    def scaled(base: np.ndarray) -> np.ndarray:
        t = (base * scale + 50) // 100
        return np.clip(t, 1, 255).astype(np.uint8)

    return scaled(_BASE_LUMA), scaled(_BASE_CHROMA)


def quantize_blocks(coeffs, table):
    """Quantize DCT coefficients: round(coef / table) → int16.

    ``coeffs``: [..., 8, 8] float; ``table``: broadcastable [..., 8, 8].
    Division is a multiply by the precomputed reciprocal (device-friendly).
    """
    recip = 1.0 / table.astype(jnp.float32)
    return jnp.round(coeffs * recip).astype(jnp.int16)


def zigzag_blocks(blocks):
    """[..., 8, 8] → [..., 64] in zigzag order (device gather)."""
    flat = blocks.reshape(*blocks.shape[:-2], 64)
    return jnp.take(flat, jnp.asarray(ZIGZAG), axis=-1)
