"""Pallas motion-search kernel: exhaustive ME + exact MC in VMEM.

The XLA formulations of the H.264 motion search (ops/motion.py) are
HBM-traffic-bound: every candidate offset re-reads the current and
reference planes from HBM, so even the chunk-batched form measured
~30 ms/frame at 1080p (625 offsets × ~100 MB/chunk of traffic). One
stripe's entire search window — current luma (64×1920), padded reference
(88×1944), chroma — is ~0.6 MB, a trivial VMEM fit, so this kernel runs
the complete search per stripe with the planes resident on-chip:

  * grid = (n_stripes,); each program owns one stripe;
  * pass 1: static unroll over dx, ``fori_loop`` over dy; per offset the
    shifted reference is a VMEM slice, SAD per 16×16 block is a reshape
    row-sum + lane-group sum, and only a (nby, nbx) best/rank pair is
    carried;
  * tie-breaking is *rank-based*: every offset carries its index in the
    |dy|+|dx|-sorted order used by ops/motion.py, and ties keep the
    lower rank — bit-identical winners to the exhaustive XLA search
    regardless of evaluation order;
  * pass 2 re-walks the offsets and, predicated on "this offset won at
    least one block" (``@pl.when``), builds the winning luma prediction
    and the §8.4.2.2.2-exact chroma bilinear by masked select — a frame
    with few distinct motions pays for few updates.

The public entry :func:`me_mc_stripes` takes stripe-batched planes
(S, H, W) and returns (mv, pred_y, pred_cb, pred_cr) with the same
semantics as ``vmap(full_search_mc)``. Falls back to interpreter mode
off-TPU so the CPU test mesh exercises the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .motion import _offsets, pad_replicate

#: jax ≥ 0.5 renamed TPUCompilerParams → CompilerParams; accept either so
#: the interpret-mode CPU path keeps working on older runtimes
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

MB = 16


def _rank_table(search: int) -> np.ndarray:
    """rank[dy+search, dx+search] = index in the sorted offset order."""
    offs = _offsets(search)
    n = 2 * search + 1
    rank = np.zeros((n, n), np.int32)
    for r, (dy, dx) in enumerate(offs):
        rank[dy + search, dx + search] = r
    return rank


def _me_mc_kernel(ranks_ref, cur_ref, ref_ref, cb_ref, cr_ref,
                  rank_out, py_out, pcb_out, pcr_out,
                  best_sad, best_rank, *, search: int, h: int,
                  w: int, hc: int, wc: int):
    nby, nbx = h // MB, w // MB
    n_dy = 2 * search + 1
    cur = cur_ref[0].astype(jnp.int32)                    # (h, w)

    # lane-group indicator (w, nbx): Mosaic cannot reshape-split the lane
    # dim, so the 16-lane column sum rides the MXU instead
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (w, nbx), 0) // MB
    grp_ids = jax.lax.broadcasted_iota(jnp.int32, (w, nbx), 1)
    col_ind = (col_ids == grp_ids).astype(jnp.float32)

    # ---- pass 1: SAD-only sweep, carry (best_sad, best_rank) ----------
    big = jnp.int32(1 << 30)
    best_sad[:nby, :nbx] = jnp.full((nby, nbx), big, jnp.int32)
    best_rank[:nby, :nbx] = jnp.full((nby, nbx), big, jnp.int32)

    # int32 once: Mosaic's dynamic rotate only handles 32-bit lanes
    win_all = ref_ref[0].astype(jnp.int32)                # (h+2s, w+2s)

    def body(dyi, _):
        # ONE dynamic row shift per dy, realized as a circular roll
        # (Mosaic cannot prove unaligned dynamic sublane slices; the
        # compiled rotate takes the dynamic amount as unsigned, hence
        # the positive shift ≡ -dyi mod rows). h + 2·search window rows
        # mean no wrapped garbage enters the [0:h) slice. The dx axis
        # is handled by static lane slices of the rolled window, and
        # all n_dy row-sum grids ride ONE MXU matmul (M = n_dy·nby)
        # instead of n_dy M=nby slivers.
        rolled = pltpu.roll(win_all, win_all.shape[0] - dyi, 0)[:h]
        rows_all = jnp.concatenate(
            [jnp.abs(cur - rolled[:, dxi:dxi + w])
             .reshape(nby, MB, w).sum(axis=1)
             for dxi in range(n_dy)], axis=0)            # (n_dy·nby, w)
        # HIGHEST: row sums reach 4080, past bf16's exact-integer range;
        # the MXU's default bf16 operand rounding would drift near-tie
        # winners between backends (same hazard as ops/motion.py:88 and
        # the round-2 device-entropy corruption)
        sads_all = jnp.dot(rows_all.astype(jnp.float32), col_ind,
                           preferred_element_type=jnp.float32,
                           precision=jax.lax.Precision.HIGHEST)
        for dxi in range(n_dy):
            sad = sads_all[dxi * nby:(dxi + 1) * nby].astype(jnp.int32)
            rank = ranks_ref[dyi, dxi]
            bs = best_sad[:nby, :nbx]
            br = best_rank[:nby, :nbx]
            take = (sad < bs) | ((sad == bs) & (rank < br))
            best_sad[:nby, :nbx] = jnp.where(take, sad, bs)
            best_rank[:nby, :nbx] = jnp.where(take, rank, br)
        return 0

    jax.lax.fori_loop(0, n_dy, body, 0)

    win_rank = best_rank[:nby, :nbx]
    rank_out[0] = win_rank

    # ---- pass 2: exact predictions for winning offsets only -----------
    rc = search // 2 + 1
    cbsz = MB // 2

    def _expand_inds(rows_n, cols_n, cell):
        # block mask (nby, nbx) → pixel mask (rows_n, cols_n) via two
        # indicator matmuls (jnp.repeat lowers to reshapes Mosaic
        # rejects; the MXU does this for free)
        r_blk = jax.lax.broadcasted_iota(jnp.int32, (rows_n, nby), 0) // cell
        r_tgt = jax.lax.broadcasted_iota(jnp.int32, (rows_n, nby), 1)
        c_blk = jax.lax.broadcasted_iota(jnp.int32, (nbx, cols_n), 1) // cell
        c_tgt = jax.lax.broadcasted_iota(jnp.int32, (nbx, cols_n), 0)
        return ((r_blk == r_tgt).astype(jnp.float32),
                (c_blk == c_tgt).astype(jnp.float32))

    rexp_y, cexp_y = _expand_inds(h, w, MB)
    rexp_c, cexp_c = _expand_inds(hc, wc, cbsz)

    def expand_mask(take, rexp, cexp):
        t = take.astype(jnp.float32)
        px = jnp.dot(jnp.dot(rexp, t, preferred_element_type=jnp.float32),
                     cexp, preferred_element_type=jnp.float32)
        return px != 0

    cb_all = cb_ref[0].astype(jnp.int32)
    cr_all = cr_ref[0].astype(jnp.int32)

    def body2(dyi, _):
        # Gate whole dy rows on "some block's winner lives in this row":
        # the rolls + 25 per-dx mask/update bodies below were measured at
        # ~5.3 of the kernel's 8.3 ms/frame when run unconditionally,
        # while typical desktop motion has 1-2 winning dy rows, not 25.
        # The membership test is 25 vector compares of the (nby, nbx)
        # winner grid — noise next to one skipped roll. (A pass-1 SMEM
        # winner-flag scratch was tried first; scratch carried between
        # two fori_loops faults Mosaic inside lax.scan programs.)
        row_hit = jnp.zeros((nby, nbx), jnp.bool_)
        for dxi in range(n_dy):
            row_hit = row_hit | (win_rank == ranks_ref[dyi, dxi])

        @pl.when(jnp.any(row_hit))
        def _(dyi=dyi):
            rolled = pltpu.roll(win_all, win_all.shape[0] - dyi, 0)[:h]
            dy = dyi - search
            iy = dy >> 1
            yf = (dy & 1) * 4
            y0 = rc + 1 + iy
            cb_roll = pltpu.roll(cb_all, cb_all.shape[0] - y0, 0)
            cr_roll = pltpu.roll(cr_all, cr_all.shape[0] - y0, 0)
            for dxi in range(n_dy):
                dx = dxi - search
                rank = ranks_ref[dyi, dxi]
                take = win_rank == rank                  # (nby, nbx)
                # chroma lane geometry, xf folded in statically
                # (§8.4.2.2.2: integer luma mv → {0,4}-eighth weights)
                ix = dx >> 1
                xf = (dx & 1) * 4
                x0 = rc + 1 + ix

                @pl.when(jnp.any(take))
                def _(take=take, dxi=dxi, x0=x0, xf=xf,
                      rolled=rolled, cb_roll=cb_roll, cr_roll=cr_roll,
                      yf=yf):
                    tpx = expand_mask(take, rexp_y, cexp_y)
                    py_out[0] = jnp.where(
                        tpx, rolled[:, dxi:dxi + w].astype(jnp.uint8),
                        py_out[0])

                    def ctap(roll_c, off):
                        a = roll_c[off:off + hc, x0:x0 + wc]
                        if xf == 0:
                            return a * 8
                        return (a * (8 - xf)
                                + roll_c[off:off + hc,
                                         x0 + 1:x0 + 1 + wc] * xf)

                    ncb = ((8 - yf) * ctap(cb_roll, 0)
                           + yf * ctap(cb_roll, 1) + 32) >> 6
                    ncr = ((8 - yf) * ctap(cr_roll, 0)
                           + yf * ctap(cr_roll, 1) + 32) >> 6
                    tcx = expand_mask(take, rexp_c, cexp_c)
                    pcb_out[0] = jnp.where(tcx, ncb.astype(jnp.uint8),
                                           pcb_out[0])
                    pcr_out[0] = jnp.where(tcx, ncr.astype(jnp.uint8),
                                           pcr_out[0])

        return 0

    jax.lax.fori_loop(0, n_dy, body2, 0)


@functools.partial(jax.jit, static_argnames=("search", "interpret"))
def me_mc_stripes(cur, ref, ref_cb, ref_cr, *, search: int = 12,
                  interpret: bool | None = None):
    """Stripe-batched fused ME+MC via the VMEM-resident Pallas kernel.

    cur/ref: (S, h, w) uint8 luma; ref_cb/ref_cr: (S, h/2, w/2) uint8.
    Returns (mv (S, nby, nbx, 2) int32, pred_y, pred_cb, pred_cr uint8)
    with selection semantics identical to ``vmap(full_search_mc)``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S, h, w = cur.shape
    hc, wc = ref_cb.shape[-2:]
    nby, nbx = h // MB, w // MB
    n_dy = 2 * search + 1
    rc = search // 2 + 1

    ref_pad = pad_replicate(ref, search)                  # (S, h+2s, w+2s)
    cbp = pad_replicate(ref_cb, rc + 1)
    crp = pad_replicate(ref_cr, rc + 1)
    ranks = jnp.asarray(_rank_table(search))

    kern = functools.partial(_me_mc_kernel, search=search, h=h, w=w,
                             hc=hc, wc=wc)
    rank_w, py, pcb, pcr = pl.pallas_call(
        kern,
        grid=(S,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),        # ranks
            pl.BlockSpec((1, h, w), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h + 2 * search, w + 2 * search),
                         lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hc + 2 * (rc + 1), wc + 2 * (rc + 1)),
                         lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hc + 2 * (rc + 1), wc + 2 * (rc + 1)),
                         lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, nby, nbx), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h, w), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hc, wc), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hc, wc), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S, nby, nbx), jnp.int32),
            jax.ShapeDtypeStruct((S, h, w), jnp.uint8),
            jax.ShapeDtypeStruct((S, hc, wc), jnp.uint8),
            jax.ShapeDtypeStruct((S, hc, wc), jnp.uint8),
        ],
        scratch_shapes=[
            pltpu.VMEM((max(8, nby), max(128, nbx)), jnp.int32),
            pltpu.VMEM((max(8, nby), max(128, nbx)), jnp.int32),
        ],
        # 4K stripes (w=3840) need ~18 MB of scoped VMEM (the rolled
        # int32 window + the indicator constants); the default 16 MB
        # scope is conservative, not the physical limit
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(ranks, cur, ref_pad, cbp, crp)
    mv = jnp.asarray(_offsets(search))[rank_w]            # (S, nby, nbx, 2)
    return mv, py, pcb, pcr
