"""Block motion estimation + compensation for the tpuenc H.264 profile.

TPU-first design: instead of the reference's x264 per-thread diamond search
(pixelflux, closed C++), motion search is expressed as a dense
shifted-SAD tensor contraction — every candidate offset for every
macroblock is evaluated in one batched elementwise+reduce pipeline, which
is the shape XLA tiles well.  Offsets are processed in chunks under
``lax.scan`` to bound peak memory.

Edge semantics: the reference frame is replicate-padded by the search
radius.  Slicing the padded plane at offset (dy, dx) reproduces H.264's
decoder-side coordinate clamping (§8.4.2.2.1 edge extension) exactly for
|mv| ≤ radius, so encoder reconstruction stays bit-exact with a conformant
decoder.  Stripes are independent sequences, so padding also isolates
stripe boundaries.

Chroma MC: integer luma MVs become half-pel chroma positions in 4:2:0;
the §8.4.2.2.2 eighth-pel bilinear reduces to weights {0,4} which this
module implements exactly in int32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def pad_replicate(plane: jnp.ndarray, r: int) -> jnp.ndarray:
    """Replicate-pad the last two axes by r."""
    cfg = [(0, 0)] * (plane.ndim - 2) + [(r, r), (r, r)]
    return jnp.pad(plane, cfg, mode="edge")


def _offsets(search: int) -> np.ndarray:
    """All (dy, dx) in [-search, search]², zero offset first.

    Ordering matters for ties: argmin picks the first minimum, and we want
    (0,0) to win ties (cheaper MVDs, skip eligibility).  Remaining offsets
    are sorted by |dy|+|dx| so near-zero motion wins over far offsets with
    equal SAD.
    """
    offs = [(dy, dx)
            for dy in range(-search, search + 1)
            for dx in range(-search, search + 1)]
    offs.sort(key=lambda o: (abs(o[0]) + abs(o[1]), abs(o[0]), abs(o[1])))
    return np.asarray(offs, np.int32)


def _sad_per_mb(diff: jnp.ndarray, mb: int) -> jnp.ndarray:
    """(..., H, W) abs-diff → (..., H//mb, W//mb) block sums."""
    h, w = diff.shape[-2:]
    lead = diff.shape[:-2]
    v = diff.reshape(*lead, h // mb, mb, w // mb, mb)
    return v.sum(axis=(-3, -1))


@functools.lru_cache(maxsize=8)
def _block_indicators(h: int, w: int, mb: int):
    """0/1 indicator matrices so block sums run on the MXU:
    sums = A @ |d| @ B with A [h/mb, h], B [w, w/mb]."""
    a = np.zeros((h // mb, h), np.float32)
    for i in range(h // mb):
        a[i, i * mb:(i + 1) * mb] = 1.0
    b = np.zeros((w, w // mb), np.float32)
    for j in range(w // mb):
        b[j * mb:(j + 1) * mb, j] = 1.0
    return a, b


def _sad_per_mb_mxu(diff_f32: jnp.ndarray, mb: int) -> jnp.ndarray:
    """(..., H, W) f32 abs-diff → (..., H//mb, W//mb) block sums via two
    indicator matmuls.

    The reshape/strided-sum form costs ~0.12 ms per ME offset at 1080p on
    the TPU (cross-lane reductions); routed through the MXU the whole
    625-offset search drops ~10×. Precision.HIGHEST keeps it exact: the
    intermediate partial sums reach 4080, past bf16's exact-integer
    range, and an inexact SAD would let mv selection drift between
    backends (every value here is < 2^24, so HIGHEST's bf16x3 passes
    reconstruct the f32 arithmetic exactly).
    """
    h, w = diff_f32.shape[-2:]
    a, b = _block_indicators(h, w, mb)
    return jnp.einsum("rh,...hw,wc->...rc", jnp.asarray(a), diff_f32,
                      jnp.asarray(b), precision=jax.lax.Precision.HIGHEST)


def _sad_per_mb_hybrid(diff_i16: jnp.ndarray, mb: int) -> jnp.ndarray:
    """(..., H, W) int16 abs-diff → (..., H//mb, W//mb) f32 block sums.

    Row sums ride the VPU (a sublane-axis reduction, cheap) and only the
    lane-axis column sum goes through the MXU — and with rows pre-summed
    the matmul's M dimension is batch×(H/mb) instead of H/mb, so the
    systolic array actually fills. The two-einsum form fed the MXU M=4
    matmuls (one per 64-row stripe), which measured 0.5 TFLOP/s and made
    exhaustive ME 80% of the H.264 device step. int16 row sums are exact
    (≤ 16·255 = 4080); the f32 HIGHEST matmul is exact below 2^24.
    """
    *lead, h, w = diff_i16.shape
    rows = diff_i16.reshape(*lead, h // mb, mb, w).sum(-2)
    _, b = _block_indicators(h, w, mb)
    return jnp.matmul(rows.astype(jnp.float32), jnp.asarray(b),
                      precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit, static_argnames=("mb", "search", "chunk"))
def full_search_mv(cur: jnp.ndarray, ref: jnp.ndarray, *,
                   mb: int = 16, search: int = 12, chunk: int = 25):
    """Integer-pel exhaustive search.

    cur, ref: (..., H, W) uint8 luma (H, W multiples of mb).
    Returns (mv, sad0, best_sad):
      mv:       (..., H//mb, W//mb, 2) int32 — (dy, dx), SAD-optimal
      sad0:     (..., H//mb, W//mb) int32 — SAD at zero offset
      best_sad: (..., H//mb, W//mb) int32
    """
    offs = _offsets(search)
    n = offs.shape[0]
    pad_n = (-n) % chunk
    offs_padded = np.concatenate([offs, np.tile(offs[:1], (pad_n, 1))])
    offs_chunks = jnp.asarray(
        offs_padded.reshape(-1, chunk, 2))          # (n_chunks, chunk, 2)
    idx_chunks = jnp.asarray(
        np.concatenate([np.arange(n), np.zeros(pad_n)])
        .astype(np.int32).reshape(-1, chunk))

    h, w = cur.shape[-2:]
    cur_i = cur.astype(jnp.int16)
    ref_pad = pad_replicate(ref.astype(jnp.int16), search)

    def slice_at(off):
        start = (search + off[0], search + off[1])
        starts = (0,) * (ref_pad.ndim - 2) + start
        sizes = ref_pad.shape[:-2] + (h, w)
        return jax.lax.dynamic_slice(ref_pad, starts, sizes)

    def body(carry, chunk_in):
        best_sad, best_idx = carry
        offs_c, idx_c = chunk_in
        shifted = jax.vmap(slice_at)(offs_c)         # (chunk, ..., H, W)
        diff = jnp.abs(cur_i[None] - shifted).astype(jnp.int32)
        sads = _sad_per_mb(diff, mb)                 # (chunk, ..., nby, nbx)
        c_best = sads.min(axis=0)
        c_arg = sads.argmin(axis=0).astype(jnp.int32)
        c_idx = idx_c[c_arg]
        take = c_best < best_sad                     # strict: earlier wins
        return ((jnp.where(take, c_best, best_sad),
                 jnp.where(take, c_idx, best_idx)), None)

    nby, nbx = h // mb, w // mb
    init_sad = jnp.full(cur.shape[:-2] + (nby, nbx), 2**30, jnp.int32)
    init_idx = jnp.zeros(cur.shape[:-2] + (nby, nbx), jnp.int32)
    (best_sad, best_idx), _ = jax.lax.scan(
        body, (init_sad, init_idx), (offs_chunks, idx_chunks))

    mv = jnp.asarray(offs)[best_idx]                 # (..., nby, nbx, 2)
    # SAD at zero offset (offset 0 is first in sorted order)
    diff0 = jnp.abs(cur_i - ref_pad[..., search:search + h,
                                    search:search + w]).astype(jnp.int32)
    sad0 = _sad_per_mb(diff0, mb)
    return mv, sad0, best_sad


@functools.partial(jax.jit, static_argnames=("mb", "search", "chunk"))
def full_search_mc(cur, ref, ref_cb, ref_cr, *, mb: int = 16,
                   search: int = 12, chunk: int = 25):
    """Fused exhaustive ME + luma/chroma MC, chunk-batched.

    The separate ME → mc_luma/mc_chroma pipeline pays per-macroblock
    gathers (vmapped dynamic_slice with per-block starts): ~3M gathered
    elements/frame through the TPU scalar core dominated the whole H.264
    encode. The round-2 form fixed that with a 625-iteration lax.scan —
    but scan costs ~0.1-0.2 ms/iteration of fixed overhead (carry DMA +
    program dispatch), which at 625 offsets was ~68 ms/frame, 80% of the
    device step, at 0.4 TFLOP/s MXU utilization. This version processes
    offsets in ``chunk``-sized batches inside a statically unrolled
    Python loop: every candidate slice has a *static* start (a pure
    copy, no scalar-core gather), each batch's SADs ride one MXU einsum,
    and only one select per batch touches the prediction carries, so the
    select chain stays short (n/chunk links, not n — full unrolling was
    measured WORSE: 625-deep select chains explode live ranges).

    Tie-breaking matches full_search_mv exactly: offsets are processed
    in |dy|+|dx|-sorted order, within a batch argmin keeps the first
    (earliest) minimum, and a strict ``<`` across batches keeps the
    earliest global minimum — so (0,0) and near-zero motion win ties.

    Returns (mv, pred_y u8, pred_cb u8, pred_cr u8).
    """
    if chunk > 256:
        # c_arg below is uint8; a larger chunk would silently wrap the
        # within-chunk argmin and select wrong predictions
        raise ValueError(f"chunk must be <= 256, got {chunk}")
    h, w = cur.shape[-2:]
    hc, wc = ref_cb.shape[-2:]
    cb2 = mb // 2
    nby, nbx = h // mb, w // mb
    offs_np = _offsets(search)
    n = offs_np.shape[0]
    cur_i = cur.astype(jnp.int16)
    ref_pad = pad_replicate(ref, search)             # uint8: slices stay u8
    rc = search // 2 + 1
    cbp = pad_replicate(ref_cb.astype(jnp.int16), rc + 1)
    crp = pad_replicate(ref_cr.astype(jnp.int16), rc + 1)

    def luma_slice(dy: int, dx: int):
        y0, x0 = search + dy, search + dx
        return ref_pad[..., y0:y0 + h, x0:x0 + w]

    def chroma_pred(cp, dy: int, dx: int):
        # §8.4.2.2.2: integer luma MV → {0,4}-eighth chroma bilinear;
        # static weights mean even offsets fold to a plain slice
        iy, ix = dy >> 1, dx >> 1
        yf, xf = (dy & 1) * 4, (dx & 1) * 4
        y0, x0 = rc + 1 + iy, rc + 1 + ix
        if yf == 0 and xf == 0:
            return cp[..., y0:y0 + hc, x0:x0 + wc]
        a = cp[..., y0:y0 + hc + 1, x0:x0 + wc + 1]
        tl = a[..., :hc, :wc]
        tr = a[..., :hc, 1:]
        bl = a[..., 1:, :wc]
        br = a[..., 1:, 1:]
        acc = ((8 - xf) * (8 - yf) * tl.astype(jnp.int32)
               + xf * (8 - yf) * tr + (8 - xf) * yf * bl
               + xf * yf * br + 32) >> 6
        return acc.astype(jnp.int16)

    def block_px(mask, cell):
        return jnp.repeat(jnp.repeat(mask, cell, -2), cell, -1)

    lead = cur.shape[:-2]
    best_sad = jnp.full(lead + (nby, nbx), jnp.inf, jnp.float32)
    best_idx = jnp.zeros(lead + (nby, nbx), jnp.int32)
    py = jnp.zeros(lead + (h, w), jnp.uint8)
    pcb = jnp.zeros(lead + (hc, wc), jnp.uint8)
    pcr = jnp.zeros(lead + (hc, wc), jnp.uint8)

    for c0 in range(0, n, chunk):
        batch = [tuple(int(v) for v in o) for o in offs_np[c0:c0 + chunk]]
        k = len(batch)
        shifted = jnp.stack([luma_slice(dy, dx) for dy, dx in batch])
        diff = jnp.abs(cur_i[None] - shifted.astype(jnp.int16))
        sads = _sad_per_mb_hybrid(diff, mb)
        c_best = sads.min(axis=0)
        c_arg = sads.argmin(axis=0).astype(jnp.uint8)  # first min wins
        # per-pixel winner index (u8) lets the one-hot compare fuse into
        # the masked sums instead of materializing k boolean planes
        argpx = block_px(c_arg, mb)
        argcx = block_px(c_arg, cb2)
        ks = jnp.arange(k, dtype=jnp.uint8)
        kpx = ks.reshape((k,) + (1,) * argpx.ndim)
        # exactly one k contributes per pixel → the masked sum IS a select
        py_c = jnp.sum(jnp.where(kpx == argpx[None], shifted, 0)
                       .astype(jnp.int16), axis=0).astype(jnp.uint8)
        ncb = jnp.stack([chroma_pred(cbp, dy, dx) for dy, dx in batch])
        ncr = jnp.stack([chroma_pred(crp, dy, dx) for dy, dx in batch])
        kcx = ks.reshape((k,) + (1,) * argcx.ndim)
        ohcx = kcx == argcx[None]
        pcb_c = jnp.sum(jnp.where(ohcx, ncb, 0), axis=0).astype(jnp.uint8)
        pcr_c = jnp.sum(jnp.where(ohcx, ncr, 0), axis=0).astype(jnp.uint8)

        take = c_best < best_sad                      # strict: earlier wins
        tpx = block_px(take, mb)
        tcx = block_px(take, cb2)
        best_idx = jnp.where(take, c_arg.astype(jnp.int32) + c0, best_idx)
        best_sad = jnp.where(take, c_best, best_sad)
        py = jnp.where(tpx, py_c, py)
        pcb = jnp.where(tcx, pcb_c, pcb)
        pcr = jnp.where(tcx, pcr_c, pcr)

    mv = jnp.asarray(offs_np)[best_idx]              # tiny [nby, nbx] take
    return mv, py, pcb, pcr


@functools.partial(jax.jit, static_argnames=("mb", "search"))
def full_search_mc_scan(cur, ref, ref_cb, ref_cr, *, mb: int = 16,
                   search: int = 12):
    """Round-2 scan formulation of the fused search (selectable backend).

    The separate ME → mc_luma/mc_chroma pipeline pays per-macroblock
    gathers (vmapped dynamic_slice with per-block starts): ~3M gathered
    elements/frame through the TPU scalar core dominated the whole H.264
    encode (~90-110 ms each at 1080p). Here every candidate offset is a
    single dynamic-base slice (a DMA, not a gather), and the winning
    prediction — luma and the §8.4.2.2.2-exact chroma bilinear — is
    selected with elementwise masks inside the same scan, so NO
    per-block random access exists anywhere in the P-frame path.

    Tie-breaking matches full_search_mv exactly: offsets scan in
    |dy|+|dx|-sorted order and a strict ``<`` keeps the earliest
    minimum, so (0,0) and near-zero motion win ties.

    Returns (mv, pred_y u8, pred_cb u8, pred_cr u8).
    """
    h, w = cur.shape[-2:]
    hc, wc = ref_cb.shape[-2:]
    cb2 = mb // 2
    nby, nbx = h // mb, w // mb
    offs_np = _offsets(search)
    offs = jnp.asarray(offs_np)
    # f32 pixels: exact (≤ 255) and the SAD block sums ride the MXU
    cur_i = cur.astype(jnp.float32)
    ref_pad = pad_replicate(ref.astype(jnp.float32), search)
    rc = search // 2 + 1
    cbp = pad_replicate(ref_cb.astype(jnp.int32), rc + 1)
    crp = pad_replicate(ref_cr.astype(jnp.int32), rc + 1)

    def chroma_pred(cp, off):
        iy = off[0] >> 1
        ix = off[1] >> 1
        yf = (off[0] & 1) * 4
        xf = (off[1] & 1) * 4
        starts = (0,) * (cp.ndim - 2) + (rc + 1 + iy, rc + 1 + ix)
        a = jax.lax.dynamic_slice(
            cp, starts, cp.shape[:-2] + (hc + 1, wc + 1))
        tl = a[..., :hc, :wc]
        tr = a[..., :hc, 1:]
        bl = a[..., 1:, :wc]
        br = a[..., 1:, 1:]
        return ((8 - xf) * (8 - yf) * tl + xf * (8 - yf) * tr +
                (8 - xf) * yf * bl + xf * yf * br + 32) >> 6

    def block_px(mask, cell):
        return jnp.repeat(jnp.repeat(mask, cell, -2), cell, -1)

    def body(carry, xs):
        best_sad, best_idx, py, pcb, pcr = carry
        off, idx = xs
        starts = (0,) * (ref_pad.ndim - 2) + (search + off[0],
                                              search + off[1])
        shifted = jax.lax.dynamic_slice(
            ref_pad, starts, ref_pad.shape[:-2] + (h, w))
        sad = _sad_per_mb_mxu(jnp.abs(cur_i - shifted), mb)
        take = sad < best_sad
        ncb = chroma_pred(cbp, off)
        ncr = chroma_pred(crp, off)
        tpx = block_px(take, mb)
        tcx = block_px(take, cb2)
        # uint8 carries: every prediction value is ≤ 255, and the scan
        # re-reads + re-writes the carries each of the 625 iterations —
        # carry bytes are the dominant HBM traffic of the whole search
        return ((jnp.where(take, sad, best_sad),
                 jnp.where(take, idx, best_idx),
                 jnp.where(tpx, shifted.astype(jnp.uint8), py),
                 jnp.where(tcx, ncb.astype(jnp.uint8), pcb),
                 jnp.where(tcx, ncr.astype(jnp.uint8), pcr)), None)

    lead = cur.shape[:-2]
    init = (jnp.full(lead + (nby, nbx), jnp.inf, jnp.float32),
            jnp.zeros(lead + (nby, nbx), jnp.int32),
            jnp.zeros(lead + (h, w), jnp.uint8),
            jnp.zeros(lead + (hc, wc), jnp.uint8),
            jnp.zeros(lead + (hc, wc), jnp.uint8))
    n = offs.shape[0]
    (best_sad, best_idx, py, pcb, pcr), _ = jax.lax.scan(
        body, init, (offs, jnp.arange(n, dtype=jnp.int32)))
    mv = offs[best_idx]                              # tiny [nby, nbx] take
    return mv, py, pcb, pcr



@functools.partial(jax.jit, static_argnames=("mb", "search"))
def mc_luma(ref: jnp.ndarray, mv: jnp.ndarray, *,
            mb: int = 16, search: int = 12) -> jnp.ndarray:
    """Motion-compensated luma prediction.

    ref: (H, W) uint8; mv: (H//mb, W//mb, 2) int32 → (H, W) uint8 pred.
    """
    h, w = ref.shape
    nby, nbx = h // mb, w // mb
    ref_pad = pad_replicate(ref, search)

    def block(by, bx):
        off = mv[by, bx]
        return jax.lax.dynamic_slice(
            ref_pad, (search + by * mb + off[0], search + bx * mb + off[1]),
            (mb, mb))

    rows = jax.vmap(jax.vmap(block, in_axes=(None, 0)), in_axes=(0, None))(
        jnp.arange(nby), jnp.arange(nbx))            # (nby, nbx, mb, mb)
    return rows.swapaxes(1, 2).reshape(h, w)


@functools.partial(jax.jit, static_argnames=("mb", "search"))
def mc_chroma(ref_c: jnp.ndarray, mv: jnp.ndarray, *,
              mb: int = 16, search: int = 12) -> jnp.ndarray:
    """Motion-compensated 4:2:0 chroma prediction, §8.4.2.2.2-exact.

    ref_c: (H/2, W/2) uint8 one chroma plane; mv: luma MVs
    (H//mb, W//mb, 2).  Chroma blocks are mb/2 × mb/2.  Integer luma MVs
    give xFrac/yFrac ∈ {0, 4} eighths; the bilinear is computed in int32.
    """
    hc, wc = ref_c.shape
    cb = mb // 2
    nby, nbx = hc // cb, wc // cb
    rc = search // 2 + 1
    ref_pad = pad_replicate(ref_c.astype(jnp.int32), rc + 1)

    def block(by, bx):
        off = mv[by, bx]
        iy = off[0] >> 1                  # arithmetic floor
        ix = off[1] >> 1
        yf = (off[0] & 1) * 4
        xf = (off[1] & 1) * 4
        y0 = rc + 1 + by * cb + iy
        x0 = rc + 1 + bx * cb + ix
        a = jax.lax.dynamic_slice(ref_pad, (y0, x0), (cb + 1, cb + 1))
        tl = a[:cb, :cb]
        tr = a[:cb, 1:]
        bl = a[1:, :cb]
        br = a[1:, 1:]
        return ((8 - xf) * (8 - yf) * tl + xf * (8 - yf) * tr +
                (8 - xf) * yf * bl + xf * yf * br + 32) >> 6

    rows = jax.vmap(jax.vmap(block, in_axes=(None, 0)), in_axes=(0, None))(
        jnp.arange(nby), jnp.arange(nbx))
    return rows.swapaxes(1, 2).reshape(hc, wc).astype(jnp.uint8)


class NumpyMotionMirror:
    """Independent numpy model used by tests (decoder-side semantics)."""

    @staticmethod
    def mc_luma(ref, mv, mb=16):
        h, w = ref.shape
        out = np.zeros_like(ref)
        for by in range(h // mb):
            for bx in range(w // mb):
                dy, dx = mv[by, bx]
                for y in range(mb):
                    sy = min(max(by * mb + y + dy, 0), h - 1)
                    for x in range(mb):
                        sx = min(max(bx * mb + x + dx, 0), w - 1)
                        out[by * mb + y, bx * mb + x] = ref[sy, sx]
        return out

    @staticmethod
    def mc_chroma(ref_c, mv, mb=16):
        hc, wc = ref_c.shape
        cb = mb // 2
        out = np.zeros_like(ref_c)
        r = ref_c.astype(np.int64)
        for by in range(hc // cb):
            for bx in range(wc // cb):
                dy, dx = mv[by, bx]
                iy, ix = dy >> 1, dx >> 1
                yf, xf = (dy & 1) * 4, (dx & 1) * 4
                for y in range(cb):
                    for x in range(cb):
                        def at(yy, xx):
                            return r[min(max(yy, 0), hc - 1),
                                     min(max(xx, 0), wc - 1)]
                        py, px = by * cb + y + iy, bx * cb + x + ix
                        val = ((8 - xf) * (8 - yf) * at(py, px) +
                               xf * (8 - yf) * at(py, px + 1) +
                               (8 - xf) * yf * at(py + 1, px) +
                               xf * yf * at(py + 1, px + 1) + 32) >> 6
                        out[by * cb + y, bx * cb + x] = val
        return out
