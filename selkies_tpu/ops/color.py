"""Color-space transforms on device.

The reference's pixel pipeline does BGRX→YUV conversion inside pixelflux's
C++ SIMD code before x264/libjpeg; here it is a fused device op: a single
3x3 matmul + offset that XLA folds into the surrounding encode pipeline
(one HBM pass).

Coefficients are JFIF/BT.601 full-range, the convention both libjpeg-class
JPEG decoders and the browser `ImageDecoder` assume.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Rows: Y, Cb, Cr; columns: R, G, B.
_RGB2YCC = jnp.array(
    [
        [0.299, 0.587, 0.114],
        [-0.168736, -0.331264, 0.5],
        [0.5, -0.418688, -0.081312],
    ],
    dtype=jnp.float32,
)
_YCC_OFFSET = jnp.array([0.0, 128.0, 128.0], dtype=jnp.float32)


def rgb_to_ycbcr(rgb):
    """[..., H, W, 3] uint8/float RGB → (Y, Cb, Cr) float32 planes [..., H, W].

    Values are in [0, 255]; no level shift here (the DCT stage subtracts
    128). Elementwise FMA form, not a matmul: a [N, 3] @ [3, 3] dot is the
    worst possible MXU shape (and at HIGHEST precision costs 6 passes) —
    the VPU does this in one fused pass per plane.
    """
    x = rgb.astype(jnp.float32)
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    m = _RGB2YCC
    y = m[0, 0] * r + m[0, 1] * g + m[0, 2] * b
    cb = m[1, 0] * r + m[1, 1] * g + m[1, 2] * b + 128.0
    cr = m[2, 0] * r + m[2, 1] * g + m[2, 2] * b + 128.0
    return y, cb, cr


def subsample_420(plane):
    """2x2 mean-pool chroma subsampling: [..., H, W] → [..., H/2, W/2]."""
    h, w = plane.shape[-2], plane.shape[-1]
    p = plane.reshape(*plane.shape[:-2], h // 2, 2, w // 2, 2)
    return p.mean(axis=(-3, -1))
