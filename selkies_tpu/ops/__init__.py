from .color import rgb_to_ycbcr, subsample_420  # noqa: F401
from .dct import dct8_matrix, block_dct2, block_idct2, blockify, unblockify  # noqa: F401
from .quant import (  # noqa: F401
    ZIGZAG,
    base_quant_tables,
    quality_scaled_tables,
    quantize_blocks,
)
