"""H.264 4×4 integer transform, Hadamard DC transforms, and quantization.

TPU-native building blocks for the tpuenc H.264-class profile (replacing
the reference's x264/NVENC encode stage, gstwebrtc_app.py:200-770 and the
pixelflux striped-x264 path).  Everything here is expressed as batched
4×4 matrix products over ``(..., 4, 4)`` block arrays so XLA tiles them
onto the MXU; all arithmetic follows ITU-T H.264 §8.5 exactly (integer,
bit-exact with a conforming decoder — the encoder's reconstruction loop
reuses these same dequant/inverse paths).

Layout convention: a plane of shape (H, W) is viewed as 4×4 blocks with
``plane.reshape(H//4, 4, W//4, 4).transpose(0, 2, 1, 3)`` → (nby, nbx, 4, 4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# -- core matrices -----------------------------------------------------------

_CF = np.array([[1, 1, 1, 1],
                [2, 1, -1, -2],
                [1, -1, -1, 1],
                [1, -2, 2, -1]], np.int32)

# decoder-side inverse uses the exact butterfly below (§8.5.12.2); the
# matrix form with halves is only used to derive it.
_H4 = np.array([[1, 1, 1, 1],
                [1, 1, -1, -1],
                [1, -1, -1, 1],
                [1, -1, 1, -1]], np.int32)

_H2 = np.array([[1, 1], [1, -1]], np.int32)

# quant multiplier MF (encoder) per QP%6 × coefficient class
# class 0: positions (0,0),(0,2),(2,0),(2,2); class 1: (1,1),(1,3),(3,1),(3,3);
# class 2: the rest.
_MF = np.array([
    [13107, 5243, 8066],
    [11916, 4660, 7490],
    [10082, 4194, 6554],
    [9362, 3647, 5825],
    [8192, 3355, 5243],
    [7282, 2893, 4559],
], np.int32)

# dequant scale V (decoder LevelScale4x4) per QP%6 × class
_V = np.array([
    [10, 16, 13],
    [11, 18, 14],
    [13, 20, 16],
    [14, 23, 18],
    [16, 25, 20],
    [18, 29, 23],
], np.int32)

# position → class map for a 4×4 block
_POS_CLASS = np.array([[0, 2, 0, 2],
                       [2, 1, 2, 1],
                       [0, 2, 0, 2],
                       [2, 1, 2, 1]], np.int32)

#: MF/V expanded to (6, 4, 4)
MF_TABLE = _MF[:, _POS_CLASS]          # (6,4,4)
V_TABLE = _V[:, _POS_CLASS]            # (6,4,4)

# QPc mapping from QPy (chroma_qp_index_offset = 0), §8.5.8 table
_QPC = np.concatenate([
    np.arange(30),
    np.array([29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37,
              38, 38, 38, 39, 39, 39, 39]),
]).astype(np.int32)

ZIGZAG_4x4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                      np.int32)


def qpc_for(qp):
    """Chroma QP for a luma QP (chroma_qp_index_offset == 0).

    Works on python ints and traced jax scalars alike.
    """
    if isinstance(qp, (int, np.integer)):
        return int(_QPC[min(max(qp, 0), 51)])
    return jnp.asarray(_QPC)[jnp.clip(qp, 0, 51)]


# ---------------------------------------------------------------------------
# block layout helpers


def plane_to_blocks(plane: jnp.ndarray) -> jnp.ndarray:
    """(H, W) → (H//4, W//4, 4, 4)."""
    h, w = plane.shape[-2:]
    lead = plane.shape[:-2]
    return plane.reshape(*lead, h // 4, 4, w // 4, 4).swapaxes(-3, -2)


def blocks_to_plane(blocks: jnp.ndarray) -> jnp.ndarray:
    """(..., H//4, W//4, 4, 4) → (..., H, W)."""
    nby, nbx = blocks.shape[-4:-2]
    lead = blocks.shape[:-4]
    return blocks.swapaxes(-3, -2).reshape(*lead, nby * 4, nbx * 4)


# ---------------------------------------------------------------------------
# forward/inverse core transform


def _cf_1d(x0, x1, x2, x3):
    """One 1-D core-transform butterfly (rows of Cf applied to a lane)."""
    s0 = x0 + x3
    s1 = x1 + x2
    d0 = x0 - x3
    d1 = x1 - x2
    return s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1


def forward_dct4(blocks: jnp.ndarray) -> jnp.ndarray:
    """Core transform W = Cf · X · Cfᵀ over (..., 4, 4) int32 blocks.

    Butterfly form (adds/shifts on whole lanes), not an einsum: TPU has
    no integer MXU path, so a batched 4×4 int dot lowers to slow
    scalar/loop code — the same reason inverse_dct4 is written as
    butterflies.
    """
    x = blocks.astype(jnp.int32)
    # vertical (left multiply): combine rows
    v0, v1, v2, v3 = _cf_1d(x[..., 0, :], x[..., 1, :],
                            x[..., 2, :], x[..., 3, :])
    v = jnp.stack([v0, v1, v2, v3], axis=-2)
    # horizontal (right multiply by Cfᵀ): combine columns
    h0, h1, h2, h3 = _cf_1d(v[..., :, 0], v[..., :, 1],
                            v[..., :, 2], v[..., :, 3])
    return jnp.stack([h0, h1, h2, h3], axis=-1)


def inverse_dct4(coeffs: jnp.ndarray) -> jnp.ndarray:
    """Decoder inverse transform (§8.5.12.2) with final (x+32)>>6.

    Input: dequantized coefficients d (int32). Output: residual (int32).
    Stage order (horizontal along j, then vertical along i) matters because
    of the >>1 floors — this follows the spec exactly.
    """
    d = coeffs.astype(jnp.int32)
    # horizontal: butterfly across the column index j within each row
    d0, d1, d2, d3 = d[..., :, 0], d[..., :, 1], d[..., :, 2], d[..., :, 3]
    e0 = d0 + d2
    e1 = d0 - d2
    e2 = (d1 >> 1) - d3
    e3 = d1 + (d3 >> 1)
    f = jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)
    # vertical: same butterfly across the row index i
    f0, f1, f2, f3 = f[..., 0, :], f[..., 1, :], f[..., 2, :], f[..., 3, :]
    g0 = f0 + f2
    g1 = f0 - f2
    g2 = (f1 >> 1) - f3
    g3 = f1 + (f3 >> 1)
    r = jnp.stack([g0 + g3, g1 + g2, g1 - g2, g0 - g3], axis=-2)
    return (r + 32) >> 6


# ---------------------------------------------------------------------------
# AC / plain 4×4 quantization


def quant4(coeffs: jnp.ndarray, qp: jnp.ndarray, intra: bool) -> jnp.ndarray:
    """Quantize core-transform output. qp is a scalar (per-stripe QP).

    int32 is sufficient throughout: |W| ≤ 255·36 and MF ≤ 13107, so
    |W|·MF ≤ 1.2e8 < 2³¹.
    """
    qp = jnp.asarray(qp, jnp.int32)
    mf = jnp.asarray(MF_TABLE)[qp % 6]           # (4,4)
    qbits = 15 + qp // 6
    f = jnp.left_shift(1, qbits) // (3 if intra else 6)
    w = coeffs.astype(jnp.int32)
    mag = (jnp.abs(w) * mf + f) >> qbits
    # decoders store dequantized coefficients in int16; clamp levels so
    # |z·V| << (qp/6) ≤ 32767 (only adversarial content ever hits this)
    zmax = (32767 >> (qp // 6)) // jnp.asarray(V_TABLE)[qp % 6]
    mag = jnp.minimum(mag, zmax)
    return jnp.sign(w) * mag


def dequant4(levels: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """Decoder §8.5.12.1 scaling for plain 4×4 blocks (AC positions too)."""
    qp = jnp.asarray(qp, jnp.int32)
    v = jnp.asarray(V_TABLE)[qp % 6]
    return (levels.astype(jnp.int32) * v) << (qp // 6)


# ---------------------------------------------------------------------------
# Intra16x16 luma DC path


def _h4_1d(x0, x1, x2, x3):
    """One 1-D 4-point Hadamard butterfly (rows of _H4)."""
    a = x0 + x1
    b = x2 + x3
    c = x0 - x1
    e = x2 - x3
    return a + b, a - b, c - e, c + e


def hadamard4_fwd(dc: jnp.ndarray) -> jnp.ndarray:
    """Encoder DC transform: (H·X·Hᵀ)/2 over (..., 4, 4), butterfly form
    (no integer einsum — see forward_dct4)."""
    x = dc.astype(jnp.int32)
    v0, v1, v2, v3 = _h4_1d(x[..., 0, :], x[..., 1, :],
                            x[..., 2, :], x[..., 3, :])
    v = jnp.stack([v0, v1, v2, v3], axis=-2)
    h0, h1, h2, h3 = _h4_1d(v[..., :, 0], v[..., :, 1],
                            v[..., :, 2], v[..., :, 3])
    y = jnp.stack([h0, h1, h2, h3], axis=-1)
    return y >> 1  # /2 per spec encoder convention (x264 does the same)


def quant_dc16(dc_t: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """Quantize Hadamard-transformed luma DC.

    Shift derivation: the decoder (§8.5.10) computes
    ``dcY = (f·LevelScale(qp%6,0,0)) · 2^(qp/6−6)`` (with rounding below
    qp 36) where ``f = H·z·H`` and LevelScale = 16·V (flat default weight
    scale 16).  Consistency with the AC dequant domain (d = 4·W at any QP)
    requires transmitted ``z = y·2^(1−qp/6)/V00`` for ``y = (H·dc·H)/2``,
    i.e. ``z = y·MF00 >> (16 + qp/6)`` since MF00·V00 = 2¹⁷.
    Round-to-nearest (not the intra deadzone): DC banding is visible.
    """
    qp = jnp.asarray(qp, jnp.int32)
    mf00 = jnp.asarray(MF_TABLE)[qp % 6, 0, 0]
    s = 16 + qp // 6
    f = jnp.left_shift(1, s) >> 1
    w = dc_t.astype(jnp.int32)
    mag = (jnp.abs(w) * mf00 + f) >> s
    # Levels from the forward path are bounded by linear consistency
    # (|dc| ≤ 4080 ⇒ decoder dcY ≈ 4·dc ≤ 16320 for ANY sign pattern, since
    # the chain is linear); only clamp the transmitted level itself to int16.
    mag = jnp.minimum(mag, 32767)
    return jnp.sign(w) * mag


def dequant_dc16(levels: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """Decoder §8.5.10 exactly: inverse Hadamard FIRST, then scale with
    LevelScale = 16·V (flat default scaling list)."""
    qp = jnp.asarray(qp, jnp.int32)
    x = levels.astype(jnp.int32)
    v0, v1, v2, v3 = _h4_1d(x[..., 0, :], x[..., 1, :],
                            x[..., 2, :], x[..., 3, :])
    v = jnp.stack([v0, v1, v2, v3], axis=-2)
    h0, h1, h2, h3 = _h4_1d(v[..., :, 0], v[..., :, 1],
                            v[..., :, 2], v[..., :, 3])
    f = jnp.stack([h0, h1, h2, h3], axis=-1)
    ls = jnp.asarray(V_TABLE)[qp % 6, 0, 0] * 16
    shift = qp // 6
    hi = (f * ls) << jnp.maximum(shift - 6, 0)
    lo_shift = jnp.maximum(6 - shift, 0)
    lo = (f * ls + (1 << jnp.maximum(lo_shift - 1, 0))) >> lo_shift
    return jnp.where(qp >= 36, hi, lo)


# ---------------------------------------------------------------------------
# chroma DC path (2×2)


def _h2_2d(x):
    """H2 · X · H2 over (..., 2, 2) as adds (H2 is its own transpose)."""
    a = x[..., 0, 0]
    b = x[..., 0, 1]
    c = x[..., 1, 0]
    d = x[..., 1, 1]
    return jnp.stack([
        jnp.stack([a + b + c + d, a - b + c - d], axis=-1),
        jnp.stack([a + b - c - d, a - b - c + d], axis=-1),
    ], axis=-2)


def hadamard2_fwd(dc: jnp.ndarray) -> jnp.ndarray:
    """Encoder chroma DC transform over (..., 2, 2) (no scaling)."""
    return _h2_2d(dc.astype(jnp.int32))


def quant_dc2(dc_t: jnp.ndarray, qpc: jnp.ndarray) -> jnp.ndarray:
    """Chroma DC quant; same consistency derivation as :func:`quant_dc16`
    against §8.5.11 (``dcC = ((f·16·V00) << qp/6) >> 5``, H2⁻¹ = H2/2)
    lands on the identical ``>> (16 + qp/6)`` shift for y = H2·dc·H2."""
    qpc = jnp.asarray(qpc, jnp.int32)
    mf00 = jnp.asarray(MF_TABLE)[qpc % 6, 0, 0]
    s = 16 + qpc // 6
    f = jnp.left_shift(1, s) >> 1
    w = dc_t.astype(jnp.int32)
    mag = (jnp.abs(w) * mf00 + f) >> s
    # int16 decoder bound: |dcC| ≈ z·V00·2^(qp/6) ≤ 32767
    zmax = (32767 >> (qpc // 6)) // jnp.asarray(V_TABLE)[qpc % 6, 0, 0]
    mag = jnp.minimum(mag, zmax)
    return jnp.sign(w) * mag


def dequant_dc2(levels: jnp.ndarray, qpc: jnp.ndarray) -> jnp.ndarray:
    """Decoder §8.5.11 exactly: inverse 2×2 Hadamard then
    ((f·16·V)<<(qp/6))>>5 (LevelScale = 16·V, flat scaling list)."""
    qpc = jnp.asarray(qpc, jnp.int32)
    f = _h2_2d(levels.astype(jnp.int32))
    ls = jnp.asarray(V_TABLE)[qpc % 6, 0, 0] * 16
    return ((f * ls) << (qpc // 6)) >> 5


# ---------------------------------------------------------------------------
# numpy mirror (the test oracle: an independent, readable decoder-side model)


class NumpyMirror:
    """Pure-numpy decoder-side reference for the ops above."""

    @staticmethod
    def inverse_dct4(d):
        # §8.5.12.2 verbatim: horizontal (along j) then vertical (along i)
        d = d.astype(np.int64)
        e = np.empty_like(d)
        e[..., :, 0] = d[..., :, 0] + d[..., :, 2]
        e[..., :, 1] = d[..., :, 0] - d[..., :, 2]
        e[..., :, 2] = (d[..., :, 1] >> 1) - d[..., :, 3]
        e[..., :, 3] = d[..., :, 1] + (d[..., :, 3] >> 1)
        f = np.empty_like(d)
        f[..., :, 0] = e[..., :, 0] + e[..., :, 3]
        f[..., :, 1] = e[..., :, 1] + e[..., :, 2]
        f[..., :, 2] = e[..., :, 1] - e[..., :, 2]
        f[..., :, 3] = e[..., :, 0] - e[..., :, 3]
        g = np.empty_like(f)
        g[..., 0, :] = f[..., 0, :] + f[..., 2, :]
        g[..., 1, :] = f[..., 0, :] - f[..., 2, :]
        g[..., 2, :] = (f[..., 1, :] >> 1) - f[..., 3, :]
        g[..., 3, :] = f[..., 1, :] + (f[..., 3, :] >> 1)
        r = np.empty_like(g)
        r[..., 0, :] = g[..., 0, :] + g[..., 3, :]
        r[..., 1, :] = g[..., 1, :] + g[..., 2, :]
        r[..., 2, :] = g[..., 1, :] - g[..., 2, :]
        r[..., 3, :] = g[..., 0, :] - g[..., 3, :]
        return (r + 32) >> 6

    @staticmethod
    def dequant4(levels, qp):
        return (levels.astype(np.int64) * V_TABLE[qp % 6]) << (qp // 6)

    @staticmethod
    def dequant_dc16(levels, qp):
        f = np.einsum("ij,...jk,lk->...il", _H4, levels.astype(np.int64), _H4)
        ls = V_TABLE[qp % 6, 0, 0] * 16
        if qp >= 36:
            return (f * ls) << (qp // 6 - 6)
        s = 6 - qp // 6
        return (f * ls + (1 << (s - 1))) >> s

    @staticmethod
    def dequant_dc2(levels, qpc):
        f = np.einsum("ij,...jk,lk->...il", _H2, levels.astype(np.int64), _H2)
        ls = V_TABLE[qpc % 6, 0, 0] * 16
        return ((f * ls) << (qpc // 6)) >> 5
