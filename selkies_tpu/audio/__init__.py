"""Audio subsystem: Opus codec, capture sources, and the server pipeline.

The pcmflux-equivalent of this framework (reference: external pcmflux pip
package consumed at selkies.py:939-1090).  CPU-only by design.
"""

from .capture import (AudioCapture, AudioCaptureSettings, PcmSource,
                      PulseSource, SilenceSource, SyntheticTone, open_source)
from .codec import OpusDecoder, OpusEncoder, opus_available, pulse_available
from .pipeline import AudioPipeline, MicSink

__all__ = [
    "AudioCapture", "AudioCaptureSettings", "AudioPipeline", "MicSink",
    "OpusDecoder", "OpusEncoder", "PcmSource", "PulseSource",
    "SilenceSource", "SyntheticTone", "open_source", "opus_available",
    "pulse_available",
]
