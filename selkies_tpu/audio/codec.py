"""Opus codec over the native audio runtime (ctypes).

Python face of the pcmflux-equivalent encode stage (reference consumes
pcmflux's Opus output at selkies.py:939-952 and ships it as ``b'\\x01\\x00'``
frames).  Audio is CPU work by design (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..native import audio_lib


def opus_available() -> bool:
    lib = audio_lib()
    return bool(lib and lib.sa_opus_available())


def pulse_available() -> bool:
    lib = audio_lib()
    return bool(lib and lib.sa_pulse_available())


class OpusEncoder:
    """Streaming Opus encoder (s16 interleaved in, packets out)."""

    def __init__(self, sample_rate: int = 48000, channels: int = 2,
                 bitrate: int = 320000, vbr: bool = True,
                 complexity: int = 10, lowdelay: bool = False,
                 inband_fec: bool = False) -> None:
        lib = audio_lib()
        if lib is None or not lib.sa_opus_available():
            raise RuntimeError("libopus unavailable")
        self._lib = lib
        self._h = lib.sa_enc_new(sample_rate, channels, bitrate,
                                 int(vbr), complexity, int(lowdelay),
                                 int(inband_fec))
        if not self._h:
            raise RuntimeError("opus encoder init failed")
        self.sample_rate = sample_rate
        self.channels = channels
        self._out = np.empty(4000, np.uint8)  # opus recommended max packet

    def encode(self, pcm: np.ndarray) -> bytes:
        """``pcm``: int16 array of interleaved samples, shape (frames*ch,)
        or (frames, ch); frames must be a valid Opus frame size."""
        pcm = np.ascontiguousarray(pcm, np.int16).reshape(-1)
        frames = pcm.size // self.channels
        n = self._lib.sa_enc_encode(self._h, pcm, frames, self._out,
                                    self._out.size)
        if n < 0:
            raise RuntimeError(f"opus_encode error {n}")
        return bytes(self._out[:n])

    def close(self) -> None:
        if self._h:
            self._lib.sa_enc_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class OpusDecoder:
    """Streaming Opus decoder (packets in, s16 interleaved out)."""

    def __init__(self, sample_rate: int = 48000, channels: int = 2) -> None:
        lib = audio_lib()
        if lib is None or not lib.sa_opus_available():
            raise RuntimeError("libopus unavailable")
        self._lib = lib
        self._h = lib.sa_dec_new(sample_rate, channels)
        if not self._h:
            raise RuntimeError("opus decoder init failed")
        self.sample_rate = sample_rate
        self.channels = channels
        # 120 ms at 48 kHz is the max opus frame
        self._buf = np.empty(5760 * channels, np.int16)

    def decode(self, packet: bytes) -> np.ndarray:
        """→ int16 array (frames, channels)."""
        data = np.frombuffer(packet, np.uint8)
        n = self._lib.sa_dec_decode(
            self._h, np.ascontiguousarray(data), len(packet), self._buf,
            self._buf.size // self.channels)
        if n < 0:
            raise RuntimeError(f"opus_decode error {n}")
        return self._buf[:n * self.channels].reshape(n, self.channels).copy()

    def decode_fec(self, next_packet: bytes, frames: int) -> np.ndarray:
        """Reconstruct a LOST frame from the in-band FEC data of the
        packet that followed it. ``frames`` = the lost frame's duration
        in samples/channel (960 for the 20 ms default)."""
        frames = min(int(frames), self._buf.size // self.channels)
        data = np.frombuffer(next_packet, np.uint8)
        n = self._lib.sa_dec_decode_fec(
            self._h, np.ascontiguousarray(data), len(next_packet),
            self._buf, frames)
        if n < 0:
            raise RuntimeError(f"opus_decode fec error {n}")
        return self._buf[:n * self.channels].reshape(n, self.channels).copy()

    def decode_plc(self, frames: int) -> np.ndarray:
        """Packet-loss concealment when no FEC data is available."""
        frames = min(int(frames), self._buf.size // self.channels)
        n = self._lib.sa_dec_plc(self._h, self._buf, frames)
        if n < 0:
            raise RuntimeError(f"opus plc error {n}")
        return self._buf[:n * self.channels].reshape(n, self.channels).copy()

    def close(self) -> None:
        if self._h:
            self._lib.sa_dec_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
