"""Server-side audio pipeline: capture→Opus broadcast + mic reverse path.

Equivalent of the reference's pcmflux pipeline (capture thread → asyncio
queue → ``b'\\x01\\x00'+opus`` broadcast, selkies.py:939-1090) and its mic
ingest (binary 0x02 PCM frames → PulseAudio virtual source playback,
selkies.py:1642-1844).  Plugs into ``DataStreamingServer.audio_pipeline``
(START_AUDIO/STOP_AUDIO verbs and the 0x02 binary branch).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import numpy as np

from ..native import audio_lib
from ..protocol.wire import pack_audio_chunk
from .capture import AudioCapture, AudioCaptureSettings, PcmSource
from .codec import pulse_available

logger = logging.getLogger("selkies_tpu.audio")

_QUEUE_MAX = 64  # ~1.3 s of 20 ms chunks; drop-oldest beyond


class MicSink:
    """Destination for client microphone PCM (s16le interleaved).

    With PulseAudio present this plays into the virtual-source playback
    stream (the "SelkiesVirtualMic" role in the reference); headless hosts
    just count frames so the protocol path stays exercised.
    """

    def __init__(self, sample_rate: int = 48000, channels: int = 1) -> None:
        self.sample_rate = sample_rate
        self.channels = channels
        self.frames_in = 0
        self._h = None
        lib = audio_lib()
        if lib is not None and lib.sa_pulse_available():
            self._lib = lib
            self._h = lib.sa_pa_new(None, sample_rate, channels, 1,
                                    b"selkies-virtual-mic")
            if not self._h:
                logger.warning("mic playback stream open failed")

    def write(self, pcm_bytes: bytes) -> None:
        self.frames_in += 1
        if self._h:
            if len(pcm_bytes) % 2:  # truncated s16 frame: drop the odd byte
                pcm_bytes = pcm_bytes[:-1]
            if not pcm_bytes:
                return
            pcm = np.frombuffer(pcm_bytes, np.int16)
            self._lib.sa_pa_write(self._h, np.ascontiguousarray(pcm),
                                  pcm.nbytes)

    def close(self) -> None:
        if self._h:
            self._lib.sa_pa_free(self._h)
            self._h = None


class AudioPipeline:
    """Owns the capture thread, the chunk queue, and the sender task."""

    def __init__(self, server, settings: AudioCaptureSettings,
                 source: Optional[PcmSource] = None) -> None:
        self.server = server
        self.settings = settings
        self._source = source
        self._capture: Optional[AudioCapture] = None
        self._queue: Optional[asyncio.Queue] = None
        self._sender: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.mic = MicSink(sample_rate=settings.sample_rate, channels=1)
        self.chunks_sent = 0
        self.chunks_dropped = 0

    @property
    def running(self) -> bool:
        return self._capture is not None

    # -- capture-thread side -------------------------------------------------

    def _on_chunk(self, packet: bytes) -> None:
        loop, queue = self._loop, self._queue
        if loop is None or queue is None or loop.is_closed():
            return
        loop.call_soon_threadsafe(self._enqueue, queue, packet)

    def _enqueue(self, queue: asyncio.Queue, packet: bytes) -> None:
        if queue.full():  # audio is realtime: drop oldest, keep newest
            try:
                queue.get_nowait()
                self.chunks_dropped += 1
            except asyncio.QueueEmpty:
                pass
        queue.put_nowait(packet)

    # -- asyncio side --------------------------------------------------------

    async def start(self) -> None:
        if self.running:
            return
        self._loop = asyncio.get_running_loop()
        self._queue = asyncio.Queue(_QUEUE_MAX)

        def _build():  # source open (pa_simple_new) blocks: off the loop
            cap = AudioCapture(self.settings, self._on_chunk,
                               source=self._source)
            cap.start_capture()
            return cap

        self._capture = await asyncio.to_thread(_build)
        self._sender = asyncio.create_task(self._send_loop())
        logger.info("audio pipeline started (%d Hz, %d ch, %d bps, pulse=%s)",
                    self.settings.sample_rate, self.settings.channels,
                    self.settings.opus_bitrate, pulse_available())

    async def stop(self) -> None:
        cap, self._capture = self._capture, None
        if cap is not None:
            await asyncio.to_thread(cap.stop_capture)
        if self._sender is not None:
            self._sender.cancel()
            try:
                await self._sender
            except asyncio.CancelledError:
                pass
            self._sender = None
        self._queue = None

    async def _send_loop(self) -> None:
        queue = self._queue
        while True:
            packet = await queue.get()
            self.server.broadcast(pack_audio_chunk(packet))
            self.chunks_sent += 1

    async def on_mic_data(self, pcm: bytes) -> None:
        """Binary 0x02 payload from the client's mic worklet."""
        await asyncio.to_thread(self.mic.write, pcm)

    def close(self) -> None:
        self.mic.close()
