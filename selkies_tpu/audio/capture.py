"""Audio capture sources + the chunked Opus capture loop.

Capability-parity with pcmflux's ``AudioCapture.start_capture(settings,
callback)`` surface (reference selkies.py:1005-1026): a capture thread pulls
20 ms PCM chunks from a source, applies the silence gate, Opus-encodes, and
hands packets to a callback.  Sources: PulseAudio monitor (when libpulse is
present) or synthetic generators for tests/headless rigs.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ..native import audio_lib
from .codec import OpusEncoder, pulse_available

logger = logging.getLogger("selkies_tpu.audio")


@dataclass
class AudioCaptureSettings:
    """Mirrors the reference's pcmflux AudioCaptureSettings fields
    (selkies.py:1005-1015)."""

    device_name: str = ""
    sample_rate: int = 48000
    channels: int = 2
    opus_bitrate: int = 320000
    frame_duration_ms: int = 20
    use_vbr: bool = True
    use_silence_gate: bool = False
    debug_logging: bool = False

    @property
    def chunk_frames(self) -> int:
        return self.sample_rate * self.frame_duration_ms // 1000


class PcmSource:
    """A blocking PCM source delivering int16 interleaved chunks."""

    def read_chunk(self, frames: int) -> Optional[np.ndarray]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class PulseSource(PcmSource):
    """PulseAudio record stream (typically a sink monitor)."""

    def __init__(self, settings: AudioCaptureSettings) -> None:
        lib = audio_lib()
        if lib is None or not lib.sa_pulse_available():
            raise RuntimeError("libpulse unavailable")
        self._lib = lib
        self.channels = settings.channels
        self._h = lib.sa_pa_new(settings.device_name.encode() or None,
                                settings.sample_rate, settings.channels, 0,
                                b"selkies-audio-capture")
        if not self._h:
            raise RuntimeError(
                f"pulse capture open failed (device={settings.device_name!r})")

    def read_chunk(self, frames: int) -> Optional[np.ndarray]:
        buf = np.empty(frames * self.channels, np.int16)
        rc = self._lib.sa_pa_read(self._h, buf, buf.nbytes)
        return buf if rc == 0 else None

    def close(self) -> None:
        if self._h:
            self._lib.sa_pa_free(self._h)
            self._h = None


class SyntheticTone(PcmSource):
    """Deterministic sine source, real-time paced (tests / headless)."""

    def __init__(self, settings: AudioCaptureSettings, freq: float = 440.0,
                 amplitude: float = 0.3, realtime: bool = True) -> None:
        self.rate = settings.sample_rate
        self.channels = settings.channels
        self.freq = freq
        self.amp = amplitude
        self.realtime = realtime
        self._t = 0

    def read_chunk(self, frames: int) -> Optional[np.ndarray]:
        if self.realtime:
            time.sleep(frames / self.rate)
        n = np.arange(self._t, self._t + frames)
        self._t += frames
        wave = np.sin(2 * np.pi * self.freq * n / self.rate) * self.amp
        pcm = (wave * 32767).astype(np.int16)
        return np.repeat(pcm, self.channels)


class SilenceSource(PcmSource):
    """All-zero source (exercises the silence gate)."""

    def __init__(self, settings: AudioCaptureSettings,
                 realtime: bool = True) -> None:
        self.rate = settings.sample_rate
        self.channels = settings.channels
        self.realtime = realtime

    def read_chunk(self, frames: int) -> Optional[np.ndarray]:
        if self.realtime:
            time.sleep(frames / self.rate)
        return np.zeros(frames * self.channels, np.int16)


def open_source(settings: AudioCaptureSettings) -> PcmSource:
    """Best available source: Pulse monitor, else a silent synthetic feed
    (keeps the pipeline alive on hosts with no audio server)."""
    if pulse_available():
        try:
            return PulseSource(settings)
        except RuntimeError as e:
            logger.warning("pulse capture unavailable (%s); using silence", e)
    return SilenceSource(settings)


# Reference pcmflux gates chunks whose peak stays under a small threshold;
# hangover keeps a few trailing chunks so decoders ring down naturally.
SILENCE_THRESHOLD = 192       # of 32767 peak
SILENCE_HANGOVER_CHUNKS = 25  # 500 ms at 20 ms chunks


class AudioCapture:
    """Capture thread: source → silence gate → Opus → callback(bytes).

    The callback runs on the capture thread; callers marshal into asyncio
    themselves (same contract as the reference's C callback,
    selkies.py:939-952).
    """

    def __init__(self, settings: AudioCaptureSettings,
                 callback: Callable[[bytes], None],
                 source: Optional[PcmSource] = None) -> None:
        self.settings = settings
        self.callback = callback
        self.source = source if source is not None else open_source(settings)
        self._enc: Optional[OpusEncoder] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.chunks_encoded = 0
        self.chunks_gated = 0

    def start_capture(self) -> None:
        if self._thread is not None:
            return
        self._enc = OpusEncoder(
            self.settings.sample_rate, self.settings.channels,
            self.settings.opus_bitrate, vbr=self.settings.use_vbr)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="selkies-audio-capture", daemon=True)
        self._thread.start()

    def stop_capture(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
            if thread.is_alive():
                # The thread is wedged in a blocking source read; freeing the
                # encoder under it would be a use-after-free.  Leak both and
                # let the thread exit on its next wakeup (it checks _stop).
                logger.warning("capture thread did not stop in 2 s; "
                               "leaking encoder/source until it exits")
                return
        if self._enc is not None:
            self._enc.close()
            self._enc = None
        self.source.close()

    def _run(self) -> None:
        frames = self.settings.chunk_frames
        enc = self._enc  # local ref: survives stop_capture() racing us
        quiet_for = SILENCE_HANGOVER_CHUNKS  # start gated until sound appears
        while not self._stop.is_set():
            pcm = self.source.read_chunk(frames)
            if pcm is None:
                time.sleep(0.01)
                continue
            if self._stop.is_set():
                break
            if self.settings.use_silence_gate:
                peak = int(np.abs(pcm).max()) if pcm.size else 0
                quiet_for = 0 if peak >= SILENCE_THRESHOLD else quiet_for + 1
                if quiet_for > SILENCE_HANGOVER_CHUNKS:
                    self.chunks_gated += 1
                    continue
            try:
                packet = enc.encode(pcm)
            except RuntimeError as e:
                logger.error("opus encode failed: %s", e)
                continue
            self.chunks_encoded += 1
            self.callback(packet)
