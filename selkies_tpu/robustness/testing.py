"""In-process client stand-in for driving the data server without sockets.

``data_server._ws_broadcast`` duck-types on ``send_nowait``, and
``ws_handler`` only needs async ``send``/``close`` plus async iteration —
so this one class is a full client as far as the server is concerned. It
is the canonical fake for the fault-injection tier-1 tests
(tests/test_robustness.py) and the chaos harness (tools/chaos_run.py);
keeping it in one place keeps the duck-typed surface from silently
diverging between the two.
"""

from __future__ import annotations

import asyncio
from typing import List


class InProcessClient:
    """Just enough websocket surface for ws_handler + _ws_broadcast."""

    def __init__(self) -> None:
        self.sent: List = []
        self.closed = False
        self._incoming: asyncio.Queue = asyncio.Queue()

    # -- server → client ---------------------------------------------------

    async def send(self, message) -> None:
        if self.closed:
            raise ConnectionError("closed")
        self.sent.append(message)

    def send_nowait(self, message) -> None:
        if not self.closed:
            self.sent.append(message)

    # -- client → server ---------------------------------------------------

    def feed(self, message) -> None:
        """Queue a client message for the handler's async iteration."""
        self._incoming.put_nowait(message)

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._incoming.put_nowait(None)

    # -- inspection helpers ------------------------------------------------

    def binary(self) -> List[bytes]:
        return [m for m in self.sent if isinstance(m, (bytes, bytearray))]

    def texts(self) -> List[str]:
        return [m for m in self.sent if isinstance(m, str)]

    def n_frames(self) -> int:
        return len(self.binary())

    # -- async iteration (ws_handler's `async for message in websocket`) ---

    def __aiter__(self) -> "InProcessClient":
        return self

    async def __anext__(self):
        m = await self._incoming.get()
        if m is None:
            raise StopAsyncIteration
        return m
