"""In-process stand-ins for driving the data server without sockets or jax.

``data_server._ws_broadcast`` duck-types on ``send_nowait``, and
``ws_handler`` only needs async ``send``/``close`` plus async iteration —
so :class:`InProcessClient` is a full client as far as the server is
concerned. It is the canonical fake for the fault-injection tier-1 tests
(tests/test_robustness.py), the chaos harness (tools/chaos_run.py), and
the swarm churn harness (tools/swarm_run.py); keeping it in one place
keeps the duck-typed surface from silently diverging between consumers.

:class:`FakeMeshEncoder` is the device-free counterpart on the encoder
side: it speaks the mesh encoder surface the coordinator drives
(``dispatch``/``harvest``/``fetch_ready``/``reset_session``/
``force_keyframe``), so scheduler behavior — dynamic lanes, slot health,
quarantine/migration, churn — is testable at hundreds of sessions
without compiling a single device program.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import List


class InProcessClient:
    """Just enough websocket surface for ws_handler + _ws_broadcast."""

    def __init__(self) -> None:
        self.sent: List = []
        self.closed = False
        self._incoming: asyncio.Queue = asyncio.Queue()

    # -- server → client ---------------------------------------------------

    async def send(self, message) -> None:
        if self.closed:
            raise ConnectionError("closed")
        self.sent.append(message)

    def send_nowait(self, message) -> None:
        if not self.closed:
            self.sent.append(message)

    # -- client → server ---------------------------------------------------

    def feed(self, message) -> None:
        """Queue a client message for the handler's async iteration."""
        self._incoming.put_nowait(message)

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._incoming.put_nowait(None)

    # -- inspection helpers ------------------------------------------------

    def binary(self) -> List[bytes]:
        return [m for m in self.sent if isinstance(m, (bytes, bytearray))]

    def texts(self) -> List[str]:
        return [m for m in self.sent if isinstance(m, str)]

    def n_frames(self) -> int:
        return len(self.binary())

    # -- async iteration (ws_handler's `async for message in websocket`) ---

    def __aiter__(self) -> "InProcessClient":
        return self

    async def __anext__(self):
        m = await self._incoming.get()
        if m is None:
            raise StopAsyncIteration
        return m


# ---------------------------------------------------------------------------
# mesh-encoder stand-in (scheduler tests / swarm harness)


@dataclass
class FakeStripe:
    """Just enough stripe surface for the wire packer (no ``annexb``
    attribute → packs as a JPEG stripe)."""

    y_start: int = 0
    height: int = 16
    jpeg: bytes = b"\xff\xd8\xfa\x4b\x45\xff\xd9"
    is_paintover: bool = False


class FakeMeshEncoder:
    """Mesh-encoder lookalike: one tiny stripe per submitted session
    (``n_shards`` of them for an SFE-shaped lane — the torn-access-unit
    tests assert a harvested frame always carries ALL of its shard
    stripes or none).

    ``fail_dispatches`` fails that many whole dispatch calls (a lane-level
    fault); slot-scoped faults are injected upstream of dispatch via the
    coordinator's ``mesh.slot_raise`` point, not here. Harvests report a
    ``last_harvest_stages`` fetch/concat split like the real mesh
    encoders so the coordinator's flight-recorder attribution is
    exercised device-free.
    """

    def __init__(self, n_sessions: int, width: int = 0, height: int = 0,
                 fail_dispatches: int = 0, n_shards: int = 1) -> None:
        self.n_sessions = int(n_sessions)
        self.width, self.height = width, height
        self.fail_dispatches = int(fail_dispatches)
        self.n_shards = max(1, int(n_shards))
        self.dispatches = 0
        self.resets: List[int] = []
        self.keyframes: List[int] = []
        self.last_harvest_stages = None
        #: tests add session indices here to model encoder-INTERNAL
        #: stripe-job failures (whole-frame containment: harvest returns
        #: an empty AU for them, nothing raises) — reported through
        #: last_failed_sessions so the coordinator charges slot health
        self.fail_sessions: set = set()
        self.last_failed_sessions: frozenset = frozenset()

    def reset_session(self, session: int) -> None:
        self.resets.append(session)

    def force_keyframe(self, session: int) -> None:
        self.keyframes.append(session)

    def dispatch(self, frames):
        if self.fail_dispatches > 0:
            self.fail_dispatches -= 1
            raise RuntimeError("injected mesh dispatch failure")
        self.dispatches += 1
        return [f is not None for f in frames]

    def fetch_ready(self, pending) -> bool:
        return True

    def harvest(self, pending):
        out = [
            [FakeStripe(y_start=16 * k, height=16)
             for k in range(self.n_shards)] if took else []
            for took in pending]
        failed = {n for n, took in enumerate(pending)
                  if took and n in self.fail_sessions}
        for n in failed:
            out[n] = []                      # withheld whole, never torn
        self.last_failed_sessions = frozenset(failed)
        session_bytes = [sum(len(st.jpeg) for st in s) for s in out]
        self.last_harvest_stages = {
            "fetch_ms": 0.2, "concat_ms": 0.1,
            "per_shard_fetch_ms": [0.2 / self.n_shards] * self.n_shards}
        return out, session_bytes
