"""Deterministic fault injection for the capture→encode→transport path.

The supervision layer (``supervisor.py``, ``ladder.py``) only earns trust if
its recovery behavior is *provable*: tier-1 tests must be able to crash a
capture loop, stall a fetch, or drop a websocket on demand and then assert
restart counts and ladder transitions. This module provides named fault
points that are checked at the real call sites (``data_server._capture_loop``
and friends), armed either programmatically or from the
``SELKIES_TPU_FAULTS`` environment variable / ``tpu_faults`` setting.

Grammar (comma-separated entries)::

    SELKIES_TPU_FAULTS="capture.raise*2,fetch.hang*1=30,ws.drop"

    entry   := point ['*' count] ['=' arg]
    point   := dotted fault-point name (see POINTS)
    count   := how many checks fire before the point disarms (default: 1)
    arg     := point-specific parameter (hang points: seconds, default 3600)

Fault points and their semantics at the call site:

==================  =======================================================
``capture.raise``   capture loop raises at the top of its tick
``capture.stall``   capture loop hangs (await) before reading the source —
                    no frame progress, so the watchdog must trip
``encode.raise``    the encoder submit call site raises (models a device /
                    entropy failure; classified as an EncoderFault, which
                    steps the degradation ladder)
``fetch.hang``      the poll/fetch call site hangs — stalled D2H transfer
``ws.drop``         the display's websocket is closed mid-stream
``mesh.tick_raise`` the mesh coordinator's whole tick raises (every lane
                    skips this tick; the worker backs off and survives)
``mesh.slot_raise`` ONE slot's dispatch is failed at frame-take time
                    (arg: ``lane:slot`` or a bare slot index; empty =
                    first checked slot) — the cohabiting sessions' tick
                    proceeds, so chaos can prove slot faults never
                    become mesh faults
==================  =======================================================

A check on a disarmed point is a dict lookup — the production cost of the
harness is negligible, and a server with no faults armed never allocates.
"""

from __future__ import annotations

import asyncio
import logging
import re
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger("selkies_tpu.robustness")

#: the known fault points; arming an unknown name is an error so a typo in a
#: chaos spec fails loudly instead of silently never firing
POINTS = (
    "capture.raise",
    "capture.stall",
    "encode.raise",
    "fetch.hang",
    "ws.drop",
    "mesh.tick_raise",
    "mesh.slot_raise",
)

_ENTRY_RE = re.compile(
    r"^(?P<name>[a-z0-9_.]+)(?:\*(?P<count>\d+))?(?:=(?P<arg>.+))?$")

#: default hang duration — long enough that only a watchdog ends it
DEFAULT_HANG_S = 3600.0


class FaultInjected(RuntimeError):
    """Raised by a ``*.raise`` fault point."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault: {point}")
        self.point = point


class FaultInjector:
    """Thread-safe registry of armed fault points.

    One injector per :class:`~selkies_tpu.server.data_server.DataStreamingServer`
    (constructed from ``settings.tpu_faults``) keeps tests isolated; tools
    arm points on a live server via ``server.faults.arm(...)``.
    """

    def __init__(self, spec: str = "") -> None:
        self._lock = threading.Lock()
        #: point -> (remaining_count, arg)
        self._armed: Dict[str, Tuple[int, Optional[str]]] = {}
        #: point -> times fired (monotonic, survives disarm; test assertions)
        self.fired: Dict[str, int] = {}
        if spec:
            self.arm_spec(spec)

    # -- arming ------------------------------------------------------------

    def arm_spec(self, spec: str) -> None:
        """Arm every entry of a ``SELKIES_TPU_FAULTS``-grammar string."""
        for entry in str(spec).split(","):
            entry = entry.strip()
            if not entry:
                continue
            m = _ENTRY_RE.match(entry)
            if not m:
                raise ValueError(f"bad fault spec entry {entry!r}")
            count = int(m.group("count")) if m.group("count") else 1
            self.arm(m.group("name"), times=count, arg=m.group("arg"))

    def arm(self, point: str, times: int = 1,
            arg: Optional[str] = None) -> None:
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {list(POINTS)}")
        with self._lock:
            self._armed[point] = (max(1, int(times)), arg)
        logger.warning("fault point armed: %s (times=%d, arg=%r)",
                       point, times, arg)

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._armed.clear()
            else:
                self._armed.pop(point, None)

    def reset(self) -> None:
        """Disarm everything and clear fire counters (test teardown)."""
        with self._lock:
            self._armed.clear()
            self.fired.clear()

    @property
    def armed(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._armed)

    # -- call-site checks --------------------------------------------------

    def should_fire(self, point: str) -> bool:
        """Consume one firing of ``point`` if armed (decrements the count)."""
        arg_unused, fired = self._take(point)
        return fired

    def should_fire_for(self, point: str, *keys) -> bool:
        """Consume one firing only when the armed arg targets one of
        ``keys`` (a call site may answer to several identities — e.g. a
        mesh slot is both ``lane:slot`` and its bare slot index).

        A keyed fault point (``mesh.slot_raise=0:3``) fires only at the
        call site checking that key; an argless arming fires for the
        first site checked. A non-matching check leaves the point armed —
        it neither fires nor consumes."""
        with self._lock:
            entry = self._armed.get(point)
            if entry is None:
                return False
            remaining, arg = entry
            if arg is not None and str(arg) not in {str(k) for k in keys}:
                return False
            if remaining <= 1:
                self._armed.pop(point, None)
            else:
                self._armed[point] = (remaining - 1, arg)
            self.fired[point] = self.fired.get(point, 0) + 1
        logger.warning("fault point fired: %s (keys=%s, #%d)", point, keys,
                       self.fired[point])
        return True

    def maybe_raise(self, point: str) -> None:
        """Raise :class:`FaultInjected` if ``point`` is armed."""
        _, fired = self._take(point)
        if fired:
            raise FaultInjected(point)

    async def maybe_hang(self, point: str) -> None:
        """Hang (cancellable await) if ``point`` is armed; the arg is the
        hang duration in seconds (default: effectively forever)."""
        arg, fired = self._take(point)
        if fired:
            duration = self._hang_duration(point, arg)
            await asyncio.sleep(duration)

    def maybe_hang_sync(self, point: str) -> None:
        """Thread-context counterpart of :meth:`maybe_hang` for call
        sites that run off the event loop (the async encode driver's
        fetch/harvest site): a plain blocking sleep, so chaos can stall
        the driver thread exactly where a wedged D2H transfer would."""
        arg, fired = self._take(point)
        if fired:
            import time

            duration = self._hang_duration(point, arg)
            time.sleep(duration)

    @staticmethod
    def _hang_duration(point: str, arg: Optional[str]) -> float:
        try:
            duration = float(arg) if arg else DEFAULT_HANG_S
        except ValueError:
            duration = DEFAULT_HANG_S
        logger.warning("fault %s: hanging %.1fs", point, duration)
        return duration

    def _take(self, point: str) -> Tuple[Optional[str], bool]:
        with self._lock:
            entry = self._armed.get(point)
            if entry is None:
                return None, False
            remaining, arg = entry
            if remaining <= 1:
                self._armed.pop(point, None)
            else:
                self._armed[point] = (remaining - 1, arg)
            self.fired[point] = self.fired.get(point, 0) + 1
        logger.warning("fault point fired: %s (#%d)", point,
                       self.fired[point])
        return arg, True
