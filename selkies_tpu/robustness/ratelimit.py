"""Hostile-client armor for the wire edge: token buckets, error budgets,
and bounded send queues.

PR 2 made the *inside* of a session fault-tolerant; this module hardens
the *edge* (docs/hardening.md). Everything here is pure, clock-injected
policy so it unit-tests without asyncio or sockets; the server wires it
to real connections in ``server/data_server.py``:

* :class:`TokenBucket` — the standard refill-rate/burst limiter, used per
  connection and per message class;
* :class:`ConnectionGuard` — one per websocket: a bucket per message
  class plus a slow-refilling protocol-error budget whose exhaustion
  means "this client is hostile, close it";
* :class:`BoundedSendQueue` — per-client fan-out queue with
  drop-oldest-video / never-drop-control semantics and a sustained-
  overflow eviction verdict, so one stalled consumer costs itself, not
  the capture loop or its healthy co-viewers.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

__all__ = [
    "DEFAULT_LIMITS", "MESSAGE_CLASSES", "UPLOAD_VERB_COST",
    "BoundedSendQueue", "ConnectionGuard", "TokenBucket", "classify_verb",
    "parse_limit_spec",
]

#: message classes the edge meters independently. Units: messages/s for
#: the verb classes, bytes/s for the binary-plane classes.
MESSAGE_CLASSES = ("input", "control", "settings", "resize", "upload", "mic")

#: per-class (refill_per_s, burst) defaults. Rationale:
#:  input    mouse-move streams run 100-250 msg/s; 1000/s leaves honest
#:           clients untouched and caps a flood at ~1k handler calls/s
#:  control  CLIENT_FRAME_ACK arrives once per decoded frame (<=120/s)
#:  settings SETTINGS re-negotiation (and cmd) is a human-scale event;
#:           every accepted one can restart pipelines
#:  resize   resize observers fire in bursts while dragging; the debounced
#:           reconfigure absorbs the cost, this just bounds parse work
#:  upload   file chunks (bytes/s) — a saturated 500 Mb/s link
#:  mic      48 kHz stereo s16 PCM is ~192 KiB/s; 1 MiB/s is generous
DEFAULT_LIMITS: Dict[str, Tuple[float, float]] = {
    "input": (1000.0, 2000.0),
    "control": (300.0, 900.0),
    "settings": (1.0, 5.0),
    "resize": (10.0, 40.0),
    "upload": (64e6, 128e6),
    "mic": (1e6, 4e6),
}

#: client verbs that are cheap bookkeeping, not work triggers
_CONTROL_VERBS = frozenset({
    "CLIENT_FRAME_ACK", "_f", "_l",
    "SET_NATIVE_CURSOR_RENDERING",
})

#: stateful upload verbs: DROPPING one corrupts the transfer (a lost END
#: leaves the fd open and splices the next file into it), so like upload
#: bytes they are PACED through the upload bucket, never dropped
_UPLOAD_VERBS = frozenset({
    "FILE_UPLOAD_START", "FILE_UPLOAD_END", "FILE_UPLOAD_ERROR",
})

#: nominal byte charge per upload verb against the upload bucket — each
#: START is an open()/makedirs on the server, far heavier than a text
#: parse; 64 KiB bounds file-churn spam to ~rate/64Ki verbs per second
UPLOAD_VERB_COST = 64 * 1024

#: verbs that can (re)start pipelines or spawn processes — human-scale
#: only. START/STOP_VIDEO tear down / rebuild a capture+encode pipeline
#: and START/STOP_AUDIO toggle the shared audio pipeline, so they are as
#: heavy as a SETTINGS renegotiation, not cheap control traffic.
_SETTINGS_VERBS = frozenset({
    "SETTINGS", "cmd",
    "START_VIDEO", "STOP_VIDEO", "START_AUDIO", "STOP_AUDIO",
})

#: verbs that feed the (debounced) display-reconfigure path
_RESIZE_VERBS = frozenset({"r", "s"})


def classify_verb(verb: str) -> str:
    """Map a parsed client verb onto its rate-limit class; everything not
    otherwise classified is input-plane grammar (kd/ku/m/js/clipboard/…).
    The ``upload`` class is special at the call site: paced, not dropped."""
    if verb in _SETTINGS_VERBS:
        return "settings"
    if verb in _RESIZE_VERBS:
        return "resize"
    if verb in _CONTROL_VERBS:
        return "control"
    if verb in _UPLOAD_VERBS:
        return "upload"
    return "input"


def parse_limit_spec(spec: str) -> Dict[str, Tuple[float, float]]:
    """Parse the ``rate_limits`` setting: ``class=rate[:burst],...``
    overriding :data:`DEFAULT_LIMITS` (burst defaults to 2x rate).

    ``settings=2:10,mic=512000`` → settings 2/s burst 10, mic 512 KB/s
    burst 1 MB. Unknown classes raise so a typo fails loudly.
    """
    limits = dict(DEFAULT_LIMITS)
    for entry in str(spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rates = entry.partition("=")
        name = name.strip()
        if not sep or name not in limits:
            raise ValueError(
                f"bad rate_limits entry {entry!r}; classes: "
                f"{list(MESSAGE_CLASSES)}, grammar class=rate[:burst]")
        rate_s, _, burst_s = rates.partition(":")
        rate = float(rate_s)
        burst = float(burst_s) if burst_s else 2.0 * rate
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate_limits entry {entry!r} must be positive")
        limits[name] = (rate, burst)
    return limits


class TokenBucket:
    """Classic token bucket: ``burst`` capacity refilled at ``rate``/s."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._at = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._at) * self.rate)
        self._at = now

    def try_take(self, n: float = 1.0) -> bool:
        """Consume ``n`` tokens if available; False means rate-limited."""
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def take_with_debt(self, n: float = 1.0) -> float:
        """Always consume ``n`` (tokens may go negative) and return the
        seconds the caller should pace before reading more — the pacing
        variant for byte planes where dropping corrupts the stream
        (uploads): sleeping in the handler propagates straight into TCP
        backpressure on the sender."""
        self._refill()
        self._tokens -= n
        if self._tokens >= 0:
            return 0.0
        return -self._tokens / self.rate

    @property
    def tokens(self) -> float:
        """Current level (refreshes first; for tests/introspection)."""
        self._refill()
        return self._tokens


class ConnectionGuard:
    """Per-connection protocol armor: class buckets + an error budget.

    The error budget is itself a token bucket (capacity
    ``error_budget``, refilled at ``error_refill_per_s``) so a long-lived
    session forgives the occasional glitch while a malformed-message
    flood still exhausts it quickly. :meth:`record_error` returns True
    when the budget is exhausted — the caller should send
    ``KILL protocol_abuse`` and close that one socket.
    """

    def __init__(self, limits: Optional[Dict[str, Tuple[float, float]]] = None,
                 error_budget: int = 25, error_refill_per_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        limits = limits or DEFAULT_LIMITS
        self._buckets = {
            cls: TokenBucket(rate, burst, clock=clock)
            for cls, (rate, burst) in limits.items()
        }
        self._errors = TokenBucket(error_refill_per_s,
                                   max(1.0, float(error_budget)), clock=clock)
        self.errors_total = 0

    def allow(self, cls: str, n: float = 1.0) -> bool:
        """Charge ``n`` units (messages or bytes) against ``cls``; False
        means the message should be dropped. Counting dropped messages is
        the caller's job (one accounting site: the server's edge stats +
        ``rate_limited_total{klass}``).

        ``n`` is clamped to the bucket's burst: the bucket meters *rate*,
        size gating belongs to the explicit caps (``max_mic_chunk_kb``,
        ``max_upload_mb``) — otherwise one unit larger than the burst
        could never be admitted at any send rate."""
        bucket = self._buckets.get(cls)
        return bucket is None or bucket.try_take(min(n, bucket.burst))

    def throttle(self, cls: str, n: float = 1.0,
                 max_wait_s: float = 30.0) -> float:
        """Pacing variant of :meth:`allow` for streams where dropping
        corrupts state (file uploads): always accepts, returns how long
        the caller should sleep before reading more (0.0 = no debt)."""
        bucket = self._buckets.get(cls)
        if bucket is None:
            return 0.0
        return min(max_wait_s, bucket.take_with_debt(n))

    def record_error(self) -> bool:
        """Count one protocol error; True → budget exhausted, kill."""
        self.errors_total += 1
        return not self._errors.try_take(1.0)


class BoundedSendQueue:
    """Per-client fan-out queue: drop-oldest-video, never-drop-control.

    Video (binary media) entries are bounded at ``max_video``; offering
    past the bound drops the *oldest* queued video message so a slow
    consumer always converges toward the live edge of the stream.
    Control (text) messages are never dropped — they are small, rare,
    and semantically load-bearing (KILL, PIPELINE_RESETTING, settings).

    Eviction verdict: the first drop of a saturated stretch stamps
    ``overflow_since``; draining back under half capacity clears it. A
    consumer saturated for ``evict_after_s`` (or whose control backlog
    exceeds 10x the video bound — it is not reading *anything*) should
    be evicted (:attr:`should_evict`).
    """

    def __init__(self, max_video: int = 120, evict_after_s: float = 4.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.max_video = max(1, int(max_video))
        self.evict_after_s = float(evict_after_s)
        self._clock = clock
        self._q: Deque[Tuple[object, bool]] = deque()
        self.video_len = 0
        self.dropped_video_total = 0
        self.overflow_since: Optional[float] = None
        #: optional hook called with each video message discarded by the
        #: drop-oldest policy — the flight recorder closes a dropped
        #: frame's span through it (never raises into the offer path)
        self.on_drop: Optional[Callable[[object], None]] = None

    def __len__(self) -> int:
        return len(self._q)

    def offer(self, message, control: bool = False) -> bool:
        """Enqueue; returns False when an old video message was dropped
        to make room (the new message itself is always queued)."""
        if control:
            self._q.append((message, True))
            return True
        dropped = False
        if self.video_len >= self.max_video:
            for i, (msg, ctl) in enumerate(self._q):
                if not ctl:
                    del self._q[i]
                    self.video_len -= 1
                    self.dropped_video_total += 1
                    dropped = True
                    if self.overflow_since is None:
                        self.overflow_since = self._clock()
                    if self.on_drop is not None:
                        try:
                            self.on_drop(msg)
                        except Exception:
                            pass
                    break
        self._q.append((message, False))
        self.video_len += 1
        return not dropped

    def pop(self):
        """Next message in FIFO order, or None when empty."""
        if not self._q:
            return None
        message, control = self._q.popleft()
        if not control:
            self.video_len -= 1
        if (self.overflow_since is not None
                and self.video_len <= self.max_video // 2):
            self.overflow_since = None   # consumer caught back up
        return message

    @property
    def should_evict(self) -> bool:
        if len(self._q) - self.video_len > 10 * self.max_video:
            return True
        return (self.overflow_since is not None
                and self._clock() - self.overflow_since >= self.evict_after_s)
