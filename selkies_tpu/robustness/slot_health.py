"""Per-slot fault domains for the mesh session scheduler.

A batch lane (``parallel/coordinator.py``) packs several sessions into one
SPMD dispatch, which makes the *slot* — one session's position in the
batch — the natural fault domain: a slot that keeps surfacing errors
(failed dispatch/harvest ticks attributed to it, injected slot faults)
poisons every tick it rides, so the scheduler must stop trusting it and
move its session somewhere healthy. This module is the pure policy half:
error/latency EWMAs per slot, a sickness verdict, and the quarantine set.
The coordinator owns the mechanism (live migration, lane recycling).

Clock-injected and lock-free by design: the coordinator calls it under
its own lock, and tests drive it with a fake clock (the same discipline
as :mod:`.ratelimit`).

Decay model: the error score is a leaky accumulator with half-life
``window_s`` — ``record_error`` adds 1, and the score halves every
window. ``sick_errors`` is therefore "roughly this many errors within
the recent window", not a lifetime count: a slot that faulted a lot last
minute but is clean now converges back to healthy instead of being
condemned by history. Quarantine, by contrast, is sticky for the life of
the lane: once a slot is quarantined it never returns to the free list —
the lane itself is retired (and rebuilt on demand) once it drains, which
is how a chronically sick fault domain gets recycled.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Set

__all__ = ["SlotHealth"]


class SlotHealth:
    """Error/latency EWMAs and quarantine verdicts for one lane's slots."""

    def __init__(
        self,
        n_slots: int,
        *,
        sick_errors: float = 3.0,
        window_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.n_slots = int(n_slots)
        self.sick_errors = max(0.5, float(sick_errors))
        self.window_s = max(0.1, float(window_s))
        self._clock = clock
        now = clock()
        #: decayed error score per slot (≈ errors within the last window)
        self._score: List[float] = [0.0] * n_slots
        self._score_at: List[float] = [now] * n_slots
        #: EWMA of per-tick harvest latency attributed to this slot (ms);
        #: observability only — latency does not feed the sickness verdict
        #: (a slow lane is a capacity problem, not a fault domain)
        self.latency_ewma_ms: List[float] = [0.0] * n_slots
        #: lifetime error count per slot (monotonic; health feed / tests)
        self.errors_total: List[int] = [0] * n_slots
        #: slots removed from service for the life of the lane
        self.quarantined: Set[int] = set()

    # -- recording ---------------------------------------------------------

    def _decayed(self, slot: int) -> float:
        now = self._clock()
        dt = now - self._score_at[slot]
        if dt > 0:
            self._score[slot] *= 0.5 ** (dt / self.window_s)
            self._score_at[slot] = now
        return self._score[slot]

    def record_error(self, slot: int) -> None:
        self._decayed(slot)
        self._score[slot] += 1.0
        self.errors_total[slot] += 1

    def record_ok(self, slot: int, latency_ms: float = 0.0) -> None:
        self._decayed(slot)
        if latency_ms > 0.0:
            prev = self.latency_ewma_ms[slot]
            self.latency_ewma_ms[slot] = (
                latency_ms if prev == 0.0 else 0.8 * prev + 0.2 * latency_ms)

    # -- verdicts ----------------------------------------------------------

    def score(self, slot: int) -> float:
        return self._decayed(slot)

    def is_sick(self, slot: int) -> bool:
        """True when the slot's recent error mass crossed the threshold
        (quarantined slots are no longer *sick* — they are out of
        service, which is a different answer)."""
        return (slot not in self.quarantined
                and self._decayed(slot) >= self.sick_errors)

    def quarantine(self, slot: int) -> None:
        self.quarantined.add(slot)

    # -- export ------------------------------------------------------------

    def state(self) -> Dict[str, object]:
        """Health snapshot for the ``system_health`` feed / stats()."""
        return {
            "scores": [round(self._decayed(s), 2)
                       for s in range(self.n_slots)],
            "latency_ewma_ms": [round(v, 2) for v in self.latency_ewma_ms],
            "errors_total": list(self.errors_total),
            "quarantined": sorted(self.quarantined),
        }
