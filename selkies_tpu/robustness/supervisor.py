"""Async task supervision: bounded-backoff restarts plus a frame watchdog.

The reference desktop stack keeps a session alive across encoder hiccups and
capture stalls (SURVEY §0); here the equivalent is a :class:`Supervisor`
wrapped around each display's capture and backpressure loops: a crash
restarts the loop with exponential backoff and jitter, a restart budget over
a sliding window turns a crash loop into a terminal ``failed`` state instead
of a log-spamming hot loop, and an optional frame-deadline watchdog cancels
and restarts a child that stops making progress (stalled capture or D2H
fetch) even though it never raised.

The supervised coroutine calls :meth:`Supervisor.beat` whenever it makes
progress; everything else is driven by :meth:`run`, which is itself the
asyncio task the owner creates/cancels.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Any, Callable, Coroutine, Dict, List, Optional

logger = logging.getLogger("selkies_tpu.robustness")

#: supervisor lifecycle states
IDLE, RUNNING, BACKOFF, FAILED, STOPPED = (
    "idle", "running", "backoff", "failed", "stopped")


def backoff_delay(attempt: int, base_s: float, cap_s: float,
                  jitter: float = 0.0,
                  rng: Optional[random.Random] = None) -> float:
    """Capped exponential backoff: ``min(cap, base * 2**(attempt-1))``
    scaled by ``1 + jitter*rand()``. The one formula for every retry site
    (supervisor restarts, server bind retries, mesh tick backoff)."""
    attempt = max(1, int(attempt))
    delay = min(cap_s, base_s * (2 ** min(attempt - 1, 32)))
    if jitter:
        delay *= 1.0 + jitter * (rng or random).random()
    return delay


class Supervisor:
    """Restart an async task factory until cancelled, failed, or stopped.

    Restart policy
    --------------
    * child raised → restart after ``min(max_delay, base_delay * 2**n)``
      scaled by ``1 + jitter*rand()``, where n counts recent failures;
    * watchdog tripped (no :meth:`beat` within ``watchdog_timeout_s``) →
      child is cancelled and restarted like a failure;
    * child returned cleanly → restart after ``base_delay`` without
      counting against the budget (the capture loop returns cleanly on a
      deliberate reconfigure, e.g. a degradation-ladder rung change);
    * more than ``max_restarts`` failure/watchdog restarts within
      ``restart_window_s`` → terminal :data:`FAILED` state.

    ``on_event(kind, info)`` fires with kinds ``"failure"`` (info: the
    exception), ``"watchdog"``, ``"clean"``, ``"restart"``, ``"failed"`` —
    the owner uses it for metrics, the degradation ladder, and health
    broadcasts. Callback errors are logged, never propagated.
    """

    def __init__(
        self,
        name: str,
        factory: Callable[[], Coroutine],
        *,
        max_restarts: int = 6,
        restart_window_s: float = 60.0,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        jitter: float = 0.25,
        watchdog_timeout_s: Optional[float] = None,
        on_event: Optional[Callable[[str, Any], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.name = name
        self.factory = factory
        self.max_restarts = int(max_restarts)
        self.restart_window_s = float(restart_window_s)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.watchdog_timeout_s = watchdog_timeout_s
        self.on_event = on_event
        self._clock = clock
        self._rng = rng or random.Random()

        self.state = IDLE
        self.restarts_total = 0
        self.failures_total = 0
        self.watchdog_restarts_total = 0
        self.clean_restarts_total = 0
        self.last_error: Optional[str] = None
        self._beat = clock()
        self._failure_times: List[float] = []

    # -- progress heartbeat ------------------------------------------------

    def beat(self) -> None:
        """Mark progress; the watchdog measures staleness against this."""
        self._beat = self._clock()

    def forgive(self) -> None:
        """Clear the failure budget.

        The owner calls this when it took a corrective action in response
        to a failure (e.g. a degradation-ladder step-down): subsequent
        failures should be judged against the NEW configuration, not
        accumulate on top of the dead one — otherwise ladder probe cycles
        burn the budget and terminally fail a display whose degraded rung
        is perfectly healthy."""
        self._failure_times.clear()

    # -- main loop ---------------------------------------------------------

    async def run(self) -> None:
        """Supervise until cancelled (→ ``stopped``) or failed."""
        try:
            while True:
                self._set_state(RUNNING)
                self.beat()
                child = asyncio.ensure_future(self.factory())
                failure: Optional[BaseException] = None
                watchdog = False
                try:
                    failure, watchdog = await self._await_child(child)
                except asyncio.CancelledError:
                    await self._kill(child)
                    self._set_state(STOPPED)
                    raise
                counted = watchdog or failure is not None
                now = self._clock()
                if counted:
                    # charge the budget BEFORE emitting, so an on_event
                    # forgive() (ladder step-down) clears THIS failure too
                    # and the new configuration truly starts fresh
                    self._failure_times = [
                        t for t in self._failure_times
                        if now - t < self.restart_window_s]
                    self._failure_times.append(now)
                if watchdog:
                    self.watchdog_restarts_total += 1
                    self.last_error = "watchdog: no frame progress within " \
                        f"{self.watchdog_timeout_s:.2f}s"
                    logger.warning("[%s] %s; restarting", self.name,
                                   self.last_error)
                    self._emit("watchdog", None)
                elif failure is not None:
                    self.failures_total += 1
                    self.last_error = repr(failure)
                    logger.error("[%s] supervised task crashed: %r",
                                 self.name, failure)
                    self._emit("failure", failure)
                else:
                    self.clean_restarts_total += 1
                    self._emit("clean", None)

                if counted:
                    if len(self._failure_times) > self.max_restarts:
                        self._set_state(FAILED)
                        logger.error(
                            "[%s] restart budget exhausted (%d within "
                            "%.0fs); giving up", self.name,
                            len(self._failure_times), self.restart_window_s)
                        self._emit("failed", None)
                        return
                    delay = backoff_delay(
                        len(self._failure_times), self.base_delay_s,
                        self.max_delay_s, self.jitter, self._rng)
                else:
                    delay = self.base_delay_s
                self.restarts_total += 1
                self._emit("restart", None)
                self._set_state(BACKOFF)
                await asyncio.sleep(delay)
        except asyncio.CancelledError:
            self._set_state(STOPPED)
            raise

    async def _await_child(self, child: asyncio.Task):
        """Wait for the child to finish, policing the watchdog deadline.
        Returns (failure_exception_or_None, watchdog_tripped)."""
        while True:
            timeout = None
            if self.watchdog_timeout_s is not None:
                timeout = max(0.05, self.watchdog_timeout_s / 4.0)
            done, _ = await asyncio.wait({child}, timeout=timeout)
            if done:
                if child.cancelled():
                    # someone cancelled the child directly; treat like a
                    # clean return — the owner is reconfiguring
                    return None, False
                return child.exception(), False
            if (self.watchdog_timeout_s is not None
                    and self._clock() - self._beat > self.watchdog_timeout_s):
                await self._kill(child)
                return None, True

    @staticmethod
    async def _kill(child: asyncio.Task) -> None:
        child.cancel()
        await asyncio.gather(child, return_exceptions=True)

    # -- bookkeeping -------------------------------------------------------

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state

    def _emit(self, kind: str, info: Any) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(kind, info)
        except Exception:
            logger.exception("[%s] on_event(%s) callback failed",
                             self.name, kind)

    def stats(self) -> Dict:
        return {
            "name": self.name,
            "state": self.state,
            "restarts_total": self.restarts_total,
            "failures_total": self.failures_total,
            "watchdog_restarts_total": self.watchdog_restarts_total,
            "clean_restarts_total": self.clean_restarts_total,
            "last_error": self.last_error,
        }
