"""Session robustness: supervision, graceful degradation, fault injection.

Three cooperating pieces keep a streaming session alive across encoder
hiccups, capture stalls, and client churn (docs/robustness.md):

* :class:`Supervisor` — bounded-backoff restarts with a restart budget and
  a frame-deadline watchdog, wrapped around each display's capture and
  backpressure loops;
* :class:`DegradationLadder` — device → host → jpeg encoder rungs, stepped
  down on repeated :class:`EncoderFault` and probed back up after a clean
  window;
* :class:`FaultInjector` — named fault points checked at the real call
  sites, armed via ``SELKIES_TPU_FAULTS`` so tests prove recovery
  end-to-end instead of assuming it.
"""

from .faults import DEFAULT_HANG_S, POINTS, FaultInjected, FaultInjector
from .ladder import RUNGS, DegradationLadder, EncoderFault
from .supervisor import (BACKOFF, FAILED, IDLE, RUNNING, STOPPED, Supervisor,
                         backoff_delay)
from .testing import InProcessClient

__all__ = [
    "BACKOFF", "DEFAULT_HANG_S", "DegradationLadder", "EncoderFault",
    "FAILED", "FaultInjected", "FaultInjector", "IDLE", "InProcessClient",
    "POINTS", "RUNGS", "RUNNING", "STOPPED", "Supervisor", "backoff_delay",
]
