"""Session robustness: supervision, graceful degradation, fault injection.

Three cooperating pieces keep a streaming session alive across encoder
hiccups, capture stalls, and client churn (docs/robustness.md):

* :class:`Supervisor` — bounded-backoff restarts with a restart budget and
  a frame-deadline watchdog, wrapped around each display's capture and
  backpressure loops;
* :class:`DegradationLadder` — device → host → jpeg encoder rungs, stepped
  down on repeated :class:`EncoderFault` and probed back up after a clean
  window;
* :class:`FaultInjector` — named fault points checked at the real call
  sites, armed via ``SELKIES_TPU_FAULTS`` so tests prove recovery
  end-to-end instead of assuming it.

The wire-edge armor (docs/hardening.md) lives in :mod:`.ratelimit`:
:class:`TokenBucket` / :class:`ConnectionGuard` per-class rate limiting
and error budgets, and :class:`BoundedSendQueue` slow-consumer
isolation — pure clock-injected policy the server wires to real
connections.

The session scheduler's fault-domain policy (docs/scaling.md) lives in
:mod:`.slot_health`: per-slot error EWMAs whose quarantine verdicts
drive the mesh coordinator's live migration; :mod:`.testing` carries the
device-free stand-ins (:class:`InProcessClient`,
:class:`FakeMeshEncoder`) the chaos and swarm harnesses share.
"""

from .faults import DEFAULT_HANG_S, POINTS, FaultInjected, FaultInjector
from .ladder import RUNGS, DegradationLadder, EncoderFault
from .ratelimit import (DEFAULT_LIMITS, MESSAGE_CLASSES, UPLOAD_VERB_COST,
                        BoundedSendQueue, ConnectionGuard, TokenBucket,
                        classify_verb, parse_limit_spec)
from .slot_health import SlotHealth
from .supervisor import (BACKOFF, FAILED, IDLE, RUNNING, STOPPED, Supervisor,
                         backoff_delay)
from .testing import FakeMeshEncoder, FakeStripe, InProcessClient

__all__ = [
    "BACKOFF", "BoundedSendQueue", "ConnectionGuard", "DEFAULT_HANG_S",
    "DEFAULT_LIMITS", "DegradationLadder", "EncoderFault", "FAILED",
    "FakeMeshEncoder", "FakeStripe", "FaultInjected", "FaultInjector",
    "IDLE", "InProcessClient", "MESSAGE_CLASSES", "POINTS", "RUNGS",
    "RUNNING", "STOPPED", "SlotHealth", "Supervisor", "TokenBucket",
    "UPLOAD_VERB_COST", "backoff_delay", "classify_verb",
    "parse_limit_spec",
]
