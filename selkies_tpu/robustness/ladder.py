"""Graceful-degradation ladder for the encoder path.

The encode pipeline has three operating points with strictly decreasing
device dependence (docs/entropy.md describes the entropy tiers):

  rung 0  ``device``  entropy coding on the TPU; D2H is the bitstream
  rung 1  ``host``    transform/quant on device, entropy coding on host
  rung 2  ``jpeg``    JPEG profile with host entropy — the paint-over
                      fallback of last resort (reference parity: the
                      jpeg paint-over path that keeps a session usable
                      when the main encoder misbehaves)

Repeated encoder failures (``EncoderFault``, counted consecutively) step the
ladder DOWN one rung; a clean probe window at a degraded rung steps it back
UP one rung.  The ladder itself is a passive state machine — the capture
loop reads :attr:`rung` when (re)building its encoder and returns cleanly
when the rung changed under it, so every transition takes effect as an
encoder rebuild on the next supervised restart.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List


#: rung order, most capable first; index == degradation level
RUNGS = ("device", "host", "jpeg")


class EncoderFault(RuntimeError):
    """An encoder-path failure (device dispatch, fetch, entropy coding).

    The capture loop wraps exceptions from encoder submit/poll call sites in
    this type so the supervisor can distinguish "the encoder is sick" (step
    the ladder) from "the capture source hiccuped" (just restart).

    ``force_step`` marks overwhelming single-shot evidence (a wedged
    pipeline detected after a long no-progress window): the handler steps
    the ladder immediately via :meth:`DegradationLadder.force_step_down`
    instead of counting toward the consecutive threshold — which
    per-restart submit successes would otherwise keep resetting.
    """

    def __init__(self, message: str, *, force_step: bool = False) -> None:
        super().__init__(message)
        self.force_step = force_step


class DegradationLadder:
    """Consecutive-failure step-down, clean-probe step-up."""

    def __init__(self, fail_threshold: int = 3, probe_after_s: float = 15.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.fail_threshold = max(1, int(fail_threshold))
        self.probe_after_s = float(probe_after_s)
        self._clock = clock
        self._level = 0
        self._consecutive = 0
        self._last_change = clock()
        #: probe-up requires a window clean of ANY failure, not just a
        #: window since the transition — an intermittently failing tier
        #: must keep pushing the probe deadline out
        self._last_failure = clock()
        self.failures_total = 0
        #: transition log, e.g. ["device->host", "host->device"]
        self.transitions: List[str] = []

    @property
    def level(self) -> int:
        return self._level

    @property
    def rung(self) -> str:
        return RUNGS[self._level]

    @property
    def degraded(self) -> bool:
        return self._level > 0

    def record_failure(self) -> bool:
        """Count one encoder failure; True when the ladder stepped down."""
        self.failures_total += 1
        self._consecutive += 1
        self._last_failure = self._clock()
        if (self._consecutive >= self.fail_threshold
                and self._level < len(RUNGS) - 1):
            self._step(self._level + 1)
            return True
        return False

    def force_step_down(self) -> bool:
        """Immediate step-down on overwhelming single-shot evidence.

        A wedged pipeline detected after a long no-progress window IS the
        proof the current tier is sick — routing it through the
        consecutive-failure threshold would let the post-restart submit
        successes reset the count each cycle and the ladder would never
        move. True when a step happened (False at the bottom rung)."""
        self.failures_total += 1
        self._last_failure = self._clock()
        if self._level < len(RUNGS) - 1:
            self._step(self._level + 1)
            return True
        return False

    def record_success(self) -> bool:
        """Count clean progress; True when a probe stepped the ladder up.

        Success clears the consecutive-failure count.  At a degraded rung,
        ``probe_after_s`` of operation clean of BOTH transitions and
        failures is treated as a successful probe and the ladder recovers
        one rung (so a flapping device walks down again via the failure
        threshold, not instantly — hysteresis comes from the two windows).
        """
        self._consecutive = 0
        quiet_since = max(self._last_change, self._last_failure)
        if (self._level > 0
                and self._clock() - quiet_since >= self.probe_after_s):
            self._step(self._level - 1)
            return True
        return False

    def _step(self, level: int) -> None:
        self.transitions.append(f"{RUNGS[self._level]}->{RUNGS[level]}")
        self._level = level
        self._consecutive = 0
        self._last_change = self._clock()

    def state(self) -> Dict:
        return {
            "rung": self.rung,
            "level": self._level,
            "consecutive_failures": self._consecutive,
            "failures_total": self.failures_total,
            "transitions": list(self.transitions),
        }
