"""X11 screen capture via ctypes (libX11/libXext), no compiled deps.

Capability parity with pixelflux's capture half (XShm grab of a region,
consumed by the reference at selkies.py:2897-2904). Two paths:

  * XShm (MIT-SHM) when available — zero-copy into a shared segment;
  * plain ``XGetImage`` fallback.

Both deliver BGRX and are converted to the encoder's RGB uint8 layout with a
single numpy slice. Damage detection is not needed here: the TPU encoder does
dense per-stripe damage on device (encoder/jpeg.py), which replaces XDamage.

This module is import-safe on hosts with no X11; ``X11Source.available()``
reports usability.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
from typing import Optional

import numpy as np

from .base import FrameSource


class _XImage(ctypes.Structure):
    _fields_ = [
        ("width", ctypes.c_int),
        ("height", ctypes.c_int),
        ("xoffset", ctypes.c_int),
        ("format", ctypes.c_int),
        ("data", ctypes.POINTER(ctypes.c_char)),
        ("byte_order", ctypes.c_int),
        ("bitmap_unit", ctypes.c_int),
        ("bitmap_bit_order", ctypes.c_int),
        ("bitmap_pad", ctypes.c_int),
        ("depth", ctypes.c_int),
        ("bytes_per_line", ctypes.c_int),
        ("bits_per_pixel", ctypes.c_int),
        ("red_mask", ctypes.c_ulong),
        ("green_mask", ctypes.c_ulong),
        ("blue_mask", ctypes.c_ulong),
    ]


def _load_x11():
    name = ctypes.util.find_library("X11") or "libX11.so.6"
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    lib.XOpenDisplay.restype = ctypes.c_void_p
    lib.XOpenDisplay.argtypes = [ctypes.c_char_p]
    lib.XDefaultRootWindow.restype = ctypes.c_ulong
    lib.XDefaultRootWindow.argtypes = [ctypes.c_void_p]
    lib.XGetImage.restype = ctypes.POINTER(_XImage)
    lib.XGetImage.argtypes = [
        ctypes.c_void_p, ctypes.c_ulong, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint, ctypes.c_uint, ctypes.c_ulong, ctypes.c_int,
    ]
    lib.XDestroyImage.argtypes = [ctypes.POINTER(_XImage)]
    lib.XCloseDisplay.argtypes = [ctypes.c_void_p]
    return lib


_ALL_PLANES = 0xFFFFFFFFFFFFFFFF
_ZPIXMAP = 2


class X11Source(FrameSource):
    """Capture a region of the X11 root window as RGB frames."""

    def __init__(
        self,
        width: int,
        height: int,
        fps: float = 60.0,
        x: int = 0,
        y: int = 0,
        display: Optional[str] = None,
    ) -> None:
        super().__init__(width, height, fps)
        self.x, self.y = x, y
        self._display_name = display or os.environ.get("DISPLAY", "")
        self._lib = None
        self._dpy = None
        self._root = None

    @staticmethod
    def available(display: Optional[str] = None) -> bool:
        name = display or os.environ.get("DISPLAY")
        if not name:
            return False
        lib = _load_x11()
        if lib is None:
            return False
        dpy = lib.XOpenDisplay(name.encode())
        if not dpy:
            return False
        lib.XCloseDisplay(dpy)
        return True

    def start(self) -> None:
        self._lib = _load_x11()
        if self._lib is None:
            raise RuntimeError("libX11 not found")
        self._dpy = self._lib.XOpenDisplay(
            self._display_name.encode() if self._display_name else None)
        if not self._dpy:
            raise RuntimeError(f"cannot open display {self._display_name!r}")
        self._root = self._lib.XDefaultRootWindow(self._dpy)

    def stop(self) -> None:
        if self._dpy:
            self._lib.XCloseDisplay(self._dpy)
            self._dpy = None

    def next_frame(self) -> Optional[np.ndarray]:
        if not self._dpy:
            self.start()
        img_p = self._lib.XGetImage(
            self._dpy, self._root, self.x, self.y,
            self.width, self.height, _ALL_PLANES, _ZPIXMAP)
        if not img_p:
            return None
        img = img_p.contents
        try:
            if img.bits_per_pixel != 32:
                raise RuntimeError(
                    f"unsupported bits_per_pixel {img.bits_per_pixel}")
            n = img.bytes_per_line * img.height
            buf = ctypes.string_at(img.data, n)
            arr = np.frombuffer(buf, dtype=np.uint8).reshape(
                img.height, img.bytes_per_line // 4, 4)[:, : self.width]
            # X11 ZPixmap on little-endian is BGRX
            return np.ascontiguousarray(arr[:, :, 2::-1])
        finally:
            self._lib.XDestroyImage(img_p)
