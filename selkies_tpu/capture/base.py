"""Frame-source interface.

The reference's capture is pixelflux's XShm+XDamage C++ thread delivering
encoded stripes via callback (consumed at selkies.py:2897-2904). Here capture
and encode are decoupled: a :class:`FrameSource` yields raw RGB frames; the
capture manager feeds them to the TPU encoder. The synthetic source is the
deterministic "fake device layer" the test strategy calls for (SURVEY.md §4).
"""

from __future__ import annotations

import abc
from typing import Optional

import numpy as np


class FrameSource(abc.ABC):
    """Produces uint8 RGB frames of a fixed geometry."""

    def __init__(self, width: int, height: int, fps: float = 60.0) -> None:
        self.width = width
        self.height = height
        self.fps = fps

    @abc.abstractmethod
    def next_frame(self) -> Optional[np.ndarray]:
        """The next [H, W, 3] uint8 frame, or None if none is due yet."""

    def start(self) -> None:  # pragma: no cover - default no-op
        pass

    def stop(self) -> None:  # pragma: no cover - default no-op
        pass
