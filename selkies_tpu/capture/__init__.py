from .base import FrameSource  # noqa: F401
from .synthetic import SyntheticSource  # noqa: F401
