"""Deterministic synthetic frame sources for tests and benchmarks.

Patterns model desktop-streaming workloads: static UI with a moving region
(the common case damage gating exploits), scrolling text, and full-motion
video-like noise (worst case for the entropy coder).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .base import FrameSource


class DeviceScrollSource:
    """Device-resident scrolling frame source for encoder benchmarks.

    Generates the same "scroll" workload as :class:`SyntheticSource` (every
    stripe damaged every frame — no damage-gating shortcuts) but materializes
    frames *on the TPU* with a tiny jitted roll, so a benchmark measures the
    encoder instead of host↔device link bandwidth. Production capture feeds
    the encoder over PCIe where a 6 MB 1080p upload costs well under a
    millisecond; on tunneled dev chips the same upload costs ~450 ms, which
    would swamp any encoder measurement.
    """

    def __init__(self, width: int, height: int, seed: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        base = SyntheticSource(width, height, pattern="scroll", seed=seed)
        self.width, self.height = width, height
        self._bg = jax.device_put(base._bg)
        self._roll = jax.jit(lambda bg, t: jnp.roll(bg, shift=-4 * t, axis=0))

        def roll_batch(bg, t0, n):
            ts = t0 + jnp.arange(n)
            return jax.vmap(lambda t: jnp.roll(bg, shift=-4 * t, axis=0))(ts)

        self._roll_batch = jax.jit(roll_batch, static_argnames=("n",))
        self._t = 0

    def next_frame(self):
        t = self._t
        self._t += 1
        return self._roll(self._bg, t % self.height)

    def next_batch(self, n: int):
        """(n, H, W, 3) scrolled frames in ONE device program — a
        per-frame roll would cost n dispatches, which on RPC-attached
        transports costs more than the encode itself."""
        t = self._t
        self._t += n
        return self._roll_batch(self._bg, t % self.height, n)


class SyntheticSource(FrameSource):
    PATTERNS = ("desktop", "scroll", "motion", "static", "noise")

    def __init__(
        self,
        width: int,
        height: int,
        fps: float = 60.0,
        pattern: str = "desktop",
        seed: int = 0,
    ) -> None:
        super().__init__(width, height, fps)
        if pattern not in self.PATTERNS:
            raise ValueError(f"unknown pattern {pattern!r}")
        self.pattern = pattern
        self._t = 0
        rng = np.random.default_rng(seed)
        yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
        # background: smooth "wallpaper" plus window-like rectangles
        bg = np.stack(
            [
                120 + 60 * np.sin(xx / 181.0) * np.cos(yy / 127.0),
                110 + 60 * np.cos(xx / 149.0),
                140 + 50 * np.sin(yy / 167.0),
            ],
            axis=-1,
        )
        for _ in range(6):  # window rectangles with 1px borders
            x0, y0 = rng.integers(0, max(1, width - 80)), rng.integers(0, max(1, height - 60))
            w, h = rng.integers(60, min(400, width)), rng.integers(40, min(300, height))
            x1, y1 = min(width, x0 + w), min(height, y0 + h)
            bg[y0:y1, x0:x1] = rng.integers(180, 250, size=3)
            bg[y0:y1, x0:x0 + 2] = bg[y0:y1, x1 - 2:x1] = 60
        self._bg = np.clip(bg, 0, 255).astype(np.uint8)
        self._noise_rng = rng

    def next_frame(self) -> Optional[np.ndarray]:
        t = self._t
        self._t += 1
        h, w = self.height, self.width
        if self.pattern == "static":
            return self._bg.copy()
        if self.pattern == "noise":
            return self._noise_rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        if self.pattern == "scroll":
            return np.roll(self._bg, shift=-(4 * t) % h, axis=0)
        if self.pattern == "motion":
            yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
            f = np.stack(
                [
                    128 + 100 * np.sin(xx / 97.0 + t * 0.31) * np.cos(yy / 53.0),
                    128 + 100 * np.cos(xx / 71.0 + t * 0.23),
                    128 + 100 * np.sin(yy / 89.0 + t * 0.17),
                ],
                axis=-1,
            )
            return np.clip(f, 0, 255).astype(np.uint8)
        # "desktop": static background + one moving "cursor/window" block
        f = self._bg.copy()
        bw, bh = max(8, w // 12), max(8, h // 12)
        x = int((np.sin(t * 0.13) * 0.45 + 0.5) * (w - bw))
        y = int((np.cos(t * 0.11) * 0.45 + 0.5) * (h - bh))
        f[y:y + bh, x:x + bw] = (230, 60, 60)
        return f
