"""Async pipeline driver: kills the dispatch/fetch floor (ISSUE 12).

The device encodes a 1080p H.264 frame in ~15 ms, yet the served encode
share measured ~20x that: the capture loop drove the encoder in lockstep
— every frame paid a dispatch round trip plus a blocking D2H fetch on
the shared event loop (ThreadedEncoderAdapter serialized the two inside
one worker ``encode_frame`` call). The low-latency GPU-encoder
literature (PAPERS.md: NVENC 4K low-latency, NVENC-efficiency) says the
fix plainly: hardware encoders only hit their rated latency when the
submission queue never drains.

:class:`AsyncEncodeDriver` restructures the path so the chip never
idles waiting on the host:

* the capture loop's ``try_submit``/``poll`` become pure queue
  operations — no device interaction ever runs on the event loop;
* a dedicated driver thread owns the pipelined encoder
  (:mod:`.pipeline`) and keeps >=2 batches in flight end-to-end:
  dispatch of batch N+1 is issued while batch N's eagerly-started
  ``copy_to_host_async`` completes;
* host frames double-buffer through the donated staging ring
  (:class:`.h264_device.StagingRing`), so H2D upload overlaps the
  previous batch's compute and donation never serializes dispatches;
* a bounded submit queue gives backpressure (frames drop at the edge,
  counted, instead of stalling every display on the loop);
* ``flush()`` drains deterministically; ``close()`` mid-flight neither
  deadlocks nor leaks a staging slot, so PR 2 supervisor restarts and
  PR 3 evictions stay safe.

docs/pipeline.md describes the in-flight model and flush semantics.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

logger = logging.getLogger("selkies_tpu.encoder.async_driver")

#: fault point checked at the driver's harvest site (same name the
#: capture loop uses for its async stall, so one SELKIES_TPU_FAULTS
#: entry can wedge either side of the fetch)
FETCH_HANG_POINT = "fetch.hang"


class AsyncEncodeDriver:
    """Non-blocking facade + driver thread around a pipelined encoder.

    ``pipe`` is a :class:`~.pipeline.PipelinedJpegEncoder` or
    :class:`~.pipeline.PipelinedH264Encoder`; the driver is its only
    user after construction, so the pipe needs no locking of its own.

    Capture-loop surface (same duck type the server already speaks):
    ``try_submit`` / ``poll`` / ``flush`` / ``force_keyframe`` /
    ``close`` / ``stats`` / ``metrics`` / ``on_error`` — plus
    ``wire_fullframe`` for the server's stripe packer.
    """

    #: seconds the driver thread sleeps between harvest polls when work
    #: is in flight but nothing is ready (an ``is_ready`` check is
    #: cheap; the short beat keeps both submit and harvest latency low)
    POLL_INTERVAL_S = 0.002

    def __init__(self, pipe, *, submit_depth: Optional[int] = None,
                 flush_partial_when_idle: bool = True,
                 wire_fullframe: bool = False,
                 metrics=None, faults=None) -> None:
        self.pipe = pipe
        self.submit_depth = int(submit_depth or max(4, pipe.depth))
        #: JPEG / batch=1 H.264: ship partial fetch groups as soon as the
        #: submit queue runs dry (lowest latency). Batched H.264 keeps
        #: False so the re-armed batch deadline — not every idle poll —
        #: decides when a partial batch ships.
        self.flush_partial_when_idle = bool(flush_partial_when_idle)
        self.wire_fullframe = bool(wire_fullframe)
        self._metrics = metrics
        pipe.metrics = metrics
        #: fault injector (server wires its own in); checked with the
        #: sync variant at the harvest site, where a stalled D2H would
        #: really block
        self.faults = faults
        #: server ladder hook: called with the exception for every frame
        #: lost to a device/entropy error (driver thread context)
        self.on_error: Optional[Callable[[BaseException], None]] = None

        self._cond = threading.Condition()
        self._in_q: deque = deque()          # (driver_seq, frame)
        self._out: deque = deque()           # (driver_seq, stripes)
        #: driver_seq -> flight-recorder stage intervals harvested with
        #: the frame (pulled from the pipe at emit time, under _cond, so
        #: the event-loop pop never touches pipe state the driver thread
        #: is mutating); bounded like the pipe's own trace store
        self._trace_out: dict = {}
        #: pipe seq -> driver seq, recorded per successful submit: a
        #: frame the pipe never accepted has no entry, so its loss can
        #: never shift later results onto wrong driver seqs
        self._seq_map: dict = {}
        self._seq = 0
        self._flush_req = 0                  # flush generation counter
        self._flush_ack = 0
        self._stop = False
        self.frames_dropped_total = 0
        self.encode_errors_total = 0
        self._error_streak = 0
        #: pipe.stats() snapshot maintained by the driver thread — the
        #: event-loop stats() surface must not iterate deques the driver
        #: thread is mutating
        self._stats_cache = dict(pipe.stats())
        self._thread = threading.Thread(
            target=self._run, name="tpuenc-async", daemon=True)
        self._thread.start()

    # -- event-loop surface (never blocks) --------------------------------

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, m) -> None:
        # the server attaches its Metrics after construction; the pipe
        # publishes the d2h/inflight gauges, so it needs the handle too
        self._metrics = m
        self.pipe.metrics = m

    def try_submit(self, frame) -> Optional[int]:
        """Queue one frame for the driver thread; None = dropped (queue
        full — the pipeline is not keeping up, backpressure at the edge
        instead of a stalled event loop)."""
        with self._cond:
            if self._stop:
                return None
            if len(self._in_q) >= self.submit_depth:
                self.frames_dropped_total += 1
                if self._metrics is not None:
                    self._metrics.inc_frames_dropped()
                return None
            seq = self._seq
            self._seq += 1
            self._in_q.append((seq, frame))
            self._cond.notify_all()
            return seq

    def submit(self, frame) -> Optional[int]:
        """Alias of :meth:`try_submit` — this facade NEVER blocks the
        caller; a full queue drops (the capture loop's contract)."""
        return self.try_submit(frame)

    def poll(self) -> List[Tuple[int, list]]:
        """Harvest whatever the driver thread completed (pure queue
        drain; ordering follows submission order)."""
        with self._cond:
            out = list(self._out)
            self._out.clear()
        return out

    def flush(self, timeout: float = 60.0) -> List[Tuple[int, list]]:
        """Drain everything submitted so far (deterministic: on return,
        every accepted frame has been harvested or accounted as an
        error). Blocks the caller — warm-up/teardown paths only."""
        with self._cond:
            if not self._thread.is_alive():
                out = list(self._out)
                self._out.clear()
                return out
            self._flush_req += 1
            want = self._flush_req
            self._cond.notify_all()
            self._cond.wait_for(
                lambda: self._flush_ack >= want or self._stop,
                timeout=timeout)
            out = list(self._out)
            self._out.clear()
        return out

    def close(self) -> None:
        """Stop the driver and abandon queued frames (display teardown,
        supervised restart). NEVER blocks the caller: teardown runs on
        the event loop, where a join would stall every display sharing
        it. All cleanup (pipe.close + ring release) happens on the
        driver thread as it exits — releasing the rings from HERE would
        race the thread's current dispatch and defeat the
        use-after-donate guard. A thread wedged in a dead device fetch
        is abandoned with its (equally abandoned) pipe — the bounded
        exposure ThreadedEncoderAdapter also documents, policed by the
        server's wedge_faults cap; the supervised restart builds a
        fresh pipeline with fresh rings either way."""
        with self._cond:
            self._stop = True
            self._in_q.clear()
            self._cond.notify_all()

    # -- control passthrough ----------------------------------------------

    def request_keyframe(self) -> None:
        kick = getattr(self.pipe, "force_keyframe", None) \
            or getattr(self.pipe, "request_keyframe", None)
        if kick is not None:
            kick()

    force_keyframe = request_keyframe

    @property
    def qp(self):
        return getattr(self.pipe, "qp", None)

    @qp.setter
    def qp(self, value):
        if hasattr(type(self.pipe), "qp"):
            self.pipe.qp = value

    @property
    def n_inflight(self) -> int:
        return self.pipe.n_inflight + len(self._in_q)

    def stats(self) -> dict:
        """Pipe gauges plus the driver's own accounting (shape-compatible
        with the other encoder adapters for health feeds and bench).
        Reads the driver thread's snapshot of pipe.stats() — calling the
        pipe directly from here would iterate deques the driver thread
        mutates concurrently."""
        with self._cond:
            st = dict(self._stats_cache)
            st["submit_queue_depth"] = len(self._in_q)
        st["frames_dropped"] = (st.get("frames_dropped", 0)
                                + self.frames_dropped_total)
        st["encode_errors"] = self.encode_errors_total
        return st

    # -- driver thread ------------------------------------------------------

    def pop_trace(self, seq: int):
        """Stage intervals for a harvested frame, keyed by DRIVER seq
        (the seq try_submit returned) — the capture loop's side of the
        flight-recorder contract."""
        with self._cond:
            return self._trace_out.pop(seq, None)

    def _emit(self, results) -> None:
        if not results:
            return
        pop_tr = getattr(self.pipe, "pop_trace", None)
        with self._cond:
            for pipe_seq, stripes in results:
                seq = self._seq_map.pop(pipe_seq, pipe_seq)
                if pop_tr is not None:
                    tr = pop_tr(pipe_seq)
                    if tr:
                        self._trace_out[seq] = tr
                        while len(self._trace_out) > 4 * self.submit_depth:
                            self._trace_out.pop(
                                next(iter(self._trace_out)))
                self._out.append((seq, stripes))
            # results arrive in pipe order: mappings below the newest
            # emitted pipe seq belong to frames the pipe lost to errors
            # and will never be yielded — drop them so the map stays
            # bounded
            horizon = results[-1][0]
            for k in [k for k in self._seq_map if k < horizon]:
                self._seq_map.pop(k)
            self._cond.notify_all()

    def _harvest(self, flush_partial: bool) -> bool:
        """One non-blocking harvest pass; True if anything completed."""
        if self.faults is not None:
            self.faults.maybe_hang_sync(FETCH_HANG_POINT)
        results = self.pipe.poll(flush_partial=flush_partial)
        self._emit(results)
        return bool(results)

    def _run(self) -> None:
        try:
            while self._run_pass():
                pass
        finally:
            # thread-side cleanup: close() must never block the event
            # loop, so the pipe teardown happens HERE, where the pipe's
            # single-owner discipline makes it race-free
            self._cleanup()

    def _run_pass(self) -> bool:
        """One driver pass; False when the driver is stopping."""
        with self._cond:
            if self._stop:
                return False
            work = list(self._in_q)
            self._in_q.clear()
            flush_want = self._flush_req
        # 1. dispatch every queued frame. pipe.submit may block
        # harvesting the OLDEST batch when the pipe is full — exactly
        # the overlap we want: batches 2..N keep computing while the
        # driver waits on batch 1's fetch. An erroring frame costs
        # ITSELF (counted + reported), never the rest of the pass; a
        # frame the pipe never accepted gets no seq mapping, so its
        # loss cannot shift later results onto wrong seqs.
        for seq, frame in work:
            try:
                pipe_seq = self.pipe.submit(frame)
            except Exception as exc:
                self._count_error(exc)
            else:
                if pipe_seq is not None:
                    with self._cond:
                        self._seq_map[pipe_seq] = seq
        try:
            # 2. harvest whatever is ready (never blocks)
            with self._cond:
                idle = not self._in_q
            self._harvest(flush_partial=(
                idle and self.flush_partial_when_idle))
            self._error_streak = 0
        except Exception as exc:
            # harvest failure: completed frames stay queued in the
            # pipe's ready list (surfacing next pass); the lost frame's
            # stale seq mapping is pruned at the next emit
            self._count_error(exc)
        # 3. explicit flush: drain the pipe COMPLETELY — a mid-drain
        # error costs its frame (counted) and the drain resumes, so
        # the ack below never strands unharvested frames behind a
        # raising one. Each failed drain removes at least the raising
        # frame, so this terminates.
        if flush_want > self._flush_ack:
            while True:
                try:
                    self._emit(self.pipe.flush())
                    break
                except Exception as exc:
                    self._count_error(exc)
                    if (self.pipe.n_inflight == 0
                            and not getattr(self.pipe, "_batch_frames",
                                            None)):
                        break
        with self._cond:
            self._stats_cache = dict(self.pipe.stats())
            if flush_want > self._flush_ack:
                # flush() returns once everything submitted either
                # completed or was accounted as an error — never strands
                self._flush_ack = flush_want
                self._cond.notify_all()
            if self._stop:
                return False
            if self._in_q or self._flush_req > self._flush_ack:
                return True
            # in-flight work pending: short beat, then re-poll; the
            # batch deadline also needs the beat to fire. Otherwise
            # sleep until new work arrives.
            waiting = (self.pipe.n_inflight > 0
                       or bool(getattr(self.pipe, "_batch_frames", None)))
            self._cond.wait(self.POLL_INTERVAL_S if waiting else 0.25)
        return True

    def _cleanup(self) -> None:
        # pipe.close() owns ring release (both pipelines force-release
        # their staging lanes as their last close step)
        close = getattr(self.pipe, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                logger.exception("pipe close raised")

    def _count_error(self, exc: BaseException) -> None:
        """A device/entropy failure costs its frame; it is COUNTED,
        REPORTED to the ladder hook, and survived — the supervisor owns
        escalation, not this thread."""
        self.encode_errors_total += 1
        if self._metrics is not None:
            self._metrics.inc_encode_errors()
        logger.exception("async encode pass failed")
        if self.on_error is not None:
            try:
                self.on_error(exc)
            except Exception:
                logger.exception("on_error hook failed")
        self._error_streak += 1
        # interruptible backoff: close() must not wait out an error storm
        with self._cond:
            if not self._stop:
                self._cond.wait(min(1.0, 0.05 * self._error_streak))
