"""tpuenc JPEG-stripe profile.

The frame is split into horizontal stripes (the reference's unit of spatial
parallelism and of client-side decode — SURVEY.md §2.7); one jit-compiled
device dispatch per frame produces quantized, zigzagged DCT coefficients for
every stripe plus a per-stripe damage measure, and the host entropy-codes and
ships only the stripes that changed ("damage gating", the TPU answer to the
reference's XDamage-driven skip: always dispatch dense work on device, mask on
host — SURVEY.md §7 hard part 4).

Paint-over: after ``paint_over_trigger_frames`` consecutive static frames a
stripe is re-emitted once at the high paint-over quality (same behavior as
pixelflux's quality escalation, consumed via CaptureSettings at
reference selkies.py:2919-2963).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.color import rgb_to_ycbcr, subsample_420
from ..ops.dct import block_dct2, blockify
from ..ops.quant import ZIGZAG, quality_scaled_tables
from . import entropy_py
from .jfif import EOI, jfif_headers
from ..native import entropy_lib
from .jpeg_tables import std_tables


@dataclass(frozen=True)
class StripeOutput:
    """One encoded stripe ready for protocol packing."""

    y_start: int
    height: int
    jpeg: bytes
    is_paintover: bool


@functools.partial(
    jax.jit,
    static_argnames=("stripe_h",),
    donate_argnames=("prev",),
)
def _device_encode(frame, prev, qy, qc, qsel, *, stripe_h: int):
    """One whole-frame encode dispatch.

    Args:
      frame: [H, W, 3] uint8 RGB (H multiple of stripe_h, W multiple of 16).
      prev:  [H, W, 3] uint8 previous frame (for damage detection); donated.
      qy/qc: [nq, 8, 8] float32 quant tables (normal, paint-over, ...).
      qsel:  [S] int32 per-stripe table index.
    Returns:
      yq  [H/8,  W/8,  64] int16 zigzag coefficients,
      cbq [H/16, W/16, 64] int16,
      crq [H/16, W/16, 64] int16,
      damage [S] int32 max abs pixel delta per stripe,
      frame (to become the caller's new ``prev`` without a host round-trip).
    """
    h, w, _ = frame.shape
    s = h // stripe_h

    diff = jnp.abs(frame.astype(jnp.int16) - prev.astype(jnp.int16))
    damage = diff.reshape(s, stripe_h * w * 3).max(axis=1).astype(jnp.int32)

    y, cb, cr = rgb_to_ycbcr(frame)
    cb = subsample_420(cb)
    cr = subsample_420(cr)

    zz = jnp.asarray(ZIGZAG)

    def component(plane, tables, rows_per_stripe):
        blocks = blockify(plane) - 128.0            # [by, bx, 8, 8]
        coeffs = block_dct2(blocks)
        by = blocks.shape[0]
        row_stripe = jnp.arange(by) // rows_per_stripe
        recip = 1.0 / tables                        # [nq, 8, 8]
        row_recip = recip[qsel[row_stripe]]         # [by, 8, 8]
        q = jnp.round(coeffs * row_recip[:, None]).astype(jnp.int16)
        return jnp.take(q.reshape(by, q.shape[1], 64), zz, axis=-1)

    yq = component(y, qy, stripe_h // 8)
    cbq = component(cb, qc, stripe_h // 16)
    crq = component(cr, qc, stripe_h // 16)
    return yq, cbq, crq, damage, frame


def _entropy_encode_420(y: np.ndarray, cb: np.ndarray, cr: np.ndarray) -> bytes:
    lib = entropy_lib()
    if lib is None:
        return entropy_py.encode_scan_420(y, cb, cr)
    dc_l, ac_l, dc_c, ac_c = std_tables()
    # worst case ~16 bits/coeff plus stuffing headroom
    cap = (y.size + cb.size + cr.size) * 4 + 4096
    out = np.empty(cap, dtype=np.uint8)
    n = lib.jpeg_encode_scan_420(
        np.ascontiguousarray(y), np.ascontiguousarray(cb),
        np.ascontiguousarray(cr),
        y.shape[0], y.shape[1],
        dc_l.code_arr, dc_l.len_arr, ac_l.code_arr, ac_l.len_arr,
        dc_c.code_arr, dc_c.len_arr, ac_c.code_arr, ac_c.len_arr,
        out, cap,
    )
    if n < 0:
        return entropy_py.encode_scan_420(y, cb, cr)
    return out[:n].tobytes()


class JpegStripeEncoder:
    """Stateful per-display JPEG-stripe encoder (tpuenc v0).

    Equivalent role to one pixelflux ``ScreenCapture`` encode context in the
    reference; constructed per display by the capture manager.
    """

    def __init__(
        self,
        width: int,
        height: int,
        stripe_height: int = 64,
        quality: int = 40,
        paintover_quality: int = 90,
        use_paint_over_quality: bool = True,
        paint_over_trigger_frames: int = 15,
        damage_threshold: int = 0,
    ) -> None:
        if stripe_height % 16:
            raise ValueError("stripe_height must be a multiple of 16 (4:2:0 MCUs)")
        self.width = width
        self.height = height
        # Padded geometry: width to 16 (MCU), height to a stripe multiple.
        self.pad_w = -(-width // 16) * 16
        self.pad_h = -(-height // stripe_height) * stripe_height
        self.stripe_h = stripe_height
        self.n_stripes = self.pad_h // stripe_height
        self.damage_threshold = int(damage_threshold)
        self.use_paint_over_quality = use_paint_over_quality
        self.paint_over_trigger_frames = int(paint_over_trigger_frames)

        self.set_quality(quality, paintover_quality)

        self._prev = jnp.zeros((self.pad_h, self.pad_w, 3), dtype=jnp.uint8)
        self._static_frames = np.zeros(self.n_stripes, dtype=np.int64)
        self._painted = np.zeros(self.n_stripes, dtype=bool)
        self._first_frame = True

    # -- configuration -----------------------------------------------------

    def set_quality(self, quality: int, paintover_quality: Optional[int] = None):
        self.quality = int(quality)
        if paintover_quality is not None:
            self.paintover_quality = int(paintover_quality)
        ly, lc = quality_scaled_tables(self.quality)
        py, pc = quality_scaled_tables(self.paintover_quality)
        self._qy_np = (ly, py)
        self._qc_np = (lc, pc)
        self._qy = jnp.stack([jnp.asarray(ly, jnp.float32), jnp.asarray(py, jnp.float32)])
        self._qc = jnp.stack([jnp.asarray(lc, jnp.float32), jnp.asarray(pc, jnp.float32)])
        self._headers: Dict[int, bytes] = {}

    def _stripe_headers(self, qidx: int) -> bytes:
        hdr = self._headers.get(qidx)
        if hdr is None:
            hdr = jfif_headers(
                self.pad_w, self.stripe_h,
                self._qy_np[qidx], self._qc_np[qidx], subsampling="420",
            )
            self._headers[qidx] = hdr
        return hdr

    # -- per-frame ---------------------------------------------------------

    def _pad(self, frame: np.ndarray) -> np.ndarray:
        if frame.shape[0] == self.pad_h and frame.shape[1] == self.pad_w:
            return frame
        return np.pad(
            frame,
            ((0, self.pad_h - frame.shape[0]), (0, self.pad_w - frame.shape[1]), (0, 0)),
            mode="edge",
        )

    def encode_frame(self, frame: np.ndarray) -> List[StripeOutput]:
        """Encode one [H, W, 3] uint8 RGB frame; returns changed stripes only."""
        frame = self._pad(np.asarray(frame, dtype=np.uint8))

        # Paint-over candidacy is decided from *previous* frames' history so
        # the table index can ride the same dispatch.
        paint_candidate = (
            self.use_paint_over_quality
            & (self._static_frames >= self.paint_over_trigger_frames)
            & ~self._painted
        )
        qsel = jnp.asarray(paint_candidate.astype(np.int32))

        yq, cbq, crq, damage, new_prev = _device_encode(
            jnp.asarray(frame), self._prev, self._qy, self._qc, qsel,
            stripe_h=self.stripe_h,
        )
        self._prev = new_prev
        yq, cbq, crq, damage = (np.asarray(a) for a in (yq, cbq, crq, damage))

        damaged = damage > self.damage_threshold
        if self._first_frame:
            damaged[:] = True
            self._first_frame = False

        out: List[StripeOutput] = []
        yrows = self.stripe_h // 8
        crows = self.stripe_h // 16
        for s in range(self.n_stripes):
            emit = False
            is_paint = False
            if damaged[s]:
                self._static_frames[s] = 0
                self._painted[s] = False
                emit = True
                is_paint = bool(paint_candidate[s])  # quantized w/ HQ table
            else:
                self._static_frames[s] += 1
                if paint_candidate[s]:
                    emit = True
                    is_paint = True
                    self._painted[s] = True
            if not emit:
                continue
            scan = _entropy_encode_420(
                yq[s * yrows:(s + 1) * yrows],
                cbq[s * crows:(s + 1) * crows],
                crq[s * crows:(s + 1) * crows],
            )
            qidx = 1 if is_paint else 0
            jpeg = self._stripe_headers(qidx) + scan + EOI
            out.append(
                StripeOutput(
                    y_start=s * self.stripe_h,
                    height=self.stripe_h,
                    jpeg=jpeg,
                    is_paintover=is_paint,
                )
            )
        return out

    def force_keyframe(self) -> None:
        """Make the next frame emit every stripe (client (re)connect)."""
        self._first_frame = True
        self._static_frames[:] = 0
        self._painted[:] = False
