"""tpuenc JPEG-stripe profile.

The frame is split into horizontal stripes (the reference's unit of spatial
parallelism and of client-side decode — SURVEY.md §2.7); one jit-compiled
device dispatch per frame produces quantized, zigzagged DCT coefficients for
every stripe plus a per-stripe damage measure, and the host entropy-codes and
ships only the stripes that changed ("damage gating", the TPU answer to the
reference's XDamage-driven skip: always dispatch dense work on device, mask on
host — SURVEY.md §7 hard part 4).

Paint-over: after ``paint_over_trigger_frames`` consecutive static frames a
stripe is re-emitted once at the high paint-over quality (same behavior as
pixelflux's quality escalation, consumed via CaptureSettings at
reference selkies.py:2919-2963).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.color import rgb_to_ycbcr, subsample_420
from ..ops.dct import block_dct2, blockify
from ..ops.quant import ZIGZAG, quality_scaled_tables
from . import entropy_py
from .h264_device import StagingRing
from .jfif import EOI, jfif_headers
from ..native import entropy_lib
from .jpeg_tables import std_tables


@dataclass(frozen=True)
class StripeOutput:
    """One encoded stripe ready for protocol packing."""

    y_start: int
    height: int
    jpeg: bytes
    is_paintover: bool


def _encode_body(frame, prev, qy, qc, qsel, *, stripe_h: int,
                 wm_scaled=None, alpha_inv=None):
    """One whole-frame encode dispatch.

    Args:
      frame: [H, W, 3] uint8 RGB (H multiple of stripe_h, W multiple of 16).
      prev:  [H, W, 3] uint8 previous frame (for damage detection); donated.
      qy/qc: [nq, 8, 8] float32 quant tables (normal, paint-over, ...).
      qsel:  [S] int32 per-stripe table index.
      wm_scaled/alpha_inv: optional watermark overlay (premultiplied RGB
        [H, W, 3] u16 and inverse alpha [H, W, 1] u16) blended on device —
        the pixelflux watermark feature (reference selkies.py:2959-2962).
    Returns:
      yq  [H/8,  W/8,  64] int16 zigzag coefficients,
      cbq [H/16, W/16, 64] int16,
      crq [H/16, W/16, 64] int16,
      damage [S] int32 max abs pixel delta per stripe,
      frame (to become the caller's new ``prev`` without a host round-trip).
    """
    h, w, _ = frame.shape
    s = h // stripe_h

    if wm_scaled is not None:
        blended = (frame.astype(jnp.uint32) * alpha_inv.astype(jnp.uint32)
                   + wm_scaled.astype(jnp.uint32) + 127) // 255
        frame = blended.astype(jnp.uint8)

    diff = jnp.abs(frame.astype(jnp.int16) - prev.astype(jnp.int16))
    damage = diff.reshape(s, stripe_h * w * 3).max(axis=1).astype(jnp.int32)

    y, cb, cr = rgb_to_ycbcr(frame)
    cb = subsample_420(cb)
    cr = subsample_420(cr)

    zz = jnp.asarray(ZIGZAG)

    def component(plane, tables, rows_per_stripe):
        blocks = blockify(plane) - 128.0            # [by, bx, 8, 8]
        coeffs = block_dct2(blocks)
        by = blocks.shape[0]
        row_stripe = jnp.arange(by) // rows_per_stripe
        recip = 1.0 / tables                        # [nq, 8, 8]
        row_recip = recip[qsel[row_stripe]]         # [by, 8, 8]
        q = jnp.round(coeffs * row_recip[:, None]).astype(jnp.int16)
        return jnp.take(q.reshape(by, q.shape[1], 64), zz, axis=-1)

    yq = component(y, qy, stripe_h // 8)
    cbq = component(cb, qc, stripe_h // 16)
    crq = component(cr, qc, stripe_h // 16)
    return yq, cbq, crq, damage, frame


_device_encode = functools.partial(
    jax.jit,
    static_argnames=("stripe_h",),
    donate_argnames=("prev",),
)(_encode_body)


@functools.lru_cache(maxsize=32)
def _device_pipeline(pad_h: int, pad_w: int, stripe_h: int,
                     watermark: bool = False):
    """Shared (packer, jitted step) per frame geometry.

    Keyed like :func:`device_entropy.scan_geometry` so reconnects/resizes to
    an already-seen resolution reuse the compiled executable instead of
    retracing a fresh per-instance closure (a multi-second stall on the
    shared event loop otherwise)."""
    from .device_entropy import DeviceEntropyPacker

    # Streaming fast path: 16-word (512-bit) per-block budget and a 16 KB
    # per-stripe cap (typical q40 1080p stripes are ~3 KB; the boundary
    # machinery costs ~10 ns per word-slot, so halving the cap buys ~3 ms
    # per frame). Blocks/stripes beyond either budget flag their stripe,
    # which falls back to the host coder in _scans_from_packed — output
    # stays bit-exact.
    packer = DeviceEntropyPacker(pad_h, pad_w, stripe_h, block_words=16,
                                 max_stripe_bytes=1 << 14)
    packer_fn = packer._pack_fn
    n_stripes = pad_h // stripe_h

    @functools.partial(jax.jit, donate_argnames=("prev",))
    def step(frame, prev, qy, qc, qsel, wm_scaled=None, alpha_inv=None):
        yq, cbq, crq, damage, new_prev = _encode_body(
            frame, prev, qy, qc, qsel, stripe_h=stripe_h,
            wm_scaled=wm_scaled if watermark else None,
            alpha_inv=alpha_inv if watermark else None)
        words, nbytes, base, ovf = packer_fn(yq, cbq, crq)
        # One fetchable buffer per frame: 4*S words of metadata followed by
        # the packed bitstream. Tunneled/RPC transports pay ~25-100 ms per
        # transfer regardless of size, so the host must be able to harvest a
        # frame with a single D2H read (see pipeline.PipelinedJpegEncoder).
        head = jnp.concatenate([
            nbytes.astype(jnp.uint32),
            base.astype(jnp.uint32),
            ovf.astype(jnp.uint32),
            damage.astype(jnp.uint32),
        ])
        packed = jnp.concatenate([head, words])
        return packed, new_prev, yq, cbq, crq

    return packer, step


META_WORDS_PER_STRIPE = 4  # nbytes, base_words, overflow, damage


def split_meta(head_np: np.ndarray, n_stripes: int):
    """Parse the 4*S metadata words at the front of a packed step buffer."""
    s = n_stripes
    nbytes = head_np[0:s].astype(np.int64)
    base = head_np[s:2 * s].astype(np.int64)
    ovf = head_np[2 * s:3 * s] != 0
    damage = head_np[3 * s:4 * s].astype(np.int64)
    return nbytes, base, ovf, damage


def _entropy_encode_420(y: np.ndarray, cb: np.ndarray, cr: np.ndarray) -> bytes:
    lib = entropy_lib()
    if lib is None:
        return entropy_py.encode_scan_420(y, cb, cr)
    dc_l, ac_l, dc_c, ac_c = std_tables()
    # worst case ~16 bits/coeff plus stuffing headroom
    cap = (y.size + cb.size + cr.size) * 4 + 4096
    out = np.empty(cap, dtype=np.uint8)
    n = lib.jpeg_encode_scan_420(
        np.ascontiguousarray(y), np.ascontiguousarray(cb),
        np.ascontiguousarray(cr),
        y.shape[0], y.shape[1],
        dc_l.code_arr, dc_l.len_arr, ac_l.code_arr, ac_l.len_arr,
        dc_c.code_arr, dc_c.len_arr, ac_c.code_arr, ac_c.len_arr,
        out, cap,
    )
    if n < 0:
        return entropy_py.encode_scan_420(y, cb, cr)
    return out[:n].tobytes()


class JpegStripeEncoder:
    """Stateful per-display JPEG-stripe encoder (tpuenc v0).

    Equivalent role to one pixelflux ``ScreenCapture`` encode context in the
    reference; constructed per display by the capture manager.

    ``entropy="device"`` (default) runs Huffman coding on the TPU too
    (:mod:`.device_entropy`), so per-frame D2H is just the compressed
    bitstream; ``entropy="host"`` pulls coefficient planes back and codes
    them with the native/Python coder (oracle and fallback path).
    """

    def __init__(
        self,
        width: int,
        height: int,
        stripe_height: int = 64,
        quality: int = 40,
        paintover_quality: int = 90,
        use_paint_over_quality: bool = True,
        paint_over_trigger_frames: int = 15,
        damage_threshold: int = 0,
        entropy: str = "device",
        watermark_path: str = "",
        watermark_location: int = -1,
    ) -> None:
        if stripe_height % 16:
            raise ValueError("stripe_height must be a multiple of 16 (4:2:0 MCUs)")
        if entropy not in ("device", "host"):
            raise ValueError(f"unknown entropy mode {entropy!r}")
        self.width = width
        self.height = height
        # Padded geometry: width to 16 (MCU), height to a stripe multiple.
        self.pad_w = -(-width // 16) * 16
        self.pad_h = -(-height // stripe_height) * stripe_height
        self.stripe_h = stripe_height
        self.n_stripes = self.pad_h // stripe_height
        self.damage_threshold = int(damage_threshold)
        self.use_paint_over_quality = use_paint_over_quality
        self.paint_over_trigger_frames = int(paint_over_trigger_frames)
        self.entropy = entropy

        self.set_quality(quality, paintover_quality)

        #: overflowed stripes that fell back to host entropy coding —
        #: sustained growth means the device packing budget is wrong for
        #: this content and the degradation ladder's host rung is cheaper
        self.host_fallback_stripes_total = 0

        self._prev = jnp.zeros((self.pad_h, self.pad_w, 3), dtype=jnp.uint8)
        self._static_frames = np.zeros(self.n_stripes, dtype=np.int64)
        self._painted = np.zeros(self.n_stripes, dtype=bool)
        self._first_frame = True
        #: donated H2D staging lane (ISSUE 12): the synchronous
        #: encode_frame path (host-entropy rung of the degradation
        #: ladder included) double-buffers its uploads through the same
        #: ring the async pipeline uses, instead of allocating per frame
        self._staging = StagingRing(depth=2)
        self._staging_ticket: Optional[tuple] = None
        self._wm_scaled, self._alpha_inv = self._load_watermark(
            watermark_path, watermark_location)

        if entropy == "device":
            self._packer, self._step = _device_pipeline(
                self.pad_h, self.pad_w, self.stripe_h,
                watermark=self._wm_scaled is not None)

    # -- configuration -----------------------------------------------------

    def _load_watermark(self, path: str, location: int):
        """Build the full-frame premultiplied overlay (pixelflux watermark
        parity, reference selkies.py:2959-2962). Locations: 0 TL, 1 TR,
        2 BL, 3 BR (default), 4 center, 5 middle-left, 6 middle-right."""
        if not path:
            return None, None
        try:
            from PIL import Image

            img = np.asarray(Image.open(path).convert("RGBA"), np.uint16)
        except Exception:
            import logging

            logging.getLogger("selkies_tpu.encoder").warning(
                "watermark %s unreadable; disabled", path)
            return None, None
        wh, ww = img.shape[:2]
        wh, ww = min(wh, self.pad_h), min(ww, self.pad_w)
        img = img[:wh, :ww]
        m = 16  # margin
        positions = {
            0: (m, m),
            1: (m, self.pad_w - ww - m),
            2: (self.pad_h - wh - m, m),
            3: (self.pad_h - wh - m, self.pad_w - ww - m),
            4: ((self.pad_h - wh) // 2, (self.pad_w - ww) // 2),
            5: ((self.pad_h - wh) // 2, m),
            6: ((self.pad_h - wh) // 2, self.pad_w - ww - m),
        }
        y0, x0 = positions.get(int(location), positions[3])
        y0, x0 = max(0, y0), max(0, x0)
        # clamp to the space remaining at the placement (a mark near the
        # frame edge is cropped, never a broadcast error)
        wh = min(wh, self.pad_h - y0)
        ww = min(ww, self.pad_w - x0)
        if wh <= 0 or ww <= 0:
            return None, None
        img = img[:wh, :ww]
        # integer alpha blend: out = (frame*(255-a) + rgb*a + 127) // 255
        a = img[:, :, 3:4]
        wm_scaled = np.zeros((self.pad_h, self.pad_w, 3), np.uint16)
        wm_scaled[y0:y0 + wh, x0:x0 + ww] = img[:, :, :3] * a
        alpha_inv = np.full((self.pad_h, self.pad_w, 1), 255, np.uint16)
        alpha_inv[y0:y0 + wh, x0:x0 + ww] = 255 - a
        return jnp.asarray(wm_scaled), jnp.asarray(alpha_inv)

    def set_quality(self, quality: int, paintover_quality: Optional[int] = None):
        self.quality = int(quality)
        if paintover_quality is not None:
            self.paintover_quality = int(paintover_quality)
        ly, lc = quality_scaled_tables(self.quality)
        py, pc = quality_scaled_tables(self.paintover_quality)
        self._qy_np = (ly, py)
        self._qc_np = (lc, pc)
        self._qy = jnp.stack([jnp.asarray(ly, jnp.float32), jnp.asarray(py, jnp.float32)])
        self._qc = jnp.stack([jnp.asarray(lc, jnp.float32), jnp.asarray(pc, jnp.float32)])
        self._headers: Dict[int, bytes] = {}

    def _stripe_headers(self, qidx: int) -> bytes:
        hdr = self._headers.get(qidx)
        if hdr is None:
            hdr = jfif_headers(
                self.pad_w, self.stripe_h,
                self._qy_np[qidx], self._qc_np[qidx], subsampling="420",
            )
            self._headers[qidx] = hdr
        return hdr

    # -- per-frame ---------------------------------------------------------

    def _pad(self, frame: np.ndarray) -> np.ndarray:
        if frame.shape[0] == self.pad_h and frame.shape[1] == self.pad_w:
            return frame
        return np.pad(
            frame,
            ((0, self.pad_h - frame.shape[0]), (0, self.pad_w - frame.shape[1]), (0, 0)),
            mode="edge",
        )

    def _paint_candidates(self) -> np.ndarray:
        """Paint-over candidacy from *previous* frames' history, so the quant
        table index can ride the same dispatch as the frame."""
        return (
            self.use_paint_over_quality
            & (self._static_frames >= self.paint_over_trigger_frames)
            & ~self._painted
        )

    def _decide_emits(self, damaged: np.ndarray, paint_candidate: np.ndarray):
        """Update damage history; return (emit, is_paint) flag arrays."""
        if self._first_frame:
            damaged = np.ones_like(damaged)
            self._first_frame = False
        emit = np.zeros(self.n_stripes, dtype=bool)
        is_paint = np.zeros(self.n_stripes, dtype=bool)
        for s in range(self.n_stripes):
            if damaged[s]:
                self._static_frames[s] = 0
                self._painted[s] = False
                emit[s] = True
                is_paint[s] = bool(paint_candidate[s])  # quantized w/ HQ table
            else:
                self._static_frames[s] += 1
                if paint_candidate[s]:
                    emit[s] = True
                    is_paint[s] = True
                    self._painted[s] = True
        return emit, is_paint

    def _assemble(self, emit, is_paint, scans) -> List[StripeOutput]:
        out: List[StripeOutput] = []
        for s in range(self.n_stripes):
            if not emit[s]:
                continue
            qidx = 1 if is_paint[s] else 0
            out.append(
                StripeOutput(
                    y_start=s * self.stripe_h,
                    height=self.stripe_h,
                    jpeg=self._stripe_headers(qidx) + scans[s] + EOI,
                    is_paintover=bool(is_paint[s]),
                )
            )
        return out

    @staticmethod
    def total_packed_words(base_np: np.ndarray, nbytes_np: np.ndarray) -> int:
        """Packed-word count of the whole frame (last stripe's base + span)."""
        return int(base_np[-1]) + (int(nbytes_np[-1]) + 3) // 4

    def _scans_from_packed(
        self, words_np, base_np, nbytes_np, ovf_np, emit, yq, cbq, crq,
    ) -> List[bytes]:
        """Per-stripe entropy scans from the device-packed word buffer;
        overflowed stripes fall back to host-coding their coefficients."""
        from .device_entropy import stuff_bytes, words_to_stripe_bytes

        yrows, crows = self.stripe_h // 8, self.stripe_h // 16
        raw = words_to_stripe_bytes(words_np, base_np, nbytes_np)
        scans: List[bytes] = [b""] * self.n_stripes
        for s in range(self.n_stripes):
            if not emit[s]:
                continue
            if ovf_np[s]:  # pathological stripe: host-code its coeffs
                self.host_fallback_stripes_total += 1
                scans[s] = _entropy_encode_420(
                    np.asarray(yq[s * yrows:(s + 1) * yrows]),
                    np.asarray(cbq[s * crows:(s + 1) * crows]),
                    np.asarray(crq[s * crows:(s + 1) * crows]))
            else:
                scans[s] = stuff_bytes(raw[s])
        return scans

    def _stage_frame(self, frame: np.ndarray):
        """Stage one padded host frame through the donated ring.

        encode_frame is synchronous (the previous frame was fully
        fetched before this call), so the previous ticket is released
        here and the two slots ping-pong."""
        self._staging.release(self._staging_ticket)
        staged, self._staging_ticket = self._staging.stage(frame)
        return staged

    def encode_frame(self, frame: np.ndarray) -> List[StripeOutput]:
        """Encode one [H, W, 3] uint8 RGB frame; returns changed stripes only."""
        frame = self._pad(np.asarray(frame, dtype=np.uint8))
        paint_candidate = self._paint_candidates()
        qsel = jnp.asarray(paint_candidate.astype(np.int32))
        yrows = self.stripe_h // 8
        crows = self.stripe_h // 16

        if self.entropy == "device":
            packed, new_prev, yq, cbq, crq = self._step(
                self._stage_frame(frame), self._prev, self._qy, self._qc,
                qsel, self._wm_scaled, self._alpha_inv)
            self._prev = new_prev
            mw = META_WORDS_PER_STRIPE * self.n_stripes
            head_np = np.asarray(packed[:mw])
            nbytes_np, base_np, ovf_np, damage_np = split_meta(
                head_np, self.n_stripes)
            emit, is_paint = self._decide_emits(
                damage_np > self.damage_threshold, paint_candidate)
            scans: List[bytes] = [b""] * self.n_stripes
            if emit.any():
                total = self.total_packed_words(base_np, nbytes_np)
                bucket = self._packer.bucket_words(total)
                words_np = np.asarray(packed[mw:mw + bucket])
                scans = self._scans_from_packed(
                    words_np, base_np, nbytes_np, ovf_np, emit, yq, cbq, crq)
            return self._assemble(emit, is_paint, scans)

        yq, cbq, crq, damage, new_prev = _device_encode(
            self._stage_frame(frame), self._prev, self._qy, self._qc, qsel,
            stripe_h=self.stripe_h,
            wm_scaled=self._wm_scaled, alpha_inv=self._alpha_inv,
        )
        self._prev = new_prev
        yq, cbq, crq, damage = (np.asarray(a) for a in (yq, cbq, crq, damage))
        emit, is_paint = self._decide_emits(
            damage > self.damage_threshold, paint_candidate)
        scans = [
            _entropy_encode_420(
                yq[s * yrows:(s + 1) * yrows],
                cbq[s * crows:(s + 1) * crows],
                crq[s * crows:(s + 1) * crows],
            ) if emit[s] else b""
            for s in range(self.n_stripes)
        ]
        return self._assemble(emit, is_paint, scans)

    def force_keyframe(self) -> None:
        """Make the next frame emit every stripe (client (re)connect)."""
        self._first_frame = True
        self._static_frames[:] = 0
        self._painted[:] = False
