"""rANS entropy coding prototype — the BASELINE config-3 decision spike.

Context (SURVEY.md §7 hard part 1, VERDICT round-1 item 10): after the
JPEG-stripe latency data landed, the deferred decision was whether a
learned-codec/rANS profile should replace or join the Huffman scan. This
module is the measurement instrument for that gate: a correct,
round-trip-tested range-ANS coder over the *same* quantized, zigzagged
DCT planes the device pipeline emits, with per-frame adaptive symbol
models — i.e. the best entropy stage a config-3 profile could put behind
the existing transform, measured on identical inputs.

Model: the JPEG symbol decomposition ((run,size) pairs + raw value bits,
DC diffs per component with stripe-reset prediction) with per-frame
adaptive frequencies, 12-bit quantized, transmitted as a table header.
Value bits are interleaved raw (rANS codes only the modelled symbols, as
in JPEG: value bits are already near-uniform). This keeps the comparison
apples-to-apples: identical symbol stream, Huffman lengths vs adaptive
arithmetic lengths.

The coder is host/numpy (the gate measures *bits*, not device time; the
device-side cost model is in docs/config3_decision.md). 32-bit rANS,
16-bit renormalization, single stream per stripe so stripes stay
independently decodable like the JPEG scans they would replace.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

RANS_L = 1 << 16          # lower bound: with 16-bit renorm the state
                          # stays in [2^16, 2^32) — a u32 on the wire
PROB_BITS = 12            # quantized probability resolution
PROB_SCALE = 1 << PROB_BITS


# ------------------------------------------------------------ symbolization


def _bitlen(v: np.ndarray) -> np.ndarray:
    out = np.zeros_like(v)
    a = np.abs(v)
    nz = a > 0
    out[nz] = np.floor(np.log2(a[nz])).astype(v.dtype) + 1
    return out


def symbolize_block_plane(plane: np.ndarray,
                          dc_reset_every: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[N, 64] zigzag blocks → (symbols, value_bits, value_lens).

    Symbols (one alphabet, 512 wide):
      0..255    AC (run<<4 | size), run 0-15, size 1-10 (+ ZRL 0xF0, EOB 0x00)
      256..267  DC size 0-11
    DC prediction resets every ``dc_reset_every`` blocks (stripe bounds).
    """
    n = plane.shape[0]
    syms: List[int] = []
    vbits: List[int] = []
    vlens: List[int] = []
    pred = 0
    for i in range(n):
        if i % dc_reset_every == 0:
            pred = 0
        blk = plane[i]
        dc = int(blk[0])
        diff = dc - pred
        pred = dc
        size = int(_bitlen(np.asarray([diff]))[0])
        syms.append(256 + size)
        if size:
            raw = diff if diff > 0 else diff + (1 << size) - 1
            vbits.append(raw & ((1 << size) - 1))
            vlens.append(size)
        run = 0
        for k in range(1, 64):
            v = int(blk[k])
            if v == 0:
                run += 1
                continue
            while run >= 16:
                syms.append(0xF0)
                run -= 16
            size = int(_bitlen(np.asarray([v]))[0])
            syms.append((run << 4) | size)
            raw = v if v > 0 else v + (1 << size) - 1
            vbits.append(raw & ((1 << size) - 1))
            vlens.append(size)
            run = 0
        if run:
            syms.append(0x00)
    return (np.asarray(syms, np.int32), np.asarray(vbits, np.int64),
            np.asarray(vlens, np.int32))


# ------------------------------------------------------------------ model


def build_model(symbols: np.ndarray, alphabet: int = 268) -> np.ndarray:
    """Quantized per-frame frequency table: [alphabet] uint16 summing to
    PROB_SCALE, every present symbol ≥ 1."""
    counts = np.bincount(symbols, minlength=alphabet).astype(np.float64)
    present = counts > 0
    if not present.any():
        freqs = np.zeros(alphabet, np.int64)
        freqs[0] = PROB_SCALE
        return freqs.astype(np.uint16)
    scaled = counts * (PROB_SCALE / counts.sum())
    freqs = np.maximum(np.round(scaled).astype(np.int64), present.astype(np.int64))
    # exact renormalization to PROB_SCALE: trim/boost the largest entries
    while freqs.sum() != PROB_SCALE:
        delta = PROB_SCALE - int(freqs.sum())
        idx = int(np.argmax(freqs)) if delta < 0 else int(np.argmax(counts))
        step = max(1, abs(delta) // 2) * (1 if delta > 0 else -1)
        if freqs[idx] + step < 1:
            step = 1 - int(freqs[idx])
        freqs[idx] += step
    return freqs.astype(np.uint16)


def model_header(freqs: np.ndarray) -> bytes:
    """Sparse table serialization: u16 count, then (u16 sym, u16 freq)."""
    nz = np.flatnonzero(freqs)
    out = struct.pack("<H", len(nz))
    for s in nz:
        out += struct.pack("<HH", int(s), int(freqs[s]))
    return out


def parse_model_header(data: bytes, alphabet: int = 268
                       ) -> Tuple[np.ndarray, int]:
    if len(data) < 2:
        raise ValueError("malformed rANS stream: header truncated")
    (n,) = struct.unpack_from("<H", data)
    if 2 + 4 * n > len(data):
        raise ValueError("malformed rANS stream: model table truncated")
    freqs = np.zeros(alphabet, np.int64)
    pos = 2
    for _ in range(n):
        s, f = struct.unpack_from("<HH", data, pos)
        if s >= alphabet:
            raise ValueError(f"malformed rANS stream: symbol {s} outside "
                             f"alphabet {alphabet}")
        freqs[s] = f
        pos += 4
    if int(freqs.sum()) != PROB_SCALE:
        raise ValueError("malformed rANS stream: model does not sum to "
                         "PROB_SCALE")
    return freqs.astype(np.uint16), pos


# ------------------------------------------------------------------ coder


def rans_encode(symbols: np.ndarray, freqs: np.ndarray) -> bytes:
    """Single-stream 32-bit rANS, 16-bit renorm, encoded in reverse so the
    decoder reads forward."""
    cum = np.zeros(len(freqs) + 1, np.int64)
    np.cumsum(freqs, out=cum[1:])
    state = RANS_L
    out: List[int] = []                  # u16 words, reversed at the end
    x_max_base = ((RANS_L >> PROB_BITS) << 16)
    for s in symbols[::-1]:
        f = int(freqs[s])
        # renormalize: stream out low 16 bits while state too large
        x_max = x_max_base * f
        while state >= x_max:
            out.append(state & 0xFFFF)
            state >>= 16
        state = ((state // f) << PROB_BITS) + (state % f) + int(cum[s])
    header = struct.pack("<I", state)
    body = np.asarray(out[::-1], np.uint16).tobytes()
    return header + body


def rans_decode(data: bytes, freqs: np.ndarray, count: int) -> np.ndarray:
    cum = np.zeros(len(freqs) + 1, np.int64)
    np.cumsum(freqs, out=cum[1:])
    # slot → symbol lookup
    slot2sym = np.zeros(PROB_SCALE, np.int32)
    for s in np.flatnonzero(freqs):
        slot2sym[cum[s]:cum[s + 1]] = s
    if len(data) < 4:
        raise ValueError("malformed rANS stream: state header truncated")
    (state,) = struct.unpack_from("<I", data)
    words = np.frombuffer(data[4:len(data) - (len(data) - 4) % 2], np.uint16)
    wi = 0
    out = np.empty(count, np.int32)
    for i in range(count):
        slot = state & (PROB_SCALE - 1)
        s = int(slot2sym[slot])
        out[i] = s
        f = int(freqs[s])
        state = f * (state >> PROB_BITS) + slot - int(cum[s])
        while state < RANS_L:
            if wi >= len(words):
                raise ValueError("rans stream truncated")
            state = (state << 16) | int(words[wi])
            wi += 1
    return out


# ----------------------------------------------------------- value bits


def pack_value_bits(vbits: np.ndarray, vlens: np.ndarray) -> bytes:
    """MSB-first concatenation of the raw value-bit fields."""
    total = int(vlens.sum())
    buf = bytearray((total + 7) // 8)
    pos = 0
    for v, ln in zip(vbits.tolist(), vlens.tolist()):
        for b in range(ln - 1, -1, -1):
            if (v >> b) & 1:
                buf[pos >> 3] |= 0x80 >> (pos & 7)
            pos += 1
    return bytes(buf)


def unpack_value_bits(data: bytes, vlens: np.ndarray) -> np.ndarray:
    if int(vlens.sum() if len(vlens) else 0) > len(data) * 8:
        raise ValueError("malformed rANS stream: value bits truncated")
    out = np.empty(len(vlens), np.int64)
    pos = 0
    for i, ln in enumerate(vlens.tolist()):
        v = 0
        for _ in range(ln):
            bit = (data[pos >> 3] >> (7 - (pos & 7))) & 1
            v = (v << 1) | bit
            pos += 1
        out[i] = v
    return out


# --------------------------------------------------------------- profile


def encode_planes(yq: np.ndarray, cbq: np.ndarray, crq: np.ndarray,
                  blocks_per_stripe_y: int) -> bytes:
    """Full config-3 candidate bitstream for one frame's planes: adaptive
    model header + rANS symbol stream + raw value bits, per component
    class (luma / chroma) like JPEG's table split."""
    y2 = yq.reshape(-1, 64)
    c2 = np.concatenate([cbq.reshape(-1, 64), crq.reshape(-1, 64)])
    out = b""
    for plane, reset in ((y2, blocks_per_stripe_y),
                         (c2, max(1, blocks_per_stripe_y // 4))):
        syms, vbits, vlens = symbolize_block_plane(plane, reset)
        freqs = build_model(syms)
        stream = rans_encode(syms, freqs)
        values = pack_value_bits(vbits, vlens)
        hdr = model_header(freqs)
        out += struct.pack("<III", len(syms), len(stream), len(values))
        out += hdr + stream + values
    return out


def decode_planes(data: bytes, y_blocks: int, c_blocks: int,
                  blocks_per_stripe_y: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of encode_planes → ([y_blocks, 64], [c_blocks, 64])."""
    pos = 0
    planes = []
    for n_blocks, reset in ((y_blocks, blocks_per_stripe_y),
                            (c_blocks, max(1, blocks_per_stripe_y // 4))):
        if pos + 12 > len(data):
            raise ValueError("malformed rANS stream: plane header truncated")
        nsym, nstream, nvalues = struct.unpack_from("<III", data, pos)
        pos += 12
        if pos + nstream + nvalues > len(data):
            raise ValueError("malformed rANS stream: plane sizes exceed data")
        # a block emits ≤ 65 symbols (DC + 64 AC/EOB) and ≤ 65 values, so
        # an untrusted 32-bit count beyond that is an attack, not a frame —
        # without this bound a ~30-byte blob forces a multi-GB allocation
        # and a near-unbounded decode loop
        if nsym > n_blocks * 65 or nvalues > n_blocks * 65 * 8:
            raise ValueError("malformed rANS stream: counts exceed geometry")
        freqs, consumed = parse_model_header(data[pos:])
        pos += consumed
        syms = rans_decode(data[pos:pos + nstream], freqs, nsym)
        pos += nstream
        values_raw = data[pos:pos + nvalues]
        pos += nvalues
        # reconstruct blocks from the symbol stream
        vlens = []
        for s in syms.tolist():
            if s >= 256:
                vlens.append(s - 256)
            elif s not in (0x00, 0xF0):
                vlens.append(s & 15)
        vlens_arr = np.asarray([l for l in vlens if l > 0], np.int32)
        vals = unpack_value_bits(values_raw, vlens_arr)
        blocks = np.zeros((n_blocks, 64), np.int16)
        n_syms = len(syms)
        n_vals = len(vals)
        vi = 0
        si = 0
        pred = 0

        def _bad(what: str) -> ValueError:
            # corrupt/truncated input must surface as a clean decode
            # error, not an IndexError, before this coder ever fronts
            # untrusted wire data
            return ValueError(f"malformed rANS stream: {what} "
                              f"(block {b}, si={si}, vi={vi})")

        for b in range(n_blocks):
            if b % reset == 0:
                pred = 0
            if si >= n_syms:
                raise _bad("symbol stream exhausted at DC")
            s = int(syms[si]); si += 1
            size = s - 256
            if not 0 <= size <= 15:
                raise _bad(f"DC symbol {s} out of range")
            if size:
                if vi >= n_vals:
                    raise _bad("value stream exhausted at DC")
                raw = int(vals[vi]); vi += 1
                diff = raw if raw >= (1 << (size - 1)) \
                    else raw - (1 << size) + 1
            else:
                diff = 0
            pred += diff
            blocks[b, 0] = pred
            k = 1
            while k < 64:
                if si >= n_syms:
                    raise _bad("symbol stream exhausted mid-block")
                s = int(syms[si]); si += 1
                if s == 0x00:
                    break
                if s == 0xF0:
                    k += 16
                    continue
                if not 0 <= s <= 0xFF:
                    raise _bad(f"AC symbol {s} out of range")
                run, size = s >> 4, s & 15
                if size == 0:
                    raise _bad(f"AC symbol {s:#x} has zero size")
                k += run
                if k >= 64:
                    raise _bad(f"run overflows block ({k})")
                if vi >= n_vals:
                    raise _bad("value stream exhausted mid-block")
                raw = int(vals[vi]); vi += 1
                v = raw if raw >= (1 << (size - 1)) else raw - (1 << size) + 1
                blocks[b, k] = v
                k += 1
                if k == 64:
                    break
            # blocks that end exactly on coefficient 63 carry no EOB
        planes.append(blocks)
    return planes[0], planes[1]
