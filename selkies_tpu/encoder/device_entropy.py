"""Device-side (on-TPU) baseline-JPEG Huffman entropy coding.

Why: pulling DCT coefficients to the host costs ~6 MB/frame of D2H traffic —
the dominant cost on PCIe-attached chips at high session counts and fatal on
tunneled devices. Entropy coding *on device* shrinks the per-frame transfer to
the compressed bitstream itself (tens of KB). This is SURVEY.md §7 "hard part
1" resolved in favor of option (a'): a data-parallel formulation of Huffman
coding that fits XLA/TPU:

  1. blocks are gathered into JPEG MCU scan order (static permutation);
  2. DC deltas come from a static predecessor-index gather (the serial DC
     chain is just a shifted subtraction in scan order);
  3. zero-run lengths come from an inclusive ``cummax`` of nonzero positions
     (the only "sequential" part of RLE, done as an associative scan);
  4. every coefficient expands into ≤4 fixed symbol slots (3 ZRL + 1 value;
     a run ≤62 needs ≤3 ZRLs), giving a dense [blocks, 254] symbol grid;
  5. symbol bit offsets are a segmented cumulative sum (per stripe);
  6. bit packing exploits that contributions to one 32-bit output word have
     disjoint bits: word values are recovered from a plain (wrapping) cumsum
     of per-symbol word contributions differenced at word boundaries found
     by ``searchsorted`` — no scatter, no atomics;
  7. stripes are padded with 1-bits to byte alignment (T.81 F.1.2.3) via one
     synthetic trailing symbol per stripe, then compacted back-to-back at
     word granularity so the host fetches one dense buffer.

The output is bit-exact with the host coders (entropy_py / native); byte
stuffing (0xFF→0xFF00) happens on host over the ~75 KB result.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .jpeg_tables import std_tables


# --------------------------------------------------------------------------
# Static geometry


@functools.lru_cache(maxsize=32)
def scan_geometry(pad_h: int, pad_w: int, stripe_h: int):
    """Static scan-order arrays for a 4:2:0 frame geometry.

    Returns (perm, is_chroma, dc_prev_idx, blocks_per_stripe):
      perm[M]        — index into concat(Y, Cb, Cr) flattened block arrays,
                       in MCU-interleaved stripe-major order;
      is_chroma[M]   — Huffman table selector per block;
      dc_prev_idx[M] — stream index of the DC predecessor (same component,
                       same stripe) or -1 at each stripe/component start.
    """
    by, bx = pad_h // 8, pad_w // 8
    cby, cbx = pad_h // 16, pad_w // 16
    s_cnt = pad_h // stripe_h
    yrows, crows = stripe_h // 8, stripe_h // 16
    mcols = pad_w // 16

    perm = []
    is_chroma = []
    dc_prev = []
    last = {}
    y_base, cb_base, cr_base = 0, by * bx, by * bx + cby * cbx
    for s in range(s_cnt):
        last.clear()  # DC prediction resets per stripe (independent JPEGs)
        for mr in range(crows):
            for mc in range(mcols):
                for dy in (0, 1):
                    for dx in (0, 1):
                        perm.append(
                            y_base + (s * yrows + 2 * mr + dy) * bx + (2 * mc + dx))
                        is_chroma.append(0)
                        i = len(perm) - 1
                        dc_prev.append(last.get("y", -1))
                        last["y"] = i
                for base, key in ((cb_base, "cb"), (cr_base, "cr")):
                    perm.append(base + (s * crows + mr) * cbx + mc)
                    is_chroma.append(1)
                    i = len(perm) - 1
                    dc_prev.append(last.get(key, -1))
                    last[key] = i
    blocks_per_stripe = crows * mcols * 6
    return (
        np.asarray(perm, np.int32),
        np.asarray(is_chroma, np.int32),
        np.asarray(dc_prev, np.int32),
        blocks_per_stripe,
    )


def _huff_arrays():
    """Stacked [2, 256] (luma, chroma) code/length arrays for DC and AC."""
    dc_l, ac_l, dc_c, ac_c = std_tables()
    dc_code = np.stack([dc_l.code_arr, dc_c.code_arr]).astype(np.uint32)
    dc_len = np.stack([dc_l.len_arr, dc_c.len_arr]).astype(np.int32)
    ac_code = np.stack([ac_l.code_arr, ac_c.code_arr]).astype(np.uint32)
    ac_len = np.stack([ac_l.len_arr, ac_c.len_arr]).astype(np.int32)
    return dc_code, dc_len, ac_code, ac_len


def _bitlen(a):
    """Magnitude category of |a| (int32, |a| ≤ 2047): exact via f32 log2."""
    af = jnp.abs(a).astype(jnp.float32)
    return jnp.where(a == 0, 0, jnp.floor(jnp.log2(jnp.maximum(af, 1.0))) + 1
                     ).astype(jnp.int32)


def _vbits(v, size):
    """Value bits: v for v>0 else ones'-complement (T.81 F.1.2.1)."""
    raw = jnp.where(v > 0, v, v + (1 << size) - 1)
    return (raw & ((1 << size) - 1)).astype(jnp.uint32)


def _sorted_segment_words(word_idx, contrib, n_words):
    """Sum contributions grouped by (sorted, non-decreasing) word index.

    Within a word all contributions have disjoint bits, so their u32 sum is
    exact; the wrapping cumsum across words cancels in the difference.
    """
    cs = jnp.cumsum(contrib.astype(jnp.uint32), dtype=jnp.uint32)
    hi = jnp.searchsorted(word_idx, jnp.arange(n_words, dtype=word_idx.dtype),
                          side="right")
    s_at = jnp.where(hi > 0, cs[jnp.maximum(hi - 1, 0)], 0)
    return s_at - jnp.concatenate([jnp.zeros((1,), jnp.uint32), s_at[:-1]])


class DeviceEntropyPacker:
    """Per-geometry compiled entropy pack: coefficients → packed bitstreams.

    ``pack(yq, cbq, crq)`` returns:
      words  [cap_words] uint32 — all stripes' scans compacted back-to-back
             (each stripe starts word-aligned; bits are MSB-first, so bytes
             come from big-endian u32 serialization);
      nbytes [S] int32         — scan byte count per stripe (incl. padding);
      base_words [S] int32     — word offset of each stripe in ``words``.
    """

    #: symbol slots per block: DC + 63 × (3 ZRL + value) + EOB
    SLOTS = 254

    def __init__(
        self,
        pad_h: int,
        pad_w: int,
        stripe_h: int,
        max_stripe_bytes: int = 1 << 17,
    ) -> None:
        perm, is_chroma, dc_prev, bps = scan_geometry(pad_h, pad_w, stripe_h)
        self.n_stripes = pad_h // stripe_h
        self.blocks_per_stripe = bps
        self.max_stripe_words = max_stripe_bytes // 4
        # Sized for the worst case (every stripe at its cap), so compaction
        # can never spill a stripe past the buffer — an overflowing stripe is
        # clamped to max_stripe_words and flagged; later stripes stay intact.
        self.cap_words = self.n_stripes * self.max_stripe_words
        dc_code, dc_len, ac_code, ac_len = _huff_arrays()

        n_stripes = self.n_stripes
        max_w = self.max_stripe_words
        cap_words = self.cap_words
        slots = self.SLOTS
        syms_per_stripe = bps * slots

        def pack_fn(yq, cbq, crq):
            allb = jnp.concatenate(
                [yq.reshape(-1, 64), cbq.reshape(-1, 64), crq.reshape(-1, 64)]
            ).astype(jnp.int32)
            stream = allb[jnp.asarray(perm)]                    # [M, 64]
            chroma = jnp.asarray(is_chroma)                     # [M]
            m_blocks = stream.shape[0]

            def lut(table_pair, sym):
                """Per-block table select without materializing [M, 256]:
                gather from each 256-entry constant, then pick by component."""
                tl = jnp.take(jnp.asarray(table_pair[0]), sym)
                tc = jnp.take(jnp.asarray(table_pair[1]), sym)
                sel = chroma.reshape((-1,) + (1,) * (sym.ndim - 1)) == 1
                return jnp.where(sel, tc, tl)

            # ---- DC symbols ------------------------------------------------
            dc = stream[:, 0]
            prev_idx = jnp.asarray(dc_prev)
            pred = jnp.where(prev_idx < 0, 0, dc[jnp.maximum(prev_idx, 0)])
            diff = dc - pred
            dsize = _bitlen(diff)
            dcode = lut(dc_code, dsize)
            dlen = lut(dc_len, dsize)
            dc_bits = ((dcode << dsize.astype(jnp.uint32))
                       | _vbits(diff, dsize)).astype(jnp.uint32)
            dc_slen = dlen + dsize

            # ---- AC run-lengths -------------------------------------------
            z = stream[:, 1:]                                   # [M, 63]
            nzm = z != 0
            posk = jnp.arange(1, 64, dtype=jnp.int32)[None, :]
            p = jnp.where(nzm, posk, 0)
            m_incl = jax.lax.associative_scan(jnp.maximum, p, axis=1)
            prev_excl = jnp.concatenate(
                [jnp.zeros((m_blocks, 1), jnp.int32), m_incl[:, :-1]], axis=1)
            run = posk - prev_excl - 1
            size = _bitlen(z)
            rem = run & 15
            nzrl = run >> 4                                     # 0..3

            ac_sym = ((rem << 4) | size)
            acode = lut(ac_code, ac_sym)
            alen = lut(ac_len, ac_sym)
            main_bits = ((acode << size.astype(jnp.uint32))
                         | _vbits(z, size)).astype(jnp.uint32)
            main_len = jnp.where(nzm, alen + size, 0)

            zrl_code = jnp.where(chroma == 1, int(ac_code[1][0xF0]),
                                 int(ac_code[0][0xF0]))[:, None]
            zrl_len = jnp.where(chroma == 1, int(ac_len[1][0xF0]),
                                int(ac_len[0][0xF0]))[:, None]
            zrl_slots_bits = jnp.broadcast_to(
                zrl_code[..., None], (m_blocks, 63, 3)).astype(jnp.uint32)
            zrl_active = nzm[..., None] & (
                nzrl[..., None] > jnp.arange(3)[None, None, :])
            zrl_slots_len = jnp.where(zrl_active, zrl_len[..., None], 0)

            # ---- EOB -------------------------------------------------------
            eob_active = m_incl[:, -1] != 63
            eob_bits = jnp.where(chroma == 1, int(ac_code[1][0x00]),
                                 int(ac_code[0][0x00])).astype(jnp.uint32)
            eob_len = jnp.where(
                eob_active,
                jnp.where(chroma == 1, int(ac_len[1][0x00]), int(ac_len[0][0x00])),
                0)

            # ---- dense symbol grid [M, 254] -------------------------------
            ac_slots_bits = jnp.concatenate(
                [zrl_slots_bits, main_bits[..., None]], axis=2).reshape(m_blocks, 252)
            ac_slots_len = jnp.concatenate(
                [zrl_slots_len, main_len[..., None]], axis=2).reshape(m_blocks, 252)
            bits_g = jnp.concatenate(
                [dc_bits[:, None], ac_slots_bits, eob_bits[:, None]], axis=1)
            lens_g = jnp.concatenate(
                [dc_slen[:, None], ac_slots_len, eob_len[:, None]], axis=1)

            flat_bits = bits_g.reshape(-1)
            flat_len = lens_g.reshape(-1)

            # ---- per-stripe bit offsets (segmented cumsum) ----------------
            cum = jnp.cumsum(flat_len)
            seg_last = cum.reshape(n_stripes, syms_per_stripe)[:, -1]
            stripe_end = seg_last                            # inclusive cumsum @ seg end
            stripe_base = jnp.concatenate(
                [jnp.zeros((1,), cum.dtype), stripe_end[:-1]])
            stripe_of = (
                jnp.arange(flat_len.shape[0], dtype=jnp.int32) // syms_per_stripe)
            off = cum - flat_len - stripe_base[stripe_of]    # bit offset in stripe
            t_bits = stripe_end - stripe_base                # [S]

            # ---- stripe byte-alignment padding ----------------------------
            pad = (-t_bits) % 8
            t_bytes = ((t_bits + pad) // 8).astype(jnp.int32)

            # ---- word contributions ---------------------------------------
            def contributions(offv, lenv, bitsv, stripev):
                """Split each symbol into ≤2 word contributions (len ≤ 27 < 32)."""
                word_in_stripe = jnp.minimum((offv >> 5), max_w - 1)
                overflow = (offv + lenv) > (max_w * 32)
                bitpos = (offv & 31).astype(jnp.int32)
                shift = 32 - bitpos - lenv
                safe = jnp.where((lenv > 0) & ~overflow, bitsv, 0)
                c0 = jnp.where(
                    shift >= 0,
                    safe << jnp.maximum(shift, 0).astype(jnp.uint32),
                    safe >> jnp.maximum(-shift, 0).astype(jnp.uint32),
                ).astype(jnp.uint32)
                c1 = jnp.where(
                    shift >= 0, jnp.uint32(0),
                    safe << jnp.maximum(32 + shift, 0).astype(jnp.uint32),
                ).astype(jnp.uint32)
                w0 = stripev * max_w + word_in_stripe
                w1 = jnp.minimum(w0 + 1, n_stripes * max_w - 1)
                return w0, c0, w1, c1

            n_words = n_stripes * max_w
            w0, c0, w1, c1 = contributions(off, flat_len, flat_bits, stripe_of)
            # Both streams are sorted (symbols are stripe-major with monotone
            # offsets), so word values fall out of a wrapping cumsum
            # differenced at word boundaries — no scatter.
            words = (
                _sorted_segment_words(w0, c0, n_words)
                + _sorted_segment_words(w1, c1, n_words)
            )
            # The S padding symbols (one per stripe) are added by a tiny
            # scatter instead of re-sorting 12M symbols around them.
            pw0, pc0, pw1, pc1 = contributions(
                t_bits, pad, ((1 << pad) - 1).astype(jnp.uint32),
                jnp.arange(n_stripes, dtype=jnp.int32))
            words = words.at[pw0].add(pc0).at[pw1].add(pc1)

            # ---- compaction ------------------------------------------------
            # Per-stripe clamp: an overflowed stripe still occupies exactly
            # max_w words so downstream stripes' offsets stay valid.
            wc = jnp.minimum((t_bytes + 3) // 4, max_w)
            base_words = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(wc)[:-1].astype(jnp.int32)])
            j = jnp.arange(cap_words, dtype=jnp.int32)
            sidx = jnp.clip(
                jnp.searchsorted(base_words, j, side="right") - 1, 0, n_stripes - 1)
            src = sidx * max_w + (j - base_words[sidx])
            valid = j < (base_words[-1] + wc[-1])
            src = jnp.clip(src, 0, n_words - 1)
            compacted = jnp.where(valid, words[src], 0)

            stripe_overflow = t_bytes > (max_w * 4)
            return compacted, t_bytes, base_words, stripe_overflow

        self._pack_fn = pack_fn
        self._pack = jax.jit(pack_fn)

    def pack(self, yq, cbq, crq):
        return self._pack(yq, cbq, crq)

    def bucket_words(self, total_words: int) -> int:
        """Power-of-two fetch size for a packed-word count (bounds the number
        of distinct slice executables compiled for D2H)."""
        n = 1024
        while n < total_words:
            n <<= 1
        return min(n, self.cap_words)


def stuff_bytes(scan: bytes) -> bytes:
    """JPEG byte stuffing (0xFF → 0xFF 0x00) over a scan, vectorized."""
    arr = np.frombuffer(scan, dtype=np.uint8)
    idx = np.flatnonzero(arr == 0xFF)
    if idx.size == 0:
        return scan
    return np.insert(arr, idx + 1, 0).tobytes()


def words_to_stripe_bytes(
    words: np.ndarray, base_words: np.ndarray, nbytes: np.ndarray
) -> Tuple[bytes, ...]:
    """Split the compacted word buffer into per-stripe scan byte strings."""
    be = words.astype(">u4").tobytes()
    out = []
    for s in range(len(nbytes)):
        start = int(base_words[s]) * 4
        out.append(be[start:start + int(nbytes[s])])
    return tuple(out)
