"""Device-side (on-TPU) baseline-JPEG Huffman entropy coding.

Why: pulling DCT coefficients to the host costs ~6 MB/frame of D2H traffic —
the dominant cost on PCIe-attached chips at high session counts and fatal on
tunneled devices. Entropy coding *on device* shrinks the per-frame transfer to
the compressed bitstream itself (tens of KB). This is SURVEY.md §7 "hard part
1" resolved as a data-parallel Huffman formulation that fits XLA/TPU.

v2 design notes (why it looks the way it does): TPU random-access ops
(gather/scatter/searchsorted) cost ~10 ns *per element* on the scalar core,
so the v1 formulation — a [blocks, 254] dense symbol grid with a global
12.4M-element cumsum and a 557k-query ``searchsorted`` — spent ~340 ms/frame
at 1080p almost entirely in scalar-core ops. v2 eliminates every large
irregular access:

  1. symbols live in a [M, 192] per-block slot grid (DC code, DC bits, and
     per-AC-coefficient {ZRL-pair, ZRL+code, value-bits} triples — each slot
     ≤ 27 bits so a slot spans ≤ 2 of the block's 32-bit words);
  2. Huffman code/length lookup is a two-level one-hot *matmul* (MXU) over a
     packed (code<<5|len) table — ~6× faster than ``jnp.take``'s gather;
  3. slots pack into ≤ W per-block words with a masked compare-and-sum
     contraction (VPU-friendly; no scatter);
  4. block base offsets are a per-stripe cumsum over block *totals* (M-sized,
     not symbol-sized), and each block word lands in global words
     ``g0+w`` / ``g0+w+1`` — an *analytic* index, linear in w;
  5. per-output-word sums use the cumsum-difference trick where the segment
     boundary is computed analytically from (4): the boundary block comes
     from a tiny 49k scatter-max + cummax, and the boundary slot within it
     is ``min(w - g0, W-1)`` — no searchsorted anywhere;
  6. stripes are padded with 1-bits to byte alignment (T.81 F.1.2.3) and
     compacted back-to-back at word granularity so the host fetches one
     dense buffer.

The output is bit-exact with the host coders (entropy_py / native); byte
stuffing (0xFF→0xFF00) happens on host over the ~75 KB result.

Overflow containment: a block whose bitstream exceeds ``32*block_words``
bits, or a stripe exceeding ``max_stripe_bytes``, flags its stripe in the
returned ``overflow`` array; flagged stripes are host-coded by the caller
(encoder/jpeg.py _scans_from_packed). The default ``block_words=56`` covers
the worst legal JPEG block (~1660 bits), so overflow can only be a stripe-
size event; the streaming pipeline uses the faster ``block_words=16``
variant where pathological blocks fall back to the host coder.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .jpeg_tables import std_tables


# --------------------------------------------------------------------------
# Static geometry


@functools.lru_cache(maxsize=32)
def scan_geometry(pad_h: int, pad_w: int, stripe_h: int):
    """Static scan-order arrays for a 4:2:0 frame geometry.

    Returns (perm, is_chroma, dc_prev_idx, blocks_per_stripe):
      perm[M]        — index into concat(Y, Cb, Cr) flattened block arrays,
                       in MCU-interleaved stripe-major order;
      is_chroma[M]   — Huffman table selector per block;
      dc_prev_idx[M] — stream index of the DC predecessor (same component,
                       same stripe) or -1 at each stripe/component start.
    """
    by, bx = pad_h // 8, pad_w // 8
    cby, cbx = pad_h // 16, pad_w // 16
    s_cnt = pad_h // stripe_h
    yrows, crows = stripe_h // 8, stripe_h // 16
    mcols = pad_w // 16

    perm = []
    is_chroma = []
    dc_prev = []
    last = {}
    y_base, cb_base, cr_base = 0, by * bx, by * bx + cby * cbx
    for s in range(s_cnt):
        last.clear()  # DC prediction resets per stripe (independent JPEGs)
        for mr in range(crows):
            for mc in range(mcols):
                for dy in (0, 1):
                    for dx in (0, 1):
                        perm.append(
                            y_base + (s * yrows + 2 * mr + dy) * bx + (2 * mc + dx))
                        is_chroma.append(0)
                        i = len(perm) - 1
                        dc_prev.append(last.get("y", -1))
                        last["y"] = i
                for base, key in ((cb_base, "cb"), (cr_base, "cr")):
                    perm.append(base + (s * crows + mr) * cbx + mc)
                    is_chroma.append(1)
                    i = len(perm) - 1
                    dc_prev.append(last.get(key, -1))
                    last[key] = i
    blocks_per_stripe = crows * mcols * 6
    return (
        np.asarray(perm, np.int32),
        np.asarray(is_chroma, np.int32),
        np.asarray(dc_prev, np.int32),
        blocks_per_stripe,
    )


def _bitlen(a):
    """Magnitude category of |a| (int32, |a| ≤ 2047): exact via f32 log2."""
    af = jnp.abs(a).astype(jnp.float32)
    return jnp.where(a == 0, 0, jnp.floor(jnp.log2(jnp.maximum(af, 1.0))) + 1
                     ).astype(jnp.int32)


def _vbits(v, size):
    """Value bits: v for v>0 else ones'-complement (T.81 F.1.2.1)."""
    raw = jnp.where(v > 0, v, v + (1 << size) - 1)
    return (raw & ((1 << size) - 1)).astype(jnp.uint32)


def _packed_ac_tables() -> np.ndarray:
    """[512] float32 packed (code<<5 | len) AC table, luma then chroma."""
    _, ac_l, _, ac_c = std_tables()
    packed = np.zeros(512, np.float32)
    for comp, tbl in ((0, ac_l), (1, ac_c)):
        packed[comp * 256:(comp + 1) * 256] = (
            tbl.code_arr.astype(np.int64) << 5) + tbl.len_arr.astype(np.int64)
    return packed


def _lut512(idx_flat):
    """packed = table[idx] for idx ∈ [0, 512), via two-level one-hot matmul.

    ``jnp.take`` gathers cost ~10 ns/element on the TPU scalar core (~25 ms
    at 3.1M lookups); routing the same lookup through the MXU costs ~2 ms.
    Values are ≤ 2^21 so float32 arithmetic is exact — but ONLY at
    ``Precision.HIGHEST``: the TPU MXU's default f32 path rounds operands
    to bf16 (8 mantissa bits), which silently corrupts the packed
    code/len table and with it the whole bitstream. (Found driving the
    encoder on a real v5e chip; CPU/GPU backends mask the bug because
    their f32 matmuls are true f32.)
    """
    table = _packed_ac_tables().reshape(32, 16)
    hi = idx_flat >> 4
    lo = idx_flat & 15
    rows = jnp.dot(jax.nn.one_hot(hi, 32, dtype=jnp.float32),
                   jnp.asarray(table),
                   precision=jax.lax.Precision.HIGHEST)
    picked = (rows * jax.nn.one_hot(lo, 16, dtype=jnp.float32)).sum(-1)
    return picked.astype(jnp.int32)


class DeviceEntropyPacker:
    """Per-geometry compiled entropy pack: coefficients → packed bitstreams.

    ``pack(yq, cbq, crq)`` returns:
      words  [cap_words] uint32 — all stripes' scans compacted back-to-back
             (each stripe starts word-aligned; bits are MSB-first, so bytes
             come from big-endian u32 serialization);
      nbytes [S] int32         — scan byte count per stripe (incl. padding);
      base_words [S] int32     — word offset of each stripe in ``words``;
      overflow [S] bool        — stripe unusable (host-code it instead).
    """

    #: slot grid per block: 2 DC slots + 63 × (ZRL-pair, ZRL+code, value) + pad
    SLOTS = 192

    def __init__(
        self,
        pad_h: int,
        pad_w: int,
        stripe_h: int,
        max_stripe_bytes: int = 1 << 15,
        block_words: int = 56,
    ) -> None:
        perm, is_chroma, dc_prev, bps = scan_geometry(pad_h, pad_w, stripe_h)
        self.n_stripes = pad_h // stripe_h
        self.blocks_per_stripe = bps
        self.max_stripe_words = max_stripe_bytes // 4
        self.block_words = block_words
        self.cap_words = self.n_stripes * self.max_stripe_words

        dc_l, ac_l, dc_c, ac_c = std_tables()
        # [2, 12] DC code/len (symbol = magnitude category 0..11)
        dc_code_t = np.stack([dc_l.code_arr[:12], dc_c.code_arr[:12]]).astype(np.uint32)
        dc_len_t = np.stack([dc_l.len_arr[:12], dc_c.len_arr[:12]]).astype(np.int32)
        zrl_c = (int(ac_l.code_arr[0xF0]), int(ac_c.code_arr[0xF0]))
        zrl_l = (int(ac_l.len_arr[0xF0]), int(ac_c.len_arr[0xF0]))
        eob_c = (int(ac_l.code_arr[0x00]), int(ac_c.code_arr[0x00]))
        eob_l = (int(ac_l.len_arr[0x00]), int(ac_c.len_arr[0x00]))

        S = self.n_stripes
        V = self.max_stripe_words
        W = self.block_words
        M = len(perm)
        SLOTS = self.SLOTS
        cap_words = self.cap_words
        chroma = jnp.asarray(is_chroma)          # [M]
        prevd = jnp.asarray(dc_prev)             # [M]
        permd = jnp.asarray(perm)

        def pack_fn(yq, cbq, crq):
            allb = jnp.concatenate(
                [yq.reshape(-1, 64), cbq.reshape(-1, 64), crq.reshape(-1, 64)]
            ).astype(jnp.int32)
            stream = allb[permd]                                 # [M, 64]

            # ---- DC symbols (per block) -----------------------------------
            dc = stream[:, 0]
            pred = jnp.where(prevd < 0, 0, dc[jnp.maximum(prevd, 0)])
            diff = dc - pred
            dsize = _bitlen(diff)                                # ≤ 11
            dci = chroma * 12 + dsize
            dcode = jnp.take(jnp.asarray(dc_code_t).reshape(-1), dci)
            dlen = jnp.take(jnp.asarray(dc_len_t).reshape(-1), dci)
            dc_b = jnp.stack([dcode, _vbits(diff, dsize)], axis=1)   # [M, 2]
            dc_l_ = jnp.stack([dlen, dsize], axis=1)

            # ---- AC symbols [M, 63] ---------------------------------------
            z = stream[:, 1:]
            nzm = z != 0
            posk = jnp.arange(1, 64, dtype=jnp.int32)[None, :]
            p = jnp.where(nzm, posk, 0)
            m_incl = jax.lax.associative_scan(jnp.maximum, p, axis=1)
            prev_excl = jnp.concatenate(
                [jnp.zeros((M, 1), jnp.int32), m_incl[:, :-1]], axis=1)
            run = posk - prev_excl - 1
            size = _bitlen(z)                                    # ≤ 10
            rem = run & 15
            nzrl = run >> 4                                      # 0..3

            idx = chroma[:, None] * 256 + ((rem << 4) | size)
            packed = _lut512(idx.reshape(-1)).reshape(M, 63)
            acode = (packed >> 5).astype(jnp.uint32)
            alen = packed & 31

            zc = jnp.where(chroma == 1, zrl_c[1], zrl_c[0]).astype(jnp.uint32)[:, None]
            zl = jnp.where(chroma == 1, zrl_l[1], zrl_l[0])[:, None]

            # slot 0: first two ZRLs; slot 1: third ZRL ∥ code; slot 2: value
            s0b = jnp.where(nzrl >= 2, (zc << zl.astype(jnp.uint32)) | zc,
                            jnp.where(nzrl >= 1, zc, 0))
            s0l = jnp.where(nzm, jnp.minimum(nzrl, 2) * zl, 0)
            s1b = jnp.where(nzrl >= 3, (zc << alen.astype(jnp.uint32)) | acode, acode)
            s1l = jnp.where(nzm, alen + jnp.where(nzrl >= 3, zl, 0), 0)
            s2b = _vbits(z, size)
            s2l = jnp.where(nzm, size, 0)

            # EOB folds into coefficient 63's (ZRL∥code) slot when the block
            # doesn't end in a nonzero coefficient.
            eob_on = m_incl[:, -1] != 63
            ec = jnp.where(chroma == 1, eob_c[1], eob_c[0]).astype(jnp.uint32)
            el = jnp.where(chroma == 1, eob_l[1], eob_l[0])
            s1b = s1b.at[:, 62].set(
                jnp.where(nzm[:, 62], s1b[:, 62], jnp.where(eob_on, ec, 0)))
            s1l = s1l.at[:, 62].set(
                jnp.where(nzm[:, 62], s1l[:, 62], jnp.where(eob_on, el, 0)))

            # ---- [M, 192] slot grid (emission order; last slot is padding)
            ac_b = jnp.stack([s0b, s1b, s2b], axis=2).reshape(M, 189)
            ac_l2 = jnp.stack([s0l, s1l, s2l], axis=2).reshape(M, 189)
            bits = jnp.concatenate(
                [dc_b.astype(jnp.uint32), ac_b, jnp.zeros((M, 1), jnp.uint32)], axis=1)
            lens = jnp.concatenate(
                [dc_l_, ac_l2, jnp.zeros((M, 1), jnp.int32)], axis=1)

            # ---- intra-block pack into ≤W words ---------------------------
            cum = jnp.cumsum(lens, axis=1)
            off = cum - lens                                     # [M, SLOTS]
            Lb = cum[:, -1]                                      # [M] ≥ 6
            blk_ovf = Lb > 32 * W

            j0 = jnp.minimum(off >> 5, W - 1)
            pos = off & 31
            sh = 32 - pos - lens
            safe = jnp.where(lens > 0, bits, 0)
            c0 = jnp.where(
                sh >= 0,
                safe << jnp.clip(sh, 0, 31).astype(jnp.uint32),
                safe >> jnp.clip(-sh, 0, 31).astype(jnp.uint32)).astype(jnp.uint32)
            c1 = jnp.where(
                sh < 0, safe << jnp.clip(32 + sh, 0, 31).astype(jnp.uint32),
                jnp.uint32(0)).astype(jnp.uint32)
            j1 = jnp.minimum(j0 + 1, W - 1)

            wk = jnp.arange(W, dtype=jnp.int32)[None, None, :]
            words_blk = (
                jnp.where(j0[..., None] == wk, c0[..., None], 0)
                + jnp.where(j1[..., None] == wk, c1[..., None], 0)
            ).sum(axis=1, dtype=jnp.uint32)                      # [M, W]

            # ---- block bases within stripe --------------------------------
            Lb2 = Lb.reshape(S, bps)
            cumb = jnp.cumsum(Lb2, axis=1)
            base = cumb - Lb2                                    # [S, bps] bits
            t_bits = cumb[:, -1]
            pad = (-t_bits) % 8
            t_bytes = ((t_bits + pad) // 8).astype(jnp.int32)

            g0 = base >> 5                                       # [S, bps]
            r = base & 31
            e = (base + Lb2 - 1) >> 5                            # last word touched

            # ---- globalize block words (analytic indices) -----------------
            v = words_blk.reshape(S, bps, W)
            r3 = r[..., None]
            u0 = v >> r3.astype(jnp.uint32)
            u1 = jnp.where(r3 == 0, jnp.uint32(0),
                           v << (32 - r3).astype(jnp.uint32))
            cs0 = jnp.cumsum(u0.reshape(S, bps * W), axis=1, dtype=jnp.uint32)
            cs1 = jnp.cumsum(u1.reshape(S, bps * W), axis=1, dtype=jnp.uint32)

            # boundary block per output word: last block with g0 ≤ w
            g0c = jnp.clip(g0, 0, V - 1)
            srows = jnp.arange(S, dtype=jnp.int32)[:, None]
            bidx = jnp.arange(bps, dtype=jnp.int32)[None, :]
            lastblk = jnp.zeros((S, V), jnp.int32).at[srows, g0c].max(bidx)
            lastblk = jax.lax.associative_scan(jnp.maximum, lastblk, axis=1)

            # pack (g0, e) for one boundary gather: both < 2^15
            ge = (jnp.clip(g0, 0, (1 << 15) - 1) << 16) | (
                jnp.clip(e + 1, 0, (1 << 15) - 1))
            ge_b = jnp.take_along_axis(ge, lastblk, axis=1)       # [S, V]
            g0b = ge_b >> 16
            e1b = ge_b & 0xFFFF                                   # e + 1
            w_ar = jnp.arange(V, dtype=jnp.int32)[None, :]

            jstar = jnp.where(e1b <= w_ar, W - 1,
                              jnp.minimum(w_ar - g0b, W - 1))
            s_at0 = jnp.take_along_axis(cs0, lastblk * W + jstar, axis=1)
            word0 = s_at0 - jnp.concatenate(
                [jnp.zeros((S, 1), jnp.uint32), s_at0[:, :-1]], axis=1)

            # stream-1 boundary: last block with g0 ≤ w-1 (shift by one word)
            lastblk1 = jnp.concatenate(
                [jnp.zeros((S, 1), jnp.int32), lastblk[:, :-1]], axis=1)
            ge_b1 = jnp.take_along_axis(ge, lastblk1, axis=1)
            g0b1 = ge_b1 >> 16
            e1b1 = ge_b1 & 0xFFFF
            jstar1 = jnp.where(e1b1 + 1 <= w_ar, W - 1,
                               jnp.clip(w_ar - 1 - g0b1, 0, W - 1))
            s_at1 = jnp.take_along_axis(cs1, lastblk1 * W + jstar1, axis=1)
            s_at1 = jnp.where(w_ar == 0, 0, s_at1)
            word1 = s_at1 - jnp.concatenate(
                [jnp.zeros((S, 1), jnp.uint32), s_at1[:, :-1]], axis=1)

            words_stripe = word0 + word1                          # [S, V]

            # ---- stripe byte-alignment padding (1-bits) -------------------
            mask = ((1 << pad) - 1).astype(jnp.uint32)
            ppos = t_bits & 31
            psh = 32 - ppos - pad
            pw = jnp.clip(t_bits >> 5, 0, V - 1)
            pc0 = jnp.where(psh >= 0, mask << jnp.clip(psh, 0, 31).astype(jnp.uint32),
                            mask >> jnp.clip(-psh, 0, 31).astype(jnp.uint32))
            pc1 = jnp.where(psh < 0,
                            mask << jnp.clip(32 + psh, 0, 31).astype(jnp.uint32),
                            jnp.uint32(0))
            srow = jnp.arange(S, dtype=jnp.int32)
            words_stripe = words_stripe.at[srow, pw].add(pc0.astype(jnp.uint32))
            words_stripe = words_stripe.at[srow, jnp.clip(pw + 1, 0, V - 1)].add(
                pc1.astype(jnp.uint32))

            # ---- compaction (stripes back-to-back, word aligned) ----------
            wc = jnp.minimum((t_bytes + 3) // 4, V)
            base_words = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.cumsum(wc)[:-1].astype(jnp.int32)])
            j = jnp.arange(cap_words, dtype=jnp.int32)
            sidx = jnp.clip(
                jnp.searchsorted(base_words, j, side="right") - 1, 0, S - 1)
            src = sidx * V + jnp.clip(j - base_words[sidx], 0, V - 1)
            valid = j < (base_words[-1] + wc[-1])
            compacted = jnp.where(valid, words_stripe.reshape(-1)[src], 0)

            stripe_overflow = (t_bytes > V * 4) | blk_ovf.reshape(S, bps).any(axis=1)
            return compacted, t_bytes, base_words, stripe_overflow

        self._pack_fn = pack_fn
        self._pack = jax.jit(pack_fn)

    def pack(self, yq, cbq, crq):
        return self._pack(yq, cbq, crq)

    def bucket_words(self, total_words: int) -> int:
        """Power-of-two fetch size for a packed-word count (bounds the number
        of distinct slice executables compiled for D2H)."""
        n = 1024
        while n < total_words:
            n <<= 1
        return min(n, self.cap_words)


def stuff_bytes(scan: bytes) -> bytes:
    """JPEG byte stuffing (0xFF → 0xFF 0x00) over a scan, vectorized."""
    arr = np.frombuffer(scan, dtype=np.uint8)
    idx = np.flatnonzero(arr == 0xFF)
    if idx.size == 0:
        return scan
    return np.insert(arr, idx + 1, 0).tobytes()


def words_to_stripe_bytes(
    words: np.ndarray, base_words: np.ndarray, nbytes: np.ndarray
) -> Tuple[bytes, ...]:
    """Split the compacted word buffer into per-stripe scan byte strings."""
    be = words.astype(">u4").tobytes()
    out = []
    for s in range(len(nbytes)):
        start = int(base_words[s]) * 4
        out.append(be[start:start + int(nbytes[s])])
    return tuple(out)
