"""JFIF (baseline JPEG) container writer.

Each stripe is an independent, self-contained JFIF image — the stripe is the
unit of parallelism and of client-side decode (the reference client feeds each
0x03 payload straight to an ``ImageDecoder``, selkies-core.js:2908-2924).
"""

from __future__ import annotations

import struct

import numpy as np

from .jpeg_tables import std_tables
from ..ops.quant import ZIGZAG


def _marker(tag: int, payload: bytes) -> bytes:
    return struct.pack(">BBH", 0xFF, tag, len(payload) + 2) + payload


def jfif_headers(
    width: int,
    height: int,
    qtable_luma: np.ndarray,
    qtable_chroma: np.ndarray,
    subsampling: str = "420",
) -> bytes:
    """SOI..SOS headers for a 3-component YCbCr baseline image.

    ``qtable_*`` are 8x8 arrays in raster order (written zigzagged, as DQT
    requires). ``subsampling``: "420" (2x2,1x1,1x1) or "444".
    """
    zz = ZIGZAG
    dc_l, ac_l, dc_c, ac_c = std_tables()

    out = bytearray(b"\xff\xd8")  # SOI
    out += _marker(0xE0, b"JFIF\x00\x01\x01\x00\x00\x01\x00\x01\x00\x00")  # APP0

    ql = qtable_luma.reshape(64).astype(np.uint8)[zz]
    qc = qtable_chroma.reshape(64).astype(np.uint8)[zz]
    out += _marker(0xDB, bytes([0x00]) + ql.tobytes())  # DQT id 0
    out += _marker(0xDB, bytes([0x01]) + qc.tobytes())  # DQT id 1

    if subsampling == "420":
        y_sampling = 0x22
    elif subsampling == "444":
        y_sampling = 0x11
    else:
        raise ValueError(f"unsupported subsampling {subsampling!r}")
    sof = struct.pack(">BHHB", 8, height, width, 3)
    sof += bytes([1, y_sampling, 0])  # Y: id 1, sampling, qtable 0
    sof += bytes([2, 0x11, 1])        # Cb
    sof += bytes([3, 0x11, 1])        # Cr
    out += _marker(0xC0, sof)  # SOF0 baseline

    out += _marker(0xC4, dc_l.dht_payload(0, 0))
    out += _marker(0xC4, ac_l.dht_payload(1, 0))
    out += _marker(0xC4, dc_c.dht_payload(0, 1))
    out += _marker(0xC4, ac_c.dht_payload(1, 1))

    sos = bytes([3, 1, 0x00, 2, 0x11, 3, 0x11, 0, 63, 0])
    out += _marker(0xDA, sos)
    return bytes(out)


EOI = b"\xff\xd9"
