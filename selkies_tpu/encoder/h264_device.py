"""Device-side H.264 stripe encode step (tpuenc v1).

Replaces the reference's x264/NVENC encode stage (pixelflux striped x264;
legacy gstwebrtc_app.py:260-770 encoder zoo) with a jit-compiled JAX
pipeline.  TPU-first structure — every macroblock is processed in parallel;
there are NO sequential prediction chains on device:

* IDR stripes use Intra16x16 DC prediction with every MB in its own slice,
  which makes the prediction the constant 128 (all neighbors unavailable,
  §8.3.3) — exact, conformant, and embarrassingly parallel.  The per-MB
  slice-header cost is a few bytes and only paid on keyframes.
* P stripes are inter-only (P_16x16, one integer-pel MV per MB searched
  exhaustively on device).  MV *prediction* (median) only affects bitstream
  MVD bits, so it lives in the host entropy coder, not on device.
* The reconstruction loop (dequant → inverse transform → clip) runs on
  device with the exact decoder arithmetic from ops/h264_transform.py, so
  the reference frames match a conformant decoder bit-for-bit.

Each stripe is an independent video sequence (the client runs one
VideoDecoder per stripe Y — reference selkies-core.js:2925-2968), so ME
never crosses stripe boundaries.

Outputs are quantized level arrays + MVs; the host C++ coder (cavlc.cpp)
turns them into Annex-B NAL units.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import h264_transform as ht
from ..ops.color import rgb_to_ycbcr, subsample_420
from ..ops.motion import (full_search_mc, full_search_mc_scan,
                          full_search_mv, mc_chroma, mc_luma,
                          pad_replicate)
from ..ops.pallas_me import me_mc_stripes

MB = 16
SEARCH = 12


def _me_backend() -> str:
    """'pallas' (default: VMEM-resident kernel) or 'xla' (chunked scan)."""
    import os
    return os.environ.get("SELKIES_TPU_ME", "pallas")


class StripeEncodeOut(NamedTuple):
    """Device outputs for one stripe (n = number of MBs, raster order).

    Luma 4×4 blocks are indexed (row-major 4×4 grid within the MB); the
    host coder reorders to the spec's 8×8-then-raster scan.
    """
    mv: jnp.ndarray            # (n, 2) int32 (dy, dx); zeros for IDR
    luma: jnp.ndarray          # (n, 16, 4, 4) int32 quantized levels
    luma_dc: jnp.ndarray       # (n, 4, 4) int32 (IDR only; zeros for P)
    chroma_dc: jnp.ndarray     # (n, 2, 2, 2) int32
    chroma_ac: jnp.ndarray     # (n, 2, 4, 4, 4) int32 (position 0 zeroed)
    recon_y: jnp.ndarray       # (H, W) uint8
    recon_cb: jnp.ndarray      # (H/2, W/2) uint8
    recon_cr: jnp.ndarray      # (H/2, W/2) uint8


def _mb_blocks(plane: jnp.ndarray, mb: int = MB) -> jnp.ndarray:
    """(H, W) → (n_mb, mb//4 * mb//4, 4, 4), raster MBs, raster 4×4s."""
    h, w = plane.shape
    nby, nbx = h // mb, w // mb
    g = mb // 4
    v = plane.reshape(nby, mb, nbx, mb).swapaxes(1, 2)     # (nby,nbx,mb,mb)
    v = v.reshape(nby * nbx, g, 4, g, 4).swapaxes(2, 3)    # (n,g,g,4,4)
    return v.reshape(nby * nbx, g * g, 4, 4)


def _mb_unblocks(blocks: jnp.ndarray, h: int, w: int, mb: int = MB
                 ) -> jnp.ndarray:
    """Inverse of :func:`_mb_blocks`."""
    nby, nbx = h // mb, w // mb
    g = mb // 4
    v = blocks.reshape(nby * nbx, g, g, 4, 4).swapaxes(2, 3)
    v = v.reshape(nby, nbx, mb, mb).swapaxes(1, 2)
    return v.reshape(h, w)


#: x264-style decimation weights per 4×4 coefficient position: the cost
#: of a LONE |level|==1 coefficient there (x264 decimate_table4 indexed
#: by the reverse-zigzag leading run, mapped back to (row, col)). High
#: frequencies are expensive (they force coding every run before them),
#: low frequencies nearly free. Clustered coefficients over-count with
#: this per-position sum — i.e. the approximation only KEEPS more.
_DECIMATE_W = np.array([[0, 0, 0, 0],
                        [0, 0, 0, 1],
                        [0, 0, 1, 2],
                        [0, 1, 2, 3]], np.int32)


def _decimate_score(z):
    """Per-block x264-style decimation score; (..., 4, 4) → (...)."""
    a = jnp.abs(z)
    w = jnp.asarray(_DECIMATE_W)
    # any |level|>1 prices the block out of decimation (score 9 each)
    per = jnp.where(a > 1, 9, jnp.where(a == 1, w, 0))
    return per.sum(axis=(-2, -1))


def _encode_luma_residual(res_blocks, qp, intra, decimate: bool = False):
    """4×4 transform+quant and exact decoder-side reconstruction.

    res_blocks: (n, 16, 4, 4) int32 residual.
    Returns (levels, recon_res) — both (n, 16, 4, 4) int32.

    ``decimate`` (inter only) drops a macroblock's whole luma residual
    when its x264-style score is < 6 — the "single small coefficient"
    noise that costs cbp+run bits but buys no visible quality (x264
    x264_macroblock_probe_skip / decimate path). The round-4 quality
    gate measured isolated ±1 coefficients as a dominant bit cost on
    near-static desktop content. The zeroed levels feed the
    reconstruction below, so encoder refs stay decoder-exact.
    """
    w = ht.forward_dct4(res_blocks)
    z = ht.quant4(w, qp, intra=intra)
    if decimate and not intra:
        mb_score = _decimate_score(z).sum(axis=-1)        # (n,)
        keep = (mb_score >= 6)[:, None, None, None]
        z = jnp.where(keep, z, 0)
    d = ht.dequant4(z, qp)
    r = ht.inverse_dct4(d)
    return z, r


def _encode_luma_i16(res_blocks, qp):
    """Intra16x16 luma path: Hadamard DC + AC-only 4×4 levels.

    res_blocks: (n, 16, 4, 4).  Returns (z_dc (n,4,4), z_ac (n,16,4,4),
    recon_res (n,16,4,4)).
    """
    w = ht.forward_dct4(res_blocks)                    # (n,16,4,4)
    dc = w[..., 0, 0].reshape(-1, 4, 4)                # raster DC grid
    y = ht.hadamard4_fwd(dc)
    z_dc = ht.quant_dc16(y, qp)
    d_dc = ht.dequant_dc16(z_dc, qp)                   # (n,4,4), = 4·W scale
    z_ac = ht.quant4(w, qp, intra=True)
    z_ac = z_ac.at[..., 0, 0].set(0)
    d = ht.dequant4(z_ac, qp)
    d = d.at[..., 0, 0].set(d_dc.reshape(-1, 16))
    r = ht.inverse_dct4(d)
    return z_dc, z_ac, r


def _encode_chroma(res_blocks, qpc, intra, decimate: bool = False):
    """Chroma path (always DC 2×2 Hadamard + AC blocks).

    res_blocks: (n, 4, 4, 4) one component, 4 4×4 blocks per MB (2×2 grid).
    Returns (z_dc (n,2,2), z_ac (n,4,4,4), recon_res (n,4,4,4)).

    ``decimate`` drops the component's AC levels when their per-MB
    score is ≤ 3 (x264 uses < 7 over both components combined; each
    component separately at half that is the conservative split). DC
    always survives — it carries the visible tint.
    """
    w = ht.forward_dct4(res_blocks)                    # (n,4,4,4)
    dc = w[..., 0, 0].reshape(-1, 2, 2)
    y = ht.hadamard2_fwd(dc)
    z_dc = ht.quant_dc2(y, qpc)
    d_dc = ht.dequant_dc2(z_dc, qpc)
    z_ac = ht.quant4(w, qpc, intra=intra)
    z_ac = z_ac.at[..., 0, 0].set(0)
    if decimate and not intra:
        score = _decimate_score(z_ac).sum(axis=-1)     # (n,)
        keep = (score > 3)[:, None, None, None]
        z_ac = jnp.where(keep, z_ac, 0)
    d = ht.dequant4(z_ac, qpc)
    d = d.at[..., 0, 0].set(d_dc.reshape(-1, 4))
    r = ht.inverse_dct4(d)
    return z_dc, z_ac, r


def _clip8(x):
    return jnp.clip(x, 0, 255).astype(jnp.uint8)


@jax.jit
def encode_stripe_idr(y, cb, cr, qp) -> StripeEncodeOut:
    """IDR stripe: I16x16/DC with per-MB slices (pred ≡ 128).

    ``qp`` is traced (one compile covers every QP — paint-over and rate
    control change it per frame).
    """
    qpc = ht.qpc_for(qp)
    h, w = y.shape
    n = (h // MB) * (w // MB)

    res_y = _mb_blocks(y.astype(jnp.int32) - 128)
    z_dc, z_ac, r = _encode_luma_i16(res_y, qp)
    recon_y = _clip8(_mb_unblocks(r + 128, h, w))

    outs_c = []
    recons_c = []
    for plane in (cb, cr):
        res = _mb_blocks(plane.astype(jnp.int32) - 128, mb=MB // 2)
        zc_dc, zc_ac, rc = _encode_chroma(res, qpc, intra=True)
        outs_c.append((zc_dc, zc_ac))
        recons_c.append(_clip8(_mb_unblocks(rc + 128, h // 2, w // 2,
                                            mb=MB // 2)))

    return StripeEncodeOut(
        mv=jnp.zeros((n, 2), jnp.int32),
        luma=z_ac,
        luma_dc=z_dc,
        chroma_dc=jnp.stack([outs_c[0][0], outs_c[1][0]], axis=1),
        chroma_ac=jnp.stack([outs_c[0][1], outs_c[1][1]], axis=1),
        recon_y=recon_y,
        recon_cb=recons_c[0],
        recon_cr=recons_c[1],
    )


@functools.partial(jax.jit, static_argnames=("search",))
def encode_stripe_p(y, cb, cr, ref_y, ref_cb, ref_cr, qp,
                    search: int = SEARCH) -> StripeEncodeOut:
    """P stripe: P_16x16 with device full-search integer-pel ME."""
    mv_grid, pred_y, pred_cb, pred_cr = full_search_mc(
        y, ref_y, ref_cb, ref_cr, mb=MB, search=search)
    return encode_stripe_p_pred(y, cb, cr, mv_grid, pred_y, pred_cb,
                                pred_cr, qp)


@jax.jit
def encode_stripe_p_pred(y, cb, cr, mv_grid, pred_y, pred_cb, pred_cr,
                         qp) -> StripeEncodeOut:
    """P stripe transform/quant/recon given precomputed ME predictions
    (the production path runs ME for all stripes in one Pallas kernel —
    ops/pallas_me.py — and feeds the winners here)."""
    qpc = ht.qpc_for(qp)
    h, w = y.shape

    res_y = _mb_blocks(y.astype(jnp.int32) - pred_y.astype(jnp.int32))
    z_l, r = _encode_luma_residual(res_y, qp, intra=False, decimate=True)
    recon_y = _clip8(
        _mb_unblocks(r, h, w) + pred_y.astype(jnp.int32))

    outs_c = []
    recons_c = []
    for plane, pred in ((cb, pred_cb), (cr, pred_cr)):
        res = _mb_blocks(plane.astype(jnp.int32) - pred.astype(jnp.int32),
                         mb=MB // 2)
        zc_dc, zc_ac, rc = _encode_chroma(res, qpc, intra=False,
                                          decimate=True)
        outs_c.append((zc_dc, zc_ac))
        recons_c.append(_clip8(
            _mb_unblocks(rc, h // 2, w // 2, mb=MB // 2)
            + pred.astype(jnp.int32)))

    n = (h // MB) * (w // MB)
    return StripeEncodeOut(
        mv=mv_grid.reshape(n, 2),
        luma=z_l,
        luma_dc=jnp.zeros((n, 4, 4), jnp.int32),
        chroma_dc=jnp.stack([outs_c[0][0], outs_c[1][0]], axis=1),
        chroma_ac=jnp.stack([outs_c[0][1], outs_c[1][1]], axis=1),
        recon_y=recon_y,
        recon_cb=recons_c[0],
        recon_cr=recons_c[1],
    )


def _stripe_view(plane, n_stripes, sh):
    return plane.reshape(n_stripes, sh, plane.shape[-1])


def _collapse_mv_ties(cur, ref, ref_cb, ref_cr, mv,
                      pred_y, pred_cb, pred_cr, *, search: int):
    """Re-point SAD-tied macroblocks at the stripe's dominant motion.

    The exhaustive search breaks SAD ties toward small |mv| per MB in
    isolation. On desktop content that checkerboards flat regions
    between mv=0 and the true motion, so the host coder's P_Skip runs
    never form and every such MB pays mb_type+mvd+cbp syntax — measured
    ~12x the bits of x264 superfast at equal PSNR on scrolling text
    (tools/quality_measure.py, the round-4 quality gate). x264 solves
    this with rate-aware MV costs inside the search; the TPU-shaped
    equivalent is this whole-stripe post-pass: find the stripe's most
    common winner, and move every MB whose SAD at that offset EQUALS
    its winner's SAD (a true tie — quality is untouched) onto it. The
    MV field then collapses to long uniform runs that skip/mvd-predict
    to almost nothing. Pure XLA, so every ME backend shares it.

    cur/ref: (h, w) uint8; ref_cb/ref_cr: (hc, wc) uint8.
    """
    h, w = cur.shape
    hc, wc = ref_cb.shape
    nby, nbx = h // MB, w // MB
    n = 2 * search + 1

    ridx = (mv[..., 0] + search) * n + (mv[..., 1] + search)
    counts = (ridx.reshape(-1, 1)
              == jnp.arange(n * n, dtype=jnp.int32)[None, :]).sum(0)
    dom = jnp.argmax(counts).astype(jnp.int32)      # first max = lowest idx
    ddy = dom // n - search
    ddx = dom % n - search

    # luma prediction at the dominant offset: one dynamic-base slice of
    # the replicate-padded window (a fast DMA, not a gather)
    win = pad_replicate(ref, search)
    ref_dom = jax.lax.dynamic_slice(
        win, (search + ddy, search + ddx), (h, w))
    cur_i = cur.astype(jnp.int32)
    sad_dom = jnp.abs(cur_i - ref_dom.astype(jnp.int32)) \
        .reshape(nby, MB, nbx, MB).sum(axis=(1, 3))
    sad_best = jnp.abs(cur_i - pred_y.astype(jnp.int32)) \
        .reshape(nby, MB, nbx, MB).sum(axis=(1, 3))
    take = sad_dom <= sad_best                       # == : a true tie

    mv_new = jnp.where(take[..., None],
                       jnp.stack([ddy, ddx]).astype(jnp.int32)[None, None],
                       mv)
    take_px = jnp.repeat(jnp.repeat(take, MB, 0), MB, 1)
    pred_y2 = jnp.where(take_px, ref_dom.astype(jnp.uint8), pred_y)

    # chroma at the dominant offset (§8.4.2.2.2: integer luma mv →
    # {0,4}-eighth bilinear); arithmetic >> and & match the per-offset
    # path in ops/motion.py chroma_pred
    rc = search // 2 + 1
    iy, ix = ddy >> 1, ddx >> 1
    yf, xf = (ddy & 1) * 4, (ddx & 1) * 4
    out_c = []
    for cp in (ref_cb, ref_cr):
        cpad = pad_replicate(cp.astype(jnp.int32), rc + 1)
        a = jax.lax.dynamic_slice(
            cpad, (rc + 1 + iy, rc + 1 + ix), (hc + 1, wc + 1))
        tl = a[:hc, :wc]
        tr = a[:hc, 1:]
        bl = a[1:, :wc]
        br = a[1:, 1:]
        acc = ((8 - xf) * (8 - yf) * tl + xf * (8 - yf) * tr
               + (8 - xf) * yf * bl + xf * yf * br + 32) >> 6
        out_c.append(acc.astype(jnp.uint8))
    cb2 = MB // 2
    take_cx = jnp.repeat(jnp.repeat(take, cb2, 0), cb2, 1)
    pred_cb2 = jnp.where(take_cx, out_c[0], pred_cb)
    pred_cr2 = jnp.where(take_cx, out_c[1], pred_cr)
    return mv_new, pred_y2, pred_cb2, pred_cr2


def _frame_p_core(y, cb, cr, prev_y, prev_cb, prev_cr,
                  ref_y, ref_cb, ref_cr, paint, qp, paint_qp,
                  *, n_stripes: int, sh: int, search: int,
                  me: str = "pallas"):
    """Shared body of the dense whole-frame P encode: every stripe in ONE
    dispatch.

    Per-stripe dispatches cost ~25-100 ms each on RPC-attached devices —
    17 stripes × latency swamped the encode itself (round-1 H.264 ran at
    ~1 fps). Here stripes ride a vmap axis, damage detection runs in the
    same program, and undamaged stripes keep their old reference planes
    via an on-device select, so the host makes exactly one fetch.
    """
    S = n_stripes
    ys = _stripe_view(y, S, sh)
    pys = _stripe_view(prev_y, S, sh)
    pcbs = _stripe_view(prev_cb, S, sh // 2)
    pcrs = _stripe_view(prev_cr, S, sh // 2)
    rys = _stripe_view(ref_y, S, sh)
    rcbs = _stripe_view(ref_cb, S, sh // 2)
    rcrs = _stripe_view(ref_cr, S, sh // 2)
    cbs = _stripe_view(cb, S, sh // 2)
    crs = _stripe_view(cr, S, sh // 2)

    damage = jax.vmap(
        lambda a, b, c, d, e, f:
        jnp.any(a != b) | jnp.any(c != d) | jnp.any(e != f)
    )(ys, pys, cbs, pcbs, crs, pcrs)

    update = damage | (paint != 0)
    qps = jnp.where(paint != 0, paint_qp, qp)            # [S]

    # ME for every stripe in ONE VMEM-resident kernel (ops/pallas_me.py),
    # then the per-stripe transform/quant/recon rides a vmap. The XLA
    # chunked search remains selectable (SELKIES_TPU_ME=xla): over the
    # tunneled dev transport, per-dispatch RPC overhead — not device
    # compute — decides end-to-end fps, and the two backends trade
    # differently there.
    if me == "pallas":
        mv, pred_y, pred_cb, pred_cr = me_mc_stripes(
            ys, rys, rcbs, rcrs, search=search)
    else:
        fn = full_search_mc_scan if me == "scan" else full_search_mc
        mv, pred_y, pred_cb, pred_cr = jax.vmap(
            functools.partial(fn, mb=MB, search=search)
        )(ys, rys, rcbs, rcrs)
    # SAD-tied MBs re-point at each stripe's dominant motion so skip
    # runs form (same quality, far fewer syntax bits — see
    # _collapse_mv_ties); shared across every ME backend
    mv, pred_y, pred_cb, pred_cr = jax.vmap(
        functools.partial(_collapse_mv_ties, search=search)
    )(ys, rys, rcbs, rcrs, mv, pred_y, pred_cb, pred_cr)
    enc = jax.vmap(encode_stripe_p_pred)(
        ys, cbs, crs, mv, pred_y, pred_cb, pred_cr, qps)

    sel = update[:, None, None]
    new_ref_y = jnp.where(sel, enc.recon_y, rys).reshape(y.shape)
    new_ref_cb = jnp.where(sel, enc.recon_cb, rcbs).reshape(cb.shape)
    new_ref_cr = jnp.where(sel, enc.recon_cr, rcrs).reshape(cr.shape)

    return enc, damage, update, new_ref_y, new_ref_cb, new_ref_cr


@functools.partial(jax.jit, static_argnames=("n_stripes", "sh", "search", "me"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_p(y, cb, cr, prev_y, prev_cb, prev_cr,
                   ref_y, ref_cb, ref_cr, paint, qp, paint_qp,
                   *, n_stripes: int, sh: int, search: int = SEARCH,
                   me: str = "pallas"):
    """Dense P encode returning (flat8, flat16, ...): flat8 is the
    i8-packed coefficient buffer + per-stripe damage/overflow tail, flat16
    the exact levels for rare |level|>127 stripes."""
    enc, damage, update, new_ref_y, new_ref_cb, new_ref_cr = _frame_p_core(
        y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
        paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
        me=me)
    flat16, flat8 = _pack_levels(enc, damage, update)
    return flat8, flat16, y, cb, cr, new_ref_y, new_ref_cb, new_ref_cr


#: sparse pack geometry: levels are grouped into 16-element cells; a
#: per-cell nonzero bitmap + the compacted nonzero cells are the transfer
CELL = 16


def sparse_geometry(stripe_words: int,
                    cap_frac: int = 4) -> "tuple[int, int, int]":
    """(padded_words, n_cells, cap_cells) for one stripe's flat16 row."""
    pad_words = -(-stripe_words // (CELL * 8)) * (CELL * 8)
    n_cells = pad_words // CELL
    cap = max(1, n_cells // cap_frac)
    return pad_words, n_cells, cap


def _pack_sparse(flat16, damage, update, cap_frac: int = 4):
    """Block-sparse device pack of the level buffer (P frames).

    Most 16-element cells of the coefficient buffer are all-zero at
    streaming QPs, and D2H bandwidth — not compute — bounds H.264 fps on
    RPC-attached devices (3.3 MB/frame dense at 1080p → ~5 fps over the
    tunnel). Ship a per-cell bitmap plus only the nonzero cells,
    compacted back-to-back across stripes so the host can fetch a
    prefix sized by the actual content:

      head   [S, 4]  u8  — count_lo, count_hi, damage, overflow
      bitmap [S, n_cells/8] u8 — LSB-first cell-nonzero bits
      cells  [total ≤ S*cap*CELL] u8 — int8 cell values, stripes
             back-to-back in bitmap order

    Overflow (cell count > cap, or |level| > 127) falls back to the
    exact flat16 row for that stripe, like the dense path's tail flags.
    """
    S, W = flat16.shape
    pad_words, n_cells, cap = sparse_geometry(W, cap_frac)
    blk = jnp.pad(flat16, ((0, 0), (0, pad_words - W))) \
        .reshape(S, n_cells, CELL)
    nzb = (blk != 0).any(-1) & update[:, None]            # [S, B]
    count = nzb.sum(axis=1).astype(jnp.int32)             # [S]
    # nonzero cells first, original order preserved (stable sort)
    order = jnp.argsort(~nzb, axis=1, stable=True)[:, :cap]
    cells16 = jnp.take_along_axis(blk, order[:, :, None], axis=1)
    range_ovf = (jnp.abs(cells16) > 127).any(axis=(1, 2))
    ovf = range_ovf | (count > cap)
    cells8 = jnp.clip(cells16, -127, 127).astype(jnp.int8)

    weights = (1 << jnp.arange(8, dtype=jnp.int32))
    bitmap = (nzb.reshape(S, n_cells // 8, 8).astype(jnp.int32)
              * weights[None, None, :]).sum(-1).astype(jnp.uint8)

    # compact used cells back-to-back across stripes
    used = jnp.minimum(count, cap) * CELL                 # bytes per stripe
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(used)[:-1]])
    total_cap = S * cap * CELL
    j = jnp.arange(total_cap, dtype=jnp.int32)
    sidx = jnp.clip(jnp.searchsorted(starts, j, side="right") - 1, 0, S - 1)
    within = j - starts[sidx]
    valid = within < used[sidx]
    flat_cells = cells8.reshape(S, cap * CELL)
    gathered = flat_cells[sidx, jnp.clip(within, 0, cap * CELL - 1)]
    cells_out = jnp.where(valid, gathered, jnp.int8(0))

    head = jnp.stack([
        (count & 0xFF).astype(jnp.uint8),
        ((count >> 8) & 0xFF).astype(jnp.uint8),
        damage.astype(jnp.uint8),
        ovf.astype(jnp.uint8),
    ], axis=1)                                            # [S, 4]
    return jnp.concatenate([
        head.reshape(-1),
        bitmap.reshape(-1),
        cells_out.view(jnp.uint8),
    ])


@functools.partial(jax.jit,
                   static_argnames=("n_stripes", "sh", "search", "cap_frac", "me"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_p_sparse(y, cb, cr, prev_y, prev_cb, prev_cr,
                          ref_y, ref_cb, ref_cr, paint, qp, paint_qp,
                          *, n_stripes: int, sh: int, search: int = SEARCH,
                          cap_frac: int = 4, me: str = "pallas"):
    """P encode with the block-sparse transfer: returns (sparse_buf,
    flat16, new state...). sparse_buf layout is documented on
    :func:`_pack_sparse`; flat16 backs per-stripe overflow re-reads."""
    enc, damage, update, new_ref_y, new_ref_cb, new_ref_cr = _frame_p_core(
        y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
        paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
        me=me)
    flat16, _ = _pack_levels(enc, damage, update)
    buf = _pack_sparse(flat16, damage, update, cap_frac=cap_frac)
    return buf, flat16, y, cb, cr, new_ref_y, new_ref_cb, new_ref_cr


@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "n_stripes", "sh",
                                    "search", "cap_frac", "prefix", "me"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_p_rgb(rgb, prev_y, prev_cb, prev_cr,
                       ref_y, ref_cb, ref_cr, paint, qp, paint_qp,
                       *, pad_h: int, pad_w: int, n_stripes: int, sh: int,
                       search: int = SEARCH, cap_frac: int = 4,
                       prefix: int = 0, me: str = "pallas"):
    """Whole per-frame P program in ONE dispatch: RGB→planes, damage,
    ME/MC, transform/quant/recon, sparse pack, and the fetch-prefix slice.

    On RPC-attached transports each *program dispatch* pays a fixed
    round-trip, so the eager prepare_planes ops + separate prefix slice
    that used to surround :func:`encode_frame_p_sparse` cost more wall
    time than the encode itself. ``prefix`` > 0 additionally returns
    ``buf[:prefix]`` so the pipeline's fetch needs no separate slice
    program."""
    y, cb, cr = prepare_planes(rgb, pad_h, pad_w)
    enc, damage, update, new_ref_y, new_ref_cb, new_ref_cr = _frame_p_core(
        y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
        paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
        me=me)
    flat16, _ = _pack_levels(enc, damage, update)
    buf = _pack_sparse(flat16, damage, update, cap_frac=cap_frac)
    head = buf[:prefix] if prefix else buf
    return (buf, head, flat16, y, cb, cr,
            new_ref_y, new_ref_cb, new_ref_cr)


@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "n_stripes", "sh",
                                    "search", "max_stripe_bytes", "prefix",
                                    "me"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_p_cavlc_rgb(rgb, prev_y, prev_cb, prev_cr,
                             ref_y, ref_cb, ref_cr, paint, qp, paint_qp,
                             *, pad_h: int, pad_w: int, n_stripes: int,
                             sh: int, search: int = SEARCH,
                             max_stripe_bytes: int = 0, prefix: int = 0,
                             me: str = "pallas"):
    """P encode with ON-DEVICE CAVLC: the whole per-frame program — planes,
    damage, ME/MC, transform/quant/recon, entropy coding, and the
    fetch-prefix slice — in ONE dispatch.  The host fetches per-stripe
    bit-exact P-slice payloads (encoder/device_cavlc.py) instead of the
    block-sparse level buffer, shrinking the named D2H bottleneck to the
    actual bitstream size; flat16 stays on device for overflow/resync."""
    from . import device_cavlc as dcav

    y, cb, cr = prepare_planes(rgb, pad_h, pad_w)
    enc, damage, update, new_ref_y, new_ref_cb, new_ref_cr = _frame_p_core(
        y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
        paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
        me=me)
    flat16, _ = _pack_levels(enc, damage, update)
    S = n_stripes
    buf = dcav.pack_p_frame(
        enc.mv.reshape(S, -1, 2),
        enc.luma.reshape(S, -1, 16, 4, 4),
        enc.chroma_dc.reshape(S, -1, 2, 2, 2),
        enc.chroma_ac.reshape(S, -1, 2, 4, 4, 4),
        damage, update, mb_w=pad_w // MB, mb_h=sh // MB,
        max_stripe_bytes=max_stripe_bytes)
    head = buf[:prefix] if prefix else buf
    return (buf, head, flat16, y, cb, cr,
            new_ref_y, new_ref_cb, new_ref_cr)


#: no donation — see encode_frame_p_batch_rgb
@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "n_stripes", "sh",
                                    "search", "max_stripe_bytes", "prefix",
                                    "me"))
def encode_frame_p_batch_cavlc_rgb(rgbs, prev_y, prev_cb, prev_cr,
                                   ref_y, ref_cb, ref_cr, paints, qps,
                                   paint_qp, *, pad_h: int, pad_w: int,
                                   n_stripes: int, sh: int,
                                   search: int = SEARCH,
                                   max_stripe_bytes: int = 0,
                                   prefix: int = 0, me: str = "pallas"):
    """B sequential P frames with on-device CAVLC in ONE program (the
    reference chain rides a lax.scan exactly like
    :func:`encode_frame_p_batch_rgb`); heads are per-frame fetch-prefix
    slices of the CAVLC buffer."""
    from . import device_cavlc as dcav

    S = n_stripes

    def step(carry, xs):
        prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr = carry
        rgb, paint, qp = xs
        y, cb, cr = prepare_planes(rgb, pad_h, pad_w)
        enc, damage, update, nry, nrcb, nrcr = _frame_p_core(
            y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
            paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
            me=me)
        flat16, _ = _pack_levels(enc, damage, update)
        buf = dcav.pack_p_frame(
            enc.mv.reshape(S, -1, 2),
            enc.luma.reshape(S, -1, 16, 4, 4),
            enc.chroma_dc.reshape(S, -1, 2, 2, 2),
            enc.chroma_ac.reshape(S, -1, 2, 4, 4, 4),
            damage, update, mb_w=pad_w // MB, mb_h=sh // MB,
            max_stripe_bytes=max_stripe_bytes)
        head = buf[:prefix] if prefix else buf
        return (y, cb, cr, nry, nrcb, nrcr), (head, flat16)

    carry0 = (prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr)
    (ly, lcb, lcr, nry, nrcb, nrcr), (heads, flat16s) = jax.lax.scan(
        step, carry0, (rgbs, paints, qps))
    return heads, flat16s, ly, lcb, lcr, nry, nrcb, nrcr


@functools.partial(jax.jit, static_argnames=("pad_h", "pad_w",
                                             "n_stripes", "sh"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_idr_rgb(rgb, prev_y, prev_cb, prev_cr,
                         ref_y, ref_cb, ref_cr, qp,
                         *, pad_h: int, pad_w: int, n_stripes: int,
                         sh: int):
    """IDR counterpart of :func:`encode_frame_p_rgb` (one dispatch)."""
    y, cb, cr = prepare_planes(rgb, pad_h, pad_w)
    return encode_frame_idr(y, cb, cr, prev_y, prev_cb, prev_cr,
                            ref_y, ref_cb, ref_cr, qp,
                            n_stripes=n_stripes, sh=sh)


#: NO donate_argnames here, deliberately: donation measurably serializes
#: dispatches on RPC-attached transports (8.1 → 10.4 fps when removed in
#: round 3), and the ~15 MB/batch of un-reused plane buffers is noise
#: against 16 GB of HBM. PCIe deployments that want donation back can
#: re-enable it with a wrapper.
@functools.partial(jax.jit,
                   static_argnames=("pad_h", "pad_w", "n_stripes", "sh",
                                    "search", "cap_frac", "prefix", "me"))
def encode_frame_p_batch_rgb(rgbs, prev_y, prev_cb, prev_cr,
                             ref_y, ref_cb, ref_cr, paints, qps, paint_qp,
                             *, pad_h: int, pad_w: int, n_stripes: int,
                             sh: int, search: int = SEARCH,
                             cap_frac: int = 4, prefix: int = 0,
                             me: str = "pallas"):
    """B sequential P frames in ONE device program.

    RPC-attached transports pay a fixed round trip per *program
    dispatch* — not per FLOP — and the P-frame reference chain forbids
    overlapping separate dispatches. Carrying the chain through a
    ``lax.scan`` *inside* one program divides the per-frame dispatch
    cost by B: the tunnel sees one round trip per batch while the
    device still encodes each frame against the previous frame's exact
    reconstruction. PCIe deployments run B=1 (no added latency).

    rgbs: (B, H, W, 3) uint8; paints: (B, S) int32; qps: (B,) int32.
    Returns (heads (B, prefix), flat16s (B, S, words), last y/cb/cr,
    new refs) — heads are the fetch-prefix slices, one per frame.
    """
    def step(carry, xs):
        prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr = carry
        rgb, paint, qp = xs
        y, cb, cr = prepare_planes(rgb, pad_h, pad_w)
        enc, damage, update, nry, nrcb, nrcr = _frame_p_core(
            y, cb, cr, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
            paint, qp, paint_qp, n_stripes=n_stripes, sh=sh, search=search,
        me=me)
        flat16, _ = _pack_levels(enc, damage, update)
        buf = _pack_sparse(flat16, damage, update, cap_frac=cap_frac)
        head = buf[:prefix] if prefix else buf
        return (y, cb, cr, nry, nrcb, nrcr), (head, flat16)

    carry0 = (prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr)
    (ly, lcb, lcr, nry, nrcb, nrcr), (heads, flat16s) = jax.lax.scan(
        step, carry0, (rgbs, paints, qps))
    return heads, flat16s, ly, lcb, lcr, nry, nrcb, nrcr


@functools.partial(jax.jit, static_argnames=("n_stripes", "sh"),
                   donate_argnames=("prev_y", "prev_cb", "prev_cr",
                                    "ref_y", "ref_cb", "ref_cr"))
def encode_frame_idr(y, cb, cr, prev_y, prev_cb, prev_cr,
                     ref_y, ref_cb, ref_cr, qp,
                     *, n_stripes: int, sh: int):
    """Dense whole-frame IDR encode (all stripes refresh; one dispatch).

    IDR levels can exceed int8, so the host fetches flat16 (keyframes are
    rare — connect, reset, PLI). prev/ref inputs are donated so the state
    chain matches :func:`encode_frame_p`.
    """
    S = n_stripes
    ys = _stripe_view(y, S, sh)
    cbs = _stripe_view(cb, S, sh // 2)
    crs = _stripe_view(cr, S, sh // 2)
    qps = jnp.broadcast_to(qp, (S,))

    enc = jax.vmap(encode_stripe_idr)(ys, cbs, crs, qps)
    new_ref_y = enc.recon_y.reshape(y.shape)
    new_ref_cb = enc.recon_cb.reshape(cb.shape)
    new_ref_cr = enc.recon_cr.reshape(cr.shape)
    damage = jnp.ones((S,), bool)
    flat16, flat8 = _pack_levels(enc, damage, damage)
    return flat8, flat16, y, cb, cr, new_ref_y, new_ref_cb, new_ref_cr


def _pack_levels(enc: StripeEncodeOut, damage, update):
    """Device-side packing of one frame's level arrays for a single fetch.

    flat16: [S, words] int16 exact concat of (mv, luma, luma_dc, chroma_dc,
    chroma_ac) per stripe. flat8: the same clipped to int8 (halves the
    transfer; levels at streaming QPs rarely leave [-127, 127]) with a
    per-stripe tail of (damage, overflow) flags — overflowed stripes are
    re-read from flat16.
    """
    S = enc.mv.shape[0]
    parts = [enc.mv.reshape(S, -1), enc.luma.reshape(S, -1),
             enc.luma_dc.reshape(S, -1), enc.chroma_dc.reshape(S, -1),
             enc.chroma_ac.reshape(S, -1)]
    flat16 = jnp.concatenate(parts, axis=1).astype(jnp.int16)
    ovf = (jnp.abs(flat16.astype(jnp.int32)) > 127).any(axis=1)
    tail = jnp.stack([damage.astype(jnp.int8), ovf.astype(jnp.int8)],
                     axis=1)
    flat8 = jnp.concatenate(
        [jnp.clip(flat16, -127, 127).astype(jnp.int8), tail], axis=1)
    return flat16, flat8


@functools.partial(jax.jit, donate_argnames=("slot",))
def _stage_into(slot, frame):
    """H2D staging step for one ring slot.

    ``slot`` is the retiring ring buffer (donated): XLA may write the
    freshly transferred ``frame`` into its device memory instead of
    allocating, so a ring of N slots bounds staging memory at N frames
    no matter how many frames stream through. The elementwise merge is
    the cheapest op that makes the output *computed* (eligible to alias
    the donated operand) rather than a pass-through of the transfer
    buffer.
    """
    return frame | (slot & 0)


class StagingRing:
    """Double-buffered (depth>=2) H2D staging lane with donated slots.

    The pipelined encoders stage each host frame through here before
    dispatch: while the device encodes the frame staged into slot A, the
    host's next upload lands in slot B, so H2D transfer overlaps compute
    and donation can never serialize two consecutive dispatches against
    the same buffer.

    Donation hazard: a slot handed to ``_stage_into`` is *deleted* at
    call time — any later host read of that array would crash. ``stage``
    therefore refuses to donate a slot whose ticket is still held by an
    in-flight batch and falls back to a fresh allocation (counted in
    ``stalls_total``) — correctness never depends on the caller sizing
    the ring right, only peak memory does. tests/test_pipeline_async.py
    pins the guard.
    """

    def __init__(self, depth: int = 2) -> None:
        self.depth = max(2, int(depth))
        #: shape/dtype-keyed slot lists — a resize or batch-size change
        #: simply starts a new lane; stale lanes are dropped
        self._slots: "list[object]" = [None] * self.depth
        self._busy = [False] * self.depth
        self._shape = None
        self._next = 0
        #: lane generation: tickets carry it so a ticket issued before a
        #: shape change can never free (and thus re-donate) the NEW
        #: lane's same-index slot while it is still in flight
        self._generation = 0
        self.stalls_total = 0
        self.staged_total = 0

    @property
    def in_use(self) -> int:
        return sum(self._busy)

    def stage(self, frame) -> "tuple[jnp.ndarray, Optional[tuple]]":
        """Stage one host frame; returns (device_array, ticket).

        ticket is None when the ring stalled (every slot still in
        flight) and a fresh unmanaged buffer was allocated instead.
        Release the ticket via :meth:`release` once the consuming batch
        has been harvested.
        """
        frame = jnp.asarray(frame)
        key = (frame.shape, frame.dtype)
        if key != self._shape:
            # geometry change: abandon old slots (freed by GC) and
            # restart the lane — donation needs shape-stable buffers.
            # Outstanding tickets become stale via the generation bump.
            self._shape = key
            self._slots = [None] * self.depth
            self._busy = [False] * self.depth
            self._next = 0
            self._generation += 1
        idx = self._next
        if self._busy[idx]:
            # use-after-donate guard: a busy slot's occupant is still
            # referenced by an in-flight batch — donating it would
            # delete a buffer someone may read. Prefer ANY free slot
            # (so one leaked slot costs capacity, never the whole
            # lane); with every slot busy, allocate fresh instead.
            free = next((i for i in range(self.depth)
                         if not self._busy[i]), None)
            if free is None:
                self.stalls_total += 1
                return frame, None
            idx = free
        if self._slots[idx] is None:
            staged = frame
        else:
            staged = _stage_into(self._slots[idx], frame)
        self._slots[idx] = staged
        self._busy[idx] = True
        self._next = (idx + 1) % self.depth
        self.staged_total += 1
        return staged, (self._generation, idx)

    def release(self, ticket: "Optional[tuple]") -> None:
        """Mark a slot's contents consumed (safe to donate again).
        Tickets from a retired lane (pre-shape-change) are no-ops."""
        if ticket is not None:
            gen, idx = ticket
            if gen == self._generation:
                self._busy[idx] = False

    def release_all(self) -> None:
        """Teardown path: a closed pipeline holds no live readers, so
        every slot becomes donatable — a restarted encoder must never
        inherit a phantom-busy ring."""
        self._busy = [False] * self.depth


class StagingTicket:
    """Refcounted handle shared by the frames of one staged batch: the
    ring slot is released only after the LAST frame of the batch is
    harvested (batch dispatches carry B frames on one staged buffer)."""

    __slots__ = ("_ring", "_ticket", "_refs")

    def __init__(self, ring: StagingRing, ticket: "Optional[int]",
                 refs: int = 1) -> None:
        self._ring = ring
        self._ticket = ticket
        self._refs = refs

    def release(self) -> None:
        self._refs -= 1
        if self._refs <= 0 and self._ticket is not None:
            self._ring.release(self._ticket)
            self._ticket = None


def prepare_planes(rgb: jnp.ndarray, pad_h: int, pad_w: int):
    """RGB (H, W, 3) → padded uint8 (Y, Cb, Cr) planes.

    Pads to MB multiples by edge replication (the padded region is cropped
    away by the SPS frame_cropping fields).
    """
    h, w = rgb.shape[:2]
    if (pad_h, pad_w) != (h, w):
        rgb = jnp.pad(rgb, ((0, pad_h - h), (0, pad_w - w), (0, 0)),
                      mode="edge")
    yf, cbf, crf = rgb_to_ycbcr(rgb)
    y = _clip8(jnp.round(yf).astype(jnp.int32))
    cb = _clip8(jnp.round(subsample_420(cbf)).astype(jnp.int32))
    cr = _clip8(jnp.round(subsample_420(crf)).astype(jnp.int32))
    return y, cb, cr
