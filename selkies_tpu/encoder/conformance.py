"""Bitstream conformance oracle: decode tpuenc output with libavcodec.

Stands in for the browser's WebCodecs decoders (reference client
selkies-core.js:2032 VideoDecoder, :2155 ImageDecoder, :2925-2968 per-stripe
decoder pool): every byte we ship must decode cleanly there, and for H.264
the decoder's pixels must be *bit-exact* with the encoder's reconstruction
loop (both run the same §8.5 integer arithmetic).  Used by tests and debug
tooling only.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from ..native import conformance_lib

YuvFrame = Tuple[np.ndarray, np.ndarray, np.ndarray]


class ConformanceDecoder:
    """Stateful H.264 (or MJPEG) decoder over libavcodec.

    ``codec`` is "h264" or "mjpeg".  ``max_dim`` bounds the plane buffers.
    """

    def __init__(self, codec: str = "h264", max_dim: int = 4096) -> None:
        lib = conformance_lib()
        if lib is None:
            raise RuntimeError("conformance decoder unavailable")
        self._lib = lib
        ctor = lib.conf_h264_new if codec == "h264" else lib.conf_mjpeg_new
        self._h = ctor()
        if not self._h:
            raise RuntimeError(f"could not open {codec} decoder")
        self._y = np.empty(max_dim * max_dim, np.uint8)
        self._u = np.empty((max_dim // 2) * (max_dim // 2), np.uint8)
        self._v = np.empty_like(self._u)

    def close(self) -> None:
        if self._h:
            self._lib.conf_dec_free(self._h)
            self._h = None

    def __del__(self) -> None:  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    def _take(self, w: int, h: int) -> YuvFrame:
        cw, ch = (w + 1) // 2, (h + 1) // 2
        y = self._y[:w * h].reshape(h, w).copy()
        u = self._u[:cw * ch].reshape(ch, cw).copy()
        v = self._v[:cw * ch].reshape(ch, cw).copy()
        return y, u, v

    def decode(self, data: bytes) -> Optional[YuvFrame]:
        """Feed one access unit; return the decoded frame (or None)."""
        w = ctypes.c_int()
        h = ctypes.c_int()
        buf = np.frombuffer(data, np.uint8)
        n = self._lib.conf_dec_decode(
            self._h, np.ascontiguousarray(buf), len(data),
            self._y, self._u, self._v, self._y.size, self._u.size,
            ctypes.byref(w), ctypes.byref(h))
        if n < 0:
            raise RuntimeError(f"decode error {n}")
        if n == 0:
            return None
        return self._take(w.value, h.value)

    def flush(self) -> List[YuvFrame]:
        w = ctypes.c_int()
        h = ctypes.c_int()
        out: List[YuvFrame] = []
        n = self._lib.conf_dec_flush(
            self._h, self._y, self._u, self._v, self._y.size, self._u.size,
            ctypes.byref(w), ctypes.byref(h))
        if n > 0:
            out.append(self._take(w.value, h.value))
        return out


def available() -> bool:
    return conformance_lib() is not None
