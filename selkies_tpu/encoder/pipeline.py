"""Pipelined frame encoder: overlaps device dispatch, D2H, and host assembly.

JAX dispatch is asynchronous; the only blocking points are host reads. This
wrapper keeps several frames in flight so per-frame round-trip latency
(PCIe on production hosts, ~25-350 ms per transfer on tunneled dev chips) is
hidden behind throughput: submit(frame_N) while harvesting frame_{N-depth}.

Transfer economics drive the design: an RPC-tunneled device pays a fixed
~25-100 ms per D2H read regardless of size, and allows only a handful of
concurrent reads. The encode step therefore packs the per-frame metadata
(sizes, stripe bases, overflow, damage) into the head of the bitstream
buffer (jpeg._device_pipeline), and this pipeline fetches metadata + payload
as ONE predicted-size read per frame; only a size-prediction miss (bitrate
spike) costs a second read. The prediction adapts to the recent largest
frame plus one bucket of headroom.

The reference achieves the same overlap with pixelflux's capture/encode C++
threads feeding an asyncio queue (selkies.py:2865-2894); here the "threads"
are the device stream plus async host copies.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .h264_device import StagingRing, StagingTicket
from .jpeg import META_WORDS_PER_STRIPE, JpegStripeEncoder, StripeOutput, split_meta


def _p50(samples) -> float:
    """Median of a bounded timing window (0.0 when empty)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return float(s[len(s) // 2])


class _PipelineTelemetry:
    """Shared dispatch/fetch instrumentation for the pipelined encoders
    (ISSUE 12): bounded timing windows, the in-flight high-water mark,
    and the stats()/metrics publication — one implementation so the two
    pipelines cannot drift. Subclasses provide ``inflight_batches`` and
    a ``metrics`` attribute.

    Flight-recorder hookup (ISSUE 13): per-frame stage intervals
    (stage/dispatch/fetch_wait/pack, absolute ``time.monotonic``
    ``(start, end)`` pairs) accumulate on the in-flight item and are
    published under the frame's seq at harvest; the capture loop pops
    them with :meth:`pop_trace` and folds them into that frame's
    :class:`~selkies_tpu.observability.tracing.FrameTrace`."""

    def _init_telemetry(self) -> None:
        self._dispatch_ms: deque = deque(maxlen=256)
        self._fetch_wait_ms: deque = deque(maxlen=256)
        self.inflight_batches_max = 0
        #: seq -> {stage: (t_start, t_end)} for harvested frames, pruned
        #: oldest-first so an un-popping caller (bench loops, mesh) can
        #: never grow it unboundedly
        self._trace_out: "dict" = {}

    def _trace_store(self, seq: int, intervals: dict) -> None:
        if not intervals:
            return
        self._trace_out[seq] = intervals
        while len(self._trace_out) > 4 * max(8, getattr(self, "depth", 8)):
            self._trace_out.pop(next(iter(self._trace_out)))

    def pop_trace(self, seq: int):
        """Stage intervals for a harvested frame (once; None if unknown)."""
        return self._trace_out.pop(seq, None)

    def _note_inflight(self) -> None:
        self.inflight_batches_max = max(self.inflight_batches_max,
                                        self.inflight_batches)

    def _record_dispatch(self, ms: float) -> None:
        self._dispatch_ms.append(ms)
        self._note_inflight()
        if self.metrics is not None:
            self.metrics.observe_dispatch(ms)

    def _record_fetch_wait(self, ms: float) -> None:
        self._fetch_wait_ms.append(ms)
        if self.metrics is not None:
            self.metrics.observe_fetch_wait(ms)

    def _telemetry_stats(self) -> dict:
        return {
            "inflight_batches": self.inflight_batches,
            "inflight_batches_max": self.inflight_batches_max,
            "dispatch_p50_ms": round(_p50(self._dispatch_ms), 3),
            "fetch_wait_p50_ms": round(_p50(self._fetch_wait_ms), 3),
        }


@dataclass
class _FetchGroup:
    """One D2H read covering several frames' packed buffers, concatenated
    on device: RPC-attached chips pay fixed per-transfer latency and allow
    only a handful of concurrent reads, so frames-per-read — not bytes —
    sets the fetch ceiling."""

    arr: Any                        # device concat, one async host copy
    stride: int = 0                 # uniform member size, when applicable
    host: Optional[np.ndarray] = None
    #: per-member (start, length) when member sizes differ (the H.264
    #: two-tier head prefixes); empty → uniform stride slicing
    offsets: Tuple[Tuple[int, int], ...] = ()
    #: host-blocked interval materializing this group's copy (shared by
    #: every member frame's trace: the wait gated them all)
    fetch_iv: Optional[Tuple[float, float]] = None


@dataclass
class _InFlight:
    seq: int
    paint_candidate: np.ndarray
    packed: Any                     # full device buffer (meta head + words)
    yq: Any
    cbq: Any
    crq: Any
    group: Optional[_FetchGroup] = None
    group_index: int = 0
    guess_words: int = 0
    meta_done: bool = False
    emit: Optional[np.ndarray] = None
    is_paint: Optional[np.ndarray] = None
    refetch: Any = None             # second read when prediction missed
    meta: Tuple[Optional[np.ndarray], ...] = (None, None, None)
    words_np: Optional[np.ndarray] = None
    ticket: Optional[StagingTicket] = None
    #: per-frame stage intervals for the flight recorder
    trace: Dict[str, Tuple[float, float]] = field(default_factory=dict)


class PipelinedJpegEncoder(_PipelineTelemetry):
    """Depth-N pipelined wrapper around a device-entropy JpegStripeEncoder.

    Usage::

        enc = PipelinedJpegEncoder(JpegStripeEncoder(w, h))
        enc.submit(frame)                 # non-blocking dispatch
        for seq, stripes in enc.poll():   # harvest whatever completed
            ...
        enc.flush()                       # drain everything (blocking)
    """

    def __init__(self, base: JpegStripeEncoder, depth: int = 8,
                 fetch_group: int = 1, metrics=None) -> None:
        if base.entropy != "device":
            raise ValueError("pipelining requires entropy='device'")
        self.base = base
        self.depth = depth
        self.fetch_group = max(1, fetch_group)
        self._inflight: deque[_InFlight] = deque()
        self._unfetched: List[_InFlight] = []
        self._ready: List[Tuple[int, List[StripeOutput]]] = []
        self._seq = 0
        self._meta_words = META_WORDS_PER_STRIPE * base.n_stripes
        self._guess = base._packer.bucket_words(8192)
        #: D2H / host-entropy accounting (observability/metrics.py gauges
        #: d2h_bytes_per_frame + host_entropy_ms_per_frame; bench.py
        #: emits both so the fetch-bottleneck claim stays measured)
        self.metrics = metrics
        self.d2h_bytes_total = 0
        self.host_entropy_ms_total = 0.0
        self.frames_completed = 0
        #: frames rejected by try_submit because the pipeline was full —
        #: surfaced in stats()/metrics instead of vanishing (ISSUE 2)
        self.frames_dropped_total = 0
        #: donated H2D staging lane (ISSUE 12): host frames double-buffer
        #: through a ring instead of allocating per dispatch, so upload
        #: overlaps the previous frame's encode. Sized so every in-flight
        #: frame can hold a slot without stalling the ring.
        self._staging = StagingRing(depth=depth + 1)
        self._init_telemetry()

    @property
    def inflight_batches(self) -> int:
        """Fetch groups dispatched but not yet materialized on the host —
        the ISSUE 12 acceptance gauge (>=2 in steady state means the chip
        never waits on a lockstep host round trip). Dispatched-but-
        ungrouped frames count as one forming group."""
        groups = {id(it.group) for it in self._inflight
                  if it.group is not None and it.group.host is None}
        return len(groups) + (1 if self._unfetched else 0)

    def stats(self) -> dict:
        """Per-frame transfer/host-entropy gauges over the run so far."""
        n = max(1, self.frames_completed)
        return {
            "frames": self.frames_completed,
            "d2h_bytes_per_frame": self.d2h_bytes_total / n,
            "host_entropy_ms_per_frame": self.host_entropy_ms_total / n,
            "frames_dropped": self.frames_dropped_total,
            "host_fallback_stripes": getattr(
                self.base, "host_fallback_stripes_total", 0),
            "entropy": self.base.entropy,
            "staging_stalls": self._staging.stalls_total,
            **self._telemetry_stats(),
        }

    def _publish_metrics(self) -> None:
        if self.metrics is not None and self.frames_completed:
            st = self.stats()
            self.metrics.set_d2h_bytes_per_frame(st["d2h_bytes_per_frame"])
            self.metrics.set_host_entropy_ms_per_frame(
                st["host_entropy_ms_per_frame"])
            self.metrics.set_inflight_batches(st["inflight_batches"])

    @property
    def n_inflight(self) -> int:
        return len(self._inflight)

    def force_keyframe(self) -> None:
        """Next frame emits every stripe (viewer join / PIPELINE reset)."""
        self.base.force_keyframe()

    def try_submit(self, frame) -> Optional[int]:
        """Dispatch one frame without ever blocking; returns None (frame
        dropped) when the pipeline is full. This is the capture-loop entry
        point: with a single asyncio loop owning all displays, blocking here
        would stall every other client (SURVEY.md §5 concurrency invariant),
        so a saturated pipeline degrades by dropping frames instead."""
        self._advance_ready()
        if len(self._inflight) >= self.depth:
            self.frames_dropped_total += 1
            if self.metrics is not None:
                self.metrics.inc_frames_dropped()
            return None
        return self._dispatch(frame)

    def submit(self, frame) -> int:
        """Dispatch one frame; blocks (harvesting the oldest) if full."""
        while len(self._inflight) >= self.depth:
            # Harvest the oldest synchronously to free a slot; the result is
            # delivered by the next poll()/flush().
            self._ready.append(self._drain_one())
        return self._dispatch(frame)

    def _dispatch(self, frame) -> int:
        b = self.base
        t0 = time.perf_counter()
        tm0 = time.monotonic()
        ticket = None
        stage_iv = None
        if isinstance(frame, jnp.ndarray):
            # Device-resident frame (e.g. DeviceScrollSource): must already
            # be padded to the encoder geometry; skips the host staging copy.
            if frame.shape != (b.pad_h, b.pad_w, 3):
                raise ValueError(
                    f"device frame must be pre-padded to {(b.pad_h, b.pad_w, 3)}")
        else:
            # donated staging lane: the upload lands in a recycled ring
            # slot and overlaps the in-flight frames' encode/fetch
            frame, slot = self._staging.stage(
                b._pad(np.asarray(frame, dtype=np.uint8)))
            stage_iv = (tm0, time.monotonic())
            ticket = StagingTicket(self._staging, slot)
            try:
                return self._dispatch_staged(frame, ticket, t0, stage_iv)
            except Exception:
                # the slot must not leak busy; release via the ticket —
                # idempotent, so a harvest that also releases (when the
                # failure came after the in-flight item took ownership)
                # cannot double-free a re-staged slot
                ticket.release()
                raise
        return self._dispatch_staged(frame, ticket, t0, stage_iv)

    def _dispatch_staged(self, frame, ticket, t0, stage_iv=None) -> int:
        b = self.base
        td0 = time.monotonic()
        paint_candidate = b._paint_candidates().copy()
        # Optimistic mark: frames submitted while this one is in flight must
        # not re-trigger the same paint-over (a damaged stripe clears the
        # mark again at harvest in _decide_emits).
        b._painted |= paint_candidate
        qsel = jnp.asarray(paint_candidate.astype(np.int32))
        packed, new_prev, yq, cbq, crq = b._step(
            frame, b._prev, b._qy, b._qc, qsel,
            b._wm_scaled, b._alpha_inv)
        b._prev = new_prev
        item = _InFlight(
            seq=self._seq, paint_candidate=paint_candidate,
            packed=packed, yq=yq, cbq=cbq, crq=crq, ticket=ticket,
        )
        if stage_iv is not None:
            item.trace["stage"] = stage_iv
        item.trace["dispatch"] = (td0, time.monotonic())
        self._seq += 1
        self._inflight.append(item)
        self._unfetched.append(item)
        if len(self._unfetched) >= self.fetch_group:
            self._issue_fetch()
        self._record_dispatch((time.perf_counter() - t0) * 1000.0)
        self._advance_ready()
        return item.seq

    def _issue_fetch(self) -> None:
        """Combine the pending frames' buffers into ONE device concat and
        start a single async host copy for the lot."""
        group_items, self._unfetched = self._unfetched, []
        if not group_items:
            return
        guess = self._guess
        stride = self._meta_words + guess
        slices = [it.packed[:stride] for it in group_items]
        arr = slices[0] if len(slices) == 1 else jnp.concatenate(slices)
        arr.copy_to_host_async()
        group = _FetchGroup(arr=arr, stride=stride)
        for i, it in enumerate(group_items):
            it.group = group
            it.group_index = i
            it.guess_words = guess
        self._note_inflight()

    # -- pipeline stages ---------------------------------------------------

    def _advance_ready(self) -> None:
        """Advance in-flight items in submission order (non-blocking).

        ``_decide_emits`` mutates shared damage/paint history, so the meta
        stage must run strictly in frame order: stop offering the meta stage
        to an item until every earlier item has completed it.
        """
        meta_ok = True
        for item in self._inflight:
            if not meta_ok:
                break
            self._advance(item, block=False)
            meta_ok = item.meta_done

    def _advance(self, item: _InFlight, block: bool) -> bool:
        """Move one item forward; returns True when fully harvestable."""
        b = self.base
        if not item.meta_done:
            if item.group is None:
                if not block:
                    return False
                self._issue_fetch()   # flush the partial group
            if not block and not item.group.arr.is_ready():
                return False
            if item.group.host is None:
                t0 = time.perf_counter()
                tm0 = time.monotonic()
                item.group.host = np.asarray(item.group.arr)
                item.group.fetch_iv = (tm0, time.monotonic())
                self._record_fetch_wait((time.perf_counter() - t0) * 1000.0)
                self.d2h_bytes_total += item.group.host.nbytes
            if item.group.fetch_iv is not None:
                item.trace["fetch_wait"] = item.group.fetch_iv
            stride = item.group.stride
            buf = item.group.host[item.group_index * stride:
                                  (item.group_index + 1) * stride]
            nbytes_np, base_np, ovf_np, damage_np = split_meta(
                buf[: self._meta_words], b.n_stripes)
            emit, is_paint = b._decide_emits(
                damage_np > b.damage_threshold, item.paint_candidate)
            item.emit, item.is_paint = emit, is_paint
            item.meta = (nbytes_np, base_np, ovf_np)
            item.meta_done = True
            total = b.total_packed_words(base_np, nbytes_np)
            if emit.any():
                if total <= item.guess_words:
                    item.words_np = buf[self._meta_words:]
                else:  # prediction miss: one more read for the full payload
                    bucket = b._packer.bucket_words(total)
                    item.refetch = item.packed[
                        self._meta_words: self._meta_words + bucket]
                    item.refetch.copy_to_host_async()
            # adapt: track the frame size plus one bucket of headroom
            target = b._packer.bucket_words(max(total * 2, 8192))
            self._guess = max(target, self._guess // 2)
            item.packed = None  # release our handle; refetch slice holds data
        if item.refetch is not None and item.words_np is None:
            if not block and not item.refetch.is_ready():
                return False
            tm0 = time.monotonic()
            item.words_np = np.asarray(item.refetch)
            # a prediction-miss second read extends the frame's fetch wait
            fw = item.trace.get("fetch_wait")
            item.trace["fetch_wait"] = (fw[0] if fw else tm0,
                                        time.monotonic())
            self.d2h_bytes_total += item.words_np.nbytes
        return True

    def _finish(self, item: _InFlight) -> List[StripeOutput]:
        b = self.base
        self.frames_completed += 1
        if item.ticket is not None:
            # harvested: the staged input's ring slot is donatable again
            item.ticket.release()
            item.ticket = None
        nbytes_np, base_np, ovf_np = item.meta
        emit, is_paint = item.emit, item.is_paint
        if not emit.any() or item.words_np is None:
            self._trace_store(item.seq, item.trace)
            return []
        t0 = time.monotonic()
        scans = b._scans_from_packed(
            item.words_np, base_np, nbytes_np, ovf_np,
            emit, item.yq, item.cbq, item.crq)
        out = b._assemble(emit, is_paint, scans)
        t1 = time.monotonic()
        item.trace["pack"] = (t0, t1)
        self._trace_store(item.seq, item.trace)
        self.host_entropy_ms_total += (t1 - t0) * 1000.0
        self._publish_metrics()
        return out

    def _drain_one(self) -> Tuple[int, List[StripeOutput]]:
        item = self._inflight.popleft()
        try:
            self._advance(item, block=True)
        except Exception:
            # the item is already off the deque: a failed fetch must
            # still free its staging slot, or the ring stalls forever
            if item.ticket is not None:
                item.ticket.release()
                item.ticket = None
            raise
        return item.seq, self._finish(item)

    # -- public harvest ----------------------------------------------------

    def poll(self, flush_partial: bool = True
             ) -> List[Tuple[int, List[StripeOutput]]]:
        """Harvest all completed frames (non-blocking, in order).

        ``flush_partial`` (default) issues any partially filled fetch
        group so frames are never stranded when submissions pause — the
        low-latency choice for live streaming. Throughput-oriented
        callers that poll after every submit pass False so groups only
        ship at ``fetch_group`` size (``flush()`` remains the deadline).

        Results accumulate in ``self._ready`` and are swapped out only
        at the end: a harvest raising mid-pass must not discard frames
        already completed this pass (they surface on the next call).
        """
        if self._unfetched and flush_partial:
            self._issue_fetch()
        self._advance_ready()
        while self._inflight and self._advance(self._inflight[0], block=False):
            item = self._inflight.popleft()
            self._ready.append((item.seq, self._finish(item)))
        out, self._ready = self._ready, []
        return out

    def flush(self) -> List[Tuple[int, List[StripeOutput]]]:
        """Drain the pipeline (blocking)."""
        while self._inflight:
            self._ready.append(self._drain_one())
        out, self._ready = self._ready, []
        return out

    def close(self) -> None:
        """Abandon in-flight work (display teardown / supervised restart):
        drop device handles and release every staging slot so a rebuilt
        pipeline never inherits a phantom-busy ring."""
        self._inflight.clear()
        self._unfetched.clear()
        self._ready.clear()
        self._trace_out.clear()
        self._staging.release_all()


class ThreadedEncoderAdapter:
    """submit()/poll()/flush() facade over a synchronous ``encode_frame``
    encoder (the H.264 profiles), keeping the shared event loop free: one
    worker thread preserves frame order, a bounded queue drops frames
    under overload exactly like try_submit does."""

    def __init__(self, base, depth: int = 3,
                 wire_fullframe: bool = False, metrics=None) -> None:
        import concurrent.futures

        self.base = base
        self.depth = depth
        #: ship as one 0x00 full-frame packet instead of 0x04 stripes
        self.wire_fullframe = wire_fullframe
        #: observability Metrics (inc_frames_dropped / inc_encode_errors);
        #: the server attaches its instance after construction
        self.metrics = metrics
        #: called with the exception for every errored frame — the server
        #: routes this into the degradation ladder (ISSUE 2)
        self.on_error = None
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpuenc")
        self._pending: deque = deque()
        self._done: List = []
        self._seq = 0
        self.frames_completed = 0
        self.frames_dropped_total = 0
        self.encode_errors_total = 0
        #: flight-recorder intervals (the synchronous host encode is all
        #: "pack" — there is no separate device dispatch to attribute)
        self._trace_out: dict = {}

    def stats(self) -> dict:
        """Drop/error accounting plus the base encoder's entropy gauges
        (same shape as the pipelined encoders' stats for bench/health)."""
        n = max(1, self.frames_completed)
        return {
            "frames": self.frames_completed,
            "frames_dropped": self.frames_dropped_total,
            "encode_errors": self.encode_errors_total,
            "d2h_bytes_per_frame":
                getattr(self.base, "d2h_refetch_bytes_total", 0) / n,
            "host_entropy_ms_per_frame":
                getattr(self.base, "host_entropy_ms_total", 0.0) / n,
            "entropy": getattr(self.base, "entropy", None),
        }

    def try_submit(self, frame) -> Optional[int]:
        self._harvest()
        if len(self._pending) >= self.depth:
            self.frames_dropped_total += 1
            if self.metrics is not None:
                self.metrics.inc_frames_dropped()
            return None
        return self.submit(frame)

    def pop_trace(self, seq: int):
        """Stage intervals for a harvested frame (once; None if unknown)."""
        return self._trace_out.pop(seq, None)

    def _settle(self, seq: int, fut, out: List) -> None:
        """Resolve one finished encode future into ``out`` with full
        error accounting (shared by the poll and flush drains)."""
        try:
            stripes, iv = fut.result()
            out.append((seq, stripes))
            self._trace_out[seq] = {"pack": iv}
            while len(self._trace_out) > 4 * max(8, self.depth):
                self._trace_out.pop(next(iter(self._trace_out)))
            self.frames_completed += 1
        except Exception as exc:
            # encoder error: the frame is lost, but it must be COUNTED
            # (metrics + stats) and REPORTED (ladder hook), not just
            # logged — silent decay is what ISSUE 2 removes
            import logging

            self.encode_errors_total += 1
            if self.metrics is not None:
                self.metrics.inc_encode_errors()
            logging.getLogger(__name__).exception("encode failed")
            if self.on_error is not None:
                try:
                    self.on_error(exc)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "encode on_error hook failed")

    def _harvest(self) -> None:
        while self._pending and self._pending[0][1].done():
            seq, fut = self._pending.popleft()
            self._settle(seq, fut, self._done)

    def submit(self, frame) -> int:
        # defensive crop: encoder dims can be tighter than the source's
        # (H.264 needs even dims); mismatch must not poison the worker
        h = getattr(self.base, "height", None)
        w = getattr(self.base, "width", None)
        if h is not None and frame.shape[0] >= h and frame.shape[1] >= w \
                and (frame.shape[0] != h or frame.shape[1] != w):
            frame = frame[:h, :w]
        seq = self._seq
        self._seq += 1
        self._pending.append(
            (seq, self._pool.submit(self._timed_encode, frame)))
        return seq

    def _timed_encode(self, frame):
        """Worker-side encode wrapped with its flight-recorder interval."""
        t0 = time.monotonic()
        out = self.base.encode_frame(frame)
        return out, (t0, time.monotonic())

    # control surface passthrough (PLI/viewer-join keyframes, rate control)
    def request_keyframe(self) -> None:
        rk = getattr(self.base, "request_keyframe", None)
        if rk is not None:
            rk()

    force_keyframe = request_keyframe

    @property
    def qp(self):
        return getattr(self.base, "qp", None)

    @qp.setter
    def qp(self, value):
        if hasattr(self.base, "qp"):
            self.base.qp = value

    def poll(self):
        self._harvest()
        out, self._done = self._done, []
        return out

    def flush(self):
        out, self._done = self._done, []
        while self._pending:
            seq, fut = self._pending.popleft()
            self._settle(seq, fut, out)
        return out

    def close(self) -> None:
        """Stop the worker and abandon queued frames (display teardown).

        An encode_frame ALREADY RUNNING cannot be interrupted — a truly
        hung native coder leaves its thread blocked past shutdown. The
        server bounds that exposure (DisplayState.wedge_faults caps
        rebuild cycles of a wedged bottom-rung encoder)."""
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._pending.clear()
        self._done.clear()
        self._trace_out.clear()


@dataclass
class _H264InFlight:
    seq: int
    pending: Any                     # h264._H264Pending
    group: Any = None                # _FetchGroup (P frames)
    group_index: int = 0
    host: Optional[np.ndarray] = None
    ticket: Optional[StagingTicket] = None
    #: per-frame stage intervals for the flight recorder
    trace: Dict[str, Tuple[float, float]] = field(default_factory=dict)


class PipelinedH264Encoder(_PipelineTelemetry):
    """Depth-N pipelined wrapper around H264StripeEncoder with grouped
    sparse-buffer fetches.

    Same transfer economics as PipelinedJpegEncoder: an RPC-attached
    device pays ~25-110 ms per D2H read regardless of size, so several
    frames' sparse level buffers (h264_device._pack_sparse) are
    concatenated on device and fetched in ONE read. IDR frames carry the
    full flat16 levels and fetch solo (they are rare: connect/reset/PLI).
    """

    def __init__(self, base, depth: int = 8, fetch_group: int = 4,
                 batch: int = 1,
                 batch_deadline_s: Optional[float] = None,
                 metrics=None) -> None:
        self.base = base
        self.depth = depth
        self.fetch_group = max(1, fetch_group)
        #: transfer accounting for the d2h_bytes_per_frame /
        #: host_entropy_ms_per_frame gauges (host-entropy time and
        #: refetch bytes accumulate on the base encoder in harvest)
        self.metrics = metrics
        self.d2h_bytes_total = 0
        self.frames_completed = 0
        self.frames_dropped_total = 0
        #: frames encoded per device dispatch (dev.encode_frame_p_batch_rgb)
        #: — RPC-attached transports pay per dispatch, so batch>1 divides
        #: that cost; PCIe deployments keep 1 (no added latency)
        self.batch = max(1, batch)
        #: inactivity deadline at which poll(flush_partial=False)
        #: dispatches a partial batch anyway. RE-ARMED by every submit
        #: (ISSUE 12 satellite): the deadline detects a PAUSED caller —
        #: no new frame within the window — not a slow one, so a stream
        #: ticking slower than batch/deadline still accumulates full
        #: ``fetch_group`` batches instead of degrading to single-frame
        #: dispatches forever (worst-case frame staleness stays bounded
        #: at ``batch`` deadlines — see _batch_deadline_due).
        if batch_deadline_s is None:
            batch_deadline_s = max(0.05, 2.5 * self.batch / 60.0)
        self.batch_deadline_s = batch_deadline_s
        self._batch_t0 = 0.0        # first frame of the forming group
        self._batch_last = 0.0      # last submit — re-arms the deadline
        self._batch_frames: List[Any] = []
        self._inflight: deque[_H264InFlight] = deque()
        self._unfetched: List[_H264InFlight] = []
        self._ready: List[Tuple[int, list]] = []
        self._seq = 0
        #: donated H2D staging lanes (ISSUE 12): one ring per input shape
        #: — single frames and stacked batches ping-pong independently so
        #: alternating paths never thrash a shared ring
        self._staging = StagingRing(depth=depth + 1)
        self._staging_batch = StagingRing(
            depth=max(2, -(-depth // self.batch) + 1))
        self._init_telemetry()

    @property
    def n_inflight(self) -> int:
        return len(self._inflight)

    @property
    def inflight_batches(self) -> int:
        """Dispatched-but-not-yet-materialized fetch units: grouped P
        reads, batch heads, and solo IDR flat16 fetches each count once
        while their host copy is outstanding (the ISSUE 12 gauge)."""
        groups = set()
        solo = 0
        for it in self._inflight:
            if it.pending.is_idr:
                if it.host is None:
                    solo += 1
            elif it.group is not None:
                if it.group.host is None:
                    groups.add(id(it.group))
        return len(groups) + solo + (1 if self._unfetched else 0)

    def stats(self) -> dict:
        """Per-frame transfer/host-entropy gauges over the run so far.
        D2H counts grouped head fetches, solo IDR flat16 reads, and the
        base encoder's undershoot/overflow re-reads; entropy ms is the
        base harvest's host coding+glue wall time."""
        n = max(1, self.frames_completed)
        d2h = self.d2h_bytes_total \
            + getattr(self.base, "d2h_refetch_bytes_total", 0)
        ems = getattr(self.base, "host_entropy_ms_total", 0.0)
        return {
            "frames": self.frames_completed,
            "d2h_bytes_per_frame": d2h / n,
            "host_entropy_ms_per_frame": ems / n,
            "frames_dropped": self.frames_dropped_total,
            "entropy_errors": getattr(self.base, "entropy_errors_total", 0),
            "entropy": getattr(self.base, "entropy", None),
            "staging_stalls": (self._staging.stalls_total
                               + self._staging_batch.stalls_total),
            **self._telemetry_stats(),
        }

    def _publish_metrics(self) -> None:
        if self.metrics is not None and self.frames_completed:
            st = self.stats()
            self.metrics.set_d2h_bytes_per_frame(st["d2h_bytes_per_frame"])
            self.metrics.set_host_entropy_ms_per_frame(
                st["host_entropy_ms_per_frame"])
            self.metrics.set_inflight_batches(st["inflight_batches"])

    def request_keyframe(self) -> None:
        self.base.request_keyframe()

    force_keyframe = request_keyframe

    @property
    def qp(self):
        return self.base.qp

    @qp.setter
    def qp(self, value):
        self.base.qp = value

    def try_submit(self, frame) -> Optional[int]:
        if len(self._inflight) >= self.depth:
            self.frames_dropped_total += 1
            if self.metrics is not None:
                self.metrics.inc_frames_dropped()
            return None
        return self.submit(frame)

    def _stage(self, frame, ring: StagingRing):
        """Host frames ride the donated staging ring; device-resident
        frames pass through untouched."""
        if isinstance(frame, jnp.ndarray):
            return frame, None
        return ring.stage(np.asarray(frame, dtype=np.uint8))

    def submit(self, frame) -> int:
        while len(self._inflight) + len(self._batch_frames) >= self.depth:
            if not self._inflight:
                self._flush_batch()
                continue
            self._ready.append(self._drain_one())
        if self.batch > 1:
            seq = self._seq + len(self._batch_frames)
            now = time.monotonic()
            if not self._batch_frames:
                self._batch_t0 = now
            self._batch_last = now      # every submit re-arms the deadline
            self._batch_frames.append(frame)
            if len(self._batch_frames) >= self.batch:
                self._flush_batch()
            return seq
        return self._dispatch_solo(frame)

    def _dispatch_solo(self, frame) -> int:
        t0 = time.perf_counter()
        ts0 = time.monotonic()
        frame, slot = self._stage(frame, self._staging)
        td0 = time.monotonic()
        try:
            p = self.base.dispatch(frame, fetch=False)
        except Exception:
            # no ticket exists yet: free the staged slot here or it
            # leaks busy forever and the lane loses a buffer
            self._staging.release(slot)
            raise
        item = _H264InFlight(seq=self._seq, pending=p,
                             ticket=StagingTicket(self._staging, slot))
        if slot is not None:
            item.trace["stage"] = (ts0, td0)
        item.trace["dispatch"] = (td0, time.monotonic())
        self._seq += 1
        self._inflight.append(item)
        if p.is_idr:
            # IDR fetches flat16 solo (rare: connect/reset/PLI)
            p.flat16.copy_to_host_async()
        else:
            self._unfetched.append(item)
            if len(self._unfetched) >= self.fetch_group:
                self._issue_fetch()
        self._record_dispatch((time.perf_counter() - t0) * 1000.0)
        return item.seq

    def submit_batch(self, rgbs) -> List[int]:
        """Submit a pre-stacked (B, H, W, 3) array as one batch — the
        zero-extra-dispatch path when the source can produce batches
        (device batch sources, stacked host capture)."""
        while len(self._inflight) >= self.depth:
            self._ready.append(self._drain_one())
        self._flush_batch()                  # keep ordering with singles
        first = self._seq
        self._dispatch_batch(rgbs)
        return list(range(first, self._seq))

    def _flush_batch(self) -> None:
        """Dispatch the accumulated frames as one batched program; its
        heads array doubles as the fetch group (one async read per
        batch). Partial batches go through the already-compiled
        single-frame program — a (B-k)-shaped batch scan would compile
        from scratch for every distinct partial size. A deadline flush
        landing here re-arms nothing: the NEXT group's window starts at
        its own first submit, so a resumed stream returns to full
        batches immediately."""
        frames, self._batch_frames = self._batch_frames, []
        if not frames:
            return
        if len(frames) < self.batch:
            for i, frame in enumerate(frames):
                try:
                    self._dispatch_solo(frame)
                except Exception:
                    # the raising frame is the caller's error to count;
                    # the not-yet-attempted remainder must not vanish
                    # silently — they are drops, visible to the ladder
                    # and health feed
                    self._count_dropped(len(frames) - i - 1)
                    self._issue_fetch()
                    raise
            self._issue_fetch()
            return
        if any(not isinstance(f, jnp.ndarray) for f in frames):
            # host frames: stack host-side and stage the whole batch
            # through the donated batch lane (ONE H2D upload)
            rgbs = np.stack([np.asarray(f, dtype=np.uint8) for f in frames])
        else:
            rgbs = jnp.stack(frames)
        try:
            self._dispatch_batch(rgbs)
        except Exception:
            # one exception surfaces to the caller; the other B-1
            # frames of the failed batch are accounted as drops
            self._count_dropped(len(frames) - 1)
            raise

    def _count_dropped(self, n: int) -> None:
        if n <= 0:
            return
        self.frames_dropped_total += n
        if self.metrics is not None:
            self.metrics.inc_frames_dropped(n)

    def _dispatch_batch(self, rgbs) -> None:
        # fetch=False: this pipeline owns every transfer — the encoder
        # starting its own head copies AND _issue_fetch concatenating the
        # same heads would double-transfer the IDR-recovery path
        t0 = time.perf_counter()
        ts0 = time.monotonic()
        rgbs, slot = self._stage(rgbs, self._staging_batch)
        td0 = time.monotonic()
        try:
            pendings = self.base.dispatch_batch(rgbs, fetch=False)
        except Exception:
            self._staging_batch.release(slot)
            raise
        td1 = time.monotonic()
        # one staged buffer backs every frame of the batch: the ring slot
        # frees when the LAST of them harvests
        ticket = StagingTicket(self._staging_batch, slot,
                               refs=len(pendings))
        group_items = []
        for p in pendings:
            item = _H264InFlight(seq=self._seq, pending=p, ticket=ticket)
            # one staged buffer + one program back the whole batch, so
            # every member frame was gated by the same intervals
            if slot is not None:
                item.trace["stage"] = (ts0, td0)
            item.trace["dispatch"] = (td0, td1)
            self._seq += 1
            self._inflight.append(item)
            if p.is_idr:
                p.flat16.copy_to_host_async()
            elif p.batch_heads is not None:
                group_items.append(item)
            else:
                self._unfetched.append(item)
        if group_items:
            arr = group_items[0].pending.batch_heads
            arr.copy_to_host_async()
            group = _FetchGroup(arr=arr)
            for it in group_items:
                it.group = group
                it.group_index = it.pending.batch_index
        if self._unfetched:
            self._issue_fetch()
        self._record_dispatch((time.perf_counter() - t0) * 1000.0)

    def _issue_fetch(self) -> None:
        group_items, self._unfetched = self._unfetched, []
        if not group_items:
            return
        # the dispatch program already produced each frame's prefix slice
        # (one fewer program per frame); members may have different sizes
        # (two-tier head prefixes), so the group records per-member
        # offsets instead of assuming a uniform stride
        slices = []
        offsets = []
        pos = 0
        for it in group_items:
            s = it.pending.head if it.pending.head is not None \
                else it.pending.buf[:self.base._batch_prefix]
            n = int(s.shape[0])
            slices.append(s)
            offsets.append((pos, n))
            pos += n
        arr = slices[0] if len(slices) == 1 else jnp.concatenate(slices)
        arr.copy_to_host_async()
        group = _FetchGroup(arr=arr, offsets=tuple(offsets))
        for i, it in enumerate(group_items):
            it.group = group
            it.group_index = i
        self._note_inflight()

    def _advance(self, item: _H264InFlight, block: bool) -> bool:
        p = item.pending
        if p.is_idr:
            if not block and not p.flat16.is_ready():
                return False
            if item.host is None:
                t0 = time.perf_counter()
                tm0 = time.monotonic()
                item.host = np.asarray(p.flat16)
                item.trace["fetch_wait"] = (tm0, time.monotonic())
                self._record_fetch_wait((time.perf_counter() - t0) * 1000.0)
                self.d2h_bytes_total += item.host.nbytes
            return True
        if item.group is None:
            if not block:
                return False
            self._issue_fetch()
        if not block and not item.group.arr.is_ready():
            return False
        if item.group.host is None:
            t0 = time.perf_counter()
            tm0 = time.monotonic()
            item.group.host = np.asarray(item.group.arr)
            item.group.fetch_iv = (tm0, time.monotonic())
            self._record_fetch_wait((time.perf_counter() - t0) * 1000.0)
            self.d2h_bytes_total += item.group.host.nbytes
        if item.group.fetch_iv is not None:
            item.trace["fetch_wait"] = item.group.fetch_iv
        if item.group.host.ndim == 2:      # batched dispatch: (B, prefix)
            item.host = item.group.host[item.group_index]
        elif item.group.offsets:
            start, length = item.group.offsets[item.group_index]
            item.host = item.group.host[start:start + length]
        else:
            stride = item.group.stride
            item.host = item.group.host[item.group_index * stride:
                                        (item.group_index + 1) * stride]
        return True

    @staticmethod
    def _release_ticket(item) -> None:
        if item.ticket is not None:
            item.ticket.release()
            item.ticket = None

    def _harvest_item(self, item: _H264InFlight) -> Tuple[int, list]:
        t0 = time.monotonic()
        try:
            out = self.base.harvest(item.pending, host=item.host)
        finally:
            # the item is already off the deque: even a failed harvest
            # must free its staging slot, or the ring stalls forever
            self._release_ticket(item)
        item.trace["pack"] = (t0, time.monotonic())
        self._trace_store(item.seq, item.trace)
        self.frames_completed += 1
        return item.seq, out

    def _drain_one(self) -> Tuple[int, list]:
        # harvest() mutates per-stripe frame_num/static history, so frames
        # complete strictly in submission order (deque head first)
        item = self._inflight.popleft()
        try:
            self._advance(item, block=True)
        except Exception:
            self._release_ticket(item)
            raise
        seq_out = self._harvest_item(item)
        self._publish_metrics()
        return seq_out

    def _batch_deadline_due(self) -> bool:
        """True when the forming group should ship incomplete: the
        caller went quiet for a full deadline since its LAST submit.
        Staleness stays bounded without an extra age check — every
        inter-submit gap under the deadline means the batch fills within
        ``(batch-1)`` such gaps, so no frame ever waits longer than
        ``batch * batch_deadline_s``."""
        return time.monotonic() - self._batch_last > self.batch_deadline_s

    def poll(self, flush_partial: bool = True) -> List[Tuple[int, list]]:
        """Harvest completed frames in order; see PipelinedJpegEncoder.poll
        for the ``flush_partial`` latency/throughput trade.

        Results accumulate in ``self._ready`` and are swapped out only at
        the end: a harvest raising mid-pass must not discard the frames
        already completed this pass (they surface on the next call)."""
        if self._batch_frames and (flush_partial
                                   or self._batch_deadline_due()):
            # deadline flush: frames buffered toward a batch must not wait
            # forever when the caller pauses submission
            self._flush_batch()
        if self._unfetched and flush_partial:
            self._issue_fetch()
        while self._inflight and self._advance(self._inflight[0],
                                               block=False):
            self._ready.append(self._harvest_item(self._inflight.popleft()))
        self._publish_metrics()
        out, self._ready = self._ready, []
        return out

    def flush(self) -> List[Tuple[int, list]]:
        self._flush_batch()
        while self._inflight:
            self._ready.append(self._drain_one())
        out, self._ready = self._ready, []
        return out

    def close(self) -> None:
        self._batch_frames.clear()
        self._inflight.clear()
        self._unfetched.clear()
        self._ready.clear()
        self._trace_out.clear()
        # a rebuilt pipeline must never inherit phantom-busy ring slots
        self._staging.release_all()
        self._staging_batch.release_all()
