"""Pipelined frame encoder: overlaps device dispatch, D2H, and host assembly.

JAX dispatch is asynchronous; the only blocking points are host reads. This
wrapper keeps several frames in flight so per-frame round-trip latency
(PCIe on production hosts, ~50-90 ms on tunneled dev chips) is hidden behind
throughput: submit(frame_N) while harvesting frame_{N-depth}.

The reference achieves the same overlap with pixelflux's capture/encode C++
threads feeding an asyncio queue (selkies.py:2865-2894); here the "threads"
are the device stream plus async host copies.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .jpeg import JpegStripeEncoder, StripeOutput


@dataclass
class _InFlight:
    seq: int
    paint_candidate: np.ndarray
    words: Any
    nbytes: Any
    base: Any
    ovf: Any
    damage: Any
    yq: Any
    cbq: Any
    crq: Any
    meta_done: bool = False
    emit: Optional[np.ndarray] = None
    is_paint: Optional[np.ndarray] = None
    fetched_words: Any = None
    meta: Tuple[Optional[np.ndarray], ...] = (None, None, None)


class PipelinedJpegEncoder:
    """Depth-N pipelined wrapper around a device-entropy JpegStripeEncoder.

    Usage::

        enc = PipelinedJpegEncoder(JpegStripeEncoder(w, h))
        enc.submit(frame)                 # non-blocking dispatch
        for seq, stripes in enc.poll():   # harvest whatever completed
            ...
        enc.flush()                       # drain everything (blocking)
    """

    def __init__(self, base: JpegStripeEncoder, depth: int = 3) -> None:
        if base.entropy != "device":
            raise ValueError("pipelining requires entropy='device'")
        self.base = base
        self.depth = depth
        self._inflight: deque[_InFlight] = deque()
        self._ready: List[Tuple[int, List[StripeOutput]]] = []
        self._seq = 0

    @property
    def n_inflight(self) -> int:
        return len(self._inflight)

    def try_submit(self, frame: np.ndarray) -> Optional[int]:
        """Dispatch one frame without ever blocking; returns None (frame
        dropped) when the pipeline is full. This is the capture-loop entry
        point: with a single asyncio loop owning all displays, blocking here
        would stall every other client (SURVEY.md §5 concurrency invariant),
        so a saturated pipeline degrades by dropping frames instead."""
        self._advance_ready()
        if len(self._inflight) >= self.depth:
            return None
        return self._dispatch(frame)

    def submit(self, frame: np.ndarray) -> int:
        """Dispatch one frame; blocks (harvesting the oldest) if full."""
        while len(self._inflight) >= self.depth:
            # Harvest the oldest synchronously to free a slot; the result is
            # delivered by the next poll()/flush().
            self._ready.append(self._drain_one())
        return self._dispatch(frame)

    def _dispatch(self, frame: np.ndarray) -> int:
        b = self.base
        frame = b._pad(np.asarray(frame, dtype=np.uint8))
        paint_candidate = b._paint_candidates().copy()
        # Optimistic mark: frames submitted while this one is in flight must
        # not re-trigger the same paint-over (a damaged stripe clears the
        # mark again at harvest in _decide_emits).
        b._painted |= paint_candidate
        qsel = jnp.asarray(paint_candidate.astype(np.int32))
        words, nbytes, base_w, ovf, damage, new_prev, yq, cbq, crq = b._step(
            jnp.asarray(frame), b._prev, b._qy, b._qc, qsel)
        b._prev = new_prev
        for a in (nbytes, base_w, ovf, damage):
            a.copy_to_host_async()
        item = _InFlight(
            seq=self._seq, paint_candidate=paint_candidate,
            words=words, nbytes=nbytes, base=base_w, ovf=ovf, damage=damage,
            yq=yq, cbq=cbq, crq=crq,
        )
        self._seq += 1
        self._inflight.append(item)
        self._advance_ready()
        return item.seq

    # -- pipeline stages ---------------------------------------------------

    def _advance_ready(self) -> None:
        """Advance in-flight items in submission order (non-blocking).

        ``_decide_emits`` mutates shared damage/paint history, so the meta
        stage must run strictly in frame order: stop offering the meta stage
        to an item until every earlier item has completed it.
        """
        meta_ok = True
        for item in self._inflight:
            if not meta_ok:
                break
            self._advance(item, block=False)
            meta_ok = item.meta_done

    def _advance(self, item: _InFlight, block: bool) -> bool:
        """Move one item forward; returns True when fully harvestable."""
        b = self.base
        if not item.meta_done:
            if not block and not all(
                    a.is_ready() for a in (item.nbytes, item.base, item.ovf,
                                           item.damage)):
                return False
            nbytes_np = np.asarray(item.nbytes)
            base_np = np.asarray(item.base)
            damage_np = np.asarray(item.damage)
            ovf_np = np.asarray(item.ovf)
            emit, is_paint = b._decide_emits(
                damage_np > b.damage_threshold, item.paint_candidate)
            item.emit, item.is_paint = emit, is_paint
            item.meta = (nbytes_np, base_np, ovf_np)
            item.meta_done = True
            if emit.any():
                n = b._packer.bucket_words(
                    b.total_packed_words(base_np, nbytes_np))
                item.fetched_words = item.words[:n]
                item.fetched_words.copy_to_host_async()
        if item.fetched_words is not None:
            if not block and not item.fetched_words.is_ready():
                return False
        return True

    def _finish(self, item: _InFlight) -> List[StripeOutput]:
        b = self.base
        nbytes_np, base_np, ovf_np = item.meta
        emit, is_paint = item.emit, item.is_paint
        if not emit.any():
            return []
        scans = b._scans_from_packed(
            np.asarray(item.fetched_words), base_np, nbytes_np, ovf_np,
            emit, item.yq, item.cbq, item.crq)
        return b._assemble(emit, is_paint, scans)

    def _drain_one(self) -> Tuple[int, List[StripeOutput]]:
        item = self._inflight.popleft()
        self._advance(item, block=True)
        return item.seq, self._finish(item)

    # -- public harvest ----------------------------------------------------

    def poll(self) -> List[Tuple[int, List[StripeOutput]]]:
        """Harvest all completed frames (non-blocking, in order)."""
        out, self._ready = self._ready, []
        self._advance_ready()
        while self._inflight and self._advance(self._inflight[0], block=False):
            item = self._inflight.popleft()
            out.append((item.seq, self._finish(item)))
        return out

    def flush(self) -> List[Tuple[int, List[StripeOutput]]]:
        """Drain the pipeline (blocking)."""
        out, self._ready = self._ready, []
        while self._inflight:
            out.append(self._drain_one())
        return out
