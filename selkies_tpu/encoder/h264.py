"""tpuenc v1: H.264 Constrained-Baseline striped encoder.

Capability parity with the reference's ``x264enc-striped`` / ``x264enc``
pixelflux modes (CaptureSettings output_mode=1, selkies.py:2919-2963;
client decoders selkies-core.js:2925-2968): each horizontal stripe is an
independent H.264 video sequence with its own SPS/PPS/IDR chain, so the
client can run one WebCodecs ``VideoDecoder`` per stripe and only damaged
stripes are ever encoded or shipped.

Split of work (TPU-first, SURVEY.md §7 step 6):
  * device (encoder/h264_device.py): color/4:2:0, exhaustive ME, transforms,
    quant, and the exact decoder-arithmetic reconstruction loop;
  * host (native/cavlc.cpp): CAVLC entropy coding + NAL packaging of the
    device's level arrays;
  * here: stripe/GOP orchestration, damage gating, paint-over escalation
    (low-QP P frames — no IDR needed, unlike the reference's burst
    keyframes), SPS/PPS generation, reference-plane state.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..native import cavlc_lib
from . import device_cavlc as dcav
from . import h264_device as dev

logger = logging.getLogger("selkies_tpu.encoder.h264")

MB = 16

_POOL = None


def _entropy_pool():
    """Shared thread pool for per-stripe CAVLC (the C coder releases the
    GIL, so stripes of one frame entropy-code concurrently)."""
    global _POOL
    if _POOL is None:
        import concurrent.futures
        import os
        _POOL = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, os.cpu_count() or 4),
            thread_name_prefix="cavlc")
    return _POOL


# ---------------------------------------------------------------------------
# SPS / PPS


class _BitWriter:
    def __init__(self) -> None:
        self.bits: List[int] = []

    def u(self, value: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.bits.append((value >> i) & 1)

    def ue(self, v: int) -> None:
        vp1 = v + 1
        n = vp1.bit_length() - 1
        self.u(0, n)
        self.u(vp1, n + 1)

    def se(self, v: int) -> None:
        self.ue(-2 * v if v <= 0 else 2 * v - 1)

    def rbsp(self) -> bytes:
        bits = self.bits + [1]
        while len(bits) % 8:
            bits.append(0)
        out = bytearray()
        for i in range(0, len(bits), 8):
            b = 0
            for bit in bits[i:i + 8]:
                b = (b << 1) | bit
            out.append(b)
        # emulation prevention
        esc = bytearray()
        zeros = 0
        for b in out:
            if zeros >= 2 and b <= 3:
                esc.append(3)
                zeros = 0
            esc.append(b)
            zeros = zeros + 1 if b == 0 else 0
        return bytes(esc)


def _nal(nal_type: int, rbsp: bytes, ref_idc: int = 3) -> bytes:
    return b"\x00\x00\x00\x01" + bytes(((ref_idc << 5) | nal_type,)) + rbsp


def make_sps(width: int, height: int, *, coded_height: Optional[int] = None,
             level_idc: int = 40, full_range: bool = True) -> bytes:
    """Constrained-Baseline SPS for a (possibly cropped) 4:2:0 frame.

    ``coded_height`` (a MB multiple ≥ height) must match the rows the
    slices actually code — the uniform stripe grid encodes full
    ``stripe_h`` rows even for a partial last stripe, and an SPS declaring
    fewer MB rows than the slice codes is an invalid bitstream
    (libavcodec: "first_mb_in_slice overflow")."""
    mb_w = (width + 15) // 16
    mb_h = ((coded_height or height) + 15) // 16
    crop_r = (mb_w * 16 - width) // 2
    crop_b = (mb_h * 16 - height) // 2
    bw = _BitWriter()
    bw.u(66, 8)          # profile_idc: Baseline
    bw.u(0b11000000, 8)  # constraint_set0+1 (constrained baseline)
    bw.u(level_idc, 8)
    bw.ue(0)             # sps id
    bw.ue(0)             # log2_max_frame_num_minus4 → 4-bit frame_num
    bw.ue(2)             # pic_order_cnt_type
    bw.ue(1)             # max_num_ref_frames
    bw.u(0, 1)           # gaps_in_frame_num_value_allowed
    bw.ue(mb_w - 1)
    bw.ue(mb_h - 1)
    bw.u(1, 1)           # frame_mbs_only
    bw.u(1, 1)           # direct_8x8_inference
    if crop_r or crop_b:
        bw.u(1, 1)
        bw.ue(0)
        bw.ue(crop_r)
        bw.ue(0)
        bw.ue(crop_b)
    else:
        bw.u(0, 1)
    # VUI: declare BT.601 + range so the browser matches our color matrix
    bw.u(1, 1)           # vui_parameters_present
    bw.u(0, 1)           # aspect_ratio_info_present
    bw.u(0, 1)           # overscan_info_present
    bw.u(1, 1)           # video_signal_type_present
    bw.u(5, 3)           # video_format: unspecified
    bw.u(1 if full_range else 0, 1)
    bw.u(1, 1)           # colour_description_present
    bw.u(6, 8)           # primaries: SMPTE 170M
    bw.u(6, 8)           # transfer
    bw.u(6, 8)           # matrix: BT.601
    bw.u(0, 1)           # chroma_loc_info_present
    bw.u(0, 1)           # timing_info_present
    bw.u(0, 1)           # nal_hrd
    bw.u(0, 1)           # vcl_hrd
    bw.u(0, 1)           # pic_struct_present
    bw.u(0, 1)           # bitstream_restriction
    return _nal(7, bw.rbsp())


def make_pps() -> bytes:
    bw = _BitWriter()
    bw.ue(0)     # pps id
    bw.ue(0)     # sps id
    bw.u(0, 1)   # entropy_coding_mode: CAVLC
    bw.u(0, 1)   # bottom_field_pic_order_in_frame_present
    bw.ue(0)     # num_slice_groups_minus1
    bw.ue(0)     # num_ref_idx_l0_default_active_minus1
    bw.ue(0)     # num_ref_idx_l1_default_active_minus1
    bw.u(0, 1)   # weighted_pred
    bw.u(0, 2)   # weighted_bipred_idc
    bw.se(0)     # pic_init_qp_minus26 (slice writer assumes 26)
    bw.se(0)     # pic_init_qs_minus26
    bw.se(0)     # chroma_qp_index_offset (qpc_for assumes 0)
    bw.u(1, 1)   # deblocking_filter_control_present (slices disable it)
    bw.u(0, 1)   # constrained_intra_pred
    bw.u(0, 1)   # redundant_pic_cnt_present
    return _nal(8, bw.rbsp())


# ---------------------------------------------------------------------------
# host entropy dispatch


def encode_picture_nals(out: dev.StripeEncodeOut, *, is_idr: bool,
                        mb_w: int, mb_h: int, qp: int, frame_num: int,
                        idr_pic_id: int = 0) -> bytes:
    """Run the native CAVLC coder over one stripe's device outputs."""
    return encode_picture_nals_np(
        np.ascontiguousarray(np.asarray(out.mv), np.int32),
        np.ascontiguousarray(np.asarray(out.luma), np.int32),
        np.ascontiguousarray(np.asarray(out.luma_dc), np.int32),
        np.ascontiguousarray(np.asarray(out.chroma_dc), np.int32),
        np.ascontiguousarray(np.asarray(out.chroma_ac), np.int32),
        is_idr=is_idr, mb_w=mb_w, mb_h=mb_h, qp=qp,
        frame_num=frame_num, idr_pic_id=idr_pic_id)


def encode_picture_nals_np(mv, luma, luma_dc, chroma_dc, chroma_ac, *,
                           is_idr: bool, mb_w: int, mb_h: int, qp: int,
                           frame_num: int, idr_pic_id: int = 0,
                           deblock: bool = False) -> bytes:
    """CAVLC over host-resident coefficient arrays (already fetched).

    ``deblock`` writes disable_deblocking_filter_idc=0 into P slice
    headers (decoder runs the in-loop filter). STAGED, default off: the
    encoder's device reconstruction does not yet mirror the filter —
    the spec's per-macroblock filtering order carries a 3×3-corner
    sequential dependency that defeats the straightforward
    all-vertical-then-all-horizontal vectorization, so an exact
    TPU-shaped formulation (wavefront or corner-fixup) is round-5 work
    (BASELINE.md "Quality vs x264" decision 2). Until then, enabling
    this flag drifts encoder refs from decoder output.
    """
    lib = cavlc_lib()
    if lib is None:
        raise RuntimeError("native CAVLC coder unavailable")
    cap = 1 << 22
    buf = np.empty(cap, np.uint8)
    n = lib.h264_encode_picture(
        1 if is_idr else 0, mb_w, mb_h, qp, frame_num & 0xF, idr_pic_id,
        np.ascontiguousarray(mv, np.int32),
        np.ascontiguousarray(luma, np.int32),
        np.ascontiguousarray(luma_dc, np.int32),
        np.ascontiguousarray(chroma_dc, np.int32),
        np.ascontiguousarray(chroma_ac, np.int32),
        buf, cap, 1 if deblock else 0)
    if n < 0:
        raise RuntimeError("CAVLC output exceeded capacity")
    return bytes(buf[:n])


# ---------------------------------------------------------------------------
# stripe orchestration


@dataclass
class H264Stripe:
    y_start: int
    width: int          # coded (cropped) width
    height: int         # coded (cropped) height of this stripe
    annexb: bytes
    is_key: bool


@dataclass
class _StripeState:
    y0: int             # luma row offset (unpadded coordinates)
    h: int              # unpadded stripe height
    pad_h: int          # MB-aligned height
    frame_num: int = 0
    idr_pic_id: int = 0
    need_idr: bool = True
    static_frames: int = 0
    painted_over: bool = False


class H264StripeEncoder:
    """Striped (or full-frame) H.264 encoder with damage gating.

    ``fullframe=True`` reproduces the reference's ``x264enc`` mode: one
    stripe covering the whole frame. The server ships it as 0x00
    full-frame packets (the wire routing lives in the encoder adapter's
    ``wire_fullframe`` flag, not here — reference h264_fullframe,
    selkies.py:2937, wire demux selkies-core.js 0x00 path).
    """

    def __init__(self, width: int, height: int, *, stripe_height: int = 64,
                 qp: int = 26, paint_over_qp: int = 18,
                 paint_over_trigger_frames: int = 15,
                 search: int = 12, fullframe: bool = False,
                 cap_frac: int = 8,
                 entropy: Optional[str] = None) -> None:
        if width % 2 or height % 2:
            raise ValueError("frame dimensions must be even")
        if stripe_height % MB:
            raise ValueError("stripe_height must be a multiple of 16")
        self.width = width
        self.height = height
        self.qp = int(np.clip(qp, 0, 51))
        self.paint_over_qp = int(np.clip(paint_over_qp, 0, 51))
        self.paint_over_trigger = paint_over_trigger_frames
        self.search = search
        self.pad_w = (width + MB - 1) // MB * MB
        sh = height if fullframe else stripe_height
        sh = (sh + MB - 1) // MB * MB
        self.stripe_h = sh
        self.stripes: List[_StripeState] = []
        y = 0
        while y < height:
            h = min(sh, height - y)
            self.stripes.append(_StripeState(y0=y, h=h, pad_h=sh))
            y += h
        #: uniform stripe grid: total padded height is S × stripe_h so the
        #: whole frame encodes as one vmapped device dispatch
        self.n_stripes = len(self.stripes)
        self.pad_h = self.n_stripes * sh
        self._sps_pps: Dict[int, bytes] = {}

        # device state chains (donated through each dispatch)
        self._prev_y = jnp.zeros((self.pad_h, self.pad_w), jnp.uint8)
        self._prev_cb = jnp.zeros((self.pad_h // 2, self.pad_w // 2),
                                  jnp.uint8)
        self._prev_cr = jnp.zeros_like(self._prev_cb)
        self._ref_y = jnp.zeros_like(self._prev_y)
        self._ref_cb = jnp.zeros_like(self._prev_cb)
        self._ref_cr = jnp.zeros_like(self._prev_cr)

        n = (sh // MB) * (self.pad_w // MB)
        self._shapes = [((n, 2), 2 * n), ((n, 16, 4, 4), 256 * n),
                        ((n, 4, 4), 16 * n), ((n, 2, 2, 2), 8 * n),
                        ((n, 2, 4, 4, 4), 128 * n)]
        self._stripe_words = sum(s for _, s in self._shapes)

        # block-sparse transfer geometry (dev._pack_sparse): fixed head +
        # bitmap prefix, then content-sized compacted cells. The fetch
        # prefix adapts to the previous frame's content (pipeline.py's
        # bucket strategy) so a mostly-static desktop ships a few KB.
        # cap_frac=8 measured best end-to-end on the tunnel (55 fps vs
        # 44 at the round-3 cap_frac=4) while halving the compaction's
        # sort/gather domain (device 14.0 vs 20.5 ms/frame). cap_frac=32
        # is another 3 ms/frame faster on the raw device slope (11.0 ms,
        # 90 device-fps) but collapses the tunneled pipelined rate 3x —
        # PCIe deployments, where D2H is bandwidth- not RPC-bound,
        # should prefer it.
        self._cap_frac = cap_frac
        self._pad_words, self._n_cells, self._cap_cells = \
            dev.sparse_geometry(self._stripe_words, cap_frac)

        #: entropy tier for P frames (docs/entropy.md): "device" packs
        #: bit-exact CAVLC payloads on TPU (encoder/device_cavlc.py) so
        #: the fetch is the ~12 KB bitstream itself and steady state
        #: needs no host entropy threads; "host" ships the block-sparse
        #: levels and runs native CAVLC.  IDR and overflow stripes use
        #: the host path in both modes.
        if entropy is None:
            entropy = os.environ.get("SELKIES_TPU_H264_ENTROPY", "device")
        if entropy not in ("device", "host"):
            raise ValueError(f"entropy must be device|host, got {entropy!r}")
        self.entropy = entropy
        #: fetch tiers: _batch_prefix must be a STABLE static prefix —
        #: an adaptive one recompiles the (expensive) batched program on
        #: every bucket move; undershoot falls back to the exact flat16
        #: rows and grows it (bounded recompiles). _prefix_small serves
        #: static/quiet content — shipping the worst-case head every
        #: frame would cost 10-30x the D2H bytes of an idle desktop.
        if entropy == "device":
            self._cavlc_msb = dcav.default_max_stripe_bytes(
                self.pad_w // MB, sh // MB)
            self._fixed_bytes = dcav.HEAD_BYTES * self.n_stripes
            self._buf_bytes = self._fixed_bytes \
                + self.n_stripes * self._cavlc_msb
            # CAVLC payloads run ~4-6x smaller than the sparse cells, so
            # the fetch tiers shrink accordingly: full-damage 1080p
            # scroll measures ~12.7 KB/frame of bitstream, so pixels/80
            # (~26 KB at 1080p → the 32 KB bucket) leaves ~2.5x headroom
            # before the undershoot fallback engages
            self._sparse_guess = self._bucket(self._fixed_bytes + (16 << 10))
            self._batch_prefix = self._bucket(
                self._fixed_bytes
                + max(24 << 10, self.pad_h * self.pad_w // 80))
        else:
            self._cavlc_msb = 0
            self._fixed_bytes = 4 * self.n_stripes \
                + self.n_stripes * (self._n_cells // 8)
            self._buf_bytes = self._fixed_bytes \
                + self.n_stripes * self._cap_cells * dev.CELL
            self._sparse_guess = self._bucket(
                self._fixed_bytes + (64 << 10))
            # worst-case full-damage content at streaming QPs runs
            # ~1/20 of the pixel count in sparse cells (scroll source)
            self._batch_prefix = self._bucket(
                self._fixed_bytes
                + max(96 << 10, self.pad_h * self.pad_w // 20))
        self._prefix_small = self._bucket(self._fixed_bytes + 4096)

        #: observability (ISSUE 1 satellite): host entropy wall time and
        #: D2H re-read bytes, accumulated per harvested frame so the
        #: pipeline / bench can report per-frame gauges
        self.host_entropy_ms_total = 0.0
        self.d2h_refetch_bytes_total = 0
        #: stripes whose entropy coding failed and forced an IDR resync —
        #: repeated growth here is the signal the degradation ladder acts
        #: on (ISSUE 2: rung device -> host -> jpeg)
        self.entropy_errors_total = 0

    def _choose_prefix(self) -> int:
        """Pick between the two compiled head sizes from the adaptive
        estimate harvest maintains (_sparse_guess tracks ~1.5x the last
        frame's needed bytes)."""
        if self._sparse_guess <= self._prefix_small:
            return self._prefix_small
        return self._batch_prefix

    def _bucket(self, nbytes: int) -> int:
        """Power-of-two fetch prefix (bounds distinct slice executables)."""
        n = 4096
        while n < nbytes:
            n <<= 1
        return min(n, self._buf_bytes)

    # -- helpers -----------------------------------------------------------

    def _sps_pps_for(self, st: _StripeState) -> bytes:
        key = st.h
        if key not in self._sps_pps:
            self._sps_pps[key] = (
                make_sps(self.width, st.h, coded_height=self.stripe_h)
                + make_pps())
        return self._sps_pps[key]

    # -- encode ------------------------------------------------------------

    def dispatch(self, rgb, fetch: bool = True) -> "_H264Pending":
        """One dense device dispatch for the whole frame (every stripe);
        pair with :meth:`harvest`. Damage detection, reference-plane
        selection, and sparse level packing all happen inside the single
        jit program — the host's only per-frame read is the packed buffer.

        ``fetch=False`` skips starting the host copy; the caller owns the
        transfer (PipelinedH264Encoder groups several frames per read)."""
        rgb = jnp.asarray(rgb)

        is_idr = any(st.need_idr for st in self.stripes)
        if is_idr:
            # optimistic clear so pipelined dispatch-ahead frames don't
            # re-IDR; entropy failure at harvest re-arms the flag
            for st in self.stripes:
                st.need_idr = False
        paint = np.zeros(self.n_stripes, np.int8)
        if not is_idr:
            for i, st in enumerate(self.stripes):
                # candidacy from *previous* frames' history; optimistic
                # mark so in-flight frames don't re-trigger (cleared again
                # by damage at harvest)
                if (st.static_frames >= self.paint_over_trigger
                        and not st.painted_over):
                    paint[i] = 1
                    st.painted_over = True

        head = None
        if is_idr:
            (flat8, flat16, self._prev_y, self._prev_cb, self._prev_cr,
             self._ref_y, self._ref_cb, self._ref_cr) = \
                dev.encode_frame_idr_rgb(
                    rgb, self._prev_y, self._prev_cb, self._prev_cr,
                    self._ref_y, self._ref_cb, self._ref_cr,
                    jnp.int32(self.qp), pad_h=self.pad_h, pad_w=self.pad_w,
                    n_stripes=self.n_stripes, sh=self.stripe_h)
            pending_buf = None
            fetch_arr = flat16 if fetch else None
        elif self.entropy == "device":
            # on-device CAVLC: the fetch prefix is head + bit-exact
            # P-slice payloads (device_cavlc.py); flat16 stays device-
            # resident for overflow/IDR-resync re-reads
            (buf, head, flat16, self._prev_y, self._prev_cb, self._prev_cr,
             self._ref_y, self._ref_cb, self._ref_cr) = \
                dev.encode_frame_p_cavlc_rgb(
                    rgb, self._prev_y, self._prev_cb, self._prev_cr,
                    self._ref_y, self._ref_cb, self._ref_cr,
                    jnp.asarray(paint, jnp.int32),
                    jnp.int32(self.qp), jnp.int32(self.paint_over_qp),
                    pad_h=self.pad_h, pad_w=self.pad_w,
                    n_stripes=self.n_stripes, sh=self.stripe_h,
                    search=self.search,
                    max_stripe_bytes=self._cavlc_msb,
                    prefix=self._choose_prefix(), me=dev._me_backend())
            pending_buf = buf
            fetch_arr = head if fetch else None
        else:
            # the whole per-frame program — planes, encode, pack, and the
            # fetch-prefix slice — is ONE dispatch (RPC-attached devices
            # pay per program, not per FLOP)
            (buf, head, flat16, self._prev_y, self._prev_cb, self._prev_cr,
             self._ref_y, self._ref_cb, self._ref_cr) = \
                dev.encode_frame_p_rgb(
                    rgb, self._prev_y, self._prev_cb, self._prev_cr,
                    self._ref_y, self._ref_cb, self._ref_cr,
                    jnp.asarray(paint, jnp.int32),
                    jnp.int32(self.qp), jnp.int32(self.paint_over_qp),
                    pad_h=self.pad_h, pad_w=self.pad_w,
                    n_stripes=self.n_stripes, sh=self.stripe_h,
                    # two-tier prefix: static content ships the small
                    # head, busy content the sized one — two compiled
                    # programs, no per-bucket recompile churn; undershoot
                    # re-reads from buf
                    search=self.search, prefix=self._choose_prefix(),
                    cap_frac=self._cap_frac, me=dev._me_backend())
            pending_buf = buf
            fetch_arr = head if fetch else None
        if fetch_arr is not None:
            fetch_arr.copy_to_host_async()
        qp_arr = np.where(paint != 0, self.paint_over_qp, self.qp)
        return _H264Pending(fetch=fetch_arr, flat16=flat16, is_idr=is_idr,
                            paint=paint, qp=qp_arr, buf=pending_buf,
                            head=head,
                            cavlc=(not is_idr and self.entropy == "device"),
                            head_len=0 if is_idr else int(head.shape[0]))

    def dispatch_batch(self, rgbs, fetch: bool = True
                       ) -> List["_H264Pending"]:
        """Encode B sequential frames in ONE device dispatch.

        ``rgbs``: (B, H, W, 3) uint8 (device or host). The P-frame
        reference chain rides a scan inside the program
        (dev.encode_frame_p_batch_rgb), so RPC-attached transports pay
        one round trip per batch instead of per frame. Falls back to
        per-frame dispatch while any stripe needs an IDR."""
        B = int(rgbs.shape[0])
        if any(st.need_idr for st in self.stripes):
            # keyframe recovery must not wait on a compile: the single
            # frame programs are already built, whereas a (B-1)-shaped
            # batch scan would compile from scratch mid-recovery
            return [self.dispatch(rgbs[b], fetch=fetch) for b in range(B)]
        paints = np.zeros((B, self.n_stripes), np.int8)
        for b in range(B):
            for i, st in enumerate(self.stripes):
                # forecast static_frames per in-batch offset (harvest has
                # not advanced per-stripe history for frames still inside
                # this batch): a stripe crossing the trigger mid-batch
                # paints at the right frame, not up to B-1 frames late.
                # If damage lands mid-batch instead, that frame emits at
                # paint QP (extra quality, never a stale stripe).
                if (st.static_frames + b >= self.paint_over_trigger
                        and not st.painted_over):
                    paints[b, i] = 1
                    st.painted_over = True
        qps = np.where(paints != 0, self.paint_over_qp, self.qp)
        prefix = self._choose_prefix()
        if self.entropy == "device":
            (heads, flat16s, self._prev_y, self._prev_cb, self._prev_cr,
             self._ref_y, self._ref_cb, self._ref_cr) = \
                dev.encode_frame_p_batch_cavlc_rgb(
                    jnp.asarray(rgbs),
                    self._prev_y, self._prev_cb, self._prev_cr,
                    self._ref_y, self._ref_cb, self._ref_cr,
                    jnp.asarray(paints, jnp.int32),
                    jnp.full((B,), self.qp, jnp.int32),
                    jnp.int32(self.paint_over_qp),
                    pad_h=self.pad_h, pad_w=self.pad_w,
                    n_stripes=self.n_stripes, sh=self.stripe_h,
                    search=self.search,
                    max_stripe_bytes=self._cavlc_msb,
                    prefix=prefix, me=dev._me_backend())
        else:
            (heads, flat16s, self._prev_y, self._prev_cb, self._prev_cr,
             self._ref_y, self._ref_cb, self._ref_cr) = \
                dev.encode_frame_p_batch_rgb(
                    jnp.asarray(rgbs),
                    self._prev_y, self._prev_cb, self._prev_cr,
                    self._ref_y, self._ref_cb, self._ref_cr,
                    jnp.asarray(paints, jnp.int32),
                    jnp.full((B,), self.qp, jnp.int32),
                    jnp.int32(self.paint_over_qp),
                    pad_h=self.pad_h, pad_w=self.pad_w,
                    n_stripes=self.n_stripes, sh=self.stripe_h,
                    search=self.search, prefix=prefix,
                    cap_frac=self._cap_frac, me=dev._me_backend())
        if fetch:
            heads.copy_to_host_async()
        cache: Dict[str, np.ndarray] = {}   # shared host copy of heads
        return [_H264Pending(
            fetch=None, flat16=None, is_idr=False, paint=paints[b],
            qp=qps[b], batch_heads=heads, batch_flat16=flat16s,
            batch_index=b, head_len=prefix,
            cavlc=(self.entropy == "device"),
            batch_cache=cache) for b in range(B)]

    def _recover_undershoot(self, p: "_H264Pending", host, needed: int,
                            ovf: np.ndarray, damage: np.ndarray):
        """Prediction-miss recovery shared by the sparse and device-CAVLC
        transfers.  Single-frame dispatches re-read the right bucket from
        the full device buffer; batch dispatches keep no full buffer, so
        every emitting stripe falls back to the exact flat16 rows and the
        pinned batch prefix grows (bucketed → bounded recompiles)."""
        if needed > len(host):
            if p.buf is not None:
                full = p.buf[:self._bucket(needed)]
                full.copy_to_host_async()
                host = np.asarray(full)
                self.d2h_refetch_bytes_total += host.nbytes
            else:
                ovf = ovf | damage | (p.paint != 0)
                if len(host) >= self._batch_prefix:
                    # undershoot at the LARGE prefix: worst-case head
                    # really is bigger — grow it. An undershoot at the
                    # small tier just means the scene got busy; the
                    # guess below re-tiers it.
                    self._batch_prefix = min(
                        self._buf_bytes,
                        self._bucket(needed + needed // 2))
        self._sparse_guess = self._bucket(
            max(needed + needed // 2, self._fixed_bytes + 4096))
        return host, ovf

    def _refetch_overflow_rows(self, p: "_H264Pending", damage, ovf):
        """Exact flat16 re-reads for overflow stripes, all started before
        any blocking (rare: |level| beyond the packed range)."""
        if p.flat16 is None and p.batch_flat16 is not None:
            p.flat16 = p.batch_flat16[p.batch_index]
        refetch = {}
        need_rows = [i for i in range(self.n_stripes)
                     if ovf[i] and (damage[i] or p.paint[i])]
        if len(need_rows) > 2:
            # whole-frame fallback (batch undershoot): ONE read of the
            # exact levels instead of a per-stripe RPC each
            rows_host = np.asarray(p.flat16)
            self.d2h_refetch_bytes_total += rows_host.nbytes
            refetch = {i: rows_host[i] for i in need_rows}
        else:
            for i in need_rows:
                sl = p.flat16[i]
                sl.copy_to_host_async()
                refetch[i] = sl
                self.d2h_refetch_bytes_total += 2 * self._stripe_words
        return refetch

    def harvest(self, p: "_H264Pending",
                host: Optional[np.ndarray] = None) -> List[H264Stripe]:
        """Entropy-code one dispatched frame (host CAVLC over the fetched
        levels). Must be called in dispatch order. ``host`` supplies the
        already-fetched bytes when a pipeline owns the transfer."""
        if host is None:
            if p.batch_heads is not None:
                # one device read shared by every frame of the batch
                if p.batch_cache.get("heads") is None:
                    p.batch_cache["heads"] = np.asarray(p.batch_heads)
                host = p.batch_cache["heads"][p.batch_index]
            else:
                host = np.asarray(p.fetch)
        S = self.n_stripes
        t_bits = base_words = None
        if p.is_idr:
            levels16 = host
            damage = np.ones(S, bool)
            ovf = np.zeros(S, bool)
        elif p.cavlc:
            # device-CAVLC transfer: head + bit-exact slice payloads
            levels16 = None
            t_bits, base_words, damage, ovf = dcav.parse_cavlc_head(host, S)
            # mirror the device's per-stripe word clip: an overflowing
            # stripe records its unclipped t_bits but compacts at most V
            # words, and an unclipped estimate here would force a
            # full-buffer refetch exactly on busy content
            wc = np.minimum((t_bits + 31) // 32, self._cavlc_msb // 4)
            needed = self._fixed_bytes + 4 * int(base_words[-1] + wc[-1])
            host, ovf = self._recover_undershoot(p, host, needed,
                                                 ovf, damage)
            refetch = self._refetch_overflow_rows(p, damage, ovf)
        else:
            levels16 = None
            head = host[:4 * S].reshape(S, 4)
            counts = head[:, 0].astype(np.int64) \
                + (head[:, 1].astype(np.int64) << 8)
            damage = head[:, 2] != 0
            ovf = head[:, 3] != 0
            used = np.minimum(counts, self._cap_cells) * dev.CELL
            needed = self._fixed_bytes + int(used.sum())
            host, ovf = self._recover_undershoot(p, host, needed,
                                                 ovf, damage)
            bitmaps = host[4 * S:self._fixed_bytes] \
                .reshape(S, self._n_cells // 8)
            starts = np.concatenate(
                [[0], np.cumsum(used)[:-1]]) + self._fixed_bytes
            refetch = self._refetch_overflow_rows(p, damage, ovf)

        out: List[H264Stripe] = []
        mb_w = self.pad_w // MB
        mb_h = self.stripe_h // MB
        jobs: List[tuple] = []
        for i, st in enumerate(self.stripes):
            if p.is_idr:
                emit, is_key = True, True
                st.static_frames = 0
                st.painted_over = False
            elif damage[i]:
                emit, is_key = True, False
                st.static_frames = 0
                st.painted_over = False
            elif p.paint[i]:
                emit, is_key = True, False
                st.static_frames += 1
            else:
                emit = False
                st.static_frames += 1
            if not emit:
                continue

            if not p.is_idr and p.cavlc and not ovf[i]:
                # device already entropy-coded this stripe: the host job
                # is header/exp-Golomb glue only (no per-MB work)
                pb, nbits = dcav.payload_slice(host, S, base_words,
                                               t_bits, i)
                jobs.append((i, st, is_key, int(p.qp[i]),
                             ("bits", pb, nbits)))
                continue
            if p.is_idr:
                row = levels16[i].astype(np.int32)
            elif ovf[i]:
                row = np.asarray(refetch[i]).astype(np.int32)
            else:
                # rebuild the dense row from bitmap + compacted cells
                bits = np.unpackbits(bitmaps[i], bitorder="little")
                idx = np.flatnonzero(bits[:self._n_cells])
                cells = host[starts[i]:starts[i] + used[i]] \
                    .view(np.int8).astype(np.int32).reshape(-1, dev.CELL)
                dense = np.zeros(self._pad_words, np.int32)
                dense.reshape(-1, dev.CELL)[idx[:len(cells)]] = cells
                row = dense[:self._stripe_words]
            parts = []
            pos = 0
            for shape, size in self._shapes:
                parts.append(row[pos:pos + size].reshape(shape))
                pos += size
            mv, luma, luma_dc, chroma_dc, chroma_ac = parts
            jobs.append((i, st, is_key, int(p.qp[i]),
                         ("levels", mv, luma, luma_dc, chroma_dc,
                          chroma_ac)))

        def run_one(job):
            i, st, is_key, qp, work = job
            if work[0] == "bits":
                _, pb, nbits = work
                return dcav.assemble_p_slice(pb, nbits, qp, st.frame_num)
            _, mv, luma, luma_dc, chroma_dc, chroma_ac = work
            if is_key:
                nals = encode_picture_nals_np(
                    mv, luma, luma_dc, chroma_dc, chroma_ac,
                    is_idr=True, mb_w=mb_w, mb_h=mb_h, qp=qp,
                    frame_num=0, idr_pic_id=st.idr_pic_id)
                return self._sps_pps_for(st) + nals
            return encode_picture_nals_np(
                mv, luma, luma_dc, chroma_dc, chroma_ac,
                is_idr=False, mb_w=mb_w, mb_h=mb_h, qp=qp,
                frame_num=st.frame_num)

        def safe_one(job):
            try:
                return run_one(job)
            except Exception as exc:       # surfaced per stripe below
                return exc

        # the C coder releases the GIL: stripes entropy-code in parallel
        # (pixelflux does the same with per-stripe C++ threads)
        t_entropy0 = time.perf_counter()
        if len(jobs) > 1:
            payloads = list(_entropy_pool().map(safe_one, jobs))
        else:
            payloads = [safe_one(job) for job in jobs]
        self.host_entropy_ms_total += \
            (time.perf_counter() - t_entropy0) * 1000.0
        for job, payload in zip(jobs, payloads):
            i, st, is_key, qp, _ = job
            if isinstance(payload, Exception):
                # the device ref already advanced to a reconstruction the
                # decoder will never see — resynchronize with an IDR
                # instead of drifting every following P frame
                self.entropy_errors_total += 1
                logger.error("entropy coding failed for stripe %d; "
                             "forcing IDR resync", i, exc_info=payload)
                st.need_idr = True
                continue
            if is_key:
                st.frame_num = 1
                st.idr_pic_id = (st.idr_pic_id + 1) % 16
                st.need_idr = False
            else:
                st.frame_num = (st.frame_num + 1) % 16
            out.append(H264Stripe(
                y_start=st.y0, width=self.width, height=st.h,
                annexb=payload, is_key=is_key))
        return out

    def encode_frame(self, rgb) -> List[H264Stripe]:
        """RGB (H, W, 3) uint8 → encoded stripes (only damaged/paint-over)."""
        return self.harvest(self.dispatch(rgb))

    def request_keyframe(self) -> None:
        """Force IDR on every stripe (client join / PIPELINE_RESETTING)."""
        for st in self.stripes:
            st.need_idr = True

    def stripe_ref(self, i: int):
        """Host copies of stripe i's reference planes (conformance oracle)."""
        sh = self.stripe_h
        y = np.asarray(self._ref_y[i * sh:(i + 1) * sh])
        cb = np.asarray(self._ref_cb[i * sh // 2:(i + 1) * sh // 2])
        cr = np.asarray(self._ref_cr[i * sh // 2:(i + 1) * sh // 2])
        return y, cb, cr


@dataclass
class _H264Pending:
    """One in-flight H.264 dispatch."""

    fetch: object               # async-fetching buffer (sparse u8 for P,
    flat16: object              # i16 for IDR); exact levels for re-reads
    is_idr: bool
    paint: np.ndarray
    qp: np.ndarray
    buf: object = None          # full sparse device buffer (undershoot)
    head: object = None         # prefix slice produced inside the program
    head_len: int = 0
    batch_heads: object = None      # (B, prefix) heads of a batch dispatch
    batch_flat16: object = None     # (B, S, words) exact levels
    batch_index: int = 0
    batch_cache: Optional[Dict] = None  # shared host copy across the batch
    cavlc: bool = False             # buffer holds device-CAVLC payloads


