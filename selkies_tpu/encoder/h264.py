"""tpuenc v1: H.264 Constrained-Baseline striped encoder.

Capability parity with the reference's ``x264enc-striped`` / ``x264enc``
pixelflux modes (CaptureSettings output_mode=1, selkies.py:2919-2963;
client decoders selkies-core.js:2925-2968): each horizontal stripe is an
independent H.264 video sequence with its own SPS/PPS/IDR chain, so the
client can run one WebCodecs ``VideoDecoder`` per stripe and only damaged
stripes are ever encoded or shipped.

Split of work (TPU-first, SURVEY.md §7 step 6):
  * device (encoder/h264_device.py): color/4:2:0, exhaustive ME, transforms,
    quant, and the exact decoder-arithmetic reconstruction loop;
  * host (native/cavlc.cpp): CAVLC entropy coding + NAL packaging of the
    device's level arrays;
  * here: stripe/GOP orchestration, damage gating, paint-over escalation
    (low-QP P frames — no IDR needed, unlike the reference's burst
    keyframes), SPS/PPS generation, reference-plane state.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..native import cavlc_lib
from . import h264_device as dev

logger = logging.getLogger("selkies_tpu.encoder.h264")

MB = 16


# ---------------------------------------------------------------------------
# SPS / PPS


class _BitWriter:
    def __init__(self) -> None:
        self.bits: List[int] = []

    def u(self, value: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.bits.append((value >> i) & 1)

    def ue(self, v: int) -> None:
        vp1 = v + 1
        n = vp1.bit_length() - 1
        self.u(0, n)
        self.u(vp1, n + 1)

    def se(self, v: int) -> None:
        self.ue(-2 * v if v <= 0 else 2 * v - 1)

    def rbsp(self) -> bytes:
        bits = self.bits + [1]
        while len(bits) % 8:
            bits.append(0)
        out = bytearray()
        for i in range(0, len(bits), 8):
            b = 0
            for bit in bits[i:i + 8]:
                b = (b << 1) | bit
            out.append(b)
        # emulation prevention
        esc = bytearray()
        zeros = 0
        for b in out:
            if zeros >= 2 and b <= 3:
                esc.append(3)
                zeros = 0
            esc.append(b)
            zeros = zeros + 1 if b == 0 else 0
        return bytes(esc)


def _nal(nal_type: int, rbsp: bytes, ref_idc: int = 3) -> bytes:
    return b"\x00\x00\x00\x01" + bytes(((ref_idc << 5) | nal_type,)) + rbsp


def make_sps(width: int, height: int, *, level_idc: int = 40,
             full_range: bool = True) -> bytes:
    """Constrained-Baseline SPS for a (possibly cropped) 4:2:0 frame."""
    mb_w = (width + 15) // 16
    mb_h = (height + 15) // 16
    crop_r = (mb_w * 16 - width) // 2
    crop_b = (mb_h * 16 - height) // 2
    bw = _BitWriter()
    bw.u(66, 8)          # profile_idc: Baseline
    bw.u(0b11000000, 8)  # constraint_set0+1 (constrained baseline)
    bw.u(level_idc, 8)
    bw.ue(0)             # sps id
    bw.ue(0)             # log2_max_frame_num_minus4 → 4-bit frame_num
    bw.ue(2)             # pic_order_cnt_type
    bw.ue(1)             # max_num_ref_frames
    bw.u(0, 1)           # gaps_in_frame_num_value_allowed
    bw.ue(mb_w - 1)
    bw.ue(mb_h - 1)
    bw.u(1, 1)           # frame_mbs_only
    bw.u(1, 1)           # direct_8x8_inference
    if crop_r or crop_b:
        bw.u(1, 1)
        bw.ue(0)
        bw.ue(crop_r)
        bw.ue(0)
        bw.ue(crop_b)
    else:
        bw.u(0, 1)
    # VUI: declare BT.601 + range so the browser matches our color matrix
    bw.u(1, 1)           # vui_parameters_present
    bw.u(0, 1)           # aspect_ratio_info_present
    bw.u(0, 1)           # overscan_info_present
    bw.u(1, 1)           # video_signal_type_present
    bw.u(5, 3)           # video_format: unspecified
    bw.u(1 if full_range else 0, 1)
    bw.u(1, 1)           # colour_description_present
    bw.u(6, 8)           # primaries: SMPTE 170M
    bw.u(6, 8)           # transfer
    bw.u(6, 8)           # matrix: BT.601
    bw.u(0, 1)           # chroma_loc_info_present
    bw.u(0, 1)           # timing_info_present
    bw.u(0, 1)           # nal_hrd
    bw.u(0, 1)           # vcl_hrd
    bw.u(0, 1)           # pic_struct_present
    bw.u(0, 1)           # bitstream_restriction
    return _nal(7, bw.rbsp())


def make_pps() -> bytes:
    bw = _BitWriter()
    bw.ue(0)     # pps id
    bw.ue(0)     # sps id
    bw.u(0, 1)   # entropy_coding_mode: CAVLC
    bw.u(0, 1)   # bottom_field_pic_order_in_frame_present
    bw.ue(0)     # num_slice_groups_minus1
    bw.ue(0)     # num_ref_idx_l0_default_active_minus1
    bw.ue(0)     # num_ref_idx_l1_default_active_minus1
    bw.u(0, 1)   # weighted_pred
    bw.u(0, 2)   # weighted_bipred_idc
    bw.se(0)     # pic_init_qp_minus26 (slice writer assumes 26)
    bw.se(0)     # pic_init_qs_minus26
    bw.se(0)     # chroma_qp_index_offset (qpc_for assumes 0)
    bw.u(1, 1)   # deblocking_filter_control_present (slices disable it)
    bw.u(0, 1)   # constrained_intra_pred
    bw.u(0, 1)   # redundant_pic_cnt_present
    return _nal(8, bw.rbsp())


# ---------------------------------------------------------------------------
# host entropy dispatch


def encode_picture_nals(out: dev.StripeEncodeOut, *, is_idr: bool,
                        mb_w: int, mb_h: int, qp: int, frame_num: int,
                        idr_pic_id: int = 0) -> bytes:
    """Run the native CAVLC coder over one stripe's device outputs."""
    return encode_picture_nals_np(
        np.ascontiguousarray(np.asarray(out.mv), np.int32),
        np.ascontiguousarray(np.asarray(out.luma), np.int32),
        np.ascontiguousarray(np.asarray(out.luma_dc), np.int32),
        np.ascontiguousarray(np.asarray(out.chroma_dc), np.int32),
        np.ascontiguousarray(np.asarray(out.chroma_ac), np.int32),
        is_idr=is_idr, mb_w=mb_w, mb_h=mb_h, qp=qp,
        frame_num=frame_num, idr_pic_id=idr_pic_id)


def encode_picture_nals_np(mv, luma, luma_dc, chroma_dc, chroma_ac, *,
                           is_idr: bool, mb_w: int, mb_h: int, qp: int,
                           frame_num: int, idr_pic_id: int = 0) -> bytes:
    """CAVLC over host-resident coefficient arrays (already fetched)."""
    lib = cavlc_lib()
    if lib is None:
        raise RuntimeError("native CAVLC coder unavailable")
    cap = 1 << 22
    buf = np.empty(cap, np.uint8)
    n = lib.h264_encode_picture(
        1 if is_idr else 0, mb_w, mb_h, qp, frame_num & 0xF, idr_pic_id,
        np.ascontiguousarray(mv, np.int32),
        np.ascontiguousarray(luma, np.int32),
        np.ascontiguousarray(luma_dc, np.int32),
        np.ascontiguousarray(chroma_dc, np.int32),
        np.ascontiguousarray(chroma_ac, np.int32),
        buf, cap)
    if n < 0:
        raise RuntimeError("CAVLC output exceeded capacity")
    return bytes(buf[:n])


# ---------------------------------------------------------------------------
# stripe orchestration


@dataclass
class H264Stripe:
    y_start: int
    width: int          # coded (cropped) width
    height: int         # coded (cropped) height of this stripe
    annexb: bytes
    is_key: bool


@dataclass
class _StripeState:
    y0: int             # luma row offset (unpadded coordinates)
    h: int              # unpadded stripe height
    pad_h: int          # MB-aligned height
    frame_num: int = 0
    idr_pic_id: int = 0
    need_idr: bool = True
    static_frames: int = 0
    painted_over: bool = False
    ref_y: Optional[jnp.ndarray] = None
    ref_cb: Optional[jnp.ndarray] = None
    ref_cr: Optional[jnp.ndarray] = None


class H264StripeEncoder:
    """Striped (or full-frame) H.264 encoder with damage gating.

    ``fullframe=True`` reproduces the reference's ``x264enc`` mode: one
    stripe covering the whole frame. The server ships it as 0x00
    full-frame packets (the wire routing lives in the encoder adapter's
    ``wire_fullframe`` flag, not here — reference h264_fullframe,
    selkies.py:2937, wire demux selkies-core.js 0x00 path).
    """

    def __init__(self, width: int, height: int, *, stripe_height: int = 64,
                 qp: int = 26, paint_over_qp: int = 18,
                 paint_over_trigger_frames: int = 15,
                 search: int = 12, fullframe: bool = False) -> None:
        if width % 2 or height % 2:
            raise ValueError("frame dimensions must be even")
        if stripe_height % MB:
            raise ValueError("stripe_height must be a multiple of 16")
        self.width = width
        self.height = height
        self.qp = int(np.clip(qp, 0, 51))
        self.paint_over_qp = int(np.clip(paint_over_qp, 0, 51))
        self.paint_over_trigger = paint_over_trigger_frames
        self.search = search
        self.pad_w = (width + MB - 1) // MB * MB
        sh = height if fullframe else stripe_height
        self.stripe_h = sh
        self.stripes: List[_StripeState] = []
        y = 0
        while y < height:
            h = min(sh, height - y)
            self.stripes.append(_StripeState(
                y0=y, h=h, pad_h=(h + MB - 1) // MB * MB))
            y += h
        self._sps_pps: Dict[int, bytes] = {}
        self._prev_rgb: Optional[jnp.ndarray] = None

    # -- helpers -----------------------------------------------------------

    def _sps_pps_for(self, st: _StripeState) -> bytes:
        key = st.h
        if key not in self._sps_pps:
            self._sps_pps[key] = (make_sps(self.width, st.h) + make_pps())
        return self._sps_pps[key]

    def _damage_flags(self, rgb: jnp.ndarray) -> np.ndarray:
        if self._prev_rgb is None:
            return np.ones(len(self.stripes), bool)
        flags = _stripe_damage(rgb, self._prev_rgb,
                               tuple(s.y0 for s in self.stripes),
                               tuple(s.h for s in self.stripes))
        return np.asarray(flags)

    # -- encode ------------------------------------------------------------

    def encode_frame(self, rgb) -> List[H264Stripe]:
        """RGB (H, W, 3) uint8 → encoded stripes (only damaged/paint-over)."""
        rgb = jnp.asarray(rgb)
        damage = self._damage_flags(rgb)
        self._prev_rgb = rgb

        y_full, cb_full, cr_full = dev.prepare_planes(
            rgb, self.height, self.pad_w)

        # Phase 1 — dispatch every damaged stripe's device encode (async;
        # dispatches pipeline on the device stream).
        pending = []     # (st, enc_out, is_idr, qp)
        for i, st in enumerate(self.stripes):
            paint_over = False
            if not damage[i] and not st.need_idr:
                st.static_frames += 1
                if (st.static_frames >= self.paint_over_trigger
                        and not st.painted_over):
                    paint_over = True
                    st.painted_over = True
                else:
                    continue
            else:
                st.static_frames = 0
                st.painted_over = False

            sy = _pad_stripe(y_full, st.y0, st.h, st.pad_h)
            scb = _pad_stripe(cb_full, st.y0 // 2, st.h // 2, st.pad_h // 2)
            scr = _pad_stripe(cr_full, st.y0 // 2, st.h // 2, st.pad_h // 2)
            qp = self.paint_over_qp if paint_over else self.qp
            if st.need_idr or st.ref_y is None:
                enc = dev.encode_stripe_idr(sy, scb, scr, qp)
                pending.append((st, enc, True, qp))
            else:
                enc = dev.encode_stripe_p(
                    sy, scb, scr, st.ref_y, st.ref_cb, st.ref_cr, qp,
                    self.search)
                pending.append((st, enc, False, qp))

        if not pending:
            return []

        # Phase 2 — ONE device concat + ONE host read for every stripe's
        # coefficients (i16 halves the transfer; levels/MVs fit easily).
        # Per-fetch latency dominates RPC-attached devices: the naive
        # per-array asarray() path costs 5 reads × stripes per frame.
        # Each stripe flattens through a per-geometry jitted pack so the
        # final concatenate only varies with the pending COUNT, not with
        # which subset of stripes was damaged.
        chunks = []
        splits = []
        for st, enc, is_idr, qp in pending:
            arrs = (enc.mv, enc.luma, enc.luma_dc, enc.chroma_dc,
                    enc.chroma_ac)
            shapes = [a.shape for a in arrs]
            sizes = [int(np.prod(s)) for s in shapes]
            splits.append((shapes, sizes))
            chunks.append(_flatten_stripe_i16(*arrs))
        flat = np.asarray(
            chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks))

        out: List[H264Stripe] = []
        pos = 0
        mb_w = self.pad_w // MB
        for (st, enc, is_idr, qp), (shapes, sizes) in zip(pending, splits):
            parts = []
            for shape, size in zip(shapes, sizes):
                parts.append(flat[pos:pos + size].reshape(shape)
                             .astype(np.int32))
                pos += size
            mv, luma, luma_dc, chroma_dc, chroma_ac = parts
            mb_h = st.pad_h // MB
            if is_idr:
                nals = encode_picture_nals_np(
                    mv, luma, luma_dc, chroma_dc, chroma_ac,
                    is_idr=True, mb_w=mb_w, mb_h=mb_h, qp=qp,
                    frame_num=0, idr_pic_id=st.idr_pic_id)
                payload = self._sps_pps_for(st) + nals
                st.frame_num = 1
                st.idr_pic_id = (st.idr_pic_id + 1) % 16
                st.need_idr = False
            else:
                payload = encode_picture_nals_np(
                    mv, luma, luma_dc, chroma_dc, chroma_ac,
                    is_idr=False, mb_w=mb_w, mb_h=mb_h, qp=qp,
                    frame_num=st.frame_num)
                st.frame_num = (st.frame_num + 1) % 16
            # commit the reference ONLY once the bitstream for this stripe
            # exists: an entropy failure must not leave the encoder
            # predicting from a reconstruction the decoder never got
            st.ref_y, st.ref_cb, st.ref_cr = (
                enc.recon_y, enc.recon_cb, enc.recon_cr)
            out.append(H264Stripe(
                y_start=st.y0, width=self.width, height=st.h,
                annexb=payload, is_key=is_idr))
        return out

    def request_keyframe(self) -> None:
        """Force IDR on every stripe (client join / PIPELINE_RESETTING)."""
        for st in self.stripes:
            st.need_idr = True


@jax.jit
def _flatten_stripe_i16(mv, luma, luma_dc, chroma_dc, chroma_ac):
    """One stripe's device outputs → one flat i16 buffer (fixed shape per
    stripe geometry, so the cross-stripe concatenate stays shape-stable)."""
    return jnp.concatenate([
        a.reshape(-1).astype(jnp.int16)
        for a in (mv, luma, luma_dc, chroma_dc, chroma_ac)])


@functools.partial(jax.jit, static_argnames=("y0s", "hs"))
def _stripe_damage(rgb, prev, y0s, hs):
    flags = []
    for y0, h in zip(y0s, hs):
        a = jax.lax.dynamic_slice_in_dim(rgb, y0, h, axis=0)
        b = jax.lax.dynamic_slice_in_dim(prev, y0, h, axis=0)
        flags.append(jnp.any(a != b))
    return jnp.stack(flags)


@functools.partial(jax.jit, static_argnames=("y0", "h", "pad_h"))
def _pad_stripe(plane, y0: int, h: int, pad_h: int):
    s = jax.lax.dynamic_slice_in_dim(plane, y0, h, axis=0)
    if pad_h != h:
        s = jnp.pad(s, ((0, pad_h - h), (0, 0)), mode="edge")
    return s
