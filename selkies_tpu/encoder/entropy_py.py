"""Pure-Python baseline-JPEG entropy coder — reference implementation.

This is the correctness oracle for the C++ coder in ``selkies_tpu/native``
(and the fallback when no C++ toolchain is available). Input is the device
pipeline's output: zigzagged, quantized int16 coefficients per 8x8 block.
"""

from __future__ import annotations

import numpy as np

from .jpeg_tables import std_tables


class BitWriter:
    """MSB-first bit packer with JPEG 0xFF byte stuffing."""

    def __init__(self) -> None:
        self._out = bytearray()
        self._acc = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        if nbits == 0:
            return
        self._acc = (self._acc << nbits) | (value & ((1 << nbits) - 1))
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            byte = (self._acc >> self._nbits) & 0xFF
            self._out.append(byte)
            if byte == 0xFF:
                self._out.append(0x00)
        self._acc &= (1 << self._nbits) - 1

    def flush(self) -> bytes:
        """Pad with 1-bits to a byte boundary (T.81 F.1.2.3) and return."""
        if self._nbits:
            pad = 8 - self._nbits
            self.write((1 << pad) - 1, pad)
        return bytes(self._out)


def _category(v: int) -> int:
    return int(v).bit_length() if v > 0 else int(-v).bit_length()


def _encode_block(bw: BitWriter, zz: np.ndarray, pred_dc: int, dc_tab, ac_tab) -> int:
    """Encode one zigzagged 64-coefficient block; returns its DC value."""
    dc = int(zz[0])
    diff = dc - pred_dc
    size = _category(diff)
    code, length = dc_tab.codes[size]
    bw.write(code, length)
    if size:
        # negative values are stored as ones'-complement (T.81 F.1.2.1)
        bw.write(diff if diff > 0 else diff + (1 << size) - 1, size)

    run = 0
    for k in range(1, 64):
        v = int(zz[k])
        if v == 0:
            run += 1
            continue
        while run >= 16:
            code, length = ac_tab.codes[0xF0]  # ZRL
            bw.write(code, length)
            run -= 16
        size = _category(v)
        code, length = ac_tab.codes[(run << 4) | size]
        bw.write(code, length)
        bw.write(v if v > 0 else v + (1 << size) - 1, size)
        run = 0
    if run:
        code, length = ac_tab.codes[0x00]  # EOB
        bw.write(code, length)
    return dc


def encode_scan_420(
    y_blocks: np.ndarray,   # [by, bx, 64] int (by, bx even)
    cb_blocks: np.ndarray,  # [by/2, bx/2, 64]
    cr_blocks: np.ndarray,  # [by/2, bx/2, 64]
) -> bytes:
    """Entropy-code a 4:2:0 interleaved scan (MCU = 4 Y + Cb + Cr)."""
    dc_l, ac_l, dc_c, ac_c = std_tables()
    by, bx, _ = y_blocks.shape
    bw = BitWriter()
    pred_y = pred_cb = pred_cr = 0
    for mr in range(by // 2):
        for mc in range(bx // 2):
            for dy in (0, 1):
                for dx in (0, 1):
                    pred_y = _encode_block(
                        bw, y_blocks[2 * mr + dy, 2 * mc + dx], pred_y, dc_l, ac_l)
            pred_cb = _encode_block(bw, cb_blocks[mr, mc], pred_cb, dc_c, ac_c)
            pred_cr = _encode_block(bw, cr_blocks[mr, mc], pred_cr, dc_c, ac_c)
    return bw.flush()


def encode_scan_444(
    y_blocks: np.ndarray, cb_blocks: np.ndarray, cr_blocks: np.ndarray
) -> bytes:
    """Entropy-code a 4:4:4 interleaved scan (MCU = Y + Cb + Cr)."""
    dc_l, ac_l, dc_c, ac_c = std_tables()
    by, bx, _ = y_blocks.shape
    bw = BitWriter()
    pred_y = pred_cb = pred_cr = 0
    for r in range(by):
        for c in range(bx):
            pred_y = _encode_block(bw, y_blocks[r, c], pred_y, dc_l, ac_l)
            pred_cb = _encode_block(bw, cb_blocks[r, c], pred_cb, dc_c, ac_c)
            pred_cr = _encode_block(bw, cr_blocks[r, c], pred_cr, dc_c, ac_c)
    return bw.flush()
