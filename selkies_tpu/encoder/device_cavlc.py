"""Device-side (on-TPU) H.264 CAVLC entropy coding for P slices.

Why: the H.264 path's named steady-state bottleneck (BENCH_r05
``h264_bottleneck``) is the per-batch D2H read of the block-sparse
coefficient buffer, plus a per-session host CPU cost for the native CAVLC
coder (encoder/h264.py ``_entropy_pool``).  The JPEG path already proved
the fix (encoder/device_entropy.py): run entropy coding on device and
fetch only the compressed bits.  A P slice's mean bitstream is ~12.7 KB
at 1080p — far below the sparse level transfer — so packing CAVLC on
device shrinks the named bottleneck directly AND removes the per-session
host entropy threads (the "millions of users" scaling wall).

Unlike CABAC, every CAVLC context is *data-parallel*: the nC context of a
4×4 block is a function of its neighbors' totalCoeff — a pure count of
nonzeros, independent of any coded bit.  Skip runs, MV prediction and cbp
are likewise closed-form over the MV/level grids.  The only sequential
chain is the per-block level suffix_length adaptation, which spans ≤ 16
coefficients and unrolls into 16 vectorized steps.

Structure (mirrors device_entropy.py's slot-grid design):

  1. per-MB syntax (skip decision, mb_skip_run, mvd, cbp, mb_qp_delta)
     and per-residual-block CAVLC symbols are computed into fixed
     (bits, len) slot grids — each slot ≤ 32 bits;
  2. VLC tables (coeff_token / total_zeros / run_before, ITU-T H.264
     Tables 9-5..9-10, transcribed from native/cavlc.cpp) are looked up
     through a two-level one-hot matmul over one packed (code<<5|len)
     table — MXU-friendly, no scalar-core gathers;
  3. each *unit* (MB header, one residual block, or the stripe's
     trailing skip run) packs into ≤ ``UNIT_WORDS`` 32-bit words with a
     masked shift-and-sum contraction;
  4. units globalize into the per-stripe bitstream with the analytic
     cumsum-difference trick (no searchsorted), and stripes compact
     back-to-back at word granularity with a (t_bits, base, overflow)
     head so the host fetches ONE buffer.

The payload is the P slice *after* the slice header: the host prepends
the (qp, frame_num)-dependent header bits, appends rbsp_trailing, and
runs emulation-prevention escaping — O(bytes) vectorized glue, no per-MB
work.  Output is bit-exact with native/cavlc.cpp; overflow stripes
(|level| beyond the 28-bit escape, a unit past UNIT_WORDS, or a stripe
past ``max_stripe_bytes``) are flagged and fall back to the exact flat16
levels + host coder, exactly like the JPEG overflow tail.

IDR pictures keep the host coder: they are rare (connect/reset/PLI), use
per-MB slices, and their levels routinely exceed int8 anyway.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MB = 16

#: 32-bit words per packed unit (512 bits).  The worst *legal* residual
#: block is ~476 bits (16 escape-coded levels + coeff_token + signs); a
#: MB header unit is ≤ ~90 bits.  Anything larger flags overflow.
UNIT_WORDS = 16

#: fixed per-stripe head: t_bits u32 LE, base_words u32 LE, damage, ovf,
#: 2 pad bytes
HEAD_BYTES = 12

# ---------------------------------------------------------------------------
# VLC tables (transcribed from native/cavlc.cpp — ITU-T H.264 §9.2)

_COEFF_TOKEN_LEN = np.array([
    [1, 0, 0, 0, 6, 2, 0, 0, 8, 6, 3, 0, 9, 8, 7, 5,
     10, 9, 8, 6, 11, 10, 9, 7, 13, 11, 10, 8, 13, 13, 11, 9,
     13, 13, 13, 10, 14, 14, 13, 11, 14, 14, 14, 13, 15, 15, 14, 14,
     15, 15, 15, 14, 16, 15, 15, 15, 16, 16, 16, 15, 16, 16, 16, 16,
     16, 16, 16, 16],
    [2, 0, 0, 0, 6, 2, 0, 0, 6, 5, 3, 0, 7, 6, 6, 4,
     8, 6, 6, 4, 8, 7, 7, 5, 9, 8, 8, 6, 11, 9, 9, 6,
     11, 11, 11, 7, 12, 11, 11, 9, 12, 12, 12, 11, 12, 12, 12, 11,
     13, 13, 13, 12, 13, 13, 13, 13, 13, 14, 13, 13, 14, 14, 14, 13,
     14, 14, 14, 14],
    [4, 0, 0, 0, 6, 4, 0, 0, 6, 5, 4, 0, 6, 5, 5, 4,
     7, 5, 5, 4, 7, 5, 5, 4, 7, 6, 6, 4, 7, 6, 6, 4,
     8, 7, 7, 5, 8, 8, 7, 6, 9, 8, 8, 7, 9, 9, 8, 8,
     9, 9, 9, 8, 10, 9, 9, 9, 10, 10, 10, 10, 10, 10, 10, 10,
     10, 10, 10, 10],
], np.int64)

_COEFF_TOKEN_BITS = np.array([
    [1, 0, 0, 0, 5, 1, 0, 0, 7, 4, 1, 0, 7, 6, 5, 3,
     7, 6, 5, 3, 7, 6, 5, 4, 15, 6, 5, 4, 11, 14, 5, 4,
     8, 10, 13, 4, 15, 14, 9, 4, 11, 10, 13, 12, 15, 14, 9, 12,
     11, 10, 13, 8, 15, 1, 9, 12, 11, 14, 13, 8, 7, 10, 9, 12,
     4, 6, 5, 8],
    [3, 0, 0, 0, 11, 2, 0, 0, 7, 7, 3, 0, 7, 10, 9, 5,
     7, 6, 5, 4, 4, 6, 5, 6, 7, 6, 5, 8, 15, 6, 5, 4,
     11, 14, 13, 4, 15, 10, 9, 4, 11, 14, 13, 12, 8, 10, 9, 8,
     15, 14, 13, 12, 11, 10, 9, 12, 7, 11, 6, 8, 9, 8, 10, 1,
     7, 6, 5, 4],
    [15, 0, 0, 0, 15, 14, 0, 0, 11, 15, 13, 0, 8, 12, 14, 12,
     15, 10, 11, 11, 11, 8, 9, 10, 9, 14, 13, 9, 8, 10, 9, 8,
     15, 14, 13, 13, 11, 14, 10, 12, 15, 10, 13, 12, 11, 14, 9, 12,
     8, 10, 13, 8, 13, 7, 9, 12, 9, 12, 11, 10, 5, 8, 7, 6,
     1, 4, 3, 2],
], np.int64)

_COEFF_TOKEN_CDC_LEN = np.array(
    [2, 0, 0, 0, 6, 1, 0, 0, 6, 6, 3, 0, 6, 7, 7, 6, 6, 8, 8, 7],
    np.int64)
_COEFF_TOKEN_CDC_BITS = np.array(
    [1, 0, 0, 0, 7, 1, 0, 0, 4, 6, 1, 0, 3, 3, 2, 5, 2, 3, 2, 0],
    np.int64)

_TOTAL_ZEROS_LEN = [
    [0],
    [1, 3, 3, 4, 4, 5, 5, 6, 6, 7, 7, 8, 8, 9, 9, 9],
    [3, 3, 3, 3, 3, 4, 4, 4, 4, 5, 5, 6, 6, 6, 6],
    [4, 3, 3, 3, 4, 4, 3, 3, 4, 5, 5, 6, 5, 6],
    [5, 3, 4, 4, 3, 3, 3, 4, 3, 4, 5, 5, 5],
    [4, 4, 4, 3, 3, 3, 3, 3, 4, 5, 4, 5],
    [6, 5, 3, 3, 3, 3, 3, 3, 4, 3, 6],
    [6, 5, 3, 3, 3, 2, 3, 4, 3, 6],
    [6, 4, 5, 3, 2, 2, 3, 3, 6],
    [6, 6, 4, 2, 2, 3, 2, 5],
    [5, 5, 3, 2, 2, 2, 4],
    [4, 4, 3, 3, 1, 3],
    [4, 4, 2, 1, 3],
    [3, 3, 1, 2],
    [2, 2, 1],
    [1, 1],
]
_TOTAL_ZEROS_BITS = [
    [0],
    [1, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 3, 2, 1],
    [7, 6, 5, 4, 3, 5, 4, 3, 2, 3, 2, 3, 2, 1, 0],
    [5, 7, 6, 5, 4, 3, 4, 3, 2, 3, 2, 1, 1, 0],
    [3, 7, 5, 4, 6, 5, 4, 3, 3, 2, 2, 1, 0],
    [5, 4, 3, 7, 6, 5, 4, 3, 2, 1, 1, 0],
    [1, 1, 7, 6, 5, 4, 3, 2, 1, 1, 0],
    [1, 1, 5, 4, 3, 3, 2, 1, 1, 0],
    [1, 1, 1, 3, 3, 2, 2, 1, 0],
    [1, 0, 1, 3, 2, 1, 1, 1],
    [1, 0, 1, 3, 2, 1, 1],
    [0, 1, 1, 2, 1, 3],
    [0, 1, 1, 1, 1],
    [0, 1, 1, 1],
    [0, 1, 1],
    [0, 1],
]

_TZ_CDC_LEN = [[0], [1, 2, 3, 3], [1, 2, 2, 0], [1, 1, 0, 0]]
_TZ_CDC_BITS = [[0], [1, 1, 1, 0], [1, 1, 0, 0], [1, 0, 0, 0]]

_RUN_BEFORE_LEN = [
    [0],
    [1, 1],
    [1, 2, 2],
    [2, 2, 2, 2],
    [2, 2, 2, 3, 3],
    [2, 2, 3, 3, 3, 3],
    [2, 3, 3, 3, 3, 3, 3],
    [3, 3, 3, 3, 3, 3, 3, 4, 5, 6, 7, 8, 9, 10, 11],
]
_RUN_BEFORE_BITS = [
    [0],
    [1, 0],
    [1, 1, 0],
    [3, 2, 1, 0],
    [3, 2, 1, 1, 0],
    [3, 2, 3, 2, 1, 0],
    [3, 0, 1, 3, 2, 5, 4],
    [7, 6, 5, 4, 3, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1],
]

#: coded_block_pattern me(v) mapping for Inter prediction (Table 9-4)
_CBP_INTER_BY_CODENUM = np.array([
    0, 16, 1, 2, 4, 8, 32, 3, 5, 10, 12, 15, 47, 7, 11, 13,
    14, 6, 9, 31, 35, 37, 42, 44, 33, 34, 36, 40, 39, 43, 45, 46,
    17, 18, 20, 24, 19, 21, 26, 28, 23, 27, 29, 30, 22, 25, 38, 41],
    np.int64)
_CBP_INTER_CODENUM = np.zeros(48, np.int32)
_CBP_INTER_CODENUM[_CBP_INTER_BY_CODENUM] = np.arange(48)

_ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                    np.int32)

#: spec z-scan emission order of luma 4×4 blocks, as raster index r*4+c
_LUMA_SCAN = np.array([0, 1, 4, 5, 2, 3, 6, 7, 8, 9, 12, 13, 10, 11, 14, 15],
                      np.int32)

# packed (bits<<5 | len) LUT regions — one 1024-entry table, looked up
# via two one-hot matmuls (values < 2^21, exact in f32 at HIGHEST)
_TOK_BASE = 0           # 3 × 68 coeff_token classes
_TOKC_BASE = 204        # 20 chroma-DC coeff_token
_TZ_BASE = 224          # 16 × 16 total_zeros
_TZC_BASE = 480         # 4 × 4 chroma-DC total_zeros
_RB_BASE = 496          # 8 × 15 run_before


@functools.lru_cache(maxsize=1)
def _packed_lut() -> np.ndarray:
    lut = np.zeros(1024, np.float32)

    def put(base, i, bits, length):
        lut[base + i] = (int(bits) << 5) | int(length)

    for cls in range(3):
        for i in range(68):
            put(_TOK_BASE + cls * 68, i, _COEFF_TOKEN_BITS[cls][i],
                _COEFF_TOKEN_LEN[cls][i])
    for i in range(20):
        put(_TOKC_BASE, i, _COEFF_TOKEN_CDC_BITS[i], _COEFF_TOKEN_CDC_LEN[i])
    for t in range(16):
        row_l, row_b = _TOTAL_ZEROS_LEN[t], _TOTAL_ZEROS_BITS[t]
        for tz in range(len(row_l)):
            put(_TZ_BASE + t * 16, tz, row_b[tz], row_l[tz])
    for t in range(4):
        row_l, row_b = _TZ_CDC_LEN[t], _TZ_CDC_BITS[t]
        for tz in range(len(row_l)):
            put(_TZC_BASE + t * 4, tz, row_b[tz], row_l[tz])
    for zl in range(8):
        row_l, row_b = _RUN_BEFORE_LEN[zl], _RUN_BEFORE_BITS[zl]
        for run in range(len(row_l)):
            put(_RB_BASE + zl * 15, run, row_b[run], row_l[run])
    return lut


def _lut1024(idx):
    """packed = table[idx] for idx ∈ [0, 1024) via one-hot matmuls.

    Same rationale (and the same Precision.HIGHEST requirement) as
    device_entropy._lut512: TPU scalar-core gathers cost ~10 ns/element,
    and the MXU's default f32 path rounds operands to bf16."""
    table = _packed_lut().reshape(32, 32)
    hi = idx >> 5
    lo = idx & 31
    rows = jnp.dot(jax.nn.one_hot(hi, 32, dtype=jnp.float32),
                   jnp.asarray(table),
                   precision=jax.lax.Precision.HIGHEST)
    picked = (rows * jax.nn.one_hot(lo, 32, dtype=jnp.float32)).sum(-1)
    return picked.astype(jnp.int32)


# ---------------------------------------------------------------------------
# exp-Golomb on device


def _ue_dev(v):
    """ue(v) → (bits u32, len i32); exact for v < 2^16 - 1."""
    vp1 = (v + 1).astype(jnp.int32)
    nb = jnp.zeros_like(vp1)
    for b in range(1, 17):       # integer bit_length-1, no float log2
        nb = nb + (vp1 >= (1 << b)).astype(jnp.int32)
    return vp1.astype(jnp.uint32), 2 * nb + 1


def _se_dev(v):
    m = jnp.where(v <= 0, -2 * v, 2 * v - 1)
    return _ue_dev(m)


# ---------------------------------------------------------------------------
# residual_block CAVLC symbols (§9.2), vectorized over blocks


def _code_blocks(scan, nC, n_coeff: int, chroma_dc: bool):
    """CAVLC symbols for B residual blocks.

    scan: [B, n_coeff] int32 coefficients in scan order; nC: [B] int32
    (ignored for chroma DC).  Returns (bits [B, NS] u32, lens [B, NS]
    i32, ovf [B] bool) with NS = 2*n_coeff + 2 slots laid out as
    [coeff_token, t1-signs, level_0.._{n-1} (reverse order),
    total_zeros, run_before_0.._{n-2}].  Lens include the token even for
    total == 0; callers gate whole blocks (cbp / skip) by zeroing lens.
    """
    B = scan.shape[0]
    K = n_coeff
    nz = scan != 0
    t = nz.sum(-1).astype(jnp.int32)

    # k-th nonzero from the END (reverse scan order) via suffix ranks
    suf = jnp.cumsum(nz[:, ::-1].astype(jnp.int32), axis=1)[:, ::-1]
    kk = jnp.arange(K, dtype=jnp.int32)
    sel = nz[:, :, None] & ((suf - 1)[:, :, None] == kk[None, None, :])
    vals_rev = (scan[:, :, None] * sel).sum(1).astype(jnp.int32)
    pos_rev = (jnp.arange(K, dtype=jnp.int32)[None, :, None] * sel).sum(1)

    # trailing ones: leading run of |v|==1 in rev order, capped at 3
    isone = jnp.abs(vals_rev) == 1
    lead = jnp.cumprod(isone.astype(jnp.int32), axis=1)
    t1 = lead[:, :min(3, K)].sum(1)

    # ---- coeff_token ------------------------------------------------------
    tok_idx = t * 4 + t1
    if chroma_dc:
        packed = _lut1024(_TOKC_BASE + tok_idx)
        token_bits = (packed >> 5).astype(jnp.uint32)
        token_len = packed & 31
    else:
        cls = jnp.where(nC < 2, 0, jnp.where(nC < 4, 1, 2))
        packed = _lut1024(_TOK_BASE + cls * 68 + tok_idx)
        flc = jnp.where(t == 0, 3, ((t - 1) << 2) | t1)
        token_bits = jnp.where(nC >= 8, flc,
                               packed >> 5).astype(jnp.uint32)
        token_len = jnp.where(nC >= 8, 6, packed & 31)

    # ---- trailing-one signs (one slot, MSB-first emission order) ----------
    within = kk[None, :] < t1[:, None]
    sign = ((vals_rev < 0) & within).astype(jnp.uint32)
    shift = jnp.clip(t1[:, None] - 1 - kk[None, :], 0, 31).astype(jnp.uint32)
    sign_bits = (sign << shift).sum(1).astype(jnp.uint32)

    # ---- levels (reverse order, sequential suffix_length over ≤K steps) ---
    sl = jnp.where((t > 10) & (t1 < 3), 1, 0).astype(jnp.int32)
    lvl_bits: List = []
    lvl_lens: List = []
    ovf = jnp.zeros((B,), bool)
    for k in range(K):
        v = vals_rev[:, k]
        mag = jnp.abs(v)
        lc = 2 * (mag - 1) + (v < 0).astype(jnp.int32)
        lc = lc - jnp.where((t1 == k) & (t1 < 3), 2, 0)
        emit = (kk[k] >= t1) & (k < t)

        # suffix_length == 0 encoding
        esc0 = lc >= 30
        b0 = jnp.where(lc < 14, 1,
                       jnp.where(~esc0, (1 << 4) | (lc - 14),
                                 (1 << 12) | ((lc - 30) & 0xFFF)))
        l0 = jnp.where(lc < 14, lc + 1, jnp.where(~esc0, 19, 28))
        o0 = lc >= 30 + 4096
        # suffix_length > 0 encoding
        th = 15 << sl
        esc1 = lc >= th
        b1 = jnp.where(~esc1, (1 << sl) | (lc & ((1 << sl) - 1)),
                       (1 << 12) | ((lc - th) & 0xFFF))
        l1 = jnp.where(~esc1, (lc >> sl) + 1 + sl, 28)
        o1 = lc >= th + 4096

        zero_sl = sl == 0
        bits_k = jnp.where(zero_sl, b0, b1)
        len_k = jnp.where(zero_sl, l0, l1)
        ovf = ovf | (emit & jnp.where(zero_sl, o0, o1))
        lvl_bits.append(jnp.where(emit, bits_k, 0).astype(jnp.uint32))
        lvl_lens.append(jnp.where(emit, len_k, 0))

        new_sl = jnp.maximum(sl, 1)
        new_sl = new_sl + ((mag > (3 << (new_sl - 1)))
                           & (new_sl < 6)).astype(jnp.int32)
        sl = jnp.where(emit, new_sl, sl)

    # ---- total_zeros ------------------------------------------------------
    tz = pos_rev[:, 0] + 1 - t
    max_coeff = 4 if chroma_dc else n_coeff
    emit_tz = (t > 0) & (t < max_coeff)
    if chroma_dc:
        tzi = _TZC_BASE + jnp.clip(t, 0, 3) * 4 + jnp.clip(tz, 0, 3)
    else:
        tzi = _TZ_BASE + jnp.clip(t, 0, 15) * 16 + jnp.clip(tz, 0, 15)
    packed = _lut1024(tzi)
    tz_bits = jnp.where(emit_tz, packed >> 5, 0).astype(jnp.uint32)
    tz_len = jnp.where(emit_tz, packed & 31, 0)

    # ---- run_before (reverse order; zeros_left_i = p_i - i closed form) ---
    rb_bits: List = []
    rb_lens: List = []
    for k in range(K - 1):
        zeros_left = pos_rev[:, k] - (t - 1 - k)
        run = pos_rev[:, k] - pos_rev[:, k + 1] - 1
        emit = (k <= t - 2) & (zeros_left > 0)
        zl = jnp.clip(zeros_left, 0, 7)
        packed = _lut1024(_RB_BASE + zl * 15 + jnp.clip(run, 0, 14))
        rb_bits.append(jnp.where(emit, packed >> 5, 0).astype(jnp.uint32))
        rb_lens.append(jnp.where(emit, packed & 31, 0))

    bits = jnp.stack(
        [token_bits, sign_bits] + lvl_bits + [tz_bits] + rb_bits, axis=1)
    lens = jnp.stack(
        [token_len, t1] + lvl_lens + [tz_len] + rb_lens, axis=1)
    return bits, lens.astype(jnp.int32), ovf


# ---------------------------------------------------------------------------
# unit pack + stripe globalization (device_entropy.py's word machinery)


def _pack_units(bits, lens, W: int):
    """[U, SLOTS] slot grids → ([U, W] u32 words MSB-first, Lb [U], ovf)."""
    cum = jnp.cumsum(lens, axis=1)
    off = cum - lens
    Lb = cum[:, -1]
    unit_ovf = Lb > 32 * W

    j0 = jnp.minimum(off >> 5, W - 1)
    pos = off & 31
    sh = 32 - pos - lens
    safe = jnp.where(lens > 0, bits, 0).astype(jnp.uint32)
    c0 = jnp.where(
        sh >= 0,
        safe << jnp.clip(sh, 0, 31).astype(jnp.uint32),
        safe >> jnp.clip(-sh, 0, 31).astype(jnp.uint32)).astype(jnp.uint32)
    c1 = jnp.where(
        sh < 0, safe << jnp.clip(32 + sh, 0, 31).astype(jnp.uint32),
        jnp.uint32(0)).astype(jnp.uint32)
    j1 = jnp.minimum(j0 + 1, W - 1)

    wk = jnp.arange(W, dtype=jnp.int32)[None, None, :]
    words = (jnp.where(j0[..., None] == wk, c0[..., None], 0)
             + jnp.where(j1[..., None] == wk, c1[..., None], 0)
             ).sum(axis=1, dtype=jnp.uint32)
    return words, Lb.astype(jnp.int32), unit_ovf


def _globalize(words_unit, Lb, V: int):
    """Concatenate each stripe's units into its bitstream words.

    words_unit: [S, U, W] u32; Lb: [S, U] i32 bit lengths (0 = empty
    unit).  Returns (words_stripe [S, V] u32, t_bits [S] i32).  Same
    analytic boundary construction as device_entropy (empty units are
    safe: a non-boundary unit never has bits past the word its successor
    starts in)."""
    S, U, W = words_unit.shape
    cumb = jnp.cumsum(Lb, axis=1)
    base = cumb - Lb
    t_bits = cumb[:, -1]

    g0 = base >> 5
    r = base & 31
    e = (base + Lb - 1) >> 5

    r3 = r[..., None]
    u0 = words_unit >> r3.astype(jnp.uint32)
    u1 = jnp.where(r3 == 0, jnp.uint32(0),
                   words_unit << (32 - r3).astype(jnp.uint32))
    cs0 = jnp.cumsum(u0.reshape(S, U * W), axis=1, dtype=jnp.uint32)
    cs1 = jnp.cumsum(u1.reshape(S, U * W), axis=1, dtype=jnp.uint32)

    g0c = jnp.clip(g0, 0, V - 1)
    srows = jnp.arange(S, dtype=jnp.int32)[:, None]
    bidx = jnp.arange(U, dtype=jnp.int32)[None, :]
    lastblk = jnp.zeros((S, V), jnp.int32).at[srows, g0c].max(bidx)
    lastblk = jax.lax.associative_scan(jnp.maximum, lastblk, axis=1)

    ge = (jnp.clip(g0, 0, (1 << 15) - 1) << 16) | (
        jnp.clip(e + 1, 0, (1 << 15) - 1))
    ge_b = jnp.take_along_axis(ge, lastblk, axis=1)
    g0b = ge_b >> 16
    e1b = ge_b & 0xFFFF
    w_ar = jnp.arange(V, dtype=jnp.int32)[None, :]

    jstar = jnp.where(e1b <= w_ar, W - 1,
                      jnp.minimum(w_ar - g0b, W - 1))
    s_at0 = jnp.take_along_axis(cs0, lastblk * W + jstar, axis=1)
    word0 = s_at0 - jnp.concatenate(
        [jnp.zeros((S, 1), jnp.uint32), s_at0[:, :-1]], axis=1)

    lastblk1 = jnp.concatenate(
        [jnp.zeros((S, 1), jnp.int32), lastblk[:, :-1]], axis=1)
    ge_b1 = jnp.take_along_axis(ge, lastblk1, axis=1)
    g0b1 = ge_b1 >> 16
    e1b1 = ge_b1 & 0xFFFF
    jstar1 = jnp.where(e1b1 + 1 <= w_ar, W - 1,
                       jnp.clip(w_ar - 1 - g0b1, 0, W - 1))
    s_at1 = jnp.take_along_axis(cs1, lastblk1 * W + jstar1, axis=1)
    s_at1 = jnp.where(w_ar == 0, 0, s_at1)
    word1 = s_at1 - jnp.concatenate(
        [jnp.zeros((S, 1), jnp.uint32), s_at1[:, :-1]], axis=1)

    return word0 + word1, t_bits


# ---------------------------------------------------------------------------
# P-slice payload pack (the tentpole entry point)


def default_max_stripe_bytes(mb_w: int, mb_h: int) -> int:
    """Per-stripe payload capacity: 256 B/MB of headroom (streaming QPs
    measure ~27 B/MB mean, paint-over ~4x that), pow2, ≥ 16 KB."""
    n = 16384
    while n < 256 * mb_w * mb_h:
        n <<= 1
    return n


def pack_p_frame_words(mv, luma, chroma_dc, chroma_ac, update, *,
                       mb_w: int, mb_h: int, max_stripe_bytes: int):
    """Device CAVLC over one P frame's level tensors.

    mv [S, n, 2] (dy, dx) int; luma [S, n, 16, 4, 4] (raster 4×4 grid);
    chroma_dc [S, n, 2, 2, 2]; chroma_ac [S, n, 2, 4, 4, 4] (position 0
    zeroed); update [S] bool — stripes outside the mask pack nothing.

    Returns (words [cap_words] u32 — per-stripe P-slice payloads (post
    slice header, MSB-first) compacted back-to-back word-aligned;
    t_bits [S] i32; base_words [S] i32; overflow [S] bool).
    """
    S = mv.shape[0]
    n = mb_w * mb_h
    V = max_stripe_bytes // 4
    W = UNIT_WORDS
    cap_words = S * V

    mv = mv.astype(jnp.int32)
    luma = luma.astype(jnp.int32)
    chroma_dc = chroma_dc.astype(jnp.int32)
    chroma_ac = chroma_ac.astype(jnp.int32)
    upd = update.astype(bool)

    # ---- per-block totalCoeff and cbp ------------------------------------
    lt = (luma != 0).sum((-1, -2)).astype(jnp.int32)         # [S, n, 16]
    cact = (chroma_ac != 0).sum((-1, -2)).astype(jnp.int32)  # [S, n, 2, 4]
    cdct = (chroma_dc != 0).sum((-1, -2)).astype(jnp.int32)  # [S, n, 2]

    nz88 = (lt > 0).reshape(S, n, 2, 2, 2, 2).any(axis=(3, 5))  # [S,n,2,2]
    w88 = jnp.asarray([[1, 2], [4, 8]], jnp.int32)
    cbp_luma = (nz88 * w88[None, None]).sum((-1, -2))
    has_cac = (cact > 0).any((-1, -2))
    has_cdc = (cdct > 0).any(-1)
    cbp_chroma = jnp.where(has_cac, 2, jnp.where(has_cdc, 1, 0))
    cbp = cbp_luma | (cbp_chroma << 4)
    any_coeff = cbp > 0                                      # [S, n]

    # ---- MV prediction, skip decision, mvd (§8.4.1) ----------------------
    mvg = mv.reshape(S, mb_h, mb_w, 2)
    zpad = functools.partial(jnp.pad, mode="constant")
    a = zpad(mvg, ((0, 0), (0, 0), (1, 0), (0, 0)))[:, :, :-1]   # left
    b = zpad(mvg, ((0, 0), (1, 0), (0, 0), (0, 0)))[:, :-1]      # top
    c_tr = zpad(mvg, ((0, 0), (1, 0), (0, 1), (0, 0)))[:, :-1, 1:]
    d_tl = zpad(mvg, ((0, 0), (1, 0), (1, 0), (0, 0)))[:, :-1, :-1]
    col = jnp.arange(mb_w, dtype=jnp.int32)[None, None, :]
    row = jnp.arange(mb_h, dtype=jnp.int32)[None, :, None]
    a_av = col > 0
    b_av = row > 0
    ctr_av = (row > 0) & (col + 1 < mb_w)
    d_av = (row > 0) & (col > 0)
    c = jnp.where(ctr_av[..., None], c_tr,
                  jnp.where(d_av[..., None], d_tl, 0))
    c_av = ctr_av | d_av

    med = jnp.maximum(jnp.minimum(a, b),
                      jnp.minimum(jnp.maximum(a, b), c))
    only_a = a_av & ~b_av & ~c_av
    pred = jnp.where(only_a[..., None], a, med)              # [S,mh,mw,2]

    a_zero = (a == 0).all(-1)
    b_zero = (b == 0).all(-1)
    skip_mv = jnp.where((~a_av | ~b_av | a_zero | b_zero)[..., None],
                        0, pred)
    anyc_g = any_coeff.reshape(S, mb_h, mb_w)
    skip = ~anyc_g & (mvg == skip_mv).all(-1)
    coded = (~skip).reshape(S, n)

    mvd = ((mvg - pred) * 4).reshape(S, n, 2)                # qpel

    # ---- mb_skip_run + trailing run (prefix-max over raster order) -------
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]
    masked = jnp.where(coded, idx, -1)
    run_max = jax.lax.associative_scan(jnp.maximum, masked, axis=1)
    prev_coded = jnp.concatenate(
        [jnp.full((S, 1), -1, jnp.int32), run_max[:, :-1]], axis=1)
    skip_run = idx - prev_coded - 1
    tail_run = n - 1 - run_max[:, -1]                        # [S]

    # ---- header unit slots [S, n, 6] -------------------------------------
    sr_b, sr_l = _ue_dev(skip_run)
    mx_b, mx_l = _se_dev(mvd[..., 1])                        # x first
    my_b, my_l = _se_dev(mvd[..., 0])
    cn = jnp.take(jnp.asarray(_CBP_INTER_CODENUM), cbp)
    cb_b, cb_l = _ue_dev(cn)
    one_u32 = jnp.ones_like(sr_b)
    hdr_bits = jnp.stack(
        [sr_b, one_u32, mx_b, my_b, cb_b, one_u32], axis=-1)
    hdr_lens = jnp.stack(
        [sr_l, jnp.ones_like(sr_l), mx_l, my_l, cb_l,
         any_coeff.astype(jnp.int32)], axis=-1)
    gate_mb = (coded & upd[:, None]).astype(jnp.int32)
    hdr_lens = hdr_lens * gate_mb[..., None]

    # ---- nC grids (neighbor totalCoeff; -1 = unavailable) ----------------
    def _nc_from_grid(grid):
        left = jnp.pad(grid, ((0, 0), (0, 0), (1, 0)),
                       constant_values=-1)[:, :, :-1]
        top = jnp.pad(grid, ((0, 0), (1, 0), (0, 0)),
                      constant_values=-1)[:, :-1]
        both = (left >= 0) & (top >= 0)
        return jnp.where(both, (left + top + 1) >> 1,
                         jnp.where(left >= 0, left,
                                   jnp.where(top >= 0, top, 0)))

    lgrid = lt.reshape(S, mb_h, mb_w, 4, 4).transpose(0, 1, 3, 2, 4) \
        .reshape(S, mb_h * 4, mb_w * 4)
    nc_l = _nc_from_grid(lgrid).reshape(S, mb_h, 4, mb_w, 4) \
        .transpose(0, 1, 3, 2, 4).reshape(S, n, 16)

    def _nc_chroma(totals):                                  # [S, n, 4]
        grid = totals.reshape(S, mb_h, mb_w, 2, 2) \
            .transpose(0, 1, 3, 2, 4).reshape(S, mb_h * 2, mb_w * 2)
        return _nc_from_grid(grid).reshape(S, mb_h, 2, mb_w, 2) \
            .transpose(0, 1, 3, 2, 4).reshape(S, n, 4)

    nc_cb = _nc_chroma(cact[:, :, 0])
    nc_cr = _nc_chroma(cact[:, :, 1])

    # ---- residual units ---------------------------------------------------
    zz = jnp.asarray(_ZIGZAG4)
    lscan = luma.reshape(S, n, 16, 16)[..., zz]              # [S,n,16,16]
    lu_bits, lu_lens, lu_ovf = _code_blocks(
        lscan.reshape(-1, 16), nc_l.reshape(-1), 16, False)
    NSL = 2 * 16 + 2
    lu_bits = lu_bits.reshape(S, n, 16, NSL)
    lu_lens = lu_lens.reshape(S, n, 16, NSL)
    b8 = jnp.asarray(
        [(r // 2) * 2 + (c // 2) for r in range(4) for c in range(4)],
        jnp.int32)
    lu_gate = ((cbp_luma[..., None] >> b8[None, None]) & 1) \
        * gate_mb[..., None]
    lu_lens = lu_lens * lu_gate[..., None]
    lu_ovf = (lu_ovf.reshape(S, n, 16) & (lu_gate > 0)).any((-1, -2))

    cdc_scan = chroma_dc.reshape(S, n, 2, 4)                 # raster = scan
    cd_bits, cd_lens, cd_ovf = _code_blocks(
        cdc_scan.reshape(-1, 4), None, 4, True)
    NSC = 2 * 4 + 2
    cd_bits = cd_bits.reshape(S, n, 2, NSC)
    cd_lens = cd_lens.reshape(S, n, 2, NSC)
    cd_gate = (cbp_chroma >= 1).astype(jnp.int32) * gate_mb
    cd_lens = cd_lens * cd_gate[..., None, None]
    cd_ovf = (cd_ovf.reshape(S, n, 2) & (cd_gate > 0)[..., None]) \
        .any((-1, -2))

    cac_scan = chroma_ac.reshape(S, n, 2, 4, 16)[..., zz[1:]]  # [S,n,2,4,15]
    nc_c = jnp.stack([nc_cb, nc_cr], axis=2)                 # [S, n, 2, 4]
    ca_bits, ca_lens, ca_ovf = _code_blocks(
        cac_scan.reshape(-1, 15), nc_c.reshape(-1), 15, False)
    NSA = 2 * 15 + 2
    ca_bits = ca_bits.reshape(S, n, 8, NSA)
    ca_lens = ca_lens.reshape(S, n, 8, NSA)
    ca_gate = (cbp_chroma == 2).astype(jnp.int32) * gate_mb
    ca_lens = ca_lens * ca_gate[..., None, None]
    ca_ovf = (ca_ovf.reshape(S, n, 8) & (ca_gate > 0)[..., None]) \
        .any((-1, -2))

    # ---- unit sequence: [hdr, luma×16 (z-scan), cdc×2, cac×8] per MB -----
    SLOT = NSL                                               # 34 = max

    def padslots(x, ns):
        return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, SLOT - ns)))

    lscan_order = jnp.asarray(_LUMA_SCAN)
    u_bits = jnp.concatenate([
        padslots(hdr_bits[:, :, None, :], 6),
        lu_bits[:, :, lscan_order],
        padslots(cd_bits, NSC),
        padslots(ca_bits, NSA),
    ], axis=2)                                               # [S, n, 27, SLOT]
    u_lens = jnp.concatenate([
        padslots(hdr_lens[:, :, None, :], 6),
        lu_lens[:, :, lscan_order],
        padslots(cd_lens, NSC),
        padslots(ca_lens, NSA),
    ], axis=2)

    tr_b, tr_l = _ue_dev(tail_run)
    tail_bits = jnp.zeros((S, 1, SLOT), jnp.uint32) \
        .at[:, 0, 0].set(tr_b)
    tail_lens = jnp.zeros((S, 1, SLOT), jnp.int32).at[:, 0, 0].set(
        tr_l * (tail_run > 0).astype(jnp.int32)
        * upd.astype(jnp.int32))

    U = n * 27 + 1
    all_bits = jnp.concatenate(
        [u_bits.reshape(S, n * 27, SLOT), tail_bits], axis=1)
    all_lens = jnp.concatenate(
        [u_lens.reshape(S, n * 27, SLOT), tail_lens], axis=1)

    # ---- pack + globalize + compact --------------------------------------
    words_u, Lb, unit_ovf = _pack_units(
        all_bits.reshape(S * U, SLOT), all_lens.reshape(S * U, SLOT), W)
    words_stripe, t_bits = _globalize(
        words_u.reshape(S, U, W), Lb.reshape(S, U), V)

    wc = jnp.minimum((t_bits + 31) // 32, V)
    base_words = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(wc)[:-1].astype(jnp.int32)])
    j = jnp.arange(cap_words, dtype=jnp.int32)
    sidx = jnp.clip(
        jnp.searchsorted(base_words, j, side="right") - 1, 0, S - 1)
    src = sidx * V + jnp.clip(j - base_words[sidx], 0, V - 1)
    valid = j < (base_words[-1] + wc[-1])
    words = jnp.where(valid, words_stripe.reshape(-1)[src], 0)

    # a slot may span at most 2 words (len ≤ 32); exp-Golomb header slots
    # are the only unbounded-by-table lengths and stay ≤ 31 bits for any
    # n_mb < 32767 — flag the stripe rather than corrupt if exceeded
    hdr_slot_ovf = (hdr_lens > 32).any((-1, -2))
    overflow = (lu_ovf | cd_ovf | ca_ovf | hdr_slot_ovf
                | (t_bits > 32 * V)
                | unit_ovf.reshape(S, U).any(-1)) & upd
    return words, t_bits, base_words, overflow


def pack_p_frame(mv, luma, chroma_dc, chroma_ac, damage, update, *,
                 mb_w: int, mb_h: int, max_stripe_bytes: int):
    """Fetchable uint8 buffer: [S, HEAD_BYTES] head + big-endian payload.

    Head per stripe: t_bits u32 LE, base_words u32 LE, damage u8,
    overflow u8, 2 pad bytes.  Payload: the compacted words serialized
    MSB-first (big-endian), so byte i of a stripe's payload carries its
    bits 8i..8i+7."""
    words, t_bits, base_words, overflow = pack_p_frame_words(
        mv, luma, chroma_dc, chroma_ac, update,
        mb_w=mb_w, mb_h=mb_h, max_stripe_bytes=max_stripe_bytes)
    S = t_bits.shape[0]

    def le4(x):
        x = x.astype(jnp.uint32)
        return jnp.stack([(x >> (8 * i)) & 0xFF for i in range(4)],
                         axis=1).astype(jnp.uint8)

    head = jnp.concatenate([
        le4(t_bits), le4(base_words),
        damage.astype(jnp.uint8)[:, None],
        overflow.astype(jnp.uint8)[:, None],
        jnp.zeros((S, 2), jnp.uint8),
    ], axis=1)
    payload = jnp.stack([
        (words >> 24) & 0xFF, (words >> 16) & 0xFF,
        (words >> 8) & 0xFF, words & 0xFF,
    ], axis=-1).astype(jnp.uint8).reshape(-1)
    return jnp.concatenate([head.reshape(-1), payload])


# ---------------------------------------------------------------------------
# host-side glue: slice header + payload + trailing + EP escape → NAL


def parse_cavlc_head(host: np.ndarray, n_stripes: int):
    """(t_bits, base_words, damage, ovf) from a fetched head prefix."""
    h = np.asarray(host[:HEAD_BYTES * n_stripes], np.uint8) \
        .reshape(n_stripes, HEAD_BYTES)
    w = (1 << (8 * np.arange(4, dtype=np.int64)))
    t_bits = (h[:, 0:4].astype(np.int64) * w).sum(1)
    base_words = (h[:, 4:8].astype(np.int64) * w).sum(1)
    return t_bits, base_words, h[:, 8] != 0, h[:, 9] != 0


def _p_slice_header_bits(qp: int, frame_num: int) -> List[int]:
    """Bit list for the P slice header native/cavlc.cpp writes
    (deblocking disabled, single slice, first_mb 0)."""
    bits: List[int] = []

    def u(v, nb):
        for i in range(nb - 1, -1, -1):
            bits.append((v >> i) & 1)

    def ue(v):
        vp1 = v + 1
        nb = vp1.bit_length() - 1
        u(0, nb)
        u(vp1, nb + 1)

    def se(v):
        ue(-2 * v if v <= 0 else 2 * v - 1)

    ue(0)                       # first_mb_in_slice
    ue(5)                       # slice_type: P (all)
    ue(0)                       # pps id
    u(frame_num & 0xF, 4)
    u(0, 1)                     # num_ref_idx_active_override
    u(0, 1)                     # ref_pic_list_modification_l0
    u(0, 1)                     # adaptive_ref_pic_marking
    se(qp - 26)                 # slice_qp_delta
    ue(1)                       # disable_deblocking_filter_idc
    return bits


def _ep_escape(rbsp: np.ndarray) -> bytes:
    """Emulation-prevention escaping with the sequential reset semantics
    (an accepted escape restarts the zero-run count), vectorized over
    the rare candidate positions."""
    a = np.asarray(rbsp, np.uint8)
    if len(a) < 3:
        return a.tobytes()
    z = a == 0
    cand = np.flatnonzero(z[:-2] & z[1:-1] & (a[2:] <= 3)) + 2
    if cand.size == 0:
        return a.tobytes()
    accepted = []
    last = -10
    for j in cand:
        if j == last + 1:       # inserted 0x03 reset the zero run
            continue
        accepted.append(j)
        last = j
    return np.insert(a, accepted, 3).tobytes()


def assemble_p_slice(payload: np.ndarray, nbits: int, qp: int,
                     frame_num: int) -> bytes:
    """One Annex-B P-slice NAL from a device-packed payload.

    payload: uint8 big-endian bit buffer (≥ ceil(nbits/8) bytes, bits
    past ``nbits`` zero).  Bit-exact with h264_encode_picture's P path.
    """
    hdr = _p_slice_header_bits(qp, frame_num)
    k = len(hdr)
    npay = (nbits + 7) // 8
    pb = np.asarray(payload[:npay], np.uint8)
    total_bits = k + nbits + 1                  # + rbsp stop bit
    nbytes = (total_bits + 7) // 8
    out = np.zeros(nbytes + 1, np.uint8)
    hb = np.packbits(np.asarray(hdr, np.uint8))
    out[:len(hb)] = hb
    base, s = k // 8, k % 8
    if s == 0:
        out[base:base + npay] = pb
    else:
        out[base:base + npay] |= pb >> s
        out[base + 1:base + 1 + npay] |= (
            (pb.astype(np.uint16) << (8 - s)) & 0xFF).astype(np.uint8)
    stop = k + nbits
    out[stop >> 3] |= 0x80 >> (stop & 7)
    return (b"\x00\x00\x00\x01" + bytes(((3 << 5) | 1,))
            + _ep_escape(out[:nbytes]))


def payload_slice(host: np.ndarray, n_stripes: int,
                  base_words: np.ndarray, t_bits: np.ndarray,
                  i: int) -> Tuple[np.ndarray, int]:
    """(payload bytes, nbits) for stripe ``i`` of a fetched buffer."""
    start = HEAD_BYTES * n_stripes + int(base_words[i]) * 4
    nbits = int(t_bits[i])
    return host[start:start + ((nbits + 31) // 32) * 4], nbits
