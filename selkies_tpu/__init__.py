"""selkies_tpu — a TPU-native remote-desktop streaming framework.

A brand-new framework with the capabilities of Selkies (skipperro/selkies-gstreamer):
low-latency X11 → HTML5 browser streaming, where the video-encode path is a
jit-compiled JAX/Pallas pipeline on TPU ("tpuenc") instead of NVENC/VA-API/x264.

Package layout:
  settings   — declarative flag/config system (reference: src/selkies/settings.py)
  protocol   — byte-exact wire protocol codec (reference: selkies-core.js:2720-2990)
  ops        — TPU compute primitives: color convert, blocked DCT, quantization
  encoder    — tpuenc: the jit encode pipelines (JPEG-stripe, H.264-class)
  models     — learned neural codec (flax) — flagship trainable model
  parallel   — device meshes, shardings, multi-session batching over ICI
  capture    — frame sources: synthetic (deterministic tests) and X11/XShm
  server     — asyncio WebSocket data/control server, backpressure, displays
  inputs     — keyboard/mouse/clipboard/gamepad injection plane
  audio      — Opus encode (ctypes libopus) and audio pipelines
  native     — C++ runtime components (entropy coder, ...) + build glue
"""

__version__ = "0.1.0"
