"""Per-stage frame tracing: capture → stage → encode → fetch → send.

The reference has no tracer (SURVEY §5 row 1: client-side FPS counting
only). Here every frame can carry a ring of stage timestamps so tail
latency is attributable: the dominant failure mode on accelerator-attached
encode (dispatch queuing vs. D2H vs. websocket backpressure) is invisible
to an end-to-end number.

Zero-dependency and allocation-light: a fixed ring of float arrays; when
jax profiling is wanted instead, wrap the block in
``jax.profiler.trace`` externally.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

STAGES = ("capture", "stage", "dispatch", "harvest", "send")


@dataclass
class StageSpan:
    frame_id: int
    stamps: Dict[str, float] = field(default_factory=dict)

    def mark(self, stage: str) -> None:
        self.stamps[stage] = time.monotonic()

    def duration_ms(self, a: str, b: str) -> Optional[float]:
        if a in self.stamps and b in self.stamps:
            return (self.stamps[b] - self.stamps[a]) * 1000.0
        return None

    @property
    def total_ms(self) -> Optional[float]:
        if not self.stamps:
            return None
        return (max(self.stamps.values()) - min(self.stamps.values())) * 1e3


class FrameTracer:
    """Ring buffer of recent frame spans + percentile summaries."""

    def __init__(self, capacity: int = 600):
        self.capacity = capacity
        self._ring: List[StageSpan] = []
        self._open: Dict[int, StageSpan] = {}

    def begin(self, frame_id: int) -> StageSpan:
        span = StageSpan(frame_id)
        span.mark("capture")
        self._open[frame_id] = span
        return span

    def mark(self, frame_id: int, stage: str) -> None:
        span = self._open.get(frame_id)
        if span is not None:
            span.mark(stage)

    def finish(self, frame_id: int) -> Optional[StageSpan]:
        span = self._open.pop(frame_id, None)
        if span is None:
            return None
        span.mark("send")
        self._ring.append(span)
        if len(self._ring) > self.capacity:
            self._ring = self._ring[-self.capacity:]
        return span

    def percentile_ms(self, a: str, b: str, pct: float = 50.0) -> Optional[float]:
        vals = sorted(
            d for s in self._ring
            if (d := s.duration_ms(a, b)) is not None)
        if not vals:
            return None
        idx = min(len(vals) - 1, int(len(vals) * pct / 100.0))
        return vals[idx]

    def summary(self) -> Dict[str, Optional[float]]:
        return {
            "p50_total_ms": self.percentile_ms("capture", "send", 50),
            "p95_total_ms": self.percentile_ms("capture", "send", 95),
            "p50_encode_ms": self.percentile_ms("dispatch", "harvest", 50),
            "frames": float(len(self._ring)),
        }
