"""Frame flight recorder: per-stage tracing from capture to client ACK.

The reference has no tracer (SURVEY §5 row 1: client-side FPS counting
only), so its end-to-end latency was never attributable — and neither
was ours: the async driver (docs/pipeline.md) hides the dispatch/fetch
round trip, but nothing proved *where* the remaining glass-to-glass
milliseconds lived. This module is the measurement substrate for that
question (ROADMAP item 1's "measured at the glass, not the chip"), and
the feedback channel items 2-3 (SFE, rate control) will read from.

Every served frame carries a :class:`FrameTrace` — a trace context of
(display/session id, wire frame id) threaded through the full path::

    capture -> stage -> dispatch -> fetch_wait -> pack -> queue -> send -> ack

Call sites mark stages with absolute monotonic intervals; the recorder
never reads the clock on the hot path. A span is *closed* exactly once,
with a terminal mark:

* ``acked``            — the client's CLIENT_FRAME_ACK landed (the ack
                         stage is true network RTT + client decode);
* ``empty``            — the frame encoded to zero emitted stripes
                         (damage gating; normal, not a loss);
* ``dropped@<stage>``  — the frame was lost at that stage (submit
                         backpressure, encoder error, send-queue
                         overflow, supervised restart, ...);
* ``expired@<stage>``  — no terminal event arrived within the expiry
                         window (e.g. a client that never ACKs).

Dropped and expired frames therefore NEVER leak an open span — the
open-span count is an invariant tools/chaos_run.py asserts to zero.

Concurrency: marks land from the event loop, the async-driver thread,
and mesh worker threads. The recorder is lock-free in the CPython
sense — the completed ring is a preallocated list written through a
single monotonically increasing index, and the open/awaiting tables are
plain dicts; every mutation is one GIL-atomic operation, so there are
no locks (and no possible lock-order inversions) anywhere on the frame
path.

Export surfaces:

* per-stage Prometheus histograms with a ``display`` label, plus
  ``glass_to_glass_ms`` / ``encode_only_ms`` (observability/metrics.py);
* Chrome trace-event JSON (Perfetto-loadable) of the last N seconds —
  served at ``/debug/trace`` and summarized by tools/trace_report.py;
* per-display stage summaries riding the ``system_health`` wire feed.

``FrameTracer``/``StageSpan`` below are the pre-recorder API, kept as a
compatibility shim (stamp-based spans; summaries over a list ring).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "STAGES", "FlightRecorder", "FrameTrace", "FrameTracer", "StageSpan",
]

#: the eight stages of a served frame's flight, in path order.
#:
#: capture     host wall time in ``source.next_frame()``
#: stage       H2D staging (donated ring copy / host batch stack)
#: dispatch    device program launch (not device compute)
#: fetch_wait  host time blocked materializing the D2H fetch
#: pack        host-side entropy glue / stripe assembly
#: queue       dwell in the owner's bounded send queue
#: send        transport send (websocket write)
#: ack         send completion -> CLIENT_FRAME_ACK (network RTT + decode)
STAGES = ("capture", "stage", "dispatch", "fetch_wait", "pack",
          "queue", "send", "ack")


class FrameTrace:
    """One frame's flight: (display, wire frame id) + stage intervals.

    ``spans`` maps stage name to an absolute ``(start, end)`` monotonic
    interval. Stages may overlap or be missing (a mesh session folds
    pack into fetch_wait; a host-rung frame has no device dispatch) —
    consumers read durations per stage, never assume contiguity.
    """

    __slots__ = ("display", "frame_id", "t0", "spans", "terminal",
                 "_token")

    def __init__(self, display: str, t0: float) -> None:
        self.display = display
        self.frame_id: int = -1        # wire id; assigned at pack time
        self.t0 = t0                   # span open (capture start)
        self.spans: Dict[str, Tuple[float, float]] = {}
        self.terminal: Optional[str] = None
        self._token: int = 0

    def mark(self, stage: str, t_start: float, t_end: float) -> None:
        """Record one stage's absolute interval (idempotent per stage:
        a re-mark overwrites, keeping one interval per stage)."""
        self.spans[stage] = (t_start, t_end)

    def merge(self, intervals: Optional[Dict[str, Tuple[float, float]]]
              ) -> None:
        """Fold in the encoder-side intervals harvested with the frame
        (the pipelines report stage/dispatch/fetch_wait/pack)."""
        if intervals:
            self.spans.update(intervals)

    def duration_ms(self, stage: str) -> Optional[float]:
        iv = self.spans.get(stage)
        if iv is None:
            return None
        return (iv[1] - iv[0]) * 1000.0

    @property
    def t_end(self) -> float:
        """Latest marked instant (== close time for terminal spans)."""
        if not self.spans:
            return self.t0
        return max(iv[1] for iv in self.spans.values())

    @property
    def total_ms(self) -> float:
        """Open -> latest mark. For acked spans this is glass-to-glass."""
        return (self.t_end - self.t0) * 1000.0

    @property
    def encode_only_ms(self) -> Optional[float]:
        """Submit -> stripes host-packed: the ROADMAP item 1 criterion
        (compare against ``h264_device_ms_per_frame``). Elapsed wall
        between the first encoder-side stage start and the pack end —
        queueing inside the async driver counts, because the glass does
        not care which thread was slow."""
        starts = [self.spans[s][0] for s in ("stage", "dispatch")
                  if s in self.spans]
        end = self.spans.get("pack") or self.spans.get("fetch_wait")
        if not starts or end is None:
            return None
        return max(0.0, (end[1] - min(starts)) * 1000.0)

    @property
    def last_stage(self) -> str:
        """The stage whose interval ends latest ('open' when none)."""
        if not self.spans:
            return "open"
        return max(self.spans.items(), key=lambda kv: kv[1][1])[0]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "display": self.display,
            "frame_id": self.frame_id,
            "terminal": self.terminal,
            "total_ms": round(self.total_ms, 3),
            "stages": {s: round((iv[1] - iv[0]) * 1000.0, 3)
                       for s, iv in self.spans.items()},
        }


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * q / 100.0))
    return sorted_vals[idx]


class FlightRecorder:
    """Ring-buffer recorder of frame flights + open-span accounting.

    * :meth:`begin` opens a span; every opened span MUST reach exactly
      one of :meth:`close` / :meth:`drop` / :meth:`expire` /
      :meth:`drop_awaiting` — :meth:`open_spans` is the leak detector.
    * :meth:`sent` registers the span for ACK correlation under its
      (display, wire frame id); :meth:`ack` closes it with the true
      network round trip.
    * Completed spans land in a fixed ring (single write index, no
      locks); :meth:`summary` and :meth:`export_trace_events` read a
      consistent-enough snapshot of it (a torn read can at worst miss
      or double-see one in-rotation frame — fine for percentiles).

    ``clock`` is injectable for deterministic tests; call sites that
    already measured their own intervals pass absolute times instead.
    """

    #: default seconds before an un-terminated span is expired
    EXPIRE_AFTER_S = 30.0

    def __init__(self, capacity: int = 4096, clock=time.monotonic) -> None:
        self.capacity = max(16, int(capacity))
        self._clock = clock
        self._ring: List[Optional[FrameTrace]] = [None] * self.capacity
        self._widx = 0
        self._next_token = 1
        #: token -> open trace (every span not yet terminal)
        self._open: Dict[int, FrameTrace] = {}
        #: (display, frame_id) -> trace awaiting CLIENT_FRAME_ACK
        self._awaiting: Dict[Tuple[str, int], FrameTrace] = {}
        self.metrics = None          # observability.Metrics, wired lazily
        # terminal accounting (cheap mirrors, assertable without prom)
        self.closed_total = 0
        self.dropped_total = 0
        self.expired_total = 0
        self.acked_total = 0
        #: epoch anchor so trace-event timestamps are wall-clock-ish
        self._epoch_mono = clock()
        self._epoch_wall = time.time()

    # -- span lifecycle ----------------------------------------------------

    def begin(self, display: str, t: Optional[float] = None) -> FrameTrace:
        tr = FrameTrace(display, self._clock() if t is None else t)
        token = self._next_token
        self._next_token = token + 1
        tr._token = token
        self._open[token] = tr
        return tr

    def open_spans(self) -> int:
        """Spans opened but not yet terminal (the leak invariant)."""
        return len(self._open)

    def _retire(self, tr: FrameTrace, terminal: str) -> None:
        """Single exit gate: detach from the open/awaiting tables, stamp
        the terminal mark, rotate into the ring, publish metrics."""
        if tr.terminal is not None:     # already closed (idempotent)
            return
        tr.terminal = terminal
        self._open.pop(tr._token, None)
        if tr.frame_id >= 0:
            cur = self._awaiting.get((tr.display, tr.frame_id))
            if cur is tr:
                self._awaiting.pop((tr.display, tr.frame_id), None)
        self._ring[self._widx % self.capacity] = tr
        self._widx += 1
        self.closed_total += 1
        self._publish(tr)

    def close(self, tr: FrameTrace, terminal: str = "acked") -> None:
        if terminal == "acked":
            self.acked_total += 1
        self._retire(tr, terminal)

    def drop(self, tr: FrameTrace, stage: str) -> None:
        """Terminal ``dropped@<stage>``: the frame was lost there."""
        self.dropped_total += 1
        self._retire(tr, f"dropped@{stage}")

    def finish_empty(self, tr: FrameTrace) -> None:
        """Damage gating emitted nothing: a normal coalesced frame, not
        a loss — closed so the span cannot leak, kept out of the drop
        counters and the glass-to-glass series."""
        self._retire(tr, "empty")

    # -- ACK correlation ---------------------------------------------------

    def sent(self, tr: FrameTrace) -> None:
        """The frame's last stripe left the transport: register under
        its wire id so the client's CLIENT_FRAME_ACK can close it. A
        wire-id collision (2^16 wrap with a stalled client) expires the
        stale span rather than leaking it."""
        if tr.terminal is not None or tr.frame_id < 0:
            return
        key = (tr.display, tr.frame_id)
        old = self._awaiting.get(key)
        if old is not None and old is not tr:
            self.expired_total += 1
            self._retire(old, f"expired@{old.last_stage}")
        self._awaiting[key] = tr

    def ack(self, display: str, frame_id: int,
            t: Optional[float] = None) -> Optional[FrameTrace]:
        """CLIENT_FRAME_ACK landed: close the span with the true network
        round trip (send end -> ack arrival)."""
        tr = self._awaiting.pop((display, int(frame_id)), None)
        if tr is None:
            return None
        now = self._clock() if t is None else t
        send_iv = tr.spans.get("send")
        t0 = send_iv[1] if send_iv else tr.t_end
        tr.mark("ack", t0, max(t0, now))
        self.close(tr, "acked")
        return tr

    # -- leak control ------------------------------------------------------

    def expire(self, older_than_s: Optional[float] = None) -> int:
        """Close every open span older than the window (clients that
        never ACK, abandoned in-flight work). Returns how many."""
        horizon = self._clock() - (self.EXPIRE_AFTER_S
                                   if older_than_s is None
                                   else older_than_s)
        stale = [tr for tr in list(self._open.values()) if tr.t0 <= horizon]
        for tr in stale:
            self.expired_total += 1
            self._retire(tr, f"expired@{tr.last_stage}")
        return len(stale)

    def drop_awaiting(self, display: str, stage: str = "reset") -> int:
        """Pipeline reset / display teardown: frames sent but not yet
        ACKed will never be — their ids restart at 1. Returns how many
        spans were closed."""
        stale = [tr for (d, _fid), tr in list(self._awaiting.items())
                 if d == display]
        for tr in stale:
            self.drop(tr, stage)
        return len(stale)

    # -- metrics -----------------------------------------------------------

    def _publish(self, tr: FrameTrace) -> None:
        m = self.metrics
        if m is None:
            return
        try:
            for stage, iv in tr.spans.items():
                m.observe_stage(tr.display, stage,
                                (iv[1] - iv[0]) * 1000.0)
            if tr.terminal == "acked":
                m.observe_glass_to_glass(tr.display, tr.total_ms)
            enc = tr.encode_only_ms
            if enc is not None and tr.terminal != "empty":
                m.observe_encode_only(tr.display, enc)
            if tr.terminal and tr.terminal.startswith(("dropped@",
                                                       "expired@")):
                m.inc_trace_dropped(tr.terminal.split("@", 1)[1])
            m.set_trace_open_spans(len(self._open))
        except Exception:       # metrics must never break the frame path
            pass

    # -- readers -----------------------------------------------------------

    def _completed(self, display: Optional[str] = None,
                   last_s: Optional[float] = None) -> List[FrameTrace]:
        horizon = None if last_s is None else self._clock() - last_s
        out = []
        for tr in list(self._ring):
            if tr is None:
                continue
            if display is not None and tr.display != display:
                continue
            if horizon is not None and tr.t_end < horizon:
                continue
            out.append(tr)
        return out

    def summary(self, display: Optional[str] = None,
                last_s: Optional[float] = None) -> Dict[str, Any]:
        """Per-stage p50/p95/p99 plus the two headline series, over the
        ring (optionally filtered by display / recency)."""
        traces = self._completed(display, last_s)
        stages: Dict[str, Any] = {}
        for stage in STAGES:
            vals = sorted(d for tr in traces
                          if (d := tr.duration_ms(stage)) is not None)
            if vals:
                stages[stage] = {
                    "p50_ms": round(_pct(vals, 50), 3),
                    "p95_ms": round(_pct(vals, 95), 3),
                    "p99_ms": round(_pct(vals, 99), 3),
                    "n": len(vals),
                }
        g2g = sorted(tr.total_ms for tr in traces
                     if tr.terminal == "acked")
        enc = sorted(e for tr in traces if tr.terminal != "empty"
                     and (e := tr.encode_only_ms) is not None)
        out: Dict[str, Any] = {
            "frames": len(traces),
            "acked": sum(1 for t in traces if t.terminal == "acked"),
            "dropped": sum(1 for t in traces if t.terminal
                           and t.terminal.startswith("dropped@")),
            "open_spans": len(self._open),
            "stages": stages,
        }
        if g2g:
            out["glass_to_glass_p50_ms"] = round(_pct(g2g, 50), 1)
            out["glass_to_glass_p95_ms"] = round(_pct(g2g, 95), 1)
        if enc:
            out["encode_only_p50_ms"] = round(_pct(enc, 50), 1)
            out["encode_only_p95_ms"] = round(_pct(enc, 95), 1)
        return out

    def slowest(self, k: int = 5, display: Optional[str] = None
                ) -> List[Dict[str, Any]]:
        """Top-k slowest completed frames with their stage timelines."""
        traces = sorted(self._completed(display),
                        key=lambda t: t.total_ms, reverse=True)
        return [tr.as_dict() for tr in traces[:max(0, int(k))]]

    # -- Chrome trace-event (Perfetto) export ------------------------------

    def export_trace_events(self, last_s: Optional[float] = None,
                            include_open: bool = False) -> Dict[str, Any]:
        """The last N seconds as Chrome trace-event JSON: load the
        result at https://ui.perfetto.dev (docs/observability.md has the
        walkthrough). One process per display, one thread row per frame
        (rows recycle mod a small constant so the view stays readable),
        one complete ("X") slice per stage."""
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = {}
        traces = self._completed(None, last_s)
        if include_open:
            traces = traces + list(self._open.values())
        for tr in traces:
            pid = pids.setdefault(tr.display, len(pids) + 1)
            tid = (tr.frame_id if tr.frame_id >= 0 else tr._token) % 64 + 1
            for stage, iv in sorted(tr.spans.items(),
                                    key=lambda kv: kv[1][0]):
                events.append({
                    "name": stage,
                    "cat": "frame",
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": round((iv[0] - self._epoch_mono) * 1e6, 1),
                    "dur": round(max(0.0, iv[1] - iv[0]) * 1e6, 1),
                    "args": {
                        "frame_id": tr.frame_id,
                        "display": tr.display,
                        "terminal": tr.terminal or "open",
                        # unique per span: consumers regrouping events
                        # must not merge distinct frames that share a
                        # recycled tid and frame_id -1 (never-sent drops)
                        "span": tr._token,
                    },
                })
        for display, pid in pids.items():
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"display:{display}"},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "recorder": "selkies-tpu flight recorder",
                "epoch_unix_s": round(self._epoch_wall, 3),
                "open_spans": len(self._open),
            },
        }


# ---------------------------------------------------------------------------
# jax.profiler capture hook (served at /debug/jax-trace)


_JAX_TRACE_LOCK = threading.Lock()


def capture_jax_trace(out_dir: str, duration_ms: float) -> Dict[str, Any]:
    """Run a ``jax.profiler`` trace for ``duration_ms`` into ``out_dir``
    so device-side stalls can be correlated with the host-side spans.
    Serialized (one capture at a time); raises on an unavailable
    profiler — the HTTP layer maps that to an error response."""
    import jax

    duration_s = min(30.0, max(0.01, float(duration_ms) / 1000.0))
    if not _JAX_TRACE_LOCK.acquire(blocking=False):
        raise RuntimeError("a jax trace capture is already running")
    try:
        with jax.profiler.trace(out_dir):
            time.sleep(duration_s)
    finally:
        _JAX_TRACE_LOCK.release()
    return {"path": out_dir, "duration_ms": duration_s * 1000.0}


# ---------------------------------------------------------------------------
# Compatibility shim: the pre-recorder stamp-based API
#
# FrameTracer predates the flight recorder (it was imported by nothing
# but its own test). The names stay importable so downstream code and
# tests evolve instead of breaking; new call sites use FlightRecorder.


@dataclass
class StageSpan:
    """Stamp-based span (compat): a dict of instant timestamps."""

    frame_id: int
    stamps: Dict[str, float] = field(default_factory=dict)

    def mark(self, stage: str) -> None:
        self.stamps[stage] = time.monotonic()

    def duration_ms(self, a: str, b: str) -> Optional[float]:
        if a in self.stamps and b in self.stamps:
            return (self.stamps[b] - self.stamps[a]) * 1000.0
        return None

    @property
    def total_ms(self) -> Optional[float]:
        if not self.stamps:
            return None
        return (max(self.stamps.values()) - min(self.stamps.values())) * 1e3


class FrameTracer:
    """Compat ring of :class:`StageSpan` + percentile summaries."""

    def __init__(self, capacity: int = 600):
        self.capacity = capacity
        self._ring: List[StageSpan] = []
        self._open: Dict[int, StageSpan] = {}

    def begin(self, frame_id: int) -> StageSpan:
        span = StageSpan(frame_id)
        span.mark("capture")
        self._open[frame_id] = span
        return span

    def mark(self, frame_id: int, stage: str) -> None:
        span = self._open.get(frame_id)
        if span is not None:
            span.mark(stage)

    def finish(self, frame_id: int) -> Optional[StageSpan]:
        span = self._open.pop(frame_id, None)
        if span is None:
            return None
        span.mark("send")
        self._ring.append(span)
        if len(self._ring) > self.capacity:
            self._ring = self._ring[-self.capacity:]
        return span

    def percentile_ms(self, a: str, b: str, pct: float = 50.0
                      ) -> Optional[float]:
        vals = sorted(
            d for s in self._ring
            if (d := s.duration_ms(a, b)) is not None)
        if not vals:
            return None
        return _pct(vals, pct)

    def summary(self) -> Dict[str, Optional[float]]:
        return {
            "p50_total_ms": self.percentile_ms("capture", "send", 50),
            "p95_total_ms": self.percentile_ms("capture", "send", 95),
            "p50_encode_ms": self.percentile_ms("dispatch", "harvest", 50),
            "frames": float(len(self._ring)),
        }
