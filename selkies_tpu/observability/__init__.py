"""Observability: Prometheus metrics + per-stage frame tracing.

Parity targets: ``legacy/metrics.py`` (Prometheus gauges/histogram/Info on
:8000, WebRTC-stats CSV dump) and the SURVEY §5 tracing gap (the reference
has no tracer; we add per-stage timestamps around the encode path).
"""

from .metrics import Metrics
from .tracing import FrameTracer, StageSpan

__all__ = ["Metrics", "FrameTracer", "StageSpan"]
