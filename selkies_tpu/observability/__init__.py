"""Observability: Prometheus metrics + the frame flight recorder.

Two surfaces (docs/observability.md):

* :class:`Metrics` — the Prometheus registry (parity with
  ``legacy/metrics.py`` gauges plus the tpuenc/robustness/edge series)
  and the observability HTTP endpoint: ``/metrics``, ``/healthz``,
  ``/debug/trace`` (Perfetto-loadable flight-recorder export), and the
  opt-in ``/debug/jax-trace`` profiler hook.
* :class:`FlightRecorder` / :class:`FrameTrace` — per-frame stage
  tracing from capture to CLIENT_FRAME_ACK (:data:`STAGES`), the
  measurement substrate behind ``glass_to_glass_ms`` /
  ``encode_only_ms``, the ``system_health`` stage breakdown, and
  tools/trace_report.py.

``FrameTracer``/``StageSpan`` are the pre-recorder stamp-based API,
kept as a compatibility shim.
"""

from .metrics import Metrics
from .tracing import (STAGES, FlightRecorder, FrameTrace, FrameTracer,
                      StageSpan)

__all__ = ["Metrics", "FlightRecorder", "FrameTrace", "STAGES",
           "FrameTracer", "StageSpan"]
