"""Prometheus metrics + observability HTTP endpoint.

Parity with ``legacy/metrics.py:39-75``: ``fps`` gauge, ``fps_hist``
histogram, ``gpu_utilization`` (here: TPU duty estimate), ``latency``
gauge, and a ``webrtc_statistics`` Info — plus tpuenc-specific series
(encode ms, stripe bytes, backpressure state) and the flight-recorder
stage series (docs/observability.md). Falls back to a no-op registry
when prometheus_client is unavailable so the server never grows a hard
dependency.

The HTTP side is our own threaded server rather than
``prometheus_client.start_http_server`` because the port carries more
than the exposition: ``/healthz`` (liveness), ``/debug/trace`` (the
flight recorder's Perfetto-loadable capture of the last N seconds) and
``/debug/jax-trace`` (an on-demand ``jax.profiler`` capture, guarded by
the ``jax_trace_enabled`` setting). A bind failure logs and disables
the endpoint — it never takes the data server down with it.

Every series registered here must be documented in
docs/observability.md; tools/metrics_lint.py (tier-1) enforces the
correspondence in both directions.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
from typing import Dict, Optional

logger = logging.getLogger("selkies_tpu.observability.metrics")

try:
    import prometheus_client as prom
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, Info)
    HAVE_PROM = True
except Exception:  # pragma: no cover
    HAVE_PROM = False


class Metrics:
    def __init__(self, port: int = 8000):
        self.port = port
        self._started = False
        self._httpd = None
        self._http_thread = None
        #: actual bound port once start_http succeeds (port=0 binds
        #: ephemeral — tests use this)
        self.http_port: Optional[int] = None
        #: the server's FlightRecorder, wired by main()/bench so
        #: /debug/trace can export it (None -> endpoint answers 503)
        self.recorder = None
        #: /debug/jax-trace is an on-demand profiler with filesystem
        #: side effects: disabled unless the operator opts in
        #: (jax_trace_enabled setting)
        self.jax_trace_enabled = False
        if not HAVE_PROM:  # pragma: no cover
            return
        self.registry = CollectorRegistry()
        self.fps = Gauge("fps", "Frames per second observed by client",
                         registry=self.registry)
        self.fps_hist = Histogram(
            "fps_hist", "Histogram of FPS observed by client",
            buckets=(0, 10, 20, 30, 40, 50, 60, 90, 120, float("inf")),
            registry=self.registry)
        self.latency = Gauge("latency", "Latency observed by client (ms)",
                             registry=self.registry)
        self.tpu_utilization = Gauge(
            "tpu_utilization", "TPU encode duty cycle percent",
            registry=self.registry)
        self.gpu_utilization = Gauge(
            "gpu_utilization", "Alias of tpu_utilization for dashboards "
            "built against the reference", registry=self.registry)
        self.encode_ms = Histogram(
            "tpuenc_encode_ms", "Per-frame encode wall time (ms)",
            buckets=(1, 2, 4, 8, 16, 33, 66, 100, float("inf")),
            registry=self.registry)
        self.frame_bytes = Histogram(
            "tpuenc_frame_bytes", "Encoded bytes per frame",
            buckets=(1e3, 5e3, 2e4, 5e4, 1e5, 2.5e5, 1e6, float("inf")),
            registry=self.registry)
        # ISSUE 1: the H.264 bottleneck claims (D2H transfer size, host
        # entropy cost per session) must be measured, not inferred — the
        # pipelined encoders record these per frame
        self.d2h_bytes_per_frame = Gauge(
            "tpuenc_d2h_bytes_per_frame", "Device-to-host bytes fetched "
            "per encoded frame (heads, payloads, and overflow re-reads)",
            registry=self.registry)
        self.host_entropy_ms_per_frame = Gauge(
            "tpuenc_host_entropy_ms_per_frame", "Host-side entropy-coding "
            "wall time per frame (native CAVLC / overflow fallbacks; ~0 "
            "when the device entropy tiers carry steady state)",
            registry=self.registry)
        # ISSUE 12: the dispatch/fetch-floor claims must stay measured —
        # the async pipeline driver keeps >=2 batches in flight, and
        # these series prove (or disprove) it per deployment
        self.inflight_batches = Gauge(
            "tpuenc_inflight_batches", "Encode batches dispatched but not "
            "yet harvested (the async pipeline keeps >=2 in flight so the "
            "chip never waits on a host round trip)",
            registry=self.registry)
        self.dispatch_ms = Histogram(
            "tpuenc_dispatch_ms", "Host wall time to stage + dispatch one "
            "encode batch (program launch, not device compute)",
            buckets=(0.5, 1, 2, 4, 8, 16, 33, 66, 100, 250, float("inf")),
            registry=self.registry)
        self.fetch_wait_ms = Histogram(
            "tpuenc_fetch_wait_ms", "Host wall time blocked materializing "
            "an eagerly-started D2H fetch (~0 when the overlap hides the "
            "transfer; the RPC floor when it does not)",
            buckets=(0.5, 1, 2, 4, 8, 16, 33, 66, 100, 250, float("inf")),
            registry=self.registry)
        # ISSUE 2: supervision / degradation observability — dropped and
        # errored frames were previously log lines only; restart and ladder
        # activity must be scrapeable to be actionable
        self.frames_dropped = Counter(
            "frames_dropped_total", "Frames dropped by saturated or "
            "errored encode pipelines", registry=self.registry)
        self.encode_errors = Counter(
            "encode_errors_total", "Frames lost to encoder exceptions",
            registry=self.registry)
        self.watchdog_restarts = Counter(
            "watchdog_restarts_total", "Pipeline restarts triggered by the "
            "frame-deadline watchdog (stalled capture/fetch)",
            registry=self.registry)
        self.supervisor_restarts = Counter(
            "supervisor_restarts_total", "Supervised restarts of display "
            "capture/backpressure loops (crash + watchdog + clean)",
            registry=self.registry)
        self.degradation_rung = Gauge(
            "degradation_rung", "Worst degradation-ladder rung across "
            "displays (0 device entropy, 1 host entropy, 2 jpeg fallback)",
            registry=self.registry)
        self.failed_displays = Gauge(
            "failed_displays", "Displays whose supervisor exhausted its "
            "restart budget (terminal failed state)",
            registry=self.registry)
        # ISSUE 3: wire-edge hardening — malformed/floody/stalled clients
        # must be visible as first-class series, not debug log lines
        self.protocol_errors = Counter(
            "protocol_errors_total", "Client messages dropped by the "
            "per-message exception boundary (malformed frames, spoofed "
            "server verbs, handler crashes)", registry=self.registry)
        self.rate_limited = Counter(
            "rate_limited_total", "Client messages dropped by per-class "
            "token-bucket rate limiting", ("klass",),
            registry=self.registry)
        self.upload_paced = Counter(
            "upload_paced_total", "Upload messages accepted after a "
            "pacing sleep (byte-rate smoothing; nothing was dropped)",
            registry=self.registry)
        self.sessions_rejected = Counter(
            "sessions_rejected_total", "Connections/displays refused by "
            "admission control (max_clients, max_displays, load shedding)",
            registry=self.registry)
        self.slow_client_evictions = Counter(
            "slow_client_evictions_total", "Clients disconnected after "
            "sustained send-queue overflow (KILL slow_consumer)",
            registry=self.registry)
        self.send_queue_depth = Gauge(
            "send_queue_depth", "Deepest per-client bounded send queue",
            registry=self.registry)
        self.reconfigure_coalesced = Counter(
            "reconfigure_coalesced_total", "Resize/SETTINGS requests "
            "absorbed into an already-scheduled display reconfiguration",
            registry=self.registry)
        self.sessions_queued = Counter(
            "sessions_queued_total", "Display joins that waited in the "
            "admission queue for a scheduler slot (admit-after-wait and "
            "shed-after-wait both count)", registry=self.registry)
        # ISSUE 14: session-scheduler health — the coordinator's per-slot
        # fault domains were stats()-only before; a sick slot, a
        # quarantine, or a live migration must be scrapeable
        # (docs/scaling.md). Cumulative values are mirrored from the
        # coordinator as gauges (the coordinator owns the counters).
        self.mesh_active_sessions = Gauge(
            "mesh_active_sessions", "Sessions attached to mesh scheduler "
            "slots across all geometry buckets", registry=self.registry)
        self.mesh_lanes = Gauge(
            "mesh_lanes", "Live batch lanes across all geometry buckets "
            "(each lane is one compiled SPMD encoder)",
            registry=self.registry)
        self.mesh_inflight_batches = Gauge(
            "mesh_inflight_batches", "Mesh ticks dispatched but not yet "
            "harvested, summed over lanes", registry=self.registry)
        self.mesh_slot_errors = Gauge(
            "mesh_slot_errors_total", "Frames lost to failed mesh "
            "dispatch/harvest ticks, summed over slots (cumulative; "
            "per-slot detail rides the system_health feed)",
            registry=self.registry)
        self.mesh_tick_errors = Gauge(
            "mesh_tick_errors_total", "Failed mesh coordinator ticks "
            "(cumulative, lane-contained failures included)",
            registry=self.registry)
        self.mesh_worker_restarts = Gauge(
            "mesh_worker_restarts_total", "Mesh tick-thread re-spawns "
            "after a worker death (cumulative)", registry=self.registry)
        self.mesh_quarantined_slots = Gauge(
            "mesh_quarantined_slots", "Scheduler slots removed from "
            "service as sick fault domains", registry=self.registry)
        self.mesh_migrations = Gauge(
            "mesh_sessions_migrated_total", "Sessions live-migrated off "
            "quarantined slots onto healthy lanes (cumulative)",
            registry=self.registry)
        # ISSUE 15: split-frame encoding — one 4K/8K frame's stripe
        # bands sharded across chips; the shard fan-out and the
        # host-side slice-concat wall must be scrapeable
        self.sfe_shards_g = Gauge(
            "sfe_shards", "Stripe shards one frame spans on the widest "
            "active split-frame-encoding lane (0 = no SFE lanes)",
            registry=self.registry)
        self.sfe_concat_ms = Gauge(
            "sfe_concat_ms", "Host wall per mesh tick concatenating "
            "per-shard slice payloads into access units on SFE lanes "
            "(recent p50, mirrored from the coordinator)",
            registry=self.registry)
        # ISSUE 13: flight-recorder stage series — the per-stage latency
        # decomposition behind the glass-to-glass number, labeled by
        # display so a sick session is attributable (docs/observability.md)
        _stage_buckets = (0.25, 0.5, 1, 2, 4, 8, 16, 33, 66, 100, 250,
                         500, 1000, float("inf"))
        self.frame_stage_ms = Histogram(
            "frame_stage_ms", "Per-frame wall time in one pipeline stage "
            "(capture/stage/dispatch/fetch_wait/pack/queue/send/ack)",
            ("stage", "display"), buckets=_stage_buckets,
            registry=self.registry)
        self.glass_to_glass_ms = Histogram(
            "glass_to_glass_ms", "Capture start to CLIENT_FRAME_ACK per "
            "acked frame (the latency the user feels)",
            ("display",), buckets=_stage_buckets, registry=self.registry)
        self.encode_only_ms = Histogram(
            "encode_only_ms", "Submit to stripes-host-packed per frame "
            "(the ROADMAP item 1 criterion vs device ms/frame)",
            ("display",), buckets=_stage_buckets, registry=self.registry)
        self.trace_open_spans = Gauge(
            "trace_open_spans", "Frame spans opened but not yet terminal "
            "(a steady nonzero residue means a span leak)",
            registry=self.registry)
        self.trace_dropped = Counter(
            "trace_dropped_total", "Frame spans closed with a dropped@/"
            "expired@ terminal mark, by the stage that lost them",
            ("stage",), registry=self.registry)
        self.clients = Gauge("connected_clients", "WebSocket clients",
                             registry=self.registry)
        self.backpressured = Gauge(
            "backpressured_displays", "Displays currently throttled by the "
            "frame-ACK backpressure loop", registry=self.registry)
        self.webrtc_stats = Info("webrtc_statistics", "Last WebRTC stats",
                                 registry=self.registry)

    def start_http(self) -> bool:
        """Expose /metrics + /healthz + /debug/trace [+ /debug/jax-trace]
        (parity with legacy Metrics.start_http, plus the observability
        surface). A bind failure is NON-FATAL: it logs, leaves the
        endpoint disabled, and returns False — a busy metrics port must
        never crash the data server."""
        if self._started:
            return True
        from http.server import ThreadingHTTPServer

        try:
            self._httpd = ThreadingHTTPServer(
                ("0.0.0.0", int(self.port)),
                _make_observability_handler())
        except OSError as e:
            logger.error("metrics http bind failed on :%s (%s); metrics "
                         "endpoint disabled", self.port, e)
            self._httpd = None
            return False
        self._httpd.daemon_threads = True
        self._httpd.metrics = self
        self.http_port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)
        self._http_thread.start()
        self._started = True
        logger.info("observability http on :%d (/metrics /healthz "
                    "/debug/trace%s)", self.http_port,
                    " /debug/jax-trace" if self.jax_trace_enabled else "")
        return True

    def stop_http(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._started = False

    # no-op-safe setters -------------------------------------------------

    def set_fps(self, fps: float) -> None:
        if HAVE_PROM:
            self.fps.set(fps)
            self.fps_hist.observe(fps)

    def set_latency(self, ms: float) -> None:
        if HAVE_PROM:
            self.latency.set(ms)

    def set_tpu_utilization(self, pct: float) -> None:
        if HAVE_PROM:
            self.tpu_utilization.set(pct)
            self.gpu_utilization.set(pct)

    def observe_encode(self, ms: float, nbytes: int) -> None:
        if HAVE_PROM:
            self.encode_ms.observe(ms)
            self.frame_bytes.observe(nbytes)

    def set_d2h_bytes_per_frame(self, nbytes: float) -> None:
        if HAVE_PROM:
            self.d2h_bytes_per_frame.set(nbytes)

    def set_host_entropy_ms_per_frame(self, ms: float) -> None:
        if HAVE_PROM:
            self.host_entropy_ms_per_frame.set(ms)

    def set_inflight_batches(self, n: int) -> None:
        if HAVE_PROM:
            self.inflight_batches.set(n)

    def observe_dispatch(self, ms: float) -> None:
        if HAVE_PROM:
            self.dispatch_ms.observe(ms)

    def observe_fetch_wait(self, ms: float) -> None:
        if HAVE_PROM:
            self.fetch_wait_ms.observe(ms)

    def observe_stage(self, display: str, stage: str, ms: float) -> None:
        if HAVE_PROM:
            self.frame_stage_ms.labels(stage=stage, display=display) \
                .observe(ms)

    def observe_glass_to_glass(self, display: str, ms: float) -> None:
        if HAVE_PROM:
            self.glass_to_glass_ms.labels(display=display).observe(ms)

    def observe_encode_only(self, display: str, ms: float) -> None:
        if HAVE_PROM:
            self.encode_only_ms.labels(display=display).observe(ms)

    def set_trace_open_spans(self, n: int) -> None:
        if HAVE_PROM:
            self.trace_open_spans.set(n)

    def inc_trace_dropped(self, stage: str, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.trace_dropped.labels(stage=stage).inc(n)

    def inc_frames_dropped(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.frames_dropped.inc(n)

    def inc_encode_errors(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.encode_errors.inc(n)

    def inc_watchdog_restart(self) -> None:
        if HAVE_PROM:
            self.watchdog_restarts.inc()

    def inc_supervisor_restart(self) -> None:
        if HAVE_PROM:
            self.supervisor_restarts.inc()

    def set_degradation_rung(self, level: int) -> None:
        if HAVE_PROM:
            self.degradation_rung.set(level)

    def set_failed_displays(self, n: int) -> None:
        if HAVE_PROM:
            self.failed_displays.set(n)

    def inc_protocol_errors(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.protocol_errors.inc(n)

    def inc_rate_limited(self, klass: str, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.rate_limited.labels(klass=klass).inc(n)

    def inc_upload_paced(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.upload_paced.inc(n)

    def inc_sessions_rejected(self) -> None:
        if HAVE_PROM:
            self.sessions_rejected.inc()

    def inc_slow_client_eviction(self) -> None:
        if HAVE_PROM:
            self.slow_client_evictions.inc()

    def set_send_queue_depth(self, n: int) -> None:
        if HAVE_PROM:
            self.send_queue_depth.set(n)

    def inc_reconfigure_coalesced(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.reconfigure_coalesced.inc(n)

    def inc_sessions_queued(self) -> None:
        if HAVE_PROM:
            self.sessions_queued.inc()

    def set_mesh_health(self, *, active_sessions: int, lanes: int,
                        inflight: int, slot_errors: int, tick_errors: int,
                        worker_restarts: int, quarantined: int,
                        migrations: int) -> None:
        """Mirror the session scheduler's aggregate health (stats tick)."""
        if not HAVE_PROM:
            return
        self.mesh_active_sessions.set(active_sessions)
        self.mesh_lanes.set(lanes)
        self.mesh_inflight_batches.set(inflight)
        self.mesh_slot_errors.set(slot_errors)
        self.mesh_tick_errors.set(tick_errors)
        self.mesh_worker_restarts.set(worker_restarts)
        self.mesh_quarantined_slots.set(quarantined)
        self.mesh_migrations.set(migrations)

    def set_sfe_health(self, *, shards: int,
                       concat_ms_p50: float) -> None:
        """Mirror the SFE lane fan-out + slice-concat wall (stats tick)."""
        if HAVE_PROM:
            self.sfe_shards_g.set(shards)
            self.sfe_concat_ms.set(concat_ms_p50)

    def set_clients(self, n: int) -> None:
        if HAVE_PROM:
            self.clients.set(n)

    def set_backpressured(self, n: int) -> None:
        if HAVE_PROM:
            self.backpressured.set(n)

    def set_webrtc_stats(self, stats: Dict[str, str]) -> None:
        if HAVE_PROM:
            self.webrtc_stats.info(
                {str(k): str(v) for k, v in stats.items()})

    def render(self) -> bytes:
        """Current exposition text (for tests / ad-hoc scraping)."""
        if not HAVE_PROM:  # pragma: no cover
            return b""
        return prom.generate_latest(self.registry)


# ---------------------------------------------------------------------------
# the observability HTTP endpoint


def _make_observability_handler():
    from http.server import BaseHTTPRequestHandler
    from urllib.parse import parse_qs, urlparse

    class Handler(BaseHTTPRequestHandler):
        server_version = "selkies-tpu-observability"

        def _reply(self, code: int, body: bytes,
                   ctype: str = "text/plain; charset=utf-8") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def log_message(self, fmt, *args):  # quiet: scrapes are periodic
            logger.debug("http %s", fmt % args)

        def do_GET(self):  # noqa: N802 (http.server API)
            m: Metrics = self.server.metrics
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                if url.path == "/healthz":
                    self._reply(200, b"ok\n")
                elif url.path == "/metrics" or url.path == "/":
                    self._reply(200, m.render() if m else b"",
                                "text/plain; version=0.0.4; charset=utf-8")
                elif url.path == "/debug/trace":
                    rec = m.recorder if m else None
                    if rec is None:
                        self._reply(503, b"no flight recorder attached\n")
                        return
                    last_s = float(q.get("s", ["30"])[0])
                    body = json.dumps(rec.export_trace_events(
                        last_s=last_s)).encode()
                    self._reply(200, body, "application/json")
                elif url.path == "/debug/jax-trace":
                    if not (m and m.jax_trace_enabled):
                        self._reply(
                            403, b"jax tracing disabled; set "
                            b"jax_trace_enabled=true on the server\n")
                        return
                    import shutil

                    from .tracing import capture_jax_trace

                    ms = float(q.get("ms", ["500"])[0])
                    # one fixed dir, pruned per capture: a polling
                    # client must not accumulate profile dumps until
                    # the disk fills (captures can be tens of MB)
                    out_dir = os.path.join(tempfile.gettempdir(),
                                           "selkies_jax_trace")
                    shutil.rmtree(out_dir, ignore_errors=True)
                    os.makedirs(out_dir, exist_ok=True)
                    info = capture_jax_trace(out_dir, ms)
                    self._reply(200, json.dumps(info).encode(),
                                "application/json")
                else:
                    self._reply(404, b"not found\n")
            except Exception as e:
                logger.exception("observability endpoint %s failed",
                                 url.path)
                self._reply(500, f"error: {e!r}\n".encode())

    return Handler
