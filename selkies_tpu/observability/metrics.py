"""Prometheus metrics endpoint.

Parity with ``legacy/metrics.py:39-75``: ``fps`` gauge, ``fps_hist``
histogram, ``gpu_utilization`` (here: TPU duty estimate), ``latency``
gauge, and a ``webrtc_statistics`` Info — plus tpuenc-specific series
(encode ms, stripe bytes, backpressure state). Falls back to a no-op
registry when prometheus_client is unavailable so the server never grows
a hard dependency.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

logger = logging.getLogger("selkies_tpu.observability.metrics")

try:
    import prometheus_client as prom
    from prometheus_client import (CollectorRegistry, Counter, Gauge,
                                   Histogram, Info, start_http_server)
    HAVE_PROM = True
except Exception:  # pragma: no cover
    HAVE_PROM = False


class Metrics:
    def __init__(self, port: int = 8000):
        self.port = port
        self._started = False
        if not HAVE_PROM:  # pragma: no cover
            return
        self.registry = CollectorRegistry()
        self.fps = Gauge("fps", "Frames per second observed by client",
                         registry=self.registry)
        self.fps_hist = Histogram(
            "fps_hist", "Histogram of FPS observed by client",
            buckets=(0, 10, 20, 30, 40, 50, 60, 90, 120, float("inf")),
            registry=self.registry)
        self.latency = Gauge("latency", "Latency observed by client (ms)",
                             registry=self.registry)
        self.tpu_utilization = Gauge(
            "tpu_utilization", "TPU encode duty cycle percent",
            registry=self.registry)
        self.gpu_utilization = Gauge(
            "gpu_utilization", "Alias of tpu_utilization for dashboards "
            "built against the reference", registry=self.registry)
        self.encode_ms = Histogram(
            "tpuenc_encode_ms", "Per-frame encode wall time (ms)",
            buckets=(1, 2, 4, 8, 16, 33, 66, 100, float("inf")),
            registry=self.registry)
        self.frame_bytes = Histogram(
            "tpuenc_frame_bytes", "Encoded bytes per frame",
            buckets=(1e3, 5e3, 2e4, 5e4, 1e5, 2.5e5, 1e6, float("inf")),
            registry=self.registry)
        # ISSUE 1: the H.264 bottleneck claims (D2H transfer size, host
        # entropy cost per session) must be measured, not inferred — the
        # pipelined encoders record these per frame
        self.d2h_bytes_per_frame = Gauge(
            "tpuenc_d2h_bytes_per_frame", "Device-to-host bytes fetched "
            "per encoded frame (heads, payloads, and overflow re-reads)",
            registry=self.registry)
        self.host_entropy_ms_per_frame = Gauge(
            "tpuenc_host_entropy_ms_per_frame", "Host-side entropy-coding "
            "wall time per frame (native CAVLC / overflow fallbacks; ~0 "
            "when the device entropy tiers carry steady state)",
            registry=self.registry)
        # ISSUE 12: the dispatch/fetch-floor claims must stay measured —
        # the async pipeline driver keeps >=2 batches in flight, and
        # these series prove (or disprove) it per deployment
        self.inflight_batches = Gauge(
            "tpuenc_inflight_batches", "Encode batches dispatched but not "
            "yet harvested (the async pipeline keeps >=2 in flight so the "
            "chip never waits on a host round trip)",
            registry=self.registry)
        self.dispatch_ms = Histogram(
            "tpuenc_dispatch_ms", "Host wall time to stage + dispatch one "
            "encode batch (program launch, not device compute)",
            buckets=(0.5, 1, 2, 4, 8, 16, 33, 66, 100, 250, float("inf")),
            registry=self.registry)
        self.fetch_wait_ms = Histogram(
            "tpuenc_fetch_wait_ms", "Host wall time blocked materializing "
            "an eagerly-started D2H fetch (~0 when the overlap hides the "
            "transfer; the RPC floor when it does not)",
            buckets=(0.5, 1, 2, 4, 8, 16, 33, 66, 100, 250, float("inf")),
            registry=self.registry)
        # ISSUE 2: supervision / degradation observability — dropped and
        # errored frames were previously log lines only; restart and ladder
        # activity must be scrapeable to be actionable
        self.frames_dropped = Counter(
            "frames_dropped_total", "Frames dropped by saturated or "
            "errored encode pipelines", registry=self.registry)
        self.encode_errors = Counter(
            "encode_errors_total", "Frames lost to encoder exceptions",
            registry=self.registry)
        self.watchdog_restarts = Counter(
            "watchdog_restarts_total", "Pipeline restarts triggered by the "
            "frame-deadline watchdog (stalled capture/fetch)",
            registry=self.registry)
        self.supervisor_restarts = Counter(
            "supervisor_restarts_total", "Supervised restarts of display "
            "capture/backpressure loops (crash + watchdog + clean)",
            registry=self.registry)
        self.degradation_rung = Gauge(
            "degradation_rung", "Worst degradation-ladder rung across "
            "displays (0 device entropy, 1 host entropy, 2 jpeg fallback)",
            registry=self.registry)
        self.failed_displays = Gauge(
            "failed_displays", "Displays whose supervisor exhausted its "
            "restart budget (terminal failed state)",
            registry=self.registry)
        # ISSUE 3: wire-edge hardening — malformed/floody/stalled clients
        # must be visible as first-class series, not debug log lines
        self.protocol_errors = Counter(
            "protocol_errors_total", "Client messages dropped by the "
            "per-message exception boundary (malformed frames, spoofed "
            "server verbs, handler crashes)", registry=self.registry)
        self.rate_limited = Counter(
            "rate_limited_total", "Client messages dropped by per-class "
            "token-bucket rate limiting", ("klass",),
            registry=self.registry)
        self.upload_paced = Counter(
            "upload_paced_total", "Upload messages accepted after a "
            "pacing sleep (byte-rate smoothing; nothing was dropped)",
            registry=self.registry)
        self.sessions_rejected = Counter(
            "sessions_rejected_total", "Connections/displays refused by "
            "admission control (max_clients, max_displays, load shedding)",
            registry=self.registry)
        self.slow_client_evictions = Counter(
            "slow_client_evictions_total", "Clients disconnected after "
            "sustained send-queue overflow (KILL slow_consumer)",
            registry=self.registry)
        self.send_queue_depth = Gauge(
            "send_queue_depth", "Deepest per-client bounded send queue",
            registry=self.registry)
        self.reconfigure_coalesced = Counter(
            "reconfigure_coalesced_total", "Resize/SETTINGS requests "
            "absorbed into an already-scheduled display reconfiguration",
            registry=self.registry)
        self.clients = Gauge("connected_clients", "WebSocket clients",
                             registry=self.registry)
        self.backpressured = Gauge(
            "backpressured_displays", "Displays currently throttled by the "
            "frame-ACK backpressure loop", registry=self.registry)
        self.webrtc_stats = Info("webrtc_statistics", "Last WebRTC stats",
                                 registry=self.registry)

    def start_http(self) -> None:
        """Expose /metrics (parity with legacy Metrics.start_http)."""
        if HAVE_PROM and not self._started:
            start_http_server(self.port, registry=self.registry)
            self._started = True

    # no-op-safe setters -------------------------------------------------

    def set_fps(self, fps: float) -> None:
        if HAVE_PROM:
            self.fps.set(fps)
            self.fps_hist.observe(fps)

    def set_latency(self, ms: float) -> None:
        if HAVE_PROM:
            self.latency.set(ms)

    def set_tpu_utilization(self, pct: float) -> None:
        if HAVE_PROM:
            self.tpu_utilization.set(pct)
            self.gpu_utilization.set(pct)

    def observe_encode(self, ms: float, nbytes: int) -> None:
        if HAVE_PROM:
            self.encode_ms.observe(ms)
            self.frame_bytes.observe(nbytes)

    def set_d2h_bytes_per_frame(self, nbytes: float) -> None:
        if HAVE_PROM:
            self.d2h_bytes_per_frame.set(nbytes)

    def set_host_entropy_ms_per_frame(self, ms: float) -> None:
        if HAVE_PROM:
            self.host_entropy_ms_per_frame.set(ms)

    def set_inflight_batches(self, n: int) -> None:
        if HAVE_PROM:
            self.inflight_batches.set(n)

    def observe_dispatch(self, ms: float) -> None:
        if HAVE_PROM:
            self.dispatch_ms.observe(ms)

    def observe_fetch_wait(self, ms: float) -> None:
        if HAVE_PROM:
            self.fetch_wait_ms.observe(ms)

    def inc_frames_dropped(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.frames_dropped.inc(n)

    def inc_encode_errors(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.encode_errors.inc(n)

    def inc_watchdog_restart(self) -> None:
        if HAVE_PROM:
            self.watchdog_restarts.inc()

    def inc_supervisor_restart(self) -> None:
        if HAVE_PROM:
            self.supervisor_restarts.inc()

    def set_degradation_rung(self, level: int) -> None:
        if HAVE_PROM:
            self.degradation_rung.set(level)

    def set_failed_displays(self, n: int) -> None:
        if HAVE_PROM:
            self.failed_displays.set(n)

    def inc_protocol_errors(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.protocol_errors.inc(n)

    def inc_rate_limited(self, klass: str, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.rate_limited.labels(klass=klass).inc(n)

    def inc_upload_paced(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.upload_paced.inc(n)

    def inc_sessions_rejected(self) -> None:
        if HAVE_PROM:
            self.sessions_rejected.inc()

    def inc_slow_client_eviction(self) -> None:
        if HAVE_PROM:
            self.slow_client_evictions.inc()

    def set_send_queue_depth(self, n: int) -> None:
        if HAVE_PROM:
            self.send_queue_depth.set(n)

    def inc_reconfigure_coalesced(self, n: int = 1) -> None:
        if HAVE_PROM and n > 0:
            self.reconfigure_coalesced.inc(n)

    def set_clients(self, n: int) -> None:
        if HAVE_PROM:
            self.clients.set(n)

    def set_backpressured(self, n: int) -> None:
        if HAVE_PROM:
            self.backpressured.set(n)

    def set_webrtc_stats(self, stats: Dict[str, str]) -> None:
        if HAVE_PROM:
            self.webrtc_stats.info(
                {str(k): str(v) for k, v in stats.items()})

    def render(self) -> bytes:
        """Current exposition text (for tests / ad-hoc scraping)."""
        if not HAVE_PROM:  # pragma: no cover
            return b""
        return prom.generate_latest(self.registry)
