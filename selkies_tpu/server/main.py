"""Server entry point wiring (reference: selkies.py:3133-3307 main())."""

from __future__ import annotations

import asyncio
import logging

from ..settings import Settings
from .app import StreamingApp
from .data_server import DataStreamingServer


def run(settings: Settings) -> int:
    logging.basicConfig(
        level=logging.DEBUG if settings.debug.value else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return asyncio.run(_amain(settings)) or 0


async def _amain(settings: Settings) -> int:
    app = StreamingApp(settings)
    server = DataStreamingServer(settings, app=app)
    app.data_server = server

    if settings.audio_enabled.value:
        try:
            from ..audio import AudioCaptureSettings, AudioPipeline, opus_available

            if opus_available():
                server.audio_pipeline = AudioPipeline(server, AudioCaptureSettings(
                    device_name=settings.audio_device_name,
                    opus_bitrate=int(settings.audio_bitrate),
                    use_silence_gate=True))
            else:
                logging.getLogger("selkies_tpu").warning(
                    "audio disabled: libopus unavailable")
        except Exception:
            logging.getLogger("selkies_tpu").exception("audio init failed")

    input_handler = None
    cursor_monitor = None
    try:
        from ..input import InputHandler, open_clipboard_backend, open_x11_backend
        from ..input.cursor import CursorMonitor, open_cursor_source

        input_handler = InputHandler(
            backend=open_x11_backend(),
            clipboard=open_clipboard_backend(),
            data_server=server,
            enable_clipboard=(
                "true" if settings.clipboard_enabled.value else "false"),
            enable_binary_clipboard=settings.enable_binary_clipboard.value,
        )
        def _on_set_fps(fps: int) -> None:
            app.set_framerate(fps)
            asyncio.get_running_loop().create_task(server.set_framerate(fps))

        input_handler.on_set_fps = _on_set_fps
        server.input_handler = input_handler
        cursor_monitor = CursorMonitor(open_cursor_source(), app.send_cursor)
    except Exception as e:  # no X display etc. — stream-only mode
        logging.getLogger("selkies_tpu").warning("input plane disabled: %s", e)

    tasks = [asyncio.create_task(server.run_server())]
    if input_handler is not None:
        tasks.append(asyncio.create_task(input_handler.run_clipboard_poll()))
    if cursor_monitor is not None:
        tasks.append(asyncio.create_task(cursor_monitor.run()))
    try:
        await asyncio.gather(*tasks)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if cursor_monitor is not None:
            cursor_monitor.stop()
            cursor_monitor.source.close()
        if input_handler is not None:
            try:
                await input_handler.close()
            except Exception:
                logging.getLogger("selkies_tpu").exception(
                    "input plane shutdown failed")
        await server.stop()
    return 0
