"""Server entry point wiring (reference: selkies.py:3133-3307 main())."""

from __future__ import annotations

import asyncio
import logging
import os

from ..settings import Settings
from .app import StreamingApp
from .data_server import DataStreamingServer


def run(settings: Settings) -> int:
    logging.basicConfig(
        level=logging.DEBUG if settings.debug.value else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return asyncio.run(_amain(settings)) or 0


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: the first 1080p step costs tens of
    seconds to compile; across restarts it should cost a disk read."""
    try:
        import jax

        cache_dir = os.environ.get(
            "SELKIES_JAX_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "selkies-tpu-xla"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        logging.getLogger("selkies_tpu").debug("compile cache unavailable")


def _warm_default_geometry(settings: Settings) -> None:
    """Background-compile the default encoder geometry so the first client
    doesn't pay the jit stall on the event loop."""
    import threading

    def work():
        try:
            from ..server.data_server import default_encoder_factory

            enc = default_encoder_factory(1920, 1080, settings)
            import numpy as np

            enc.submit(np.zeros((1080, 1920, 3), np.uint8))
            enc.flush()
            close = getattr(enc, "close", None)
            if close:
                close()
            logging.getLogger("selkies_tpu").info("encoder warm-up done")
        except Exception:
            logging.getLogger("selkies_tpu").debug("warm-up skipped")

    threading.Thread(target=work, name="tpuenc-warmup", daemon=True).start()


async def _amain(settings: Settings) -> int:
    _enable_compile_cache()
    app = StreamingApp(settings)
    server = DataStreamingServer(settings, app=app)
    app.data_server = server
    _warm_default_geometry(settings)

    if settings.audio_enabled.value:
        try:
            from ..audio import AudioCaptureSettings, AudioPipeline, opus_available

            if opus_available():
                server.audio_pipeline = AudioPipeline(server, AudioCaptureSettings(
                    device_name=settings.audio_device_name,
                    opus_bitrate=int(settings.audio_bitrate),
                    use_silence_gate=True))
            else:
                logging.getLogger("selkies_tpu").warning(
                    "audio disabled: libopus unavailable")
        except Exception:
            logging.getLogger("selkies_tpu").exception("audio init failed")

    input_handler = None
    cursor_monitor = None
    try:
        from ..input import InputHandler, open_clipboard_backend, open_x11_backend
        from ..input.cursor import CursorMonitor, open_cursor_source

        input_handler = InputHandler(
            backend=open_x11_backend(),
            clipboard=open_clipboard_backend(),
            data_server=server,
            enable_clipboard=(
                "true" if settings.clipboard_enabled.value else "false"),
            enable_binary_clipboard=settings.enable_binary_clipboard.value,
        )
        def _on_set_fps(fps: int) -> None:
            app.set_framerate(fps)
            asyncio.get_running_loop().create_task(server.set_framerate(fps))

        input_handler.on_set_fps = _on_set_fps
        server.input_handler = input_handler
        cursor_monitor = CursorMonitor(open_cursor_source(), app.send_cursor)
    except Exception as e:  # no X display etc. — stream-only mode
        logging.getLogger("selkies_tpu").warning("input plane disabled: %s", e)

    tasks = [asyncio.create_task(server.run_server())]

    # HTTP side: serve the bundled web client + /turn + signaling on the
    # web port (reference: signalling_web.py serves gst-web on 8080)
    web_server = None
    try:
        from ..rtc import SignalingServer
        from . import bundled_web_root

        web_root = bundled_web_root()
        if web_root is not None:
            files_root = None
            if "download" in settings.file_transfers:
                from .data_server import upload_dir

                files_root = upload_dir()
            web_server = SignalingServer(
                addr="0.0.0.0", port=int(settings.web_port),
                web_root=web_root,
                files_root=files_root,
                turn_shared_secret=str(settings.turn_shared_secret),
                turn_host=str(settings.turn_host),
                turn_port=str(settings.turn_port),
            )

            async def _run_web(ws=web_server):
                # a busy web port must not take the media plane down
                try:
                    await ws.run()
                except OSError as e:
                    logging.getLogger("selkies_tpu").error(
                        "web server bind failed (%s); client serving "
                        "disabled", e)

            tasks.append(asyncio.create_task(_run_web()))
        else:
            logging.getLogger("selkies_tpu").warning(
                "web client assets not bundled; HTTP serving disabled")
    except Exception:
        logging.getLogger("selkies_tpu").exception("web server init failed")

    metrics = None
    try:
        from ..observability import Metrics

        if int(settings.metrics_port) > 0:
            metrics = Metrics(port=int(settings.metrics_port))
            # observability surface (docs/observability.md): the flight
            # recorder backs /debug/trace; the jax.profiler hook is
            # opt-in. start_http is non-fatal on a busy port.
            metrics.recorder = server.recorder
            metrics.jax_trace_enabled = bool(
                settings.jax_trace_enabled.value)
            metrics.start_http()
            server.metrics = metrics
            server.recorder.metrics = metrics
    except Exception as e:
        logging.getLogger("selkies_tpu").warning("metrics disabled: %s", e)

    if input_handler is not None:
        tasks.append(asyncio.create_task(input_handler.run_clipboard_poll()))
    if cursor_monitor is not None:
        tasks.append(asyncio.create_task(cursor_monitor.run()))
    try:
        await asyncio.gather(*tasks)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if web_server is not None:
            await web_server.stop()
        if cursor_monitor is not None:
            cursor_monitor.stop()
            cursor_monitor.source.close()
        if input_handler is not None:
            try:
                await input_handler.close()
            except Exception:
                logging.getLogger("selkies_tpu").exception(
                    "input plane shutdown failed")
        await server.stop()
    return 0
