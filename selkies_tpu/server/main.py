"""Server entry point wiring (reference: selkies.py:3133-3307 main())."""

from __future__ import annotations

import asyncio
import logging

from ..settings import Settings
from .app import StreamingApp
from .data_server import DataStreamingServer


def run(settings: Settings) -> int:
    logging.basicConfig(
        level=logging.DEBUG if settings.debug.value else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return asyncio.run(_amain(settings)) or 0


async def _amain(settings: Settings) -> int:
    app = StreamingApp(settings)
    server = DataStreamingServer(settings, app=app)
    app.data_server = server

    input_handler = None
    try:
        from ..inputs.handler import InputHandler

        input_handler = InputHandler(app=app, settings=settings)
        server.input_handler = input_handler
    except Exception as e:  # no X display etc. — stream-only mode
        logging.getLogger("selkies_tpu").warning("input plane disabled: %s", e)

    tasks = [asyncio.create_task(server.run_server())]
    if input_handler is not None:
        tasks.extend(input_handler.start_tasks())
    try:
        await asyncio.gather(*tasks)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await server.stop()
    return 0
