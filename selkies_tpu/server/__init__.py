"""Server plane: data/control server, entry points, WebRTC session app."""

import os


def bundled_web_root():
    """Absolute path of the bundled web client, or None when not shipped
    (e.g. a bare wheel install without the repo's web/ directory)."""
    root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "web")
    return root if os.path.isdir(root) else None
