"""WebRTC-mode entry point (parity: legacy ``wr_entrypoint``/``main()``,
reference legacy/webrtc.py:330-988): an in-process signaling+web server,
RTC-config monitors feeding TURN credentials, and the streaming session
app that calls the browser peer and carries tpuenc H.264 + Opus + the
input data channel over the in-repo WebRTC stack.

Run: ``selkies-tpu-webrtc`` (console script) or
``python -m selkies_tpu.server.webrtc_main``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys

from ..settings import Settings

logger = logging.getLogger("selkies_tpu.webrtc_main")


async def _amain(settings: Settings) -> int:
    from ..input import InputHandler, open_clipboard_backend, open_x11_backend
    from ..rtc import HMACRTCMonitor, SignalingServer
    from .webrtc_app import WebRTCStreamingApp

    from . import bundled_web_root

    signaling = SignalingServer(
        addr="0.0.0.0", port=int(settings.web_port),
        web_root=bundled_web_root(),
        turn_shared_secret=str(settings.turn_shared_secret),
        turn_host=str(settings.turn_host),
        turn_port=str(settings.turn_port),
    )
    tasks = [asyncio.create_task(signaling.run())]

    input_handler = None
    try:
        input_handler = InputHandler(
            backend=open_x11_backend(),
            clipboard=open_clipboard_backend(),
        )
    except Exception as e:
        logger.warning("input plane disabled: %s", e)

    app = WebRTCStreamingApp(settings, input_handler=input_handler)

    if input_handler is not None:
        # clipboard poll → JSON control object on the input data channel
        # (the browser peer's webrtc.js onmessage handler; parity with
        # the legacy send_clipboard helper, gstwebrtc_app.py:1371-1471)
        import base64

        last_clip = {"msg": None}

        async def _clip_out(data: bytes, mime: str) -> None:
            if mime != "text/plain":
                # the WebRTC control channel carries text clipboard only
                # for now; log instead of silently absorbing the read
                # (the poll's dedup would otherwise suppress a re-copy)
                logger.info("dropping non-text clipboard (%s, %d bytes) "
                            "on the WebRTC control channel", mime,
                            len(data))
                return
            msg = {"type": "clipboard",
                   "data": base64.b64encode(data).decode()}
            # cache: content read before the data channel opens (or
            # between sessions) is re-sent on the next channel open
            # instead of being lost to the poll's dedup
            last_clip["msg"] = msg
            app.send_json(msg)

        def _on_input_open() -> None:
            if last_clip["msg"] is not None:
                app.send_json(last_clip["msg"])

        input_handler.on_clipboard_read = _clip_out
        app.on_input_channel_open = _on_input_open
        tasks.append(asyncio.create_task(
            input_handler.run_clipboard_poll()))

    if str(settings.turn_shared_secret) and str(settings.turn_host):
        monitor = HMACRTCMonitor(
            str(settings.turn_host), str(settings.turn_port),
            str(settings.turn_shared_secret), "selkies")
        monitor.on_rtc_config = lambda stun, turn, cfg: logger.info(
            "RTC config refreshed (%d stun, %d turn)", len(stun), len(turn))
        tasks.append(asyncio.create_task(monitor.start()))

    uri = f"ws://127.0.0.1:{settings.web_port}/ws"
    # the server registers as peer "0" and calls the browser peer "1"
    # (legacy peer-numbering, webrtc.py:563-575); retry while no peer yet
    while True:
        try:
            await app.run(uri, "0", "1")
        except Exception:
            logger.exception("webrtc session ended; retrying in 2s")
        await app.stop_pipeline()
        await asyncio.sleep(2.0)
    return 0


def main() -> int:
    settings = Settings(argv=sys.argv[1:], env=dict(os.environ))
    logging.basicConfig(
        level=logging.DEBUG if settings.debug.value else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    try:
        return asyncio.run(_amain(settings))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
