"""WebRTC streaming session: tpuenc video + Opus audio + input data channel
over the in-repo WebRTC stack.

Role parity with the reference's legacy pipeline builder + orchestrator
(``legacy/gstwebrtc_app.py`` — webrtcbin, 14 encoder branches, data
channel; ``legacy/webrtc.py:330-980`` — signaling wiring, RTC config,
bitrate handlers), redesigned: the encoder is the TPU H.264 stripe encoder
in full-frame mode, the transport is :mod:`selkies_tpu.webrtc`, and the
signaling grammar is the same HELLO/SESSION + JSON sdp/ice the reference
speaks (``legacy/webrtc_signalling.py``), so either side can be swapped.

Flow (caller role, like the reference: the streaming server initiates):
  signaling HELLO → SESSION <peer> → SESSION_OK → create offer →
  {"sdp": offer} → {"sdp": answer} from browser → ICE → DTLS-SRTP →
  media tasks pump frames; "input" data channel feeds the input handler.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, List, Optional

import numpy as np

from ..audio.capture import AudioCaptureSettings, open_source
from ..audio.codec import OpusEncoder, opus_available
from ..webrtc.peerconnection import PeerConnection
from ..rtc.signaling_client import SignalingClient

logger = logging.getLogger("selkies_tpu.server.webrtc_app")

VIDEO_CLOCK = 90000
OPUS_CLOCK = 48000
FRAME_MS = 20


def bitrate_to_qp(bps: int) -> int:
    """Map a congestion-control bitrate to an H.264 QP.

    Monotone heuristic calibrated around the reference's defaults: 8 Mbps
    (legacy default, webrtc.py:466) ≈ QP 26 (our encoder default); each
    halving of bitrate costs ~4 QP, clamped to [18, 46]."""
    if bps <= 0:
        return 46
    qp = 26 - 4.0 * np.log2(bps / 8_000_000)
    return int(np.clip(round(qp), 18, 46))


class WebRTCStreamingApp:
    def __init__(
        self,
        settings,
        encoder_factory: Optional[Callable] = None,
        source_factory: Optional[Callable] = None,
        audio_settings: Optional[AudioCaptureSettings] = None,
        input_handler=None,
        interfaces: Optional[List[str]] = None,
    ):
        self.settings = settings
        self.input_handler = input_handler
        self.interfaces = interfaces
        self.width = getattr(settings, "initial_width", 1280)
        self.height = getattr(settings, "initial_height", 720)
        # the real Settings exposes framerate as a RangeValue (allowed
        # range + default); plain numbers (tests, embedders) pass through
        fr = getattr(settings, "framerate", 60)
        self.framerate = float(getattr(fr, "default", fr))
        self.encoder_factory = encoder_factory or self._default_encoder
        self.source_factory = source_factory or self._default_source
        self.audio_settings = audio_settings or AudioCaptureSettings()

        self.pc: Optional[PeerConnection] = None
        self.signaling: Optional[SignalingClient] = None
        #: fired when the input data channel opens (webrtc_main re-sends
        #: the cached clipboard so pre-connect content isn't lost)
        self.on_input_channel_open: Optional[Callable[[], None]] = None
        self.encoder = None
        self.source = None
        self.input_channel = None
        self._tasks: List[asyncio.Task] = []
        self._running = False
        self.frames_sent = 0
        self.current_qp: Optional[int] = None

    # ------------------------------------------------------- factories

    def _default_encoder(self, width: int, height: int):
        from ..encoder.h264 import H264StripeEncoder

        pad16 = -(-height // 16) * 16
        return H264StripeEncoder(width, height, stripe_height=pad16)

    def _default_source(self, width: int, height: int, fps: float):
        from ..capture.x11 import X11Source
        from ..capture.synthetic import SyntheticSource

        if X11Source.available():
            return X11Source(width, height, fps)
        return SyntheticSource(width, height, fps, pattern="desktop")

    # ------------------------------------------------------- signaling

    async def run(self, signaling_uri: str, uid: str, peer_id: str) -> None:
        """Register with the signaling server and stream to ``peer_id``."""
        self.signaling = SignalingClient(signaling_uri, uid, peer_id)
        self.signaling.on_connect = self.signaling.setup_call
        self.signaling.on_session = lambda pid, meta: asyncio.ensure_future(
            self.start_pipeline())
        self.signaling.on_sdp = self._on_sdp
        self.signaling.on_ice = self._on_ice
        await self.signaling.connect()
        await self.signaling.start()

    async def _on_sdp(self, sdp_type: str, sdp: str) -> None:
        if sdp_type == "answer" and self.pc is not None:
            await self.pc.set_remote_description(sdp, "answer")

    async def _on_ice(self, mlineindex: int, candidate: str) -> None:
        if self.pc is not None and candidate:
            self.pc.add_ice_candidate(candidate)

    # -------------------------------------------------------- pipeline

    async def start_pipeline(self) -> None:
        """Build the session: encoder + pc + senders + offer (parity with
        GSTWebRTCApp.start_pipeline, gstwebrtc_app.py:1676)."""
        self.pc = PeerConnection(interfaces=self.interfaces)
        self.video_sender = self.pc.add_video_sender()
        fec_pct = int(getattr(self.settings, "video_packetloss_percent", 0))
        if fec_pct > 0:
            self.video_sender.enable_fec(fec_pct)
        self.audio_sender = self.pc.add_audio_sender()
        self.input_channel = self.pc.create_data_channel(
            "input", ordered=True, max_retransmits=0)
        self.input_channel.on_message = self._on_input_message
        self.input_channel.on_open = lambda: (
            self.on_input_channel_open and self.on_input_channel_open())
        self.pc.on_bitrate = self.set_video_bitrate
        self.pc.on_keyframe_request = self._on_keyframe_request

        self.encoder = self.encoder_factory(self.width, self.height)
        self.source = self.source_factory(
            self.width, self.height, self.framerate)

        offer = await self.pc.create_offer()
        if self.signaling is not None:
            await self.signaling.send_sdp("offer", offer)
        self._running = True
        self._tasks = [asyncio.create_task(self._video_loop())]
        if opus_available():
            self._tasks.append(asyncio.create_task(self._audio_loop()))

    async def stop_pipeline(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self.pc is not None:
            await self.pc.close()
            self.pc = None

    # ----------------------------------------------------- media loops

    async def _video_loop(self) -> None:
        await self.pc.wait_connected()
        t0 = time.monotonic()
        # dispatch/harvest-capable encoders run pipelined so device
        # latency hides behind the frame interval; fakes/others stay
        # synchronous
        pipe = None
        if hasattr(self.encoder, "dispatch"):
            from ..encoder.pipeline import PipelinedH264Encoder

            pipe = PipelinedH264Encoder(self.encoder, depth=3,
                                        fetch_group=1)

        def _send(seq: int, stripes) -> None:
            if not stripes or not self._running:
                return
            au = b"".join(s.annexb for s in stripes)
            # timestamps advance per encoded frame, not per wall-clock
            # send instant: poll() can deliver several frames in one tick
            # and identical RTP timestamps would merge distinct AUs
            ts = int(seq * VIDEO_CLOCK / max(self.framerate, 1.0))
            self.video_sender.send_frame(au, ts)
            self.frames_sent += 1

        sync_seq = 0
        try:
            while self._running:
                start = time.monotonic()
                frame = self.source.next_frame()
                if pipe is None:
                    if frame is not None:
                        stripes = await asyncio.to_thread(
                            self.encoder.encode_frame, frame)
                        _send(sync_seq, stripes)
                        sync_seq += 1
                else:
                    # poll-then-submit every tick: completed frames ship
                    # even when capture hiccups, and draining first frees
                    # a pipeline slot the new frame would otherwise lose
                    def tick(f=frame):
                        done = pipe.poll()
                        if f is not None:
                            pipe.try_submit(f)
                        return done
                    for seq, stripes in await asyncio.to_thread(tick):
                        _send(seq, stripes)
                elapsed = time.monotonic() - start
                await asyncio.sleep(
                    max(0.0, 1.0 / max(self.framerate, 1.0) - elapsed))
        finally:
            if pipe is not None:
                # teardown arrives as a task cancellation: drain what the
                # device already produced (sends are gated on _running)
                for seq, stripes in await asyncio.shield(
                        asyncio.to_thread(pipe.flush)):
                    _send(seq, stripes)

    async def _audio_loop(self) -> None:
        await self.pc.wait_connected()
        settings = self.audio_settings
        src = open_source(settings)
        # in-band FEC on the lossy (SRTP) path, like the reference's
        # opusenc inband-fec=true (legacy/gstwebrtc_app.py:1048): the
        # receiver recovers a lost 20 ms frame from the next packet
        enc = OpusEncoder(settings.sample_rate, settings.channels,
                          settings.opus_bitrate, inband_fec=True)
        frames = settings.sample_rate * FRAME_MS // 1000
        ts = 0
        try:
            while self._running:
                pcm = await asyncio.to_thread(src.read_chunk, frames)
                if pcm is None:
                    await asyncio.sleep(FRAME_MS / 1000)
                    continue
                packet = enc.encode(pcm)
                if packet:
                    self.audio_sender.send_frame(packet, ts)
                ts += frames
        finally:
            src.close()
            enc.close()

    # ------------------------------------------------------- control

    def set_video_bitrate(self, bps: int) -> None:
        """Congestion-control feedback → encoder QP (parity with
        set_video_bitrate, gstwebrtc_app.py:1269, fed by rtpgccbwe)."""
        qp = bitrate_to_qp(bps)
        if qp != self.current_qp and self.encoder is not None:
            self.current_qp = qp
            if hasattr(self.encoder, "qp"):
                self.encoder.qp = qp

    def set_framerate(self, fps: float) -> None:
        self.framerate = float(np.clip(fps, 1, 120))

    def _on_keyframe_request(self) -> None:
        if self.encoder is not None and hasattr(self.encoder,
                                                "request_keyframe"):
            self.encoder.request_keyframe()

    def _on_input_message(self, data: bytes) -> None:
        """Input-plane messages from the browser data channel (parity with
        the legacy data channel → WebRTCInput.on_message path)."""
        if self.input_handler is None:
            return
        try:
            msg = data.decode()
        except UnicodeDecodeError:
            return
        result = self.input_handler.on_message(msg)
        if asyncio.iscoroutine(result):
            asyncio.ensure_future(result)

    def send_json(self, obj) -> None:
        """Server→client control message over the input channel (parity
        with the legacy send_clipboard/cursor data-channel helpers,
        gstwebrtc_app.py:1371-1471)."""
        import json

        if self.input_channel is not None and self.input_channel.open:
            self.input_channel.send(json.dumps(obj))
