"""The WebSocket data/control server.

Behavioral counterpart of the reference's ``DataStreamingServer``
(selkies.py:803-2964): one asyncio server owning the client registry,
settings negotiation, per-display capture/encode pipelines, the frame-ID
backpressure gate, file upload, and the periodic stats feed. The media path
differs by design: instead of pixelflux C++ threads pushing encoded stripes
through a queue, each display runs an asyncio capture loop that submits raw
frames to the pipelined TPU encoder and broadcasts the harvested stripes.

Concurrency model (same invariant as the reference, SURVEY.md §5): a single
asyncio loop owns all mutable state; the TPU pipeline is driven with
non-blocking submits/polls from that loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Set, Tuple

from ..protocol.wire import (
    FrameId,
    ProtocolError,
    pack_full_frame,
    pack_h264_stripe,
    pack_jpeg_stripe,
    pack_system_health,
    parse_text_message,
    unpack_client_binary,
)
from ..observability.tracing import FlightRecorder
from ..robustness import (
    FAILED,
    UPLOAD_VERB_COST,
    BoundedSendQueue,
    ConnectionGuard,
    DegradationLadder,
    EncoderFault,
    FaultInjector,
    Supervisor,
    backoff_delay,
    classify_verb,
    parse_limit_spec,
)
from ..settings import SETTING_DEFINITIONS, Settings
from .backpressure import CHECK_INTERVAL_S, BackpressureState

logger = logging.getLogger("selkies_tpu.server")

STATS_INTERVAL_S = 5.0
UPLOAD_DIR_ENV = "SELKIES_UPLOAD_DIR"

#: largest accepted client display dimension: an unbounded resize request
#: is a memory bomb (the capture source allocates width*height*3 per
#: frame); 8192 covers 8K while keeping one frame under ~200 MB
MAX_DISPLAY_DIM = 8192

#: bounded mesh geometry-bucket count: each bucket's lanes hold device
#: prev planes for all their slots. Joins past the cap are served by
#: solo pipelines — the admission verdict and the acquire-time fallback
#: must agree on this number, or verdicts shed clients solo could serve.
MESH_BUCKET_CAP = 4


def _clamp_dim(v: int) -> int:
    """Clamp a client-requested display dimension to [16, MAX] and even."""
    return min(MAX_DISPLAY_DIM, max(16, int(v) & ~1))


def _ws_broadcast(targets, message) -> None:
    """Fan one message out to many clients.

    Real websockets go through ``websockets.broadcast`` (non-blocking,
    drops slow consumers at the transport layer). Targets exposing a
    synchronous ``send_nowait`` are served directly instead — that keeps
    the whole data plane drivable by in-process fakes on hosts without the
    websockets package (fault-injection tests, tools/chaos_run.py) and
    open to alternative transports."""
    real = []
    for t in targets:
        fn = getattr(t, "send_nowait", None)
        if fn is not None:
            try:
                fn(message)
            except Exception:
                logger.debug("send_nowait target failed", exc_info=True)
        else:
            real.append(t)
    if real:
        import websockets

        websockets.broadcast(real, message)


class _TracedChunk:
    """A media chunk carrying its frame's flight-recorder trace through
    the owner's send queue: only the LAST stripe of a frame rides traced
    (the frame is decodable when that stripe lands), so queue/send/ack
    measure the whole frame without N-stripe double counting."""

    __slots__ = ("payload", "trace", "t_offer")

    def __init__(self, payload, trace, t_offer: float) -> None:
        self.payload = payload
        self.trace = trace
        self.t_offer = t_offer

    def __len__(self) -> int:       # byte accounting parity with bytes
        return len(self.payload)


class _ClientSendQueue:
    """Asyncio drainer around a :class:`BoundedSendQueue` for one client.

    The fan-out path offers into the bounded queue (synchronous, never
    blocks the capture loop); this drainer task awaits the transport's
    real ``send`` so per-client flow control backs up into the queue —
    where drop-oldest-video and the eviction verdict live — instead of
    into the shared event loop.

    Flight-recorder duty (ISSUE 13): a :class:`_TracedChunk` passing
    through here closes the frame's ``queue`` and ``send`` stages and
    registers the span for ACK correlation; every way a traced chunk can
    die (drop-oldest overflow, a raising transport send, queue teardown)
    lands a terminal ``dropped@`` mark instead of leaking the span."""

    def __init__(self, ws, q: BoundedSendQueue, on_evict,
                 recorder: Optional[FlightRecorder] = None) -> None:
        self.ws = ws
        self.q = q
        self.evicted = False
        self._on_evict = on_evict
        self._recorder = recorder
        # drop-oldest may discard a traced chunk: its span must close
        q.on_drop = self._on_video_dropped
        self._wake = asyncio.Event()
        self.task = asyncio.create_task(self._drain())

    def _on_video_dropped(self, message) -> None:
        if isinstance(message, _TracedChunk) and self._recorder is not None:
            self._recorder.drop(message.trace, "queue")

    def offer(self, message, control: bool) -> None:
        self.q.offer(message, control=control)
        self._wake.set()
        if not self.evicted and self.q.should_evict:
            self.evicted = True
            self._on_evict(self)

    def offer_traced(self, payload, trace) -> None:
        """Queue the frame's last stripe with its trace attached (the
        queue stage opens now; the drainer closes it at pop time)."""
        self.offer(_TracedChunk(payload, trace, time.monotonic()),
                   control=False)

    async def _send_one(self, message) -> None:
        if not isinstance(message, _TracedChunk):
            await self.ws.send(message)
            return
        tr = message.trace
        now = time.monotonic()
        tr.mark("queue", message.t_offer, now)
        # register for ACK correlation BEFORE the await: under write
        # backpressure the payload can reach the client (and its ACK the
        # reader task) while this coroutine is still suspended in send —
        # exactly the frames glass_to_glass_ms exists to observe. An ack
        # racing the send closes the span from the queue-exit mark; the
        # RTT then includes the transport write, which is honest.
        if self._recorder is not None:
            self._recorder.sent(tr)
        try:
            await self.ws.send(message.payload)
        except BaseException:
            # transport death / cancellation mid-send: terminal mark,
            # then let the existing error handling decide the session
            if self._recorder is not None and tr.terminal is None:
                self._recorder.drop(tr, "send")
            raise
        if tr.terminal is None:
            tr.mark("send", now, time.monotonic())

    async def _drain(self) -> None:
        try:
            while True:
                await self._wake.wait()
                self._wake.clear()
                while True:
                    message = self.q.pop()
                    if message is None:
                        break
                    await self._send_one(message)
        except asyncio.CancelledError:
            raise
        except Exception:
            # the connection died mid-send; ws_handler's cleanup owns the
            # socket, the drainer just stops
            logger.debug("send-queue drain ended", exc_info=True)

    def close(self) -> None:
        if self.task is not None and not self.task.done():
            self.task.cancel()
        # spans queued behind the cancellation point must still close
        while True:
            message = self.q.pop()
            if message is None:
                break
            self._on_video_dropped(message)


def upload_dir() -> str:
    """The file-manager root (uploads land here; /files serves it) —
    reference FILE_MANAGER_PATH, selkies.py:98-103."""
    d = os.environ.get(UPLOAD_DIR_ENV) or os.path.join(
        os.path.expanduser("~"), "Desktop")
    os.makedirs(d, exist_ok=True)
    return d


def default_encoder_factory(
    width: int, height: int, settings: Settings,
    overrides: Optional[Dict[str, Any]] = None,
):
    """Encoder-profile selection (parity: the reference's encoder enum,
    settings.py 'encoder' / pixelflux output_mode): ``jpeg`` is the
    device-entropy striped pipeline; ``x264enc-striped``/``x264enc`` are
    the TPU H.264 profiles (striped / one full-frame stripe). CRF settings
    map onto the QP scale (both 0-51).

    The degradation ladder (docs/robustness.md) rides the ``tpu_entropy``
    override: ``host`` builds the encoder with host-side entropy coding;
    the ladder's last rung additionally forces ``encoder=jpeg``. Entropy is
    fixed at construction (the device programs are compiled per tier), so a
    rung change takes effect as a supervised pipeline restart.

    Device-entropy tiers ride the async pipeline driver (ISSUE 12,
    docs/pipeline.md): a dedicated thread keeps >=2 batches in flight —
    dispatch of batch N+1 overlapped with batch N's D2H fetch — so the
    capture loop's submit/poll never touch the device and the served
    encode latency tracks the chip, not the round-trip floor. Host
    rungs keep the threaded adapter (their encode is synchronous by
    construction)."""
    from ..encoder.async_driver import AsyncEncodeDriver
    from ..encoder.jpeg import JpegStripeEncoder
    from ..encoder.pipeline import (PipelinedH264Encoder,
                                    PipelinedJpegEncoder,
                                    ThreadedEncoderAdapter)

    #: frames encoded per device dispatch; >1 amortizes the fixed
    #: dispatch RPC on tunneled transports at a latency cost — PCIe
    #: deployments keep 1 (the re-armed batch deadline still bounds
    #: staleness either way)
    batch = max(1, int(os.environ.get("SELKIES_TPU_ASYNC_BATCH", "1")))

    ov = overrides or {}
    profile = ov.get("encoder", settings.encoder)
    #: None → the encoder's own default (H.264 honors the
    #: SELKIES_TPU_H264_ENTROPY env tier selection; JPEG is device)
    entropy = ov.get("tpu_entropy")
    if profile in ("x264enc", "x264enc-striped"):
        from ..encoder.h264 import H264StripeEncoder

        if str(settings.watermark_path):
            logger.warning(
                "watermark is implemented in the JPEG profile only; the "
                "H.264 profiles ignore watermark_path for now")
        crf = int(ov.get("h264_crf", settings.h264_crf.default))
        paint_crf = int(ov.get("h264_paintover_crf",
                               settings.h264_paintover_crf.default))
        even_w, even_h = width - width % 2, height - height % 2
        base = H264StripeEncoder(
            even_w, even_h,
            stripe_height=int(settings.tpu_stripe_height),
            qp=crf, paint_over_qp=paint_crf,
            fullframe=(profile == "x264enc"),
            entropy=entropy,
        )
        if base.entropy != "device":
            # host-entropy rung: harvest is CPU-bound host CAVLC, the
            # threaded adapter's one worker is the right shape for it
            return ThreadedEncoderAdapter(
                base, depth=3, wire_fullframe=(profile == "x264enc"))
        return AsyncEncodeDriver(
            PipelinedH264Encoder(base, depth=max(4, 3 * batch),
                                 fetch_group=2, batch=batch),
            flush_partial_when_idle=(batch == 1),
            wire_fullframe=(profile == "x264enc"))
    base = JpegStripeEncoder(
        width,
        height,
        stripe_height=settings.tpu_stripe_height,
        quality=ov.get("jpeg_quality", settings.jpeg_quality.default),
        paintover_quality=ov.get(
            "paint_over_jpeg_quality",
            settings.paint_over_jpeg_quality.default),
        use_paint_over_quality=ov.get(
            "use_paint_over_quality",
            settings.use_paint_over_quality.value),
        entropy=entropy or "device",
        watermark_path=str(settings.watermark_path),
        watermark_location=int(settings.watermark_location),
    )
    if base.entropy != "device":
        # degraded rung: host entropy coding can't ride the device-packed
        # pipeline, so the synchronous encode_frame path runs off-loop in
        # the threaded adapter instead
        return ThreadedEncoderAdapter(base, depth=3)
    return AsyncEncodeDriver(
        PipelinedJpegEncoder(base, depth=4, fetch_group=2))


def default_source_factory(width: int, height: int, fps: float,
                           x: int = 0, y: int = 0):
    from ..capture.x11 import X11Source
    from ..capture.synthetic import SyntheticSource

    if X11Source.available():
        return X11Source(width, height, fps, x=x, y=y)
    return SyntheticSource(width, height, fps, pattern="desktop")


@dataclass
class DisplayState:
    display_id: str
    ws: Any = None
    width: int = 1024
    height: int = 768
    #: framebuffer offset of this display (set by _apply_x11_layout)
    x: int = 0
    y: int = 0
    bp: BackpressureState = field(default_factory=BackpressureState)
    #: serializes start/stop/reconfigure (they await mid-flight, so two
    #: concurrent calls could otherwise both pass the is-running guard and
    #: spawn duplicate capture loops)
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    capture_task: Optional[asyncio.Task] = None
    backpressure_task: Optional[asyncio.Task] = None
    #: supervisors owning the two loops above (ISSUE 2): crash restarts
    #: with bounded backoff, frame-deadline watchdog, restart budget
    supervisor: Optional[Supervisor] = None
    bp_supervisor: Optional[Supervisor] = None
    #: encoder degradation state (device -> host -> jpeg); persists across
    #: supervised restarts and reconfigures — it is display health, not
    #: pipeline state
    ladder: DegradationLadder = field(default_factory=DegradationLadder)
    #: sticky terminal marker: the capture supervisor exhausted its restart
    #: budget and the pipeline was torn down (cleared by an explicit
    #: START_VIDEO / reconfigure restart)
    failed: bool = False
    #: wedge faults at the bottom rung (nowhere left to degrade): each
    #: restart of a hung encoder can abandon a blocked worker thread, so
    #: these are bounded — a few strikes and the display goes terminal
    wedge_faults: int = 0
    video_active: bool = True
    #: clamped per-client setting overrides from the SETTINGS handshake
    overrides: Dict[str, Any] = field(default_factory=dict)
    #: live encoder of the running capture loop (keyframe kicks)
    encoder: Any = None
    #: (w, h, x, y) the running pipeline was started with — scoped
    #: reconfiguration restarts only displays whose geometry changed
    running_geom: Optional[Tuple[int, int, int, int]] = None
    #: (overrides, framerate) snapshot at pipeline start: a SETTINGS
    #: change with unchanged geometry must still rebuild the encoder
    running_config: Optional[Tuple[Dict[str, Any], float]] = None


@dataclass
class _Upload:
    path: str
    rel_path: str  # as the client named it; echoed back in errors
    fobj: Any
    received: int = 0
    size: int = 0


class DataStreamingServer:
    def __init__(
        self,
        settings: Settings,
        app=None,
        encoder_factory: Callable = default_encoder_factory,
        source_factory: Callable = default_source_factory,
        input_handler=None,
        host: str = "0.0.0.0",
    ) -> None:
        self.settings = settings
        self.app = app
        self.input_handler = input_handler
        self.encoder_factory = encoder_factory
        self.source_factory = source_factory
        self.host = host
        self.port = settings.port

        self.clients: Set[Any] = set()
        self.display_clients: Dict[str, DisplayState] = {}
        self._uploads: Dict[Any, _Upload] = {}
        self._stats_task: Optional[asyncio.Task] = None
        self._server = None
        self._stop_event: Optional[asyncio.Event] = None
        self.bytes_sent = 0
        self.metrics = None         # wired by main() when prometheus is up
        self.audio_pipeline = None  # wired by main() when audio is enabled
        self._audio_wanted = True   # cleared by STOP_AUDIO until re-requested
        self._last_layout = None    # last xrandr-applied Layout (dedup)
        #: mesh-batched encode (tpu_mesh setting, BASELINE configs 4/5):
        #: one coordinator per display geometry, lazily built — a
        #: mismatched-resolution join gets its own bucket instead of a
        #: silent solo fallback (VERDICT r2 item 6)
        self.mesh_coordinators: Dict[Tuple[int, int, str], Any] = {}
        #: coordinator constructor override (tests / tools/swarm_run.py):
        #: same signature as MeshEncodeCoordinator — lets harnesses run
        #: the real scheduler over injected (device-free) encoders
        self.coordinator_factory: Optional[Callable] = None
        #: geometries whose coordinator construction failed — scoped per
        #: geometry so one bad bucket (e.g. a transient OOM at 4K) does
        #: not disable mesh batching for healthy buckets
        self._mesh_failed_geoms: Set[Tuple[int, int, str]] = set()
        #: counters surfaced in the stats JSON so mesh fallbacks are
        #: observable, not silent
        self.mesh_stats = {"bucketed": 0, "solo_fallback": 0}
        #: fault-injection registry for this server (docs/robustness.md):
        #: armed from the tpu_faults setting / SELKIES_TPU_FAULTS env and
        #: checked at the real capture/encode/fetch/ws call sites
        self.faults = FaultInjector(str(getattr(settings, "tpu_faults", "")
                                        or ""))
        #: frame flight recorder (ISSUE 13, docs/observability.md): every
        #: served frame's capture→ack stage timeline, exported via the
        #: metrics endpoint (/debug/trace), the system_health feed, and
        #: the per-stage Prometheus histograms. Always on — marking a
        #: trace is a few dict stores per frame.
        self.recorder = FlightRecorder(capacity=4096)
        #: fire-and-forget helpers (ws.drop closes, failed-display
        #: teardown) — referenced so they are neither GC'd mid-flight nor
        #: left to warn "exception was never retrieved"
        self._bg_tasks: Set[asyncio.Task] = set()
        # --- wire-edge hardening (ISSUE 3, docs/hardening.md) ---
        #: per-class rate limits; a bad rate_limits spec fails construction
        #: loudly, like a bad fault spec
        self._limits = parse_limit_spec(
            str(getattr(settings, "rate_limits", "") or ""))
        #: per-connection protocol armor (error budget + class buckets)
        self._guards: Dict[Any, ConnectionGuard] = {}
        #: per-client bounded send queues wrapped around the fan-out path
        self._send_queues: Dict[Any, _ClientSendQueue] = {}
        #: local mirrors of the edge metrics so behavior is assertable
        #: without prometheus (rate_limited is per message class)
        self.edge_stats: Dict[str, Any] = {
            "protocol_errors": 0,
            "rate_limited": {},
            "upload_paced": 0,
            "sessions_rejected": 0,
            "sessions_queued": 0,
            "slow_client_evictions": 0,
            "reconfigure_runs": 0,
            "reconfigure_coalesced": 0,
        }
        #: debounced/serialized display reconfiguration: a resize storm
        #: coalesces into one stop-the-world reconfigure, not one per message
        self._reconfig_task: Optional[asyncio.Task] = None
        self._reconfig_dirty = False
        #: admission-control load shedding (driven by sustained encoder
        #: drops observed in the stats loop)
        self._load_shedding = False
        self._shed_strikes = 0
        self._last_dropped_total = 0

    @property
    def mesh_coordinator(self):
        """First (primary-geometry) coordinator — back-compat accessor."""
        return next(iter(self.mesh_coordinators.values()), None)

    # ------------------------------------------------------------------
    # broadcast primitives

    def broadcast(self, message) -> None:
        if self.clients:
            self._fanout(self.clients, message)
            if isinstance(message, (bytes, bytearray)):
                self.bytes_sent += len(message) * len(self.clients)

    def _fanout(self, targets, message) -> None:
        """Fan one message out through the per-client bounded send queues
        (docs/hardening.md): text is control (never dropped), binary media
        is droppable — a slow consumer converges to the live edge of the
        stream or is evicted, and never stalls the capture loop. Targets
        without a queue (added outside ws_handler, or mid-handshake) get
        the direct transport broadcast."""
        control = isinstance(message, str)
        direct = []
        for t in targets:
            cq = self._send_queues.get(t)
            if cq is None:
                direct.append(t)
            elif not cq.evicted:
                cq.offer(message, control)
        if direct:
            _ws_broadcast(direct, message)

    def _evict_slow_client(self, cq: _ClientSendQueue) -> None:
        """Sustained send-queue overflow: this consumer is not keeping up
        and dropping video no longer helps — close its one socket (with a
        best-effort KILL) so its backlog stops costing memory."""
        self.edge_stats["slow_client_evictions"] += 1
        if self.metrics is not None:
            self.metrics.inc_slow_client_eviction()
        logger.warning(
            "evicting slow consumer: queue depth %d, %d video drops",
            len(cq.q), cq.q.dropped_video_total)
        cq.close()   # the drainer may be wedged inside a stalled send
        ws = cq.ws

        async def _kill():
            try:
                await asyncio.wait_for(ws.send("KILL slow_consumer"), 1.0)
            except Exception:
                pass
            await ws.close()

        self._spawn_background(_kill(), "evict-slow-client")

    def _viewers_of(self, display_id: str) -> Set[Any]:
        """Primary-display media is fanned out to every client (sharing
        modes); secondary displays go only to their owning client."""
        if display_id == "primary":
            return set(self.clients)
        st = self.display_clients.get(display_id)
        return {st.ws} if st and st.ws else set()

    # ------------------------------------------------------------------
    # lifecycle

    #: bind-retry policy: capped exponential backoff with jitter, then a
    #: hard error — an occupied port must fail loudly, not retry at a
    #: fixed 1 Hz forever (class attributes so tests can shrink them)
    BIND_MAX_ATTEMPTS = 8
    BIND_BASE_DELAY_S = 0.5
    BIND_MAX_DELAY_S = 10.0

    async def run_server(self) -> None:
        """Serve until stop() — with crash-restart supervision like the
        reference's run loop (selkies.py:2453-2510)."""
        import websockets.asyncio.server as ws_server

        self._stop_event = asyncio.Event()
        bind_attempts = 0
        # transport-level armor: an unbounded max_size lets one client
        # frame buffer arbitrary memory before any handler runs
        cap_mb = int(getattr(self.settings, "max_ws_message_mb", 0))
        max_size = cap_mb * 1024 * 1024 if cap_mb > 0 else None
        while not self._stop_event.is_set():
            try:
                async with ws_server.serve(
                    self.ws_handler, self.host, self.port,
                    compression=None, max_size=max_size,
                ) as server:
                    self._server = server
                    bind_attempts = 0
                    logger.info("data server listening on %s:%d", self.host, self.port)
                    await self._stop_event.wait()
            except OSError as e:
                bind_attempts += 1
                if bind_attempts >= self.BIND_MAX_ATTEMPTS:
                    raise RuntimeError(
                        f"data server could not bind {self.host}:{self.port}"
                        f" after {bind_attempts} attempts: {e}") from e
                delay = backoff_delay(bind_attempts, self.BIND_BASE_DELAY_S,
                                      self.BIND_MAX_DELAY_S, jitter=0.25)
                logger.error("server bind failed (%s); retry %d/%d in %.1fs",
                             e, bind_attempts, self.BIND_MAX_ATTEMPTS, delay)
                await asyncio.sleep(delay)

    async def stop(self) -> None:
        if self._reconfig_task is not None and not self._reconfig_task.done():
            self._reconfig_task.cancel()
        for cq in list(self._send_queues.values()):
            cq.close()
        self._send_queues.clear()
        for st in list(self.display_clients.values()):
            await self._stop_display(st)
        for coord in self.mesh_coordinators.values():
            coord.stop()
        self.mesh_coordinators.clear()
        if self.audio_pipeline is not None:
            await self.audio_pipeline.stop()
            self.audio_pipeline.close()
        if self._stats_task:
            self._stats_task.cancel()
        if self._stop_event:
            self._stop_event.set()

    # ------------------------------------------------------------------
    # connection handling

    async def _admit(self, websocket) -> bool:
        """Admission control at accept time (docs/hardening.md): a full or
        load-shedding server rejects the connection gracefully — a wire
        KILL the client UI can show — instead of degrading every session."""
        maxc = int(getattr(self.settings, "max_clients", 0) or 0)
        full = bool(maxc and len(self.clients) >= maxc)
        if not full and not self._load_shedding:
            return True
        self.edge_stats["sessions_rejected"] += 1
        if self.metrics is not None:
            self.metrics.inc_sessions_rejected()
        logger.warning("connection rejected: %s",
                       "server_full" if full else "load_shedding")
        try:
            await websocket.send("KILL server_full")
        except Exception:
            pass
        try:
            await websocket.close()
        except Exception:
            pass
        return False

    # -- display-plane admission: scheduler verdicts (docs/scaling.md) --

    def _mesh_profile_of(self, overrides: Dict[str, Any]) -> str:
        return str(overrides.get("encoder", self.settings.encoder))

    def _display_admission_verdict(self, width: int, height: int,
                                   overrides: Dict[str, Any]) -> str:
        """``admit`` / ``queue`` / ``shed`` for a NEW display join.

        The flat ``max_displays`` cap is the hard backstop; below it the
        verdict comes from live lane capacity: a join whose geometry
        bucket has a free or growable slot is admitted, a join into a
        momentarily-full scheduler queues (leave/resize churn frees slots
        within the queue window), and a genuinely full scheduler sheds.
        Displays the mesh cannot serve (solo-only profiles, watermark,
        failed geometries) are admitted toward their solo pipelines, and
        ``mesh_overflow_solo`` restores the pre-scheduler overflow-to-solo
        behavior wholesale."""
        if self._load_shedding:
            return "shed"
        maxd = int(getattr(self.settings, "max_displays", 0) or 0)
        if maxd and len(self.display_clients) >= maxd:
            return "shed"
        if not str(self.settings.tpu_mesh) or \
                bool(getattr(self.settings, "mesh_overflow_solo", False)):
            return "admit"
        profile = self._mesh_profile_of(overrides)
        if profile not in ("jpeg", "x264enc-striped") or \
                str(self.settings.watermark_path):
            return "admit"          # solo-served by design, not overflow
        geom = (_clamp_dim(width), _clamp_dim(height), profile)
        coord = self.mesh_coordinators.get(geom)
        if coord is None:
            if geom in self._mesh_failed_geoms:
                return "admit"      # this geometry runs solo (scoped)
            # below the bucket cap a fresh bucket can be built; past it
            # the acquire path serves the join with a solo encoder by
            # design — admit toward that, never queue on a condition
            # that cannot resolve (buckets are not retired)
            return "admit"
        try:
            cap = coord.capacity()
        except Exception:
            return "admit"
        if cap["slots_free"] + cap["growable_slots"] > 0:
            return "admit"
        return "queue"

    async def _await_display_admission(self, width: int, height: int,
                                       overrides: Dict[str, Any]) -> str:
        """Hold a queued join for up to ``admission_queue_ms`` waiting for
        a scheduler slot to free (leave/resize churn), then resolve to
        admit or shed. Bounded by construction — a queued client is never
        parked forever."""
        self.edge_stats["sessions_queued"] += 1
        if self.metrics is not None:
            self.metrics.inc_sessions_queued()
        wait_ms = int(getattr(self.settings, "admission_queue_ms", 0) or 0)
        deadline = time.monotonic() + wait_ms / 1000.0
        while True:
            verdict = self._display_admission_verdict(
                width, height, overrides)
            if verdict != "queue":
                return verdict
            if time.monotonic() >= deadline:
                return "shed"
            await asyncio.sleep(0.025)

    def scheduler_stats(self) -> Optional[Dict[str, int]]:
        """Aggregate live lane capacity across geometry buckets (None
        when mesh batching is off) — the admission verdicts' input,
        surfaced for the stats feed and harnesses."""
        if not str(self.settings.tpu_mesh):
            return None
        agg = {"slots_free": 0, "growable_slots": 0, "slots_total": 0,
               "quarantined_slots": 0, "active_sessions": 0, "lanes": 0}
        for coord in self.mesh_coordinators.values():
            try:
                cap = coord.capacity()
            except Exception:
                continue
            for k in agg:
                agg[k] += int(cap.get(k, 0))
        return agg

    async def ws_handler(self, websocket) -> None:
        if not await self._admit(websocket):
            return
        self._guards[websocket] = ConnectionGuard(
            limits=self._limits,
            error_budget=int(getattr(self.settings,
                                     "protocol_error_budget", 25)))
        self.clients.add(websocket)
        if self.metrics is not None:
            self.metrics.set_clients(len(self.clients))
        # late-joining viewer (sharing modes): damage gating means static
        # content would never reach it — force the next frame to be a full
        # refresh / IDR on the primary stream
        primary = self.display_clients.get("primary")
        if primary is not None and primary.encoder is not None:
            kick = getattr(primary.encoder, "force_keyframe", None) \
                or getattr(primary.encoder, "request_keyframe", None)
            if kick is not None:
                kick()
        try:
            if (self.audio_pipeline is not None and self._audio_wanted
                    and not self.audio_pipeline.running):
                await self.audio_pipeline.start()
            await websocket.send("MODE websockets")
            if self.app and self.app.last_cursor_sent:
                await websocket.send(
                    "cursor," + json.dumps(self.app.last_cursor_sent))
            await websocket.send(json.dumps(self.settings.schema_payload()))
            # handshake done: fan-out to this client now rides its bounded
            # send queue (slow-consumer isolation + eviction)
            self._send_queues[websocket] = _ClientSendQueue(
                websocket,
                BoundedSendQueue(
                    max_video=int(self.settings.max_send_queue),
                    evict_after_s=float(int(
                        self.settings.slow_client_evict_s))),
                on_evict=self._evict_slow_client,
                recorder=self.recorder)
            if self._stats_task is None or self._stats_task.done():
                self._stats_task = asyncio.create_task(self._stats_loop())
            async for message in websocket:
                # Per-message exception boundary: a malformed or
                # handler-crashing message is dropped and charged against
                # this connection's error budget — it must never end the
                # async-for loop (= the whole session) the way a transport
                # error does, and never touch other clients' sessions.
                try:
                    if isinstance(message, (bytes, bytearray)):
                        await self._handle_binary(websocket, message)
                    else:
                        await self._handle_text(websocket, message)
                except Exception as e:
                    if (isinstance(e, ConnectionError)
                            or type(e).__name__.startswith(
                                "ConnectionClosed")):
                        # a handler failing to SEND to a dead peer is
                        # transport death, not client hostility: end the
                        # session (pre-boundary behavior) instead of
                        # polluting protocol_errors_total / the budget
                        raise
                    self.edge_stats["protocol_errors"] += 1
                    if self.metrics is not None:
                        self.metrics.inc_protocol_errors()
                    logger.debug("protocol error (dropped message): %r", e)
                    guard = self._guards.get(websocket)
                    if guard is not None and guard.record_error():
                        logger.warning(
                            "error budget exhausted after %d protocol "
                            "errors; killing abusive client",
                            guard.errors_total)
                        try:
                            await websocket.send("KILL protocol_abuse")
                        except Exception:
                            pass
                        await websocket.close()
                        break
        except Exception as e:  # connection errors end the session
            logger.debug("ws session ended: %r", e)
        finally:
            self.clients.discard(websocket)
            self._guards.pop(websocket, None)
            cq = self._send_queues.pop(websocket, None)
            if cq is not None:
                cq.close()
            if self.metrics is not None:
                self.metrics.set_clients(len(self.clients))
            up = self._uploads.pop(websocket, None)
            if up is not None:
                # never leak the fd or the partial file of an interrupted
                # upload (satellite: upload fd leak on disconnect)
                self._abort_upload(up)
                logger.info("upload aborted by disconnect: %s (%d/%d bytes)",
                            up.path, up.received, up.size)
            dropped = False
            for st in list(self.display_clients.values()):
                if st.ws is websocket:
                    # deregister FIRST: a concurrent reconfigure worker
                    # must see the display as gone before our stop lands,
                    # or it can restart a zombie pipeline that holds its
                    # scheduler slot forever (found by tools/swarm_run.py)
                    del self.display_clients[st.display_id]
                    await self._stop_display(st)
                    dropped = True
            if dropped and self.display_clients:
                # surviving displays reflow into a smaller framebuffer
                self._schedule_reconfigure()
            if (not self.clients and self.audio_pipeline is not None
                    and self.audio_pipeline.running):
                await self.audio_pipeline.stop()

    # ------------------------------------------------------------------
    # text protocol

    def _count_rate_limited(self, cls: str) -> None:
        counts = self.edge_stats["rate_limited"]
        counts[cls] = counts.get(cls, 0) + 1
        if self.metrics is not None:
            self.metrics.inc_rate_limited(cls)

    def _count_upload_paced(self) -> None:
        # pacing ACCEPTS the message after a sleep: a separate series so
        # a fast healthy upload never reads as "dropped by rate limiting"
        self.edge_stats["upload_paced"] += 1
        if self.metrics is not None:
            self.metrics.inc_upload_paced()

    async def _handle_text(self, websocket, message: str) -> None:
        msg = parse_text_message(message)   # ProtocolError → boundary
        verb = msg.verb

        guard = self._guards.get(websocket)
        if guard is not None:
            cls = classify_verb(verb)
            if cls == "upload":
                # stateful upload verbs are paced like upload bytes, never
                # dropped — a dropped FILE_UPLOAD_END leaves the fd open
                # and splices the next file into it
                wait = guard.throttle("upload", UPLOAD_VERB_COST)
                if wait > 0:
                    self._count_upload_paced()
                    await asyncio.sleep(wait)
            elif not guard.allow(cls):
                self._count_rate_limited(cls)
                logger.debug("rate-limited %s message %r", cls, verb[:32])
                return

        if verb == "SETTINGS":
            await self._on_settings(websocket, msg.json_body or "{}")
        elif verb == "CLIENT_FRAME_ACK":
            # Only the display's OWNER acks: a shared-mode viewer (or a
            # hostile client) feeding random ids into the primary's
            # backpressure state would wedge the gate for everyone.
            st = self._display_of(websocket)
            if st and st.ws is websocket and msg.args:
                try:
                    fid = int(msg.args[0])
                except ValueError:
                    pass
                else:
                    st.bp.on_client_ack(fid)
                    # the ACK closes the frame's flight span with the
                    # true network round trip (send end → ack arrival)
                    self.recorder.ack(st.display_id, fid)
        elif verb == "r" and len(msg.args) >= 1:
            await self._on_resize(websocket, msg.args)
        elif verb == "START_VIDEO":
            st = self._display_of(websocket)
            if st and st.ws is websocket:
                st.video_active = True
                await self._start_display(st)
                # through the send queue, like PIPELINE_RESETTING: the
                # reply must not overtake media already queued behind it
                self._fanout({websocket}, "VIDEO_STARTED")
        elif verb == "STOP_VIDEO":
            st = self._display_of(websocket)
            if st and st.ws is websocket:
                st.video_active = False
                await self._stop_display(st)
                self._fanout({websocket}, "VIDEO_STOPPED")
        elif verb == "START_AUDIO":
            self._audio_wanted = True
            if self.audio_pipeline is not None:
                await self.audio_pipeline.start()
                self.broadcast("AUDIO_STARTED")
        elif verb == "STOP_AUDIO":
            self._audio_wanted = False
            if self.audio_pipeline is not None:
                await self.audio_pipeline.stop()
                self.broadcast("AUDIO_STOPPED")
        elif verb == "FILE_UPLOAD_START":
            await self._on_upload_start(websocket, msg.args)
        elif verb == "FILE_UPLOAD_END":
            up = self._uploads.pop(websocket, None)
            if up:
                up.fobj.close()
                if up.size and up.received < up.size:
                    # a short upload is a broken file: remove it and tell
                    # the client rather than leaving truncated data behind
                    logger.warning("short upload removed: %s (%d/%d bytes)",
                                   up.path, up.received, up.size)
                    try:
                        os.unlink(up.path)
                    except OSError:
                        pass
                    await websocket.send(
                        f"FILE_UPLOAD_ERROR:{up.rel_path}:"
                        f"short upload ({up.received}/{up.size} bytes)")
                else:
                    logger.info("upload finished: %s (%d bytes)",
                                up.path, up.received)
        elif verb == "FILE_UPLOAD_ERROR":
            up = self._uploads.pop(websocket, None)
            if up:
                self._abort_upload(up)
        elif verb == "s" and msg.args:
            # scale request (reference "s,<scale>"): HiDPI factor → Xft DPI
            try:
                scale = min(4.0, max(0.5, float(msg.args[0])))
                await self._apply_dpi(int(round(96 * scale)))
            except ValueError:
                pass
        elif verb == "SET_NATIVE_CURSOR_RENDERING" and msg.args:
            # client renders the cursor itself (CSS) vs composited frames;
            # re-send the last cursor so the toggle takes effect immediately
            if self.app is not None and self.app.last_cursor_sent:
                try:
                    await websocket.send(
                        "cursor," + json.dumps(self.app.last_cursor_sent))
                except Exception:
                    pass
        elif verb == "cmd":
            if self.settings.command_enabled.value and msg.args:
                await self._run_command(msg.args[0])
        else:
            # Everything else is input-plane grammar; forward whole messages
            # like the reference ws_handler does for non-prefixed text.
            if verb == "_f":
                st = self._display_of(websocket)
                if st and st.ws is websocket and msg.args:
                    try:
                        fps = float(msg.args[0])
                        st.bp.on_client_fps(fps)
                        if self.metrics is not None:
                            self.metrics.set_fps(fps)
                    except ValueError:
                        pass
            elif verb == "_l" and msg.args and self.metrics is not None:
                try:
                    self.metrics.set_latency(float(msg.args[0]))
                except ValueError:
                    pass
            if self.input_handler is not None:
                await self.input_handler.on_message(
                    message, self._display_id_of(websocket))
            else:
                logger.debug("unhandled message verb %r", verb)

    # ------------------------------------------------------------------
    # binary protocol (client → server)

    async def _handle_binary(self, websocket, data: bytes) -> None:
        if not data:
            raise ProtocolError("empty binary frame")
        guard = self._guards.get(websocket)
        t = data[0]
        if t == 0x01:  # file chunk
            if guard is not None:
                # uploads are PACED, not dropped (a dropped chunk corrupts
                # the file): sleeping here stops reading the socket, which
                # backpressures the sender through TCP. Charged BEFORE the
                # open-upload check so orphan 0x01 floods (no
                # FILE_UPLOAD_START) are metered like any other bytes
                # instead of being a free unmetered lane.
                wait = guard.throttle("upload", len(data))
                if wait > 0:
                    self._count_upload_paced()
                    await asyncio.sleep(wait)
            up = self._uploads.get(websocket)
            if up:
                # Absolute cap holds even when the client declares size 0
                # (or lies): declared size is a courtesy check, the cap is
                # the actual hardening.
                cap = self.settings.max_upload_mb * 1024 * 1024
                limit = min(up.size, cap) if up.size else cap
                if limit and up.received + len(data) - 1 > limit:
                    self._uploads.pop(websocket, None)
                    self._abort_upload(up)
                    await websocket.send(
                        f"FILE_UPLOAD_ERROR:{up.rel_path}:"
                        "exceeded size limit")
                    return
                up.fobj.write(data[1:])
                up.received += len(data) - 1
        elif t == 0x02:  # microphone PCM
            cap = int(getattr(self.settings, "max_mic_chunk_kb", 0)) * 1024
            if cap and len(data) - 1 > cap:
                # file chunks have max_upload_mb; mic bytes get their own
                # cap before they reach the audio pipeline's resampler
                raise ProtocolError(
                    f"mic chunk of {len(data) - 1} bytes exceeds "
                    f"{cap}-byte cap")
            if guard is not None and not guard.allow("mic", len(data)):
                self._count_rate_limited("mic")
                return
            if self.audio_pipeline is not None:
                await self.audio_pipeline.on_mic_data(data[1:])
        else:
            # the canonical demux raises the precise rejection (wrong-
            # direction 0x00/0x03/0x04 vs unknown) — one trust boundary,
            # not two that drift
            unpack_client_binary(data)
            raise ProtocolError(f"unroutable client binary type 0x{t:02x}")

    def _abort_upload(self, up: _Upload) -> None:
        """Close the fd and remove the partial file of a dead upload."""
        try:
            up.fobj.close()
        except Exception:
            pass
        try:
            os.unlink(up.path)
        except OSError:
            pass

    # ------------------------------------------------------------------
    # settings negotiation

    async def _on_settings(self, websocket, body: str) -> None:
        try:
            requested = json.loads(body)
        except json.JSONDecodeError:
            logger.warning("bad SETTINGS payload")
            return
        display_id = str(requested.get("displayId", "primary"))

        if display_id != "primary" and not self.settings.second_screen.value:
            await websocket.send("KILL Second screens are disabled on this server.")
            await websocket.close()
            return

        # Parse/clamp every client value BEFORE touching any state: a
        # garbage value must cost only itself (ignored + logged), never
        # leave a half-registered zombie display holding a max_displays
        # slot or a live display with partially-applied settings.
        known = {s.name for s in SETTING_DEFINITIONS}
        applied: Dict[str, Any] = {}
        width = height = None
        for key, value in requested.items():
            if key in ("displayId",):
                continue
            try:
                if key == "initialClientWidth":
                    width = _clamp_dim(value)
                elif key == "initialClientHeight":
                    height = _clamp_dim(value)
                elif key in known:
                    applied[key] = self.settings.clamp_client_value(
                        key, value)
            except (TypeError, ValueError):
                logger.warning("ignoring bad client setting %s=%r",
                               key, value)

        st = self.display_clients.get(display_id)
        if st and st.ws is not None and st.ws is not websocket:
            # superseded client for this display: kill the old one
            try:
                await st.ws.send("KILL Display taken over by another client.")
                await st.ws.close()
            except Exception:
                pass
        if st is None:
            # admission control on the display plane (docs/scaling.md):
            # each display is a capture+encode pipeline, far heavier than
            # a viewer — the verdict comes from live scheduler lane
            # capacity (admit / queue / shed), with max_displays as the
            # hard backstop above it
            verdict = self._display_admission_verdict(
                width or 1024, height or 768, applied)
            if verdict == "queue":
                verdict = await self._await_display_admission(
                    width or 1024, height or 768, applied)
            if verdict != "admit":
                self.edge_stats["sessions_rejected"] += 1
                if self.metrics is not None:
                    self.metrics.inc_sessions_rejected()
                logger.warning(
                    "display %s rejected (%s): %d displays live",
                    display_id, verdict, len(self.display_clients))
                await websocket.send("KILL server_full")
                await websocket.close()
                return
            # the queue wait yields the loop: another handshake may have
            # registered this display meanwhile — adopt it (superseding
            # its client, same as the pre-wait path), don't clobber
            st = self.display_clients.get(display_id)
            if st is not None and st.ws is not None \
                    and st.ws is not websocket:
                try:
                    await st.ws.send(
                        "KILL Display taken over by another client.")
                    await st.ws.close()
                except Exception:
                    pass
            if st is None:
                st = DisplayState(display_id=display_id)
                self.display_clients[display_id] = st
        st.ws = websocket
        if width is not None:
            st.width = width
        if height is not None:
            st.height = height
        st.overrides.update(applied)
        if "framerate" in applied:
            st.bp.framerate = float(applied["framerate"])
        logger.info("client settings for %s: %s", display_id, applied)

        if "scaling_dpi" in applied:
            await self._apply_dpi(int(applied["scaling_dpi"]))
        self._schedule_reconfigure()

    async def _apply_dpi(self, dpi: int) -> None:
        from ..display import DpiManager

        try:
            await asyncio.to_thread(DpiManager().set_dpi, dpi)
        except ValueError as e:
            logger.warning("dpi rejected: %s", e)

    async def _on_resize(self, websocket, args) -> None:
        if self.settings.is_manual_resolution_mode.value:
            return
        try:
            res = args[0]
            display_id = args[1] if len(args) > 1 else "primary"
            w, h = (int(v) for v in res.split("x"))
        except (ValueError, IndexError):
            return
        st = self.display_clients.get(display_id)
        if not st or st.ws is not websocket:
            # resizing is owner-only: a viewer must not be able to force
            # stop-the-world reconfigurations of someone else's display
            return
        st.width, st.height = _clamp_dim(w), _clamp_dim(h)
        self._schedule_reconfigure()
        self.broadcast(json.dumps({
            "type": "stream_resolution",
            "width": st.width,
            "height": st.height,
        }))

    def _schedule_reconfigure(self) -> None:
        """Debounce/coalesce display reconfiguration behind one serialized
        worker task: ``_reconfigure_displays`` stops and restarts EVERY
        capture pipeline, so a client spamming ``r,<WxH>`` must cost one
        reconfiguration per storm, not one per message."""
        self._reconfig_dirty = True
        if self._reconfig_task is None or self._reconfig_task.done():
            self._reconfig_task = asyncio.create_task(
                self._reconfigure_worker())
        else:
            self.edge_stats["reconfigure_coalesced"] += 1
            if self.metrics is not None:
                self.metrics.inc_reconfigure_coalesced()

    async def _reconfigure_worker(self) -> None:
        try:
            debounce = max(0, int(getattr(self.settings,
                                          "resize_debounce_ms", 0))) / 1000.0
            while self._reconfig_dirty:
                if debounce:
                    # absorb the rest of the storm before doing the work;
                    # requests landing mid-run re-arm the dirty flag and
                    # get one more (batched) pass
                    await asyncio.sleep(debounce)
                self._reconfig_dirty = False
                self.edge_stats["reconfigure_runs"] += 1
                await self._reconfigure_displays()
        except asyncio.CancelledError:
            raise
        except Exception:
            # a failed reconfigure must not take the worker down with an
            # unretrieved exception; the next request starts a fresh one
            logger.exception("display reconfiguration failed")

    async def _reconfigure_displays(self) -> None:
        """Display-plane reconfiguration (reference reconfigure_displays
        selkies.py:2616): stop captures, re-arrange the X screen, then
        restart active pipelines with their new geometry/offsets.

        With a real X server every capture stops FIRST so no XGetImage
        ever races a shrinking root window. Without one (synthetic
        capture: tests, the swarm churn harness) the restart is SCOPED to
        displays whose geometry or offset actually changed — under
        join/leave/resize churn at hundreds of sessions, a stop-the-world
        restart per event would itself be the outage (docs/scaling.md)."""
        scoped = True
        try:
            from ..display import xrandr_available

            scoped = not xrandr_available()
        except Exception:
            pass
        if not scoped:
            for st in list(self.display_clients.values()):
                await self._stop_display(st)
        await self._apply_x11_layout()
        for st in list(self.display_clients.values()):
            if not (st.video_active and st.ws is not None):
                continue
            # running_geom/_config are what the live pipeline was STARTED
            # with; st.width/height/overrides already carry the request.
            # Offset-only shifts (every join reflows the framebuffer
            # layout) don't restart in scoped mode: without xrandr there
            # is no shared root window whose regions could go stale, and
            # restarting N-1 healthy streams per join is the exact
            # stop-the-world cost this path exists to avoid. A SETTINGS
            # change (quality/framerate/encoder overrides) DOES restart —
            # the encoder is built from that snapshot.
            changed = (st.running_geom is None
                       or st.running_geom[:2] != (st.width, st.height)
                       or st.running_config != (st.overrides,
                                                st.bp.framerate))
            running = st.capture_task is not None \
                and not st.capture_task.done()
            if scoped and running and not changed:
                continue        # untouched display keeps streaming
            if scoped and running:
                await self._stop_display(st)
            await self._start_display(st)

    async def _apply_x11_layout(self) -> None:
        """Arrange the client displays into one framebuffer and mirror it
        onto the real X screen (xrandr modes, --fb, --setmonitor).  Always
        updates per-display capture offsets; the xrandr half is skipped on
        hosts without it (synthetic capture) or when the layout is unchanged
        since the last apply."""
        from ..display import (XrandrManager, compute_layout,
                               xrandr_available)

        if not self.display_clients:
            return
        displays = {d: (st.width, st.height)
                    for d, st in self.display_clients.items()}
        primary = self.display_clients.get("primary")
        position = ((primary.overrides.get("second_screen_position")
                     if primary else None)
                    or self.settings.second_screen_position)
        try:
            layout = compute_layout(displays, position)
        except ValueError as e:
            logger.warning("layout rejected: %s", e)
            return
        for p in layout.placements:
            stp = self.display_clients.get(p.display_id)
            if stp:
                stp.x, stp.y = p.x, p.y
        if not xrandr_available() or layout == self._last_layout:
            return
        try:
            mgr = XrandrManager()
            if len(layout.placements) == 1:
                p = layout.placements[0]
                await asyncio.to_thread(mgr.resize, p.width, p.height)
            else:
                await asyncio.to_thread(mgr.apply_layout, layout)
            self._last_layout = layout
        except Exception as e:
            logger.warning("x11 layout apply failed: %s", e)

    # ------------------------------------------------------------------
    # frame-id reset protocol

    async def _reset_frame_ids_and_notify(self, st: DisplayState) -> None:
        st.bp.reset()
        # ids restart at 1: frames sent under the old numbering will
        # never be ACKed — close their spans instead of leaking them
        self.recorder.drop_awaiting(st.display_id, "reset")
        message = f"PIPELINE_RESETTING {st.display_id}"
        if st.display_id == "primary":
            self.broadcast(message)
        elif st.ws:
            try:
                # ride the same per-client queue as the media so the reset
                # keeps its FIFO position relative to queued frames
                self._fanout({st.ws}, message)
            except Exception:
                # a dead secondary socket must not crash the (supervised)
                # restart that is trying to recover its display
                logger.debug("reset notify failed for %s", st.display_id)

    # ------------------------------------------------------------------
    # capture / encode pipeline per display

    async def reconfigure_display(self, st: DisplayState) -> None:
        async with st.lock:
            await self._stop_display_locked(st)
            if st.video_active:
                await self._start_display_locked(st)

    async def _start_display(self, st: DisplayState) -> None:
        async with st.lock:
            await self._start_display_locked(st)

    async def _stop_display(self, st: DisplayState) -> None:
        async with st.lock:
            await self._stop_display_locked(st)

    async def _start_display_locked(self, st: DisplayState) -> None:
        if self.display_clients.get(st.display_id) is not st:
            # the display was deregistered (client disconnect) while a
            # reconfigure/START_VIDEO raced toward this start: a pipeline
            # started now would be a zombie nobody stops — leaked capture
            # loop, leaked scheduler slot, leaked spans
            return
        if st.capture_task and not st.capture_task.done():
            return
        # A failed/finished supervisor may leave a live backpressure task
        # behind; tear both down so restarts never leak a ticking loop.
        await self._stop_display_locked(st)
        st.failed = False          # an explicit restart clears the marker
        st.wedge_faults = 0
        st.ladder.fail_threshold = max(
            1, int(self.settings.ladder_fail_threshold))
        st.ladder.probe_after_s = int(self.settings.ladder_probe_ms) / 1000.0
        fps = st.bp.framerate or 60.0
        wd_frames = int(self.settings.watchdog_frames)
        watchdog_s = (max(0.5, wd_frames / max(1.0, fps))
                      if wd_frames > 0 else None)
        max_restarts = int(self.settings.supervisor_max_restarts)
        window_s = float(int(self.settings.supervisor_restart_window_s))
        st.supervisor = Supervisor(
            f"capture:{st.display_id}",
            lambda: self._capture_loop(st),
            max_restarts=max_restarts,
            restart_window_s=window_s,
            watchdog_timeout_s=watchdog_s,
            on_event=lambda kind, info:
                self._on_supervisor_event(st, kind, info),
        )
        st.bp_supervisor = Supervisor(
            f"backpressure:{st.display_id}",
            lambda: self._backpressure_loop(st),
            max_restarts=max_restarts,
            restart_window_s=window_s,
            on_event=lambda kind, info:
                self._on_supervisor_event(st, kind, info),
        )
        st.capture_task = asyncio.create_task(st.supervisor.run())
        st.backpressure_task = asyncio.create_task(st.bp_supervisor.run())
        st.running_geom = (st.width, st.height, st.x, st.y)
        st.running_config = (dict(st.overrides), st.bp.framerate)

    async def _stop_display_locked(self, st: DisplayState) -> None:
        """Exception-safe teardown: cancel BOTH tasks even if the first
        cancellation raises, and always close the encoder adapter so worker
        threads never leak across reconfigures."""
        for attr in ("capture_task", "backpressure_task"):
            task = getattr(st, attr)
            if task and not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                except Exception:
                    logger.exception("%s teardown for %s raised",
                                     attr, st.display_id)
            setattr(st, attr, None)
        st.supervisor = None
        st.bp_supervisor = None
        st.running_geom = None
        st.running_config = None
        # a stopped display's un-ACKed frames will never resolve
        self.recorder.drop_awaiting(st.display_id, "stop")
        encoder, st.encoder = st.encoder, None
        if encoder is not None:
            close = getattr(encoder, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    logger.exception("encoder close for %s raised",
                                     st.display_id)

    async def _capture_loop(self, st: DisplayState) -> None:
        """Source frames → pipelined TPU encode → stripe broadcast.

        One *supervised* run (st.supervisor owns restarts): exceptions
        propagate to the supervisor instead of being swallowed here, with
        encoder-path failures wrapped in :class:`EncoderFault` so they step
        the degradation ladder. The loop returns cleanly when the ladder
        rung changes under it — the supervisor then restarts it, which
        rebuilds the encoder at the new rung.
        """
        sup = st.supervisor
        faults = self.faults
        fps = st.bp.framerate or 60.0
        rung = st.ladder.rung
        # The capture loop numbers frames from 1 again on EVERY (re)start —
        # supervised crash restarts included — so the client and the
        # backpressure gate must drop their old frame-id horizon; otherwise
        # desync = (1 - old_ack) mod 2^16 reads as a huge lag and wedges the
        # gate closed (reference resets likewise, selkies.py:1119-1146).
        await self._reset_frame_ids_and_notify(st)
        encoder = None
        if rung == "device":
            encoder = self._acquire_mesh_encoder(st, fps)
        if encoder is None:
            overrides = dict(st.overrides)
            if rung == "host":
                overrides["tpu_entropy"] = "host"
            elif rung == "jpeg":
                overrides["encoder"] = "jpeg"
                overrides["tpu_entropy"] = "host"
            try:
                try:
                    encoder = self.encoder_factory(
                        st.width, st.height, self.settings, overrides)
                except TypeError:  # factory without overrides support
                    encoder = self.encoder_factory(
                        st.width, st.height, self.settings)
            except Exception as e:
                # construction-time device sickness must step the ladder
                # like any other encoder failure — otherwise a broken
                # device tier is retried forever and never degrades
                raise EncoderFault(
                    f"encoder construction failed: {e!r}") from e
        if getattr(encoder, "metrics", False) is None:
            encoder.metrics = self.metrics
        if hasattr(encoder, "on_error"):
            # encode errors harvested off-loop (worker thread futures) feed
            # the same ladder as loop-crashing EncoderFaults
            encoder.on_error = lambda exc: st.ladder.record_failure()
        if getattr(encoder, "faults", False) is None:
            # the async driver checks fetch.hang at ITS harvest site, so
            # one SELKIES_TPU_FAULTS entry can wedge either side of the
            # D2H path (tools/chaos_run.py arms it for both)
            encoder.faults = faults
        st.encoder = encoder
        source = None
        recorder = self.recorder
        #: flight-recorder spans for frames submitted but not yet
        #: harvested, keyed by the encoder's submit seq; encoders whose
        #: submit() returns no seq correlate FIFO (results arrive in
        #: submission order on every adapter)
        pending_tr: Dict[int, Any] = {}
        pending_fifo: deque = deque()
        try:
            if sup is not None:
                sup.beat()   # encoder construction counts as progress
            try:
                source = self.source_factory(st.width, st.height, fps,
                                             x=st.x, y=st.y)
            except TypeError:  # factory without offset support (tests)
                source = self.source_factory(st.width, st.height, fps)
            source.start()
            frame_id = 0
            interval = 1.0 / fps
            next_tick = time.monotonic()
            #: ticks whose harvest surfaced encoder errors without the
            #: ladder stepping (i.e. at the bottom rung) — after the
            #: ladder's own threshold, force a supervised rebuild rather
            #: than streaming nothing forever
            error_ticks = 0
            #: a pipeline that stops ACCEPTING submits and harvesting
            #: anything is wedged even though the loop itself still ticks
            #: (e.g. a dead mesh worker); generous deadline so first-use
            #: jit compiles never read as a wedge
            wedge_s = None
            if sup is not None and sup.watchdog_timeout_s is not None:
                wedge_s = max(4.0 * sup.watchdog_timeout_s, 30.0)
            accepted_at = time.monotonic()
            logger.info("capture loop started for %s (%dx%d@%g, rung=%s)",
                        st.display_id, st.width, st.height, fps, rung)
            consume_migration = getattr(encoder, "consume_migration", None)
            while True:
                if sup is not None:
                    sup.beat()
                faults.maybe_raise("capture.raise")
                await faults.maybe_hang("capture.stall")
                if consume_migration is not None and consume_migration():
                    # the scheduler live-migrated this session off a
                    # quarantined slot (docs/scaling.md): same recovery
                    # grammar as a supervised restart — frame ids restart
                    # with PIPELINE_RESETTING, the new slot's reset forces
                    # a keyframe, and the restart budget is forgiven (the
                    # scheduler absorbed the fault; the session is healthy)
                    logger.warning("display %s migrated to a healthy "
                                   "lane; resetting frame ids",
                                   st.display_id)
                    frame_id = 0
                    await self._reset_frame_ids_and_notify(st)
                    if sup is not None:
                        sup.forgive()
                    self._broadcast_health()
                # clean-probe evidence for the ladder: the tick must have
                # actually exercised the encoder (submit or delivery) AND
                # harvested no new errors (on_error bumps failures_total
                # from inside try_submit/poll for the threaded adapter)
                failures_before = st.ladder.failures_total
                progressed = False
                accepted = True     # "no submit attempted" is not a wedge
                if st.bp.send_enabled:
                    t_cap0 = time.monotonic()
                    frame = source.next_frame()
                    t_cap1 = time.monotonic()
                    if frame is not None:
                        # open this frame's flight span: (display, frame)
                        # context threaded capture → ... → client ACK
                        tr = recorder.begin(st.display_id, t=t_cap0)
                        tr.mark("capture", t_cap0, t_cap1)
                        # never block the shared event loop: drop when full
                        try_submit = getattr(encoder, "try_submit", None)
                        seq = None
                        try:
                            faults.maybe_raise("encode.raise")
                            if try_submit is not None:
                                # None = dropped (pipeline full): fine in
                                # bursts, but sustained non-acceptance with
                                # no harvests below means a wedged pipeline
                                seq = try_submit(frame)
                                accepted = seq is not None
                            else:
                                seq = encoder.submit(frame)
                        except Exception as e:
                            recorder.drop(tr, "submit")
                            raise EncoderFault(
                                f"encoder submit failed: {e!r}") from e
                        if not accepted:
                            # backpressure at the edge: a dropped frame
                            # closes terminally, it never leaks a span
                            recorder.drop(tr, "submit")
                        elif seq is not None:
                            # seq reuse (the mesh facade re-numbers only
                            # at harvest): the superseded frame's span
                            # must close, not silently vanish
                            old = pending_tr.get(seq)
                            if old is not None:
                                recorder.drop(old, "submit")
                            pending_tr[seq] = tr
                            # hard bound: a pipeline accepting submits
                            # but never harvesting must not grow this
                            # map until the watchdog fires
                            while len(pending_tr) > 512:
                                oldest = next(iter(pending_tr))
                                recorder.drop(pending_tr.pop(oldest),
                                              "submit")
                        else:
                            pending_fifo.append(tr)
                            while len(pending_fifo) > 512:
                                recorder.drop(pending_fifo.popleft(),
                                              "submit")
                        progressed = True
                await faults.maybe_hang("fetch.hang")
                try:
                    harvested = encoder.poll()
                except Exception as e:
                    raise EncoderFault(
                        f"encoder poll failed: {e!r}") from e
                if sup is not None:
                    # submit/poll can legitimately block the loop for one
                    # long stretch (first-use jit compile); beating after
                    # them keeps that from reading as a stall
                    sup.beat()
                for _seq, stripes in harvested:
                    tr = pending_tr.pop(_seq, None)
                    if tr is None and pending_fifo:
                        tr = pending_fifo.popleft()
                    if tr is not None:
                        # fold in the encoder-side stage intervals
                        # (stage/dispatch/fetch_wait/pack) harvested
                        # with the frame
                        pop_trace = getattr(encoder, "pop_trace", None)
                        if pop_trace is not None:
                            try:
                                tr.merge(pop_trace(_seq))
                            except Exception:
                                logger.debug("pop_trace failed",
                                             exc_info=True)
                    if not stripes:
                        # damage gating emitted nothing: a coalesced
                        # frame, closed (not dropped, not acked)
                        if tr is not None:
                            recorder.finish_empty(tr)
                        continue
                    progressed = True
                    frame_id = FrameId.next(frame_id)
                    viewers = self._viewers_of(st.display_id)
                    try:
                        self._emit_frame(st, encoder, frame_id, stripes,
                                         viewers, tr)
                    except BaseException:
                        if tr is not None and tr.terminal is None:
                            recorder.drop(tr, "send")
                        raise
                    st.bp.on_frame_sent(frame_id)
                if any(stripes for _seq, stripes in harvested):
                    accepted = True
                now = time.monotonic()
                if accepted:
                    accepted_at = now
                elif wedge_s is not None and now - accepted_at > wedge_s:
                    # loop ticks, nothing moves: dead mesh worker / wedged
                    # pipeline — force_step tells the event handler to step
                    # the ladder immediately (one accounting site; a
                    # consecutive count would be reset by each restart's
                    # first accepted submit and never escalate)
                    raise EncoderFault(
                        f"pipeline wedged: no accepted submits or harvests "
                        f"for {now - accepted_at:.1f}s", force_step=True)
                if st.ladder.failures_total > failures_before:
                    # errors surfaced off-loop this tick (threaded-adapter
                    # harvest); if the ladder can no longer step down, a
                    # persistently sick bottom rung must still force a
                    # supervised rebuild instead of streaming nothing
                    error_ticks += 1
                    if (error_ticks >= st.ladder.fail_threshold
                            and st.ladder.rung == rung):
                        raise EncoderFault(
                            f"persistent encode errors at rung {rung} "
                            f"({error_ticks} consecutive error ticks)")
                elif progressed:
                    error_ticks = 0
                    if st.ladder.record_success():
                        logger.info("display %s probed back up to rung %s",
                                    st.display_id, st.ladder.rung)
                if st.ladder.rung != rung:
                    # rung changed under us (off-loop step-down via
                    # on_error, or the probe above): exit cleanly; the
                    # supervisor restarts with the new rung's encoder
                    self._broadcast_health()
                    return
                if st.ws is not None and faults.should_fire("ws.drop"):
                    self._spawn_background(st.ws.close(),
                                           f"ws.drop:{st.display_id}")
                next_tick += interval
                delay = next_tick - time.monotonic()
                if delay < -1.0:  # fell badly behind; resynchronize
                    next_tick = time.monotonic()
                    delay = 0.0
                await asyncio.sleep(max(0.0, delay))
        finally:
            if source is not None:
                try:
                    source.stop()
                except Exception:
                    logger.exception("source stop for %s raised",
                                     st.display_id)
            # frames in flight inside the (about to be closed) encoder
            # are abandoned with it: close their spans terminally so a
            # supervised restart never leaks open spans
            for tr in pending_tr.values():
                recorder.drop(tr, "restart")
            pending_tr.clear()
            while pending_fifo:
                recorder.drop(pending_fifo.popleft(), "restart")
            st.encoder = None
            close = getattr(encoder, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    logger.exception("encoder close for %s raised",
                                     st.display_id)

    def _emit_frame(self, st: DisplayState, encoder, frame_id: int,
                    stripes, viewers, tr) -> None:
        """Wire-pack and fan out one harvested frame.

        Flight recorder: the LAST stripe of a traced frame rides the
        owner's send queue with the trace attached (the frame is
        decodable when that stripe lands), closing queue/send there and
        registering the span for CLIENT_FRAME_ACK correlation; every
        no-delivery path (no viewers, evicted owner, ownerless display)
        closes the span terminally instead of leaking it."""
        recorder = self.recorder
        owner = st.ws
        owner_cq = self._send_queues.get(owner) if owner is not None else None
        if tr is not None:
            tr.frame_id = frame_id
        n = len(stripes)
        for i, s in enumerate(stripes):
            chunk = self._pack_stripe(frame_id, s, encoder)
            if not viewers:
                continue
            traced_here = (tr is not None and i == n - 1
                           and owner is not None and owner in viewers)
            if traced_here:
                others = viewers - {owner}
                if others:
                    self._fanout(others, chunk)
                if owner_cq is not None and not owner_cq.evicted:
                    owner_cq.offer_traced(chunk, tr)
                elif owner_cq is not None:
                    # evicted mid-kill: the frame will never reach the
                    # owner, so its span ends at the queue
                    recorder.drop(tr, "queue")
                else:
                    # no send queue (client registered outside
                    # ws_handler): direct synchronous fan-out — queue
                    # dwell is zero by construction
                    t0 = time.monotonic()
                    _ws_broadcast({owner}, chunk)
                    t1 = time.monotonic()
                    tr.mark("queue", t0, t0)
                    tr.mark("send", t0, t1)
                    recorder.sent(tr)
            else:
                self._fanout(viewers, chunk)
            self.bytes_sent += len(chunk) * len(viewers)
        if tr is not None and tr.terminal is None and not (
                viewers and owner is not None and owner in viewers):
            # encoded, but nobody to ack it (no clients / viewer-only
            # fan-out): close terminally rather than waiting on an ACK
            # that cannot come
            recorder.drop(tr, "send")

    @staticmethod
    def _pack_stripe(frame_id: int, s, encoder) -> bytes:
        """Wire-pack one encoded stripe by profile: JPEG stripes → 0x03,
        striped H.264 → 0x04, full-frame H.264 → 0x00 (the client's three
        decode paths). The fullframe routing is an explicit encoder flag
        set at construction — a short display can legitimately have one
        stripe in striped mode and must still ship 0x04."""
        if hasattr(s, "annexb"):
            if getattr(encoder, "wire_fullframe", False):
                return pack_full_frame(frame_id, s.annexb, s.is_key)
            return pack_h264_stripe(
                frame_id, s.y_start, s.width, s.height, s.annexb, s.is_key)
        return pack_jpeg_stripe(frame_id, s.y_start, s.jpeg)

    def _acquire_mesh_encoder(self, st: DisplayState, fps: float):
        """Session facade onto the mesh coordinator when ``tpu_mesh`` is
        configured (BASELINE config 5); None → solo encoder pipeline.

        Mesh batching covers the JPEG and striped-H.264 profiles with
        server-wide quality settings (SPMD uniformity); the full-frame
        x264enc profile, mismatched geometry, or slot exhaustion fall
        back to a solo encoder per display. Buckets are keyed by
        (geometry, profile) — the SPMD program is profile-specific.
        """
        spec = str(self.settings.tpu_mesh)
        if not spec:
            return None
        profile = st.overrides.get("encoder", self.settings.encoder)
        if profile not in ("jpeg", "x264enc-striped"):
            return None
        if str(self.settings.watermark_path):
            # the mesh encoder has no watermark stage yet; a configured
            # watermark must not silently vanish — keep the solo pipeline
            logger.warning(
                "tpu_mesh ignored for %s: watermark_path requires the solo "
                "JPEG pipeline", st.display_id)
            return None
        geom = (st.width, st.height, profile)
        if geom in self._mesh_failed_geoms:
            self.mesh_stats["solo_fallback"] += 1
            return None
        coord = self.mesh_coordinators.get(geom)
        if coord is None:
            if len(self.mesh_coordinators) >= MESH_BUCKET_CAP:
                self.mesh_stats["solo_fallback"] += 1
                logger.warning(
                    "mesh batching: bucket limit reached; %s at %dx%d "
                    "uses a solo encoder", st.display_id, *geom[:2])
                return None
            try:
                from ..parallel.coordinator import MeshEncodeCoordinator

                factory = self.coordinator_factory or MeshEncodeCoordinator
                coord = factory(
                    spec, int(self.settings.tpu_sessions_per_chip),
                    st.width, st.height, settings=self.settings,
                    framerate=fps, profile=profile)
                # mesh fault points (mesh.tick_raise / mesh.slot_raise)
                # check the server's injector at the coordinator's sites
                coord.faults = self.faults
                self.mesh_coordinators[geom] = coord
                sfe_n = int(getattr(coord, "sfe_shards", 1) or 1)
                logger.info(
                    "mesh batching: %s → %s session slots/lane (max %s "
                    "lanes) at %dx%d (bucket %d)%s", spec,
                    getattr(coord, "slots_per_lane", "?"),
                    getattr(coord, "max_lanes", "?"), st.width, st.height,
                    len(self.mesh_coordinators),
                    f" — SFE lanes, {sfe_n} stripe shards/frame"
                    if sfe_n > 1 else "")
            except Exception:
                logger.exception(
                    "mesh coordinator for %dx%d (%s) unavailable; that "
                    "geometry uses solo encoders", *geom)
                self._mesh_failed_geoms.add(geom)
                self.mesh_stats["solo_fallback"] += 1
                return None
        facade = coord.acquire(st.width, st.height)
        if facade is None:
            # races the admission verdict lost (two joins for the last
            # slot) land here: serve them solo rather than dropping a
            # session the front door already admitted
            self.mesh_stats["solo_fallback"] += 1
            logger.warning(
                "mesh batching: no slot for %s at %dx%d; solo encoder",
                st.display_id, st.width, st.height)
        else:
            self.mesh_stats["bucketed"] += 1
        return facade

    async def _backpressure_loop(self, st: DisplayState) -> None:
        sup = st.bp_supervisor
        while True:
            await asyncio.sleep(CHECK_INTERVAL_S)
            if sup is not None:
                sup.beat()
            st.bp.evaluate()

    # ------------------------------------------------------------------
    # supervision events + health feed (ISSUE 2)

    def _spawn_background(self, coro, name: str) -> None:
        """Run a fire-and-forget coroutine with a held reference and
        logged (not warned-at-GC) exceptions."""
        async def runner():
            try:
                await coro
            except Exception:
                logger.debug("background task %s failed", name,
                             exc_info=True)
        task = asyncio.create_task(runner())
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _on_supervisor_event(self, st: DisplayState, kind: str,
                             info: Any) -> None:
        """Metrics + ladder + health fan-out for supervisor lifecycle
        events (runs on the event loop; must never raise)."""
        if kind == "failure" and isinstance(info, EncoderFault):
            force_step = getattr(info, "force_step", False)
            stepped = (st.ladder.force_step_down() if force_step
                       else st.ladder.record_failure())
            if stepped:
                st.wedge_faults = 0
                logger.warning("display %s degraded to rung %s",
                               st.display_id, st.ladder.rung)
                if st.supervisor is not None:
                    # the ladder absorbed this failure streak; judge the
                    # new rung against a fresh budget, or probe cycles
                    # would terminally fail a healthy degraded display
                    st.supervisor.forgive()
            elif force_step:
                # wedged with nowhere left to degrade: each rebuild of a
                # hung encoder may abandon a blocked worker thread, so
                # bound the cycle instead of leaking threads forever
                st.wedge_faults += 1
                if st.wedge_faults >= 3:
                    logger.error(
                        "display %s wedged %d times at the bottom rung; "
                        "marking failed", st.display_id, st.wedge_faults)
                    kind = "failed"
        if self.metrics is not None:
            if kind == "restart":
                self.metrics.inc_supervisor_restart()
            elif kind == "watchdog":
                self.metrics.inc_watchdog_restart()
        if kind == "failed":
            # a terminally failed capture pipeline must not leave its
            # sibling backpressure loop ticking forever; tear the display
            # down from OUTSIDE the supervisor task that emitted the event
            # (stopping it inline would await the task we are inside of)
            st.failed = True
            self._spawn_background(self._teardown_failed_display(st),
                                   f"teardown-failed:{st.display_id}")
        self._broadcast_health()

    async def _teardown_failed_display(self, st: DisplayState) -> None:
        async with st.lock:
            if not st.failed:
                # an explicit START_VIDEO/reconfigure restarted the display
                # before this queued teardown ran — it is healthy again and
                # must not be torn back down
                return
            await self._stop_display_locked(st)

    def _failed_displays(self) -> int:
        return sum(1 for d in self.display_clients.values()
                   if d.failed or (d.supervisor is not None
                                   and d.supervisor.state == FAILED))

    def _health_payload(self) -> str:
        """The ``system,health`` wire message: per-display supervision,
        watchdog, and degradation-ladder state."""
        displays: Dict[str, Any] = {}
        for did, st in self.display_clients.items():
            sup = st.supervisor.stats() if st.supervisor is not None else {}
            d: Dict[str, Any] = {
                "rung": st.ladder.rung,
                "ladder": st.ladder.state(),
                "failed": st.failed,
                "supervisor": sup.get("state",
                                      "failed" if st.failed else "idle"),
                "restarts": sup.get("restarts_total", 0),
                "failures": sup.get("failures_total", 0),
                "watchdog_restarts": sup.get("watchdog_restarts_total", 0),
            }
            enc = st.encoder
            if enc is not None and hasattr(enc, "stats"):
                try:
                    est = enc.stats()
                except Exception:
                    est = {}
                d["frames_dropped"] = est.get("frames_dropped", 0)
                d["encode_errors"] = est.get("encode_errors", 0)
            # flight-recorder stage breakdown (ISSUE 13): where each
            # frame's time went, pushed so the client stats overlay can
            # show it without scraping Prometheus
            try:
                summ = self.recorder.summary(did, last_s=60.0)
            except Exception:
                summ = {}
            if summ.get("stages"):
                d["stages"] = {
                    stage: {"p50_ms": v["p50_ms"], "p95_ms": v["p95_ms"]}
                    for stage, v in summ["stages"].items()}
                for k in ("glass_to_glass_p50_ms", "encode_only_p50_ms"):
                    if k in summ:
                        d[k] = summ[k]
            displays[did] = d
        # session-scheduler slot health (ISSUE 14, docs/scaling.md): the
        # per-slot fault domains lived only in coordinator stats() before
        # — a quarantined slot or a live migration must reach the client
        # overlay and the dashboard, not just a debugger
        mesh: Dict[str, Any] = {}
        for (w, h, profile), coord in list(self.mesh_coordinators.items()):
            try:
                cs = coord.stats()
            except Exception:
                continue
            mesh[f"{w}x{h}/{profile}"] = {
                "active_sessions": cs.get("active_sessions", 0),
                "lanes": cs.get("lanes", 0),
                "capacity_slots": cs.get("capacity_slots", 0),
                "free_slots": cs.get("free_slots", 0),
                "quarantined_slots": cs.get("quarantined_slots", 0),
                "slot_errors": cs.get("slot_errors", []),
                "tick_errors_total": cs.get("tick_errors_total", 0),
                "worker_restarts_total":
                    cs.get("worker_restarts_total", 0),
                "inflight_batches": cs.get("inflight_batches", 0),
                "migrations_total": cs.get("migrations_total", 0),
                # SFE lanes (ISSUE 15): chips one frame spans, and the
                # host-side slice-concat share of the harvest wall
                "sfe_shards": cs.get("sfe_shards", 1),
                "sfe_concat_ms_p50": cs.get("sfe_concat_ms_p50", 0.0),
                "lane_detail": cs.get("lane_detail", []),
            }
        return pack_system_health(displays, mesh=mesh or None)

    def _publish_health_metrics(self) -> None:
        """Recompute the health gauges from current state — recovery and
        display removal must clear them, not only events raise them."""
        if self.metrics is None:
            return
        levels = [d.ladder.level for d in self.display_clients.values()]
        self.metrics.set_degradation_rung(max(levels) if levels else 0)
        self.metrics.set_failed_displays(self._failed_displays())

    def _update_load_shed(self) -> None:
        """Admission-control load shedding (stats-tick cadence): when the
        encode pipelines report sustained frame drops — the device can no
        longer keep up with the admitted load — stop admitting NEW
        connections until the drop rate recovers. Existing sessions keep
        their backpressure/degradation machinery; shedding only protects
        them from additional load."""
        threshold = int(getattr(self.settings, "shed_drop_threshold", 0) or 0)
        if threshold <= 0:
            self._load_shedding = False
            return
        total = 0
        for st in self.display_clients.values():
            enc = st.encoder
            if enc is not None and hasattr(enc, "stats"):
                try:
                    total += int(enc.stats().get("frames_dropped", 0))
                except Exception:
                    pass
        delta = total - self._last_dropped_total
        if delta < 0:
            # a supervised restart replaced an encoder (its cumulative
            # counter restarted from zero) — exactly when overload churn
            # is likely; the new encoder's drops are all new drops, so
            # count the post-reset total rather than resetting the strikes
            delta = total
        self._last_dropped_total = total
        if delta >= threshold:
            self._shed_strikes += 1
        else:
            self._shed_strikes = 0
        shedding = self._shed_strikes >= 2
        if shedding != self._load_shedding:
            logger.warning(
                "load shedding %s (%d frames dropped this tick, "
                "threshold %d)",
                "engaged" if shedding else "released", delta, threshold)
        self._load_shedding = shedding

    def _broadcast_health(self) -> None:
        try:
            self._publish_health_metrics()
            self.broadcast(self._health_payload())
        except Exception:
            logger.exception("health broadcast failed")

    async def set_framerate(self, fps: float) -> None:
        """Apply a new target framerate to every active display.

        Wire-level parity with the reference ``_arg_fps`` path
        (input_handler.py:1662 → app.set_fps → pipeline restart).
        """
        fps = float(self.settings.framerate.clamp(int(fps)))
        for st in list(self.display_clients.values()):
            st.bp.framerate = fps
            if st.capture_task is not None and not st.capture_task.done():
                await self.reconfigure_display(st)

    # ------------------------------------------------------------------
    # file upload (path-sanitized, reference selkies.py:1843-1952)

    def _upload_dir(self) -> str:
        return upload_dir()

    async def _on_upload_start(self, websocket, args) -> None:
        if "upload" not in self.settings.file_transfers:
            await websocket.send("FILE_UPLOAD_ERROR:GENERAL:uploads disabled")
            return
        try:
            rel_path = args[0]
            size = int(args[1]) if len(args) > 1 and args[1] else 0
        except (ValueError, IndexError):
            await websocket.send("FILE_UPLOAD_ERROR:GENERAL:bad upload header")
            return
        root = os.path.realpath(self._upload_dir())
        norm = os.path.normpath(rel_path)
        if norm.startswith(("/", "\\")) or ".." in norm.split(os.sep) \
                or any(ord(c) < 0x20 or c in '"\x7f' for c in norm):
            # control characters / quotes in names would otherwise reach
            # the /files listing + Content-Disposition planes
            await websocket.send(f"FILE_UPLOAD_ERROR:{rel_path}:invalid path")
            return
        target = os.path.realpath(os.path.join(root, norm))
        if not target.startswith(root + os.sep):
            await websocket.send(f"FILE_UPLOAD_ERROR:{rel_path}:invalid path")
            return
        os.makedirs(os.path.dirname(target), exist_ok=True)
        old = self._uploads.pop(websocket, None)
        if old:
            # superseded mid-flight: remove the truncated partial too, or
            # the /files listing serves it as if complete
            self._abort_upload(old)
        self._uploads[websocket] = _Upload(
            path=target, rel_path=rel_path, fobj=open(target, "wb"), size=size)
        logger.info("upload started: %s (%d bytes)", target, size)

    # ------------------------------------------------------------------
    # command execution

    async def _run_command(self, command: str) -> None:
        logger.info("exec: %s", command)
        try:
            await asyncio.create_subprocess_shell(
                command,
                stdout=asyncio.subprocess.DEVNULL,
                stderr=asyncio.subprocess.DEVNULL,
            )
        except OSError as e:
            logger.warning("command failed to spawn: %s", e)

    # ------------------------------------------------------------------
    # stats feed (reference selkies.py:2966-3083)

    async def _stats_loop(self) -> None:
        prev_bytes = 0
        while True:
            await asyncio.sleep(STATS_INTERVAL_S)
            try:
                self._update_load_shed()
                # flight-recorder upkeep: late metrics attachment and the
                # expiry sweep (clients that never ACK must not pin open
                # spans forever)
                self.recorder.metrics = self.metrics
                self.recorder.expire()
                if self.metrics is not None:
                    self.metrics.set_trace_open_spans(
                        self.recorder.open_spans())
                if self.metrics is not None:
                    # aggregated ONCE per tick here, not per display loop
                    self.metrics.set_backpressured(sum(
                        1 for d in self.display_clients.values()
                        if not d.bp.send_enabled))
                    self.metrics.set_send_queue_depth(max(
                        (len(cq.q) for cq in self._send_queues.values()),
                        default=0))
                    self._publish_health_metrics()
                stats = self._collect_system_stats()
                self.broadcast(json.dumps(stats))
                net = {
                    "type": "network_stats",
                    "bytes_sent_delta": self.bytes_sent - prev_bytes,
                    "interval_s": STATS_INTERVAL_S,
                }
                if self.mesh_coordinators or self.mesh_stats["solo_fallback"]:
                    # mesh fallbacks must be observable, not silent.
                    # "bucketed" is a cumulative acquisition counter (it
                    # never decrements on release), so surface it under a
                    # _total name and report live occupancy separately.
                    net["mesh_buckets"] = len(self.mesh_coordinators)
                    net["mesh_acquisitions_total"] = \
                        self.mesh_stats["bucketed"]
                    net["mesh_sessions"] = sum(
                        coord.active_sessions
                        for coord in self.mesh_coordinators.values())
                    net["mesh_solo_fallbacks"] = \
                        self.mesh_stats["solo_fallback"]
                    # per-shard fault accounting (ISSUE 2): failed ticks
                    # and worker re-spawns are health, not noise
                    net["mesh_tick_errors"] = sum(
                        coord.tick_errors_total
                        for coord in self.mesh_coordinators.values())
                    net["mesh_worker_restarts"] = sum(
                        coord.worker_restarts_total
                        for coord in self.mesh_coordinators.values())
                    # scheduler health (ISSUE 14): lane capacity feeds the
                    # admission verdicts; quarantines/migrations say the
                    # fault-domain machinery is actually firing
                    sched = self.scheduler_stats()
                    if sched is not None:
                        net["mesh_lanes"] = sched["lanes"]
                        net["mesh_slots_free"] = sched["slots_free"]
                        net["mesh_quarantined_slots"] = \
                            sched["quarantined_slots"]
                    net["mesh_migrations_total"] = sum(
                        getattr(coord, "migrations_total", 0)
                        for coord in self.mesh_coordinators.values())
                    # one stats() snapshot per coordinator per tick (it
                    # takes the scheduler lock): SFE + gauges share it
                    coord_stats = [c.stats() for c in
                                   self.mesh_coordinators.values()]
                    # SFE lanes (ISSUE 15): shard count + slice-concat
                    # wall ride the stats feed and the gauges
                    sfe_stats = [cs for cs in coord_stats
                                 if cs.get("sfe_shards", 1) > 1]
                    if sfe_stats:
                        net["mesh_sfe_shards"] = max(
                            cs["sfe_shards"] for cs in sfe_stats)
                        net["mesh_sfe_concat_ms_p50"] = max(
                            cs.get("sfe_concat_ms_p50", 0.0)
                            for cs in sfe_stats)
                    if self.metrics is not None:
                        self.metrics.set_mesh_health(
                            active_sessions=net["mesh_sessions"],
                            lanes=net.get("mesh_lanes", 0),
                            inflight=sum(
                                cs.get("inflight_batches", 0)
                                for cs in coord_stats),
                            slot_errors=sum(
                                sum(cs.get("slot_errors", []))
                                for cs in coord_stats),
                            tick_errors=net["mesh_tick_errors"],
                            worker_restarts=net["mesh_worker_restarts"],
                            quarantined=net.get(
                                "mesh_quarantined_slots", 0),
                            migrations=net["mesh_migrations_total"])
                        self.metrics.set_sfe_health(
                            shards=net.get("mesh_sfe_shards", 0),
                            concat_ms_p50=net.get(
                                "mesh_sfe_concat_ms_p50", 0.0))
                edge = self.edge_stats
                if (edge["protocol_errors"] or edge["rate_limited"]
                        or edge["sessions_rejected"]
                        or edge["sessions_queued"]
                        or edge["slow_client_evictions"]):
                    # hostile-client activity rides the stats feed so a
                    # dashboardless operator still sees it
                    net["edge"] = {
                        "protocol_errors": edge["protocol_errors"],
                        "rate_limited": dict(edge["rate_limited"]),
                        "sessions_rejected": edge["sessions_rejected"],
                        "sessions_queued": edge["sessions_queued"],
                        "slow_client_evictions":
                            edge["slow_client_evictions"],
                        "load_shedding": self._load_shedding,
                    }
                prev_bytes = self.bytes_sent
                self.broadcast(json.dumps(net))
                if self.display_clients:
                    self._broadcast_health()
                tpu = self._collect_tpu_stats()
                if tpu:
                    self.broadcast(json.dumps(tpu))
            except Exception:
                logger.exception("stats loop error")

    def _collect_system_stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"type": "system_stats"}
        try:
            import psutil

            out["cpu_percent"] = psutil.cpu_percent()
            mem = psutil.virtual_memory()
            out["mem_total"] = mem.total
            out["mem_used"] = mem.used
        except ImportError:
            la1, _, _ = os.getloadavg()
            out["load_1m"] = la1
        return out

    def _collect_tpu_stats(self) -> Optional[Dict[str, Any]]:
        """TPU occupancy takes the role of the reference's gpu_stats loop
        (GPUtil, selkies.py:2988)."""
        try:
            import jax

            devs = jax.devices()
            stats = devs[0].memory_stats() if devs else None
        except Exception:
            return None
        out = {"type": "gpu_stats", "device_count": len(devs),
               "platform": devs[0].platform if devs else "none"}
        if stats:
            out["bytes_in_use"] = stats.get("bytes_in_use", 0)
            out["bytes_limit"] = stats.get("bytes_limit", 0)
        return out

    # ------------------------------------------------------------------
    # helpers

    def _display_of(self, websocket) -> Optional[DisplayState]:
        for st in self.display_clients.values():
            if st.ws is websocket:
                return st
        # viewers (shared mode) ride the primary display
        return self.display_clients.get("primary")

    def _display_id_of(self, websocket) -> str:
        st = self._display_of(websocket)
        return st.display_id if st else "primary"
