"""Streaming app shell: cross-cutting client-facing state and broadcasts.

Role parity with the reference's ``SelkiesStreamingApp`` (selkies.py:113-213):
owns encoder/framerate/resolution defaults, the last-sent cursor, and the
clipboard/cursor broadcast helpers (including multipart chunking for large
clipboard payloads).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Any, Dict, Optional

logger = logging.getLogger("selkies_tpu.app")

CLIPBOARD_CHUNK_SIZE = 512 * 1024


class StreamingApp:
    def __init__(self, settings) -> None:
        self.settings = settings
        self.encoder = settings.encoder
        self.framerate = settings.framerate.default
        self.display_width = 1024
        self.display_height = 768
        self.last_cursor_sent: Optional[Dict[str, Any]] = None
        self.data_server = None  # wired by main()

    # -- broadcast helpers -------------------------------------------------

    def _broadcast(self, message) -> None:
        if self.data_server is not None:
            self.data_server.broadcast(message)

    async def send_clipboard(self, data, mime_type: str = "text/plain") -> None:
        """Clipboard → all clients, multipart above CLIPBOARD_CHUNK_SIZE.

        Wire verbs match the reference client's handler
        (clipboard / clipboard_binary / clipboard_start / clipboard_data /
        clipboard_finish — selkies.py:142-175).
        """
        is_binary = mime_type != "text/plain"
        if is_binary and not self.settings.enable_binary_clipboard.value:
            logger.warning("binary clipboard disabled; dropping %s", mime_type)
            return
        payload = data.encode("utf-8") if isinstance(data, str) else bytes(data)
        if len(payload) < CLIPBOARD_CHUNK_SIZE:
            b64 = base64.b64encode(payload).decode("ascii")
            self._broadcast(
                f"clipboard_binary,{mime_type},{b64}" if is_binary
                else f"clipboard,{b64}")
            return
        self._broadcast(f"clipboard_start,{mime_type},{len(payload)}")
        for off in range(0, len(payload), CLIPBOARD_CHUNK_SIZE):
            chunk = base64.b64encode(
                payload[off:off + CLIPBOARD_CHUNK_SIZE]).decode("ascii")
            self._broadcast(f"clipboard_data,{chunk}")
            await asyncio.sleep(0)
        self._broadcast("clipboard_finish")

    def send_cursor(self, cursor: Dict[str, Any]) -> None:
        """Cursor image/hotspot update → all clients (``cursor,{json}``)."""
        self.last_cursor_sent = cursor
        self._broadcast(f"cursor,{json.dumps(cursor)}")

    def set_framerate(self, framerate: int) -> None:
        self.framerate = int(framerate)
