"""Per-display frame-ID backpressure.

Behavioral port of the reference's desync loop (selkies.py:1165-1236 and
constants selkies.py:6-16): the server stamps outgoing video frames with a
u16 frame id; the client periodically ACKs the last id it decoded; if the
client falls more than ~2 s of frames behind (RTT-adjusted) or stops ACKing
for 4 s, sending is gated off until it recovers.

The decision logic lives in a pure, clock-injected class
(:class:`BackpressureState`) so it is unit-testable without asyncio; the
server wraps it in a task that ticks every ``CHECK_INTERVAL_S``.

On the TPU side this gate additionally suppresses encode dispatch for gated
displays (skip-frame under backpressure), saving device work — the analogue
of pixelflux simply not being read.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Deque, Optional

from collections import deque

from ..protocol.wire import FrameId

ALLOWED_DESYNC_MS = 2000
LATENCY_THRESHOLD_MS = 50
CHECK_INTERVAL_S = 0.5
STALLED_CLIENT_TIMEOUT_S = 4.0
RTT_SMOOTHING_SAMPLES = 20
SENT_TIMESTAMP_HISTORY = 1000


@dataclass
class BackpressureState:
    """Pure backpressure decision state for one display."""

    framerate: float = 60.0
    allowed_desync_ms: float = ALLOWED_DESYNC_MS
    latency_threshold_ms: float = LATENCY_THRESHOLD_MS

    last_sent_frame_id: int = 0
    acknowledged_frame_id: int = -1
    latest_client_fps: float = 0.0
    smoothed_rtt_ms: float = 0.0
    send_enabled: bool = True
    last_ack_time: float = field(default_factory=time.monotonic)

    _sent_timestamps: Deque = field(default_factory=lambda: deque(maxlen=SENT_TIMESTAMP_HISTORY))
    _rtt_samples: Deque = field(default_factory=lambda: deque(maxlen=RTT_SMOOTHING_SAMPLES))

    # -- sender side -------------------------------------------------------

    def on_frame_sent(self, frame_id: int, now: Optional[float] = None) -> None:
        self.last_sent_frame_id = frame_id & 0xFFFF
        self._sent_timestamps.append(
            (frame_id & 0xFFFF, time.monotonic() if now is None else now))

    # -- receiver side -----------------------------------------------------

    def on_client_ack(self, frame_id: int, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.acknowledged_frame_id = frame_id & 0xFFFF
        self.last_ack_time = now
        for fid, ts in reversed(self._sent_timestamps):
            if fid == self.acknowledged_frame_id:
                rtt_ms = max(0.0, (now - ts) * 1000.0)
                self._rtt_samples.append(rtt_ms)
                self.smoothed_rtt_ms = sum(self._rtt_samples) / len(self._rtt_samples)
                break

    def on_client_fps(self, fps: float) -> None:
        self.latest_client_fps = max(0.0, float(fps))

    def reset(self, now: Optional[float] = None) -> None:
        """PIPELINE_RESETTING semantics: ids restart, gate opens."""
        self.last_sent_frame_id = 0
        self.acknowledged_frame_id = -1
        self.send_enabled = True
        self.last_ack_time = time.monotonic() if now is None else now
        self._sent_timestamps.clear()

    # -- periodic decision -------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> bool:
        """Recompute ``send_enabled``; call every CHECK_INTERVAL_S."""
        now = time.monotonic() if now is None else now

        if self.acknowledged_frame_id == -1:
            # no ACK yet: open gate, don't count stall time
            self.send_enabled = True
            self.last_ack_time = now
            return self.send_enabled

        sent, acked = self.last_sent_frame_id, self.acknowledged_frame_id
        if FrameId.is_anomalous(sent, acked):
            # wrap-around anomaly: trust the client, reset posture
            self.send_enabled = True
            self.last_ack_time = now
            return self.send_enabled
        if sent == 0:
            return self.send_enabled

        fps = self.latest_client_fps or self.framerate or 60.0
        desync = FrameId.desync(sent, acked)
        allowed = (self.allowed_desync_ms / 1000.0) * fps
        adjust = (
            (self.smoothed_rtt_ms / 1000.0) * fps
            if self.smoothed_rtt_ms > self.latency_threshold_ms
            else 0.0
        )
        effective = desync - adjust

        if now - self.last_ack_time > STALLED_CLIENT_TIMEOUT_S:
            self.send_enabled = False
        elif effective > allowed:
            self.send_enabled = False
        else:
            self.send_enabled = True
        return self.send_enabled
