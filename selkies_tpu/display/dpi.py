"""DPI and cursor-size management across desktop environments.

Parity with the reference's ``set_dpi``/``set_cursor_size``
(selkies.py:687,750): push the value through every mechanism a session
might honor — xrdb ``Xft.dpi``, XFCE's xfconf, MATE/GNOME gsettings —
ignoring the ones that aren't present.  Same injectable runner protocol as
:mod:`.xrandr`.
"""

from __future__ import annotations

import logging
import shutil
from typing import Sequence, Tuple

from .xrandr import Runner, subprocess_runner

logger = logging.getLogger("selkies_tpu.display")


def _have(tool: str) -> bool:
    return shutil.which(tool) is not None


class DpiManager:
    def __init__(self, runner: Runner = subprocess_runner) -> None:
        self.runner = runner

    def _run(self, argv: Sequence[str]) -> bool:
        rc, _ = self.runner(argv)
        return rc == 0

    def set_dpi(self, dpi: int) -> bool:
        """Returns True if at least one mechanism accepted the value."""
        if not 16 <= dpi <= 1024:
            raise ValueError(f"implausible dpi {dpi}")
        ok = False
        if _have("xrdb"):
            # xrdb -merge reads stdin; use -query-less direct file approach:
            # echo via sh keeps the runner protocol argv-only
            ok |= self._run(["sh", "-c",
                             f"echo 'Xft.dpi: {dpi}' | xrdb -merge"])
        if _have("xfconf-query"):
            ok |= self._run(["xfconf-query", "-c", "xsettings",
                             "-p", "/Xft/DPI", "-s", str(dpi), "--create",
                             "-t", "int"])
        if _have("gsettings"):
            # GNOME/MATE express DPI as a scale factor over 96
            factor = f"{dpi / 96.0:.2f}"
            ok |= self._run(["gsettings", "set",
                             "org.gnome.desktop.interface",
                             "text-scaling-factor", factor])
            ok |= self._run(["gsettings", "set",
                             "org.mate.interface",
                             "window-scaling-factor", str(max(1, dpi // 96))])
        if not ok:
            logger.info("no DPI mechanism available (headless?)")
        return ok

    def set_cursor_size(self, size: int) -> bool:
        if not 1 <= size <= 1024:
            raise ValueError(f"implausible cursor size {size}")
        ok = False
        if _have("xfconf-query"):
            ok |= self._run(["xfconf-query", "-c", "xsettings",
                             "-p", "/Gtk/CursorThemeSize", "-s", str(size),
                             "--create", "-t", "int"])
        if _have("gsettings"):
            ok |= self._run(["gsettings", "set",
                             "org.gnome.desktop.interface", "cursor-size",
                             str(size)])
        if _have("xrdb"):
            ok |= self._run(["sh", "-c",
                             f"echo 'Xcursor.size: {size}' | xrdb -merge"])
        return ok
