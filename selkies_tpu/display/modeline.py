"""VESA GTF modeline computation (pure math, no subprocesses).

The reference shells out to ``cvt``/``gtf`` and falls back to a built-in
formula to mint xrandr modelines for arbitrary client resolutions
(selkies.py:373 generate_xrandr_gtf_modeline); here the GTF formula is
implemented directly so the display manager never depends on those tools.
"""

from __future__ import annotations

from dataclasses import dataclass

# VESA GTF standard constants
_CELL_GRAN = 8
_MIN_PORCH = 1           # lines
_V_SYNC_RQD = 3          # lines
_H_SYNC_PERCENT = 8.0    # % of line period
_MIN_VSYNC_BP = 550.0    # µs
_M = 600.0               # gradient %/kHz
_C = 40.0                # offset %
_K = 128.0               # blanking-formula scaling
_J = 20.0                # scaling-factor weighting
_C_PRIME = (_C - _J) * _K / 256.0 + _J
_M_PRIME = _K / 256.0 * _M


@dataclass(frozen=True)
class Modeline:
    name: str
    pclk_mhz: float
    hdisp: int
    hsync_start: int
    hsync_end: int
    htotal: int
    vdisp: int
    vsync_start: int
    vsync_end: int
    vtotal: int

    @property
    def refresh_hz(self) -> float:
        return self.pclk_mhz * 1e6 / (self.htotal * self.vtotal)

    def xrandr_args(self) -> list:
        """Arguments for ``xrandr --newmode``."""
        return [self.name, f"{self.pclk_mhz:.2f}",
                str(self.hdisp), str(self.hsync_start),
                str(self.hsync_end), str(self.htotal),
                str(self.vdisp), str(self.vsync_start),
                str(self.vsync_end), str(self.vtotal),
                "-HSync", "+VSync"]

    def __str__(self) -> str:
        return " ".join(["Modeline", f'"{self.name}"'] + self.xrandr_args()[1:])


def gtf_modeline(width: int, height: int, refresh: float = 60.0) -> Modeline:
    """GTF timing for ``width``×``height`` at ``refresh`` Hz.

    Matches the classic ``gtf`` utility output (e.g. 1920×1080@60 →
    172.80 MHz, htotal 2576, vtotal 1118).
    """
    if width <= 0 or height <= 0 or refresh <= 0:
        raise ValueError("dimensions and refresh must be positive")
    h_pixels = round(width / _CELL_GRAN) * _CELL_GRAN
    v_lines = height

    # estimate line period, then refine against the requested field rate
    h_period_est = ((1.0 / refresh) - _MIN_VSYNC_BP / 1e6) \
        / (v_lines + _MIN_PORCH) * 1e6
    v_sync_bp = round(_MIN_VSYNC_BP / h_period_est)
    total_v_lines = v_lines + v_sync_bp + _MIN_PORCH
    v_field_est = 1.0 / h_period_est / total_v_lines * 1e6
    h_period = h_period_est / (refresh / v_field_est)

    ideal_duty_cycle = _C_PRIME - (_M_PRIME * h_period / 1000.0)
    h_blank = round(
        h_pixels * ideal_duty_cycle / (100.0 - ideal_duty_cycle)
        / (2.0 * _CELL_GRAN)) * 2 * _CELL_GRAN
    total_pixels = h_pixels + h_blank
    pclk_mhz = total_pixels / h_period

    h_sync = round(_H_SYNC_PERCENT / 100.0 * total_pixels / _CELL_GRAN) \
        * _CELL_GRAN
    h_front = h_blank // 2 - h_sync

    name = f"{width}x{height}_{refresh:.2f}"
    return Modeline(
        name=name,
        pclk_mhz=round(pclk_mhz, 2),
        hdisp=h_pixels,
        hsync_start=h_pixels + h_front,
        hsync_end=h_pixels + h_front + h_sync,
        htotal=total_pixels,
        vdisp=v_lines,
        vsync_start=v_lines + _MIN_PORCH,
        vsync_end=v_lines + _MIN_PORCH + _V_SYNC_RQD,
        vtotal=total_v_lines,
    )
