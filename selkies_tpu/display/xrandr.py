"""xrandr orchestration: modes, resizes, logical monitors.

Command half of the reference's display manager (``resize_display``
selkies.py:278, ``reconfigure_displays`` xrandr plumbing
selkies.py:2723-2751): ensure a mode exists (GTF ``--newmode`` +
``--addmode``), apply it, and carve the framebuffer into logical monitors
with ``--setmonitor``.  All shelling goes through an injectable ``runner``
so tests exercise the full command grammar without an X server.
"""

from __future__ import annotations

import logging
import re
import shutil
import subprocess
from typing import Callable, List, Optional, Sequence, Tuple

from .layout import Layout
from .modeline import gtf_modeline

logger = logging.getLogger("selkies_tpu.display")

#: runner(argv) → (returncode, stdout)
Runner = Callable[[Sequence[str]], Tuple[int, str]]


def subprocess_runner(argv: Sequence[str]) -> Tuple[int, str]:
    try:
        proc = subprocess.run(list(argv), capture_output=True, text=True,
                              timeout=10)
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("%s failed: %s", argv[0], e)
        return 127, ""
    if proc.returncode != 0:
        logger.debug("%s rc=%d stderr=%s", " ".join(argv), proc.returncode,
                     proc.stderr.strip())
    return proc.returncode, proc.stdout


def xrandr_available() -> bool:
    return shutil.which("xrandr") is not None


class XrandrManager:
    """Stateless-ish wrapper over one X display's RandR configuration."""

    def __init__(self, runner: Runner = subprocess_runner,
                 display: Optional[str] = None) -> None:
        self.runner = runner
        self.display = display

    def _xrandr(self, *args: str) -> Tuple[int, str]:
        argv = ["xrandr"]
        if self.display:
            argv += ["-d", self.display]
        return self.runner(argv + list(args))

    # -- queries -----------------------------------------------------------

    def connected_outputs(self) -> List[str]:
        rc, out = self._xrandr("--query")
        if rc != 0:
            return []
        return [line.split()[0] for line in out.splitlines()
                if " connected" in line]

    def output_modes(self, output: str) -> List[str]:
        """Mode names listed under ``output`` in ``xrandr --query``."""
        rc, out = self._xrandr("--query")
        if rc != 0:
            return []
        modes: List[str] = []
        collecting = False
        for line in out.splitlines():
            if not line.startswith((" ", "\t")):
                collecting = line.split()[0] == output if line.split() else False
                continue
            if collecting:
                m = re.match(r"\s+(\S+)", line)
                if m:
                    modes.append(m.group(1))
        return modes

    # -- mode management ---------------------------------------------------

    def ensure_mode(self, output: str, width: int, height: int,
                    refresh: float = 60.0) -> str:
        """Create (GTF) + attach the mode if missing; returns the mode name."""
        mode = gtf_modeline(width, height, refresh)
        existing = self.output_modes(output)
        # a native WxH mode is fine too (e.g. real monitors)
        plain = f"{width}x{height}"
        if plain in existing:
            return plain
        if mode.name not in existing:
            rc, _ = self._xrandr("--newmode", *mode.xrandr_args())
            # rc!=0 usually means the mode already exists in the screen
            # resources but isn't attached — addmode below still works
            if rc not in (0, 1):
                logger.warning("newmode %s failed rc=%d", mode.name, rc)
            rc, _ = self._xrandr("--addmode", output, mode.name)
            if rc != 0:
                raise RuntimeError(f"addmode {mode.name} on {output} failed")
        return mode.name

    def delete_mode(self, output: str, mode_name: str) -> None:
        self._xrandr("--delmode", output, mode_name)
        self._xrandr("--rmmode", mode_name)

    # -- application -------------------------------------------------------

    def resize(self, width: int, height: int, refresh: float = 60.0,
               output: Optional[str] = None) -> str:
        """Single-display resize (reference resize_display selkies.py:278)."""
        outputs = self.connected_outputs()
        if output is None:
            if not outputs:
                raise RuntimeError("no connected outputs")
            output = outputs[0]
        mode_name = self.ensure_mode(output, width, height, refresh)
        rc, _ = self._xrandr("--output", output, "--mode", mode_name)
        if rc != 0:
            raise RuntimeError(f"xrandr --output {output} --mode {mode_name} "
                               f"failed")
        return mode_name

    def list_monitors(self) -> List[str]:
        rc, out = self._xrandr("--listmonitors")
        if rc != 0:
            return []
        names = []
        for line in out.splitlines()[1:]:
            m = re.match(r"\s*\d+:\s+([+*]*)(\S+)", line)
            if m:
                names.append(m.group(2))
        return names

    def apply_layout(self, layout: Layout, refresh: float = 60.0) -> None:
        """Extended-desktop reconfiguration (selkies.py:2723-2751):
        clear stale logical monitors, grow the framebuffer, then declare one
        ``--setmonitor`` logical monitor per placement."""
        for name in self.list_monitors():
            if name.startswith("selkies-"):
                self._xrandr("--delmonitor", name)

        outputs = self.connected_outputs()
        if not outputs:
            raise RuntimeError("no connected outputs")
        primary_out = outputs[0]
        # the real output spans the whole framebuffer; logical monitors
        # carve it up for the window manager.  The mode must actually be
        # activated on the output — otherwise xrandr rejects any --fb
        # smaller than the stale active CRTC mode.
        mode_name = self.ensure_mode(primary_out, layout.fb_width,
                                     layout.fb_height, refresh)
        rc, _ = self._xrandr("--output", primary_out, "--mode", mode_name)
        if rc != 0:
            logger.warning("--output %s --mode %s failed", primary_out,
                           mode_name)
        rc, _ = self._xrandr("--fb",
                             f"{layout.fb_width}x{layout.fb_height}")
        if rc != 0:
            logger.warning("--fb %dx%d failed", layout.fb_width,
                           layout.fb_height)
        for i, p in enumerate(layout.placements):
            geom = (f"{p.width}/{p.width}x{p.height}/{p.height}"
                    f"+{p.x}+{p.y}")
            self._xrandr("--setmonitor", f"selkies-{p.display_id}", geom,
                         primary_out if i == 0 else "none")
