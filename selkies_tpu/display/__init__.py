"""Display plane: modelines, layout, xrandr orchestration, DPI.

The reference's display manager (selkies.py:216-470, 2616-2779) rebuilt as
three separable pieces: pure GTF math (:mod:`.modeline`), pure layout
geometry (:mod:`.layout`), and the xrandr/DPI command layer with injectable
runners (:mod:`.xrandr`, :mod:`.dpi`).
"""

from .dpi import DpiManager
from .layout import (Layout, Placement, compute_layout, even, fit_res,
                     parse_res)
from .modeline import Modeline, gtf_modeline
from .xrandr import XrandrManager, subprocess_runner, xrandr_available

__all__ = [
    "DpiManager", "Layout", "Modeline", "Placement", "XrandrManager",
    "compute_layout", "even", "fit_res", "gtf_modeline", "parse_res",
    "subprocess_runner", "xrandr_available",
]
