"""Pure multi-display layout computation.

The geometry half of the reference's ``reconfigure_displays``
(selkies.py:2616-2779): given 1-2 logical displays and the secondary's
position relative to the primary (right/left/up/down), produce per-display
framebuffer offsets and the combined framebuffer size for xrandr
``--fb`` / ``--setmonitor``.  Also the resolution sanitizers
(``fit_res``/``parse_res``, selkies.py:216-276).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

POSITIONS = ("right", "left", "up", "down")


def even(v: int) -> int:
    """Video planes are 4:2:0 — dimensions must be even (selkies.py:3104)."""
    return max(2, v - (v % 2))


def parse_res(res: str) -> Tuple[int, int]:
    """'1920x1080' → (1920, 1080), even-aligned."""
    try:
        w_s, h_s = res.lower().split("x")
        w, h = int(w_s), int(h_s)
    except (ValueError, AttributeError):
        raise ValueError(f"invalid resolution {res!r}")
    if w <= 0 or h <= 0:
        raise ValueError(f"invalid resolution {res!r}")
    return even(w), even(h)


def fit_res(w: int, h: int, max_w: int, max_h: int) -> Tuple[int, int]:
    """Scale down into (max_w, max_h) preserving aspect (selkies.py:216)."""
    if w <= max_w and h <= max_h:
        return even(w), even(h)
    scale = min(max_w / w, max_h / h)
    return even(int(w * scale)), even(int(h * scale))


@dataclass(frozen=True)
class Placement:
    display_id: str
    width: int
    height: int
    x: int
    y: int


@dataclass(frozen=True)
class Layout:
    fb_width: int
    fb_height: int
    placements: List[Placement]

    def offset_of(self, display_id: str) -> Tuple[int, int]:
        for p in self.placements:
            if p.display_id == display_id:
                return p.x, p.y
        raise KeyError(display_id)


def compute_layout(displays: Dict[str, Tuple[int, int]],
                   position: str = "right") -> Layout:
    """Place displays into one framebuffer.

    ``displays`` maps display_id → (w, h); the display whose id is
    "primary" anchors the layout, every other display stacks to
    ``position`` of it (the reference supports exactly 2 displays; this
    generalizes by stacking along the chosen axis in insertion order).
    """
    if not displays:
        raise ValueError("no displays")
    if position not in POSITIONS:
        raise ValueError(f"position must be one of {POSITIONS}")
    ids = sorted(displays, key=lambda d: (d != "primary", d))
    sizes = {d: (even(displays[d][0]), even(displays[d][1])) for d in ids}

    placements: List[Placement] = []
    if position in ("right", "left"):
        order = ids if position == "right" else list(reversed(ids))
        x = 0
        for d in order:
            w, h = sizes[d]
            placements.append(Placement(d, w, h, x, 0))
            x += w
        fb_w = x
        fb_h = max(h for _, h in sizes.values())
    else:
        order = ids if position == "down" else list(reversed(ids))
        y = 0
        for d in order:
            w, h = sizes[d]
            placements.append(Placement(d, w, h, 0, y))
            y += h
        fb_w = max(w for w, _ in sizes.values())
        fb_h = y
    placements.sort(key=lambda p: (p.display_id != "primary", p.display_id))
    return Layout(fb_width=fb_w, fb_height=fb_h, placements=placements)
