"""Console entry point: ``selkies-tpu`` (reference: selkies.py:3297 ws_entrypoint)."""

from __future__ import annotations

import sys


def main() -> int:
    from .settings import get_settings

    settings = get_settings(sys.argv[1:])
    try:
        from .server.main import run
    except ImportError as e:  # server not built yet in this tree
        print(f"selkies-tpu: server unavailable ({e})", file=sys.stderr)
        return 1
    return run(settings)


if __name__ == "__main__":
    raise SystemExit(main())
