"""Multi-session H.264 encode over a ("session", "stripe") device mesh.

Round-3 verdict item 3: the mesh path was hard-gated to JPEG while the
config-4 memo sold an H.264-on-mesh projection. This module makes the
H.264 profile a real mesh citizen: sessions are data-parallel on the
"session" axis and each frame's height is sharded on stripe boundaries
on the "stripe" axis — legal because every stripe is an independent
video sequence (its own SPS/PPS/IDR chain and VideoDecoder client-side,
reference selkies-core.js:2925-2968), so motion estimation, the
reconstruction chain and the sparse level pack all stay shard-local.
Only nothing crosses the ICI per tick; the per-stripe CAVLC runs on the
host thread pool exactly as the solo path does (encoder/h264.py).

IDR handling keeps the dispatch SPMD-uniform: a joining session must
not force whole-batch keyframes or a divergent program, so the step
comes in two compiled flavors — a steady-state P-only program, and a
"mixed" program that additionally computes the Intra16x16 encode for
every stripe and SELECTS per stripe between intra and inter outputs.
The host dispatches the mixed program only on ticks where some stripe
needs an IDR (join/reset/entropy-resync); intra levels routinely exceed
int8, which the sparse pack already reports per stripe as overflow, so
the host recovers exact IDR levels from the flat16 rows it keeps on
device — the same fallback the solo encoder uses.
"""

from __future__ import annotations

import functools
import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..encoder import device_cavlc as dcav
from ..encoder import h264_device as dev
from ..encoder.h264 import H264Stripe, encode_picture_nals_np, make_pps, make_sps
from ..encoder.h264 import _entropy_pool
from .mesh import fetch_sharded_prefix, shard_map

logger = logging.getLogger("selkies_tpu.parallel.h264")

MB = 16


def _merge_idr(enc_p: dev.StripeEncodeOut, enc_i: dev.StripeEncodeOut,
               idr) -> dev.StripeEncodeOut:
    """Per-stripe select between the inter and intra encodes.

    ``idr``: [S] bool/int. Every StripeEncodeOut field carries the stripe
    dim first, so a broadcasted where merges the two programs' outputs.
    """
    def sel(a, b):
        flag = idr.reshape((idr.shape[0],) + (1,) * (a.ndim - 1))
        return jnp.where(flag != 0, a, b)

    return dev.StripeEncodeOut(*[sel(a, b) for a, b in zip(enc_i, enc_p)])


def make_h264_mesh_step(mesh: Mesh, pad_h: int, pad_w: int, stripe_h: int,
                        *, search: int = dev.SEARCH, cap_frac: int = 4,
                        me: str = "xla", with_idr: bool = False,
                        prefix: int = 0, entropy: str = "sparse",
                        max_stripe_bytes: int = 0):
    """Build the jitted sharded multi-session H.264 step.

    Returns (fn, s_local): fn(frames, prev_y, prev_cb, prev_cr, ref_y,
    ref_cb, ref_cr, paint, idr, qp, paint_qp) →
      (buf [N, stripe_ax, L], flat16 [N, S, words], prev planes, refs).

    frames [N, pad_h, pad_w, 3] uint8, sharded P("session", "stripe");
    plane state shards the same way; paint/idr are [N, S] int32 sharded
    on ("session", "stripe"). ``me`` defaults to the XLA chunked search:
    the Pallas kernel assumes the TPU backend, and the mesh path must
    also run on the CPU test mesh — TPU deployments pass me="pallas".

    ``entropy="device"`` runs CAVLC shard-local (encoder/device_cavlc.py)
    so ``buf`` carries per-stripe bit-exact P-slice payloads instead of
    sparse levels — multi-session steady state then needs ZERO host
    entropy threads; IDR/overflow stripes still recover from flat16.
    """
    n_stripe_ax = mesh.shape["stripe"]
    if pad_h % (n_stripe_ax * stripe_h):
        raise ValueError("pad_h must divide into stripe_ax × stripe_h bands")
    h_local = pad_h // n_stripe_ax
    s_local = h_local // stripe_h

    def one(rgb, py1, pcb1, pcr1, ry1, rcb1, rcr1, paint1, idr1,
            qp, paint_qp):
        y, cb, cr = dev.prepare_planes(rgb, h_local, pad_w)
        enc, damage, update, nry, nrcb, nrcr = dev._frame_p_core(
            y, cb, cr, py1, pcb1, pcr1, ry1, rcb1, rcr1,
            paint1, qp, paint_qp, n_stripes=s_local, sh=stripe_h,
            search=search, me=me)
        if with_idr:
            ys = y.reshape(s_local, stripe_h, pad_w)
            cbs = cb.reshape(s_local, stripe_h // 2, pad_w // 2)
            crs = cr.reshape(s_local, stripe_h // 2, pad_w // 2)
            qps = jnp.broadcast_to(qp, (s_local,))
            enc_i = jax.vmap(dev.encode_stripe_idr)(ys, cbs, crs, qps)
            enc = _merge_idr(enc, enc_i, idr1)
            damage = damage | (idr1 != 0)
            update = update | (idr1 != 0)
            sel = (idr1 != 0)[:, None, None]
            nry = jnp.where(
                sel, enc_i.recon_y, nry.reshape(s_local, stripe_h, pad_w)
            ).reshape(h_local, pad_w)
            nrcb = jnp.where(
                sel, enc_i.recon_cb,
                nrcb.reshape(s_local, stripe_h // 2, pad_w // 2)
            ).reshape(h_local // 2, pad_w // 2)
            nrcr = jnp.where(
                sel, enc_i.recon_cr,
                nrcr.reshape(s_local, stripe_h // 2, pad_w // 2)
            ).reshape(h_local // 2, pad_w // 2)
        flat16, _ = dev._pack_levels(enc, damage, update)
        if entropy == "device":
            # shard-local CAVLC: IDR stripes are masked out of the pack
            # (their merged intra levels are not P-slice material) and
            # recover from flat16 on the host, like overflow
            upd_p = update & (idr1 == 0)
            buf = dcav.pack_p_frame(
                enc.mv, enc.luma, enc.chroma_dc, enc.chroma_ac,
                damage, upd_p, mb_w=pad_w // MB, mb_h=stripe_h // MB,
                max_stripe_bytes=max_stripe_bytes)
        else:
            buf = dev._pack_sparse(flat16, damage, update,
                                   cap_frac=cap_frac)
        # byte-prefix of the content-compacted buffer (head + bitmap +
        # compacted cells), same contract as the solo encoder's
        # two-tier head; harvest refetches exact rows on undershoot
        if prefix:
            buf = buf[:prefix]
        return buf, flat16, y, cb, cr, nry, nrcb, nrcr

    def local_step(frames, prev_y, prev_cb, prev_cr,
                   ref_y, ref_cb, ref_cr, paint, idr, qp, paint_qp):
        buf, flat16, y, cb, cr, nry, nrcb, nrcr = jax.vmap(
            one, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, None, None)
        )(frames, prev_y, prev_cb, prev_cr, ref_y, ref_cb, ref_cr,
          paint, idr, qp, paint_qp)
        return (buf[:, None, :], flat16, y, cb, cr, nry, nrcb, nrcr)

    plane = P("session", "stripe")
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(plane, plane, plane, plane, plane, plane, plane,
                  plane, plane, P(), P()),
        out_specs=(
            P("session", "stripe", None),   # buf [N, stripe_ax, L]
            P("session", "stripe", None),   # flat16 [N, S, words]
            plane, plane, plane,            # prev planes
            plane, plane, plane,            # refs
        ),
    )
    return jax.jit(sharded, donate_argnums=(1, 2, 3, 4, 5, 6)), s_local


@dataclass
class _MeshH264Pending:
    prefix: Any                   # async-fetching [N, stripe_ax, prefix]
    buf: Any                      # full packed buffer (undershoot refetch)
    flat16: Any                   # [N, S, words] exact levels (device)
    idr: np.ndarray               # [N, S] bool — dispatched as IDR
    paint: np.ndarray             # [N, S] bool
    reuse_prev: np.ndarray        # [N] bool
    qp: np.ndarray                # [N, S] int — qp each stripe coded at


class MeshH264Encoder:
    """N solo H264StripeEncoders collapsed into one SPMD program.

    Mirrors MeshStripeEncoder's shape (dispatch/harvest/facade-friendly
    control surface) with the solo H264StripeEncoder's per-stripe host
    state (frame_num, idr_pic_id, damage/paint history, CAVLC pool).
    """

    def __init__(self, mesh: Mesh, n_sessions: int, width: int, height: int,
                 *, stripe_h: int = 64, qp: int = 26, paint_over_qp: int = 18,
                 use_paint_over_quality: bool = True,
                 paint_over_trigger_frames: int = 15,
                 search: int = dev.SEARCH, me: Optional[str] = None,
                 entropy: Optional[str] = None) -> None:
        n_sess_ax = mesh.shape["session"]
        self.n_stripe_ax = mesh.shape["stripe"]
        if n_sessions % n_sess_ax:
            raise ValueError(
                f"{n_sessions} sessions not divisible by session axis "
                f"{n_sess_ax}")
        if stripe_h % MB:
            raise ValueError("stripe_h must be a multiple of 16")
        if width % 2 or height % 2:
            raise ValueError("frame dimensions must be even")
        band = self.n_stripe_ax * stripe_h
        self.width, self.height = width, height
        self.pad_w = -(-width // MB) * MB
        self.pad_h = -(-height // band) * band
        self.stripe_h = stripe_h
        self.n_stripes = self.pad_h // stripe_h
        self.n_sessions = n_sessions
        self.mesh = mesh
        self.qp = int(np.clip(qp, 0, 51))
        self.paint_over_qp = int(np.clip(paint_over_qp, 0, 51))
        self.use_paint_over_quality = bool(use_paint_over_quality)
        self.paint_over_trigger = int(paint_over_trigger_frames)
        self.search = search
        if me is None:
            me = "pallas" if jax.default_backend() == "tpu" else "xla"
        self.me = me

        n = (stripe_h // MB) * (self.pad_w // MB)
        self._shapes = [((n, 2), 2 * n), ((n, 16, 4, 4), 256 * n),
                        ((n, 4, 4), 16 * n), ((n, 2, 2, 2), 8 * n),
                        ((n, 2, 4, 4, 4), 128 * n)]
        self._stripe_words = sum(s for _, s in self._shapes)
        self.s_local = self.pad_h // self.n_stripe_ax // stripe_h
        self._cap_frac = 8
        self._pad_words, self._n_cells, self._cap_cells = \
            dev.sparse_geometry(self._stripe_words, self._cap_frac)
        #: entropy tier (docs/entropy.md): "device" packs CAVLC shard-
        #: local so steady state needs no host entropy threads; "host"
        #: ships sparse levels (the pre-ISSUE-1 path)
        import os
        if entropy is None:
            entropy = os.environ.get("SELKIES_TPU_H264_ENTROPY", "device")
        if entropy not in ("device", "host"):
            raise ValueError(f"entropy must be device|host, got {entropy!r}")
        self.entropy = entropy
        if entropy == "device":
            self._cavlc_msb = dcav.default_max_stripe_bytes(
                self.pad_w // MB, stripe_h // MB)
            self._fixed_bytes = dcav.HEAD_BYTES * self.s_local
            self._buf_bytes = self._fixed_bytes \
                + self.s_local * self._cavlc_msb
            self._prefix = self._bucket(
                self._fixed_bytes + self.s_local * (4 << 10))
        else:
            self._cavlc_msb = 0
            self._fixed_bytes = 4 * self.s_local \
                + self.s_local * (self._n_cells // 8)
            self._buf_bytes = self._fixed_bytes \
                + self._cap_cells * self.s_local * dev.CELL
            #: per-(session, shard) fetch prefix over the content-
            #: compacted buffer (same layout as the solo encoder); an
            #: undershoot falls back to flat16 rows and grows the bucket
            self._prefix = self._bucket(
                self._fixed_bytes + self.s_local * (8 << 10))

        self._steps: Dict[Tuple[bool, int], Any] = {}

        plane = NamedSharding(mesh, P("session", "stripe"))
        self._plane_sharding = plane
        self._frame_sharding = plane
        z = functools.partial(jax.device_put)
        self._prev_y = z(jnp.zeros((n_sessions, self.pad_h, self.pad_w),
                                   jnp.uint8), plane)
        self._prev_cb = z(jnp.zeros(
            (n_sessions, self.pad_h // 2, self.pad_w // 2), jnp.uint8), plane)
        self._prev_cr = z(jnp.zeros_like(self._prev_cb), plane)
        self._ref_y = z(jnp.zeros_like(self._prev_y), plane)
        self._ref_cb = z(jnp.zeros_like(self._prev_cb), plane)
        self._ref_cr = z(jnp.zeros_like(self._prev_cr), plane)

        S = self.n_stripes
        self._need_idr = np.ones((n_sessions, S), bool)
        self._frame_num = np.zeros((n_sessions, S), np.int64)
        self._idr_pic_id = np.zeros((n_sessions, S), np.int64)
        self._static = np.zeros((n_sessions, S), np.int64)
        self._painted = np.zeros((n_sessions, S), bool)
        self._last_host = np.zeros(
            (n_sessions, self.pad_h, self.pad_w, 3), np.uint8)
        self._sps_pps: Dict[int, bytes] = {}
        #: fetch/concat split of the latest harvest wall with per-shard
        #: fetch attribution (the coordinator's flight-recorder feed)
        self.last_harvest_stages: Optional[dict] = None
        #: stripes recovered through the flat16 host coder (overflow /
        #: prefix undershoot; IDR resyncs excluded) — observability
        self.host_fallback_stripes_total = 0
        #: sessions whose frame was withheld by whole-frame containment:
        #: in-flight successor ticks predicted off the withheld frame's
        #: references are withheld too, until the full-IDR resync tick
        self._withheld = np.zeros(n_sessions, bool)
        #: session indices whose stripe jobs FAILED in the latest
        #: harvest (not containment carry-over) — the coordinator charges
        #: these slots' health so repeated encoder-internal failures walk
        #: the slot into quarantine + migration like injected faults
        self.last_failed_sessions: frozenset = frozenset()

    @property
    def n_shards(self) -> int:
        """Chips one frame's stripe bands are sharded across (the SFE
        stripe axis; 1 = whole frame on one chip)."""
        return self.n_stripe_ax

    # -- control -----------------------------------------------------------

    def force_keyframe(self, session: int) -> None:
        self._need_idr[session] = True
        self._static[session] = 0
        self._painted[session] = False

    def reset_session(self, session: int) -> None:
        """Recycle a slot: fresh history AND zeroed planes so no pixels
        leak across occupants (the inter refs would otherwise carry
        them — the exact hazard VERDICT r2 flagged for mesh inter)."""
        self.force_keyframe(session)
        self._frame_num[session] = 0
        self._last_host[session] = 0
        self._withheld[session] = False
        put = functools.partial(jax.device_put)
        for name in ("_prev_y", "_prev_cb", "_prev_cr",
                     "_ref_y", "_ref_cb", "_ref_cr"):
            arr = getattr(self, name)
            setattr(self, name, put(
                jnp.asarray(arr).at[session].set(0), self._plane_sharding))

    # -- helpers -----------------------------------------------------------

    def _bucket(self, nbytes: int) -> int:
        """Fetch-prefix bound quantized PER STRIPE: the payload share
        above the fixed head rounds up to s_local × a power-of-two
        per-stripe budget (≥1 KB). The set of compiled prefix shapes is
        then a function of per-stripe content alone — growing the SFE
        shard count shrinks s_local instead of multiplying distinct
        executables, and every lane of a bucket walks the same ladder
        (ISSUE 15)."""
        per = 1 << 10
        need = max(0, int(nbytes) - self._fixed_bytes)
        while per * self.s_local < need:
            per <<= 1
        return min(self._fixed_bytes + per * self.s_local, self._buf_bytes)

    def _step_for(self, with_idr: bool, prefix: int):
        key = (with_idr, prefix)
        fn = self._steps.get(key)
        if fn is None:
            fn, _ = make_h264_mesh_step(
                self.mesh, self.pad_h, self.pad_w, self.stripe_h,
                search=self.search, me=self.me, with_idr=with_idr,
                cap_frac=self._cap_frac, prefix=prefix,
                entropy="device" if self.entropy == "device" else "sparse",
                max_stripe_bytes=self._cavlc_msb)
            self._steps[key] = fn
        return fn

    def _sps_pps_for(self, h: int) -> bytes:
        if h not in self._sps_pps:
            self._sps_pps[h] = (
                make_sps(self.width, h, coded_height=self.stripe_h)
                + make_pps())
        return self._sps_pps[h]

    def _pad(self, frame: np.ndarray) -> np.ndarray:
        if frame.shape[0] == self.pad_h and frame.shape[1] == self.pad_w:
            return frame
        return np.pad(
            frame,
            ((0, self.pad_h - frame.shape[0]),
             (0, self.pad_w - frame.shape[1]), (0, 0)),
            mode="edge")

    # -- per-tick ----------------------------------------------------------

    def dispatch(self, frames) -> _MeshH264Pending:
        """One sharded step for all sessions; pair with :meth:`harvest`.

        ``frames``: [N, H, W, 3] array, a device-resident pre-padded jnp
        batch (bench/synthetic sources; bypasses the idle re-present
        cache like MeshStripeEncoder's), or a length-N sequence (None
        entries re-present the previous frame; damage gating suppresses
        them).
        """
        reuse_prev = np.zeros(self.n_sessions, bool)
        batch: Any = self._last_host
        if isinstance(frames, jnp.ndarray):
            want = (self.n_sessions, self.pad_h, self.pad_w, 3)
            if frames.shape != want:
                raise ValueError(f"device batch must be pre-padded to {want}")
            batch = frames
        elif isinstance(frames, np.ndarray) and frames.ndim == 4:
            for n in range(self.n_sessions):
                self._last_host[n] = self._pad(np.asarray(frames[n], np.uint8))
        else:
            for n, f in enumerate(frames):
                if f is None:
                    reuse_prev[n] = True
                else:
                    self._last_host[n] = self._pad(np.asarray(f, np.uint8))

        # a withheld session's client never received the content already
        # sitting in _last_host (whole-frame containment dropped it), so
        # an idle re-present is NOT a no-op for it: run the armed
        # full-frame IDR resync now instead of waiting for fresh damage
        reuse_prev &= ~self._withheld

        idr = self._need_idr & ~reuse_prev[:, None]
        paint = (self.use_paint_over_quality
                 & (self._static >= self.paint_over_trigger)
                 & ~self._painted & ~idr)
        paint &= ~reuse_prev[:, None]
        # optimistic arming (cleared by damage at harvest) — in-flight
        # ticks must not re-trigger
        self._painted |= paint
        self._need_idr &= reuse_prev[:, None]

        qp_arr = np.where(paint, self.paint_over_qp, self.qp)
        fn = self._step_for(bool(idr.any()), self._prefix)
        frames_d = jax.device_put(jnp.asarray(batch),
                                  self._frame_sharding)
        paint_d = jax.device_put(jnp.asarray(paint.astype(np.int32)),
                                 self._plane_sharding)
        idr_d = jax.device_put(jnp.asarray(idr.astype(np.int32)),
                               self._plane_sharding)
        (prefix, flat16, self._prev_y, self._prev_cb, self._prev_cr,
         self._ref_y, self._ref_cb, self._ref_cr) = fn(
            frames_d, self._prev_y, self._prev_cb, self._prev_cr,
            self._ref_y, self._ref_cb, self._ref_cr,
            paint_d, idr_d, jnp.int32(self.qp),
            jnp.int32(self.paint_over_qp))
        prefix.copy_to_host_async()
        return _MeshH264Pending(
            prefix=prefix, buf=None, flat16=flat16, idr=idr,
            paint=paint, reuse_prev=reuse_prev, qp=qp_arr)

    def fetch_ready(self, p: _MeshH264Pending) -> bool:
        """True when the eagerly-started prefix fetch has landed — the
        coordinator's in-flight window harvests without blocking then."""
        return bool(p.prefix.is_ready())

    def harvest(self, p: _MeshH264Pending
                ) -> Tuple[List[List[H264Stripe]], np.ndarray]:
        """Entropy-code one dispatched tick. Returns (stripes per session,
        coded bytes per session). Must be called in dispatch order.

        Sets :attr:`last_harvest_stages` — the fetch/concat split of the
        harvest wall with per-stripe-shard fetch attribution — which the
        coordinator folds into each frame's flight-recorder span."""
        t_h0 = time.perf_counter()
        # [N, stripe_ax, prefix]: materialized shard by shard so the D2H
        # wall is attributable per SFE stripe shard
        host, per_shard_ms = fetch_sharded_prefix(p.prefix)
        fetch_ms = sum(per_shard_ms.values())
        S, sl = self.n_stripes, self.s_local
        CELL = dev.CELL
        cavlc = self.entropy == "device"

        damage = np.zeros((self.n_sessions, S), bool)
        ovf = np.zeros((self.n_sessions, S), bool)
        counts = np.zeros((self.n_sessions, S), np.int64)
        t_bits = np.zeros((self.n_sessions, S), np.int64)
        base_words = np.zeros((self.n_sessions, S), np.int64)
        for k in range(self.n_stripe_ax):
            gs = slice(k * sl, (k + 1) * sl)
            if cavlc:
                for n in range(self.n_sessions):
                    tb, bw, dmg, ov = dcav.parse_cavlc_head(host[n, k], sl)
                    t_bits[n, gs] = tb
                    base_words[n, gs] = bw
                    damage[n, gs] = dmg
                    ovf[n, gs] = ov
            else:
                head = host[:, k, :4 * sl].reshape(self.n_sessions, sl, 4)
                counts[:, gs] = head[:, :, 0].astype(np.int64) \
                    + (head[:, :, 1].astype(np.int64) << 8)
                damage[:, gs] = head[:, :, 2] != 0
                ovf[:, gs] = head[:, :, 3] != 0

        damage[p.reuse_prev] = False
        emit = damage | p.paint | p.idr
        self._static = np.where(damage, 0, self._static + 1)
        self._painted = np.where(damage, False, self._painted)

        # per shard: device-CAVLC payload words (bit-exact slice bits) or
        # content-compacted sparse cells, back to back after the fixed
        # head. An undershoot (content past the fetched prefix), a
        # per-stripe overflow, or an IDR stripe (its merged intra levels
        # are not P-slice material; |level| > 127 routinely in sparse
        # mode) recovers from the exact flat16 rows; reads start before
        # any blocks.
        used = np.minimum(counts, self._cap_cells) * CELL
        grew = False
        for n in range(self.n_sessions):
            for k in range(self.n_stripe_ax):
                gs = slice(k * sl, (k + 1) * sl)
                if not emit[n, gs].any():
                    continue
                if cavlc:
                    # clip to the device's per-stripe word capacity: an
                    # overflow stripe records unclipped t_bits but
                    # compacts at most V words, and overshooting here
                    # would pin the grow-only prefix at its cap
                    wc = np.minimum((t_bits[n, gs] + 31) // 32,
                                    self._cavlc_msb // 4)
                    needed = self._fixed_bytes \
                        + 4 * int(base_words[n, gs][-1] + wc[-1])
                else:
                    needed = self._fixed_bytes + int(used[n, gs].sum())
                if needed > host.shape[-1]:
                    ovf[n, gs] |= emit[n, gs]
                    if not grew:
                        self._prefix = self._bucket(needed + needed // 2)
                        grew = True
        host_path = ovf | (cavlc & p.idr)
        # overflow / prefix-undershoot stripes recovered through the
        # flat16 host coder (IDR resyncs are by-construction, not faults)
        self.host_fallback_stripes_total += int((ovf & emit).sum())
        exact: Dict[Tuple[int, int], Any] = {}
        for n in range(self.n_sessions):
            for g in range(S):
                if emit[n, g] and host_path[n, g]:
                    row = p.flat16[n, g]
                    row.copy_to_host_async()
                    exact[(n, g)] = row

        mb_w = self.pad_w // MB
        mb_h = self.stripe_h // MB
        jobs = []
        for n in range(self.n_sessions):
            for g in range(S):
                if not emit[n, g]:
                    continue
                k, s = g // sl, g % sl
                if cavlc and not host_path[n, g]:
                    # device already entropy-coded the stripe; the job is
                    # slice-header glue only
                    pb, nbits = dcav.payload_slice(
                        host[n, k], sl, base_words[n, k * sl:(k + 1) * sl],
                        t_bits[n, k * sl:(k + 1) * sl], s)
                    jobs.append((n, g, False, int(p.qp[n, g]),
                                 ("bits", pb, nbits)))
                    continue
                if host_path[n, g]:
                    t_rf = time.perf_counter()
                    row = np.asarray(exact[(n, g)]).astype(np.int32)
                    rf_ms = (time.perf_counter() - t_rf) * 1000.0
                    fetch_ms += rf_ms
                    per_shard_ms[k] = per_shard_ms.get(k, 0.0) + rf_ms
                else:
                    bitmap = host[n, k, 4 * sl:self._fixed_bytes] \
                        .reshape(sl, self._n_cells // 8)[s]
                    bits = np.unpackbits(bitmap, bitorder="little")
                    idx = np.flatnonzero(bits[:self._n_cells])
                    gs0 = k * sl
                    start = self._fixed_bytes \
                        + int(used[n, gs0:g].sum())
                    cells = host[n, k, start:start + used[n, g]] \
                        .view(np.int8).astype(np.int32) \
                        .reshape(-1, CELL)
                    dense = np.zeros(self._pad_words, np.int32)
                    dense.reshape(-1, CELL)[idx[:len(cells)]] = cells
                    row = dense[:self._stripe_words]
                parts, pos = [], 0
                for shape, size in self._shapes:
                    parts.append(row[pos:pos + size].reshape(shape))
                    pos += size
                jobs.append((n, g, bool(p.idr[n, g]), int(p.qp[n, g]),
                             ("levels", parts)))

        def run_one(job):
            n, g, is_key, qp, work = job
            if work[0] == "bits":
                _, pb, nbits = work
                return dcav.assemble_p_slice(
                    pb, nbits, qp, int(self._frame_num[n, g]))
            mv, luma, luma_dc, chroma_dc, chroma_ac = work[1]
            if is_key:
                return encode_picture_nals_np(
                    mv, luma, luma_dc, chroma_dc, chroma_ac,
                    is_idr=True, mb_w=mb_w, mb_h=mb_h, qp=qp, frame_num=0,
                    idr_pic_id=int(self._idr_pic_id[n, g]))
            return encode_picture_nals_np(
                mv, luma, luma_dc, chroma_dc, chroma_ac,
                is_idr=False, mb_w=mb_w, mb_h=mb_h, qp=qp,
                frame_num=int(self._frame_num[n, g]))

        def safe_one(job):
            try:
                return run_one(job)
            except Exception as exc:
                return exc

        payloads = list(_entropy_pool().map(safe_one, jobs)) \
            if len(jobs) > 1 else [safe_one(j) for j in jobs]

        # whole-frame containment (ISSUE 15): a failed stripe job must
        # never tear the access unit. Sibling stripes of the same frame
        # are withheld WITH it — their device reference planes already
        # advanced, so emitting them while skipping the failed one would
        # silently drift every later P frame — and the whole session
        # resyncs with a full IDR on its next tick instead. Successor
        # ticks already in flight when the failure surfaces predicted
        # off the withheld references too, so the session STAYS withheld
        # until the tick that was dispatched as a full-frame IDR.
        prev_withheld = self._withheld.copy()
        failed_sessions = set()
        for job, payload in zip(jobs, payloads):
            if isinstance(payload, Exception):
                n, g = job[0], job[1]
                logger.error("mesh CAVLC failed for session %d stripe %d; "
                             "frame withheld, forcing whole-frame IDR "
                             "resync", n, g, exc_info=payload)
                failed_sessions.add(n)
        for n in failed_sessions:
            self._need_idr[n] = True
            self._withheld[n] = True
        self.last_failed_sessions = frozenset(failed_sessions)
        # the resync tick (dispatched all-IDR) releases the withhold —
        # unless it failed too, in which case the next one re-arms
        release = prev_withheld & p.idr.all(axis=1)
        for n in failed_sessions:
            release[n] = False
        self._withheld &= ~release

        out: List[List[H264Stripe]] = [[] for _ in range(self.n_sessions)]
        coded = np.zeros(self.n_sessions, np.int64)
        for job, payload in zip(jobs, payloads):
            n, g, is_key, qp, _ = job
            if n in failed_sessions or (prev_withheld[n] and not release[n]):
                continue
            y0 = g * self.stripe_h
            h = min(self.stripe_h, self.height - y0)
            if h <= 0:
                continue
            if is_key:
                payload = self._sps_pps_for(h) + payload
                self._frame_num[n, g] = 1
                self._idr_pic_id[n, g] = (self._idr_pic_id[n, g] + 1) % 16
                self._need_idr[n, g] = False
                self._static[n, g] = 0
                self._painted[n, g] = False
            else:
                self._frame_num[n, g] = (self._frame_num[n, g] + 1) % 16
            coded[n] += len(payload)
            out[n].append(H264Stripe(
                y_start=y0, width=self.width, height=h,
                annexb=payload, is_key=is_key))
        total_ms = (time.perf_counter() - t_h0) * 1000.0
        self.last_harvest_stages = {
            "fetch_ms": fetch_ms,
            "concat_ms": max(0.0, total_ms - fetch_ms),
            "per_shard_fetch_ms": [
                round(per_shard_ms.get(k, 0.0), 3)
                for k in range(self.n_stripe_ax)],
        }
        return out, coded

    def encode_frames(self, frames) -> Tuple[List[List[H264Stripe]],
                                             np.ndarray]:
        """Synchronous dispatch + harvest (tests, simple callers)."""
        return self.harvest(self.dispatch(frames))
