"""Multi-session / multi-chip parallelism (SURVEY.md §2.7, BASELINE config 5).

The reference is single-node: one pixelflux C++ thread pool per display.  The
TPU-native scale axis is a 2-D device mesh:

  * ``session`` — data parallelism over concurrent desktop sessions (the
    "8× 1080p60 on v5e-8" north star batches one frame per session per tick);
  * ``stripe``  — spatial parallelism over horizontal frame bands (the
    reference's stripe-thread axis, SURVEY.md §2.7 row 1), sharding the
    height dimension so one session's frame can span several chips.

Collectives ride ICI: per-session coded-size estimates are ``psum``-ed over
the stripe axis (a session's stripes live on different chips) and globally
over the session axis to drive the shared rate controller.
"""

__all__ = [
    "Mesh",
    "make_mesh",
    "parse_mesh_spec",
    "make_batched_step",
    "make_batched_entropy_step",
    "BatchedSessionEncoder",
    "MeshStripeEncoder",
]

#: lazily resolved (PEP 562) so the scheduler half of the package —
#: `.coordinator` with an injected encoder factory, as used by the swarm
#: harness and the scheduler tests — imports without initializing jax;
#: device-touching names still resolve exactly as before on first use
_MESH_EXPORTS = {
    "BatchedSessionEncoder", "MeshStripeEncoder",
    "make_batched_entropy_step", "make_batched_step", "make_mesh",
    "parse_mesh_spec",
}


def __getattr__(name):
    if name == "Mesh":
        from jax.sharding import Mesh
        return Mesh
    if name in _MESH_EXPORTS:
        from . import mesh
        return getattr(mesh, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
